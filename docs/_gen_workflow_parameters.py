"""Regenerates docs/workflow_parameters.md from the live registry:

    JAX_PLATFORMS=cpu python docs/_gen_workflow_parameters.py \
        > docs/workflow_parameters.md
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from veles_tpu.units import UnitRegistry
from veles_tpu.znicz import (  # noqa: F401 - populate the registry
    activation, all2all, conv, misc_units, normalization_units,
    pooling, rnn)

print("""# Layer types and parameters

(Parity topic: `manualrst_veles_workflow_parameters.rst:467-580`.
Generated from the live registry — regenerate with
`python docs/_gen_workflow_parameters.py > docs/workflow_parameters.md`.)

Layer specs are dicts: `{"type": <mapping>, "->": {forward params},
"<-": {backward params}}`.

## Backward (`<-`) parameters — every trainable layer

| Param | Meaning | Default |
|---|---|---|
| `learning_rate` | SGD step size | 0.01 |
| `learning_rate_bias` | bias step size | = learning_rate |
| `weights_decay` / `weights_decay_bias` | L2 coefficient | 0.0 |
| `gradient_moment` / `gradient_moment_bias` | momentum | 0.0 |
| `l1_vs_l2` (+ `_bias`) | regularization mix: 0 = L2 (λ·w), 1 = L1 (λ·sign w) | 0.0 |
| `factor_ortho` | soft-orthogonality gradient factor·W·(WᵀW−I) | 0.0 |
| `solver` (fused lowering) | `momentum` / `adam` / `adagrad` / `adadelta` / `rprop` update rule | momentum |
| `adam_beta1` / `adam_beta2` / `adam_epsilon` | Adam moments (decoupled decay) | 0.9 / 0.999 / 1e-8 |
| `adagrad_epsilon`, `adadelta_momentum` / `adadelta_epsilon` | adagrad/adadelta accumulators (adadelta: learning_rate=1) | 1e-6, 0.9 / 1e-6 |
| `rprop_delta_init` / `rprop_eta_plus` / `rprop_eta_minus` / `rprop_delta_min` / `rprop_delta_max` | iRprop− step-size schedule | 0.1 / 1.2 / 0.5 / 1e-6 / 50 |

## Common forward (`->`) parameters

| Param | Meaning |
|---|---|
| `output_sample_shape` | dense layer width |
| `n_kernels`, `kx`, `ky`, `padding`, `sliding` | conv geometry |
| `weights_filling` | `gaussian` / `uniform` / `constant` |
| `weights_stddev` | init scale (default 1/sqrt(fan_in)) |
| `dropout_ratio` | dropout probability |
| `alpha`, `beta`, `k`, `n` | LRN hyperparameters |
| `store_offsets` | pooling records offsets for Depooling |

## Registered layer types

| type | class | module |
|---|---|---|""")
for name in sorted(UnitRegistry.mapped):
    cls = UnitRegistry.mapped[name]
    mod = cls.__module__.replace("veles_tpu.", "")
    print("| `%s` | %s | `%s` |" % (name, cls.__name__, mod))
print("""
Aliases (reference-doc short spellings) resolve to the same classes:
`all2all_str`, `conv_str`, `activation_str`, `norm`,
`stochastic_abs_pooling`.

Forward-only types (`depooling`, `channel_splitter`, the combined
pool-depools) pair with `gd_generic` — the exact VJP of their pure
function.  `zero_filter` and `channel_merger` are service units
constructed directly, not listed in `layers`.""")
