#!/usr/bin/env bash
# Self-lint veles_tpu/ with the analyze lint pack (pass 3) — the same
# invocation the tier-1 suite gates on (test_analyze.py::
# test_lint_self_clean_tier1).  Extra args pass through, e.g.
#   scripts/lint.sh --json
#   scripts/lint.sh path/to/other/package
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m veles_tpu.analyze --lint "$@"
