#!/usr/bin/env bash
# Self-lint veles_tpu/ with the analyze lint pack (pass 3) — the same
# invocation the tier-1 suite gates on (test_analyze.py::
# test_lint_self_clean_tier1); the default path is the whole installed
# package, so the veles_tpu/trace/ observability subsystem self-lints
# here too.  Then run the workflow analyzer (graph doctor + JAX hazard
# pass, V-J06/V-J08 included) over the samples/ demo modules that
# build a real training graph; warnings print, errors fail.
# samples/analyze_demo is deliberately broken (it exercises the rule
# catalog) and is covered by test_analyze.py instead.
# Extra args pass through to the lint invocation, e.g.
#   scripts/lint.sh --json
#   scripts/lint.sh path/to/other/package
set -euo pipefail
cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
  # passthrough mode (--json, explicit paths): keep the output pure —
  # machine consumers parse it
  exec env JAX_PLATFORMS=cpu python -m veles_tpu.analyze --lint "$@"
fi
env JAX_PLATFORMS=cpu python -m veles_tpu.analyze --lint
# mnist_conv + cifar10 exercise the loader-headed stitch stage (the
# device-resident input pipeline, V-J07) on conv-shaped workflows;
# the analyzer runs with the full rule set, V-J08..V-J11 included
# (V-J11: host-side finiteness probes — the samples must stay silent,
# the in-program health knob being the prescribed remedy)
for sample in veles_tpu.samples.mnist veles_tpu.samples.mnist_ae \
              veles_tpu.samples.mnist_conv veles_tpu.samples.cifar10; do
  echo "== analyze $sample =="
  env JAX_PLATFORMS=cpu python -m veles_tpu.analyze "$sample"
done
# profiler smoke: a short stitched mnist run must leave non-zero
# per-segment flops in the ledger, a parseable perf_report(), every
# compile fingerprinted and ZERO steady-state recompiles
echo "== prof smoke (veles_tpu.samples.mnist) =="
env JAX_PLATFORMS=cpu python -m veles_tpu.prof --smoke veles_tpu.samples.mnist
# epoch-scan smoke: a stitched mnist run under engine.epoch_scan=auto
# must fold K steps per dispatch — host dispatches <= ceil(steps/K) +
# one per class span in trace_report()'s host-gap split — with ZERO
# steady-state recompiles and the V-J10 rule silent over the sample
# workflow (docs/engine_fast_path.md § Epoch mode)
echo "== epoch smoke (one-dispatch-epoch gate) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu \
  python -m veles_tpu.epoch_scan --smoke veles_tpu.samples.mnist
# chaos smoke: a fixed-seed master–slave session over real ZMQ with an
# injected slave death, a dropped job frame and a duplicated update
# frame must COMPLETE — no hang (timeout-wrapped), every job applied
# exactly once, dedup/requeue counters consistent (docs/robustness.md)
echo "== chaos smoke (fault-injection gate) =="
timeout -k 10 120 env JAX_PLATFORMS=cpu python -m veles_tpu.chaos --smoke
# generative serving smoke: warmup must cover every prefill bucket +
# the decode program, then a seeded mixed-length continuous-batching
# session completes with ZERO steady-state compiles (the recompile
# sentinel stays quiet) and every request at exactly its token budget;
# a second PAGED session (block-pool KV, chunked prefill, pool sized
# below the working set) must reproduce the contiguous token streams
# EXACTLY while exercising and recovering >=1 pool-exhaustion
# preemption — the lossless-preemption gate (docs/services.md § Paged
# KV); a third INT8 session (deploy-time per-channel weight
# quantization, the qgemm dequant-epilogue path) must complete the
# same budgets with zero steady-state compiles, a params footprint
# <=0.35x its float twin and the calibration drift gate green
# (docs/services.md § Quantized serving); a fourth PREFIX+SPEC
# session (radix prefix cache + n-gram speculative decode) must
# bitwise-match a plain paged session on a shared-prefix workload
# while actually sharing pages across live slots, evicting only
# cache-only pages and accepting drafted tokens (docs/services.md
# § Prefix cache & speculative decode)
echo "== gen smoke (generative serving + paged KV gate) =="
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m veles_tpu.gen --smoke
# obs smoke: the fleet-observability gate — with tracing off every
# obs hook must be the PR 5 one-attribute-check no-op; then ONE
# traced request must cross server -> scheduler -> engine -> a
# scripted master/slave ZMQ session with its trace id in >=3 role
# lanes of one prof-merged Perfetto timeline (flow arrows included),
# the master scrape endpoint must serve the per-slave round-trip
# histograms, and SLO evaluation over a synthetic breaching series
# must fire exactly the expected multi-window burn alerts
# (docs/observability.md § Request tracing & SLOs)
echo "== obs smoke (request tracing + SLO gate) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m veles_tpu.obs --smoke
# watch smoke: the training-health + live-bus gate — one traced
# stitched session under engine.health=on must publish >=4 distinct
# event kinds (run/epoch/health/perf) consumed by a LIVE bus
# subscriber with finite per-param-group stats; an injected NaN under
# health=strict must raise a typed HealthError naming the poisoned
# param group; and a record/replay ndjson roundtrip must reproduce
# the session exactly (docs/observability.md § Training health &
# live watch)
echo "== watch smoke (training-health telemetry + live bus gate) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m veles_tpu.watch --smoke
# ops smoke: the training-kernel gate — interpret-mode parity oracles
# for every Pallas family (fused backward-GD incl. optimizer epilogue,
# gather+normalize loader head, flash-attention fwd+bwd custom_vjp),
# a toy autotune_gd sweep round-tripped through gemm_choice (stdout
# envelope unwrap included), and a stitched run under
# engine.kernels=pallas finishing with ZERO steady-state recompiles
# (docs/engine_fast_path.md § Training kernels)
echo "== ops smoke (kernel parity + autotune + zero-recompile gate) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m veles_tpu.ops --smoke
# bench_diff self-test: the perf-regression watchdog's comparator
# validated against the banked BENCH_r0*.json envelope — banked vs
# banked clean, synthetically degraded copies caught on every field,
# cross-device lines skipped (the bench ladder is a GATE now, not an
# archive: gate a fresh run with scripts/bench_diff.py --fresh)
echo "== bench_diff self-test (perf-regression watchdog) =="
python scripts/bench_diff.py --selftest
# pod smoke: an 8-shard CPU session (one pod = one pjit'd stitched
# program) must train the seeded sample to completion with ZERO
# per-step gradient/update frames on the ZMQ wire (chaos wire-site
# counters are the probe), zero steady-state recompiles, eval parity
# with the single-device run, a chip-kill reshard mid-epoch (mesh
# shrink + generation bump) and a byte-identical mesh-sharded
# InferenceEngine — the V-P02 preflight runs inside install().
# Pod-of-pods legs ride the same gate: a pp leg (stacked stages
# pipelined over dp×pp, one dispatch per class pass, bitwise forward
# parity vs the dp twin), an ep leg (all_to_all-routed MoE, token
# parity vs the dense reference at capacity >= n_experts), a
# simulated 2-process multi-host session (the multihost test double)
# asserting the one-update-frame wire gate + lockstep rank weights,
# and a heartbeat device-loss reshard completing with eval parity
echo "== pod smoke (one-pod-one-program + pod-of-pods gate) =="
timeout -k 10 560 env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m veles_tpu.pod --smoke
# fleet smoke: the disaggregated-serving gate — a scripted 2-role
# session (prefill role over the job wire, 2 decode replicas) must
# resolve a seeded request set with EXACT token parity vs a
# single-engine oracle while chaos drops one page-handoff frame
# (exactly-once retry) and one job frame (have-list requeue), a
# chaos-fired replica_drain scales down mid-stream losslessly, a
# synthetic TTFT-p99 burn breach makes the autoscaler shift the
# decode weights, and ZERO steady-state recompiles land on either
# role (docs/services.md § Disaggregated serving)
echo "== fleet smoke (disaggregated prefill/decode gate) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m veles_tpu.fleet --smoke
# plan smoke: the static sharding planner must find a feasible plan
# for both planner paths on a forced 8-device host — the mnist
# workflow path (initialize-but-never-train pricing) and the
# transformer params-pytree path (zero-alloc, Megatron module specs);
# and a topology the batch/axes CANNOT divide must exit non-zero with
# the V-P03 reasons named per candidate (docs/analyze.md § Planner)
echo "== plan smoke (static sharding planner gate) =="
timeout -k 10 120 env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m veles_tpu.analyze --plan veles_tpu.samples.mnist
timeout -k 10 120 env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m veles_tpu.analyze --plan veles_tpu.samples.transformer
if out=$(timeout -k 10 120 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m veles_tpu.analyze --plan veles_tpu.samples.mnist \
    --topology 3); then
  echo "plan smoke: expected non-zero exit for --topology 3" >&2
  exit 1
fi
echo "$out"
case "$out" in
  *V-P03*) : ;;
  *) echo "plan smoke: V-P03 not named for the bad topology" >&2
     exit 1 ;;
esac
