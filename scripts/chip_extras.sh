#!/bin/bash
# Post-session bonus measurements — run ONLY after chip_session_v2.sh
# has banked the round's scripted artifacts.  Each invocation of
# bench.py is one backend claim; the relay may refuse any of them
# (claims are scarce outside the first minutes of a window), so every
# leg is independent and a refusal only costs that leg.
#
#     bash scripts/chip_extras.sh [outdir]
#
# Legs (scaling points the scripted ladder doesn't cover):
#   1. LM batch 64 (65k tokens/step) — does the swept flash backward
#      hold its TFLOP/s when the per-step token count doubles?
#   2. AlexNet batch 1024 — MXU saturation headroom above the
#      256/512 ladder points.
set -u
OUT=${1:-chip_session_logs_r5}
mkdir -p "$OUT"

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-$(python -c \
    'from veles_tpu.backends import COMPILE_CACHE_DIR; print(COMPILE_CACHE_DIR)' \
    2>/dev/null || echo "$HOME/.veles_tpu/cache/xla")}
export BENCH_TIMEOUT_SCALE=${BENCH_TIMEOUT_SCALE:-4}

note() { echo "[chip_extras $(date +%H:%M:%S)] $*" >&2; }

note "leg 1: LM batch 64"
BENCH_STAGES=transformer BENCH_LM_BATCH=64 BENCH_BUDGET_SEC=1500 \
    python bench.py >"$OUT/extras_lm64.jsonl" 2>"$OUT/extras_lm64.log" \
    || note "LM batch-64 leg failed (rc=$?)"

note "leg 2: AlexNet batch 1024"
BENCH_STAGES=alexnet BENCH_ALEXNET_BATCH=1024 BENCH_BUDGET_SEC=1500 \
    python bench.py >"$OUT/extras_alexnet1024.jsonl" \
    2>"$OUT/extras_alexnet1024.log" \
    || note "AlexNet batch-1024 leg failed (rc=$?)"

python scripts/collect_chip_session.py "$OUT" chip_session_r5 \
    >/dev/null 2>&1 || note "collector failed — snapshot manually"
note "done"
