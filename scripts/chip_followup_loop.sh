#!/bin/bash
# Round-5 follow-up watcher: the main session (chip_session_v2.sh)
# exits 0 once the HEADLINE has landed — even when the relay refused
# its later steps.  This loop keeps retrying the still-missing
# artifacts at later windows until each has been produced on real
# hardware:
#   - autotune sweep (fresh per-shape DB incl. flash-backward blocks)
#   - tuned re-bench of the heavies        (VERDICT r5 items 2 & 3)
#   - attn_bwd + epoch sequential-gather A/Bs  (items 2 & 3 evidence)
#   - per-layer LSTM/CIFAR profiles            (item 6)
#
#     nohup bash scripts/chip_followup_loop.sh >chip_followup_r5.log 2>&1 &
#
# Claim discipline unchanged: one python process per step, no SIGKILL,
# 10-min backoff between attempts.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-chip_session_logs_r5}
# tracked evidence target; tests MUST override with a scratch dir (a
# rehearsal against the default once laundered a fake autotune.json
# into the committed evidence — caught and reverted same session)
EVD=${2:-chip_session_r5}
mkdir -p "$OUT"

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-$(python -c \
    'from veles_tpu.backends import COMPILE_CACHE_DIR; print(COMPILE_CACHE_DIR)' \
    2>/dev/null || echo "$HOME/.veles_tpu/cache/xla")}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
export BENCH_TIMEOUT_SCALE=${BENCH_TIMEOUT_SCALE:-4}

note() { echo "[followup $(date +%H:%M:%S)] $*"; }

# FOLLOWUP_DRY_RUN=1: print the would-run command instead of claiming
# the backend — control-flow tests must NEVER touch the tunnel (a
# killed mid-claim client can wedge the relay for hours)
run_leg() {
    if [ "${FOLLOWUP_DRY_RUN:-0}" = "1" ]; then
        note "DRY: $*"
        return 1
    fi
    "$@"
}

# unique output suffix per attempt: never truncate a prior attempt's
# artifact, even across watcher restarts (code-review r5)
stamp() { date +%m%d%H%M%S; }

live_lines() {
    # exit 0 when any of the given jsonl files holds a live (non-
    # banked, non-sample-starved) real-hardware line for EVERY metric
    # substring given after "--".  Case-insensitive "tpu", matching
    # bench.py/_banked_tpu_lines and collect_chip_session.tpu_lines
    # (code-review r5).  Sample-starved records (batches_served <= 2 —
    # a dying window's transport measurement) must NOT satisfy a
    # done-check, or the watcher stops retrying a leg whose only
    # evidence is the very line the judge will refuse; the predicate
    # is bench.sample_starved, shared with the collector, not a third
    # hand-copied variant (ADVICE r5).
    python - "$@" <<'PY'
import json
import sys

from bench import sample_starved   # cwd is the repo root

paths, needles = [], []
bucket = paths
for a in sys.argv[1:]:
    if a == "--":
        bucket = needles
        continue
    bucket.append(a)
need = {n: False for n in needles}
for path in paths:
    try:
        lines = open(path).readlines()
    except OSError:
        continue
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "tpu" not in (rec.get("device_kind") or "").lower():
            continue
        if rec.get("banked") or "error" in rec:
            continue
        if sample_starved(rec):
            continue
        m = rec.get("metric") or ""
        for n in need:
            if n in m:
                need[n] = True
sys.exit(0 if need and all(need.values()) else 1)
PY
}

tuned_done() {
    live_lines "$OUT"/bench_tuned*.jsonl -- "fused train throughput"
}

ab_done() {
    live_lines "$OUT"/*.jsonl -- "flash-attention backward A/B" \
        "sequential gather A/B"
}

autotune_done() {
    # the dumped DB ({"devices": {...}, "_this_run": {...}} envelope)
    # always contains every previously-measured device (incl.
    # committed TPU entries) — only the report's _this_run provenance
    # says what THIS sweep ran on (code-review r5; envelope ADVICE r5)
    python - "$OUT"/autotune*.json <<'PY'
import json
import sys

for path in sys.argv[1:]:
    try:
        rep = json.load(open(path))
    except (OSError, ValueError):
        continue
    kind = (rep.get("_this_run") or {}).get("device_kind") or ""
    if "tpu" in kind.lower():
        sys.exit(0)
sys.exit(1)
PY
}

profiles_done() {
    # chip_session_v2 step 1b artifacts (VERDICT r5 item 6): per-layer
    # profiles re-banked on the chip.  profile_step stamps the device
    # kind in the .md header.
    # case-sensitive: device kinds are "TPU ..."; a case-insensitive
    # match would hit the substring in the word "ouTPUt"
    grep -l "TPU" PROFILE_CIFAR.md >/dev/null 2>&1 \
        && grep -l "TPU" PROFILE_LSTM.md >/dev/null 2>&1
}

state() {
    # one char per done-check; used to collect evidence only when a
    # cycle actually banked something new (a failed cycle's
    # cpu-fallback artifacts would otherwise pile junk files into the
    # tracked evidence dir every ~2h)
    s=""
    autotune_done && s="${s}A" || s="${s}-"
    tuned_done && s="${s}T" || s="${s}-"
    ab_done && s="${s}B" || s="${s}-"
    profiles_done && s="${s}P" || s="${s}-"
    echo "$s"
}

attempt=0
while true; do
    before=$(state)
    if ! autotune_done; then
        note "autotune artifact missing — attempting sweep"
        s=$(stamp)
        run_leg python -m veles_tpu.scripts.autotune \
            --precision-levels 0,1,2 \
            >"$OUT/autotune.$s.json" 2>"$OUT/autotune.$s.log" \
            && note "autotune rc=0" || note "autotune failed"
    fi
    if ! profiles_done; then
        note "per-layer profiles missing — attempting"
        run_leg python -m veles_tpu.scripts.profile_step \
            --sample cifar10 \
            --batch 1024 --per-layer --out PROFILE_CIFAR.md \
            >>"$OUT/profile_followup.log" 2>&1 \
            || note "cifar profile failed"
        run_leg python -m veles_tpu.scripts.profile_step \
            --sample mnist_rnn \
            --batch 2048 --out PROFILE_LSTM.md \
            >>"$OUT/profile_followup.log" 2>&1 \
            || note "lstm profile failed"
    fi
    if autotune_done && ! tuned_done; then
        note "tuned re-bench missing — attempting"
        s=$(stamp)
        BENCH_TPU_ONLY=1 \
            BENCH_STAGES=mnist,lstm,transformer,profile_lm,alexnet,alexnet_e2e,alexnet_epoch \
            BENCH_BUDGET_SEC=3600 \
            run_leg python bench.py >"$OUT/bench_tuned.$s.jsonl" \
            2>"$OUT/bench_tuned.$s.log" \
            && note "re-bench rc=0" || note "re-bench failed"
    fi
    if ! ab_done; then
        note "A/B adjudication lines missing — attempting"
        s=$(stamp)
        BENCH_TPU_ONLY=1 BENCH_STAGES=attn_bwd,alexnet_epoch_ab BENCH_BUDGET_SEC=2400 \
            run_leg python bench.py >"$OUT/bench_ab.$s.jsonl" \
            2>"$OUT/bench_ab.$s.log" \
            && note "A/B rc=0" || note "A/B failed"
    fi
    after=$(state)
    if [ "$after" != "$before" ]; then
        note "state $before -> $after; collecting evidence"
        run_leg python scripts/collect_chip_session.py "$OUT" "$EVD" \
            >/dev/null 2>&1 || true
    fi
    if autotune_done && tuned_done && ab_done && profiles_done; then
        note "all artifacts banked — done"
        exit 0
    fi
    attempt=$((attempt + 1))
    note "attempt $attempt incomplete; retrying in 10 min"
    sleep 600
done
