#!/usr/bin/env python
"""Snapshot a finished chip-session output directory into the tracked
evidence directory and print a README-ready summary.

``scripts/chip_session.sh`` writes its per-step logs/artifacts into an
output dir that is gitignored (``chip_session_logs*/``) so an aborted
window never leaves half-written files in the history.  Once a window
ends, this script copies everything worth committing into the tracked
``chip_session_r4/`` evidence dir and prints a markdown table of every
real-hardware line found, so the session can commit artifacts + README
update in one review pass.

Usage: python scripts/collect_chip_session.py [outdir] [evidence_dir]
"""

import json
import os
import shutil
import sys

# the repo root (bench.py's home) — this script runs both as
# `python scripts/collect_chip_session.py` (sys.path[0] = scripts/)
# and via importlib from the tests
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import sample_starved  # noqa: E402 - the ONE predicate


def tpu_lines(path):
    """Yield (record, line) for every real-hardware JSON line in a
    .jsonl file; garbage lines cost only themselves."""
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return
    for line in lines:
        try:
            rec = json.loads(line.strip())
            # same definition of "a real-hardware line" as bench.py's
            # _banked_tpu_lines (case-insensitive on device_kind)
            if "tpu" in (rec.get("device_kind") or "").lower():
                yield rec, line
        except Exception:
            continue


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "chip_session_logs_r4"
    evidence = sys.argv[2] if len(sys.argv) > 2 else "chip_session_r4"
    if not os.path.isdir(out):
        sys.exit("no such session dir: %s" % out)
    os.makedirs(evidence, exist_ok=True)

    def _digest(path):
        import hashlib
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 16), b""):
                h.update(chunk)
        return h.digest()

    copied = []
    for name in sorted(os.listdir(out)):
        src = os.path.join(out, name)
        if not os.path.isfile(src):
            continue
        # NEVER overwrite earlier-window evidence: same-named files
        # get a numeric suffix (bench.jsonl -> bench.2.jsonl), so
        # window 2 can't clobber the banked window-1 lines bench.py's
        # banked_tpu_lines provenance points at.  A byte-identical
        # copy is skipped entirely — the mid-session insurance
        # snapshot (chip_session_v2.sh step 1) must not duplicate the
        # ladder when the end-of-session snapshot re-collects it.
        stem, ext = os.path.splitext(name)
        dst = os.path.join(evidence, name)
        n = 2
        identical = False
        while os.path.exists(dst):
            if _digest(dst) == _digest(src):
                identical = True
                break
            dst = os.path.join(evidence, "%s.%d%s" % (stem, n, ext))
            n += 1
        if identical:
            continue
        shutil.copy2(src, dst)
        copied.append(dst)
    print("copied %d files %s -> %s" % (len(copied), out, evidence))

    def filekey(name):
        # same legacy freshness key as bench._banked_tpu_lines: the
        # collector's no-clobber suffix first — file mtimes are
        # index-order noise on a fresh git checkout, so they only
        # tie-break
        parts = name.split(".")
        num = int(parts[-2]) if len(parts) >= 3 and \
            parts[-2].isdigit() else 1
        try:
            mtime = os.path.getmtime(os.path.join(evidence, name))
        except OSError:
            mtime = 0.0
        return (num, mtime)

    # per-LINE rows, ordered oldest -> newest: in-band ``ts`` (bench
    # _dumps stamps every r5+ record) outranks the legacy file key —
    # it is the only chronology that survives a fresh checkout
    rows = []
    for name in sorted((n for n in os.listdir(evidence)
                        if n.endswith(".jsonl")), key=filekey):
        num, mtime = filekey(name)
        for li, (rec, _line) in enumerate(
                tpu_lines(os.path.join(evidence, name))):
            ts = rec.get("ts")
            key = (1, float(ts), 0.0, li) \
                if isinstance(ts, (int, float)) \
                else (0, float(num), mtime, li)
            rows.append((key, rec, name))
    rows.sort(key=lambda r: r[0])
    rows = [(rec, name) for _key, rec, name in rows]
    if not rows:
        print("no real-hardware lines found")
        return
    # newest valid LINE per (metric, device kind): every older row is
    # explicitly marked superseded so a stale number can never be
    # quoted as current from this index (VERDICT r4 weak item 7);
    # keyed by row identity, not source file, so a within-file
    # duplicate can't leave two "current" values (code-review r5).
    # Mirrors bench._banked_tpu_lines: banked echoes and sample-
    # starved lines (a dying window's ONE-batch e2e "measurement"
    # times the transport, not the framework) never supersede a
    # substantive measurement; a starved line is current only when it
    # is all there is, flagged low-confidence.  The predicate is
    # bench.sample_starved — shared, not copied (ADVICE r5).
    newest = {}
    starved_newest = {}
    for i, (rec, name) in enumerate(rows):
        if "error" in rec or rec.get("banked"):
            continue
        key = (rec.get("metric"), rec.get("device_kind"))
        if sample_starved(rec):
            starved_newest[key] = i
        else:
            newest[key] = i
    for key, i in starved_newest.items():
        newest.setdefault(key, i)
    lines = ["# Real-hardware evidence index",
             "",
             "Generated by scripts/collect_chip_session.py from the",
             "committed window artifacts in this directory.  Only",
             "rows marked **current** are quotable; superseded rows",
             "are retained for provenance only.",
             "",
             "| metric | value | unit | MFU | vs_baseline | source "
             "| status |",
             "|---|---|---|---|---|---|---|"]
    for i, (rec, name) in enumerate(rows):
        key = (rec.get("metric"), rec.get("device_kind"))
        if "error" in rec:
            status = "error (not a measurement)"
        elif rec.get("banked"):
            status = "banked echo (provenance, not a measurement)"
        elif newest.get(key) == i:
            status = ("**current** (LOW CONFIDENCE: sample-starved)"
                      if sample_starved(rec) else "**current**")
        elif sample_starved(rec):
            j = newest.get(key)
            status = "sample-starved (times the transport, not the " \
                "framework)%s" % ("; see %s" % rows[j][1]
                                  if j is not None else "")
        else:
            j = newest.get(key)
            status = "superseded by %s" % (
                rows[j][1] if j is not None else "?")
        lines.append("| %s | %s | %s | %s | %s | %s | %s |" % (
            rec.get("metric"),
            ("%.4g" % rec["value"]) if isinstance(
                rec.get("value"), (int, float)) else rec.get("value"),
            rec.get("unit"),
            rec.get("mfu", ""),
            rec.get("vs_baseline", ""),
            name, status))
    table = "\n".join(lines)
    print("\n" + "\n".join(lines[7:]))
    with open(os.path.join(evidence, "EVIDENCE.md"), "w") as fh:
        fh.write(table + "\n")


if __name__ == "__main__":
    main()
