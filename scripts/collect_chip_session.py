#!/usr/bin/env python
"""Snapshot a finished chip-session output directory into the tracked
evidence directory and print a README-ready summary.

``scripts/chip_session.sh`` writes its per-step logs/artifacts into an
output dir that is gitignored (``chip_session_logs*/``) so an aborted
window never leaves half-written files in the history.  Once a window
ends, this script copies everything worth committing into the tracked
``chip_session_r4/`` evidence dir and prints a markdown table of every
real-hardware line found, so the session can commit artifacts + README
update in one review pass.

Usage: python scripts/collect_chip_session.py [outdir] [evidence_dir]
"""

import json
import os
import shutil
import sys


def tpu_lines(path):
    """Yield (record, line) for every real-hardware JSON line in a
    .jsonl file; garbage lines cost only themselves."""
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return
    for line in lines:
        try:
            rec = json.loads(line.strip())
            # same definition of "a real-hardware line" as bench.py's
            # _banked_tpu_lines (case-insensitive on device_kind)
            if "tpu" in (rec.get("device_kind") or "").lower():
                yield rec, line
        except Exception:
            continue


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "chip_session_logs_r4"
    evidence = sys.argv[2] if len(sys.argv) > 2 else "chip_session_r4"
    if not os.path.isdir(out):
        sys.exit("no such session dir: %s" % out)
    os.makedirs(evidence, exist_ok=True)

    copied = []
    for name in sorted(os.listdir(out)):
        src = os.path.join(out, name)
        if not os.path.isfile(src):
            continue
        # NEVER overwrite earlier-window evidence: same-named files
        # get a numeric suffix (bench.jsonl -> bench.2.jsonl), so
        # window 2 can't clobber the banked window-1 lines bench.py's
        # banked_tpu_lines provenance points at
        stem, ext = os.path.splitext(name)
        dst = os.path.join(evidence, name)
        n = 2
        while os.path.exists(dst):
            dst = os.path.join(evidence, "%s.%d%s" % (stem, n, ext))
            n += 1
        shutil.copy2(src, dst)
        copied.append(dst)
    print("copied %d files %s -> %s" % (len(copied), out, evidence))

    rows = []
    for name in sorted(os.listdir(evidence)):
        if not name.endswith(".jsonl"):
            continue
        for rec, _line in tpu_lines(os.path.join(evidence, name)):
            rows.append((rec, name))
    if not rows:
        print("no real-hardware lines found")
        return
    print("\n| metric | value | unit | MFU | vs_baseline | source |")
    print("|---|---|---|---|---|---|")
    for rec, name in rows:
        print("| %s | %s | %s | %s | %s | %s |" % (
            rec.get("metric"),
            ("%.4g" % rec["value"]) if isinstance(
                rec.get("value"), (int, float)) else rec.get("value"),
            rec.get("unit"),
            rec.get("mfu", ""),
            rec.get("vs_baseline", ""),
            name))


if __name__ == "__main__":
    main()
