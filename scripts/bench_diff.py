#!/usr/bin/env python
"""bench_diff — the perf-regression watchdog over the bench ladder.

The repo banks every round's headline bench lines as ``BENCH_r0*.json``
(``{"parsed": {...}, "tail": ...}`` envelopes whose ``parsed`` record
is one ``bench.py`` stdout line: metric / value / unit / mfu /
sec_per_step / device_kind / ...).  Until now those files were an
archive; this script makes them a GATE: compare a fresh ``bench.py``
run (or any saved JSONL of its stdout lines) against the banked
envelope per stage and exit non-zero on any regression beyond the
tolerance.

Comparison model, per metric name (records are matched by ``metric``
AND ``device_kind`` — a CPU smoke is never judged against a banked TPU
line; ``--ignore-device`` overrides):

* ``value`` — direction inferred from ``unit`` (throughput units are
  higher-better; ``sec``/``ms``/``latency`` units lower-better);
  regression when worse than banked by more than ``--tolerance``
  (relative).
* ``mfu`` — higher-better, same tolerance.
* ``sec_per_step`` — lower-better, same tolerance.
* ``recompiles`` / ``dispatches_per_epoch`` — hard counters: any
  increase over the banked value is a regression (zero tolerance; a
  recompile that "only" costs 5% today is a compile-cache bug either
  way).
* ``steps_per_dispatch`` — lower than banked by more than the
  tolerance is a regression (the one-dispatch-epoch win eroding).
* ``vs_bf16_x`` (higher-better) / ``hbm_per_request_bytes``
  (lower-better) — the ``stage_transformer_gen`` int8 + long-tail
  columns: the quantized-serving throughput win and the per-request
  HBM footprint, gated so the int8 win is a number from round one.

Usage::

    python scripts/bench_diff.py --fresh run.jsonl          # gate a run
    python scripts/bench_diff.py --run                      # run bench.py now
    python scripts/bench_diff.py --selftest                 # CI self-test
    python scripts/bench_diff.py --fresh - < run.jsonl      # stdin

``--banked`` defaults to the repo's ``BENCH_r0*.json`` set; when
several banked records share a (metric, device kind), the NEWEST (by
in-band ``ts``, falling back to file order) wins — the envelope is the
latest accepted performance, not the best-ever (hardware sessions
differ; the newest banked line is the one the current code was
accepted against).  The envelope keys by the PAIR, so a newer line
from another device never evicts the matching-device gate.

Exit codes: 0 = no regression, 1 = regression(s) (each printed as
``REGRESSION <metric> <field>: fresh X vs banked Y (limit Z)``),
2 = usage/infrastructure error (no comparable pairs is NOT an error —
it prints a warning and exits 0, so a CPU container passes against a
TPU-only bank without faking numbers).
"""

import argparse
import glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: unit substrings that mean lower-is-better for ``value`` — checked
#: only after the rate forms ("images/sec", "tokens/s") claim
#: higher-is-better.  The rate check must NOT treat "sec/step" as a
#: rate ("/s" is a substring of "/step"), hence the endswith form.
_LOWER_BETTER_UNITS = ("sec", "ms", "latency", "/step", "bytes")


def _is_rate_unit(unit):
    return "/sec" in unit or unit.endswith("/s") or "per sec" in unit

#: hard counters: any increase over banked is a regression
_COUNTERS = ("recompiles", "dispatches_per_epoch")

#: soft fields beyond ``value`` compared with the relative tolerance
#: (vs_bf16_x: the int8 serving win over the same-run bf16 engine;
#: hbm_per_request_bytes: the paged/int8 capacity win — both from
#: the stage_transformer_gen int8/long-tail records;
#: ttft_p99_ms / handoff_bytes_per_request / autoscaler_actions: the
#: disagg-fleet record — latency under the 500 ms SLO, wire cost per
#: request, and control-loop churn are all regressions when they
#: grow)
#: vs_baseline joins the higher-better set for the same-run A/B
#: stages (transformer_lm_train: fused kernels over the XLA-kernel
#: baseline measured in the SAME process — the ratio eroding means
#: the fused path lost ground even if absolute throughput moved)
#: prefix_hit_rate / spec_accept_rate / vs_nonspec_x: the
#: prefix-cache + speculative-decode record — pages served from the
#: radix tree, drafted tokens the verify accepted, and the
#: tokens/s win over the same-run plain paged line all regress when
#: they fall
_HIGHER_BETTER_FIELDS = ("mfu", "steps_per_dispatch", "vs_bf16_x",
                         "vs_baseline", "prefix_hit_rate",
                         "spec_accept_rate", "vs_nonspec_x")
#: bubble_fraction / all_to_all_bytes_per_step: the pod pp/ep stages —
#: the GPipe ramp/drain idle share and the per-step expert-exchange
#: traffic are both pure cost; either growing means the pipeline
#: schedule or the routing buffers regressed
_LOWER_BETTER_FIELDS = ("sec_per_step", "hbm_per_request_bytes",
                        "ttft_p99_ms", "handoff_bytes_per_request",
                        "autoscaler_actions", "bubble_fraction",
                        "all_to_all_bytes_per_step")


def value_direction(record):
    """+1 = higher better, -1 = lower better, from the unit string."""
    unit = str(record.get("unit", "")).lower()
    if _is_rate_unit(unit):
        return 1
    if any(tag in unit for tag in _LOWER_BETTER_UNITS):
        return -1
    return 1


def iter_records(payload):
    """Yield bench stdout records (dicts with a ``metric`` key) from
    any of the shapes the repo stores them in: a raw record, a
    ``BENCH_r0*.json`` envelope (``parsed``), or a list of either."""
    if isinstance(payload, list):
        for item in payload:
            yield from iter_records(item)
        return
    if not isinstance(payload, dict):
        return
    if "metric" in payload:
        yield payload
        return
    parsed = payload.get("parsed")
    if parsed is not None:
        yield from iter_records(parsed)


def load_banked(paths):
    """``{(metric, device_kind): record}`` — newest banked record per
    (metric, device kind) pair (in-band ``ts`` first, file order as
    the tiebreak).  Keying by the PAIR matters: a newer banked line
    from a different device must not evict the matching-device
    envelope and silently un-gate that metric."""
    envelope = {}
    order = {}
    for rank, path in enumerate(paths):
        try:
            with open(path, "r") as fin:
                payload = json.load(fin)
        except (OSError, ValueError) as exc:
            print("bench_diff: cannot read %s: %s" % (path, exc),
                  file=sys.stderr)
            continue
        for record in iter_records(payload):
            metric = record.get("metric")
            if not metric:
                continue
            key = (metric, record.get("device_kind"))
            stamp = (record.get("ts") or 0, rank)
            if key not in envelope or stamp >= order[key]:
                envelope[key] = record
                order[key] = stamp
    return envelope


def _bank_lookup(banked, metric, device_kind, ignore_device=False):
    """The envelope record a fresh record gates against: the exact
    (metric, device_kind) entry, or — under ``ignore_device`` — the
    newest banked record for the metric across devices."""
    if not ignore_device:
        return banked.get((metric, device_kind))
    best, best_ts = None, None
    for (m, _d), record in banked.items():
        if m != metric:
            continue
        ts = record.get("ts") or 0
        if best is None or ts >= best_ts:
            best, best_ts = record, ts
    return best


def load_fresh(stream):
    """Bench stdout lines (JSONL; non-JSON lines are bench chatter and
    skipped) → list of records.  Records tagged ``"banked": true``
    are DROPPED: bench.py re-emits the banked lines verbatim on a
    dead/degraded session, and gating an echo of the bank against the
    bank would pass a run that measured nothing (the 'nothing gated'
    warning exists for exactly that case)."""
    records = []
    echoes = 0
    for line in stream:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            payload = json.loads(line)
        except ValueError:
            continue
        for record in iter_records(payload):
            if record.get("banked"):
                echoes += 1
                continue
            records.append(record)
    if echoes:
        print("bench_diff: %d banked echo record(s) in the fresh run "
              "ignored (not live measurements)" % echoes,
              file=sys.stderr)
    return records


def _rel_worse(fresh, banked, direction):
    """How much worse (fraction of banked) ``fresh`` is; <= 0 means
    no regression."""
    if banked == 0:
        return 0.0
    return direction * (banked - fresh) / abs(banked)


def compare(fresh_records, banked, tolerance=0.1, ignore_device=False):
    """Return ``(regressions, compared)``: regression message lines
    and the number of (metric, field) pairs actually compared."""
    regressions = []
    compared = 0
    for record in fresh_records:
        metric = record.get("metric")
        bank = _bank_lookup(banked, metric,
                            record.get("device_kind"),
                            ignore_device=ignore_device)
        if bank is None:
            continue

        def _soft(field, direction, fresh_v, bank_v):
            worse = _rel_worse(float(fresh_v), float(bank_v),
                               direction)
            if worse > tolerance:
                regressions.append(
                    "REGRESSION %s %s: fresh %.6g vs banked %.6g "
                    "(%.1f%% worse, tolerance %.1f%%)"
                    % (metric, field, float(fresh_v), float(bank_v),
                       100.0 * worse, 100.0 * tolerance))

        if isinstance(record.get("value"), (int, float)) \
                and isinstance(bank.get("value"), (int, float)):
            compared += 1
            _soft("value", value_direction(bank), record["value"],
                  bank["value"])
        for field in _HIGHER_BETTER_FIELDS:
            if isinstance(record.get(field), (int, float)) \
                    and isinstance(bank.get(field), (int, float)):
                compared += 1
                _soft(field, 1, record[field], bank[field])
        for field in _LOWER_BETTER_FIELDS:
            if isinstance(record.get(field), (int, float)) \
                    and isinstance(bank.get(field), (int, float)):
                compared += 1
                _soft(field, -1, record[field], bank[field])
        for field in _COUNTERS:
            if isinstance(record.get(field), (int, float)) \
                    and isinstance(bank.get(field), (int, float)):
                compared += 1
                if float(record[field]) > float(bank[field]):
                    regressions.append(
                        "REGRESSION %s %s: fresh %g vs banked %g "
                        "(hard counter, zero tolerance)"
                        % (metric, field, float(record[field]),
                           float(bank[field])))
    return regressions, compared


def run_bench(stages=None):
    """Run ``bench.py`` in a child and return its stdout records."""
    import subprocess
    env = dict(os.environ)
    if stages:
        env["BENCH_STAGES"] = stages
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT)
    sys.stderr.write(proc.stderr[-2000:])
    if proc.returncode:
        print("bench_diff: bench.py exited %d" % proc.returncode,
              file=sys.stderr)
        sys.exit(2)
    return load_fresh(proc.stdout.splitlines())


def selftest(banked_paths, tolerance):
    """The CI self-test over the real banked files:

    1. banked-vs-banked must report ZERO regressions (the gate would
       otherwise fail every honest re-run);
    2. a synthetically degraded copy (throughput halved, MFU halved,
       recompiles bumped) must be caught on every degraded field;
    3. a device_kind mismatch must be skipped, not compared.
    """
    banked = load_banked(banked_paths)
    if not banked:
        print("bench_diff selftest: FAIL — no banked records under %r"
              % (banked_paths,), file=sys.stderr)
        return 1
    records = list(banked.values())
    regressions, compared = compare(records, banked,
                                    tolerance=tolerance)
    if regressions or not compared:
        print("bench_diff selftest: FAIL — banked-vs-banked: %d "
              "compared, regressions %r" % (compared, regressions),
              file=sys.stderr)
        return 1
    degraded = []
    expect = 0
    for record in records:
        bad = dict(record)
        if isinstance(bad.get("value"), (int, float)):
            bad["value"] = bad["value"] * (2.0 if value_direction(
                bad) < 0 else 0.5)
            expect += 1
        if isinstance(bad.get("mfu"), (int, float)):
            bad["mfu"] = bad["mfu"] * 0.5
            expect += 1
        bad["recompiles"] = float(bad.get("recompiles", 0) or 0) + 5
        if isinstance(record.get("recompiles"), (int, float)):
            expect += 1
        degraded.append(bad)
    regressions, _ = compare(degraded, banked, tolerance=tolerance)
    if len(regressions) < expect:
        print("bench_diff selftest: FAIL — degraded run: %d "
              "regression(s) caught, expected >= %d:\n%s"
              % (len(regressions), expect, "\n".join(regressions)),
              file=sys.stderr)
        return 1
    moved = [dict(record, device_kind="somewhere-else")
             for record in records]
    regressions, compared = compare(
        [dict(r, value=0.0) for r in moved], banked,
        tolerance=tolerance)
    if compared or regressions:
        print("bench_diff selftest: FAIL — device mismatch was "
              "compared anyway", file=sys.stderr)
        return 1
    print("bench_diff selftest: OK — %d banked envelope line(s), "
          "degraded copies caught on %d field(s), device mismatch "
          "skipped" % (len(banked), expect))
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bench_diff",
        description="gate a bench.py run against the banked "
                    "BENCH_r0*.json envelope")
    parser.add_argument("--banked", nargs="*", default=None,
                        metavar="FILE",
                        help="banked envelope files (default: the "
                             "repo's BENCH_r0*.json)")
    parser.add_argument("--fresh", metavar="FILE",
                        help="a saved bench.py stdout (JSONL); '-' "
                             "reads stdin")
    parser.add_argument("--run", action="store_true",
                        help="run bench.py now and gate its output")
    parser.add_argument("--stages", default=None,
                        help="BENCH_STAGES for --run")
    parser.add_argument("--tolerance", type=float, default=0.1,
                        help="relative tolerance for soft fields "
                             "(default 0.10)")
    parser.add_argument("--ignore-device", action="store_true",
                        help="compare across device kinds (A/B on "
                             "different hardware is lying with "
                             "numbers; you were warned)")
    parser.add_argument("--selftest", action="store_true",
                        help="validate the comparator against the "
                             "banked files (CI)")
    ns = parser.parse_args(argv)
    banked_paths = ns.banked if ns.banked else sorted(
        glob.glob(os.path.join(REPO_ROOT, "BENCH_r0*.json")))
    if ns.selftest:
        return selftest(banked_paths, ns.tolerance)
    if ns.run:
        fresh = run_bench(ns.stages)
    elif ns.fresh == "-":
        fresh = load_fresh(sys.stdin)
    elif ns.fresh:
        with open(ns.fresh, "r") as fin:
            fresh = load_fresh(fin)
    else:
        parser.error("one of --fresh/--run/--selftest is required")
        return 2
    banked = load_banked(banked_paths)
    regressions, compared = compare(
        fresh, banked, tolerance=ns.tolerance,
        ignore_device=ns.ignore_device)
    if regressions:
        print("\n".join(regressions))
        print("bench_diff: %d regression(s) over %d comparison(s)"
              % (len(regressions), compared))
        return 1
    if not compared:
        print("bench_diff: WARNING — no comparable (metric, "
              "device_kind) pairs between the fresh run (%d record(s))"
              " and the bank (%d envelope line(s)); nothing gated"
              % (len(fresh), len(banked)))
        return 0
    print("bench_diff: OK — %d comparison(s) within tolerance %.1f%%"
          % (compared, 100.0 * ns.tolerance))
    return 0


if __name__ == "__main__":
    sys.exit(main())
