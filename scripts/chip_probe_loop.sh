#!/bin/bash
# Round-4 persistent tunnel watcher: loop the probe+session script
# (which owns the never-SIGKILL tunnel discipline) until it succeeds.
# Failures — tunnel down OR a session that died mid-way — back off
# 10 min and retry the whole probe+session.
set -u
cd "$(dirname "$0")/.."

note() { echo "[probe-loop $(date +%H:%M:%S)] $*"; }

attempt=0
until bash scripts/chip_probe_and_session.sh; do
    attempt=$((attempt + 1))
    note "attempt $attempt failed; retrying in 10 min"
    sleep 600
done
note "chip session completed"
