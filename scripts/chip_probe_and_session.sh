#!/bin/bash
# Round-4 hardware front-loader. Probes the axon tunnel with NO kill
# (killing a JAX client mid-claim wedges the relay for hours — see
# ROUND3_NOTES.md), and the moment the chip answers, runs the full
# chip_session.sh to produce every hardware artifact of the round.
#
#     nohup bash scripts/chip_probe_and_session.sh >chip_probe_r4.log 2>&1 &
#
# The probe is allowed to hang indefinitely; progress is visible in the
# log timestamps. Nothing here ever sends SIGKILL to a JAX client.
# Exit status: probe rc if the tunnel is down, else chip_session's rc.
set -u
cd "$(dirname "$0")/.."

note() { echo "[probe $(date +%H:%M:%S)] $*"; }

note "probing tunnel (patient, unkillable probe)"
python - <<'PYEOF'
import datetime
import jax

print("probe import done", datetime.datetime.now(), flush=True)
devs = jax.devices()
print("devices:", devs, flush=True)
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
y = (x @ x).sum()
print("warm matmul:", float(y), datetime.datetime.now(), flush=True)
PYEOF
rc=$?
note "probe rc=$rc"
if [ "$rc" -ne 0 ]; then
    note "tunnel down/wedged; not starting chip session"
    exit "$rc"
fi

note "tunnel LIVE — starting chip_session (v2: one claim per step)"
bash scripts/chip_session_v2.sh "${CHIP_SESSION_OUT:-chip_session_logs_r5}"
rc=$?
note "chip_session done rc=$rc"
exit "$rc"
