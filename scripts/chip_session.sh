#!/bin/bash
# One serialized TPU session producing every hardware artifact of the
# round: autotune DB -> bench ladder -> AlexNet profile -> s2d A/B.
# Run from the repo root when the tunnel is up:
#
#     bash scripts/chip_session.sh [outdir]
#
# Everything is sequential (two JAX clients racing for the single chip
# claim can wedge the tunnel relay — see ROUND3_NOTES.md), nothing here
# kills a client mid-claim, and each step's log survives in $OUT.
set -u
OUT=${1:-chip_session_logs}
mkdir -p "$OUT"

note() { echo "[chip_session] $*" >&2; }

note "1/4 autotune sweep (fills veles_tpu/devices/device_infos.json)"
# full candidate sweep over the production shape classes at precision
# level 0, then a pruned pallas-vs-xla race at the Kahan/multipartial
# levels 1,2 (entries keyed per (dtype, precision) — VERDICT r3 item 4)
python -m veles_tpu.scripts.autotune --precision-levels 0,1,2 \
    >"$OUT/autotune.json" 2>"$OUT/autotune.log"
note "autotune rc=$? (DB: veles_tpu/devices/device_infos.json)"

note "2/4 bench ladder"
BENCH_BUDGET_SEC=${BENCH_BUDGET_SEC:-2400} python bench.py \
    >"$OUT/bench.jsonl" 2>"$OUT/bench.log"
note "bench rc=$? (lines: $(wc -l <"$OUT/bench.jsonl"))"

note "2b/4 AlexNet batch sweep (256 vs 512)"
BENCH_STAGES=alexnet BENCH_ALEXNET_BATCH=512 BENCH_BUDGET_SEC=900 \
    python bench.py >"$OUT/alexnet_b512.jsonl" 2>"$OUT/alexnet_b512.log"
note "alexnet b512 rc=$?"

note "3/4 AlexNet step profile -> PROFILE.md"
python -m veles_tpu.scripts.profile_step --sample alexnet --batch 256 \
    --out PROFILE.md >"$OUT/profile.log" 2>&1
note "profile rc=$?"

note "4/4 s2d conv A/B (substantiates the space-to-depth rewrite)"
python - >"$OUT/s2d_ab.txt" 2>&1 <<'EOF'
import jax, jax.numpy as jnp, numpy
from veles_tpu.ops.timing import inprogram_marginal
from veles_tpu.znicz.conv import Conv

rng = numpy.random.default_rng(0)
batch = 256
x = jnp.asarray(rng.standard_normal((batch, 227, 227, 3)),
                jnp.bfloat16)
w = jnp.asarray(rng.standard_normal((11, 11, 3, 96)) * 0.01,
                jnp.bfloat16)
flops = 2.0 * batch * 55 * 55 * 96 * 11 * 11 * 3
for s2d in (False, True):
    def unit(carry, _s2d=s2d):
        xx, s = carry
        xx = jax.lax.dynamic_update_slice(
            xx, (xx[0:1, 0:1, 0:1, 0:1] + (s * 1e-30).astype(xx.dtype)),
            (0, 0, 0, 0))
        o = Conv.pure({"w": w}, xx, sliding=(4, 4), s2d=_s2d)
        return xx, jnp.sum(jnp.abs(o), dtype=jnp.float32)
    sec = inprogram_marginal(unit, (x, jnp.float32(0.0)), k1=4, k2=32)
    print("s2d=%s: %.3f ms/conv1, %.1f TFLOP/s effective"
          % (s2d, sec * 1e3, flops / sec / 1e12))
EOF
note "s2d A/B rc=$? (see $OUT/s2d_ab.txt)"
note "done — review $OUT, commit the DB and PROFILE.md"
