#!/bin/bash
# One serialized TPU session producing every hardware artifact of the
# round, MOST IMPORTANT FIRST — round-3 post-mortem: tunnel windows can
# be ~30 min, so the bench ladder + AlexNet profile (the three-rounds-
# missing headline artifacts) run before the long autotune sweep.
#
#     bash scripts/chip_session.sh [outdir]
#
# Everything is sequential (two JAX clients racing for the single chip
# claim can wedge the tunnel relay — see ROUND3_NOTES.md), nothing here
# kills a client mid-claim, and each step's log survives in $OUT.
set -u
OUT=${1:-chip_session_logs}
mkdir -p "$OUT"

# one persistent XLA executable cache for EVERY step (single source of
# truth: backends.COMPILE_CACHE_DIR): conv-model first compiles over
# the tunnel run for minutes, pay each exactly once
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-$(python -c \
    'from veles_tpu.backends import COMPILE_CACHE_DIR; print(COMPILE_CACHE_DIR)' \
    2>/dev/null || echo "$HOME/.veles_tpu/cache/xla")}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
# r4 live-window calibration: conv stages need ~3-4x the default caps.
# Budgets scale with it; float-safe (bash $((...)) is integer-only) and
# garbage scale values fall back to the calibrated 4x, like bench.py's
# own guard
export BENCH_TIMEOUT_SCALE=${BENCH_TIMEOUT_SCALE:-4}
scaled() { python - "$1" "$BENCH_TIMEOUT_SCALE" <<'PY'
import sys
try:
    s = float(sys.argv[2])
except ValueError:
    s = 4.0
print(int(float(sys.argv[1]) * (s if s > 0 else 4.0)))
PY
}

note() { echo "[chip_session $(date +%H:%M:%S)] $*" >&2; }

note "1/6 bench ladder (the BENCH_r04 headline lines; dispatch uses"
note "    the committed round-3 DB — step 6 re-benches post-sweep)"
# the budget stretches with the timeout scale: conv first compiles on
# a cold cache are what the scale exists for, and the AlexNet headline
# reserve inside bench.py scales the same way
BENCH_BUDGET_SEC=${BENCH_BUDGET_SEC:-$(scaled 1500)} \
    python bench.py >"$OUT/bench.jsonl" 2>"$OUT/bench.log"
note "bench rc=$? (lines: $(wc -l <"$OUT/bench.jsonl"))"

note "2/6 AlexNet step profile -> PROFILE.md"
python -m veles_tpu.scripts.profile_step --sample alexnet --batch 256 \
    --out PROFILE.md >"$OUT/profile.log" 2>&1
note "profile rc=$?"

note "2b/6 AlexNet batch sweep (256 vs 512)"
BENCH_STAGES=alexnet BENCH_ALEXNET_BATCH=512 BENCH_BUDGET_SEC=$(scaled 900) \
    python bench.py >"$OUT/alexnet_b512.jsonl" 2>"$OUT/alexnet_b512.log"
note "alexnet b512 rc=$?"

note "3/6 s2d conv A/B (substantiates the space-to-depth rewrite)"
python - >"$OUT/s2d_ab.txt" 2>&1 <<'PYEOF'
import jax, jax.numpy as jnp, numpy
from veles_tpu.ops.timing import inprogram_marginal
from veles_tpu.znicz.conv import Conv

rng = numpy.random.default_rng(0)
batch = 256
x = jnp.asarray(rng.standard_normal((batch, 227, 227, 3)),
                jnp.bfloat16)
w = jnp.asarray(rng.standard_normal((11, 11, 3, 96)) * 0.01,
                jnp.bfloat16)
flops = 2.0 * batch * 55 * 55 * 96 * 11 * 11 * 3
for s2d in (False, True):
    def unit(carry, _s2d=s2d):
        xx, s = carry
        xx = jax.lax.dynamic_update_slice(
            xx, (xx[0:1, 0:1, 0:1, 0:1] + (s * 1e-30).astype(xx.dtype)),
            (0, 0, 0, 0))
        o = Conv.pure({"w": w}, xx, sliding=(4, 4), s2d=_s2d)
        return xx, jnp.sum(jnp.abs(o), dtype=jnp.float32)
    sec = inprogram_marginal(unit, (x, jnp.float32(0.0)), k1=4, k2=32)
    print("s2d=%s: %.3f ms/conv1, %.1f TFLOP/s effective"
          % (s2d, sec * 1e3, flops / sec / 1e12))
PYEOF
note "s2d A/B rc=$? (see $OUT/s2d_ab.txt)"

note "4/6 autotune sweep, level 0 production shapes + attention regimes"
python -m veles_tpu.scripts.autotune >"$OUT/autotune.json" \
    2>"$OUT/autotune.log"
note "autotune rc=$? (DB: veles_tpu/devices/device_infos.json)"

note "5/6 autotune precision levels 1,2 (pruned pallas-vs-xla race)"
python -m veles_tpu.scripts.autotune --precision-levels 1,2 \
    --skip-attention --skip-power \
    >"$OUT/autotune_p12.json" 2>"$OUT/autotune_p12.log"
note "autotune p1/p2 rc=$?"

note "6/6 re-bench the heavies with the FRESH per-shape-class DB"
BENCH_STAGES=mnist,lstm,transformer,alexnet BENCH_BUDGET_SEC=$(scaled 900) \
    python bench.py >"$OUT/bench_tuned.jsonl" \
    2>"$OUT/bench_tuned.log"
note "tuned re-bench rc=$? (lines: $(wc -l <"$OUT/bench_tuned.jsonl"))"
note "done — review $OUT, commit the DB, PROFILE.md and the faster of"
note "bench.jsonl / bench_tuned.jsonl per stage"
