#!/bin/bash
# One serialized TPU session, MINIMUM backend claims — live-window
# post-mortems (r4 windows 1 & 2) showed the tunnel relay stops
# GRANTING claims a few minutes into a window while established
# clients keep working, so every extra process = a doomed re-claim.
#
#     bash scripts/chip_session_v2.sh [outdir]
#
# 1/3  one-claim bench ladder — probe, MLP lines, the AlexNet headline
#      (+ batch-512 sweep point), PROFILE.md, the s2d A/B, LM/LSTM/
#      e2e/power — ALL inside a single child process (bench.py
#      --ladder design).
# 2/3  autotune sweep, precision levels 0,1,2 in ONE invocation.
# 3/3  warm re-bench of the heavies with the fresh DB.
#
# Exit 0 only when the AlexNet headline landed on real hardware —
# the probe loop keeps retrying windows until it does.  Nothing here
# SIGKILLs a JAX client (a mid-claim kill wedges the relay for hours).
set -u
OUT=${1:-chip_session_logs}
mkdir -p "$OUT"

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-$(python -c \
    'from veles_tpu.backends import COMPILE_CACHE_DIR; print(COMPILE_CACHE_DIR)' \
    2>/dev/null || echo "$HOME/.veles_tpu/cache/xla")}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
# r4 live-window calibration: claims + conv first compiles over the
# tunnel need ~4x the local caps
export BENCH_TIMEOUT_SCALE=${BENCH_TIMEOUT_SCALE:-4}

note() { echo "[chip_session $(date +%H:%M:%S)] $*" >&2; }

headline_landed() {
    python - "$@" <<'PY'
import json
import sys

for path in sys.argv[1:]:
    try:
        lines = open(path).readlines()
    except OSError:
        continue
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if (rec.get("metric") ==
                "AlexNet fused train throughput per chip (bf16)"
                and "TPU" in (rec.get("device_kind") or "")):
            sys.exit(0)
sys.exit(1)
PY
}

note "1/3 one-claim bench ladder (headline + PROFILE.md + s2d ride ONE claim)"
BENCH_BUDGET_SEC=${BENCH_BUDGET_SEC:-6000} \
    BENCH_PER_LAYER=${BENCH_PER_LAYER:-1} \
    python bench.py >"$OUT/bench.jsonl" 2>"$OUT/bench.log"
note "bench rc=$? (lines: $(wc -l <"$OUT/bench.jsonl"))"

if ! headline_landed "$OUT/bench.jsonl"; then
    note "AlexNet headline NOT banked — skipping the sweep so the"
    note "probe loop retries the ladder at the next window"
    exit 1
fi
# half-window insurance: bank the ladder into the TRACKED evidence dir
# NOW — a tunnel death during autotune/re-bench must not cost the
# already-measured headline (the collector never overwrites, so the
# end-of-session snapshot below just adds suffixed copies of the rest)
python scripts/collect_chip_session.py "$OUT" chip_session_r5 \
    >/dev/null 2>&1 || note "mid-session collector failed"

note "1b/3 per-layer profiles for the two unadjudicated MFU stages"
# VERDICT r4 item 6: LSTM 0.115 / CIFAR 0.17 need a committed
# per-stage artifact (fix or roofline); these two runs provide the
# measured side of docs/performance.md's roofline notes
python -m veles_tpu.scripts.profile_step --sample cifar10 \
    --batch 1024 --per-layer --out PROFILE_CIFAR.md \
    >>"$OUT/profile.log" 2>&1 || note "cifar profile failed"
python -m veles_tpu.scripts.profile_step --sample mnist_rnn \
    --batch 2048 --out PROFILE_LSTM.md \
    >>"$OUT/profile.log" 2>&1 || note "lstm profile failed"

note "2/3 autotune sweep (levels 0,1,2 + attention + power, one claim)"
python -m veles_tpu.scripts.autotune --precision-levels 0,1,2 \
    >"$OUT/autotune.json" 2>"$OUT/autotune.log"
note "autotune rc=$? (DB: veles_tpu/devices/device_infos.json)"

note "3/3 re-bench the heavies with the fresh per-shape-class DB"
# transformer + profile_lm re-measure the LM with the swept backward
# blocks (VERDICT r5 target: backward >= 50 TFLOP/s); the epoch/e2e
# legs re-measure with the raced gather verdict
BENCH_STAGES=mnist,lstm,transformer,profile_lm,alexnet,alexnet_e2e,alexnet_epoch \
    BENCH_BUDGET_SEC=3600 \
    python bench.py >"$OUT/bench_tuned.jsonl" 2>"$OUT/bench_tuned.log"
note "tuned re-bench rc=$? (lines: $(wc -l <"$OUT/bench_tuned.jsonl"))"
# snapshot into the tracked evidence dir immediately (no-clobber), so
# a window that lands unattended still banks its artifacts; the
# builder commits the evidence dir, PROFILE*.md and the DB afterwards
EVD=chip_session_r5
python scripts/collect_chip_session.py "$OUT" "$EVD" >/dev/null 2>&1 \
    || note "collector failed — snapshot manually"
note "done — evidence snapshotted; commit $EVD/,"
note "PROFILE.md / PROFILE_LM.md and the refreshed device DB"
exit 0
