"""Fuzz the fused lowering over random-but-valid layer stacks.

The reference's zoo is exercised by hand-picked configs; this sweeps
the combination space (conv/pool/LRN/dropout stacks of random depth and
geometry, dense tails, recurrent heads) and asserts every stack lowers,
steps, and stays finite — the class of shape-inference and
dtype-propagation bugs integration tests miss."""

import jax.numpy as jnp
import numpy
import pytest

from veles_tpu import prng
from veles_tpu.znicz.fused_graph import lower_specs


def _random_conv_stack(rng, h, w):
    """Random conv/pool/lrn/dropout prefix that keeps spatial dims
    >= 4, followed by a dense tail."""
    layers = []
    depth = int(rng.integers(1, 4))
    for _ in range(depth):
        kind = rng.choice(["conv", "pool", "lrn", "dropout"])
        if kind == "conv" and min(h, w) >= 5:
            k = int(rng.choice([3, 5]))
            stride = int(rng.choice([1, 2]))
            pad = int(rng.integers(0, 2))
            layers.append({
                "type": str(rng.choice(
                    ["conv_tanh", "conv_strict_relu", "conv_sigmoid"])),
                "->": {"n_kernels": int(rng.choice([4, 8])),
                       "kx": k, "ky": k, "padding": pad,
                       "sliding": (stride, stride)},
                "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}})
            h = (h + 2 * pad - k) // stride + 1
            w = (w + 2 * pad - k) // stride + 1
        elif kind == "pool" and min(h, w) >= 4:
            layers.append({"type": str(rng.choice(
                ["max_pooling", "avg_pooling", "maxabs_pooling"])),
                "->": {"kx": 2, "ky": 2}})
            h, w = (h - 2) // 2 + 1, (w - 2) // 2 + 1
        elif kind == "lrn":
            layers.append({"type": "lrn", "->": {}})
        else:
            layers.append({"type": "dropout",
                           "->": {"dropout_ratio": 0.3}})
        if min(h, w) < 4:
            break
    layers.append({
        "type": "all2all_tanh",
        "->": {"output_sample_shape": int(rng.choice([8, 16]))},
        "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}})
    layers.append({"type": "softmax", "->": {"output_sample_shape": 5},
                   "<-": {"learning_rate": 0.01}})
    return layers


@pytest.mark.parametrize("seed", range(8))
def test_random_conv_stack_lowers_and_steps(seed):
    rng = numpy.random.default_rng(seed)
    prng.seed_all(1000 + seed)
    h = w = int(rng.choice([12, 17, 24]))
    layers = _random_conv_stack(rng, h, w)
    c = int(rng.choice([1, 3]))
    dtype = jnp.bfloat16 if seed % 2 else None
    params, step_fn, eval_fn, apply_fn = lower_specs(
        layers, (h, w, c), compute_dtype=dtype)
    x = rng.standard_normal((6, h, w, c)).astype(numpy.float32)
    labels = (numpy.arange(6) % 5).astype(numpy.int32)
    for _ in range(2):
        params, metrics = step_fn(params, x, labels)
    assert numpy.isfinite(float(metrics["loss"])), layers
    assert 0 <= int(metrics["n_err"]) <= 6
    ev = eval_fn(params, x, labels)
    assert 0 <= int(ev["n_err"]) <= int(ev["n"])
    out = apply_fn(params, x)
    assert out.shape == (6, 5)
    assert numpy.isfinite(numpy.asarray(out, numpy.float32)).all()


@pytest.mark.parametrize("seed", range(4))
def test_random_recurrent_stack(seed):
    rng = numpy.random.default_rng(100 + seed)
    prng.seed_all(2000 + seed)
    t, d = int(rng.choice([5, 9])), int(rng.choice([4, 8]))
    layers = [
        {"type": str(rng.choice(["lstm", "rnn"])),
         "->": {"hidden_units": int(rng.choice([8, 16])),
                "last_only": bool(seed % 2)},
         "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
    ]
    if not seed % 2:
        # full-sequence output: stack a second recurrent layer on it
        layers.append({"type": "lstm",
                       "->": {"hidden_units": 8, "last_only": True},
                       "<-": {"learning_rate": 0.02}})
    layers.append({"type": "softmax", "->": {"output_sample_shape": 3},
                   "<-": {"learning_rate": 0.02}})
    params, step_fn, _eval, apply_fn = lower_specs(layers, (t, d))
    x = rng.standard_normal((5, t, d)).astype(numpy.float32)
    labels = (numpy.arange(5) % 3).astype(numpy.int32)
    params, metrics = step_fn(params, x, labels)
    assert numpy.isfinite(float(metrics["loss"]))
    assert apply_fn(params, x).shape == (5, 3)
