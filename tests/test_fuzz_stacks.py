"""Fuzz the fused lowering over random-but-valid layer stacks.

The reference's zoo is exercised by hand-picked configs; this sweeps
the combination space (conv/pool/LRN/dropout stacks of random depth and
geometry, dense tails, recurrent heads) and asserts every stack lowers,
steps, and stays finite — the class of shape-inference and
dtype-propagation bugs integration tests miss."""

import jax.numpy as jnp
import numpy
import pytest

from veles_tpu import prng
from veles_tpu.znicz.fused_graph import lower_specs


def _random_conv_stack(rng, h, w,
                       kinds=("conv", "pool", "lrn", "dropout"),
                       max_depth=4):
    """Random feature prefix from ``kinds`` that keeps spatial dims
    >= 4, followed by a dense tail.  One shape-tracking implementation
    serves both the lowering fuzz (all kinds) and the eager-vs-fused
    equivalence fuzz (deterministic kinds only)."""
    layers = []
    depth = int(rng.integers(1, max_depth))
    for _ in range(depth):
        kind = rng.choice(list(kinds))
        if kind == "conv" and min(h, w) >= 5:
            k = int(rng.choice([3, 5]))
            stride = int(rng.choice([1, 2]))
            pad = int(rng.integers(0, 2))
            layers.append({
                "type": str(rng.choice(
                    ["conv_tanh", "conv_strict_relu", "conv_sigmoid"])),
                "->": {"n_kernels": int(rng.choice([4, 8])),
                       "kx": k, "ky": k, "padding": pad,
                       "sliding": (stride, stride)},
                "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}})
            h = (h + 2 * pad - k) // stride + 1
            w = (w + 2 * pad - k) // stride + 1
        elif kind == "pool" and min(h, w) >= 4:
            layers.append({"type": str(rng.choice(
                ["max_pooling", "avg_pooling", "maxabs_pooling"])),
                "->": {"kx": 2, "ky": 2}})
            h, w = (h - 2) // 2 + 1, (w - 2) // 2 + 1
        elif kind == "lrn":
            layers.append({"type": "lrn", "->": {}})
        elif kind == "dropout":
            layers.append({"type": "dropout",
                           "->": {"dropout_ratio": 0.3}})
        if min(h, w) < 4:
            break
    layers.append({
        "type": "all2all_tanh",
        "->": {"output_sample_shape": int(rng.choice([8, 16]))},
        "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}})
    layers.append({"type": "softmax", "->": {"output_sample_shape": 5},
                   "<-": {"learning_rate": 0.01,
                          "gradient_moment": 0.9}})
    return layers


@pytest.mark.parametrize("seed", range(8))
def test_random_conv_stack_lowers_and_steps(seed):
    rng = numpy.random.default_rng(seed)
    prng.seed_all(1000 + seed)
    h = w = int(rng.choice([12, 17, 24]))
    layers = _random_conv_stack(rng, h, w)
    c = int(rng.choice([1, 3]))
    dtype = jnp.bfloat16 if seed % 2 else None
    params, step_fn, eval_fn, apply_fn = lower_specs(
        layers, (h, w, c), compute_dtype=dtype)
    x = rng.standard_normal((6, h, w, c)).astype(numpy.float32)
    labels = (numpy.arange(6) % 5).astype(numpy.int32)
    for _ in range(2):
        params, metrics = step_fn(params, x, labels)
    assert numpy.isfinite(float(metrics["loss"])), layers
    assert 0 <= int(metrics["n_err"]) <= 6
    ev = eval_fn(params, x, labels)
    assert 0 <= int(ev["n_err"]) <= int(ev["n"])
    out = apply_fn(params, x)
    assert out.shape == (6, 5)
    assert numpy.isfinite(numpy.asarray(out, numpy.float32)).all()


@pytest.mark.parametrize("seed", range(4))
def test_random_recurrent_stack(seed):
    rng = numpy.random.default_rng(100 + seed)
    prng.seed_all(2000 + seed)
    t, d = int(rng.choice([5, 9])), int(rng.choice([4, 8]))
    layers = [
        {"type": str(rng.choice(["lstm", "rnn"])),
         "->": {"hidden_units": int(rng.choice([8, 16])),
                "last_only": bool(seed % 2)},
         "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
    ]
    if not seed % 2:
        # full-sequence output: stack a second recurrent layer on it
        layers.append({"type": "lstm",
                       "->": {"hidden_units": 8, "last_only": True},
                       "<-": {"learning_rate": 0.02}})
    layers.append({"type": "softmax", "->": {"output_sample_shape": 3},
                   "<-": {"learning_rate": 0.02}})
    params, step_fn, _eval, apply_fn = lower_specs(layers, (t, d))
    x = rng.standard_normal((5, t, d)).astype(numpy.float32)
    labels = (numpy.arange(5) % 3).astype(numpy.int32)
    params, metrics = step_fn(params, x, labels)
    assert numpy.isfinite(float(metrics["loss"]))
    assert apply_fn(params, x).shape == (5, 3)


#: a hand-picked deep chain guaranteeing the combinations random seeds
#: might miss: conv_tanh → avg pool → strided conv → max pool → lrn
_DEEP_DETERMINISTIC = [
    {"type": "conv_tanh",
     "->": {"n_kernels": 6, "kx": 3, "ky": 3, "padding": 1},
     "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
    {"type": "avg_pooling", "->": {"kx": 2, "ky": 2}},
    {"type": "conv_strict_relu",
     "->": {"n_kernels": 8, "kx": 3, "ky": 3, "sliding": (2, 2)},
     "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
    {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
    {"type": "lrn", "->": {}},
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
     "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 5},
     "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
]


@pytest.mark.parametrize("seed", list(range(10)) + ["deep"])
def test_random_stack_fused_matches_eager(seed):
    """Equivalence fuzz: ONE eager unit-graph train step (forwards →
    evaluator → gd chain) equals ONE fused step for a random
    deterministic conv/pool/lrn stack — the eager hand-rule math and
    the fused jax.grad math must agree across the zoo's combination
    space, not just on hand-picked configs."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    if seed == "deep":
        rng = numpy.random.default_rng(999)
        h = w = 14
        layers = [dict(s) for s in _DEEP_DETERMINISTIC]
        seed = -1
    else:
        rng = numpy.random.default_rng(1000 + seed)
        h = w = int(rng.choice([10, 12, 14]))
        layers = _random_conv_stack(rng, h, w,
                                    kinds=("conv", "pool", "lrn"))
    n = 24
    data = rng.standard_normal((n, h, w, 3)).astype(numpy.float32)
    labels = (numpy.arange(n) % 5).astype(numpy.int32)

    class L(FullBatchLoader):
        def load_data(self):
            self.original_data.mem = data
            self.original_labels = [int(v) for v in labels]
            self.class_lengths[:] = [0, 0, n]

    prng.seed_all(77 + seed)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda win: L(win, minibatch_size=n,
                                     shuffle_limit=0),
        layers=[{**s} for s in layers],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice())

    # capture initial weights BEFORE the eager step; the fused twin
    # seeds from them
    specs = []
    for spec, fwd in zip(layers, wf.forwards):
        spec = {k: v for k, v in spec.items()}
        if fwd.weights:
            fwd.weights.map_read()
            init = {"weights": numpy.array(fwd.weights.mem)}
            if fwd.bias:
                fwd.bias.map_read()
                init["bias"] = numpy.array(fwd.bias.mem)
            spec["init"] = init
        specs.append(spec)

    wf.loader.run()                      # serves the single TRAIN batch
    for fwd in wf.forwards:
        fwd.run()
    wf.evaluator.run()
    for gdu in wf.gds:
        gdu.run()

    params, step_fn, _eval, _apply = lower_specs(specs, (h, w, 3))
    mb_x = numpy.array(wf.loader.minibatch_data.mem)
    mb_y = numpy.array(wf.loader.minibatch_labels.mem,
                       dtype=numpy.int32)
    import jax
    new_params, _m = jax.jit(step_fn)(params, mb_x, mb_y)
    for state, fwd in zip(new_params, wf.forwards):
        if state.get("w") is None:
            continue
        fwd.weights.map_read()
        numpy.testing.assert_allclose(
            numpy.asarray(state["w"]), fwd.weights.mem, atol=2e-4,
            err_msg="%s (seed %d, stack %s)" % (
                fwd.name, seed, [ly["type"] for ly in layers]))
        if state.get("b") is not None and fwd.bias:
            fwd.bias.map_read()
            numpy.testing.assert_allclose(
                numpy.asarray(state["b"]), fwd.bias.mem, atol=2e-4)
