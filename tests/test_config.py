"""Config tree tests (mirrors reference ``veles/tests/test_config.py``)."""

import pytest

from veles_tpu.config import Config, root, update_from_arguments


def test_autovivify():
    cfg = Config("test")
    cfg.a.b.c = 5
    assert cfg.a.b.c == 5
    assert cfg.a.path == "test.a"


def test_update_deep_merge():
    cfg = Config("test")
    cfg.update({"x": {"y": 1, "z": 2}})
    cfg.update({"x": {"y": 10}})
    assert cfg.x.y == 10
    assert cfg.x.z == 2


def test_to_dict_roundtrip():
    cfg = Config("test")
    cfg.update({"a": {"b": 3}, "c": "s"})
    assert cfg.to_dict() == {"a": {"b": 3}, "c": "s"}


def test_protect():
    cfg = Config("test")
    cfg.key = 1
    cfg.protect("key")
    with pytest.raises(AttributeError):
        cfg.key = 2


def test_defaults_present():
    assert root.common.engine.backend in ("auto", "tpu", "cpu", "numpy")
    assert "datasets" in root.common.dirs.to_dict()


def test_cli_overrides():
    update_from_arguments(["root.common.test_override=41",
                           'common.test_str=hello'])
    assert root.common.test_override == 41
    assert root.common.test_str == "hello"


def test_contains_and_get():
    cfg = Config("test")
    cfg.a = 1
    assert "a" in cfg
    assert cfg.get("missing", 7) == 7
    assert "missing" not in cfg  # get() must not vivify
