"""Golden tests for the kernel substrate vs numpy reference — mirrors the
reference's ``test_ocl_blas.py`` / ``test_random.py`` strategy: every op
checked against a plain numpy computation, and the Pallas path checked in
interpret mode on CPU (the TPU hardware run is exercised by bench.py)."""

import jax
import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.ops import gemm, normalize, reduce as reduce_ops
from veles_tpu.ops.join import join as join_op
from veles_tpu.ops.gather import _gather_jnp, _gather_pallas, take_rows
from veles_tpu.ops.random import dropout_mask, normal, uniform


class TestMatmul:
    def _golden(self, m, k, n, activation=None, bias=False, seed=0):
        rng = numpy.random.default_rng(seed)
        a = rng.standard_normal((m, k), dtype=numpy.float32)
        b = rng.standard_normal((k, n), dtype=numpy.float32)
        bv = rng.standard_normal(n, dtype=numpy.float32) if bias else None
        ref = a @ b
        if bias:
            ref = ref + bv
        if activation == "tanh":
            ref = 1.7159 * numpy.tanh(0.6666 * ref)
        elif activation == "strict_relu":
            ref = numpy.maximum(ref, 0)
        return a, b, bv, ref

    def test_jnp_path(self):
        a, b, bv, ref = self._golden(17, 33, 9, bias=True)
        out = gemm.matmul(a, b, bv, use_pallas=False)
        assert numpy.allclose(out, ref, atol=1e-4)

    def test_pallas_interpret_matches(self):
        a, b, bv, ref = self._golden(16, 128, 128, bias=True)
        from veles_tpu.config import root
        root.common.engine.interpret = True
        try:
            out = gemm.matmul(a, b, bv, use_pallas=True)
        finally:
            root.common.engine.interpret = False
        assert numpy.allclose(out, ref, atol=1e-4)

    def test_pallas_unaligned_shapes(self):
        a, b, _, ref = self._golden(33, 70, 130)
        from veles_tpu.config import root
        root.common.engine.interpret = True
        try:
            out = gemm.matmul(a, b, use_pallas=True)
        finally:
            root.common.engine.interpret = False
        assert numpy.allclose(out, ref, atol=1e-4)

    def test_activation_fused(self):
        a, b, bv, ref = self._golden(8, 16, 4, activation="tanh", bias=True)
        out = gemm.matmul(a, b, bv, "tanh", use_pallas=False)
        assert numpy.allclose(out, ref, atol=1e-4)

    def test_grad_through_matmul(self):
        """custom VJP: jax.grad through matmul matches numerical grad of
        plain jnp composition."""
        a = numpy.random.default_rng(1).standard_normal(
            (4, 6)).astype(numpy.float32)
        b = numpy.random.default_rng(2).standard_normal(
            (6, 3)).astype(numpy.float32)

        def loss_ours(a_, b_):
            return jnp.sum(gemm.matmul(a_, b_, None, "tanh",
                                       use_pallas=False) ** 2)

        def loss_ref(a_, b_):
            return jnp.sum((1.7159 * jnp.tanh(0.6666 * (a_ @ b_))) ** 2)

        ga, gb = jax.grad(loss_ours, argnums=(0, 1))(a, b)
        ra, rb = jax.grad(loss_ref, argnums=(0, 1))(a, b)
        assert numpy.allclose(ga, ra, atol=1e-3)
        assert numpy.allclose(gb, rb, atol=1e-3)

    def test_grad_strict_relu(self):
        a = numpy.random.default_rng(3).standard_normal(
            (5, 7)).astype(numpy.float32)
        b = numpy.random.default_rng(4).standard_normal(
            (7, 2)).astype(numpy.float32)
        ga = jax.grad(lambda a_: jnp.sum(gemm.matmul(
            a_, b, None, "strict_relu", use_pallas=False)))(a)
        ra = jax.grad(lambda a_: jnp.sum(
            jnp.maximum(a_ @ b, 0)))(a)
        assert numpy.allclose(ga, ra, atol=1e-4)

    def test_bfloat16_inputs(self):
        a, b, _, ref = self._golden(16, 32, 8)
        out = gemm.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                          use_pallas=False)
        assert out.dtype == jnp.bfloat16
        assert numpy.allclose(numpy.asarray(out, numpy.float32), ref,
                              atol=0.5, rtol=0.05)


class TestReduce:
    @pytest.mark.parametrize("axis", [0, 1])
    @pytest.mark.parametrize("op", ["sum", "max", "min"])
    def test_jnp(self, axis, op):
        a = numpy.random.default_rng(0).standard_normal(
            (37, 53)).astype(numpy.float32)
        ref = getattr(numpy, op)(a, axis=axis)
        out = reduce_ops.matrix_reduce(a, axis=axis, op=op,
                                       use_pallas=False)
        assert numpy.allclose(out, ref, atol=1e-4)

    @pytest.mark.parametrize("axis", [0, 1])
    def test_pallas_interpret(self, axis):
        a = numpy.random.default_rng(1).standard_normal(
            (24, 256)).astype(numpy.float32)
        from veles_tpu.config import root
        root.common.engine.interpret = True
        try:
            out = reduce_ops.matrix_reduce(a, axis=axis, use_pallas=True)
        finally:
            root.common.engine.interpret = False
        assert numpy.allclose(out, a.sum(axis=axis), atol=1e-3)


class TestGather:
    def test_basic(self):
        data = numpy.arange(40, dtype=numpy.float32).reshape(10, 4)
        idx = numpy.array([3, 1, 7], dtype=numpy.int32)
        out = take_rows(data, idx, use_pallas=False)
        assert (numpy.asarray(out) == data[idx]).all()

    def test_negative_index_zero_fill(self):
        data = numpy.ones((5, 3), dtype=numpy.float32)
        idx = numpy.array([0, -1, 2], dtype=numpy.int32)
        out = numpy.asarray(take_rows(data, idx, use_pallas=False))
        assert (out[1] == 0).all() and (out[0] == 1).all()

    def test_pallas_interpret_matches_jnp(self):
        data = numpy.random.default_rng(2).standard_normal(
            (32, 128)).astype(numpy.float32)
        idx = numpy.array([5, 0, 31, -1, 7], dtype=numpy.int32)
        ref = numpy.asarray(_gather_jnp(jnp.asarray(data),
                                        jnp.asarray(idx)))
        out = numpy.asarray(_gather_pallas(jnp.asarray(data),
                                           jnp.asarray(idx),
                                           interpret=True))
        assert numpy.allclose(out, ref)

    def test_3d_data(self):
        data = numpy.random.default_rng(3).standard_normal(
            (6, 4, 5)).astype(numpy.float32)
        idx = numpy.array([2, 4], dtype=numpy.int32)
        out = numpy.asarray(take_rows(data, idx, use_pallas=False))
        assert out.shape == (2, 4, 5)
        assert numpy.allclose(out, data[idx])


class TestRandomOps:
    def test_uniform_range_and_determinism(self):
        key = jax.random.key(42)
        a = uniform(key, (1000,), low=-2.0, high=3.0)
        b = uniform(key, (1000,), low=-2.0, high=3.0)
        assert (numpy.asarray(a) == numpy.asarray(b)).all()
        assert a.min() >= -2.0 and a.max() < 3.0

    def test_normal_moments(self):
        key = jax.random.key(7)
        x = numpy.asarray(normal(key, (20000,), mean=1.0, stddev=2.0))
        assert abs(x.mean() - 1.0) < 0.1
        assert abs(x.std() - 2.0) < 0.1

    def test_uniform_pallas_fallback_off_tpu(self):
        from veles_tpu.ops.random import uniform_pallas
        a = numpy.asarray(uniform_pallas(3, (256,), low=-1.0, high=1.0))
        b = numpy.asarray(uniform_pallas(3, (256,), low=-1.0, high=1.0))
        c = numpy.asarray(uniform_pallas(4, (256,), low=-1.0, high=1.0))
        assert (a == b).all()
        assert not (a == c).all()
        assert a.min() >= -1.0 and a.max() < 1.0

    def test_dropout_mask(self):
        key = jax.random.key(0)
        mask = numpy.asarray(dropout_mask(key, (10000,), 0.8))
        kept = (mask > 0).mean()
        assert 0.75 < kept < 0.85
        assert numpy.allclose(mask[mask > 0], 1.0 / 0.8)


class TestNormalizeJoin:
    def test_mean_disp(self):
        x = numpy.random.default_rng(0).standard_normal(
            (8, 5)).astype(numpy.float32)
        mean = x.mean(axis=0)
        disp = 1.0 / (x.std(axis=0) + 1e-6)
        out = numpy.asarray(normalize.mean_disp_normalize(
            jnp.asarray(x), jnp.asarray(mean), jnp.asarray(disp)))
        assert numpy.allclose(out, (x - mean) * disp, atol=1e-5)

    def test_join_flattens_and_concats(self):
        a = numpy.ones((4, 2, 3), dtype=numpy.float32)
        b = numpy.zeros((4, 5), dtype=numpy.float32)
        out = numpy.asarray(join_op([jnp.asarray(a), jnp.asarray(b)]))
        assert out.shape == (4, 11)
        assert (out[:, :6] == 1).all() and (out[:, 6:] == 0).all()


class TestHog:
    """HOG features (ref vendored external/hog.py)."""

    def test_shapes_and_norm(self):
        import numpy
        from veles_tpu.ops.hog import hog, hog_batch
        rng = numpy.random.default_rng(0)
        img = rng.random((32, 32)).astype(numpy.float32)
        feat = numpy.asarray(hog(img, orientations=9, cell=8, block=2))
        # 4x4 cells → 3x3 blocks of 2x2x9
        assert feat.shape == (3 * 3 * 2 * 2 * 9,)
        # L2 block norm keeps every block at unit-ish energy
        blocks = feat.reshape(9, 36)
        norms = numpy.linalg.norm(blocks, axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        batch = numpy.asarray(hog_batch(
            rng.random((5, 32, 32, 3)).astype(numpy.float32)))
        assert batch.shape == (5, 324)

    def test_oriented_edges_dominate_expected_bin(self):
        import numpy
        from veles_tpu.ops.hog import hog
        # x-ramp → horizontal gradient → angle 0 bin
        img = numpy.tile(
            numpy.arange(32, dtype=numpy.float32), (32, 1))
        feat = numpy.asarray(hog(img, orientations=9, cell=8,
                                 block=1))
        hist = feat.reshape(-1, 9).sum(axis=0)
        assert hist.argmax() == 0
        # horizontal stripes → vertical gradient → π/2 bin (index 4)
        feat_t = numpy.asarray(hog(img.T, orientations=9, cell=8,
                                   block=1))
        hist_t = feat_t.reshape(-1, 9).sum(axis=0)
        assert hist_t.argmax() == 4

    def test_gradients_flow(self):
        import jax, numpy
        import jax.numpy as jnp
        from veles_tpu.ops.hog import hog
        img = jnp.asarray(numpy.random.default_rng(1).random(
            (16, 16)).astype(numpy.float32))
        g = jax.grad(lambda im: hog(im).sum())(img)
        assert numpy.isfinite(numpy.asarray(g)).all()
        # flat regions (gx=gy=0) must not NaN-poison the gradient
        flat = jnp.zeros((16, 16), jnp.float32).at[4:8, 4:8].set(1.0)
        g2 = jax.grad(lambda im: hog(im).sum())(flat)
        assert numpy.isfinite(numpy.asarray(g2)).all()


def test_timing_multi_step_and_marginal():
    """ops.timing: K-step in-program loop matches K sequential steps,
    probe depends on params+metric, marginal timing returns sane
    positive values (the round-2 stopwatch bug class)."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.timing import (
        host_fetch, make_multi_step, marginal_time, measure_fused_step)

    def step(params, x, labels):
        p = params["w"]
        p = p + 0.25 * jnp.mean(x) + 0.001 * labels.sum()
        return {"w": p}, {"loss": jnp.sum(p)}

    params = {"w": jnp.zeros((4,), jnp.float32)}
    x = jnp.ones((2, 4), jnp.float32)
    labels = jnp.zeros((2,), jnp.int32)
    multi = make_multi_step(step, 5)
    out_params, probe = jax.jit(multi)(params, x, labels)
    # 5 steps of +0.25 each
    numpy.testing.assert_allclose(
        host_fetch(out_params["w"]), numpy.full((4,), 1.25), rtol=1e-6)
    vals = host_fetch(probe)
    assert vals.shape == (2,)
    assert numpy.isfinite(vals).all()

    # measurement needs a step with real work — a trivial step's
    # marginal is pure dispatch jitter and can come out non-positive
    def heavy_step(params, x, labels):
        m = params["m"]
        m = m + 1e-4 * (m @ m)
        return {"m": m}, {"loss": jnp.sum(m)}

    heavy = {"m": jnp.eye(512, dtype=jnp.float32) * 0.01}
    sec_per_step, flops = measure_fused_step(
        heavy_step, heavy, x, labels, k=5, donate=False)
    assert sec_per_step > 0

    calls = []

    def call(sync=False):
        calls.append(sync)

    per = marginal_time(call, min_seconds=0.01)
    assert per > 0


def test_timing_inprogram_marginal_and_dynamic_k():
    """Round-3 stopwatch: ONE compiled program timed at two runtime
    trip counts (cross-launch timing measured above chip peak on the
    tunneled transport); flops come from a loop program's cost = 2
    steps, never total/K."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.timing import (
        host_fetch, inprogram_marginal, make_multi_step,
        measure_fused_step)

    def step(params, x, labels):
        p = params["w"]
        p = p + 0.25 * jnp.mean(x) + 0.001 * labels.sum()
        return {"w": p}, {"loss": jnp.sum(p)}

    params = {"w": jnp.zeros((4,), jnp.float32)}
    x = jnp.ones((2, 4), jnp.float32)
    labels = jnp.zeros((2,), jnp.int32)

    # dynamic trip count: the SAME jitted multi runs 3 and 7 steps
    multi = make_multi_step(step)
    jitted = jax.jit(multi)
    for n in (3, 7):
        out_params, _probe = jitted(params, x, labels,
                                    numpy.int32(n))
        numpy.testing.assert_allclose(
            host_fetch(out_params["w"]),
            numpy.full((4,), 0.25 * n), rtol=1e-6)

    # a unit with real work (a no-op unit's marginal is pure dispatch
    # jitter and can come out non-positive on a loaded CI machine)
    w = jnp.eye(128, dtype=jnp.float32) * 0.999

    def unit(c):
        return jnp.tanh(c @ w)

    per = inprogram_marginal(unit, jnp.ones((128, 128), jnp.float32),
                             k1=2, k2=64, target_signal=0.05)
    assert per > 0

    # measure_fused_step returns a positive marginal and flops of ONE
    # step (the loop program's cost analysis counts its body once, so
    # program total = inline first step + body = 2 steps).  The step
    # must do real work: a trivial step's marginal is dispatch jitter.
    def heavy_step(params, x, labels):
        m = params["m"]
        m = m + 1e-4 * (m @ m)
        return {"m": m}, {"loss": jnp.sum(m)}

    heavy = {"m": jnp.eye(512, dtype=jnp.float32) * 0.01}
    sec_per_step, flops = measure_fused_step(heavy_step, heavy, x,
                                             labels, k=8)
    assert sec_per_step > 0
    one_step = jax.jit(heavy_step).lower(heavy, x, labels).compile()
    from veles_tpu.ops.timing import cost_flops
    expect = cost_flops(one_step)
    if expect and flops:
        # probe/loop bookkeeping adds a handful of scalar flops
        assert flops == pytest.approx(expect, rel=0.5)


def test_two_point_marginal_survives_short_point_stall():
    """Round-4 hardening: a transient transport stall in the FIRST
    short-point sample must not skew the marginal — the short point is
    sampled twice up front (min wins) and its spread is recorded as
    provenance.  Before the fix, the single contaminated t1 anchored
    every widen/retry and the marginal converged to the wrong value."""
    from veles_tpu.ops.timing import _two_point_marginal

    true_per_unit = 1e-3
    overhead = 0.05
    calls = {"n": 0}

    def timed(n):
        calls["n"] += 1
        t = overhead + n * true_per_unit
        if calls["n"] == 1:           # stall hits only the first sample
            t += 5.0
        return t

    stats = {}
    m = _two_point_marginal(timed, 4, 32, target_signal=0.01,
                            max_k=10000, stats=stats)
    assert m == pytest.approx(true_per_unit, rel=1e-9)
    assert stats["marginal"] == m
    assert stats["t1_samples"] >= 2
    assert stats["t1_rel_spread"] > 1.0   # the stall left a signature
    assert stats["t1"] == pytest.approx(overhead + 4 * true_per_unit)
    # provenance invariant: the recorded points reproduce the marginal
    assert stats["marginal"] == pytest.approx(
        (stats["t2"] - stats["t1"]) / (stats["k2"] - stats["k1"]))

    # steady-noise convergence: every sample jitters ±20 %, the widen
    # loop still lands within 25 % of truth (deterministic "noise")
    seq = [1.2, 0.95, 1.1, 1.0, 0.9, 1.15, 1.05, 0.85, 1.0, 1.1]
    calls2 = {"n": 0}

    def noisy(n):
        f = seq[calls2["n"] % len(seq)]
        calls2["n"] += 1
        return overhead + n * true_per_unit * f

    m2 = _two_point_marginal(noisy, 4, 32, target_signal=0.05,
                             max_k=10000)
    assert m2 == pytest.approx(true_per_unit, rel=0.25)


def test_autotune_gather_writes_db_and_take_rows_dispatches(
        tmp_path, monkeypatch):
    """autotune_gather persists the A/B winner (Pallas failures are a
    recorded verdict, not a crash — on CPU the non-interpret Pallas
    call fails, so XLA must win); take_rows dispatch order is config
    force → DB verdict → XLA default."""
    import jax.numpy as jnp

    from veles_tpu.config import root
    from veles_tpu.ops import benchmark as B
    from veles_tpu.ops import gather as G

    db_path = str(tmp_path / "dev.json")
    info = B.autotune_gather(n=64, row=(9, 9, 3), batch=8,
                             db_path=db_path)
    entry = info.ratings["gather"]["uint8"]
    assert entry["backend"] == "xla"       # CPU: pallas can't run
    assert entry["xla_ms"] > 0
    assert entry["pallas_ms"] is None and entry["pallas_error"]
    assert B.gather_choice(db_path=db_path) is False
    assert B.gather_choice(
        db_path=str(tmp_path / "absent.json")) is None

    # a Pallas verdict transfers ONLY to the row size it was measured
    # at (unmeasured shapes could fail at Mosaic compile time, beyond
    # any fallback) — mismatched rows get XLA
    import json as _json

    import jax
    pallas_db = str(tmp_path / "pallas.json")
    model = jax.devices()[0].device_kind
    with open(pallas_db, "w") as fout:
        _json.dump({model: {"gather": {"uint8": {
            "backend": "pallas", "xla_ms": 1.0, "pallas_ms": 0.5,
            "shape": [64, 9, 9, 3], "batch": 8}}}}, fout)
    assert B.gather_choice(db_path=pallas_db,
                           row_elems=9 * 9 * 3) is True
    assert B.gather_choice(db_path=pallas_db, row_elems=784) is False
    assert B.gather_choice(db_path=pallas_db) is True  # no row info

    # dispatch: DB verdict consulted only when config doesn't force
    calls = []

    def fake_choice(dtype_name="uint8", db_path=None, row_elems=None):
        calls.append((dtype_name, row_elems))
        return False

    monkeypatch.setattr("veles_tpu.ops.benchmark.gather_choice",
                        fake_choice)
    data = jnp.zeros((4, 6), jnp.float32)
    idx = jnp.asarray([1, -1], jnp.int32)
    out = numpy.asarray(G.take_rows(data, idx))
    assert out.shape == (2, 6) and calls   # DB was consulted
    calls.clear()
    try:
        root.common.engine.pallas_gather = False
        numpy.asarray(G.take_rows(data, idx))
        assert not calls                   # config force skips the DB
    finally:
        # remove the key outright: leaving any value (even a
        # pseudo-absent sentinel) would leak order-dependent state
        root.common.engine.__dict__.pop("pallas_gather", None)


def test_timing_pins_operands_on_device():
    """Round-4 window-3 post-mortem: host-resident numpy params (what
    lower_specs returns) were re-uploaded on EVERY timed launch —
    ~0.5 GB/launch for AlexNet over the tunnel, whose transfer jitter
    swamped the marginal (bench said 141 ms/step; the device_put-ing
    profiler measured 20.6 ms on the same claim).  The stopwatch must
    device_put its operands once, so no implicit H2D transfer may
    happen during timing — pinned with jax's transfer guard."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.timing import inprogram_marginal, \
        measure_fused_step

    def heavy_step(params, x, labels):
        m = params["m"]
        m = m + 1e-4 * (m @ m)
        return {"m": m}, {"loss": jnp.sum(m)}

    heavy = {"m": numpy.eye(256, dtype=numpy.float32) * 0.01}
    x = numpy.ones((2, 4), numpy.float32)
    labels = numpy.zeros((2,), numpy.int32)
    with jax.transfer_guard("disallow"):
        sec, _flops = measure_fused_step(heavy_step, heavy, x, labels,
                                         k=5)
    assert sec > 0

    def unit(c):
        return c + 1e-4 * (c @ c)

    with jax.transfer_guard("disallow"):
        per = inprogram_marginal(
            unit, numpy.eye(128, dtype=numpy.float32) * 0.01,
            k1=2, k2=8, target_signal=0.0)
    assert per > 0


def test_peak_guard_rejects_faster_than_hardware(monkeypatch):
    """A marginal implying more FLOPs than the chip's peak must be
    re-measured and then refused, never recorded (the round-2 MFU-54
    failure class)."""
    from veles_tpu.ops import benchmark as B

    monkeypatch.setattr("veles_tpu.backends.peak_bf16_flops",
                        lambda kind: 100e12)
    # 1e12 flops in 1e-3 s = 1000 TFLOPs >> 100 peak: reject
    with pytest.raises(RuntimeError, match="exceeds"):
        B._peak_guard(1e-3, 1e12, lambda: 1e-3, "test")
    # 1e12 flops in 0.02 s = 50 TFLOPs < 100 peak: accepted unchanged
    assert B._peak_guard(0.02, 1e12, lambda: 0.02, "test") == 0.02
    # first reading absurd, re-measurement sane: keep the re-measured
    assert B._peak_guard(1e-3, 1e12, lambda: 0.02, "test") == 0.02


def test_autotune_db_drives_dispatch(tmp_path, monkeypatch):
    """The device-infos DB decides matmul dispatch: a committed entry
    flips pallas on (with its tiles) or keeps XLA, per device
    generation and dtype (ref devices/device_infos.json,
    backends.py:623-744)."""
    import json as _json

    import jax
    import jax.numpy as jnp

    from veles_tpu.ops import benchmark, gemm
    from veles_tpu.config import root

    model = jax.devices()[0].device_kind
    db_path = tmp_path / "device_infos.json"
    db_path.write_text(_json.dumps({model: {"gemm": {
        "float32": {"sec_per_flop": 1e-12, "backend": "pallas",
                    "tiles": [256, 256, 256]},
        "bfloat16": {"sec_per_flop": 1e-12, "backend": "xla",
                     "tiles": None},
    }}}))
    monkeypatch.setattr(benchmark, "DEVICE_INFOS_JSON", str(db_path))
    benchmark.gemm_choice.cache_clear()
    try:
        assert benchmark.gemm_choice(jnp.float32) == \
            ("pallas", (256, 256, 256))
        assert benchmark.gemm_choice(jnp.bfloat16) == ("xla", None)
        assert benchmark.gemm_choice(jnp.float64) is None
        assert benchmark.tiles_for_gemm(jnp.float32) == (256, 256, 256)
        on_tpu = jax.devices()[0].platform == "tpu"
        # dispatch honors the DB on TPU; CPU never picks pallas from it
        on, tiles = gemm._dispatch(None, None, jnp.float32)
        assert on == on_tpu
        if on_tpu:
            assert tiles == (256, 256, 256)   # DB tiles flow through
        # explicitly forced pallas still uses the DB's measured tiles
        root.common.engine.pallas_gemm = True
        try:
            on, tiles = gemm._dispatch(None, None, jnp.float32)
            assert on == on_tpu
            assert tiles == (256, 256, 256)
        finally:
            root.common.engine.pallas_gemm = None
        # a caller's explicit tiles beat the DB's
        assert gemm._dispatch(True, (128, 128, 128), jnp.float32) == \
            (True, (128, 128, 128))
        # legacy entries (no "backend" key) must NOT flip dispatch to
        # pallas — their sweep never measured the XLA baseline
        db = _json.loads(db_path.read_text())
        db[model]["gemm"]["float32"].pop("backend")
        db_path.write_text(_json.dumps(db))
        benchmark.gemm_choice.cache_clear()
        assert benchmark.gemm_choice(jnp.float32) == \
            ("xla", (256, 256, 256))
        # flash-attention reads its own kernel entry: blocks AND the
        # backend verdict
        db[model]["flash_attention"] = {"bfloat16": {
            "sec_per_flop": 1e-12, "backend": "xla",
            "tiles": None}}
        db_path.write_text(_json.dumps(db))
        benchmark.gemm_choice.cache_clear()
        from veles_tpu.ops.attention import (
            _resolve_backend, _resolve_blocks)
        assert _resolve_backend(None, jnp.bfloat16) is False
        assert _resolve_backend(True, jnp.bfloat16) is True
        db[model]["flash_attention"]["bfloat16"] = {
            "sec_per_flop": 1e-12, "backend": "pallas",
            "tiles": [256, 512]}
        db_path.write_text(_json.dumps(db))
        benchmark.gemm_choice.cache_clear()
        assert _resolve_blocks(None, None, jnp.bfloat16) == (256, 512)
        assert _resolve_blocks(64, None, jnp.bfloat16) == (64, 512)
        assert _resolve_blocks(None, None, jnp.float32) == (128, 128)
        assert _resolve_backend(None, jnp.bfloat16) == on_tpu
    finally:
        benchmark.gemm_choice.cache_clear()


def test_autotune_gemm_writes_db(tmp_path):
    """The sweep itself (tiny shapes, CPU): produces a DB whose entry
    has backend/tiles/sec_per_flop and that gemm_choice can read
    back — plus per-shape-class, per-precision gemm_v2 entries
    carrying the stopwatch's noise signature (VERDICT r3 items 4/5)."""
    import jax

    from veles_tpu.ops import benchmark

    info = benchmark.autotune_gemm(
        shapes=((64, 64, 64),), dtypes=("float32",),
        candidates=((64, 64, 64),), runs=1,
        db_path=str(tmp_path / "db.json"),
        precision_levels=(0, 1))
    entry = info.ratings["gemm"]["float32"]
    assert entry["backend"] in ("pallas", "xla")
    assert entry["sec_per_flop"] > 0
    choice = benchmark.gemm_choice(
        "float32", db_path=str(tmp_path / "db.json"))
    assert choice is not None
    # v2: one entry per measured precision level, classified by shape,
    # with measurement provenance
    v2 = info.ratings["gemm_v2"]["float32"]
    cls = benchmark.classify_shape(64, 64, 64)
    for lvl in ("p0", "p1"):
        e = v2[lvl][cls]
        assert e["backend"] in ("pallas", "xla")
        assert e["sec_per_flop"] > 0
        assert e["shape"] == [64, 64, 64]
        assert "t1_rel_spread" in e


def test_gemm_choice_respects_precision_and_shape_class(tmp_path,
                                                        monkeypatch):
    """Dispatch routing over the v2 DB: shape classes select their own
    measured entry; a precision level with no measurement falls back
    to XLA — NEVER to tiles raced under another precision's MXU pass
    count (VERDICT r3 item 4)."""
    import json as _json

    import jax
    import jax.numpy as jnp

    from veles_tpu.config import root
    from veles_tpu.ops import benchmark

    def e(backend, tiles, shape):
        return {"sec_per_flop": 1e-12, "backend": backend,
                "tiles": tiles, "shape": shape, "t1_rel_spread": 0.02}

    model = jax.devices()[0].device_kind
    db_path = tmp_path / "device_infos.json"
    db_path.write_text(_json.dumps({model: {
        "gemm": {"float32": {"sec_per_flop": 1e-12,
                             "backend": "pallas",
                             "tiles": [512, 512, 512]}},
        "gemm_v2": {"float32": {
            "p0": {
                "square_large": e("pallas", [256, 256, 256],
                                  [4096, 4096, 4096]),
                "tall_skinny": e("xla", None, [16384, 1024, 1024]),
            },
            "p2": {
                "square_large": e("pallas", [128, 128, 128],
                                  [4096, 4096, 4096]),
            },
        }},
    }}))
    monkeypatch.setattr(benchmark, "DEVICE_INFOS_JSON", str(db_path))
    benchmark.gemm_choice.cache_clear()
    try:
        # p0: shape-class routing picks the class's own winner
        assert benchmark.gemm_choice(
            jnp.float32, shape=(4096, 4096, 4096)) == \
            ("pallas", (256, 256, 256))
        assert benchmark.gemm_choice(
            jnp.float32, shape=(16384, 1024, 1024)) == ("xla", None)
        # no shape info: square_large is the representative entry
        assert benchmark.gemm_choice(jnp.float32) == \
            ("pallas", (256, 256, 256))
        root.common.engine.precision_level = 2
        assert benchmark.gemm_choice(
            jnp.float32, shape=(4096, 4096, 4096)) == \
            ("pallas", (128, 128, 128))
        # p1 was never measured: XLA (None), NOT the p0 tiles
        root.common.engine.precision_level = 1
        assert benchmark.gemm_choice(
            jnp.float32, shape=(4096, 4096, 4096)) is None
        # bfloat16 has neither v2 nor legacy rows at p1: still None
        assert benchmark.gemm_choice(
            jnp.bfloat16, shape=(4096, 4096, 4096)) is None
        root.common.engine.precision_level = 0
        # flash attention routes by sequence regime (flash_v2)
        db = _json.loads(db_path.read_text())
        db[model]["flash_attention_v2"] = {"bfloat16": {
            "seq_2k": e("pallas", [256, 256], [4, 2048, 8, 128]),
            "seq_8k": e("pallas", [512, 256], [1, 8192, 8, 128]),
        }}
        db_path.write_text(_json.dumps(db))
        benchmark.gemm_choice.cache_clear()
        assert benchmark.gemm_choice(
            jnp.bfloat16, kernel="flash_attention",
            shape=(4, 2048, 8, 128)) == ("pallas", (256, 256))
        assert benchmark.gemm_choice(
            jnp.bfloat16, kernel="flash_attention",
            shape=(1, 8192, 8, 128)) == ("pallas", (512, 256))
        # no shape: the canonical seq_2k regime represents the kernel
        assert benchmark.gemm_choice(
            jnp.bfloat16, kernel="flash_attention") == \
            ("pallas", (256, 256))
    finally:
        root.common.engine.precision_level = 0
        benchmark.gemm_choice.cache_clear()
