"""Vector coherence-protocol tests (ref ``veles/tests/`` Array coverage:
map/unmap semantics, pickling device data transparently)."""

import pickle

import numpy

from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.memory import Vector, Watcher


def test_empty_vector():
    v = Vector()
    assert not v
    assert v.shape is None and v.size == 0


def test_reset_and_host_access():
    v = Vector(numpy.arange(6, dtype=numpy.float32).reshape(2, 3))
    assert v.shape == (2, 3)
    assert v.dtype == numpy.float32
    assert len(v) == 2
    assert v.mem[1, 2] == 5


def test_device_upload_download():
    dev = CPUDevice()
    v = Vector(numpy.ones((4, 4), dtype=numpy.float32))
    v.initialize(dev)
    d = v.devmem
    assert hasattr(d, "devices")           # a jax.Array
    # mutate on device (reassign — jax arrays are immutable)
    v.devmem = d * 3.0
    assert (v.mem == 3.0).all()            # implicit D2H on read


def test_host_edit_republish():
    dev = CPUDevice()
    v = Vector(numpy.zeros((2, 2), dtype=numpy.float32))
    v.initialize(dev)
    _ = v.devmem                           # uploaded
    v.map_write()
    v.mem[...] = 7.0
    v.unmap()
    assert float(numpy.asarray(v.devmem)[0, 0]) == 7.0


def test_interpret_device_passthrough():
    dev = NumpyDevice()
    v = Vector(numpy.arange(4.0))
    v.initialize(dev)
    assert isinstance(v.devmem, numpy.ndarray)
    v.devmem = v.devmem * 2
    assert (v.mem == numpy.arange(4.0) * 2).all()


def test_pickle_syncs_device_to_host():
    dev = CPUDevice()
    v = Vector(numpy.zeros((3,), dtype=numpy.float32))
    v.initialize(dev)
    v.devmem = v.devmem + 5.0              # freshest data on device only
    blob = pickle.dumps(v)
    restored = pickle.loads(blob)
    assert (restored.mem == 5.0).all()
    # restored vector re-uploads lazily on a fresh device attach
    restored.initialize(CPUDevice())
    assert float(numpy.asarray(restored.devmem)[0]) == 5.0


def test_watcher_accounting():
    Watcher.reset()
    dev = CPUDevice()
    v = Vector(numpy.zeros((1024,), dtype=numpy.float32))
    v.initialize(dev)
    _ = v.devmem
    assert Watcher.bytes_in_use >= 4096
    v.reset(None)
    assert Watcher.bytes_in_use == 0
    assert Watcher.peak_bytes >= 4096
