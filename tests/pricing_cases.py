"""Shared case matrix for the V-P02 / V-S01 pricing regression gate.

The pricing-core refactor (analyze/pricing.py) must not move a single
byte or word in either preflight's findings.  This module defines the
case matrix ONCE; ``python tests/pricing_cases.py`` dumps the current
reports to ``tests/fixtures/preflight_pricing.json`` (run against the
pre-refactor tree to bank the oracle), and
``tests/test_plan.py::test_pricing_refactor_fixture_parity`` replays
the same matrix and asserts byte-identical JSON.

Run under the conftest environment (JAX_PLATFORMS=cpu, 8 virtual
devices) so the mesh cases see the same topology either way.
"""

import json
import os


class GenPlanStub(object):
    """A plan-shaped object for check_generative (no device work) —
    mirrors tests/test_gen.py::_PlanStub."""

    def __init__(self, **kw):
        class _Model(object):
            causal = kw.pop("causal", True)
            seq_limit = kw.pop("seq_limit", 64)
        self.model = _Model()
        self.max_slots = kw.pop("max_slots", 2)
        self.max_seq = kw.pop("max_seq", 48)
        self.prefill_buckets = kw.pop("prefill_buckets", (8, 16))
        self.kv_cache_bytes = kw.pop("kv_cache_bytes", 1024)
        self.kv_mode = kw.pop("kv_mode", "contiguous")
        self.block_size = kw.pop("block_size", 16)
        self.num_blocks = kw.pop("num_blocks", 16)
        self.prefill_chunk = kw.pop("prefill_chunk", None)
        assert not kw, kw


#: check_generative cases: name -> (stub kwargs, check kwargs)
GEN_CASES = {
    "clean": ({}, {"hbm_bytes": 1 << 30}),
    "not_causal": ({"causal": False}, {"hbm_bytes": 1 << 30}),
    "no_slots": ({"max_slots": 0}, {"hbm_bytes": 1 << 30}),
    "over_budget": ({"kv_cache_bytes": 1000}, {"hbm_bytes": 1000}),
    "half_hbm_warn": ({"kv_cache_bytes": 600}, {"hbm_bytes": 1000}),
    "cpu_degrade": ({}, {"hbm_bytes": None}),
    "paged_bad_block": ({"kv_mode": "paged", "block_size": 10},
                        {"hbm_bytes": 1 << 30}),
    "paged_pool_small": ({"kv_mode": "paged", "num_blocks": 3},
                         {"hbm_bytes": 1 << 30}),
    "paged_mean_mix": ({"kv_mode": "paged", "num_blocks": 7,
                        "max_slots": 4}, {"hbm_bytes": 1 << 30,
                                          "mean_seq_len": 40}),
}

#: check_pod cases: name -> check_pod kwargs (workflow/mesh added by
#: the driver; "unstitched" swaps in a NumpyDevice workflow)
POD_CASES = {
    "clean": {},
    "bad_batch": {"batch_size": 60},
    "tiny_hbm": {"hbm_bytes": 1024},
    "tiny_hbm_fsdp": {"hbm_bytes": 1024, "param_rules": "fsdp"},
    "mid_hbm": {"hbm_bytes": 1 << 16},
    "no_data_axis": {"data_axis": "nope"},
    "unstitched": {},
}


def run_cases():
    """Case matrix -> {kind: {name: report-json-dict}} against the
    CURRENT tree."""
    from veles_tpu.analyze.shapes import check_generative, check_pod
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.parallel.dp import fsdp_rules
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod.__main__ import make_workflow

    out = {"gen": {}, "pod": {}}
    for name, (stub_kw, check_kw) in sorted(GEN_CASES.items()):
        report = check_generative(GenPlanStub(**stub_kw), **check_kw)
        out["gen"][name] = json.loads(report.to_json())

    mesh = mesh_from_topology("auto")
    wf = make_workflow()
    loose = make_workflow(device=NumpyDevice())
    for name, kw in sorted(POD_CASES.items()):
        kw = dict(kw)
        target = loose if name == "unstitched" else wf
        if kw.get("param_rules") == "fsdp":
            kw["param_rules"] = fsdp_rules(mesh)
        report = check_pod(target, mesh, **kw)
        out["pod"][name] = json.loads(report.to_json())
    return out


FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "preflight_pricing.json")


if __name__ == "__main__":
    results = run_cases()
    os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
    with open(FIXTURE, "w") as fout:
        json.dump(results, fout, indent=2, sort_keys=True)
        fout.write("\n")
    print("banked %d gen + %d pod cases -> %s"
          % (len(results["gen"]), len(results["pod"]), FIXTURE))
