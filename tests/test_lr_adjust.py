"""LRAdjuster parity: the five documented policies, the eager unit,
and the in-step fused schedule (ref ``veles.znicz.lr_adjust``,
``manualrst_veles_workflow_parameters.rst:655-685``)."""

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.znicz.lr_adjust import make_policy


def test_policy_math():
    assert make_policy("fixed")(123) == 1.0
    exp = make_policy("exp", {"gamma": 0.5})
    assert exp(0) == 1.0 and exp(3) == pytest.approx(0.125)
    se = make_policy("step_exp", {"gamma": 0.1, "step": 10})
    assert se(9) == pytest.approx(1.0)
    assert se(10) == pytest.approx(0.1)
    assert se(25) == pytest.approx(0.01)
    inv = make_policy("inv", {"gamma": 0.001, "power": 0.75})
    assert inv(0) == 1.0
    assert inv(1000) == pytest.approx(2.0 ** -0.75)
    arb = make_policy("arbitrary_step", {"lrs_with_lengths": [
        (1.0, 3), (0.1, 2), (0.01, 10 ** 9)]})
    got = [float(arb(t)) for t in range(7)]
    assert got == pytest.approx([1, 1, 1, 0.1, 0.1, 0.01, 0.01])
    # the last factor holds past the configured horizon
    assert float(arb(10 ** 10)) == pytest.approx(0.01)
    with pytest.raises(ValueError):
        make_policy("nope")


def test_policies_trace_under_jit():
    """Every policy must evaluate on a traced int32 tick (the fused
    step's schedule) and agree with its host value."""
    import jax
    import jax.numpy as jnp

    for name, params in [
            ("fixed", None),
            ("exp", {"gamma": 0.9}),
            ("step_exp", {"gamma": 0.5, "step": 4}),
            ("inv", {"gamma": 0.01, "power": 0.5}),
            ("arbitrary_step", {"lrs_with_lengths": [(1, 5), (0.2, 5),
                                                     (0.04, 100)]})]:
        pol = make_policy(name, params)
        jitted = jax.jit(lambda t, _p=pol: _p(t, xp=jnp))
        for t in (0, 3, 7, 12):
            assert float(jitted(numpy.int32(t))) == pytest.approx(
                float(pol(t)), rel=1e-6), (name, t)


def test_fused_schedule_matches_manual_lr():
    """Two fused steps under exp(gamma=0.5) == one step at lr, then one
    step at lr/2 (momentum 0 ⇒ update = lr·f(t)·grad)."""
    from veles_tpu.znicz.fused_graph import lower_specs

    spec = [{"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}}]
    rng = numpy.random.default_rng(0)
    x = rng.standard_normal((8, 6)).astype(numpy.float32)
    labels = (numpy.arange(8) % 4).astype(numpy.int32)

    prng.seed_all(3)
    pa, step_a, _e, _ap = lower_specs(
        spec, (6,), lr_adjuster={"lr_policy_name": "exp",
                                 "lr_parameters": {"gamma": 0.5}})
    assert int(pa[0]["tick"]) == 0
    pa, _m = step_a(pa, x, labels)
    pa, _m = step_a(pa, x, labels)
    assert int(pa[0]["tick"]) == 2

    prng.seed_all(3)
    pb, step_b, _e2, _ap2 = lower_specs(spec, (6,))
    pb, _m = step_b(pb, x, labels)          # factor 1 at t=0
    spec_half = [{"type": "softmax", "->": {"output_sample_shape": 4},
                  "<-": {"learning_rate": 0.05},
                  "init": {"weights": numpy.asarray(pb[0]["w"]),
                           "bias": numpy.asarray(pb[0]["b"])}}]
    pc, step_c, _e3, _ap3 = lower_specs(spec_half, (6,))
    pc, _m = step_c(pc, x, labels)          # == factor 0.5 at t=1
    numpy.testing.assert_allclose(numpy.asarray(pa[0]["w"]),
                                  numpy.asarray(pc[0]["w"]),
                                  rtol=1e-6, atol=1e-7)


def test_rprop_rejects_lr_adjuster():
    """iRprop-'s per-weight deltas are self-adaptive: a configured
    schedule would be silently dead, so lowering refuses it."""
    from veles_tpu.znicz.fused_graph import lower_specs

    with pytest.raises(ValueError, match="rprop"):
        lower_specs(
            [{"type": "softmax", "->": {"output_sample_shape": 4},
              "<-": {"solver": "rprop"}}], (6,),
            lr_adjuster={"lr_policy_name": "exp"})


def test_eager_workflow_lr_adjuster():
    """StandardWorkflow(lr_adjuster_config=...): the unit rescales the
    gd units' learning_rate per TRAIN minibatch from the captured base,
    like the reference's link_lr_adjuster."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(4)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=1000,
        lr_adjuster_config={"lr_policy_name": "step_exp",
                            "lr_parameters": {"gamma": 0.5,
                                              "step": 3}})
    assert wf.lr_adjuster is not None
    # the adjuster precedes the gd chain in control order, so TRAIN
    # minibatch t trains with factor f(t) — same alignment as the
    # fused in-step schedule (a post-gds link would lag one step)
    assert wf.lr_adjuster in wf.gds[0].links_from
    assert wf.decision in wf.lr_adjuster.links_from
    base = 0.03                              # the sample's configured lr
    wf.run()
    t = wf.lr_adjuster.t
    assert t >= 6                            # one train epoch = 6 batches
    expect = base * 0.5 ** ((t - 1) // 3)    # factor used at last step
    assert float(wf.gds[0].learning_rate) == pytest.approx(expect)
    results = wf.gather_results()
    assert numpy.isfinite(results["best_validation_error_pt"])


def test_fused_workflow_lr_adjuster_ticks():
    """fused=True + lr_adjuster_config: the schedule lives in the step
    (tick advances once per train minibatch) and training still
    converges."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(5)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=1000,
        fused=True,
        lr_adjuster_config={"lr_policy_name": "inv",
                            "lr_parameters": {"gamma": 0.001,
                                              "power": 0.5}})
    wf.run()
    # synthetic train split = 6000 samples → 6 train steps in epoch 2
    assert int(wf.fused_trainer._params_[0]["tick"]) == 6
    results = wf.gather_results()
    # 6 near-full-batch steps is not enough to converge far; the
    # schedule path proving is the tick count above
    assert numpy.isfinite(results["best_validation_error_pt"])
