"""Host-side executor tests: wants_thread units run off-thread with
control-graph ordering preserved, background work overlaps the main
loop, and loader prefetch overlaps (simulated) IO with a slow consumer.

Mirrors the reference's threaded-execution contract
(``veles/thread_pool.py:71``, ``veles/units.py:496-505``) under the
TPU re-design's FIFO scheduler.
"""

import threading
import time

import numpy

from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.loader.base import Loader
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import Unit


class ThreadRecorder(DummyUnit):
    def __init__(self, workflow, **kwargs):
        super(ThreadRecorder, self).__init__(workflow, **kwargs)
        self.thread_ids = []
        self.run_times = []

    def run(self):
        super(ThreadRecorder, self).run()
        self.thread_ids.append(threading.get_ident())
        self.run_times.append(time.monotonic())


class SleepUnit(ThreadRecorder):
    def __init__(self, workflow, sleep=0.05, **kwargs):
        super(SleepUnit, self).__init__(workflow, **kwargs)
        self.sleep = sleep

    def run(self):
        super(SleepUnit, self).run()
        time.sleep(self.sleep)


def test_wants_thread_runs_off_main_thread():
    wf = DummyWorkflow()
    bg = ThreadRecorder(wf, name="bg")
    bg.wants_thread = True
    fg = ThreadRecorder(wf, name="fg")
    bg.link_from(wf.start_point)
    fg.link_from(wf.start_point)
    wf.end_point.link_from(bg, fg)
    wf.initialize()
    wf.run()
    assert fg.thread_ids == [threading.get_ident()]
    assert bg.thread_ids[0] != threading.get_ident()


def test_background_unit_ordering_preserved():
    """A unit control-downstream of a wants_thread unit only runs after
    it completes."""
    wf = DummyWorkflow()
    order = []

    class Tracker(DummyUnit):
        def run(self):
            super(Tracker, self).run()
            if self.name == "slow_bg":
                time.sleep(0.1)
            order.append(self.name)

    bg = Tracker(wf, name="slow_bg")
    bg.wants_thread = True
    down = Tracker(wf, name="down")
    bg.link_from(wf.start_point)
    down.link_from(bg)
    wf.end_point.link_from(down)
    wf.initialize()
    wf.run()
    assert order == ["slow_bg", "down"]


def test_background_unit_overlaps_loop():
    """A slow wants_thread side-branch (a plotter, say) must NOT
    serialize with the main repeater loop."""
    n_iters = 5
    side_sleep = 0.1
    wf = DummyWorkflow()
    rep = Repeater(wf)
    trainer = SleepUnit(wf, sleep=0.01, name="trainer")
    side = SleepUnit(wf, sleep=side_sleep, name="side")
    side.wants_thread = True
    stop = Bool(False)
    count = {"n": 0}

    class Decision(DummyUnit):
        def run(self):
            nonlocal stop
            super(Decision, self).run()
            count["n"] += 1
            if count["n"] >= n_iters:
                stop <<= True

    dec = Decision(wf, name="decision")
    rep.link_from(wf.start_point)
    trainer.link_from(rep)
    dec.link_from(trainer)
    side.link_from(dec)          # side branch off the loop
    rep.link_from(dec)           # back-edge
    rep.gate_block = stop
    wf.end_point.link_from(dec)
    wf.end_point.gate_block = ~stop
    wf.initialize()
    tic = time.monotonic()
    wf.run()
    elapsed = time.monotonic() - tic
    assert count["n"] == n_iters
    # concurrent duplicate triggers are DISCARDED (ref units.py:793-801),
    # so the slow side branch runs fewer times than the loop iterates —
    # that's the decoupling working
    assert 1 <= side.run_count <= n_iters
    # serialized would be ≥ n_iters * side_sleep = 0.5 s; overlap keeps
    # the critical path ≈ loop time + one trailing side run
    assert elapsed < n_iters * side_sleep * 0.8, \
        "background side branch serialized the loop (%.3fs)" % elapsed


class SlowIOLoader(Loader):
    """Synthetic loader whose per-sample 'IO' sleeps, with the pure
    prefetch fill contract."""

    supports_prefetch = True

    def __init__(self, workflow, io_delay=0.05, **kwargs):
        super(SlowIOLoader, self).__init__(workflow, **kwargs)
        self.io_delay = io_delay
        self.fill_threads = []

    def load_data(self):
        self._has_labels = True
        self.class_lengths[:] = [0, 0, 64]

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size, 4), dtype=numpy.float32))

    def _fill(self, indices, data_out, raw_labels_out):
        time.sleep(self.io_delay)
        for i, idx in enumerate(indices):
            data_out[i] = float(idx)
            raw_labels_out[i] = int(idx) % 8

    def fill_minibatch(self):
        self.fill_threads.append(threading.get_ident())
        n = self.minibatch_size
        self.minibatch_data.map_write()
        self._fill(self.minibatch_indices.mem[:n],
                   self.minibatch_data.mem[:n],
                   self.raw_minibatch_labels)

    def fill_minibatch_into(self, indices, data_out, raw_labels_out):
        self.fill_threads.append(threading.get_ident())
        self._fill(indices, data_out, raw_labels_out)


def _run_loader_loop(prefetch, io_delay=0.04, train_delay=0.04,
                     epochs=2):
    from veles_tpu import prng
    prng.seed_all(4321)        # identical shuffles across compared runs
    wf = DummyWorkflow()
    loader = SlowIOLoader(wf, io_delay=io_delay, minibatch_size=16,
                          prefetch=prefetch)
    rep = Repeater(wf)
    stop = Bool(False)
    seen = []

    class Trainer(DummyUnit):
        def run(self):
            nonlocal stop
            super(Trainer, self).run()
            time.sleep(train_delay)
            seen.append(numpy.array(loader.minibatch_data.mem))
            if loader.epoch_ended and loader.epoch_number >= epochs:
                stop <<= True

    trainer = Trainer(wf, name="trainer")
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    trainer.link_from(loader)
    rep.link_from(trainer)
    rep.gate_block = stop
    wf.end_point.link_from(trainer)
    wf.end_point.gate_block = ~stop
    wf.initialize()
    tic = time.monotonic()
    wf.run()
    elapsed = time.monotonic() - tic
    return elapsed, seen, loader


def test_loader_prefetch_overlaps_io():
    # analyze_dataset also pays io_delay per batch; compare like to like
    t_off, seen_off, _ = _run_loader_loop(prefetch=False)
    t_on, seen_on, loader = _run_loader_loop(prefetch=True)
    assert len(seen_on) == len(seen_off)
    for a, b in zip(seen_on, seen_off):
        numpy.testing.assert_array_equal(a, b)
    # prefetched fills must have happened off the scheduler thread
    assert any(t != threading.get_ident() for t in loader.fill_threads)
    # with IO ≈ train time, prefetch should hide most of the IO; allow
    # slack for CI noise but require a real win
    assert t_on < t_off * 0.8, \
        "prefetch gave no overlap (on=%.3fs off=%.3fs)" % (t_on, t_off)


def test_loader_prefetch_epoch_wrap_correctness():
    """Across epoch wraps the prediction goes stale (reshuffle); the
    loader must detect it and serve identical data to the no-prefetch
    run even WITH shuffling enabled."""
    t_off, seen_off, _ = _run_loader_loop(
        prefetch=False, io_delay=0.0, train_delay=0.0, epochs=3)
    t_on, seen_on, _ = _run_loader_loop(
        prefetch=True, io_delay=0.0, train_delay=0.0, epochs=3)
    assert len(seen_on) == len(seen_off)
    for a, b in zip(seen_on, seen_off):
        numpy.testing.assert_array_equal(a, b)


def test_prefetch_exception_propagates_and_recovers():
    """A fill_minibatch_into that throws in the worker must not lose
    the batch OR the exception: the failure surfaces at consume time
    (logged) and the serve falls back to a synchronous fill — the
    served stream stays identical to the no-prefetch run."""
    fail_on = {3}

    class FlakyLoader(SlowIOLoader):
        def __init__(self, workflow, **kwargs):
            super(FlakyLoader, self).__init__(workflow, **kwargs)
            self.bg_calls = 0
            self.failures = 0

        def fill_minibatch_into(self, indices, data_out,
                                raw_labels_out):
            self.bg_calls += 1
            if self.bg_calls in fail_on:
                self.failures += 1
                raise RuntimeError("synthetic IO failure")
            super(FlakyLoader, self).fill_minibatch_into(
                indices, data_out, raw_labels_out)

    def run(prefetch, loader_cls):
        from veles_tpu import prng
        prng.seed_all(4321)
        wf = DummyWorkflow()
        loader = loader_cls(wf, io_delay=0.0, minibatch_size=16,
                            prefetch=prefetch)
        rep = Repeater(wf)
        stop = Bool(False)
        seen = []

        class Trainer(DummyUnit):
            def run(self):
                nonlocal stop
                super(Trainer, self).run()
                time.sleep(0.01)    # let the flaky future resolve
                seen.append(numpy.array(loader.minibatch_data.mem))
                if loader.epoch_ended and loader.epoch_number >= 2:
                    stop <<= True

        trainer = Trainer(wf, name="trainer")
        rep.link_from(wf.start_point)
        loader.link_from(rep)
        trainer.link_from(loader)
        rep.link_from(trainer)
        rep.gate_block = stop
        wf.end_point.link_from(trainer)
        wf.end_point.gate_block = ~stop
        wf.initialize()
        wf.run()
        return seen, loader

    seen_ref, _ = run(False, SlowIOLoader)
    seen_flaky, loader = run(True, FlakyLoader)
    assert loader.failures >= 1, "the failure injection never fired"
    assert len(seen_flaky) == len(seen_ref)
    for a, b in zip(seen_flaky, seen_ref):
        numpy.testing.assert_array_equal(a, b)


def test_no_stale_prefetch_after_reinitialize():
    """initialize() reshuffles the index space — a background fill
    buffered before the re-initialize must NOT be served afterwards
    even when its (offset, size) key matches (the stale-buffer-reuse
    hazard; initialize drops all in-flight fills)."""
    from veles_tpu import prng

    def serve_after_reinit(prefetch):
        prng.seed_all(777)
        wf = DummyWorkflow()
        loader = SlowIOLoader(wf, io_delay=0.0, minibatch_size=16,
                              prefetch=prefetch)
        loader.link_from(wf.start_point)
        wf.end_point.link_from(loader)
        wf.initialize()
        for _ in range(3):
            loader.run()    # leaves a prefetched batch 4 in flight
        assert not prefetch or loader._prefetch_futures_
        loader.initialize()                # reshuffle: new epoch order
        assert not loader._prefetch_futures_
        loader.run()
        return numpy.array(loader.minibatch_data.mem)

    a = serve_after_reinit(prefetch=True)
    b = serve_after_reinit(prefetch=False)
    numpy.testing.assert_array_equal(a, b)


def test_prefetch_ring_reuses_buffers_and_publishes_device():
    """The staging ring allocates its slots ONCE (no per-fill
    zeros_like churn) and, with a jit device attached, the worker's
    upload lands as the published device copy — both Vector sides
    fresh on a hit, nothing left for the consumer to transfer."""
    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice

    prng.seed_all(4321)
    wf = DummyWorkflow()
    wf.device = CPUDevice()
    loader = SlowIOLoader(wf, io_delay=0.0, minibatch_size=16,
                          prefetch=True)
    loader.link_from(wf.start_point)
    wf.end_point.link_from(loader)
    wf.initialize(device=wf.device)
    slot_ids = set()
    orig_acquire = type(loader._staging()).acquire

    def spy_acquire(self):
        slot = orig_acquire(self)
        slot_ids.add(id(slot))
        return slot

    type(loader._staging()).acquire = spy_acquire
    try:
        hits = 0
        for _ in range(8):
            loader.run()
            time.sleep(0.02)        # let the background fill land
            if loader.minibatch_data._dev_fresh_ \
                    and loader.minibatch_data._host_fresh_:
                hits += 1
        assert hits >= 3, "prefetch hits never published device copies"
        assert len(slot_ids) <= loader._staging().depth
    finally:
        type(loader._staging()).acquire = orig_acquire


def test_drain_waits_for_background_not_gating_end_point():
    """run() returning means quiescent: an in-flight background unit
    that the end_point does NOT wait on is still joined before run()
    returns (a unit not yet started when the workflow stops may
    legitimately skip — the contract covers *running* units)."""
    wf = DummyWorkflow()
    bg = SleepUnit(wf, sleep=0.5, name="bg")
    bg.wants_thread = True
    # fg sleeps long enough that bg is definitely mid-run when the end
    # point fires and sets stopped
    fg = SleepUnit(wf, sleep=0.15, name="fg")
    bg.link_from(wf.start_point)
    fg.link_from(wf.start_point)
    wf.end_point.link_from(fg)          # end point ignores bg entirely
    wf.initialize()
    tic = time.monotonic()
    wf.run()
    assert len(bg.run_times) == 1, "bg never started; race in test"
    assert time.monotonic() - tic >= 0.45, \
        "run() returned before the in-flight background unit finished"


def test_drain_raises_on_wedged_background_unit():
    """A running background unit outliving QUIESCENCE_TIMEOUT fails
    run() loudly instead of silently violating the quiescence
    contract."""
    import pytest

    wf = DummyWorkflow()
    bg = SleepUnit(wf, sleep=1.2, name="bg")
    bg.wants_thread = True
    fg = SleepUnit(wf, sleep=0.1, name="fg")
    bg.link_from(wf.start_point)
    fg.link_from(wf.start_point)
    wf.end_point.link_from(fg)
    wf.initialize()
    wf.QUIESCENCE_TIMEOUT = 0.2        # instance override for the test
    try:
        with pytest.raises(RuntimeError, match="not quiescent"):
            wf.run()
        assert len(bg.run_times) == 1
    finally:
        time.sleep(1.3)                # let the straggler drain out of
        # the shared pool before other tests run


def _run_dist_slave(loader_prefetch, n_jobs=8, io_delay=0.12,
                    train_delay=0.12):
    """Distributed mirror of _run_loader_loop: a master serves index
    jobs, the slave fills minibatches (slow IO) and 'trains' (sleep)."""
    from veles_tpu import prng
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.parallel.jobs import JobClient, JobServer

    class CountingLoader(SlowIOLoader):
        def init_unpickled(self):
            super(CountingLoader, self).init_unpickled()
            self.sync_fills = 0
            self.bg_fills = 0

        def fill_minibatch(self):
            self.sync_fills += 1
            super(CountingLoader, self).fill_minibatch()

        def fill_minibatch_into(self, indices, data_out, raw_labels_out):
            self.bg_fills += 1
            super(CountingLoader, self).fill_minibatch_into(
                indices, data_out, raw_labels_out)

    seen = []

    def build(is_master, is_slave):
        prng.seed_all(4321)
        wf = DummyWorkflow()
        loader = CountingLoader(
            wf, io_delay=0.0 if is_master else io_delay,
            minibatch_size=16, prefetch=loader_prefetch)

        class Trainer(DummyUnit):
            def run(self):
                super(Trainer, self).run()
                if is_slave:
                    time.sleep(train_delay)
                    seen.append(numpy.array(loader.minibatch_data.mem))

        trainer = Trainer(wf, name="trainer")
        loader.link_from(wf.start_point)
        trainer.link_from(loader)
        wf.end_point.link_from(trainer)
        wf.launcher = DummyLauncher(is_master=is_master,
                                    is_slave=is_slave)
        wf.initialize()
        return wf, loader

    master_wf, _master_loader = build(True, False)
    slave_wf, slave_loader = build(False, True)
    server = JobServer(master_wf).start()
    try:
        client = JobClient(slave_wf, server.endpoint)
        client.handshake()
        tic = time.monotonic()
        assert client.run_prefetch(max_jobs=n_jobs)
        elapsed = time.monotonic() - tic
        client.close()
    finally:
        server.stop()
    return elapsed, seen, slave_loader


def test_slave_mode_minibatch_prefetch_overlaps_io():
    """The loader's IO overlap must exist in DISTRIBUTED runs too: the
    next job's payload (already double-buffered by the job client)
    feeds prefetch_job_data, so the fill runs during the current job's
    compute instead of serializing in front of it."""
    io_delay = 0.12    # large vs comms noise — ratio asserts flake
    t_off, seen_off, loader_off = _run_dist_slave(
        loader_prefetch=False, io_delay=io_delay)
    t_on, seen_on, loader_on = _run_dist_slave(
        loader_prefetch=True, io_delay=io_delay)
    # identical data served either way
    assert len(seen_on) == len(seen_off) > 0
    for a, b in zip(seen_on, seen_off):
        numpy.testing.assert_array_equal(a, b)
    # the prefetched path was genuinely taken: only the first job (no
    # payload buffered yet) plus at most two race losers may fill
    # synchronously; analyze_dataset's fills are shared by both runs
    analyze_fills = loader_off.sync_fills - 8      # 8 jobs
    assert loader_on.bg_fills >= 5
    assert loader_on.sync_fills <= analyze_fills + 3
    # and it bought real wall-clock overlap: each consumed prefetch
    # hides one io_delay; require at least 3 fills' worth of savings
    # (absolute bound — ratio asserts flake under CI load)
    assert t_on < t_off - 3 * io_delay, \
        "slave prefetch gave no overlap (on=%.3fs off=%.3fs)" % (
            t_on, t_off)


def test_atexit_registered_once_across_recreations(monkeypatch):
    """Recreating the pool after shutdown() must not stack another
    atexit handler each time (thread_pool.py registers once per
    process)."""
    from veles_tpu import thread_pool
    calls = []
    monkeypatch.setattr(thread_pool, "_atexit_registered", False)
    monkeypatch.setattr(thread_pool.atexit, "register",
                        lambda fn, *a, **kw: calls.append(fn))
    thread_pool.shutdown()
    for _ in range(3):
        assert thread_pool.get_pool() is not None
        thread_pool.shutdown()
    assert calls == [thread_pool.shutdown]
