"""veles_tpu.obs — fleet-wide request tracing, scrape endpoints, SLO
engine, flight recorder.

Coverage map (ISSUE 13):

* trace context: W3C traceparent parse/mint/child, the PR 5-style
  disabled-path contract (identity + callable count), thread/process
  propagation;
* end-to-end identity: one traced request's id on batcher spans, the
  gen scheduler's phase spans (queue_wait/prefill/decode), the engine
  dispatch, and — over the real ZMQ wire — master and slave lanes in
  one ``prof merge`` timeline with flow arrows;
* SLO engine: ring semantics, exact burn-rate math on synthetic
  series, multi-window alert edges, the three ROADMAP autoscaling
  signals on ``/metrics`` and in ``describe()``;
* per-role scrape endpoints: the master's per-slave round-trip
  histograms + heartbeat-stall counter, the scrape-vs-lifecycle race
  (concurrent gauge/histogram register/unregister never yields a torn
  or duplicate-TYPE exposition);
* flight recorder: dump/load roundtrip, excepthook, chaos-kill
  sessions leaving a loadable post-mortem;
* ``-m slow``: the tracing-on overhead gate (>= 0.95x tracing-off
  tokens/s on the gen workload).
"""

import json
import sys
import threading
import time
import urllib.request

import numpy
import pytest

from veles_tpu import obs, trace
from veles_tpu.config import root
from veles_tpu.obs import blackbox
from veles_tpu.obs.slo import Objective, SeriesRing, SLOEngine


# -- trace context ----------------------------------------------------------

def test_traceparent_mint_parse_roundtrip():
    ctx = obs.mint()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    header = ctx.traceparent()
    assert header.startswith("00-") and header.endswith("-01")
    parsed = obs.parse(header)
    # same trace, fresh span, the incoming span as parent
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id != ctx.span_id
    assert parsed.parent_id == ctx.span_id
    assert obs.mint().trace_id != ctx.trace_id


@pytest.mark.parametrize("header", [
    None, "", "garbage", "00-zz-yy-01", "00-abc-def-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
])
def test_traceparent_malformed_headers_parse_to_none(header):
    assert obs.parse(header) is None


def test_child_links_parent_and_span_args():
    ctx = obs.mint()
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    args = child.span_args({"k": 1})
    assert args["k"] == 1
    assert args["trace"] == ctx.trace_id
    assert args["span"] == child.span_id
    assert args["parent"] == ctx.span_id


def test_disabled_path_is_identity_no_ops():
    """The PR 5 contract for every obs hook: with tracing off,
    nothing is minted, nothing is copied, the shared singletons come
    back — asserted by identity AND callable count."""
    assert not trace.enabled(), "tests must start with tracing off"
    assert obs.ingress("00-" + "ab" * 16 + "-" + "cd" * 8 + "-01") \
        is None
    assert obs.current() is None
    assert obs.current_trace_id() is None
    assert obs.activate(None) is obs.NULL_CONTEXT
    args = {"k": 1}
    assert obs.tag(args) is args
    assert obs.tag(None) is None
    msg = {"op": "job"}
    assert obs.wire_inject(msg) is msg and "tp" not in msg
    assert obs.wire_extract({"tp": "00-%s-%s-01"
                             % ("ab" * 16, "cd" * 8)}) is None
    calls = []

    def prof(frame, event, arg):
        if event == "call":
            calls.append(frame.f_code.co_name)

    sys.setprofile(prof)
    try:
        obs.ingress(None)
        obs.current()
        obs.tag(args)
        with obs.activate(None):
            pass
    finally:
        sys.setprofile(None)
    interesting = [c for c in calls
                   if c in ("ingress", "current", "tag", "activate",
                            "__enter__", "__exit__")]
    # one call each + the null activation's enter/exit — no mint, no
    # parse, no thread-local machinery underneath
    assert len(interesting) == 6, calls
    assert len(calls) <= 8, calls


@pytest.mark.traced
def test_activation_thread_local_and_process_fallback():
    ctx = obs.mint()
    assert obs.current() is None
    with obs.activate(ctx):
        assert obs.current() is ctx
        inner = obs.mint()
        with obs.activate(inner):
            assert obs.current() is inner
        assert obs.current() is ctx
    assert obs.current() is None
    # process default: any thread without an activation sees it
    previous = obs.set_process(ctx)
    try:
        assert previous is None
        assert obs.current() is ctx
        seen = []
        worker = threading.Thread(
            target=lambda: seen.append(obs.current()))
        worker.start()
        worker.join()
        assert seen == [ctx]
    finally:
        obs.set_process(None)
    assert obs.current() is None


@pytest.mark.traced
def test_ingress_continues_or_mints():
    minted = obs.ingress(None)
    assert minted is not None and minted.parent_id is None
    upstream = obs.mint()
    continued = obs.ingress(upstream.traceparent())
    assert continued.trace_id == upstream.trace_id
    assert continued.parent_id == upstream.span_id
    fresh = obs.ingress("not-a-header")
    assert fresh is not None and fresh.trace_id != upstream.trace_id


@pytest.mark.traced
def test_wire_inject_extract_roundtrip():
    ctx = obs.mint()
    with obs.activate(ctx):
        msg = obs.wire_inject({"op": "job"})
    assert "tp" in msg
    extracted = obs.wire_extract(msg)
    assert extracted.trace_id == ctx.trace_id
    # the frame carries a CHILD hop: the receiver parents to it
    assert extracted.parent_id is not None


# -- end-to-end identity ----------------------------------------------------

class _EchoEngine(object):
    """Minimal batcher engine: echoes its input rows."""

    max_batch_size = 8
    sample_shape = (4,)

    def infer(self, batch):
        return numpy.asarray(batch)

    def padded_capacity(self, n):
        return 8


@pytest.mark.traced
def test_batcher_threads_request_identity_across_handoff():
    from veles_tpu.serve.batcher import DynamicBatcher
    from veles_tpu.trace import export

    batcher = DynamicBatcher(_EchoEngine(), max_wait_ms=1.0)
    ctx = obs.mint()
    try:
        with obs.activate(ctx):
            out = batcher.infer(numpy.zeros((2, 4), numpy.float32))
        assert out.shape == (2, 4)
    finally:
        batcher.stop()
    events = export.normalize()
    spans = obs.spans_of(events, ctx.trace_id)
    names = {(ev["cat"], ev["name"]) for ev in spans}
    # the submit-side instant AND the worker-side spans carry the id:
    # identity survived the thread handoff on the request object
    assert ("serve", "enqueue") in names
    assert ("serve", "request") in names
    assert ("serve", "batch_infer") in names
    request = [ev for ev in spans if ev["name"] == "request"][0]
    assert request["args"]["trace"] == ctx.trace_id
    assert request["args"]["span"] == ctx.span_id


def _tiny_gen_engine(**kwargs):
    from veles_tpu.gen import GenerativeEngine, TransformerGenModel
    from veles_tpu.samples.transformer import TINY
    defaults = dict(max_slots=2, max_seq=48, prefill_buckets=(8,),
                    seed=0)
    defaults.update(kwargs)
    return GenerativeEngine(
        TransformerGenModel(dict(TINY, seq_len=64)), **defaults)


@pytest.mark.traced
def test_gen_request_waterfall_phases_separable():
    """One traced generation: queue_wait, prefill_phase and
    decode_phase land as DISTINCT tagged spans whose intervals tile
    the request span — the per-request anatomy the ISSUE names."""
    from veles_tpu.gen import GenerativeScheduler
    from veles_tpu.trace import export

    engine = _tiny_gen_engine().warmup()
    scheduler = GenerativeScheduler(engine, name="obs-t")
    ctx = obs.mint()
    other = obs.mint()
    try:
        with obs.activate(ctx):
            f1 = scheduler.submit([1, 2, 3], 4)
        with obs.activate(other):
            f2 = scheduler.submit([4, 5], 3)
        scheduler.run_until_idle()
        assert len(f1.result(0)) == 4 and len(f2.result(0)) == 3
    finally:
        scheduler.stop()
        engine.close()
    events = export.normalize()
    for req_ctx, n_tokens in ((ctx, 4), (other, 3)):
        spans = {ev["name"]: ev
                 for ev in obs.spans_of(events, req_ctx.trace_id)
                 if ev["ph"] == "X"}
        for phase in ("queue_wait", "prefill_phase", "decode_phase",
                      "request"):
            assert phase in spans, \
                "missing %s for %s: %r" % (phase, req_ctx.trace_id,
                                           sorted(spans))
        # engine dispatch spans carry the identity too
        assert "prefill" in spans
        request = spans["request"]
        assert request["args"]["tokens"] == n_tokens
        for phase in ("queue_wait", "prefill_phase", "decode_phase"):
            ev = spans[phase]
            assert ev["ts_us"] >= request["ts_us"] - 50
            assert ev["ts_us"] + ev["dur_us"] \
                <= request["ts_us"] + request["dur_us"] + 50
        # phases are ordered: queue -> prefill -> decode
        assert spans["queue_wait"]["ts_us"] \
            <= spans["prefill_phase"]["ts_us"]
        assert spans["prefill_phase"]["ts_us"] + \
            spans["prefill_phase"]["dur_us"] \
            <= spans["decode_phase"]["ts_us"] + 50
    # the shared decode dispatches name BOTH co-residents
    decodes = [ev for ev in events if ev["ph"] == "X"
               and ev["cat"] == "gen" and ev["name"] == "decode"]
    assert decodes, "no decode dispatch spans"
    tagged = [ev for ev in decodes
              if (ev.get("args") or {}).get("traces")]
    assert tagged, "decode spans lost the slot identities"
    assert any(set((ev["args"]["traces"])) >=
               {ctx.trace_id, other.trace_id} for ev in tagged), \
        "no decode dispatch served both traced co-residents"


class _ScriptedMaster(object):
    def __init__(self, n_jobs=3):
        self.n_jobs = n_jobs
        self.served = 0
        self.updates = []

    def checksum(self):
        return "obs-v1"

    def generate_data_for_slave(self, slave):
        if self.served >= self.n_jobs:
            return None
        self.served += 1
        return {"job_number": self.served}

    def apply_data_from_slave(self, data, slave):
        self.updates.append(data)

    def drop_slave(self, slave):
        pass


class _ScriptedSlave(object):
    def checksum(self):
        return "obs-v1"

    def do_job(self, data, callback):
        callback({"result": data["job_number"]})


@pytest.mark.traced
def test_trace_id_crosses_the_zmq_wire_into_merged_lanes(tmp_path):
    """The acceptance probe: a session context's trace id must appear
    on master-lane AND slave-lane spans of ONE ``prof merge``
    timeline, stitched by flow events."""
    from veles_tpu import prof
    from veles_tpu.parallel.jobs import JobClient, JobServer

    ctx = obs.mint()
    obs.set_process(ctx)
    master = _ScriptedMaster(n_jobs=3)
    server = JobServer(master).start()
    try:
        client = JobClient(_ScriptedSlave(), server.endpoint)
        client.handshake()
        assert client.run()
        client.close()
        bundle_path = str(tmp_path / "session.json")
        server.save_session_profile(bundle_path, roles=("master",))
    finally:
        obs.set_process(None)
        server.stop()
    bundle = prof.merge.load(bundle_path)
    merged = prof.merge.merged_events(bundle)
    lanes = obs.role_lanes(merged, ctx.trace_id)
    assert "master" in lanes, lanes
    assert any(role.startswith("slave-") for role in lanes), lanes
    master_names = set(lanes["master"])
    assert {"generate", "apply_update"} <= master_names
    slave_names = set(
        n for role, names in lanes.items()
        if role.startswith("slave-") for n in names)
    assert {"do_job", "update"} <= slave_names
    # the merged export carries the flow arrows binding the lanes
    merged_path = str(tmp_path / "merged.json")
    prof.merge.save_merged(bundle, merged_path)
    with open(merged_path) as fin:
        raw = json.load(fin)["traceEvents"]
    flows = [ev for ev in raw if ev.get("ph") in ("s", "t")
             and ev.get("id") == ctx.trace_id]
    assert len(flows) >= 3
    assert sum(1 for ev in flows if ev["ph"] == "s") == 1
    # every do_job span is a DISTINCT child hop of the session trace
    do_jobs = [ev for ev in merged
               if ev.get("name") == "do_job"
               and (ev.get("args") or {}).get("trace")
               == ctx.trace_id]
    assert len(do_jobs) == 3
    assert len({ev["args"]["span"] for ev in do_jobs}) == 3


@pytest.mark.traced
def test_flow_events_regenerate_and_load_skips_them(tmp_path):
    """Flow events are derived decoration: exports regenerate them
    from span args, ``load()`` skips them, so a file report equals
    the live one even for tagged rings."""
    from veles_tpu.trace import export

    ctx = obs.mint()
    with trace.span("serve", "http", ctx.span_args({"path": "/x"}),
                    role="server"):
        pass
    with trace.span("gen", "queue_wait", ctx.span_args(),
                    role="server"):
        pass
    chrome = export.chrome_events()
    flows = [ev for ev in chrome if ev.get("ph") in ("s", "t")]
    assert [ev["ph"] for ev in flows] == ["s", "t"]
    assert all(ev["id"] == ctx.trace_id for ev in flows)
    live = trace.summary()
    path = trace.save(str(tmp_path / "tagged.json"))
    file_events = trace.load(path)
    assert trace.summary(file_events) == live
    assert not [ev for ev in file_events
                if ev["ph"] in ("s", "t", "f")]


# -- SLO engine -------------------------------------------------------------

def test_series_ring_window_and_wraparound():
    ring = SeriesRing(capacity=4)
    for i in range(6):
        ring.append(float(i), t=100.0 + i)
    assert len(ring) == 4
    assert ring.last() == (105.0, 5.0)
    # only the newest 4 survive; the window filters by time
    assert [v for _t, v in ring.window(3.5, now=105.0)] \
        == [2.0, 3.0, 4.0, 5.0]
    assert [v for _t, v in ring.window(0.5, now=105.0)] == [5.0]
    assert ring.window(10.0, now=200.0) == []


def test_burn_rate_math_is_exact():
    engine = SLOEngine()
    ring = engine.add_signal("lat", lambda: 0.0)
    objective = engine.add_objective(Objective(
        "lat", 10.0, window_s=10.0, fast_window_s=2.0, target=0.9))
    now = 50.0
    # 10 samples in the slow window, 5 breaching -> compliance 0.5,
    # burn (1-0.5)/(1-0.9) = 5.0 exactly
    for i in range(10):
        ring.append(20.0 if i % 2 else 5.0, t=now - 10 + i + 0.5)
    assert engine.burn_rate(objective, 10.0, now=now) \
        == pytest.approx(5.0)
    # no data in the window -> 0.0 (idle burns nothing)
    assert engine.burn_rate(objective, 10.0, now=now + 100) == 0.0
    # all good -> 0.0
    ring.append(1.0, t=now + 200)
    assert engine.burn_rate(objective, 1.0, now=now + 200) == 0.0


def test_multiwindow_alerts_fire_exactly_on_both_windows():
    engine = SLOEngine()
    ring = engine.add_signal("lat", lambda: 0.0)
    engine.add_objective(Objective(
        "lat", 10.0, window_s=60.0, fast_window_s=5.0, target=0.9,
        burn_threshold=2.0))
    now = 1000.0
    for i in range(60):
        ring.append(1.0, t=now - 60 + i)
    assert engine.evaluate(now=now)[0]["alerting"] is False
    # fast-only breach (last 5 s bad, slow window still compliant
    # enough): 5/65 bad -> slow burn ~0.77 < 2 -> NO alert
    now += 5
    for i in range(5):
        ring.append(99.0, t=now - 5 + i + 0.5)
    res = engine.evaluate(now=now)[0]
    assert res["fast_burn"] >= 2.0
    assert res["slow_burn"] < 2.0
    assert res["alerting"] is False
    assert engine.alerts_total == 0
    # sustain the breach: both windows burn -> exactly one edge
    now += 30
    for i in range(30):
        ring.append(99.0, t=now - 30 + i + 0.5)
    res = engine.evaluate(now=now)[0]
    assert res["alerting"] is True
    assert engine.alerts_total == 1
    engine.evaluate(now=now)
    assert engine.alerts_total == 1, "standing alert re-counted"
    # recovery clears; a second breach is a second edge
    now += 120
    ring.append(1.0, t=now - 1)
    assert engine.evaluate(now=now)[0]["alerting"] is False
    for i in range(60):
        ring.append(99.0, t=now + i)
    assert engine.evaluate(now=now + 60)[0]["alerting"] is True
    assert engine.alerts_total == 2


def test_configure_reads_the_obs_slo_namespace():
    engine = SLOEngine()
    engine.add_signal("ttft_p99_ms", lambda: 0.0)
    engine.add_signal("batch_fill", lambda: 0.0)
    installed = engine.configure({
        "ttft_p99_ms": {"max": 123.0, "window_s": 30.0,
                        "target": 0.95},
        "batch_fill": {"min": 0.25},
        "unknown_signal": {"max": 1.0},     # skipped: not exported
        "not_a_spec": 42,                   # skipped: malformed
    })
    assert installed == 2
    by_signal = {o.signal: o for o in engine.objectives}
    assert by_signal["ttft_p99_ms"].bound == 123.0
    assert by_signal["ttft_p99_ms"].op == "<"
    assert by_signal["ttft_p99_ms"].target == 0.95
    assert by_signal["batch_fill"].op == ">"
    # the stock root.common.obs.slo default declares a TTFT objective
    stock = SLOEngine()
    stock.add_signal("ttft_p99_ms", lambda: 0.0)
    assert stock.configure() == 1


def test_standard_engine_reads_serving_gauges():
    from veles_tpu.serve.metrics import ServingMetrics

    metrics = ServingMetrics()
    metrics.register_gauge("queue_depth", lambda: 3)
    metrics.register_gauge('gen_queue_depth{model="a"}', lambda: 2)
    metrics.register_gauge('gen_batch_fill{model="a"}', lambda: 0.5)
    metrics.register_gauge('gen_batch_fill{model="b"}', lambda: 0.7)
    metrics.register_gauge('gen_ttft_p99_ms{model="a"}', lambda: 50.0)
    metrics.register_gauge('gen_ttft_p99_ms{model="b"}', lambda: 80.0)
    engine = obs.standard_engine(metrics)
    engine.sample(now=10.0)
    signals = engine.describe()["signals"]
    assert signals["queue_depth"] == 5.0      # batcher + gen summed
    assert signals["batch_fill"] == pytest.approx(0.6)
    assert signals["ttft_p99_ms"] == 80.0     # worst model
    # the autoscaling triple is always present
    auto = engine.autoscaling_signals()
    assert set(auto) == set(obs.AUTOSCALING_SIGNALS)
    text = engine.metrics_text()
    for name in ("veles_slo_queue_depth 5", "veles_slo_batch_fill 0.6",
                 "veles_slo_ttft_p99_burn_rate"):
        assert name in text, text


def test_serving_server_exports_slo_on_metrics_and_healthz():
    from veles_tpu.serve.server import ServingServer

    server = ServingServer()
    try:
        page = server.metrics_page()
        for needle in ("veles_slo_queue_depth",
                       "veles_slo_batch_fill",
                       "veles_slo_ttft_p99_burn_rate",
                       "veles_slo_burn_rate{objective="):
            assert needle in page, page
        _status, payload = server.healthz()
        slo = payload["slo"]
        assert set(slo["autoscaling"]) == set(obs.AUTOSCALING_SIGNALS)
        # the stock config's TTFT objective is declared and evaluated
        assert any(o["signal"] == "ttft_p99_ms"
                   for o in slo["objectives"])
        assert "evaluation" in slo
    finally:
        server.stop()


# -- per-role scrape endpoints ----------------------------------------------

def _parse_families(page):
    """{metric name: TYPE line count} + sample lines — the torn/
    duplicate-TYPE detector a strict Prometheus parser applies."""
    types = {}
    for line in page.splitlines():
        if line.startswith("# TYPE "):
            name = line.split()[2]
            types[name] = types.get(name, 0) + 1
    return types


def test_master_scrape_endpoint_serves_histograms_and_stalls():
    from veles_tpu.parallel.jobs import JobClient, JobServer

    master = _ScriptedMaster(n_jobs=4)
    server = JobServer(master).start()
    try:
        scrape = server.start_scrape()
        assert server.start_scrape() is scrape, "must be idempotent"
        client = JobClient(_ScriptedSlave(), server.endpoint)
        client.handshake()
        assert client.run()
        # a watchdog excursion -> the promoted counter
        server.heartbeat_stalls[client.sid] += 1
        with urllib.request.urlopen(
                "http://%s:%d/metrics" % (scrape.host, scrape.port),
                timeout=10) as resp:
            page = resp.read().decode()
        client.close()
    finally:
        server.stop()
    assert "veles_jobs_updates_applied_total 4" in page
    assert 'veles_jobs_heartbeat_stalls_total{slave="%s"} 1' \
        % client.sid in page
    # the PR 5 print_stats-only histograms are now REAL families
    assert 'veles_jobs_job_latency_seconds_bucket{slave="%s",le=' \
        % client.sid in page
    assert 'veles_jobs_job_latency_seconds_count{slave="%s"} 4' \
        % client.sid in page
    # the process-wide base rides the same endpoint
    assert "veles_prof_compiles_total" in page
    # exposition-legal: one TYPE line per family
    assert all(n == 1 for n in _parse_families(page).values())
    # /healthz names the role
    assert server._scrape is None, "stop() must tear the listener down"


def test_slave_and_pod_scrape_surfaces():
    from veles_tpu.parallel.jobs import JobClient, JobServer
    from veles_tpu.pod.membership import PodMaster

    master = _ScriptedMaster(n_jobs=1)
    server = JobServer(master).start()
    try:
        client = JobClient(_ScriptedSlave(), server.endpoint)
        client.handshake()
        assert client.run()
        scrape = client.start_scrape()
        with urllib.request.urlopen(
                "http://%s:%d/metrics" % (scrape.host, scrape.port),
                timeout=10) as resp:
            page = resp.read().decode()
        assert "veles_slave_jobs_done_total 1" in page
        with urllib.request.urlopen(
                "http://%s:%d/healthz" % (scrape.host, scrape.port),
                timeout=10) as resp:
            health = json.loads(resp.read())
        assert health["role"] == client.trace_role
        client.close()
        assert client._scrape is None
    finally:
        server.stop()
    # a PodMaster surfaces its lease table through the master's
    # metrics_text workflow passthrough
    import veles_tpu.workflow as workflow_module

    class _Anchor(object):
        def checksum(self):
            return "pod-v1"

        decision = type("D", (), {"max_epochs": 2})()

    pod_master = PodMaster(_Anchor(), pods=2)
    assert "veles_pod_leases_queued 2" in pod_master.metrics_text()
    pod_server = JobServer(pod_master)
    try:
        text = pod_server.metrics_text()
        assert "veles_pod_leases_queued 2" in text
        assert "veles_jobs_slaves 0" in text
    finally:
        pod_server.stop()
    assert workflow_module is not None


def test_scrape_never_tears_during_gauge_lifecycle_races():
    """ISSUE satellite: concurrent ``/metrics`` rendering while gen-
    scheduler-style gauges/histograms register and unregister (the
    PR 11 close path) must never yield a torn or duplicate-TYPE
    exposition."""
    from veles_tpu.metrics import LatencyHistogram
    from veles_tpu.serve.metrics import ServingMetrics

    metrics = ServingMetrics()
    metrics.request_latency.record(0.01)
    stop = threading.Event()
    failures = []

    def churn(model):
        label = '{model="%s"}' % model
        hist = LatencyHistogram()
        hist.record(0.02)
        while not stop.is_set():
            metrics.register_gauge("gen_queue_depth" + label,
                                   lambda: 1)
            metrics.register_gauge("gen_batch_fill" + label,
                                   lambda: 0.5)
            metrics.register_histogram(
                "gen_ttft_seconds", hist,
                "submit -> first token", labels={"model": model})
            metrics.unregister_gauge("gen_queue_depth" + label)
            metrics.unregister_gauge("gen_batch_fill" + label)
            metrics.unregister_histogram("gen_ttft_seconds",
                                         labels={"model": model})

    def scraper():
        while not stop.is_set():
            try:
                page = metrics.render_text()
            except Exception as e:  # noqa: BLE001 - the race probe
                failures.append("render raised: %r" % e)
                return
            types = _parse_families(page)
            dups = {n: k for n, k in types.items() if k > 1}
            if dups:
                failures.append("duplicate TYPE lines: %r" % dups)
                return
            # a histogram family present must be complete (bucket
            # lines AND _count — a torn family breaks the parser)
            if "veles_serve_gen_ttft_seconds" in types:
                if "veles_serve_gen_ttft_seconds_count" not in page \
                        or "veles_serve_gen_ttft_seconds_bucket" \
                        not in page:
                    failures.append("torn histogram family")
                    return

    threads = [threading.Thread(target=churn, args=("m%d" % i,))
               for i in range(2)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(5)
    assert not failures, failures


# -- flight recorder --------------------------------------------------------

@pytest.fixture
def blackbox_dir(tmp_path):
    saved = root.common.obs.get("blackbox_dir")
    root.common.obs.blackbox_dir = str(tmp_path / "bb")
    yield str(tmp_path / "bb")
    root.common.obs.blackbox_dir = saved
    blackbox.uninstall()


@pytest.mark.traced
def test_blackbox_dump_and_load_roundtrip(blackbox_dir):
    trace.instant("jobs", "heartbeat", {"gap_ms": 1.0}, role="master")
    path = blackbox.dump("unit test", extra={"k": "v"})
    assert path is not None and path.startswith(blackbox_dir)
    payload = blackbox.load(path)
    assert payload["kind"] == blackbox.KIND
    assert payload["reason"] == "unit test"
    assert payload["extra"] == {"k": "v"}
    assert payload["event_counts"].get("jobs", 0) >= 1
    assert any(ev["name"] == "heartbeat"
               for ev in payload["events"])
    assert "ledger" in payload
    # a non-post-mortem file is rejected, not misread
    other = blackbox_dir + "/other.json"
    with open(other, "w") as fout:
        json.dump({"kind": "nope"}, fout)
    with pytest.raises(ValueError):
        blackbox.load(other)


def test_blackbox_noop_when_unarmed():
    assert blackbox.blackbox_dir() in (None, "")
    assert blackbox.dump("nobody home") is None
    assert blackbox.install() is False


def test_blackbox_excepthook_dumps(blackbox_dir):
    import glob

    assert blackbox.install() is True
    try:
        try:
            raise RuntimeError("boom for the recorder")
        except RuntimeError:
            tp, value, tb = sys.exc_info()
        # excepthook chains: our dump runs, then the previous hook
        seen = []
        blackbox._prev_excepthook[0] = \
            lambda *a: seen.append(a[0].__name__)
        sys.excepthook(tp, value, tb)
        assert seen == ["RuntimeError"]
    finally:
        blackbox.uninstall()
    files = glob.glob(blackbox_dir + "/blackbox-*.json")
    assert len(files) == 1
    payload = blackbox.load(files[0])
    assert "boom for the recorder" in payload["reason"]


def test_blackbox_thread_excepthook_dumps(blackbox_dir):
    """Every role here runs on a thread (server loop, workers) —
    a crash there must leave a post-mortem too."""
    import glob

    assert blackbox.install() is True
    try:
        chained = []
        blackbox._prev_thread_hook[0] = \
            lambda a: chained.append(a.exc_type.__name__)

        def boom():
            raise ValueError("thread boom for the recorder")

        worker = threading.Thread(target=boom, name="bb-worker")
        worker.start()
        worker.join(5)
        assert chained == ["ValueError"], "previous hook must chain"
    finally:
        blackbox.uninstall()
    files = glob.glob(blackbox_dir + "/blackbox-*.json")
    assert len(files) == 1
    payload = blackbox.load(files[0])
    assert "bb-worker" in payload["reason"]
    assert "thread boom" in payload["reason"]


@pytest.mark.traced
def test_chaos_slave_kill_leaves_loadable_postmortem(blackbox_dir):
    """The ISSUE's chaos gate: a slave_kill session must leave a
    loadable post-mortem naming the dead slave."""
    import glob

    from veles_tpu.parallel.jobs import JobClient, JobServer

    master = _ScriptedMaster(n_jobs=2)
    server = JobServer(master).start()
    try:
        client = JobClient(_ScriptedSlave(), server.endpoint,
                           death_probability=1.0)
        client.handshake()
        assert client.run() is False, "the kill must fire"
        client.close()
    finally:
        server.stop()
    files = glob.glob(blackbox_dir + "/blackbox-*.json")
    assert len(files) == 1
    payload = blackbox.load(files[0])
    assert "kill" in payload["reason"]
    assert payload["extra"]["slave"] == client.sid
    assert payload["events"], "the trace ring must ride along"


# -- the overhead gate ------------------------------------------------------

@pytest.mark.slow
def test_tracing_on_overhead_stays_under_five_percent():
    """ISSUE acceptance: with request tracing ON the gen workload
    keeps >= 0.95x the tracing-off tokens/s.  The true tax measures
    ~2% here; per-pass host noise is ~+/-10%, so the gate compares
    BEST-of interleaved passes on ONE warm engine (no per-rep
    compile/heap churn) and remeasures once before failing."""
    from veles_tpu.gen import GenerativeScheduler

    # the bench mix (stage_transformer_gen): mostly short interactive
    # budgets with a long-form request interleaved every slots-th —
    # the workload the ISSUE's 0.95x gate is written against (an
    # admission-dominated micro mix overweights per-request span
    # costs instead of the steady decode cadence)
    rng = numpy.random.default_rng(0)
    workload = [(rng.integers(0, 50, int(rng.integers(1, 8))).tolist(),
                 32 if i % 4 == 0 else int(rng.integers(2, 10)))
                for i in range(96)]
    saved = root.common.engine.get("trace", "off")
    engine = _tiny_gen_engine(max_slots=4, max_seq=48).warmup()

    def timed_pass(traced):
        root.common.engine.trace = "on" if traced else "off"
        trace.configure()
        trace.recorder.clear()
        scheduler = GenerativeScheduler(engine, name="ovh")
        try:
            tic = time.perf_counter()
            futures = []
            for toks, max_new in workload:
                with obs.activate(obs.mint() if traced else None):
                    futures.append(scheduler.submit(toks, max_new))
            scheduler.run_until_idle()
            sec = time.perf_counter() - tic
            assert all(f.done() for f in futures)
            return scheduler.tokens_total, sec
        finally:
            scheduler.stop()
            root.common.engine.trace = saved
            trace.configure()
            trace.recorder.clear()

    def measure():
        # interleaved pairs; gate on the BETTER of two statistics —
        # best-of per mode (noise only ever subtracts throughput, so
        # the best sample is the least-contaminated estimate) and
        # the aggregate over all passes (averages the jitter).  Both
        # understate only when tracing is genuinely slow;
        # interleaving keeps one mode from monopolizing a quiet
        # stretch of the host
        on_samples, off_samples = [], []
        on_total, off_total = [0, 0.0], [0, 0.0]
        for _ in range(6):
            for traced, samples, total in (
                    (False, off_samples, off_total),
                    (True, on_samples, on_total)):
                tokens, sec = timed_pass(traced)
                samples.append(tokens / sec)
                total[0] += tokens
                total[1] += sec
        best = max(on_samples) / max(off_samples)
        aggregate = (on_total[0] / on_total[1]) \
            / (off_total[0] / off_total[1])
        return max(best, aggregate), on_samples, off_samples

    try:
        timed_pass(False)
        timed_pass(True)          # both paths warm before timing
        ratio, on_samples, off_samples = measure()
        if ratio < 0.95:          # one remeasure before failing: a
            retry, r_on, r_off = measure()   # slow host stretch is
            if retry > ratio:                # not a tracing tax
                ratio, on_samples, off_samples = retry, r_on, r_off
    finally:
        engine.close()
    print("tracing overhead: best-of ratio %.3fx (on %s / off %s)"
          % (ratio, ["%.0f" % s for s in on_samples],
             ["%.0f" % s for s in off_samples]))
    assert ratio >= 0.95, \
        "tracing-on throughput %.3fx of tracing-off (< 0.95x)" % ratio
