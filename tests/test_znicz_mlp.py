"""End-to-end Znicz-equivalent MLP training (the minimum slice from
SURVEY §7 stage 5: loader → all2all_tanh → softmax → evaluator →
decision → gd chain, looping until complete)."""

import numpy
import pytest

from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class BlobLoader(FullBatchLoader):
    """Separable 10-class gaussian blobs in 64-d (a fast MNIST stand-in:
    real-MNIST parity is gated by dataset availability, BASELINE.md)."""

    def __init__(self, workflow, n_train=400, n_valid=100, dim=64,
                 n_classes=10, **kwargs):
        self._cfg = (n_train, n_valid, dim, n_classes)
        super(BlobLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        n_train, n_valid, dim, n_classes = self._cfg
        rng = numpy.random.default_rng(42)
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(n_classes),
                            total // n_classes + 1)[:total]
        centers = rng.standard_normal((n_classes, dim)) * 3.0
        data = centers[labels] + rng.standard_normal((total, dim)) * 0.7
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels = list(int(x) for x in labels)
        self.class_lengths[:] = [0, n_valid, n_train]


LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 10},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]


def build(device, max_epochs=8, minibatch_size=50):
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size),
        layers=[{**spec} for spec in LAYERS],
        decision_config={"max_epochs": max_epochs},
    )
    from veles_tpu.dummy import DummyLauncher
    wf.launcher = DummyLauncher()
    wf.initialize(device=device)
    return wf


def test_graph_shape():
    wf = build(NumpyDevice(), max_epochs=1)
    assert len(wf.forwards) == 2
    assert len(wf.gds) == 2
    assert wf.forwards[0].weights.shape == (64, 32)
    assert wf.forwards[1].weights.shape == (32, 10)
    # gd chain reversed: gds[0] pairs the softmax layer
    assert wf.gds[0].weights is not None
    assert wf.gds[1].err_input is not None


def test_training_converges_numpy():
    from veles_tpu import prng
    prng.seed_all(5)
    wf = build(NumpyDevice(), max_epochs=8)
    wf.run()
    assert wf.stopped
    assert wf.decision.best_n_err_pt < 10.0, \
        "blobs are separable; expected <10%% err, got %.2f%%" % \
        wf.decision.best_n_err_pt


def test_training_converges_jit_and_matches_numpy():
    """The jitted CPU path must converge like the numpy path (parity of
    the two backends, ref accelerated_test.multi_device strategy)."""
    from veles_tpu import prng
    prng.seed_all(5)
    wf_np = build(NumpyDevice(), max_epochs=4)
    wf_np.run()
    prng.seed_all(5)
    wf_cpu = build(CPUDevice(), max_epochs=4)
    wf_cpu.run()
    # identical seeds → identical init; bf16-free CPU jit math ≈ numpy
    assert abs(wf_cpu.decision.best_n_err_pt -
               wf_np.decision.best_n_err_pt) < 3.0


def test_forward_parity_numpy_vs_jit():
    from veles_tpu import prng
    prng.seed_all(11)
    wf = build(NumpyDevice(), max_epochs=1)
    loader = wf.loader
    loader.run()
    fwd = wf.forwards[0]
    fwd.run()
    out_numpy = numpy.array(fwd.output.mem)

    prng.seed_all(11)
    wf2 = build(CPUDevice(), max_epochs=1)
    wf2.loader.run()
    fwd2 = wf2.forwards[0]
    fwd2.run()
    out_jit = numpy.array(fwd2.output.mem)
    assert numpy.allclose(out_numpy, out_jit, atol=1e-4)


def test_gd_updates_weights_both_paths():
    from veles_tpu import prng
    for device in (NumpyDevice(), CPUDevice()):
        prng.seed_all(3)
        wf = build(device, max_epochs=1)
        wf.loader.run()
        while wf.loader.minibatch_class != 2:   # advance to TRAIN
            wf.loader.run()
        for fwd in wf.forwards:
            fwd.run()
        wf.evaluator.run()
        before = numpy.array(wf.forwards[1].weights.mem)
        wf.gds[0].run()
        after = numpy.array(wf.forwards[1].weights.mem)
        assert not numpy.allclose(before, after), device


def test_results_and_stats():
    wf = build(NumpyDevice(), max_epochs=2)
    wf.run()
    results = wf.gather_results()
    assert "best_validation_error_pt" in results
    assert "Total epochs" in results
    stats = wf.get_unit_run_time_stats()
    assert stats[0][1] >= 0


def test_snapshot_mid_training_resumes(tmp_path):
    """Whole-workflow pickle mid-loop; restored workflow continues
    training (the §5.4 checkpoint/resume property)."""
    import pickle
    from veles_tpu import prng
    prng.seed_all(5)
    wf = build(NumpyDevice(), max_epochs=2)
    wf.run()
    first_err = wf.decision.best_n_err_pt
    blob = pickle.dumps(wf)
    restored = pickle.loads(blob)
    from veles_tpu.dummy import DummyLauncher
    restored.launcher = DummyLauncher()
    restored.decision.max_epochs = 6
    restored.decision.complete <<= False
    restored.initialize(device=NumpyDevice())
    restored.run()
    assert restored.decision.best_n_err_pt <= first_err
    assert restored.loader.epoch_number > 2


def test_weights_transposed_storage():
    """Documented knob #13 (weights_transposed): storage flips to
    (neurons, fan-in).  Given exactly transposed weights, every
    execution path — eager numpy (incl. the softmax override), pure,
    the eager GD step, and the export's canonical layout — matches the
    untransposed twin; default init derives its scale from the TRUE
    fan-in, not the storage-leading axis."""
    import jax.numpy as jnp

    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.package import _collect_arrays
    from veles_tpu.znicz.all2all import All2AllSoftmax, All2AllTanh

    wf = DummyWorkflow()
    rng = numpy.random.default_rng(2)
    x = rng.standard_normal((6, 100)).astype(numpy.float32)

    a = All2AllTanh(wf, output_sample_shape=(4,))
    a.input = Vector(x.copy())
    a.initialize(device=None)
    b = All2AllTanh(wf, output_sample_shape=(4,),
                    weights_transposed=True)
    b.input = Vector(x.copy())
    b.initialize(device=None)
    assert a.weights.mem.shape == (100, 4)
    assert b.weights.mem.shape == (4, 100)
    # default init scale comes from fan-in=100 in BOTH layouts (the
    # uniform filling is bounded by 1/sqrt(fan_in), NOT 1/sqrt(4))
    bound = 1.0 / numpy.sqrt(100) + 1e-6
    assert numpy.abs(a.weights.mem).max() <= bound
    assert numpy.abs(b.weights.mem).max() <= bound

    # exactly transposed weights ⇒ identical numerics on every path
    b.weights.map_write()
    b.weights.mem[...] = a.weights.mem.T
    b.bias.map_write()
    b.bias.mem[...] = a.bias.mem
    a.numpy_run()
    b.numpy_run()
    numpy.testing.assert_allclose(b.output.mem, a.output.mem,
                                  rtol=1e-6)
    out_p = All2AllTanh.pure({"w": jnp.asarray(b.weights.mem),
                              "b": jnp.asarray(b.bias.mem)},
                             jnp.asarray(x), activation="tanh",
                             transposed=True)
    numpy.testing.assert_allclose(numpy.asarray(out_p), a.output.mem,
                                  rtol=1e-5, atol=1e-6)
    # export normalizes to the canonical (fan-in, neurons) layout
    arrays = _collect_arrays(b, 32)
    numpy.testing.assert_allclose(arrays["weights"], a.weights.mem,
                                  rtol=1e-6)

    # the softmax subclass overrides numpy_run: same contract
    sa = All2AllSoftmax(wf, output_sample_shape=(5,))
    sa.input = Vector(x.copy())
    sa.initialize(device=None)
    sb = All2AllSoftmax(wf, output_sample_shape=(5,),
                        weights_transposed=True)
    sb.input = Vector(x.copy())
    sb.initialize(device=None)
    sb.weights.map_write()
    sb.weights.mem[...] = sa.weights.mem.T
    sb.bias.map_write()
    sb.bias.mem[...] = sa.bias.mem
    sa.numpy_run()
    sb.numpy_run()
    numpy.testing.assert_allclose(sb.output.mem, sa.output.mem,
                                  rtol=1e-6)
    numpy.testing.assert_array_equal(sb.max_idx.mem, sa.max_idx.mem)


def test_weights_transposed_eager_training_matches():
    """The eager GD chain handles transposed storage: a full
    2-epoch StandardWorkflow run with weights_transposed=True trains
    (and its first-layer weights stay the exact transpose of the
    untransposed twin's, given identical seeding)."""
    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    def run_once(transposed):
        prng.seed_all(15)
        layers = [
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 32,
                    "weights_filling": "constant",
                    "weights_stddev": 0.01,
                    "weights_transposed": transposed},
             "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.03}},
        ]
        wf = mnist.create_workflow(device=CPUDevice(), max_epochs=2,
                                   minibatch_size=1000, layers=layers)
        wf.run()
        wf.forwards[0].weights.map_read()
        return (numpy.array(wf.forwards[0].weights.mem),
                float(wf.decision.best_n_err_pt))

    w_std, err_std = run_once(False)
    w_t, err_t = run_once(True)
    assert w_std.shape == (784, 32) and w_t.shape == (32, 784)
    # constant-filled identical starts ⇒ training keeps the exact
    # transpose relation through the whole eager gd chain
    numpy.testing.assert_allclose(w_t, w_std.T, rtol=1e-5, atol=1e-6)
    assert err_t == pytest.approx(err_std, abs=1e-6)


def test_evaluator_mse_mean_knob():
    """Documented evaluator knob `mean`: False selects sum-over-batch
    gradient semantics (err_output pre-scaled by batch so the GD
    units' /batch cancels); True (default) is unchanged."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.evaluator import EvaluatorMSE

    wf = DummyWorkflow()
    rng = numpy.random.default_rng(4)
    out = rng.standard_normal((5, 3)).astype(numpy.float32)
    target = rng.standard_normal((5, 3)).astype(numpy.float32)

    def build(**kw):
        ev = EvaluatorMSE(wf, **kw)
        ev.output = Vector(out.copy())
        ev.target = Vector(target.copy())
        ev.batch_size = 5
        ev.err_output = Vector(numpy.zeros((5, 3), numpy.float32))
        ev.run()
        return ev

    a = build()
    b = build(mean=False)
    numpy.testing.assert_allclose(a.err_output.mem, out - target,
                                  rtol=1e-6)
    numpy.testing.assert_allclose(b.err_output.mem,
                                  (out - target) * 5.0, rtol=1e-6)
    assert a.mse == pytest.approx(b.mse)     # the metric is unscaled

    # short batch: the scale is the BUFFER row count (the GD units'
    # divisor), so sum semantics hold for the epoch tail too
    ev = EvaluatorMSE(wf, mean=False)
    ev.output = Vector(out.copy())
    ev.target = Vector(target.copy())
    ev.batch_size = 5
    ev.err_output = Vector(numpy.zeros((8, 3), numpy.float32))
    ev.run()
    numpy.testing.assert_allclose(ev.err_output.mem[:5],
                                  (out - target) * 8.0, rtol=1e-6)
    numpy.testing.assert_array_equal(ev.err_output.mem[5:], 0.0)
