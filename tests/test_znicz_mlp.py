"""End-to-end Znicz-equivalent MLP training (the minimum slice from
SURVEY §7 stage 5: loader → all2all_tanh → softmax → evaluator →
decision → gd chain, looping until complete)."""

import numpy
import pytest

from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class BlobLoader(FullBatchLoader):
    """Separable 10-class gaussian blobs in 64-d (a fast MNIST stand-in:
    real-MNIST parity is gated by dataset availability, BASELINE.md)."""

    def __init__(self, workflow, n_train=400, n_valid=100, dim=64,
                 n_classes=10, **kwargs):
        self._cfg = (n_train, n_valid, dim, n_classes)
        super(BlobLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        n_train, n_valid, dim, n_classes = self._cfg
        rng = numpy.random.default_rng(42)
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(n_classes),
                            total // n_classes + 1)[:total]
        centers = rng.standard_normal((n_classes, dim)) * 3.0
        data = centers[labels] + rng.standard_normal((total, dim)) * 0.7
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels = list(int(x) for x in labels)
        self.class_lengths[:] = [0, n_valid, n_train]


LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 10},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]


def build(device, max_epochs=8, minibatch_size=50):
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size),
        layers=[{**spec} for spec in LAYERS],
        decision_config={"max_epochs": max_epochs},
    )
    from veles_tpu.dummy import DummyLauncher
    wf.launcher = DummyLauncher()
    wf.initialize(device=device)
    return wf


def test_graph_shape():
    wf = build(NumpyDevice(), max_epochs=1)
    assert len(wf.forwards) == 2
    assert len(wf.gds) == 2
    assert wf.forwards[0].weights.shape == (64, 32)
    assert wf.forwards[1].weights.shape == (32, 10)
    # gd chain reversed: gds[0] pairs the softmax layer
    assert wf.gds[0].weights is not None
    assert wf.gds[1].err_input is not None


def test_training_converges_numpy():
    from veles_tpu import prng
    prng.seed_all(5)
    wf = build(NumpyDevice(), max_epochs=8)
    wf.run()
    assert wf.stopped
    assert wf.decision.best_n_err_pt < 10.0, \
        "blobs are separable; expected <10%% err, got %.2f%%" % \
        wf.decision.best_n_err_pt


def test_training_converges_jit_and_matches_numpy():
    """The jitted CPU path must converge like the numpy path (parity of
    the two backends, ref accelerated_test.multi_device strategy)."""
    from veles_tpu import prng
    prng.seed_all(5)
    wf_np = build(NumpyDevice(), max_epochs=4)
    wf_np.run()
    prng.seed_all(5)
    wf_cpu = build(CPUDevice(), max_epochs=4)
    wf_cpu.run()
    # identical seeds → identical init; bf16-free CPU jit math ≈ numpy
    assert abs(wf_cpu.decision.best_n_err_pt -
               wf_np.decision.best_n_err_pt) < 3.0


def test_forward_parity_numpy_vs_jit():
    from veles_tpu import prng
    prng.seed_all(11)
    wf = build(NumpyDevice(), max_epochs=1)
    loader = wf.loader
    loader.run()
    fwd = wf.forwards[0]
    fwd.run()
    out_numpy = numpy.array(fwd.output.mem)

    prng.seed_all(11)
    wf2 = build(CPUDevice(), max_epochs=1)
    wf2.loader.run()
    fwd2 = wf2.forwards[0]
    fwd2.run()
    out_jit = numpy.array(fwd2.output.mem)
    assert numpy.allclose(out_numpy, out_jit, atol=1e-4)


def test_gd_updates_weights_both_paths():
    from veles_tpu import prng
    for device in (NumpyDevice(), CPUDevice()):
        prng.seed_all(3)
        wf = build(device, max_epochs=1)
        wf.loader.run()
        while wf.loader.minibatch_class != 2:   # advance to TRAIN
            wf.loader.run()
        for fwd in wf.forwards:
            fwd.run()
        wf.evaluator.run()
        before = numpy.array(wf.forwards[1].weights.mem)
        wf.gds[0].run()
        after = numpy.array(wf.forwards[1].weights.mem)
        assert not numpy.allclose(before, after), device


def test_results_and_stats():
    wf = build(NumpyDevice(), max_epochs=2)
    wf.run()
    results = wf.gather_results()
    assert "best_validation_error_pt" in results
    assert "Total epochs" in results
    stats = wf.get_unit_run_time_stats()
    assert stats[0][1] >= 0


def test_snapshot_mid_training_resumes(tmp_path):
    """Whole-workflow pickle mid-loop; restored workflow continues
    training (the §5.4 checkpoint/resume property)."""
    import pickle
    from veles_tpu import prng
    prng.seed_all(5)
    wf = build(NumpyDevice(), max_epochs=2)
    wf.run()
    first_err = wf.decision.best_n_err_pt
    blob = pickle.dumps(wf)
    restored = pickle.loads(blob)
    from veles_tpu.dummy import DummyLauncher
    restored.launcher = DummyLauncher()
    restored.decision.max_epochs = 6
    restored.decision.complete <<= False
    restored.initialize(device=NumpyDevice())
    restored.run()
    assert restored.decision.best_n_err_pt <= first_err
    assert restored.loader.epoch_number > 2
