"""veles_tpu.chaos — deterministic fault injection + the robustness
upgrades it gates: exactly-once update semantics (dedup, stale-
generation rejection, lost-frame requeue), master crash-recovery
(async checkpoints → kill → resume → slave rejoin), and the
convergence-parity acceptance gate (docs/robustness.md)."""

import threading
import time

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import NumpyDevice
from veles_tpu.chaos.core import ChaosSchedule, Fault
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.parallel.jobs import (JobClient, JobServer,
                                     SlaveDescription)
from veles_tpu.znicz.standard_workflow import StandardWorkflow


@pytest.fixture(autouse=True)
def _disarm_after():
    """Every test leaves the process-wide controller disarmed."""
    yield
    chaos.controller.disarm()


@pytest.fixture
def live_trace():
    """Knob-based enabling (NOT poking the recorder): the workflows
    built inside the test call initialize() → trace.configure(),
    which re-reads the knob — a directly-enabled recorder would be
    switched back off by the first make_wf()."""
    from veles_tpu import trace
    from veles_tpu.config import root
    saved = root.common.engine.get("trace", "off")
    root.common.engine.trace = "on"
    trace.recorder.clear()
    trace.configure()
    yield trace
    root.common.engine.trace = saved
    trace.configure()
    trace.recorder.clear()


# -- the shared tiny distributed workflow (mirrors test_jobs.py) ------------

class ChaosDistLoader(FullBatchLoader):
    def load_data(self):
        rng = numpy.random.default_rng(5)
        n = 200
        labels = (numpy.arange(n) % 5).astype(int)
        centers = rng.standard_normal((5, 16)) * 3
        self.original_data.mem = (
            centers[labels] + rng.standard_normal((n, 16)) * 0.5
        ).astype(numpy.float32)
        self.original_labels = [int(v) for v in labels]
        self.class_lengths[:] = [0, 50, 150]


CHAOS_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 5},
     "<-": {"learning_rate": 0.05}},
]


def make_wf(is_master=False, is_slave=False, max_epochs=3):
    from veles_tpu import prng
    prng.seed_all(21)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: ChaosDistLoader(w, minibatch_size=25),
        layers=[{**s} for s in CHAOS_LAYERS],
        decision_config={"max_epochs": max_epochs})
    wf.launcher = DummyLauncher(is_master=is_master, is_slave=is_slave)
    wf.initialize(device=NumpyDevice())
    return wf


def final_metrics(wf):
    return {"best_n_err_pt": float(wf.decision.best_n_err_pt),
            "best_epoch": int(wf.decision.best_epoch),
            "epochs": int(wf.loader.epoch_number),
            "complete": bool(wf.decision.complete)}


def master_weights(wf):
    wf.forwards[0].weights.map_read()
    return numpy.array(wf.forwards[0].weights.mem)


# -- fault model ------------------------------------------------------------

def test_fault_validation_and_schedule_roundtrip():
    with pytest.raises(ValueError):
        Fault("master_send", "explode", nth=1)
    with pytest.raises(ValueError):        # two selectors
        Fault("master_send", "drop", nth=1, prob=0.5)
    with pytest.raises(ValueError):        # no selector
        Fault("master_send", "drop")
    sched = ChaosSchedule([
        {"site": "master_send", "action": "drop", "op": "job",
         "nth": 2},
        {"site": "slave_send", "action": "dup", "op": "update",
         "every": 3, "count": 2},
        {"site": "slave_job", "action": "slave_kill", "prob": 0.25},
    ])
    clone = ChaosSchedule.from_json(sched.to_json())
    assert [f.to_dict() for f in clone] == [f.to_dict() for f in sched]
    assert clone.faults[1].count == 2


def test_deterministic_wire_decisions_given_seed():
    """Two controllers with the same (seed, schedule) make IDENTICAL
    decisions over the same call sequence — the replayability
    contract."""
    def decisions(ctl):
        out = []
        for i in range(200):
            plan = ctl.wire("master_send", "job" if i % 3 else "update")
            out.append((plan.deliveries, plan.corrupt,
                        round(plan.delay_s, 6)))
        return out

    schedule = [{"site": "master_send", "action": "drop", "op": "job",
                 "prob": 0.2},
                {"site": "master_send", "action": "dup", "op": "update",
                 "prob": 0.3}]
    a = chaos.ChaosController()
    a.arm(list(schedule), seed=99)
    b = chaos.ChaosController()
    b.arm(list(schedule), seed=99)
    da, db = decisions(a), decisions(b)
    assert da == db
    assert any(p[0] == 0 for p in da), "seeded drops must have fired"
    assert any(p[0] > 1 for p in da), "seeded dups must have fired"
    c = chaos.ChaosController()
    c.arm(list(schedule), seed=100)
    assert decisions(c) != da, "a different seed is a different run"


def test_nth_fires_exactly_once_and_partition_window():
    ctl = chaos.ChaosController()
    ctl.arm([{"site": "slave_send", "action": "drop", "op": "update",
              "nth": 3}])
    plans = [ctl.wire("slave_send", "update") for _ in range(6)]
    assert [p.deliveries for p in plans] == [1, 1, 0, 1, 1, 1]
    assert ctl.injected.get("drop") == 1

    ctl.arm([{"site": "master_recv", "action": "partition", "nth": 1,
              "duration_s": 0.2}])
    assert ctl.wire("master_recv", "update").deliveries == 0
    assert ctl.wire("master_recv", "ping").deliveries == 0, \
        "an op-less partition swallows EVERY frame at the site"
    assert ctl.wire("slave_send", "update").deliveries == 1, \
        "other sites are unaffected"
    time.sleep(0.25)
    assert ctl.wire("master_recv", "update").deliveries == 1, \
        "the window heals"


def test_corrupt_bytes_breaks_pickle_deterministically():
    import pickle
    blob = pickle.dumps({"op": "update", "data": [1, 2, 3]})
    mangled = chaos.ChaosController.corrupt_bytes(blob)
    assert mangled == chaos.ChaosController.corrupt_bytes(blob)
    assert mangled != blob


# -- exactly-once updates (the dedup unit proof) -----------------------------

def test_update_replay_applies_exactly_once():
    """The acceptance unit-proof: replaying a captured update frame
    twice changes the weights EXACTLY once."""
    master_wf = make_wf(is_master=True)
    slave_wf = make_wf(is_slave=True)
    server = JobServer(master_wf)        # not started: direct dispatch
    try:
        slave = SlaveDescription("s1")
        server.slaves["s1"] = slave
        # burn through the two validation minibatches (their updates
        # carry zero weight delta); job 3 is a TRAIN minibatch
        for _ in range(2):
            updates = []
            slave_wf.do_job(master_wf.generate_data_for_slave(slave),
                            updates.append)
            master_wf.apply_data_from_slave(updates[0], slave)
        with server._lock:
            server._seq += 1
            seq = server._seq
            data = master_wf.generate_data_for_slave(slave)
            slave.outstanding[seq] = time.time()
        updates = []
        slave_wf.do_job(data, updates.append)
        msg = {"op": "update", "id": "s1", "data": updates[0],
               "job": {"gen": server.generation, "epoch": 0,
                       "seq": seq}, "req": 1}
        w0 = master_weights(master_wf)
        server._on_update(b"s1", slave, dict(msg))
        w1 = master_weights(master_wf)
        assert not numpy.array_equal(w0, w1), "first copy must apply"
        server._on_update(b"s1", slave, dict(msg))   # captured replay
        w2 = master_weights(master_wf)
        numpy.testing.assert_array_equal(w1, w2)
        server._on_update(b"s1", slave, dict(msg))   # and again
        numpy.testing.assert_array_equal(w1, master_weights(master_wf))
        assert server.dedup_dropped == 2
        assert server._updates_applied == 1
    finally:
        server.stop()


def test_stale_generation_update_rejected_and_logged(caplog):
    """A pre-restart slave's update (older generation) is rejected,
    logged and counted — never applied."""
    import logging
    master_wf = make_wf(is_master=True)
    slave_wf = make_wf(is_slave=True)
    server = JobServer(master_wf)
    try:
        slave = SlaveDescription("s1")
        server.slaves["s1"] = slave
        for _ in range(2):               # skip the validation jobs
            updates = []
            slave_wf.do_job(master_wf.generate_data_for_slave(slave),
                            updates.append)
            master_wf.apply_data_from_slave(updates[0], slave)
        with server._lock:
            server._seq += 1
            seq = server._seq
            data = master_wf.generate_data_for_slave(slave)
            slave.outstanding[seq] = time.time()
        updates = []
        slave_wf.do_job(data, updates.append)
        server.generation = 2            # "the master restarted"
        w0 = master_weights(master_wf)
        with caplog.at_level(logging.WARNING):
            server._on_update(b"s1", slave, {
                "op": "update", "id": "s1", "data": updates[0],
                "job": {"gen": 1, "epoch": 0, "seq": seq}, "req": 1})
        numpy.testing.assert_array_equal(w0, master_weights(master_wf))
        assert server.stale_rejected == 1
        assert server._updates_applied == 0
        assert any("stale" in r.getMessage()
                   for r in caplog.records), caplog.records
        # the reply queued for the wire says stale, not ok
        import pickle
        acks = [pickle.loads(blob) for _ident, blob in server._outbox]
        assert acks and acks[-1]["ok"] == 0 and acks[-1]["stale"] == 1
    finally:
        server.stop()


def test_duplicated_update_frames_exact_parity():
    """Chaos-parity, the EXACT half: a run whose only faults are
    duplicated update frames finishes with final weights BITWISE equal
    to the fault-free run — dedup makes duplication a provable no-op."""
    def run_session(schedule=None):
        if schedule is not None:
            chaos.controller.arm(schedule, seed=11)
        master_wf = make_wf(is_master=True)
        slave_wf = make_wf(is_slave=True)
        server = JobServer(master_wf).start()
        try:
            client = JobClient(slave_wf, server.endpoint,
                               rpc_timeout_ms=2000)
            client.handshake()
            assert client.run() is True
            client.close()
        finally:
            server.stop()
            chaos.controller.disarm()
        return master_wf, server

    clean_wf, _clean_srv = run_session()
    chaos_wf, chaos_srv = run_session([
        {"site": "slave_send", "action": "dup", "op": "update",
         "nth": 2},
        {"site": "slave_send", "action": "dup", "op": "update",
         "nth": 9, "count": 2},
    ])
    assert chaos_srv.dedup_dropped == 3, \
        "1 + 2 extra copies must all be deduplicated"
    numpy.testing.assert_array_equal(master_weights(clean_wf),
                                     master_weights(chaos_wf))
    assert final_metrics(clean_wf) == final_metrics(chaos_wf)


def test_dropped_job_frame_requeued_session_completes():
    """A job frame lost on the wire degrades to one requeued job: the
    client times out, rejoins, the master requeues the lost seq via
    the ``have`` reconciliation, and every job still applies exactly
    once."""
    from veles_tpu.chaos.__main__ import SmokeMaster, SmokeSlave
    chaos.controller.arm([
        {"site": "master_send", "action": "drop", "op": "job",
         "nth": 2},
    ], seed=3)
    master = SmokeMaster(6)
    server = JobServer(master, slave_timeout=6.0,
                       heartbeat_interval=0.3).start()
    try:
        client = JobClient(SmokeSlave(), server.endpoint,
                           rpc_timeout_ms=700, reconnect_max_wait=10.0)
        client.handshake()
        assert client.run() is True
        client.close()
    finally:
        server.stop()
    assert sorted(master.applied) == [1, 2, 3, 4, 5, 6]
    assert server.lost_requeued >= 1
    assert master.requeues >= 1


def test_partition_heal_degrades_then_rejoins():
    """A partitioned slave is reaped (its work requeued — the session
    DEGRADES to fewer slaves rather than stalling); when the window
    heals, the slave's next contact gets ``reject: unknown id`` and it
    re-handshakes back in instead of dying — every job still applies
    exactly once."""
    from veles_tpu.chaos.__main__ import SmokeMaster, SmokeSlave
    chaos.controller.arm([
        # an op-less inbound partition swallowing frame 5 onward for
        # 2.5 s: frame 5 is job 2's update (handshake, request, update,
        # request, update), so the slave is holding an unacked job when
        # the master goes deaf — the reaper must requeue it
        {"site": "master_recv", "action": "partition", "nth": 5,
         "duration_s": 2.5},
    ], seed=5)
    master = SmokeMaster(8)
    server = JobServer(master, slave_timeout=1.0,
                       heartbeat_interval=0.3).start()
    try:
        client = JobClient(SmokeSlave(), server.endpoint,
                           rpc_timeout_ms=600,
                           reconnect_max_wait=20.0)
        client.handshake()
        assert client.run() is True
        client.close()
    finally:
        server.stop()
    assert sorted(master.applied) == list(range(1, 9)), master.applied
    assert master.requeues >= 1, \
        "the reaped slave's in-flight work must have been requeued"
    assert chaos.controller.injected.get("partition") == 1


# -- master crash-recovery ---------------------------------------------------

def test_capture_restore_train_state_roundtrip(tmp_path):
    """Workflow checkpoint protocol: weights + loader cursor +
    decision accounting survive a TrainCheckpointer round-trip into a
    FRESH workflow (the restarted-master scenario, socket-free)."""
    from veles_tpu.checkpoint import TrainCheckpointer
    wf = make_wf(is_master=True)
    # advance some real state
    slave_wf = make_wf(is_slave=True)
    slave = SlaveDescription("s1")
    for _ in range(5):
        updates = []
        slave_wf.do_job(wf.generate_data_for_slave(slave), updates.append)
        wf.apply_data_from_slave(updates[0], slave)
    # one job handed out but never answered: in-flight at capture time
    wf.generate_data_for_slave(slave)
    wf.decision.best_n_err_pt = 12.5
    wf.decision.best_epoch = 1
    train, meta = wf.capture_train_state()
    assert any("weights" in v for v in train.values())
    ckpt = TrainCheckpointer(str(tmp_path / "ck"))
    ckpt.save(5, train, meta)

    fresh = make_wf(is_master=True)
    assert not numpy.array_equal(master_weights(fresh),
                                 master_weights(wf))
    abstract, _ = fresh.capture_train_state()
    step, train2, meta2 = ckpt.restore(abstract)
    ckpt.close()
    assert step == 5
    fresh.restore_train_state(train2, meta2)
    numpy.testing.assert_array_equal(master_weights(fresh),
                                     master_weights(wf))
    assert fresh.loader.epoch_number == wf.loader.epoch_number
    assert fresh.loader.global_offset == wf.loader.global_offset
    assert fresh.decision.best_n_err_pt == 12.5
    assert fresh.decision.best_epoch == 1
    # the drop-requeued minibatch came back as retriable work
    assert fresh.loader.failed_minibatches, \
        "pending/failed minibatches must survive the checkpoint"
    numpy.testing.assert_array_equal(
        numpy.array(fresh.loader.shuffled_indices.mem),
        numpy.array(wf.loader.shuffled_indices.mem))


def test_chaos_parity_gate(live_trace, tmp_path):
    """THE acceptance gate: a seeded schedule with a slave kill, a
    duplicated update frame and a dropped job frame, plus one master
    kill-and-resume mid-run.  The session must COMPLETE, with final
    eval metrics matching the fault-free run (the dedup'd duplicates
    are exact no-ops by test_duplicated_update_frames_exact_parity;
    the kill/requeue faults reorder minibatch application, so the
    metric gate here is convergence parity on the same seeded task),
    and the resume must restart within one checkpoint interval of the
    kill.  Chaos instants, checkpoint spans and the resume marker all
    land in the trace ring → merged Perfetto timeline."""
    from veles_tpu import prof, trace

    # ---- fault-free reference run
    ref_master = make_wf(is_master=True)
    ref_slave = make_wf(is_slave=True)
    server = JobServer(ref_master).start()
    try:
        client = JobClient(ref_slave, server.endpoint,
                           rpc_timeout_ms=2000)
        client.handshake()
        assert client.run() is True
        client.close()
    finally:
        server.stop()
    reference = final_metrics(ref_master)
    assert reference["complete"]

    # ---- chaos run
    chaos.controller.arm([
        {"site": "slave_job", "action": "slave_kill", "nth": 2},
        {"site": "master_send", "action": "drop", "op": "job",
         "nth": 5},
        {"site": "slave_send", "action": "dup", "op": "update",
         "nth": 4},
    ], seed=7)
    m1 = make_wf(is_master=True)
    ckdir = str(tmp_path / "ck")
    server1 = JobServer(m1, checkpoint_dir=ckdir, checkpoint_every=3,
                        slave_timeout=5.0,
                        heartbeat_interval=0.3).start()
    port = server1.port
    # slave A: scheduled to die holding its 2nd job
    sA = make_wf(is_slave=True)
    cA = JobClient(sA, server1.endpoint, rpc_timeout_ms=1200,
                   reconnect_max_wait=10.0)
    cA.handshake()
    assert cA.run() is False, "the scheduled slave kill must fire"
    cA.close()
    # slave B: survives the master kill via reconnect backoff
    sB = make_wf(is_slave=True)
    cB = JobClient(sB, server1.endpoint, rpc_timeout_ms=1200,
                   reconnect_max_wait=25.0)
    cB.handshake()
    done = []
    runner = threading.Thread(target=lambda: done.append(cB.run()))
    runner.start()
    # wait for one completed checkpoint, then kill the master mid-run
    deadline = time.time() + 60
    while time.time() < deadline:
        if server1._ckpt is not None and not server1._ckpt_busy.is_set() \
                and server1._checkpointer().latest_step() is not None:
            break
        time.sleep(0.05)
    ckpt_step = server1._checkpointer().latest_step()
    assert ckpt_step is not None, "no checkpoint completed before kill"
    killed_at = server1._updates_applied
    server1.kill()

    # "restarted process": a fresh master workflow resumes the latest
    # checkpoint on the same endpoint
    m2 = make_wf(is_master=True)
    server2 = JobServer(m2, port=port, checkpoint_dir=ckdir,
                        checkpoint_every=3, slave_timeout=5.0,
                        heartbeat_interval=0.3)
    resumed_step = server2.resume_from_checkpoint()
    assert server2.generation == 2
    # resume restarts within one checkpoint interval of the kill —
    # plus one more interval for a trigger skipped while the previous
    # async write was still in flight, plus the updates that landed
    # between reading killed_at and the socket actually closing
    assert killed_at - resumed_step <= 2 * 3 + 1, \
        (killed_at, resumed_step)
    server2.start()
    try:
        runner.join(120)
        assert not runner.is_alive(), "chaos session hung"
        assert done == [True], "surviving slave must finish the run"
        cB.close()
    finally:
        server2.stop()
        chaos.controller.disarm()

    result = final_metrics(m2)
    assert result["complete"], "the resumed session must run to the " \
        "same stop criterion"
    assert result["epochs"] >= reference["epochs"]
    # convergence parity on the seeded 5-cluster task
    assert abs(result["best_n_err_pt"] - reference["best_n_err_pt"]) \
        <= 2.0, (result, reference)
    # every scheduled fault actually fired…
    injected = chaos.controller.snapshot()["injected"]
    assert injected.get("slave_kill") == 1
    assert injected.get("dup", 0) >= 1
    assert injected.get("drop", 0) >= 1
    # …and the exactly-once machinery saw the duplicate
    assert server1.dedup_dropped + server2.dedup_dropped >= 1

    # ---- observability: chaos + recovery events in the merged timeline
    assert trace.recorder.count("jobs", "checkpoint") >= 1
    assert trace.recorder.count("jobs", "resume") == 1
    assert trace.recorder.count("chaos") >= 3
    bundle_path = str(tmp_path / "chaos_session.json")
    server2.save_session_profile(bundle_path, roles=("master",))
    bundle = prof.merge.load(bundle_path)
    merged = prof.merge.merged_events(bundle)
    cats = {ev.get("cat") for ev in merged}
    names = {(ev.get("cat"), ev.get("name")) for ev in merged}
    assert "chaos" in cats, sorted(cats)
    assert ("jobs", "resume") in names
    assert ("jobs", "checkpoint") in names
