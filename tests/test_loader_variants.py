"""Loader-variant coverage: image pipeline, format loaders, streaming,
minibatch capture/replay, InputJoiner/Avatar/MeanDispNormalizer units,
Downloader (mirrors reference tests: test_image_loader, test_hdf5,
test_pickles, test_zmq_loader, test_input_joiner,
test_mean_disp_normalizer)."""

import gzip
import json
import os
import pickle
import tarfile
import urllib.request

import numpy
import pytest

from veles_tpu.avatar import Avatar
from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.downloader import Downloader
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.input_joiner import InputJoiner
from veles_tpu.loader import (
    AutoLabelFileImageLoader, FileFilter, FullBatchImageLoader,
    HDF5Loader, InteractiveLoader, MinibatchesLoader, MinibatchesSaver,
    PicklesLoader, RestfulLoader, TEST, TRAIN, VALID, ZeroMQLoader)
from veles_tpu.mean_disp_normalizer import MeanDispNormalizer
from veles_tpu.memory import Vector


# -- fixtures ---------------------------------------------------------------
def _write_images(tmp_path, per_class=3, classes=("cat", "dog"),
                  size=(12, 10)):
    from PIL import Image
    rng = numpy.random.default_rng(3)
    for cls in classes:
        d = tmp_path / "train" / cls
        d.mkdir(parents=True, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size[1], size[0], 3),
                               dtype=numpy.uint8)
            Image.fromarray(arr).save(d / ("img%02d.png" % i))
    return str(tmp_path / "train")


def test_file_filter(tmp_path):
    (tmp_path / "a.png").write_bytes(b"")
    (tmp_path / "b.txt").write_bytes(b"")
    (tmp_path / "skip.png").write_bytes(b"")
    ff = FileFilter(ignored_files=(r"skip.*",))
    found = [os.path.basename(p) for p in ff.scan(str(tmp_path))]
    assert found == ["a.png"]


def test_auto_label_image_loader(tmp_path):
    train_dir = _write_images(tmp_path)
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[train_dir], size=(8, 8), minibatch_size=4)
    loader.initialize(device=wf.device)
    assert loader.class_lengths[TRAIN] == 6
    assert sorted(loader.labels_mapping) == ["cat", "dog"]
    loader.run()
    assert loader.minibatch_data.shape == (4, 8, 8, 3)
    assert set(loader.minibatch_labels.mem[:loader.minibatch_size]) \
        <= {0, 1}


def test_image_loader_crop_mirror(tmp_path):
    train_dir = _write_images(tmp_path, size=(16, 16))
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[train_dir], size=(16, 16), crop=(8, 6),
        mirror=True, color_space="GRAY", minibatch_size=3)
    loader.initialize(device=wf.device)
    loader.run()
    assert loader.minibatch_data.shape == (3, 6, 8, 1)


def test_fullbatch_image_loader(tmp_path):
    train_dir = _write_images(tmp_path)
    wf = DummyWorkflow()
    wf.device = CPUDevice()
    loader = FullBatchImageLoader(
        wf, train_paths=[train_dir], size=(8, 8), minibatch_size=4,
        image_loader_class=AutoLabelFileImageLoader)
    loader.initialize(device=wf.device)
    assert loader.class_lengths[TRAIN] == 6
    assert loader.original_data.shape == (6, 8, 8, 3)
    loader.run()
    assert loader.minibatch_size == 4


def test_hdf5_loader(tmp_path):
    h5py = pytest.importorskip("h5py")
    rng = numpy.random.default_rng(5)
    paths = {}
    for name, n in (("train", 20), ("valid", 8)):
        p = str(tmp_path / (name + ".h5"))
        with h5py.File(p, "w") as f:
            f["data"] = rng.standard_normal((n, 6)).astype("f4")
            f["labels"] = rng.integers(0, 3, n)
        paths[name] = p
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = HDF5Loader(wf, train_path=paths["train"],
                        validation_path=paths["valid"],
                        minibatch_size=5)
    loader.initialize(device=wf.device)
    assert loader.class_lengths == [0, 8, 20]
    loader.run()
    assert loader.minibatch_class == VALID


def test_pickles_loader(tmp_path):
    rng = numpy.random.default_rng(6)
    p = str(tmp_path / "train.pickle")
    with open(p, "wb") as f:
        pickle.dump((rng.standard_normal((15, 4)).astype("f4"),
                     list(rng.integers(0, 2, 15))), f)
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = PicklesLoader(wf, train_path=p, minibatch_size=6)
    loader.initialize(device=wf.device)
    assert loader.class_lengths == [0, 0, 15]
    loader.run()
    assert loader.minibatch_size == 6


def test_minibatch_save_replay(tmp_path):
    from tests.test_loader import SyntheticLoader
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    src = SyntheticLoader(wf, minibatch_size=10)
    src.initialize(device=wf.device)
    dump = str(tmp_path / "mb.gz")
    saver = MinibatchesSaver(wf, file_name=dump)
    saver.minibatch_data = src.minibatch_data
    saver.minibatch_labels = src.minibatch_labels
    saver.initialize()
    for _ in range(10):   # one full epoch (100 samples / 10)
        src.run()
        saver.minibatch_class = src.minibatch_class
        saver.minibatch_size = src.minibatch_size
        saver.run()
    saver.stop()

    replay = MinibatchesLoader(wf, file_name=dump)
    replay.initialize(device=wf.device)
    assert replay.class_lengths == [20, 30, 50]
    replay.run()
    assert replay.minibatch_class == TEST
    assert replay.minibatch_size == 10


def test_interactive_loader():
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = InteractiveLoader(wf, sample_shape=(4,), minibatch_size=8)
    loader.initialize(device=wf.device)
    loader.feed(numpy.ones((3, 4)), labels=[0, 1, 0])
    loader.run()
    assert loader.minibatch_size == 3
    assert loader.minibatch_class == TRAIN
    assert list(loader.minibatch_labels.mem[:3]) == [0, 1, 0]
    loader.end_epoch()
    loader.run()
    assert bool(loader.epoch_ended)
    assert loader.epoch_number == 1


def test_zmq_loader():
    zmq = pytest.importorskip("zmq")
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = ZeroMQLoader(wf, sample_shape=(2,), minibatch_size=4)
    loader.initialize(device=wf.device)
    sock = zmq.Context.instance().socket(zmq.PUSH)
    sock.connect("tcp://127.0.0.1:%d" % loader.port)
    sock.send(pickle.dumps(
        (numpy.full((2, 2), 3.0, numpy.float32), [1, 0])))
    loader.run()
    assert loader.minibatch_size == 2
    assert loader.minibatch_data.mem[0, 0] == 3.0
    sock.close(0)
    loader.stop()


def test_restful_loader():
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = RestfulLoader(wf, sample_shape=(3,), minibatch_size=4)
    loader.initialize(device=wf.device)
    body = json.dumps({"input": [[1, 2, 3]], "labels": [2]}).encode()
    req = urllib.request.Request(
        "http://127.0.0.1:%d/feed" % loader.port, data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        assert json.load(resp)["ok"]
    loader.run()
    assert loader.minibatch_size == 1
    assert list(loader.minibatch_data.mem[0]) == [1.0, 2.0, 3.0]
    loader.stop()


# -- units ------------------------------------------------------------------
@pytest.mark.parametrize("device_class", [NumpyDevice, CPUDevice])
def test_input_joiner(device_class):
    wf = DummyWorkflow()
    wf.device = device_class()
    a = Vector(numpy.arange(6, dtype=numpy.float32).reshape(3, 2))
    b = Vector(numpy.arange(12, dtype=numpy.float32).reshape(3, 2, 2))
    joiner = InputJoiner(wf, inputs=[a, b])
    joiner.initialize(device=wf.device)
    joiner.run()
    joiner.output.map_read()
    assert joiner.output.shape == (3, 6)
    numpy.testing.assert_allclose(joiner.output.mem[1],
                                  [2, 3, 4, 5, 6, 7])


def test_avatar():
    from tests.test_loader import SyntheticLoader
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = SyntheticLoader(wf, minibatch_size=10)
    loader.initialize(device=wf.device)
    avatar = Avatar(wf, source=loader)
    avatar.initialize()
    loader.run()
    avatar.run()
    numpy.testing.assert_array_equal(
        avatar.minibatch_data.mem, loader.minibatch_data.mem)
    assert avatar.minibatch_class == loader.minibatch_class
    # decoupling: producer advances, avatar keeps its copy
    kept = numpy.array(avatar.minibatch_data.mem)
    loader.run()
    numpy.testing.assert_array_equal(avatar.minibatch_data.mem, kept)


@pytest.mark.parametrize("device_class", [NumpyDevice, CPUDevice])
def test_mean_disp_normalizer(device_class):
    wf = DummyWorkflow()
    wf.device = device_class()
    rng = numpy.random.default_rng(9)
    x = rng.standard_normal((5, 7)).astype(numpy.float32)
    unit = MeanDispNormalizer(wf)
    unit.input = Vector(x.copy())
    unit.mean.mem = x.mean(axis=0)
    unit.rdisp.mem = (1.0 / (x.max(axis=0) - x.min(axis=0))).astype(
        numpy.float32)
    unit.input.initialize(wf.device)
    unit.initialize(device=wf.device)
    unit.run()
    unit.output.map_read()
    expected = (x - x.mean(axis=0)) * unit.rdisp.mem
    numpy.testing.assert_allclose(unit.output.mem, expected, rtol=1e-5)


def test_downloader(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "data.txt").write_text("hello")
    archive = tmp_path / "dataset.tar.gz"
    with tarfile.open(archive, "w:gz") as tar:
        tar.add(src / "data.txt", arcname="data.txt")
    dest = tmp_path / "dest"
    wf = DummyWorkflow()
    unit = Downloader(wf, url="file://" + str(archive),
                      directory=str(dest), files=["data.txt"])
    unit.initialize()
    assert (dest / "data.txt").read_text() == "hello"
    assert unit.already_there


def test_wav_loader(tmp_path):
    """Stdlib-wave audio ingestion (libsndfile role, SURVEY §2.3)."""
    import wave
    import numpy
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader.formats import WavLoader

    paths = []
    for label in ("yes", "no"):
        d = tmp_path / label
        d.mkdir()
        for i in range(3):
            path = str(d / ("clip%d.wav" % i))
            with wave.open(path, "wb") as w:
                w.setnchannels(1)
                w.setsampwidth(2)
                w.setframerate(8000)
                tone = (numpy.sin(numpy.arange(2000) * 0.1) *
                        20000).astype("<i2")
                w.writeframes(tone.tobytes())
            paths.append(path)
    wf = DummyWorkflow()
    loader = WavLoader(wf, train_paths=paths, window=1024,
                       minibatch_size=3)
    loader.initialize(NumpyDevice())
    assert loader.original_data.shape == (6, 1024)
    assert sorted(set(loader.original_labels)) == ["no", "yes"]
    assert float(numpy.abs(loader.original_data.mem).max()) <= 1.0


def test_lmdb_loader_gated():
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader.base import LoaderError
    from veles_tpu.loader.formats import LMDBLoader
    loader = LMDBLoader(DummyWorkflow(), train_db="/nonexistent",
                        minibatch_size=4)
    with pytest.raises(LoaderError, match="lmdb"):
        loader.load_data()


def test_hdfs_loader_parses_lines():
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader.formats import HDFSTextLoader
    loader = HDFSTextLoader(DummyWorkflow(),
                            namenode="http://example:9870",
                            minibatch_size=4)
    rows, labels = loader._parse_lines("a\t1,2,3\nb\t4,5,6\n")
    assert labels == ["a", "b"]
    assert rows[1].tolist() == [4.0, 5.0, 6.0]


class TestNativeDeviceDtype:
    """FullBatchLoader(native_device_dtype=True): the dataset stays in
    its storage dtype on device; the fitted normalizer becomes the
    fused step's input_norm (the TPU-first upgrade of the reference's
    device-resident fullbatch data, ``loader/fullbatch.py:79``)."""

    def test_affine_forms(self):
        import numpy

        from veles_tpu.normalization import normalizer_factory

        n = normalizer_factory("scale", scale=1.0 / 255.0)
        s, b = n.as_affine()
        assert (s, b) == (1.0 / 255.0, 0.0)

        n = normalizer_factory("range_linear", interval=(0, 1))
        data = numpy.array([[0.0, 255.0]], numpy.float32)
        n.analyze(data)
        s, b = n.as_affine()
        x = numpy.array([[51.0, 204.0]], numpy.float32)
        want = x.copy()
        n.normalize(want)
        numpy.testing.assert_allclose(x * s + b, want, rtol=1e-6)

        n = normalizer_factory("mean_disp")
        data = numpy.arange(12, dtype=numpy.float32).reshape(3, 4)
        n.analyze(data)
        s, b = n.as_affine()
        x = data.copy()
        n.normalize(x)
        numpy.testing.assert_allclose(data * s + b, x, atol=1e-6)

        # per-sample linear is NOT sample-independent affine
        assert normalizer_factory("linear").as_affine() is None

    def test_native_requires_affine_normalizer(self):
        import pytest

        from veles_tpu.loader.base import LoaderError
        from veles_tpu.samples import mnist

        with pytest.raises((LoaderError, ValueError)):
            mnist.create_workflow(
                max_epochs=1, minibatch_size=64, native=True,
                fused=True, normalization_type="exp")

    def test_native_requires_fused_when_stitch_off(self):
        # fused=False + native is legal ONLY because the stitched
        # gather+normalize head hands the forwards float32; with the
        # stitched path disabled the old guard must still fire
        import pytest

        from veles_tpu.config import root
        from veles_tpu.samples import mnist

        prior = root.common.engine.get("stitch", None)
        root.common.engine.stitch = "off"
        try:
            with pytest.raises(ValueError, match="fused"):
                mnist.create_workflow(max_epochs=1, minibatch_size=64,
                                      native=True)
        finally:
            if prior is None:
                root.common.engine.stitch = "on"
            else:
                root.common.engine.stitch = prior

    def test_native_stitched_eager_trains_normalized(self):
        # the gather+normalize head: fused=False + native rides the
        # stitched device fast path — the first forward program sees
        # normalized float32 while the resident dataset stays uint8
        import numpy

        from veles_tpu import prng
        from veles_tpu.samples import mnist

        prng.seed_all(4321)
        wf = mnist.create_workflow(max_epochs=1, minibatch_size=512,
                                   native=True)
        loader = wf.loader
        assert loader.original_data.mem.dtype == numpy.uint8
        assert loader.input_norm is not None
        assert loader.device_fast_path_active
        assert loader.stitch_stage() is not None
        wf.run()
        # the stitched head published normalized float32 minibatches
        mb = numpy.asarray(loader.minibatch_data.devmem)
        assert mb.dtype == numpy.float32
        assert float(numpy.abs(mb).max()) <= 1.5
        assert wf.decision.epoch_n_err[1] < loader.class_lengths[1]

    def test_native_u8_trains_like_f32(self):
        import numpy

        from veles_tpu import prng
        from veles_tpu.samples import mnist

        results = {}
        for native in (False, True):
            prng.seed_all(4321)
            wf = mnist.create_workflow(max_epochs=2,
                                       minibatch_size=512,
                                       native=native, fused=True)
            if native:
                assert wf.loader.minibatch_data.mem.dtype == numpy.uint8
                assert wf.loader.input_norm is not None
            wf.run()
            results[native] = wf.decision.epoch_n_err[1]
        # same seed, same (synthetic) images up to u8 rounding: the two
        # storage paths must land in the same accuracy neighborhood
        assert results[True] <= results[False] * 1.25 + 10


def test_image_loader_rotations_inflate_and_blend(tmp_path):
    """rotations=(0, π/2): every key yields one sample per rotation
    (ref image.py:311 samples_inflation); a 90° rotation of a solid
    image stays solid, and rotation by π/4 exposes corners that must
    blend the configured background color (ref image.py:316-368)."""
    import math
    from PIL import Image
    from veles_tpu.loader.image import AutoLabelFileImageLoader

    d = tmp_path / "train" / "solid"
    d.mkdir(parents=True)
    solid = numpy.full((12, 12, 3), 200, numpy.uint8)
    Image.fromarray(solid).save(d / "img.png")
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(12, 12),
        rotations=(0.0, math.pi / 2), minibatch_size=2)
    loader.initialize(device=wf.device)
    assert loader.samples_inflation == 2
    assert loader.class_lengths[TRAIN] == 2        # 1 key x 2 rotations
    loader.run()
    got = loader.minibatch_data.mem[:2]
    # solid image: 0° and 90° are both the solid value everywhere
    assert numpy.allclose(got, 200.0, atol=1.0)

    # π/4 exposes corners -> background color blended in
    loader2 = AutoLabelFileImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(12, 12),
        rotations=(math.pi / 4,), background_color=(0, 0, 255),
        minibatch_size=1)
    loader2.initialize(device=wf.device)
    loader2.run()
    img = loader2.minibatch_data.mem[0]
    assert img[0, 0, 2] > 200.0        # corner is (mostly) background
    assert img[0, 0, 0] < 60.0
    assert abs(img[6, 6, 0] - 200.0) < 2.0   # center untouched


def test_image_loader_background_image_shape_validated(tmp_path):
    import math
    from PIL import Image
    from veles_tpu.loader.image import AutoLabelFileImageLoader
    from veles_tpu.loader.base import LoaderError

    d = tmp_path / "train" / "c"
    d.mkdir(parents=True)
    Image.fromarray(numpy.zeros((8, 8, 3), numpy.uint8)).save(
        d / "img.png")
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(8, 8),
        rotations=(math.pi / 4,),
        background_image=numpy.zeros((4, 4, 3), numpy.float32),
        minibatch_size=1)
    with pytest.raises(LoaderError):
        # the first minibatch fill happens inside initialize()
        loader.initialize(device=wf.device)
        loader.run()


def test_image_loader_mse_targets(tmp_path):
    """ImageLoaderMSE (ref image_mse.py:46): minibatch_targets carries
    the clean target image aligned with each input sample."""
    from PIL import Image
    from veles_tpu.loader.image import ImageLoaderMSE

    d = tmp_path / "imgs"
    d.mkdir()
    rng = numpy.random.default_rng(7)
    arrays = {}
    for i in range(3):
        arr = rng.integers(0, 255, (10, 10, 3), numpy.uint8)
        name = str(d / ("t%d.png" % i))
        Image.fromarray(arr).save(name)
        arrays[name] = arr

    class NoisyLoader(ImageLoaderMSE):
        hide_from_registry = True

        def get_keys(self, class_index):
            return list(arrays) if class_index == TRAIN else []

        def load_key(self, key):          # corrupted input
            return numpy.zeros((10, 10, 3), numpy.uint8)

    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = NoisyLoader(wf, size=(10, 10), minibatch_size=3)
    loader.initialize(device=wf.device)
    loader.run()
    n = loader.minibatch_size
    assert numpy.allclose(loader.minibatch_data.mem[:n], 0.0)
    # targets are the CLEAN decodes of the same keys
    loader.minibatch_indices.map_read()
    for i, idx in enumerate(loader.minibatch_indices.mem[:n]):
        key_idx, _rot = loader._key_and_rotation(idx)
        clean = arrays[loader._flat_keys[key_idx]]
        assert numpy.allclose(loader.minibatch_targets.mem[i],
                              clean.astype(numpy.float32))


def test_fullbatch_image_loader_inflation_fills_all_rows(tmp_path):
    """FullBatchImageLoader must decode one resident row per INFLATED
    sample (key x rotation) with labels aligned — a fill keyed on the
    keys alone left the rotated rows zero (code-review r5)."""
    import math
    from PIL import Image
    from veles_tpu.loader.image import (AutoLabelFileImageLoader,
                                        FullBatchImageLoader)

    d = tmp_path / "train" / "solid"
    d.mkdir(parents=True)
    Image.fromarray(numpy.full((8, 8, 3), 150, numpy.uint8)).save(
        d / "img.png")
    wf = DummyWorkflow()
    wf.device = CPUDevice()
    loader = FullBatchImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(8, 8),
        rotations=(0.0, math.pi / 2), minibatch_size=2,
        image_loader_class=AutoLabelFileImageLoader)
    loader.initialize(device=wf.device)
    assert loader.class_lengths[TRAIN] == 2
    data = numpy.asarray(loader.original_data.mem)
    assert data.shape[0] == 2
    # BOTH rows carry the decoded (solid) image — 90° of a solid
    # square is the same solid square, never zeros
    assert numpy.allclose(data[0], 150.0, atol=1.0)
    assert numpy.allclose(data[1], 150.0, atol=1.0)
    assert len(loader.original_labels) == 2


def test_image_loader_mse_aligned_under_mirror(tmp_path):
    """Input and target must replay the SAME random mirror/crop draws
    (code-review r5): with mirror=True every train pair still
    satisfies target == clean-transform(input) when load_key ==
    load_target."""
    from PIL import Image
    from veles_tpu.loader.image import ImageLoaderMSE

    d = tmp_path / "imgs"
    d.mkdir()
    rng = numpy.random.default_rng(11)
    names = []
    for i in range(4):
        arr = rng.integers(0, 255, (8, 8, 3), numpy.uint8)
        name = str(d / ("m%d.png" % i))
        Image.fromarray(arr).save(name)
        names.append(name)

    class PassthroughMSE(ImageLoaderMSE):
        hide_from_registry = True

        def get_keys(self, class_index):
            return names if class_index == TRAIN else []

    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = PassthroughMSE(wf, size=(8, 8), mirror=True, crop=(4, 4),
                            minibatch_size=4)
    loader.initialize(device=wf.device)
    # drive until a TRAIN minibatch (random augmentation active)
    for _ in range(8):
        loader.run()
        if loader.minibatch_class == TRAIN and loader.minibatch_size:
            break
    n = loader.minibatch_size
    # identical load_key/load_target + shared decisions => identical
    # tensors, flip or not
    assert numpy.allclose(loader.minibatch_data.mem[:n],
                          loader.minibatch_targets.mem[:n])


def test_rotation_preserves_float_images(tmp_path):
    """load_key may return float images (class contract): rotation
    must not round-trip them through uint8 (code-review r5: a [0,1]
    image came back all zeros)."""
    import math
    from veles_tpu.loader.image import ImageLoader

    class FloatLoader(ImageLoader):
        hide_from_registry = True

        def get_keys(self, class_index):
            return ["a"] if class_index == TRAIN else []

        def load_key(self, key):
            return numpy.full((8, 8, 3), 0.5, numpy.float32)

    wf = DummyWorkflow()
    loader = FloatLoader(wf, size=(8, 8), minibatch_size=1)
    out = loader.preprocess(loader.load_key("a"), train=False,
                            rotation=math.pi / 2)
    assert abs(float(out.mean()) - 0.5) < 1e-3


def test_image_loader_add_sobel_channel(tmp_path):
    """add_sobel appends a per-pixel Sobel gradient-magnitude channel
    (ref image.py:484 intent): a vertical step edge yields zero
    response in flat regions and a strong response at the edge."""
    from PIL import Image
    from veles_tpu.loader.image import AutoLabelFileImageLoader

    d = tmp_path / "train" / "edge"
    d.mkdir(parents=True)
    arr = numpy.zeros((8, 8, 3), numpy.uint8)
    arr[:, 4:] = 200          # vertical step edge at x=4
    Image.fromarray(arr).save(d / "img.png")
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(8, 8),
        add_sobel=True, minibatch_size=1)
    loader.initialize(device=wf.device)
    assert loader.sample_shape == (8, 8, 4)
    loader.run()
    img = loader.minibatch_data.mem[0]
    sob = img[:, :, 3]
    assert float(sob[:, 0:2].max()) == 0.0      # flat left region
    assert float(sob[:, 6:8].max()) == 0.0      # flat right region
    assert float(sob[:, 3:5].min()) > 100.0     # edge response
    # original channels untouched
    assert numpy.allclose(img[:, :, :3], arr.astype(numpy.float32))


def test_image_loader_crop_number_inflation(tmp_path):
    """crop_number (ref image.py ctor): further inflation — each
    (key, rotation) yields crop_number random-crop samples."""
    import math
    from PIL import Image
    from veles_tpu.loader.image import AutoLabelFileImageLoader
    from veles_tpu.loader.base import LoaderError

    d = tmp_path / "train" / "c"
    d.mkdir(parents=True)
    rng = numpy.random.default_rng(5)
    Image.fromarray(rng.integers(0, 255, (16, 16, 3),
                                 numpy.uint8)).save(d / "img.png")
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(16, 16),
        crop=(8, 8), crop_number=3, rotations=(0.0, math.pi / 2),
        minibatch_size=6)
    loader.initialize(device=wf.device)
    assert loader.samples_inflation == 6          # 2 rot x 3 crops
    assert loader.class_lengths[TRAIN] == 6       # 1 key x 6
    loader.run()
    assert loader.minibatch_data.shape == (6, 8, 8, 3)
    # crop_number without crop is rejected
    with pytest.raises(LoaderError):
        AutoLabelFileImageLoader(
            wf, train_paths=[str(tmp_path / "train")], size=(16, 16),
            crop_number=2, minibatch_size=2)


def test_fullbatch_crop_number_rows_are_distinct(tmp_path):
    """crop_number in the FULL-BATCH path must decode DISTINCT
    (anchored) crops per inflated sample, never crop_number copies of
    the center crop (code-review r5)."""
    from PIL import Image
    from veles_tpu.loader.image import (AutoLabelFileImageLoader,
                                        FullBatchImageLoader)

    d = tmp_path / "train" / "c"
    d.mkdir(parents=True)
    rng = numpy.random.default_rng(9)
    Image.fromarray(rng.integers(0, 255, (16, 16, 3),
                                 numpy.uint8)).save(d / "img.png")
    wf = DummyWorkflow()
    wf.device = CPUDevice()
    loader = FullBatchImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(16, 16),
        crop=(8, 8), crop_number=5, minibatch_size=5,
        image_loader_class=AutoLabelFileImageLoader)
    loader.initialize(device=wf.device)
    data = numpy.asarray(loader.original_data.mem)
    assert data.shape == (5, 8, 8, 3)
    # center + 4 corners of a random image: all five pairwise distinct
    for i in range(5):
        for j in range(i + 1, 5):
            assert not numpy.array_equal(data[i], data[j]), (i, j)


def test_image_loader_all_options_compose(tmp_path):
    """rotations x crop_number x mirror x add_sobel x background color
    compose: shapes, inflation, and decode stay consistent and every
    minibatch fill succeeds across a full epoch."""
    import math
    from PIL import Image
    from veles_tpu.loader.image import AutoLabelFileImageLoader

    rng = numpy.random.default_rng(13)
    for cls in ("a", "b"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(2):
            Image.fromarray(rng.integers(0, 255, (20, 20, 3),
                                         numpy.uint8)).save(
                d / ("x%d.png" % i))
    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = AutoLabelFileImageLoader(
        wf, train_paths=[str(tmp_path / "train")], size=(20, 20),
        crop=(12, 12), crop_number=2, rotations=(0.0, math.pi / 4),
        mirror=True, add_sobel=True, background_color=(8, 16, 32),
        minibatch_size=8)
    loader.initialize(device=wf.device)
    assert loader.samples_inflation == 4        # 2 rot x 2 crops
    assert loader.class_lengths[TRAIN] == 16    # 4 keys x 4
    assert loader.sample_shape == (12, 12, 4)   # crop + sobel channel
    seen = 0
    for _ in range(40):
        loader.run()
        n = int(loader.minibatch_size)
        assert loader.minibatch_data.mem[:n].shape[1:] == (12, 12, 4)
        assert numpy.isfinite(loader.minibatch_data.mem[:n]).all()
        seen += n
        if bool(loader.epoch_ended):
            break
    assert seen >= 16                           # full epoch served
