"""veles_tpu.trace — the unified tracing/observability subsystem.

Recorder mechanics (ring wraparound keeps the newest spans, per-thread
nesting, the disabled path's no-work contract), Chrome trace-event
export schema, report totals matching the exported file, the
summarizer CLI, the ``engine.trace`` knob — and the CI canary: a
``traced``-marked stitched sample run asserting that ALL FIVE
instrumented categories (segment, loader, h2d, serve, jobs) actually
emit events, so a refactor can never silently detach the
instrumentation."""

import json
import sys
import threading
import time

import numpy
import pytest

from veles_tpu import trace
from veles_tpu.config import root
from veles_tpu.trace.core import TraceRecorder


@pytest.fixture
def live_trace():
    """Enable the GLOBAL recorder directly (workflow-free tests that
    must not depend on the config knob); restores the stock disabled
    state."""
    rec = trace.recorder
    saved = (rec.enabled, rec.path, rec.role)
    rec.clear()
    rec.enabled = True
    yield trace
    rec.enabled, rec.path, rec.role = saved
    rec.clear()


# -- recorder mechanics ----------------------------------------------------

def test_ring_wraparound_keeps_newest_spans():
    rec = TraceRecorder(capacity=8)
    rec.enabled = True
    for i in range(20):
        rec.record("X", "cat", "s%d" % i, i * 1000, 10)
    events = rec.events()
    assert len(events) == 8
    assert [ev[2] for ev in events] == ["s%d" % i for i in range(12, 20)]
    assert rec.dropped == 12
    assert rec.recorded == 20
    # the aggregate counters survive wraparound (bench reads these)
    assert rec.count("cat") == 20
    assert rec.count("cat", "s3") == 1          # wrapped out, still counted
    assert rec.category_counts() == {"cat": 20}


def test_thread_interleaved_spans_nest_per_thread(live_trace):
    barrier = threading.Barrier(2)

    def work(name):
        barrier.wait()
        with trace.span("test", "outer-" + name):
            time.sleep(0.002)
            with trace.span("test", "inner-" + name):
                time.sleep(0.002)
            time.sleep(0.002)

    threads = [threading.Thread(target=work, args=(n,))
               for n in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    by_tid = {}
    for ph, cat, name, ts, dur, tid, _args, _role in \
            trace.recorder.events():
        if cat == "test":
            by_tid.setdefault(tid, {})[name.split("-")[0]] = (ts,
                                                             ts + dur)
    assert len(by_tid) == 2
    names = set()
    for spans in by_tid.values():
        assert set(spans) == {"outer", "inner"}
        # context-manager spans nest strictly per thread: the inner
        # interval lies inside the SAME thread's outer interval even
        # though both threads interleave in the shared ring
        assert spans["outer"][0] < spans["inner"][0]
        assert spans["inner"][1] < spans["outer"][1]
        names.update(spans)
    assert names == {"outer", "inner"}


def test_disabled_path_is_one_check_no_allocation_no_recording():
    rec = trace.recorder
    assert not rec.enabled, "tests must start with tracing off"
    before = rec.recorded
    # no allocation: EVERY disabled span() returns the one shared
    # no-op singleton, whatever the arguments
    assert trace.span("a", "b") is trace.span("c", "d", {"k": 1})
    assert trace.span("a", "b") is trace.NULL_SPAN
    # callable-count: the disabled span costs exactly three python
    # calls (span(), NULL_SPAN.__enter__, NULL_SPAN.__exit__) — no
    # timestamping, no locking, no ring access
    calls = []

    def prof(frame, event, arg):
        if event == "call":
            calls.append(frame.f_code.co_name)

    sys.setprofile(prof)
    try:
        with trace.span("cat", "name"):
            pass
        trace.instant("cat", "name")
        trace.counter("cat", "name", 1)
        trace.complete("cat", "name", 0, 1)
    finally:
        sys.setprofile(None)
    assert calls.count("span") == 1
    assert len([c for c in calls
                if c in ("span", "__enter__", "__exit__", "instant",
                         "counter", "complete")]) == 6
    assert len(calls) <= 8, calls     # nothing else ran underneath
    assert rec.recorded == before     # and nothing was recorded


# -- export / report -------------------------------------------------------

def _record_sample_timeline():
    with trace.span("segment", "dispatch", {"segment": "fwd+gd"}):
        time.sleep(0.001)
    with trace.span("segment", "dispatch", {"segment": "fwd+gd"}):
        time.sleep(0.001)
    with trace.span("loader", "serve_minibatch"):
        pass
    trace.instant("jobs", "heartbeat", {"gap_ms": 2.0}, role="master")
    trace.counter("h2d", "h2d_bytes", 4096)
    trace.complete("serve", "request", time.perf_counter_ns() - 10000,
                   10000, {"rows": 3}, role="server")


def test_chrome_export_is_schema_valid_trace_event_json(live_trace,
                                                       tmp_path):
    _record_sample_timeline()
    path = trace.save(str(tmp_path / "t.json"))
    with open(path) as fin:
        payload = json.load(fin)
    assert payload["displayTimeUnit"] == "ms"
    events = payload["traceEvents"]
    assert isinstance(events, list) and events
    phases = set()
    pids = set()
    for ev in events:
        # the trace-event schema: every record has a phase, a pid and
        # a tid; named events have names; complete events have ts+dur
        assert ev["ph"] in ("M", "X", "i", "C")
        assert isinstance(ev["pid"], int)
        assert "tid" in ev
        phases.add(ev["ph"])
        pids.add(ev["pid"])
        if ev["ph"] == "M":
            assert ev["name"] == "process_name"
            assert ev["args"]["name"]
            continue
        assert ev["name"]
        assert isinstance(ev["ts"], (int, float))
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        if ev["ph"] == "i":
            assert ev["s"] == "t"
        if ev["ph"] == "C":
            assert "value" in ev["args"]
    assert phases == {"M", "X", "i", "C"}
    # one pid per role: trainer + master + server were all recorded
    roles = {ev["args"]["name"] for ev in events if ev["ph"] == "M"}
    assert roles == {"trainer", "master", "server"}
    assert len(pids) == 3


def test_report_totals_match_the_exported_file(live_trace, tmp_path):
    _record_sample_timeline()
    live_summary = trace.summary()
    live_report = trace.report_text()
    path = trace.save(str(tmp_path / "t.json"))
    file_events = trace.load(path)
    assert trace.summary(file_events) == live_summary
    assert trace.report_text(file_events) == live_report
    # and the numbers are the recorded truth
    assert live_summary["categories"]["segment"]["spans"] == 2
    assert live_summary["segment"]["dispatches"] == 2
    assert live_summary["segment"]["host_gap_ms"] >= 0
    assert live_summary["counters"]["h2d_bytes"] == 4096


def test_load_accepts_bare_array_trace_files(live_trace, tmp_path):
    """Chrome traces come in two standard shapes: the object form this
    module writes and a bare JSON array — load() takes both."""
    _record_sample_timeline()
    path = trace.save(str(tmp_path / "obj.json"))
    with open(path) as fin:
        events = json.load(fin)["traceEvents"]
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(events))
    assert trace.load(str(bare)) == trace.load(path)


def test_summarizer_cli(live_trace, tmp_path, capsys):
    import veles_tpu.trace.__main__ as cli
    _record_sample_timeline()
    path = trace.save(str(tmp_path / "t.json"))
    assert cli.main([path]) == 0
    out = capsys.readouterr().out
    assert "per-category totals" in out
    assert "segment" in out and "dispatch" in out
    assert cli.main([path, "--json"]) == 0
    digest = json.loads(capsys.readouterr().out)
    assert digest["categories"]["segment"]["spans"] == 2
    assert cli.main([str(tmp_path / "missing.json")]) == 2


def test_category_busy_is_interval_union_not_a_nested_sum(live_trace):
    """Nested same-category spans (a serve request enclosing its
    batched device call) must count ONCE in the category's busy_ms —
    summing would report >100% utilization."""
    with trace.span("serve", "request"):
        with trace.span("serve", "batch_infer"):
            time.sleep(0.003)
    digest = trace.summary()
    by_name = {item["name"]: item["total_ms"]
               for item in digest["top_spans"]}
    busy = digest["categories"]["serve"]["busy_ms"]
    # union == the outer span alone, NOT outer + inner
    assert busy < by_name["request"] + by_name["batch_infer"]
    assert abs(busy - by_name["request"]) < 0.5


def test_metrics_text_lines(live_trace):
    _record_sample_timeline()
    text = trace.metrics_text()
    assert "veles_trace_recorded_total %d" % trace.recorder.recorded \
        in text
    assert 'veles_trace_events_total{cat="segment"} 2' in text
    # the events_total family is labeled-only (no unlabeled sample
    # that would double sum() under aggregation) and contiguous
    samples = [l for l in text.splitlines()
               if l.startswith("veles_trace_events_total")]
    assert samples and all("{cat=" in l for l in samples)


# -- the knob --------------------------------------------------------------

def test_configure_knob_off_on_path(tmp_path):
    rec = trace.recorder
    saved = (rec.enabled, rec.path, root.common.engine.get("trace"))
    try:
        root.common.engine.trace = "off"
        assert trace.configure() is False and rec.path is None
        root.common.engine.trace = "on"
        assert trace.configure() is True and rec.path is None
        target = str(tmp_path / "run.json")
        root.common.engine.trace = target
        assert trace.configure() is True
        assert rec.path == target
    finally:
        rec.enabled, rec.path = saved[0], saved[1]
        root.common.engine.trace = saved[2]


def test_workflow_initialize_honors_trace_knob():
    from veles_tpu.workflow import Workflow
    rec = trace.recorder
    saved = (rec.enabled, rec.path, root.common.engine.get("trace"))
    try:
        root.common.engine.trace = "on"
        Workflow(None).initialize()
        assert trace.enabled()
        root.common.engine.trace = "off"
        Workflow(None).initialize()
        assert not trace.enabled()
    finally:
        rec.enabled, rec.path = saved[0], saved[1]
        root.common.engine.trace = saved[2]


def test_device_trace_is_noop_on_cpu():
    with trace.device_trace() as running:
        assert not running      # CPU backend: the bridge stays off


# -- the CI canary: five categories over a real stitched run ---------------

def _build_stitched_workflow(minibatch_size=32):
    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class BlobLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(42)
            n = 200
            labels = numpy.tile(numpy.arange(10), n // 10)
            centers = rng.standard_normal((10, 16)) * 3.0
            self.original_data.mem = (
                centers[labels]
                + rng.standard_normal((n, 16)) * 0.7
            ).astype(numpy.float32)
            self.original_labels = [int(x) for x in labels]
            self.class_lengths[:] = [0, 50, 150]

    prng.seed_all(5)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 10},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}}],
        decision_config={"max_epochs": 2, "fail_iterations": 10 ** 6})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice())
    return wf


class _ScriptedMaster(object):
    def __init__(self, n_jobs=3):
        self.n_jobs = n_jobs
        self.served = 0
        self.updates = []

    def checksum(self):
        return "traced-v1"

    def generate_data_for_slave(self, slave):
        if self.served >= self.n_jobs:
            return None
        self.served += 1
        return {"job_number": self.served}

    def apply_data_from_slave(self, data, slave):
        self.updates.append(data)

    def drop_slave(self, slave):
        pass


class _ScriptedSlave(object):
    def checksum(self):
        return "traced-v1"

    def do_job(self, data, callback):
        callback({"result": data["job_number"]})


@pytest.mark.traced
def test_all_five_instrumented_categories_emit(tmp_path):
    """The instrumentation canary (and the acceptance run): one traced
    session covering the stitched trainer, the serving engine and the
    master–slave job layer must emit events in EVERY category —
    segment (stitched dispatches), loader (minibatch serving), h2d
    (transfer counters), serve (request lifecycle) and jobs (job
    lifecycle) — and the exported JSON must be a Perfetto-loadable
    trace-event file whose report matches the live one.  A refactor
    that detaches any hook fails here, not in production."""
    from veles_tpu.parallel.jobs import JobClient, JobServer
    from veles_tpu.serve.batcher import DynamicBatcher
    from veles_tpu.serve.engine import InferenceEngine

    assert trace.enabled(), "the traced marker must arm the recorder"

    # trainer: stitched eager run → segment + loader + h2d
    wf = _build_stitched_workflow()
    assert trace.enabled(), \
        "initialize() re-read the knob and must keep recording on"
    wf.run()
    assert wf.stitch_report()["dispatches"] > 0

    # serving: engine + dynamic batcher → serve
    engine = InferenceEngine.from_forwards(
        wf.forwards, sample_shape=(16,), max_batch_size=8).warmup()
    batcher = DynamicBatcher(engine, max_wait_ms=1.0)
    try:
        out = batcher.infer(numpy.zeros((3, 16), numpy.float32))
        assert out.shape == (3, 10)
    finally:
        batcher.stop()

    # job layer: scripted master–slave session over real ZMQ → jobs
    master = _ScriptedMaster(n_jobs=3)
    server = JobServer(master).start()
    try:
        client = JobClient(_ScriptedSlave(), server.endpoint)
        client.handshake()
        assert client.run()
        client.close()
    finally:
        server.stop()
    assert len(master.updates) == 3

    counts = trace.recorder.category_counts()
    for category in ("segment", "loader", "h2d", "serve", "jobs"):
        assert counts.get(category, 0) > 0, \
            "category %r emitted nothing: %r" % (category, counts)

    # the export is Perfetto-loadable and agrees with the live report
    live_summary = trace.summary()
    path = trace.save(str(tmp_path / "session.json"))
    file_events = trace.load(path)
    assert trace.summary(file_events) == live_summary
    span_cats = {ev["cat"] for ev in file_events if ev["ph"] == "X"}
    assert {"segment", "loader", "serve", "jobs"} <= span_cats
    counter_cats = {ev["cat"] for ev in file_events
                    if ev["ph"] == "C"}
    assert "h2d" in counter_cats
    # per-role pids separated trainer, server, master and the slave
    with open(path) as fin:
        raw = json.load(fin)["traceEvents"]
    roles = {ev["args"]["name"] for ev in raw if ev["ph"] == "M"}
    assert {"trainer", "server", "master"} <= roles
    assert any(role.startswith("slave-") for role in roles)
    # the text report names every category
    report = wf.trace_report()
    for category in ("segment", "loader", "h2d", "serve", "jobs"):
        assert category in report


@pytest.mark.traced
def test_traced_run_reports_d2h_accounting():
    """The symmetric D2H satellite: a stitched run that fetches its
    deferred metrics pays accounted device→host traffic, visible both
    in Watcher.d2h_bytes and as the d2h_bytes counter track."""
    from veles_tpu.memory import Watcher

    before_bytes = Watcher.d2h_bytes
    before_events = trace.recorder.count("h2d", "d2h_bytes")
    wf = _build_stitched_workflow()
    wf.run()
    assert Watcher.d2h_bytes > before_bytes
    assert trace.recorder.count("h2d", "d2h_bytes") > before_events
