"""Test configuration: force an 8-device virtual CPU platform so mesh /
sharding tests exercise real multi-device code paths without TPU hardware
(mirrors the reference's NumpyDevice-as-universal-fake strategy,
``veles/tests/accelerated_test.py:47-80``)."""

import os

# Hard-set (the session env may point at a real TPU via an "axon" tunnel
# platform; tests must run on the virtual CPU mesh regardless).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have force-registered a TPU tunnel platform and set
# jax_platforms behind the env var's back; override before backend init.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_prng():
    """Deterministic named streams per test (ref: multi_device re-seeds
    between backends, accelerated_test.py:47-80)."""
    from veles_tpu import prng
    prng.seed_all(1234)
    yield
