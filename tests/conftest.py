"""Test configuration: force an 8-device virtual CPU platform so mesh /
sharding tests exercise real multi-device code paths without TPU hardware
(mirrors the reference's NumpyDevice-as-universal-fake strategy,
``veles/tests/accelerated_test.py:47-80``)."""

import os

# Preserved for tests that deliberately escape the CPU pin via a
# subprocess (test_accuracy_parity.py trains on the real accelerator).
ORIG_JAX_PLATFORMS = os.environ.get("JAX_PLATFORMS")
ORIG_XLA_FLAGS = os.environ.get("XLA_FLAGS", "")

# Hard-set (the session env may point at a real TPU via an "axon" tunnel
# platform; tests must run on the virtual CPU mesh regardless).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have force-registered a TPU tunnel platform and set
# jax_platforms behind the env var's back; override before backend init.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_prng():
    """Deterministic named streams per test (ref: multi_device re-seeds
    between backends, accelerated_test.py:47-80)."""
    from veles_tpu import prng
    prng.seed_all(1234)
    yield


@pytest.fixture(autouse=True)
def _force_trace(request):
    """The ``traced`` marker: force-enable the trace recorder around
    the test — via the CONFIG knob (not by poking the recorder), so a
    ``Workflow.initialize()`` inside the test (which re-reads the knob
    through ``trace.configure()``) keeps it on.  The ring starts empty
    and the default off-state is restored afterwards, so unmarked
    tests see the stock single-attribute-check disabled path."""
    if request.node.get_closest_marker("traced") is None:
        yield
        return
    from veles_tpu import trace
    from veles_tpu.config import root
    saved = root.common.engine.get("trace", "off")
    root.common.engine.trace = "on"
    trace.recorder.clear()
    trace.configure()
    yield
    root.common.engine.trace = saved
    trace.configure()
    trace.recorder.clear()


@pytest.fixture(autouse=True)
def _pin_synthetic_data(request, tmp_path, monkeypatch):
    """Short sample runs everywhere in the suite were calibrated on the
    synthetic stand-ins; a machine provisioned with real datasets (for
    test_accuracy_parity.py, which opts out) must not silently switch
    them onto real data."""
    if request.module.__name__ == "test_accuracy_parity":
        yield
        return
    from veles_tpu.config import root
    monkeypatch.delenv("VELES_DATASETS", raising=False)
    saved = root.common.dirs.get("datasets")
    root.common.dirs.datasets = str(tmp_path / "no-datasets-here")
    yield
    root.common.dirs.datasets = saved
