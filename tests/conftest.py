"""Test configuration: force an 8-device virtual CPU platform so mesh /
sharding tests exercise real multi-device code paths without TPU hardware
(mirrors the reference's NumpyDevice-as-universal-fake strategy,
``veles/tests/accelerated_test.py:47-80``)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_prng():
    """Deterministic named streams per test (ref: multi_device re-seeds
    between backends, accelerated_test.py:47-80)."""
    from veles_tpu import prng
    prng.seed_all(1234)
    yield
