"""Publishing + Forge tests (SURVEY §2.5): report rendering across
backends, and the model-hub round trip against a live local server on an
ephemeral port (mirrors reference test_forge_server/test_forge_client)."""

import json
import os

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.forge import ForgeClient, ForgeError, ForgeServer
from veles_tpu.publishing import (
    Publisher, backend_names, get_backend)
from veles_tpu.units import Unit


class MetricUnit(Unit):
    def initialize(self, **kwargs):
        pass

    def run(self):
        pass

    def get_metric_values(self):
        return {"accuracy": 0.97, "n_err": 42}


def _workflow():
    wf = DummyWorkflow()
    unit = MetricUnit(wf)
    unit.link_from(wf.start_point)
    wf.end_point.link_from(unit)
    return wf


class TestPublishing:
    def test_backend_registry(self):
        assert set(backend_names()) >= {"markdown", "html", "ipynb",
                                        "confluence"}
        with pytest.raises(ValueError, match="unknown"):
            get_backend("pdfxx")

    def test_publisher_writes_all_backends(self, tmp_path):
        wf = _workflow()
        pub = Publisher(wf, backends=("markdown", "html", "ipynb",
                                      "confluence", "pdf"),
                        out_dir=str(tmp_path),
                        description="Smoke-test report.")
        pub.initialize()
        pub.run()
        assert len(pub.published) == 5
        md = open(pub.published[0]).read()
        assert "accuracy | 0.97" in md.replace("| accuracy | 0.97 |",
                                               "accuracy | 0.97")
        assert "Smoke-test report." in md
        html = open(pub.published[1]).read()
        assert "<td>accuracy</td><td>0.97</td>" in html
        nb = json.load(open(pub.published[2]))
        assert nb["nbformat"] == 4
        assert any("accuracy" in "".join(c["source"])
                   for c in nb["cells"])
        confluence = open(pub.published[3]).read()
        assert "||Metric||Value||" in confluence
        pdf = open(pub.published[4], "rb").read()
        assert pdf.startswith(b"%PDF-")
        assert len(pdf) > 1000

    def test_publisher_rejects_unknown_backend(self):
        wf = _workflow()
        pub = Publisher(wf, backends=("nope",))
        with pytest.raises(ValueError):
            pub.initialize()

    def test_report_contains_graph_and_stats(self, tmp_path):
        wf = _workflow()
        wf.initialize()
        wf.run()
        pub = Publisher(wf, backends=("markdown",),
                        out_dir=str(tmp_path))
        info = pub.gather_info()
        assert info["results"]["accuracy"] == 0.97
        assert info["checksum"]
        assert info["graph"] is None or "digraph" in info["graph"]


@pytest.fixture
def hub(tmp_path):
    server = ForgeServer(str(tmp_path / "store"),
                         tokens={"sekrit": "alice"}).start()
    yield server
    server.stop()


def _make_package(tmp_path, name="m.zip"):
    """A real exported package (manifest = contents.json)."""
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.memory import Vector
    from veles_tpu.package import export_package
    from veles_tpu.znicz.all2all import All2AllTanh
    wf = DummyWorkflow()
    fc = All2AllTanh(wf, output_sample_shape=(4,))
    fc.input = Vector(numpy.zeros((2, 6), numpy.float32))
    fc.initialize(NumpyDevice())
    path = str(tmp_path / name)
    export_package([fc], path, with_stablehlo=False)
    return path


class TestForge:
    def test_upload_list_fetch_delete(self, hub, tmp_path):
        pkg = _make_package(tmp_path)
        client = ForgeClient(hub.endpoint, token="sekrit")
        meta = client.upload("mnist-mlp", pkg)
        assert meta["version"] == "v1"
        assert meta["uploader"] == "alice"
        assert meta["manifest"]["format_version"] == 1

        listing = client.list()
        assert [m["name"] for m in listing] == ["mnist-mlp"]
        assert listing[0]["latest"] == "v1"

        dest = str(tmp_path / "fetched.zip")
        client.fetch("mnist-mlp", dest)
        assert open(dest, "rb").read() == open(pkg, "rb").read()

        # fetched package is loadable
        from veles_tpu.package import PackagedRunner
        runner = PackagedRunner(dest)
        assert runner.contents["units"][0]["type"] == "all2all_tanh"

        client.delete("mnist-mlp")
        assert client.list() == []

    def test_versioning(self, hub, tmp_path):
        pkg = _make_package(tmp_path)
        client = ForgeClient(hub.endpoint, token="sekrit")
        client.upload("m", pkg)
        client.upload("m", pkg, version="v2")
        assert client.list()[0]["versions"] == ["v1", "v2"]
        manifest = client.manifest("m", version="v1")
        assert manifest["version"] == "v1"

    def test_auth_required_for_writes(self, hub, tmp_path):
        pkg = _make_package(tmp_path)
        anon = ForgeClient(hub.endpoint)
        with pytest.raises(ForgeError, match="token"):
            anon.upload("m", pkg)
        ForgeClient(hub.endpoint, token="sekrit").upload("m", pkg)
        with pytest.raises(ForgeError, match="token"):
            anon.delete("m")
        # reads stay public
        assert anon.list()[0]["name"] == "m"

    def test_fetch_verifies_checksum(self, hub, tmp_path):
        pkg = _make_package(tmp_path)
        client = ForgeClient(hub.endpoint, token="sekrit")
        client.upload("m", pkg)
        # corrupt the stored package behind the server's back
        mdir = os.path.join(hub.store.directory, "m")
        victim = os.path.join(mdir, "v1.pkg")
        blob = bytearray(open(victim, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(blob))
        with pytest.raises(ForgeError, match="checksum"):
            client.fetch("m", str(tmp_path / "bad.zip"))

    def test_path_traversal_rejected(self, hub, tmp_path):
        """'..' and slash names must not escape the store directory."""
        client = ForgeClient(hub.endpoint, token="sekrit")
        pkg = _make_package(tmp_path)
        for evil in ("..", ".", "a/b", "a%2Fb".replace("%2F", "/")):
            with pytest.raises(ForgeError):
                client.upload(evil, pkg)
            with pytest.raises(ForgeError):
                client.delete(evil)
        # parent directory untouched
        assert os.path.isdir(hub.store.directory)

    def test_version_natural_order(self, hub, tmp_path):
        """v10 sorts after v9; auto-versioning never collides."""
        pkg = _make_package(tmp_path)
        client = ForgeClient(hub.endpoint, token="sekrit")
        for _ in range(11):
            client.upload("m", pkg)
        listing = client.list()[0]
        assert listing["versions"][-2:] == ["v10", "v11"]
        assert listing["latest"] == "v11"
        # explicit version followed by auto must not overwrite
        client.upload("n", pkg, version="v2")
        meta = client.upload("n", pkg)
        assert meta["version"] != "v2"

    def test_missing_model_404(self, hub, tmp_path):
        client = ForgeClient(hub.endpoint)
        with pytest.raises(ForgeError, match="no such model"):
            client.fetch("ghost", str(tmp_path / "x.zip"))


def test_forge_ui_page(hub):
    """GET / serves the forge browser UI over the JSON endpoints
    (VERDICT r4 missing item 4)."""
    import urllib.request
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/" % hub.port) as r:
        assert r.headers["Content-Type"].startswith("text/html")
        body = r.read().decode()
    assert "veles-tpu forge" in body
    assert "models" in body
