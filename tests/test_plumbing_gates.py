"""Plumbing gate semantics: Repeater any-edge re-fire, FireStarter
re-arming via the public reset_gate API, EndPoint terminality, and the
gate-deadlock graph the doctor flags statically — at runtime the FIFO
scheduler drains and returns WITHOUT finishing (silent
non-termination), which is exactly why the static check exists."""

from veles_tpu.analyze import check_graph
from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import EndPoint, FireStarter, Repeater


def test_reset_gate_clears_fired_edges():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    c = DummyUnit(wf, name="c")
    c.link_from(a, b)
    assert not c.open_gate(a)           # partial fire
    assert c.links_from[a] is True
    c.reset_gate()
    assert list(c.links_from.values()) == [False, False]
    assert not c.open_gate(a)           # partial again, not leftover


def test_repeater_refires_on_single_edge():
    wf = DummyWorkflow()
    rpt = Repeater(wf, name="rpt")
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    rpt.link_from(a, b)
    # ANY one fired edge opens the gate, and the gate resets behind it
    assert rpt.open_gate(a) is True
    assert list(rpt.links_from.values()) == [False, False]
    assert rpt.open_gate(b) is True
    assert rpt.ignores_gate


def test_repeater_anchored_loop_runs_to_termination():
    wf = DummyWorkflow()
    rpt = Repeater(wf, name="rpt")
    body = DummyUnit(wf, name="body")
    class Counter(DummyUnit):
        def __init__(self, workflow, **kwargs):
            super(Counter, self).__init__(workflow, **kwargs)
            self.done = Bool(False)

        def run(self):
            super(Counter, self).run()
            if self.run_count >= 3:
                self.done <<= True

    counter = Counter(wf, name="counter")
    done = counter.done
    rpt.link_from(wf.start_point)
    body.link_from(rpt)
    counter.link_from(body)
    rpt.link_from(counter)              # back edge
    wf.end_point.link_from(counter)
    wf.end_point.gate_block = ~done
    wf.initialize()
    wf.run()
    assert counter.run_count == 3
    assert wf.stopped


def test_firestarter_rearms_via_public_api():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    c = DummyUnit(wf, name="c")
    c.link_from(a, b)
    c.open_gate(a)                      # leave a half-fired gate
    fs = FireStarter(wf, units=[c])
    fs.run()
    assert list(c.links_from.values()) == [False, False]
    # and the lint pack proves FireStarter no longer reaches into
    # _gate_lock_/links_from directly (test_analyze self-lint)


def test_endpoint_is_terminal():
    wf = DummyWorkflow()
    stray = DummyUnit(wf, name="stray")
    stray.link_from(wf.end_point)       # even with an outgoing edge...
    wf.end_point.run_dependent()        # ...nothing is scheduled
    assert len(wf._queue_) == 0
    wf.end_point.run()
    assert wf.stopped                   # running End finishes the flow


def test_gate_deadlock_flagged_statically_and_never_finishes(
        monkeypatch):
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    ghost = DummyUnit(wf, name="ghost")
    joiner = DummyUnit(wf, name="joiner")
    a.link_from(wf.start_point)
    joiner.link_from(a, ghost)          # ghost can never fire
    wf.end_point.link_from(joiner)

    findings = [f for f in check_graph(wf) if f.rule == "V-G03"]
    assert findings and findings[0].unit == "joiner"

    # Runtime ground truth: the queue drains, run() returns, but the
    # graph never finished — the silent hang the doctor catches.
    # QUIESCENCE_TIMEOUT guards the drain in case a straggler wedges.
    monkeypatch.setattr(type(wf), "QUIESCENCE_TIMEOUT", 5.0)
    wf.initialize()
    wf.run()
    assert a.run_count == 1
    assert joiner.run_count == 0        # gate never opened
    assert not wf.stopped               # on_workflow_finished never ran
