"""Flash-attention kernel tests: Pallas interpret-mode vs the jnp
reference (golden pattern from test_ops.py), gradients via the
blockwise VJP vs autodiff of the reference, and the ring-attention
composition."""

import jax
import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.ops.attention import (
    _bwd_blockwise, _flash_fwd, _mha_jnp, flash_attention)
from veles_tpu.parallel.ring import mha_reference


def _qkv(b=2, sq=24, sk=24, h=3, d=16, seed=0):
    rng = numpy.random.default_rng(seed)
    mk = lambda s: jnp.asarray(
        rng.standard_normal((b, s, h, d)).astype(numpy.float32))
    return mk(sq), mk(sk), mk(sk)


@pytest.mark.parametrize("causal", [False, True])
def test_interpret_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    assert out.shape == ref.shape
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5)
    assert lse.shape == (2, 3, 24)


def test_interpret_ragged_and_rect():
    """Non-multiple-of-block seq lengths and Sq != Sk."""
    q, k, v = _qkv(sq=13, sk=29, d=20, seed=1)
    ref = mha_reference(q, k, v)
    out, _ = _flash_fwd(q, k, v, block_q=8, block_k=8, interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5)


def test_interpret_mismatched_blocks():
    """bq != bk with lengths that are multiples of neither: every
    tensor must pad to its OWN block size (regression: shared padding
    left trailing q rows unwritten / k blocks unvisited)."""
    q, k, v = _qkv(sq=12, sk=12, d=8, seed=5)
    ref = mha_reference(q, k, v)
    for bq, bk in ((8, 12), (12, 8)):
        out, _ = _flash_fwd(q, k, v, block_q=bq, block_k=bk,
                            interpret=True)
        assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                              atol=2e-5), (bq, bk)


def test_jnp_fallback_matches_reference():
    q, k, v = _qkv(seed=2)
    out, lse = _mha_jnp(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_vjp_matches_autodiff(causal):
    q, k, v = _qkv(b=1, sq=16, sk=16, h=2, d=8, seed=3)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)

    def flash_loss(q, k, v):
        return (flash_attention(q, k, v, causal, 8, 8, False) ** 2).sum()

    dq, dk, dv = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4), \
            float(numpy.abs(numpy.asarray(got) -
                            numpy.asarray(ref)).max())


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_bwd_matches_autodiff(causal):
    """The Pallas two-kernel backward (interpret mode) against
    autodiff of the dense reference."""
    from veles_tpu.ops.attention import _flash_bwd, _flash_fwd
    q, k, v = _qkv(b=1, sq=16, sk=16, h=2, d=8, seed=3)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)
    o, lse = _flash_fwd(q, k, v, causal=causal, block_q=8, block_k=8,
                        interpret=True)
    do = 2.0 * o
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, causal=causal,
                            block_q=8, block_k=8, interpret=True)
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert got.shape == ref.shape
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4), \
            float(numpy.abs(numpy.asarray(got) -
                            numpy.asarray(ref)).max())


@pytest.mark.parametrize("bq,bk", [(8, 8), (8, 16), (16, 8)])
def test_pallas_bwd_ragged_and_mismatched_blocks(bq, bk):
    """Ragged seq lengths (padding rows/blocks) and bq != bk: padded
    q rows must contribute zero to dk/dv, padded k rows zero to dq."""
    from veles_tpu.ops.attention import _flash_bwd, _flash_fwd
    q, k, v = _qkv(b=2, sq=13, sk=21, h=2, d=12, seed=7)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)
    o, lse = _flash_fwd(q, k, v, block_q=bq, block_k=bk,
                        interpret=True)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, 2.0 * o, block_q=bq,
                            block_k=bk, interpret=True)
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4), (bq, bk)


def test_pallas_bwd_causal_ragged():
    """Causal + non-block-multiple lengths: the block-skip condition
    must not skip partially-unmasked diagonal blocks."""
    from veles_tpu.ops.attention import _flash_bwd, _flash_fwd
    q, k, v = _qkv(b=1, sq=21, sk=21, h=2, d=8, seed=9)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)
    o, lse = _flash_fwd(q, k, v, causal=True, block_q=8, block_k=8,
                        interpret=True)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, 2.0 * o, causal=True,
                            block_q=8, block_k=8, interpret=True)
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4)


def test_pallas_bwd_bf16_operands():
    """bf16 inputs: MXU-dtype operands with f32 accumulation must stay
    within bf16 tolerance of the f32 reference grads."""
    from veles_tpu.ops.attention import _flash_bwd, _flash_fwd
    q32, k32, v32 = _qkv(b=1, sq=16, sk=16, h=2, d=8, seed=11)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q32, k32, v32)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))
    o, lse = _flash_fwd(q, k, v, causal=True, block_q=8, block_k=8,
                        interpret=True)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, 2.0 * o, causal=True,
                            block_q=8, block_k=8, interpret=True)
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert got.dtype == jnp.bfloat16
        assert numpy.allclose(
            numpy.asarray(got, numpy.float32), numpy.asarray(ref),
            atol=0.12, rtol=0.1)


@pytest.mark.parametrize("causal", [False, True])
def test_grad_through_public_entry_pallas_path(causal):
    """jax.grad through flash_attention with use_pallas=True and the
    interpret config flag on: exercises the PRODUCTION dispatch —
    _flash_vjp_fwd residual pack, _resolve_bwd, _flash_bwd unpack —
    not just the kernels in isolation."""
    from veles_tpu.config import root
    q, k, v = _qkv(b=1, sq=16, sk=16, h=2, d=8, seed=13)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)

    def flash_loss(q, k, v):
        return (flash_attention(q, k, v, causal, 8, 8, True) ** 2).sum()

    root.common.engine.interpret = True
    try:
        dq, dk, dv = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        root.common.engine.interpret = False
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4), \
            float(numpy.abs(numpy.asarray(got) -
                            numpy.asarray(ref)).max())


def test_bwd_autotune_sweep_writes_db(tmp_path, monkeypatch):
    """autotune_flash_attention_bwd persists flash_attention_bwd_v2
    winners that _resolve_bwd then consumes (CPU: XLA must win)."""
    from veles_tpu.ops import benchmark
    from veles_tpu.ops.attention import _resolve_bwd
    db_path = str(tmp_path / "db.json")
    info = benchmark.autotune_flash_attention_bwd(
        shape=(1, 32, 2, 8), dtypes=("float32",),
        candidates=((8, 8),), runs=1, db_path=db_path)
    entry = info.ratings["flash_attention_bwd_v2"]["float32"]
    assert len(entry) == 1
    cls = next(iter(entry))
    assert entry[cls]["backend"] in ("xla", "pallas")
    assert entry[cls]["shape"] == [1, 32, 2, 8]
    # gemm_choice routes the new kernel key with an attention shape
    choice = benchmark.gemm_choice(
        jnp.float32, db_path=db_path, kernel="flash_attention_bwd",
        shape=(1, 32, 2, 8))
    assert choice is not None


def test_flash_attention_jit_and_fallback():
    """Public entry jits and auto-selects the fallback off-TPU."""
    q, k, v = _qkv(seed=4)
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v))(q, k, v)
    ref = mha_reference(q, k, v)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=1e-5)


def test_pallas_bwd_under_shard_map():
    """The custom VJP with the Pallas backward must trace through
    shard_map (the transformer's head-sharded _attend wrapper): grads
    via the interpret-mode Pallas path on a 1-axis CPU mesh match
    autodiff of the dense reference."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from veles_tpu.config import root
    from veles_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = make_mesh({"model": 2})
    q, k, v = _qkv(b=1, sq=16, sk=16, h=4, d=8, seed=17)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)

    spec = P(None, None, "model", None)
    att = shard_map(
        lambda q, k, v: flash_attention(q, k, v, True, 8, 8, True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)

    def loss(q, k, v):
        return (att(q, k, v) ** 2).sum()

    prior = root.common.engine.get("interpret", False)
    root.common.engine.interpret = True
    try:
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        root.common.engine.interpret = prior
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4), \
            float(numpy.abs(numpy.asarray(got) -
                            numpy.asarray(ref)).max())


# -- decode fast path (q_len=1 against a masked KV buffer) -----------------

def _decode_case(b=3, S=24, h=2, d=16, seed=11):
    rng = numpy.random.default_rng(seed)
    mk = lambda shape: jnp.asarray(
        rng.standard_normal(shape).astype(numpy.float32))
    return (mk((b, 1, h, d)), mk((b, S, h, d)), mk((b, S, h, d)))


def test_decode_dense_matches_prefix_reference():
    """The dense masked decode reference equals full attention over
    each row's valid KV prefix — the oracle everything else chains
    to."""
    from veles_tpu.ops.attention import _decode_jnp, _mha_jnp
    q, k, v = _decode_case()
    lengths = [1, 13, 24]
    out = _decode_jnp(q, k, v, jnp.asarray(lengths, jnp.int32))
    for i, n in enumerate(lengths):
        ref, _ = _mha_jnp(q[i:i + 1], k[i:i + 1, :n], v[i:i + 1, :n],
                          causal=False)
        assert numpy.allclose(numpy.asarray(out[i]),
                              numpy.asarray(ref[0]), atol=1e-5), i


def test_decode_pallas_interpret_matches_dense():
    """Pallas decode kernel (interpret mode) vs the dense masked
    reference: mixed lengths including a fully-masked tail block and
    a full-cache row."""
    from veles_tpu.ops.attention import _decode_jnp, _decode_pallas
    q, k, v = _decode_case()
    lengths = jnp.asarray([1, 13, 24], jnp.int32)
    ref = _decode_jnp(q, k, v, lengths)
    out = _decode_pallas(q, k, v, lengths, block_k=8, interpret=True)
    assert out.shape == ref.shape
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5)


def test_decode_pallas_ragged_shapes():
    """Cache length not a block multiple, head dim off the 128 lane
    boundary — per-tensor padding must stay masked."""
    from veles_tpu.ops.attention import _decode_jnp, _decode_pallas
    rng = numpy.random.default_rng(7)
    mk = lambda shape: jnp.asarray(
        rng.standard_normal(shape).astype(numpy.float32))
    q, k, v = mk((2, 1, 3, 20)), mk((2, 29, 3, 20)), mk((2, 29, 3, 20))
    lengths = jnp.asarray([7, 29], jnp.int32)
    ref = _decode_jnp(q, k, v, lengths)
    out = _decode_pallas(q, k, v, lengths, block_k=8, interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5)


def test_decode_public_entry_squeezes_and_jits():
    """decode_attention accepts (b, h, d) queries, returns the same
    leading shape, and traces under jit with traced lengths (the
    engine's fixed-shape decode program)."""
    from veles_tpu.ops.attention import _decode_jnp, decode_attention
    q, k, v = _decode_case(seed=3)
    lengths = jnp.asarray([5, 9, 2], jnp.int32)
    ref = _decode_jnp(q, k, v, lengths)
    out3 = decode_attention(q[:, 0], k, v, lengths, use_pallas=False)
    assert out3.shape == (3, 2, 16)
    assert numpy.allclose(numpy.asarray(out3), numpy.asarray(ref[:, 0]),
                          atol=1e-6)
    jitted = jax.jit(lambda q, k, v, n: decode_attention(
        q, k, v, n, use_pallas=False))
    outj = jitted(q, k, v, lengths)
    assert numpy.allclose(numpy.asarray(outj), numpy.asarray(ref),
                          atol=1e-6)


def test_decode_row_independence():
    """A slot's output is bitwise independent of what other slots
    hold — the property continuous batching's parity gate rests on."""
    from veles_tpu.ops.attention import _decode_jnp
    q, k, v = _decode_case(seed=19)
    lengths = jnp.asarray([9, 4, 17], jnp.int32)
    base = numpy.asarray(_decode_jnp(q, k, v, lengths))
    # scramble every OTHER row's query and cache (valid and garbage)
    rng = numpy.random.default_rng(23)
    for i in range(3):
        q2 = numpy.array(q)
        k2 = numpy.array(k)
        v2 = numpy.array(v)
        others = [j for j in range(3) if j != i]
        q2[others] = rng.standard_normal(q2[others].shape)
        k2[others] = rng.standard_normal(k2[others].shape)
        v2[others] = rng.standard_normal(v2[others].shape)
        out = numpy.asarray(_decode_jnp(
            jnp.asarray(q2), jnp.asarray(k2), jnp.asarray(v2), lengths))
        assert (out[i] == base[i]).all(), i


# -- paged decode (block-pool KV + block tables) ---------------------------

def _paged_case(b=3, max_blocks=3, bs=8, h=2, d=16, seed=31):
    """A contiguous decode case + its EXACT paged mirror: the same K/V
    values scattered into a shuffled block pool with tables mapping
    them back, a trash block 0 full of garbage, and unallocated table
    entries pointing at it."""
    rng = numpy.random.default_rng(seed)
    S = max_blocks * bs
    mk = lambda shape: rng.standard_normal(shape).astype(numpy.float32)
    q, k, v = mk((b, 1, h, d)), mk((b, S, h, d)), mk((b, S, h, d))
    num_blocks = b * max_blocks + 1
    k_pool = mk((num_blocks, bs, h, d))      # garbage incl. trash
    v_pool = mk((num_blocks, bs, h, d))
    # deterministic shuffle of the allocatable ids over rows
    ids = rng.permutation(numpy.arange(1, num_blocks))
    tables = numpy.zeros((b, max_blocks), numpy.int32)
    lengths = numpy.asarray([1, bs + 3, S], numpy.int32)
    next_id = 0
    for i in range(b):
        n_blk = -(-int(lengths[i]) // bs)    # ceil
        for j in range(n_blk):
            bid = int(ids[next_id])
            next_id += 1
            tables[i, j] = bid
            k_pool[bid] = k[i, j * bs:(j + 1) * bs]
            v_pool[bid] = v[i, j * bs:(j + 1) * bs]
    ja = jnp.asarray
    return (ja(q), ja(k), ja(v), ja(k_pool), ja(v_pool),
            jnp.asarray(tables), jnp.asarray(lengths))


def test_paged_decode_dense_matches_contiguous_bitwise():
    """The paged dense path (gather through the block tables) is
    BITWISE identical to the contiguous dense decode at every valid
    position — the substrate of the paged==contiguous engine parity
    gate.  Garbage beyond lengths differs between the layouts on
    purpose; the masked softmax must zero it out exactly."""
    from veles_tpu.ops.attention import _decode_jnp, _paged_decode_jnp
    q, k, v, k_pool, v_pool, tables, lengths = _paged_case()
    ref = numpy.asarray(_decode_jnp(q, k, v, lengths))
    out = numpy.asarray(_paged_decode_jnp(q, k_pool, v_pool, tables,
                                          lengths))
    assert (out == ref).all()


def test_paged_decode_pallas_interpret_matches_dense():
    """Paged Pallas kernel (interpret mode — the block table routes
    each K/V page's DMA via scalar prefetch) vs the gather+dense
    reference."""
    from veles_tpu.ops.attention import (_paged_decode_jnp,
                                         _paged_decode_pallas)
    q, k, v, k_pool, v_pool, tables, lengths = _paged_case()
    ref = _paged_decode_jnp(q, k_pool, v_pool, tables, lengths)
    out = _paged_decode_pallas(q, k_pool, v_pool, tables, lengths,
                               interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=1e-5), \
        float(numpy.abs(numpy.asarray(out) -
                        numpy.asarray(ref)).max())


def test_paged_decode_pallas_rejects_misaligned_block_size():
    from veles_tpu.ops.attention import _paged_decode_pallas
    q, k, v, k_pool, v_pool, tables, lengths = _paged_case()
    with pytest.raises(ValueError):
        _paged_decode_pallas(q, k_pool[:, :5], v_pool[:, :5],
                             tables, lengths, interpret=True)


def test_paged_decode_public_entry_squeezes_and_jits():
    """paged_decode_attention accepts (b, h, d) queries and jits with
    traced tables/lengths — the fixed-shape paged decode program's
    contract."""
    from veles_tpu.ops.attention import (_paged_decode_jnp,
                                         paged_decode_attention)
    q, k, v, k_pool, v_pool, tables, lengths = _paged_case(seed=7)
    ref = _paged_decode_jnp(q, k_pool, v_pool, tables, lengths)
    out3 = paged_decode_attention(q[:, 0], k_pool, v_pool, tables,
                                  lengths, use_pallas=False)
    assert out3.shape == (q.shape[0], q.shape[2], q.shape[3])
    assert (numpy.asarray(out3) == numpy.asarray(ref[:, 0])).all()
    jitted = jax.jit(lambda q, kp, vp, t, n: paged_decode_attention(
        q, kp, vp, t, n, use_pallas=False))
    out = jitted(q, k_pool, v_pool, tables, lengths)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=1e-6)


# -- chunked prefill attention ---------------------------------------------

def test_chunk_attention_matches_full_prefix():
    """One chunk's offset-causal attention over the full cache buffer
    equals the matching query rows of whole-prompt causal attention
    over the written prefix — stale cache tail (beyond start+C)
    hidden by the causal offset."""
    from veles_tpu.ops.attention import _mha_jnp, chunk_attention
    rng = numpy.random.default_rng(5)
    S, C, start, h, d = 32, 8, 16, 2, 16
    q_full = jnp.asarray(
        rng.standard_normal((1, start + C, h, d)).astype(numpy.float32))
    kv = rng.standard_normal((2, 1, S, h, d)).astype(numpy.float32)
    kv[:, :, start + C:] = 1e3               # stale tail: must not leak
    k, v = jnp.asarray(kv[0]), jnp.asarray(kv[1])
    ref, _ = _mha_jnp(q_full[:, :start + C], k[:, :start + C],
                      v[:, :start + C], causal=True)
    out = chunk_attention(q_full[:, start:], k, v, start,
                          use_pallas=False)
    assert numpy.allclose(numpy.asarray(out),
                          numpy.asarray(ref[:, start:]), atol=1e-5)
    # traced start (the chunk program's fixed-shape contract)
    jitted = jax.jit(lambda q, k, v, s: chunk_attention(
        q, k, v, s, use_pallas=False))
    out2 = jitted(q_full[:, start:], k, v, jnp.int32(start))
    assert numpy.allclose(numpy.asarray(out2), numpy.asarray(out),
                          atol=1e-6)
