"""Flash-attention kernel tests: Pallas interpret-mode vs the jnp
reference (golden pattern from test_ops.py), gradients via the
blockwise VJP vs autodiff of the reference, and the ring-attention
composition."""

import jax
import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.ops.attention import (
    _bwd_blockwise, _flash_fwd, _mha_jnp, flash_attention)
from veles_tpu.parallel.ring import mha_reference


def _qkv(b=2, sq=24, sk=24, h=3, d=16, seed=0):
    rng = numpy.random.default_rng(seed)
    mk = lambda s: jnp.asarray(
        rng.standard_normal((b, s, h, d)).astype(numpy.float32))
    return mk(sq), mk(sk), mk(sk)


@pytest.mark.parametrize("causal", [False, True])
def test_interpret_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    assert out.shape == ref.shape
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5)
    assert lse.shape == (2, 3, 24)


def test_interpret_ragged_and_rect():
    """Non-multiple-of-block seq lengths and Sq != Sk."""
    q, k, v = _qkv(sq=13, sk=29, d=20, seed=1)
    ref = mha_reference(q, k, v)
    out, _ = _flash_fwd(q, k, v, block_q=8, block_k=8, interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5)


def test_interpret_mismatched_blocks():
    """bq != bk with lengths that are multiples of neither: every
    tensor must pad to its OWN block size (regression: shared padding
    left trailing q rows unwritten / k blocks unvisited)."""
    q, k, v = _qkv(sq=12, sk=12, d=8, seed=5)
    ref = mha_reference(q, k, v)
    for bq, bk in ((8, 12), (12, 8)):
        out, _ = _flash_fwd(q, k, v, block_q=bq, block_k=bk,
                            interpret=True)
        assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                              atol=2e-5), (bq, bk)


def test_jnp_fallback_matches_reference():
    q, k, v = _qkv(seed=2)
    out, lse = _mha_jnp(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_vjp_matches_autodiff(causal):
    q, k, v = _qkv(b=1, sq=16, sk=16, h=2, d=8, seed=3)

    def ref_loss(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, k, v)

    def flash_loss(q, k, v):
        return (flash_attention(q, k, v, causal, 8, 8, False) ** 2).sum()

    dq, dk, dv = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for got, ref in ((dq, dq_ref), (dk, dk_ref), (dv, dv_ref)):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(ref),
                              atol=5e-4), \
            float(numpy.abs(numpy.asarray(got) -
                            numpy.asarray(ref)).max())


def test_flash_attention_jit_and_fallback():
    """Public entry jits and auto-selects the fallback off-TPU."""
    q, k, v = _qkv(seed=4)
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v))(q, k, v)
    ref = mha_reference(q, k, v)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=1e-5)
