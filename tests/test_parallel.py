"""Parallelism tests on the 8-device virtual CPU mesh: ring/Ulysses
sequence parallelism, GPipe pipeline, expert-parallel MoE, and the
DP×TP×SP transformer — each checked EXACTLY against a single-device
reference (the SPMD analogue of the reference's multi_device backend
sweep, ``accelerated_test.py:47-80``)."""

import jax
import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.parallel.mesh import make_mesh
from veles_tpu.parallel.moe import moe_mlp, moe_reference
from veles_tpu.parallel.pp import pipeline_apply
from veles_tpu.parallel.ring import (
    mha_reference, ring_attention, ulysses_attention)

RNG = numpy.random.default_rng(7)


def _qkv(B=4, S=32, H=8, D=16):
    return tuple(RNG.standard_normal((B, S, H, D)).astype("float32")
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    q, k, v = _qkv()
    mesh = make_mesh({"data": 2, "seq": 4})
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref),
                                  atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = _qkv()
    mesh = make_mesh({"data": 2, "seq": 4})
    ref = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref),
                                  atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(H=6)
    mesh = make_mesh({"seq": 4})
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh)


def test_ring_attention_grad_matches_dense():
    q, k, v = _qkv(B=2, S=16, H=4, D=8)
    mesh = make_mesh({"seq": 4})

    def loss_ring(q):
        return (ring_attention(q, k, v, mesh, causal=True,
                               batch_axis=None) ** 2).sum()

    def loss_ref(q):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_ring)(q)
    g2 = jax.grad(loss_ref)(q)
    numpy.testing.assert_allclose(numpy.asarray(g1),
                                  numpy.asarray(g2),
                                  atol=5e-4, rtol=5e-4)


def _stage_params(L=4, D=16):
    return {"w": (RNG.standard_normal((L, D, D)) * 0.3).astype(
        "float32"),
        "b": (RNG.standard_normal((L, D)) * 0.1).astype("float32")}


def _stage(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _sequential(params, x, L):
    h = x
    for i in range(L):
        h = _stage({"w": params["w"][i], "b": params["b"][i]}, h)
    return h


def test_pipeline_forward_matches_sequential():
    mesh = make_mesh({"pipe": 4, "data": 2})
    params = _stage_params()
    x = RNG.standard_normal((8, 16)).astype("float32")
    ref = _sequential(params, x, 4)
    out = pipeline_apply(_stage, params, x, mesh, n_micro=4,
                         batch_axis="data")
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref), atol=1e-5)


def test_pipeline_grad_matches_sequential():
    mesh = make_mesh({"pipe": 4})
    params = _stage_params()
    x = RNG.standard_normal((8, 16)).astype("float32")

    g1 = jax.grad(lambda p: (pipeline_apply(
        _stage, p, x, mesh, n_micro=4) ** 2).sum())(params)
    g2 = jax.grad(lambda p: (_sequential(p, x, 4) ** 2).sum())(params)
    for key in g1:
        numpy.testing.assert_allclose(numpy.asarray(g1[key]),
                                      numpy.asarray(g2[key]),
                                      atol=1e-4, rtol=1e-4)


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh({"pipe": 4})
    with pytest.raises(ValueError):
        pipeline_apply(_stage, _stage_params(),
                       numpy.zeros((7, 16), "float32"), mesh, n_micro=4)


def _moe_params(D=8, E=8, F=16):
    return {
        "router": RNG.standard_normal((D, E)).astype("float32"),
        "w1": (RNG.standard_normal((E, D, F)) * 0.3).astype("float32"),
        "b1": numpy.zeros((E, F), "float32"),
        "w2": (RNG.standard_normal((E, F, D)) * 0.3).astype("float32"),
        "b2": numpy.zeros((E, D), "float32")}


def test_moe_matches_dense_reference():
    mesh = make_mesh({"data": 2, "model": 4})
    params = _moe_params()
    x = RNG.standard_normal((4, 16, 8)).astype("float32")
    ref = moe_reference(jnp.asarray(x),
                        {k: jnp.asarray(v) for k, v in params.items()})
    out = moe_mlp(x, params, mesh, capacity_factor=8.0)
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref), atol=2e-5)


def test_moe_capacity_drops_are_bounded():
    """With capacity_factor≪1 tokens drop (zero rows) but nothing
    explodes — the switch-transformer overflow contract."""
    mesh = make_mesh({"model": 4})
    params = _moe_params()
    x = RNG.standard_normal((2, 16, 8)).astype("float32")
    out = numpy.asarray(moe_mlp(x, params, mesh, batch_axis=None,
                                capacity_factor=0.25))
    assert numpy.isfinite(out).all()
    # at least one token went through, at least one was dropped
    row_norms = numpy.abs(out).sum(-1)
    assert (row_norms > 0).any() and (row_norms == 0).any()


def test_moe_grads_flow():
    mesh = make_mesh({"model": 4})
    params = _moe_params()
    x = RNG.standard_normal((2, 16, 8)).astype("float32")
    grads = jax.grad(lambda p: (moe_mlp(
        x, p, mesh, batch_axis=None, capacity_factor=8.0) ** 2).sum())(
        params)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert numpy.isfinite(numpy.asarray(leaf)).all()
    assert float(numpy.abs(numpy.asarray(grads["w1"])).max()) > 0


def test_transformer_mesh_matches_single_device():
    """One train step of the TINY LM: single-device jit vs the full
    DP×SP×TP mesh — losses and updated params must agree."""
    from veles_tpu.samples import transformer as T
    cfg = dict(T.TINY)
    toks = T.synthetic_tokens(cfg, 4)

    p1, v1, step1 = T.build_train(cfg, mesh=None,
                                  compute_dtype=jnp.float32,
                                  remat=False)
    p1, v1, m1 = step1(p1, v1, toks)

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    p2, v2, step2 = T.build_train(cfg, mesh=mesh,
                                  compute_dtype=jnp.float32,
                                  remat=False)
    p2, v2, m2 = step2(p2, v2, toks)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b), atol=1e-6)


def test_transformer_loss_decreases():
    from veles_tpu.samples import transformer as T
    cfg = dict(T.TINY)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params, vel, step = T.build_train(cfg, mesh=mesh, lr=1e-2,
                                      compute_dtype=jnp.float32,
                                      remat=True)
    toks = T.synthetic_tokens(cfg, 8)
    first = None
    for _ in range(8):
        params, vel, metrics = step(params, vel, toks)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


def test_transformer_chunked_ce_matches_full_logits():
    """The scan-over-chunks CE (never materializes [B,S,V]) must be the
    same math as the full-logits log_softmax path — loss AND the
    updated parameters, with a chunk that does NOT divide S-1 so the
    padding/mask leg is exercised."""
    from veles_tpu.samples import transformer as T
    cfg = dict(T.TINY)                      # S=16 -> n=15 targets
    toks = T.synthetic_tokens(cfg, 4)
    full = T.make_train_step(cfg, compute_dtype=jnp.float32,
                             ce_chunk=0)
    chunked = T.make_train_step(cfg, compute_dtype=jnp.float32,
                                ce_chunk=4)  # 15 = 3*4 + 3: pad leg
    p0 = T.init_params(cfg, seed=3)
    v0 = jax.tree.map(numpy.zeros_like, p0)
    pf, vf, mf = jax.jit(full)(p0, v0, toks)
    pc, vc, mc = jax.jit(chunked)(p0, v0, toks)
    assert float(mf["loss"]) == pytest.approx(float(mc["loss"]),
                                              rel=1e-6)
    for a, b in zip(jax.tree.leaves(pf), jax.tree.leaves(pc)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b), atol=1e-6)


def test_transformer_chunked_ce_matches_full_logits_bf16():
    """The same chunked-vs-full equivalence on the bf16 compute path —
    the path the r4 dtype work actually changed (bf16 logits, bf16
    backward cotangents).  Looser bars: the readout is bf16-rounded by
    design (a deliberate precision trade, see apply_fn)."""
    from veles_tpu.samples import transformer as T
    cfg = dict(T.TINY)
    toks = T.synthetic_tokens(cfg, 4)
    full = T.make_train_step(cfg, compute_dtype=jnp.bfloat16,
                             ce_chunk=0)
    chunked = T.make_train_step(cfg, compute_dtype=jnp.bfloat16,
                                ce_chunk=4)
    p0 = T.init_params(cfg, seed=3)
    v0 = jax.tree.map(numpy.zeros_like, p0)
    pf, vf, mf = jax.jit(full)(p0, v0, toks)
    pc, vc, mc = jax.jit(chunked)(p0, v0, toks)
    assert numpy.isfinite(float(mf["loss"]))
    assert float(mf["loss"]) == pytest.approx(float(mc["loss"]),
                                              rel=2e-2)
    for a, b in zip(jax.tree.leaves(pf), jax.tree.leaves(pc)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b), atol=5e-3)


def test_flash_attention_backward_bf16_matches_f32_reference():
    """The bf16-operand flash backward (r4: operands stay bf16 on the
    MXU, f32 accumulation) must track the all-f32 backward within
    bf16 resolution — pins the changed path, which the f32-pinned
    attention tests never touch."""
    from veles_tpu.ops.attention import flash_attention

    rng = numpy.random.default_rng(7)
    shp = (2, 64, 2, 32)
    # the SAME bf16-representable values feed both paths, so the
    # comparison isolates the backward's arithmetic (bf16 operands,
    # f32 accumulation) from input quantization; smooth loss — an
    # abs() loss flips cotangent signs wherever o crosses 0
    q16, k16, v16 = (jnp.asarray(
        rng.standard_normal(shp).astype(numpy.float32), jnp.bfloat16)
        for _ in range(3))

    def loss16(q, k, v):
        o = flash_attention(q, k, v, True).astype(jnp.float32)
        return jnp.sum(o * o)

    def loss32(q, k, v):
        o = flash_attention(q.astype(jnp.float32),
                            k.astype(jnp.float32),
                            v.astype(jnp.float32), True)
        return jnp.sum(o * o)

    g16 = jax.grad(loss16, argnums=(0, 1, 2))(q16, k16, v16)
    g32 = jax.grad(loss32, argnums=(0, 1, 2))(q16, k16, v16)
    for a, b in zip(g32, g16):
        ref = numpy.asarray(a, dtype=numpy.float32)
        got = numpy.asarray(b, dtype=numpy.float32)
        denom = numpy.abs(ref).max() or 1.0
        assert numpy.abs(got - ref).max() / denom < 0.03


def test_transformer_chunked_ce_backward_stores_no_vocab_residual():
    """The checkpoint inside the CE scan is what makes the chunking
    real: without it the forward scan stacks each chunk's softmax
    residual and the backward carries the full [*, *, *, V] tensor.
    Guard: no intermediate in the grad jaxpr may have a stacked
    4-D shape ending in the vocab dimension."""
    from veles_tpu.samples import transformer as T
    cfg = dict(T.TINY)
    step = T.make_train_step(cfg, compute_dtype=jnp.float32, ce_chunk=4)
    p0 = T.init_params(cfg, seed=0)
    v0 = jax.tree.map(numpy.zeros_like, p0)
    toks = T.synthetic_tokens(cfg, 4)
    jaxpr = jax.make_jaxpr(step)(p0, v0, toks)

    def shapes(jx, out):
        for eqn in jx.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    out.add(tuple(aval.shape))
            for val in eqn.params.values():
                inner = getattr(val, "jaxpr", None)
                if inner is not None:
                    shapes(inner, out)
                if isinstance(val, (list, tuple)):
                    for item in val:
                        inner = getattr(item, "jaxpr", None)
                        if inner is not None:
                            shapes(inner, out)
        return out

    seen = shapes(jaxpr.jaxpr, set())
    stacked_vocab = [s for s in seen
                     if len(s) == 4 and s[-1] == cfg["vocab"]]
    assert not stacked_vocab, (
        "full-vocab residual stacked across CE chunks: %s" %
        stacked_vocab)


def test_transformer_mesh_chunked_ce_runs():
    """Chunked CE under a DP×TP mesh (seq unsharded -> chunking ON);
    a seq-sharded mesh falls back to the GSPMD-sharded full-logits
    readout (chunk scan axes cannot be sharded along seq)."""
    from veles_tpu.samples import transformer as T
    cfg = dict(T.TINY)
    mesh = make_mesh({"data": 2, "seq": 1, "model": 2})
    params, vel, step = T.build_train(cfg, mesh=mesh, lr=1e-2,
                                      compute_dtype=jnp.float32,
                                      ce_chunk=4)
    toks = T.synthetic_tokens(cfg, 8)
    params, vel, metrics = step(params, vel, toks)
    assert numpy.isfinite(float(metrics["loss"]))
    # the seq-parallel mesh keeps the sharded full-logits path and
    # must agree with the single-device chunked result
    mesh_sp = make_mesh({"data": 2, "seq": 2, "model": 2})
    params2, vel2, step2 = T.build_train(cfg, mesh=mesh_sp, lr=1e-2,
                                         compute_dtype=jnp.float32,
                                         ce_chunk=4)
    _p, _v, metrics_sp = step2(params2, vel2, toks)
    assert float(metrics_sp["loss"]) == pytest.approx(
        float(metrics["loss"]), rel=1e-5)


@pytest.mark.slow
def test_graft_entry_dryrun_all_modes():
    # the full multichip dry-run ladder compiles every parallelism
    # mode's real-dims program (~85 s on the virtual CPU mesh) —
    # outside the tier-1 budget; the per-leg sharding contracts are
    # pinned cheaply by test_real_shape_dryrun_leg_shardings
    import __graft_entry__ as graft
    graft.dryrun_multichip(8)


def test_real_shape_dryrun_leg_shardings():
    """Pins the per-leg sharding specs of the real-dims multichip
    dryruns (VERDICT r3 item 6) WITHOUT running the heavy steps:
    fsdp_rules on the REAL LM parameter shapes must shard every big
    weight (and momenta) over 'data' on a dim divisible by 8, and the
    conv-DP contract keeps params replicated with the batch split."""
    from jax.sharding import PartitionSpec as P

    from veles_tpu.parallel.dp import fsdp_rules

    mesh = make_mesh({"data": 8})
    rules = fsdp_rules(mesh)
    # real LM shapes (transformer.CONFIG: d=1024 L=12 V=32000 S=2048)
    d, L, V, S, f = 1024, 12, 32000, 2048, 4096
    expected = {
        (V, d): P("data", None),            # embed: vocab dim
        (S, d): P("data", None),            # pos
        (L, d, 3, 16, 64): P(None, "data", None, None, None),  # wqkv
        (L, 16, 64, d): P(None, "data", None, None),           # wo
        (L, d, f): P(None, "data", None),   # w1
        (L, f, d): P(None, "data", None),   # w2
        (L, f): P(None, "data"),            # b1
        (L, d): P(None, "data"),            # ln gains / b2
    }
    for shape, spec in expected.items():
        got = rules(numpy.empty(shape, numpy.float32))
        assert got == spec, (shape, got, spec)
    # small leaves stay replicated (collective latency > bytes saved);
    # a full d-vector (= min_elements) is big enough to shard
    assert rules(numpy.empty((64,), numpy.float32)) is None
    assert rules(numpy.empty((d,), numpy.float32)) == P("data")
    # AlexNet conv-DP leg: params replicated, batch on 'data'
    from veles_tpu.parallel import data_parallel
    from veles_tpu.parallel.dp import _params_sharding
    params = [{"w": numpy.empty((11, 11, 3, 96), numpy.float32)}]
    shard = _params_sharding(params, mesh, None)
    assert shard[0]["w"].is_fully_replicated


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_pallas_blocks_match_dense(causal):
    """Ring-FLASH with the Pallas kernels forced (interpret mode):
    per-hop _flash_fwd blocks with global causal offsets + the
    two-softmax merge must equal dense attention."""
    from veles_tpu.config import root
    q, k, v = _qkv(B=2, S=32, H=4, D=8)
    mesh = make_mesh({"seq": 4})
    ref = mha_reference(q, k, v, causal=causal)
    prior = root.common.engine.get("interpret", False)
    root.common.engine.interpret = True
    try:
        out = ring_attention(q, k, v, mesh, causal=causal,
                             batch_axis=None)
    finally:
        root.common.engine.interpret = prior
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref),
                                  atol=2e-5, rtol=2e-5)


def test_ring_flash_grads_all_inputs_match_dense():
    """The hand-rolled backward ring (dk/dv traveling with their
    blocks, global-lse flash identity) must match autodiff of dense
    attention for ALL of q, k, v — Pallas blocks forced."""
    from veles_tpu.config import root
    q, k, v = _qkv(B=1, S=16, H=2, D=8)
    mesh = make_mesh({"seq": 4})

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True,
                               batch_axis=None) ** 2).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) ** 2).sum()

    prior = root.common.engine.get("interpret", False)
    root.common.engine.interpret = True
    try:
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    finally:
        root.common.engine.interpret = prior
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, ref in zip(g_ring, g_ref):
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref),
                                      atol=5e-4, rtol=5e-4)


def test_ring_flash_oracle_path_matches():
    """use_flash=False keeps the dense-einsum online-softmax ring as
    the equivalence oracle; both paths agree with dense attention and
    with each other."""
    q, k, v = _qkv(B=2, S=32, H=4, D=8)
    mesh = make_mesh({"seq": 4})
    ref = mha_reference(q, k, v, causal=True)
    new = ring_attention(q, k, v, mesh, causal=True, batch_axis=None)
    old = ring_attention(q, k, v, mesh, causal=True, batch_axis=None,
                         use_flash=False)
    for out in (new, old):
        numpy.testing.assert_allclose(numpy.asarray(out),
                                      numpy.asarray(ref),
                                      atol=2e-5, rtol=2e-5)
