"""Eager unit-chain fast path (veles_tpu.stitch): segment construction
over the standard training graph, O(segments) dispatch counts per
minibatch, stitched↔unstitched numerical parity (weights AND metrics,
short epoch tails included), gate-semantics regressions (Repeater
re-fire, Decision barrier, shared TRAIN skip gate, ``stitch=off``
restoring the per-unit path), deferred device-scalar metrics, and the
``-m slow`` throughput floor: stitched ≥ 1.5× unstitched on CPU JAX."""

import time

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class BlobLoader(FullBatchLoader):
    """Separable 10-class gaussian blobs (the test_znicz_mlp stand-in),
    sized so minibatch 48 leaves short epoch tails in BOTH classes."""

    def __init__(self, workflow, n_train=400, n_valid=100, dim=64,
                 **kwargs):
        self._cfg = (n_train, n_valid, dim)
        super(BlobLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        n_train, n_valid, dim = self._cfg
        rng = numpy.random.default_rng(42)
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(10), total // 10 + 1)[:total]
        centers = rng.standard_normal((10, dim)) * 3.0
        data = centers[labels] + rng.standard_normal((total, dim)) * 0.7
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels = list(int(x) for x in labels)
        self.class_lengths[:] = [0, n_valid, n_train]


def _layers(hidden=32, lr=0.05):
    return [
        {"type": "all2all_tanh", "->": {"output_sample_shape": hidden},
         "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
    ]


def build(device, max_epochs=3, minibatch_size=48, seed=5, **loader_kw):
    prng.seed_all(seed)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size, **loader_kw),
        layers=_layers(),
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": 10 ** 6})
    wf.launcher = DummyLauncher()
    wf.initialize(device=device)
    return wf


@pytest.fixture
def stitch_config():
    """Snapshot/restore the engine knobs every test touches."""
    saved = (root.common.engine.get("stitch", "on"),
             root.common.engine.get("metrics_every", 0))
    yield root.common.engine
    root.common.engine.stitch = saved[0]
    root.common.engine.metrics_every = saved[1]


def _params(wf):
    """Every trained buffer: weights AND biases AND momentum state —
    misrouted per-stage hyper-parameters (e.g. a bias lr reading
    another layer's slot) must not hide behind weights-only checks."""
    out = []
    for fwd in wf.forwards:
        fwd.weights.map_read()
        out.append(numpy.array(fwd.weights.mem))
        fwd.bias.map_read()
        out.append(numpy.array(fwd.bias.mem))
    for gd in wf.gds:
        gd.gradient_weights.map_read()
        out.append(numpy.array(gd.gradient_weights.mem))
        gd.gradient_bias.map_read()
        out.append(numpy.array(gd.gradient_bias.mem))
    return out


# -- construction -----------------------------------------------------------

def test_segments_cover_forward_and_gd_chains(stitch_config):
    wf = build(CPUDevice())
    report = wf.stitch_report()
    assert report["enabled"]
    # exactly two segments: [loader, forwards..., evaluator] (the
    # device-resident input pipeline heads the first program) and
    # [gd chain]; decision / plumbing stay barriers
    assert len(report["segments"]) == 2
    fwd_names = [wf.loader.name] + [u.name for u in wf.forwards] \
        + [wf.evaluator.name]
    gd_names = [u.name for u in wf.gds]
    assert report["segments"][0] == fwd_names
    assert report["segments"][1] == gd_names
    assert report["loader_headed"] == [True, False]
    flat = [n for names in report["segments"] for n in names]
    assert wf.decision.name not in flat
    # gd members share the head's TRAIN skip gate (the eligibility rule)
    head_gate = wf.gds[0].gate_skip
    assert all(gd.gate_skip is head_gate for gd in wf.gds)


def test_loader_stays_barrier_under_host_mode(stitch_config):
    """engine.loader=host restores the PR 3 segment shape: the loader
    drops out of the first program and serves host-side."""
    saved = root.common.engine.get("loader", "auto")
    root.common.engine.loader = "host"
    try:
        wf = build(CPUDevice())
        report = wf.stitch_report()
        assert len(report["segments"]) == 2
        assert report["segments"][0][0] == wf.forwards[0].name
        assert report["loader_headed"] == [False, False]
        wf.run()
        assert wf.stopped
        assert wf.stitch_report()["dispatches"] > 0
    finally:
        root.common.engine.loader = saved


def test_stitch_on_flip_after_off_initialize_engages(stitch_config):
    """The switch is honored per run in BOTH directions: initialize
    under off, flip on, run — segments build once and engage."""
    stitch_config.stitch = "off"
    wf = build(CPUDevice(), max_epochs=2)
    assert wf.stitch_report()["segments"] == []
    stitch_config.stitch = "on"
    wf.run()
    assert len(wf.stitch_report()["segments"]) == 2
    assert wf.stitch_report()["dispatches"] > 0


def test_interpret_device_builds_no_segments(stitch_config):
    wf = build(NumpyDevice())
    assert wf.stitch_report()["segments"] == []
    wf.run()        # the plain path still trains to completion
    assert wf.stopped


def test_stitch_off_restores_per_unit_path(stitch_config, monkeypatch):
    stitch_config.stitch = "off"
    wf = build(CPUDevice(), max_epochs=2)
    assert wf.stitch_report()["segments"] == []
    calls = {"fwd": 0}
    from veles_tpu.znicz.all2all import All2All
    orig = All2All.tpu_run

    def counting(self):
        calls["fwd"] += 1
        return orig(self)

    monkeypatch.setattr(All2All, "tpu_run", counting)
    wf.run()
    assert wf.stopped
    assert calls["fwd"] > 0     # the seed per-unit dispatch path ran


# -- dispatch counts --------------------------------------------------------

def test_dispatches_are_per_segment_not_per_unit(stitch_config,
                                                 monkeypatch):
    """Per minibatch the scheduler launches O(segments) programs: ONE
    for the forward+evaluator chain (every minibatch) and ONE for the
    gd chain (TRAIN minibatches only — the Decision barrier and the
    shared skip gate are untouched); the stitched units' own per-unit
    programs never run."""
    wf = build(CPUDevice(), max_epochs=2)
    from veles_tpu.loader.base import TRAIN
    from veles_tpu.znicz.all2all import All2All
    from veles_tpu.znicz.evaluator import EvaluatorSoftmax
    from veles_tpu.znicz.gd import GradientDescent
    for klass in (All2All, EvaluatorSoftmax, GradientDescent):
        monkeypatch.setattr(
            klass, "tpu_run",
            lambda self: pytest.fail(
                "%s.tpu_run dispatched per-unit during a stitched "
                "run" % type(self).__name__))
    served = {"total": 0, "train": 0}
    orig_serve = type(wf.loader).serve_next_minibatch

    def counting_serve(self, consumer, **kwargs):
        orig_serve(self, consumer, **kwargs)
        served["total"] += 1
        if int(self.minibatch_class) == TRAIN:
            served["train"] += 1

    monkeypatch.setattr(type(wf.loader), "serve_next_minibatch",
                        counting_serve)
    wf.run()
    assert wf.stopped
    fwd_seg, gd_seg = wf._stitch_segments_
    assert served["total"] > 0 and served["train"] > 0
    assert fwd_seg.dispatches == served["total"]
    assert gd_seg.dispatches == served["train"]
    assert wf.stitch_report()["dispatches"] == \
        served["total"] + served["train"]


# -- numerical parity -------------------------------------------------------

#: deliberately DISTINCT hyper-parameters per layer and per bias: a
#: stitched stage reading a neighbour stage's (or its weight slot's)
#: scalar cannot alias into a passing run
_ASYMMETRIC_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
     "<-": {"learning_rate": 0.05, "learning_rate_bias": 0.02,
            "gradient_moment": 0.9, "gradient_moment_bias": 0.5,
            "weights_decay": 0.0005, "weights_decay_bias": 0.002}},
    {"type": "softmax", "->": {"output_sample_shape": 10},
     "<-": {"learning_rate": 0.01, "learning_rate_bias": 0.07,
            "gradient_moment": 0.3, "gradient_moment_bias": 0.8,
            "weights_decay": 0.003, "weights_decay_bias": 0.0001}},
]


def test_stitched_matches_unstitched_weights_and_metrics(stitch_config):
    def build_asym():
        prng.seed_all(5)
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: BlobLoader(w, minibatch_size=48),
            layers=[{**s} for s in _ASYMMETRIC_LAYERS],
            decision_config={"max_epochs": 3,
                             "fail_iterations": 10 ** 6})
        wf.launcher = DummyLauncher()
        wf.initialize(device=CPUDevice())
        return wf

    stitch_config.stitch = "on"
    wf_on = build_asym()
    wf_on.run()
    stitch_config.stitch = "off"
    wf_off = build_asym()
    wf_off.run()
    assert wf_on.stitch_report()["dispatches"] > 0
    assert wf_off.stitch_report()["dispatches"] == 0
    for w_on, w_off in zip(_params(wf_on), _params(wf_off)):
        numpy.testing.assert_allclose(w_on, w_off, atol=5e-3)
    # epoch metrics flushed to plain host floats, and they agree
    for cls in (1, 2):
        a = wf_on.decision.epoch_n_err_pt[cls]
        b = wf_off.decision.epoch_n_err_pt[cls]
        assert isinstance(a, float) and abs(a - b) < 1.0
    assert abs(wf_on.decision.best_n_err_pt
               - wf_off.decision.best_n_err_pt) < 1.0
    # the stitched confusion matrix (device-accumulated) matches up to
    # argmax boundary flips from float drift (<2% of samples moved)
    cm_on = numpy.array(wf_on.evaluator.confusion_matrix.mem)
    cm_off = numpy.array(wf_off.evaluator.confusion_matrix.mem)
    assert cm_on.sum() == cm_off.sum() > 0
    assert numpy.abs(cm_on - cm_off).sum() <= 0.02 * cm_on.sum()


def test_deferred_metrics_are_device_scalars_until_flush(stitch_config):
    wf = build(CPUDevice(), max_epochs=2)
    wf.run()
    # per-minibatch metric stayed a device scalar (no per-step float())
    assert not isinstance(wf.evaluator.n_err, (int, float))
    assert hasattr(wf.evaluator.n_err, "dtype")
    # ...but every epoch close flushed to plain host numbers (the
    # close also resets the bucket to int 0), nothing left pending
    assert all(isinstance(v, (int, float))
               for v in wf.decision.epoch_n_err)
    assert all(not p for p in wf.decision._pending_metrics_)


def test_metrics_every_cadence_matches_boundary_flush(stitch_config):
    stitch_config.metrics_every = 1      # flush every minibatch
    wf_k1 = build(CPUDevice(), max_epochs=3)
    wf_k1.run()
    stitch_config.metrics_every = 0      # epoch-boundary only
    wf_k0 = build(CPUDevice(), max_epochs=3)
    wf_k0.run()
    assert wf_k1.decision.best_n_err_pt == \
        pytest.approx(wf_k0.decision.best_n_err_pt, abs=1e-9)


# -- gate semantics regressions ---------------------------------------------

def test_repeater_refires_stitched_loop_to_max_epochs(stitch_config):
    wf = build(CPUDevice(), max_epochs=4)
    wf.run()
    assert wf.stopped
    # decision completes when epoch_number+1 reaches max_epochs, so the
    # Repeater's back edge re-fired the stitched loop through 3 full
    # epoch wraps (the seed loop semantics, unchanged)
    assert wf.loader.epoch_number == 3
    assert bool(wf.decision.complete)


def test_manual_unit_run_keeps_per_unit_semantics(stitch_config):
    """Direct unit.run() calls (how tests and debuggers drive the
    graph) bypass segments entirely — the fuzz/parity harnesses keep
    their exact seed semantics."""
    wf = build(CPUDevice(), max_epochs=1)
    wf.loader.run()
    from veles_tpu.loader.base import TRAIN
    while int(wf.loader.minibatch_class) != TRAIN:
        wf.loader.run()
    for fwd in wf.forwards:
        fwd.run()
    wf.evaluator.run()
    before = numpy.array(wf.forwards[1].weights.mem)
    wf.gds[0].run()
    wf.forwards[1].weights.map_read()
    after = numpy.array(wf.forwards[1].weights.mem)
    assert not numpy.allclose(before, after)
    assert wf._stitch_segments_[0].dispatches == 0   # never engaged


def test_mse_evaluator_device_matches_host():
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.evaluator import EvaluatorMSE
    rng = numpy.random.default_rng(4)
    out = rng.standard_normal((8, 3)).astype(numpy.float32)
    target = rng.standard_normal((8, 3)).astype(numpy.float32)

    def run(device, batch):
        wf = DummyWorkflow()
        ev = EvaluatorMSE(wf)
        ev.output = Vector(out.copy())
        ev.target = Vector(target.copy())
        ev.err_output = Vector(numpy.zeros((8, 3), numpy.float32))
        ev.batch_size = batch
        for vec in (ev.output, ev.target, ev.err_output):
            vec.initialize(device)
        ev.device = device
        ev.run()
        return numpy.array(ev.err_output.mem), float(ev.mse)

    for batch in (8, 5):        # full and short (masked tail) batches
        err_host, mse_host = run(NumpyDevice(), batch)
        err_dev, mse_dev = run(CPUDevice(), batch)
        numpy.testing.assert_allclose(err_dev, err_host, atol=1e-6)
        assert mse_dev == pytest.approx(mse_host, abs=1e-5)

    # unnormalized-activation regime: err² overflows float32 — the host
    # squares in f64, the device rescales per row; both must agree
    out *= numpy.float32(1e22)
    target *= 0.0
    err_host, mse_host = run(NumpyDevice(), 8)
    err_dev, mse_dev = run(CPUDevice(), 8)
    assert numpy.isfinite(mse_dev)
    assert mse_dev == pytest.approx(mse_host, rel=1e-5)


def test_softmax_evaluator_device_matches_host():
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.evaluator import EvaluatorSoftmax
    rng = numpy.random.default_rng(7)
    logits = rng.standard_normal((6, 4)).astype(numpy.float32)
    sm = numpy.exp(logits) / numpy.exp(logits).sum(1, keepdims=True)
    labels = numpy.array([0, 3, 2, -1, 1, -1], numpy.int32)
    max_idx = logits.argmax(1).astype(numpy.int32)

    def run(device):
        wf = DummyWorkflow()
        ev = EvaluatorSoftmax(wf)
        ev.output = Vector(sm.copy())
        ev.labels = Vector(labels.copy())
        ev.max_idx = Vector(max_idx.copy())
        ev.err_output = Vector(numpy.zeros((6, 4), numpy.float32))
        ev.confusion_matrix.reset(numpy.zeros((4, 4), numpy.int64))
        ev.batch_size = 6
        for vec in (ev.output, ev.labels, ev.max_idx, ev.err_output,
                    ev.confusion_matrix):
            vec.initialize(device)
        ev.device = device
        ev.run()
        return (numpy.array(ev.err_output.mem), int(ev.n_err),
                float(ev.loss), numpy.array(ev.confusion_matrix.mem))

    err_h, n_h, loss_h, cm_h = run(NumpyDevice())
    err_d, n_d, loss_d, cm_d = run(CPUDevice())
    numpy.testing.assert_allclose(err_d, err_h, atol=1e-6)
    assert n_d == n_h
    assert loss_d == pytest.approx(loss_h, abs=1e-6)
    numpy.testing.assert_array_equal(cm_d, cm_h)


def test_job_layer_slave_trains_through_segments(stitch_config):
    """The elastic job layer — the path the eager chain exists for —
    dispatches O(segments) programs per job: slave-mode graph surgery
    re-stitches, the JobClient handshake reports it, and the master
    still converges on the merged deltas."""
    from veles_tpu.parallel.jobs import JobClient, JobServer

    def mk(device, **flags):
        prng.seed_all(1234)
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: BlobLoader(w, minibatch_size=50),
            layers=_layers(),
            decision_config={"max_epochs": 3,
                             "fail_iterations": 10 ** 6},
            launcher=DummyLauncher(**flags))
        wf.initialize(device=device)
        return wf

    master = mk(NumpyDevice(), is_master=True)
    slave = mk(CPUDevice(), is_slave=True)
    server = JobServer(master).start()
    try:
        client = JobClient(slave, server.endpoint)
        client.handshake()
        assert len(slave.stitch_report()["segments"]) == 2
        assert client.run()
        client.close()
    finally:
        server.stop()
    assert client.jobs_done > 0
    assert slave.stitch_report()["dispatches"] > client.jobs_done
    assert master.decision.best_n_err_pt < 10.0


# -- throughput floor -------------------------------------------------------

@pytest.mark.slow
def test_stitched_throughput_floor_cpu(stitch_config):
    """In-process CPU JAX: a dispatch-bound eager config (tiny layers,
    batch 16) must run ≥ 1.5× faster stitched than unstitched —
    locally measured ~2.5×; the floor leaves CI headroom."""

    def measure(stitch):
        stitch_config.stitch = stitch
        prng.seed_all(5)
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: BlobLoader(
                w, n_train=640, n_valid=160, dim=32,
                minibatch_size=16),
            layers=_layers(hidden=16),
            decision_config={"max_epochs": 2,
                             "fail_iterations": 10 ** 6})
        wf.launcher = DummyLauncher()
        wf.initialize(device=CPUDevice())
        wf.run()                          # warm: compiles included
        wf.decision.complete <<= False
        wf.decision.max_epochs = 8
        tic = time.perf_counter()
        wf.run()                          # six warm epochs
        elapsed = time.perf_counter() - tic
        assert wf.stopped
        return elapsed

    t_on = measure("on")
    t_off = measure("off")
    assert t_off / t_on >= 1.5, \
        "stitched %.3fs vs unstitched %.3fs (%.2fx < 1.5x floor)" % (
            t_on, t_off, t_off / t_on)
