"""Recurrent family (znicz/rnn.py): golden LSTM math vs a hand-rolled
numpy cell, scan shapes, VJP-backward training through both the fused
lowering and the eager StandardWorkflow graph — the family the
reference left 'in progress' (``manualrst_veles_algorithms.rst``)."""

import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.backends import CPUDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.memory import Vector
from veles_tpu.znicz.rnn import LSTM, SimpleRNN


def _sigmoid(z):
    return 1.0 / (1.0 + numpy.exp(-z))


def test_lstm_pure_golden_vs_numpy():
    rng = numpy.random.default_rng(0)
    b, t, d, h = 3, 5, 4, 6
    x = rng.standard_normal((b, t, d)).astype(numpy.float32)
    w = (rng.standard_normal((d + h, 4 * h)) * 0.3).astype(numpy.float32)
    bias = (rng.standard_normal(4 * h) * 0.1).astype(numpy.float32)

    out = numpy.asarray(LSTM.pure(
        {"w": jnp.asarray(w), "b": jnp.asarray(bias)}, jnp.asarray(x),
        hidden_units=h))

    hh = numpy.zeros((b, h), numpy.float32)
    cc = numpy.zeros((b, h), numpy.float32)
    for step in range(t):
        z = numpy.concatenate([x[:, step], hh], axis=1) @ w + bias
        i, f, g, o = numpy.split(z, 4, axis=1)
        cc = _sigmoid(f) * cc + _sigmoid(i) * numpy.tanh(g)
        hh = _sigmoid(o) * numpy.tanh(cc)
        numpy.testing.assert_allclose(out[:, step], hh, atol=1e-5)


def test_lstm_shapes_and_last_only():
    rng = numpy.random.default_rng(1)
    x = rng.standard_normal((2, 7, 3)).astype(numpy.float32)
    w = rng.standard_normal((3 + 5, 20)).astype(numpy.float32) * 0.2
    p = {"w": jnp.asarray(w)}
    full = LSTM.pure(p, jnp.asarray(x), hidden_units=5)
    last = LSTM.pure(p, jnp.asarray(x), hidden_units=5, last_only=True)
    assert full.shape == (2, 7, 5)
    assert last.shape == (2, 5)
    numpy.testing.assert_allclose(numpy.asarray(full[:, -1]),
                                  numpy.asarray(last), atol=1e-6)


def test_simple_rnn_golden():
    rng = numpy.random.default_rng(2)
    b, t, d, h = 2, 4, 3, 5
    x = rng.standard_normal((b, t, d)).astype(numpy.float32)
    w = (rng.standard_normal((d + h, h)) * 0.3).astype(numpy.float32)
    out = numpy.asarray(SimpleRNN.pure(
        {"w": jnp.asarray(w)}, jnp.asarray(x), hidden_units=h))
    hh = numpy.zeros((b, h), numpy.float32)
    for step in range(t):
        hh = numpy.tanh(numpy.concatenate([x[:, step], hh], axis=1) @ w)
        numpy.testing.assert_allclose(out[:, step], hh, atol=1e-5)


def test_lstm_unit_initialize_and_forget_bias():
    wf = DummyWorkflow()
    unit = LSTM(wf, hidden_units=8, last_only=True)
    unit.input = Vector(numpy.zeros((4, 6, 10), numpy.float32))
    unit.initialize(device=None)
    assert unit.weights.mem.shape == (18, 32)
    assert unit.bias.mem.shape == (32,)
    # forget-gate slice starts at +1 (remember by default)
    assert numpy.allclose(unit.bias.mem[8:16], 1.0)
    assert unit.output.shape == (4, 8)


def test_lstm_fused_training_learns():
    """Fused lowering: LSTM(last_only) -> softmax learns a sequence
    task (which quarter of classes the FIRST timestep points at —
    requires carrying state across all steps)."""
    import jax

    from veles_tpu import prng
    from veles_tpu.znicz.fused_graph import lower_specs

    prng.seed_all(1234)
    layers = [
        {"type": "lstm", "->": {"hidden_units": 32, "last_only": True},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
    ]
    params, step_fn, _eval, _apply = lower_specs(layers, (6, 8))
    rng = numpy.random.default_rng(0)
    x = rng.standard_normal((256, 6, 8)).astype(numpy.float32)
    labels = rng.integers(0, 4, 256).astype(numpy.int32)
    # plant the signal at t=0 only: the scan must carry it to the end
    x[numpy.arange(256), 0, labels.astype(int)] += 3.0
    step = jax.jit(step_fn)
    first = None
    for _ in range(60):
        params, metrics = step(params, x, labels)
        if first is None:
            first = float(metrics["loss"])
    final_err = int(metrics["n_err"]) / 256.0
    assert float(metrics["loss"]) < first * 0.5
    assert final_err < 0.2


def test_lstm_standard_workflow_trains():
    """Eager graph path: StandardWorkflow links lstm -> softmax with
    the generic VJP backward (GD_PAIRS['lstm'])."""
    from veles_tpu.samples import mnist_rnn

    wf = mnist_rnn.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=50,
        layers=[
            {"type": "lstm", "->": {"hidden_units": 16,
                                    "last_only": True},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        ])
    wf.run()
    stats = wf.gather_results()
    # learned *something* beyond chance on the synthetic set
    assert stats["best_validation_error_pt"] < 85.0
