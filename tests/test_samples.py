"""Model-zoo sample workflows (shrunk configs, synthetic data)."""

import numpy
import pytest

from veles_tpu.backends import CPUDevice, NumpyDevice

# the synthetic-data pin lives in conftest.py (_pin_synthetic_data,
# suite-wide autouse) so every short sample run stays on the stand-ins


def test_mnist_sample_trains():
    from veles_tpu import prng
    from veles_tpu.samples import mnist
    prng.seed_all(1)
    wf = mnist.create_workflow(device=NumpyDevice(), max_epochs=2,
                               minibatch_size=500)
    wf.run()
    results = wf.gather_results()
    # measured 25.0 % on the synthetic stand-in at this seed/config —
    # the bar tracks actual achievement, not "anything beats chance"
    # (real-data parity gates live in test_accuracy_parity.py)
    assert results["best_validation_error_pt"] < 35.0


def test_mnist_ae_sample_trains():
    from veles_tpu import prng
    from veles_tpu.samples import mnist_ae
    prng.seed_all(2)
    wf = mnist_ae.create_workflow(device=NumpyDevice(), max_epochs=2,
                                  minibatch_size=500, hidden=32)
    wf.run()
    # measured 0.109 rmse on the synthetic stand-in (real-data 0.5478
    # parity gate lives in test_accuracy_parity.py)
    assert float(wf.decision.best_mse) < 0.2


def test_rbm_pretraining_reduces_reconstruction_error():
    from veles_tpu import prng
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.rbm import RBMTrainer
    prng.seed_all(3)
    rng = numpy.random.default_rng(0)
    # binary-ish structured data
    base = (rng.random((400, 64)) < 0.3).astype(numpy.float32)
    wf = DummyWorkflow()
    trainer = RBMTrainer(wf, n_hidden=32, learning_rate=0.5)
    trainer.input = Vector(base[:100])
    trainer.initialize(device=None)
    first = None
    for epoch in range(8):
        for start in range(0, 400, 100):
            trainer.input.reset(base[start:start + 100])
            trainer.run()
            if first is None:
                first = trainer.recon_error
    assert trainer.recon_error < first
    features = trainer.transform(base[:10])
    assert features.shape == (10, 32)
    assert ((features >= 0) & (features <= 1)).all()


def test_kohonen_sample_organizes():
    from veles_tpu import prng
    from veles_tpu.samples import kohonen
    prng.seed_all(4)
    wf = kohonen.create_workflow(device=CPUDevice(), shape=(6, 6),
                                 max_epochs=6)
    # untrained quantization error for comparison
    wf.loader.original_data.map_read()
    data = wf.loader.original_data.mem
    before = wf.trainer.quantization_error(data)
    wf.run()
    after = wf.get_metric_values()["quantization_error"]
    assert after < before * 0.5, (before, after)


def test_cifar_sample_builds_and_steps():
    """Full caffe-style stack builds and completes one epoch (synthetic
    data, shrunk images would change shapes — use tiny epoch count)."""
    from veles_tpu import prng
    from veles_tpu.samples import cifar10
    prng.seed_all(5)
    wf = cifar10.create_workflow(device=CPUDevice(), max_epochs=2,
                                 minibatch_size=250)
    assert len(wf.forwards) == 8   # 3 conv + 3 pool + fc + softmax
    wf.run()
    assert wf.stopped
    # measured 90.3 % train error after 2 synthetic epochs (the deep
    # stack is just starting to move) — bar requires genuine learning,
    # not mere accounting; real-data gate in test_accuracy_parity.py
    assert wf.decision.epoch_n_err_pt[2] < 93.0


def test_alexnet_fused_builds_and_steps():
    """Shrunk-input AlexNet lowers to one fused step and trains."""
    import jax
    from veles_tpu import prng
    from veles_tpu.samples import alexnet
    prng.seed_all(6)
    shrunk = [{**spec} for spec in alexnet.LAYERS]
    # shrink fc widths and classes for the 8-device CPU mesh
    shrunk[-3]["->"] = {**shrunk[-3]["->"], "output_sample_shape": 64}
    shrunk[-1]["->"] = {**shrunk[-1]["->"], "output_sample_shape": 10}
    params, step, eval_fn, apply_fn = alexnet.build_fused(
        layers=shrunk, input_shape=(67, 67, 3))
    x, labels = alexnet.synthetic_imagenet_batch(8)
    import numpy as np
    x = np.ascontiguousarray(x[:, :67, :67, :])
    labels = labels % 10
    params, metrics = step(params, x, labels)
    jax.block_until_ready(params)
    assert int(metrics["n_err"]) <= 8
    out = apply_fn(params, x)
    assert out.shape == (8, 10)


def test_alexnet_fused_data_parallel_mesh():
    from veles_tpu import prng
    from veles_tpu.parallel import make_mesh
    from veles_tpu.samples import alexnet
    prng.seed_all(7)
    layers = [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 4, "kx": 3, "ky": 3, "sliding": (2, 2)},
         "<-": {"learning_rate": 0.01}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "softmax", "->": {"output_sample_shape": 5},
         "<-": {"learning_rate": 0.01}},
    ]
    mesh = make_mesh({"data": 8})
    params, step, _eval, _apply = alexnet.build_fused(
        mesh=mesh, layers=layers, input_shape=(16, 16, 3))
    x, labels = alexnet.synthetic_imagenet_batch(16)
    x = numpy.ascontiguousarray(x[:, :16, :16, :])
    labels = labels % 5
    params, metrics = step(params, x, labels)
    assert 0 <= int(metrics["n_err"]) <= 16


def test_stl10_short_training():
    """STL-10 conv workflow (ref 35.10% gate) trains on synthetic
    stand-ins: error must drop below chance."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import stl10
    wf = stl10.create_workflow(device=CPUDevice(), max_epochs=2,
                               minibatch_size=50)
    wf.run()
    err = wf.decision.epoch_n_err_pt[1]
    assert err < 90.0   # chance = 90% on 10 classes


def test_mnist_conv_short_training():
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist_conv
    wf = mnist_conv.create_workflow(device=CPUDevice(), max_epochs=2,
                                    minibatch_size=100)
    wf.run()
    err = wf.decision.epoch_n_err_pt[1]
    assert err < 90.0


def test_mnist_conv_ae_short_training():
    """Conv autoencoder (conv + deconv, MSE) reconstructs better than
    the zero predictor after a couple of epochs."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist_ae
    wf = mnist_ae.create_workflow(device=CPUDevice(), max_epochs=2,
                                  minibatch_size=100, conv=True)
    wf.run()
    # the 0.5478-RMSE reference gate applies to full training on real
    # MNIST; two synthetic epochs just prove the conv+deconv MSE path
    # trains to something sane
    rmse = float(wf.decision.best_mse)
    assert 0.0 < rmse < 1.0


def test_real_mnist_idx_path_parses(tmp_path):
    """The real-data branch (gating test_accuracy_parity.py) reads the
    IDX layout correctly: magic dims, gz variants, [0,1] scaling."""
    import gzip
    import struct

    from veles_tpu.config import root
    from veles_tpu.samples import datasets

    base = tmp_path / "mnist"
    base.mkdir()

    def write_idx(name, arr, compress=False):
        payload = struct.pack(">I", (0x08 << 8) | arr.ndim
                              ) + struct.pack(
            ">" + "I" * arr.ndim, *arr.shape) + arr.tobytes()
        path = base / (name + (".gz" if compress else ""))
        (gzip.open if compress else open)(str(path), "wb").write(payload)

    rng = numpy.random.default_rng(0)
    tr_x = rng.integers(0, 256, (6, 28, 28)).astype(numpy.uint8)
    tr_y = rng.integers(0, 10, 6).astype(numpy.uint8)
    te_x = rng.integers(0, 256, (4, 28, 28)).astype(numpy.uint8)
    te_y = rng.integers(0, 10, 4).astype(numpy.uint8)
    write_idx("train-images-idx3-ubyte", tr_x)
    write_idx("train-labels-idx1-ubyte", tr_y, compress=True)  # mixed
    write_idx("t10k-images-idx3-ubyte", te_x)
    write_idx("t10k-labels-idx1-ubyte", te_y)

    saved = root.common.dirs.get("datasets", ".")
    root.common.dirs.datasets = str(tmp_path)
    try:
        assert datasets.mnist_available()
        x1, y1, x2, y2, real = datasets.load_mnist()
        assert real
        numpy.testing.assert_allclose(x1, tr_x / 255.0)
        numpy.testing.assert_array_equal(y1, tr_y)
        numpy.testing.assert_allclose(x2, te_x / 255.0)
        numpy.testing.assert_array_equal(y2, te_y)
        assert not datasets.cifar10_available()
    finally:
        root.common.dirs.datasets = saved


def test_grouped_conv_unit_and_validation():
    """Conv(grouping=g) initializes (kh, kw, C/g, K) weights and
    rejects indivisible configurations."""
    import pytest

    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.conv import Conv

    wf = DummyWorkflow()
    unit = Conv(wf, n_kernels=6, kx=3, ky=3, grouping=2)
    unit.input = Vector(numpy.zeros((2, 8, 8, 8), numpy.float32))
    unit.initialize(device=None)
    assert unit.weights.mem.shape == (3, 3, 4, 6)
    assert unit.output_shape_for((2, 8, 8, 8)) == (2, 6, 6, 6)

    bad = Conv(wf, n_kernels=6, kx=3, ky=3, grouping=4)
    bad.input = Vector(numpy.zeros((2, 8, 8, 8), numpy.float32))
    with pytest.raises(ValueError, match="grouping"):
        bad.initialize(device=None)


def test_vgg_sample_builds_and_steps():
    """VGG-A (the reference's second listed model): the real 11-layer
    stack lowers, steps, and evaluates — at 32x32 so five pools reduce
    to 1x1 without ImageNet-scale CPU cost."""
    import jax

    from veles_tpu import prng
    from veles_tpu.samples import vgg

    prng.seed_all(31)
    params, step, evalf, apply_fn = vgg.build_fused(
        input_shape=(32, 32, 3), compute_dtype="bfloat16")
    assert len(params) == len(vgg.LAYERS)
    rng = numpy.random.default_rng(0)
    x = rng.standard_normal((4, 32, 32, 3)).astype(numpy.float32)
    labels = (numpy.arange(4) % 1000).astype(numpy.int32)
    params, metrics = step(params, x, labels)
    jax.block_until_ready(metrics["loss"])
    assert numpy.isfinite(float(metrics["loss"]))
    ev = evalf(params, x, labels)
    assert 0 <= int(ev["n_err"]) <= 4
    # fc6 sees the 1x1x512 bottleneck: weights (512, 4096)
    fc6 = [s for s in params if s.get("w") is not None][-3]
    assert fc6["w"].shape == (512, 4096)
