"""Multi-host SPMD (parallel/multihost.py): two REAL processes join one
JAX runtime over the distributed coordinator, build a single global
mesh, feed host-local loader shards, and run the fused DP train step —
the DCN-scale analogue of the reference's ~100-node master–slave
(``manualrst_veles_distributed_training.rst:4``), with the gradient
all-reduce crossing process boundaries inside XLA instead of riding
pickled ZMQ payloads."""

import json
import os
import socket
import subprocess
import sys

import numpy

WORKER = r"""
import json, os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

from veles_tpu import prng
from veles_tpu.parallel import data_parallel, make_mesh, multihost
from veles_tpu.parallel.mesh import shard_batch
from veles_tpu.znicz.fused import init_mlp_params, make_train_step

multihost.initialize()          # VELES_* env vars from the parent
pid = multihost.process_index()

mesh = make_mesh({"data": -1})  # global: 2 procs x 4 devices = 8
prng.seed_all(1234)
layers = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
     "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 8},
     "<-": {"learning_rate": 0.01}},
]
params = init_mlp_params(32, layers)
step = data_parallel(make_train_step(layers), mesh, params)

# the GLOBAL batch: every process materializes the full array for the
# expectation check, then feeds ONLY its host_shard_range rows
rng_all = __import__("numpy").random.default_rng(0)
numpy_ = __import__("numpy")
gx = rng_all.standard_normal((32, 32)).astype(numpy_.float32)
glabels = (numpy_.arange(32) % 8).astype(numpy_.int32)
start, stop = multihost.host_shard_range(32)
x = multihost.from_host_local(gx[start:stop], shard_batch(mesh))
labels = multihost.from_host_local(
    glabels[start:stop], shard_batch(mesh, ndim=1))

params, metrics = step(params, x, labels)
jax.block_until_ready(params)
result = json.dumps({
    "pid": pid,
    "n_global_devices": len(jax.devices()),
    "n_local_devices": len(jax.local_devices()),
    "process_count": multihost.process_count(),
    "is_coordinator": multihost.is_coordinator(),
    "shard": [start, stop],
    "loss": float(metrics["loss"]),
    "n_err": int(metrics["n_err"]),
})
out_dir = os.environ.get("VELES_OUT_DIR")
if out_dir:
    # ranks launched by spmd_launch share one stdout pipe where
    # concurrent lines can interleave; files are per-rank
    with open(os.path.join(out_dir, "rank%d.json" % pid), "w") as f:
        f.write(result + "\n")
print(result)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_fused_dp_step(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.update({
            "VELES_COORDINATOR": "127.0.0.1:%d" % port,
            "VELES_NUM_PROCS": "2",
            "VELES_PROC_ID": str(pid),
            "PYTHONPATH": repo_root + os.pathsep
            + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-2000:]
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    for o in outs:
        assert o["n_global_devices"] == 8       # one mesh spans hosts
        assert o["n_local_devices"] == 4
        assert o["process_count"] == 2
    assert by_pid[0]["is_coordinator"] and not by_pid[1]["is_coordinator"]
    # contiguous non-overlapping host shards covering the global batch
    assert by_pid[0]["shard"] == [0, 16] and by_pid[1]["shard"] == [16, 32]
    # the all-reduced loss/metrics are REPLICATED: every process sees
    # the same global number (the step consumed rows from both hosts)
    assert by_pid[0]["loss"] == by_pid[1]["loss"]
    assert by_pid[0]["n_err"] == by_pid[1]["n_err"]
    assert 0 <= by_pid[0]["n_err"] <= 32
    assert numpy.isfinite(by_pid[0]["loss"])


def test_spmd_launch_boots_local_fleet(tmp_path):
    """scripts/spmd_launch runs the same command on every node with
    rank env vars set (``sh -c`` stands in for ssh, as in the slave
    bootstrap tests) and the booted processes form one runtime."""
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                         "")
    env["VELES_OUT_DIR"] = str(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu.scripts.spmd_launch",
         "-n", "localhost x2",
         "--coordinator", "127.0.0.1:%d" % port,
         "--launch-transform", "sh -c",
         "--", sys.executable, str(script)],
        env=env, cwd=repo_root, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    outs = [json.loads((tmp_path / ("rank%d.json" % pid)).read_text())
            for pid in range(2)]
    assert len(outs) == 2
    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    assert all(o["n_global_devices"] == 8 for o in outs)
    assert by_pid[0]["loss"] == by_pid[1]["loss"]
    # both ranks were announced on stderr with their target host
    assert "rank 0 on localhost" in proc.stderr
    assert "rank 1 on localhost" in proc.stderr


# -- in-process edge cases (the process_double test double) ------------------

def test_host_shard_range_even_and_uneven():
    from veles_tpu.parallel import multihost
    from veles_tpu.parallel.multihost import MultiHostShardError
    with multihost.process_double(3) as dbl:
        # even split: typed refusal when the batch does not divide
        import pytest
        with pytest.raises(MultiHostShardError):
            multihost.host_shard_range(10)
        # uneven: the remainder lands on the LAST rank only
        spans = []
        for rank in range(3):
            with dbl.rank(rank):
                spans.append(multihost.host_shard_range(
                    10, allow_uneven=True))
        assert spans == [(0, 3), (3, 6), (6, 10)]
        # spans tile [0, 10) with no overlap
        assert spans[0][1] == spans[1][0] and spans[1][1] == spans[2][0]


def test_from_host_local_single_process_identity():
    """No coordinator, one process: from_host_local must be a plain
    identity placement — the transparent single-host fallback the
    pod-of-pods delegation contract rides on."""
    import jax

    from veles_tpu.parallel import multihost
    from veles_tpu.parallel.mesh import make_mesh, shard_batch
    assert not multihost.configured()
    mesh = make_mesh({"data": -1})
    local = numpy.arange(32, dtype=numpy.float32).reshape(16, 2)
    out = multihost.from_host_local(local, shard_batch(mesh))
    assert isinstance(out, jax.Array)
    assert out.shape == (16, 2)
    numpy.testing.assert_array_equal(numpy.asarray(out), local)


def test_from_host_local_typed_error_on_indivisible_axis():
    """The sharding's data axis must split evenly across processes —
    a typed MultiHostShardError (a ValueError subclass, so legacy
    handlers keep working)."""
    import pytest

    from veles_tpu.parallel import multihost
    from veles_tpu.parallel.multihost import MultiHostShardError
    from veles_tpu.parallel.mesh import make_mesh, shard_batch
    mesh = make_mesh({"data": -1})          # 8 shards; 8 % 3 != 0
    local = numpy.zeros((4, 2), numpy.float32)
    with multihost.process_double(3):
        with pytest.raises(MultiHostShardError) as err:
            multihost.from_host_local(local, shard_batch(mesh),
                                      global_shape=(12, 2))
        assert issubclass(err.type, ValueError)


def test_process_double_banks_shards_incrementally():
    """Sequential rank simulation: each rank's from_host_local banks
    its shard, the LAST rank's call returns the fully assembled
    global — the invariant the pod smoke's 2-process leg drives."""
    from veles_tpu.parallel import multihost
    from veles_tpu.parallel.mesh import make_mesh, shard_batch
    mesh = make_mesh({"data": -1})
    full = numpy.arange(64, dtype=numpy.float32).reshape(16, 4)
    with multihost.process_double(2) as dbl:
        assert multihost.configured()
        assert multihost.process_count() == 2
        with dbl.rank(0):
            assert multihost.is_coordinator()
            partial = multihost.from_host_local(
                full[:8], shard_batch(mesh), global_shape=(16, 4))
            # rank 1 has not contributed yet: its rows are zero-padded
            got = numpy.asarray(partial)
            numpy.testing.assert_array_equal(got[:8], full[:8])
            assert not got[8:].any()
        with dbl.rank(1):
            assert not multihost.is_coordinator()
            out = multihost.from_host_local(
                full[8:], shard_batch(mesh), global_shape=(16, 4))
            numpy.testing.assert_array_equal(numpy.asarray(out), full)
    assert not multihost.configured()


def test_process_double_does_not_nest_and_checks_rank():
    import pytest

    from veles_tpu.parallel import multihost
    with multihost.process_double(2) as dbl:
        with pytest.raises(RuntimeError):
            with multihost.process_double(2):
                pass
        with pytest.raises(ValueError):
            with dbl.rank(2):
                pass
    with pytest.raises(ValueError):
        multihost.process_double(0)
