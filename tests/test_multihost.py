"""Multi-host SPMD (parallel/multihost.py): two REAL processes join one
JAX runtime over the distributed coordinator, build a single global
mesh, feed host-local loader shards, and run the fused DP train step —
the DCN-scale analogue of the reference's ~100-node master–slave
(``manualrst_veles_distributed_training.rst:4``), with the gradient
all-reduce crossing process boundaries inside XLA instead of riding
pickled ZMQ payloads."""

import json
import os
import socket
import subprocess
import sys

import numpy

WORKER = r"""
import json, os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

from veles_tpu import prng
from veles_tpu.parallel import data_parallel, make_mesh, multihost
from veles_tpu.parallel.mesh import shard_batch
from veles_tpu.znicz.fused import init_mlp_params, make_train_step

multihost.initialize()          # VELES_* env vars from the parent
pid = multihost.process_index()

mesh = make_mesh({"data": -1})  # global: 2 procs x 4 devices = 8
prng.seed_all(1234)
layers = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
     "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 8},
     "<-": {"learning_rate": 0.01}},
]
params = init_mlp_params(32, layers)
step = data_parallel(make_train_step(layers), mesh, params)

# the GLOBAL batch: every process materializes the full array for the
# expectation check, then feeds ONLY its host_shard_range rows
rng_all = __import__("numpy").random.default_rng(0)
numpy_ = __import__("numpy")
gx = rng_all.standard_normal((32, 32)).astype(numpy_.float32)
glabels = (numpy_.arange(32) % 8).astype(numpy_.int32)
start, stop = multihost.host_shard_range(32)
x = multihost.from_host_local(gx[start:stop], shard_batch(mesh))
labels = multihost.from_host_local(
    glabels[start:stop], shard_batch(mesh, ndim=1))

params, metrics = step(params, x, labels)
jax.block_until_ready(params)
result = json.dumps({
    "pid": pid,
    "n_global_devices": len(jax.devices()),
    "n_local_devices": len(jax.local_devices()),
    "process_count": multihost.process_count(),
    "is_coordinator": multihost.is_coordinator(),
    "shard": [start, stop],
    "loss": float(metrics["loss"]),
    "n_err": int(metrics["n_err"]),
})
out_dir = os.environ.get("VELES_OUT_DIR")
if out_dir:
    # ranks launched by spmd_launch share one stdout pipe where
    # concurrent lines can interleave; files are per-rank
    with open(os.path.join(out_dir, "rank%d.json" % pid), "w") as f:
        f.write(result + "\n")
print(result)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_fused_dp_step(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.update({
            "VELES_COORDINATOR": "127.0.0.1:%d" % port,
            "VELES_NUM_PROCS": "2",
            "VELES_PROC_ID": str(pid),
            "PYTHONPATH": repo_root + os.pathsep
            + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-2000:]
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    for o in outs:
        assert o["n_global_devices"] == 8       # one mesh spans hosts
        assert o["n_local_devices"] == 4
        assert o["process_count"] == 2
    assert by_pid[0]["is_coordinator"] and not by_pid[1]["is_coordinator"]
    # contiguous non-overlapping host shards covering the global batch
    assert by_pid[0]["shard"] == [0, 16] and by_pid[1]["shard"] == [16, 32]
    # the all-reduced loss/metrics are REPLICATED: every process sees
    # the same global number (the step consumed rows from both hosts)
    assert by_pid[0]["loss"] == by_pid[1]["loss"]
    assert by_pid[0]["n_err"] == by_pid[1]["n_err"]
    assert 0 <= by_pid[0]["n_err"] <= 32
    assert numpy.isfinite(by_pid[0]["loss"])


def test_spmd_launch_boots_local_fleet(tmp_path):
    """scripts/spmd_launch runs the same command on every node with
    rank env vars set (``sh -c`` stands in for ssh, as in the slave
    bootstrap tests) and the booted processes form one runtime."""
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                         "")
    env["VELES_OUT_DIR"] = str(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu.scripts.spmd_launch",
         "-n", "localhost x2",
         "--coordinator", "127.0.0.1:%d" % port,
         "--launch-transform", "sh -c",
         "--", sys.executable, str(script)],
        env=env, cwd=repo_root, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    outs = [json.loads((tmp_path / ("rank%d.json" % pid)).read_text())
            for pid in range(2)]
    assert len(outs) == 2
    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    assert all(o["n_global_devices"] == 8 for o in outs)
    assert by_pid[0]["loss"] == by_pid[1]["loss"]
    # both ranks were announced on stderr with their target host
    assert "rank 0 on localhost" in proc.stderr
    assert "rank 1 on localhost" in proc.stderr
