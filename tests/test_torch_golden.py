"""Cross-framework golden tests: the layer zoo vs torch.

The reference validated its OpenCL/CUDA kernels against known-good
implementations (SURVEY §4's golden-model discipline; the repo's own
``package.py`` golden model plays that role for the native engine).
Here torch (CPU) is the independent oracle: forwards AND backwards of
the core layers must agree numerically with ``torch.nn.functional``.

Layout notes: veles_tpu is NHWC with HWIO kernels and ``sliding``
given as (x, y) like the reference; torch is NCHW/OIHW.  Znicz
activation quirks under test: scaled tanh ``1.7159·tanh(0.6666x)``
and "relu" = softplus (``ops/gemm.py``).  LRN is the Krizhevsky
``α·Σ`` form — torch's ``local_response_norm`` divides alpha by n, so
the golden passes ``alpha·n`` to torch.
"""

import numpy
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _t(x_nhwc):
    return torch.tensor(numpy.asarray(x_nhwc)).permute(0, 3, 1, 2)


def _from_t(x_nchw):
    return x_nchw.permute(0, 2, 3, 1).detach().numpy()


@pytest.mark.parametrize("sliding,padding", [
    ((1, 1), (0, 0, 0, 0)),
    ((2, 2), (1, 1, 1, 1)),
    ((4, 4), (0, 0, 0, 0)),       # AlexNet conv1 stride (s2d regime)
])
def test_conv_forward_and_wgrad_match_torch(sliding, padding):
    from veles_tpu.znicz.conv import Conv

    rng = numpy.random.default_rng(7)
    x = rng.standard_normal((2, 13, 13, 3)).astype(numpy.float32)
    w = (rng.standard_normal((5, 5, 3, 8)) * 0.2).astype(numpy.float32)

    ours = Conv.pure({"w": w}, jnp.asarray(x), padding=padding,
                     sliding=sliding)
    # and the exact s2d rewrite must agree with the plain conv
    if sliding[0] == sliding[1] and sliding[0] > 1:
        s2d = Conv.pure({"w": w}, jnp.asarray(x), padding=padding,
                        sliding=sliding, s2d=True)
        numpy.testing.assert_allclose(numpy.asarray(s2d),
                                      numpy.asarray(ours),
                                      rtol=1e-5, atol=1e-5)

    tx = _t(x).requires_grad_(True)
    tw = torch.tensor(w).permute(3, 2, 0, 1).requires_grad_(True)
    left, right, top, bottom = padding
    assert left == right and top == bottom  # torch's symmetric padding
    theirs = torch.nn.functional.conv2d(
        tx, tw, stride=(sliding[1], sliding[0]), padding=(top, left))
    numpy.testing.assert_allclose(numpy.asarray(ours),
                                  _from_t(theirs), rtol=1e-4,
                                  atol=1e-4)

    # backward: dL/dw and dL/dx for L = sum(out²)/2
    def loss(w_, x_):
        o = Conv.pure({"w": w_}, x_, padding=padding, sliding=sliding)
        return 0.5 * jnp.sum(o.astype(jnp.float32) ** 2)

    dw, dx = jax.grad(loss, argnums=(0, 1))(jnp.asarray(w),
                                            jnp.asarray(x))
    (0.5 * (theirs ** 2).sum()).backward()
    numpy.testing.assert_allclose(
        numpy.asarray(dw),
        tw.grad.permute(2, 3, 1, 0).detach().numpy(),
        rtol=1e-3, atol=1e-3)
    numpy.testing.assert_allclose(numpy.asarray(dx),
                                  _from_t(tx.grad), rtol=1e-3,
                                  atol=1e-3)


@pytest.mark.parametrize("kind", ["max", "avg"])
def test_pooling_matches_torch(kind):
    from veles_tpu.znicz.pooling import PoolingBase

    rng = numpy.random.default_rng(3)
    x = rng.standard_normal((2, 9, 9, 4)).astype(numpy.float32)
    ours = PoolingBase.pure({}, jnp.asarray(x), kx=3, ky=3,
                            sliding=(2, 2), kind=kind)
    fn = (torch.nn.functional.max_pool2d if kind == "max"
          else torch.nn.functional.avg_pool2d)
    theirs = fn(_t(x), kernel_size=3, stride=2)
    numpy.testing.assert_allclose(numpy.asarray(ours),
                                  _from_t(theirs), rtol=1e-6,
                                  atol=1e-6)


def test_lrn_matches_torch():
    from veles_tpu.znicz.normalization_units import LRNormalizerForward

    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((2, 7, 7, 16)).astype(numpy.float32)
    alpha, beta, k, n = 1e-4, 0.75, 2.0, 5
    ours = LRNormalizerForward.pure(None, jnp.asarray(x), alpha=alpha,
                                    beta=beta, k=k, n=n)
    # torch divides alpha by the window size; ours (like the paper and
    # the reference) multiplies the raw sum
    theirs = torch.nn.functional.local_response_norm(
        _t(x), size=n, alpha=alpha * n, beta=beta, k=k)
    numpy.testing.assert_allclose(numpy.asarray(ours),
                                  _from_t(theirs), rtol=1e-5,
                                  atol=1e-6)


def test_lstm_matches_torch():
    """Fused-gate scan vs torch.nn.LSTM: same i,f,g,o stacking; ours
    concatenates [x, h] against one (D+H, 4H) matrix = torch's
    w_ih/w_hh pair; single bias = bias_ih with bias_hh zeroed."""
    from veles_tpu.znicz.rnn import LSTM

    B, T, D, H = 4, 11, 6, 9
    rng = numpy.random.default_rng(11)
    x = rng.standard_normal((B, T, D)).astype(numpy.float32)
    w = (rng.standard_normal((D + H, 4 * H)) * 0.3).astype(
        numpy.float32)
    b = (rng.standard_normal(4 * H) * 0.1).astype(numpy.float32)

    ours = LSTM.pure({"w": w, "b": b}, jnp.asarray(x),
                     hidden_units=H, last_only=False)

    lstm = torch.nn.LSTM(D, H, batch_first=True)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(w[:D].T))
        lstm.weight_hh_l0.copy_(torch.tensor(w[D:].T))
        lstm.bias_ih_l0.copy_(torch.tensor(b))
        lstm.bias_hh_l0.zero_()
    theirs, (h_n, _c_n) = lstm(torch.tensor(x))
    numpy.testing.assert_allclose(numpy.asarray(ours),
                                  theirs.detach().numpy(), rtol=1e-4,
                                  atol=1e-5)
    last = LSTM.pure({"w": w, "b": b}, jnp.asarray(x),
                     hidden_units=H, last_only=True)
    numpy.testing.assert_allclose(numpy.asarray(last),
                                  h_n[0].detach().numpy(), rtol=1e-4,
                                  atol=1e-5)


def test_simple_rnn_matches_torch():
    from veles_tpu.znicz.rnn import SimpleRNN

    B, T, D, H = 3, 8, 5, 7
    rng = numpy.random.default_rng(13)
    x = rng.standard_normal((B, T, D)).astype(numpy.float32)
    w = (rng.standard_normal((D + H, H)) * 0.4).astype(numpy.float32)
    b = (rng.standard_normal(H) * 0.1).astype(numpy.float32)
    ours = SimpleRNN.pure({"w": w, "b": b}, jnp.asarray(x),
                          hidden_units=H, last_only=False)
    rnn = torch.nn.RNN(D, H, nonlinearity="tanh", batch_first=True)
    with torch.no_grad():
        rnn.weight_ih_l0.copy_(torch.tensor(w[:D].T))
        rnn.weight_hh_l0.copy_(torch.tensor(w[D:].T))
        rnn.bias_ih_l0.copy_(torch.tensor(b))
        rnn.bias_hh_l0.zero_()
    theirs, _h = rnn(torch.tensor(x))
    numpy.testing.assert_allclose(numpy.asarray(ours),
                                  theirs.detach().numpy(), rtol=1e-4,
                                  atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_attention_matches_torch_sdpa(causal):
    """flash_attention (XLA path on CPU) vs torch's
    scaled_dot_product_attention, forward and q-gradient."""
    from veles_tpu.ops.attention import flash_attention

    b, s, h, d = 2, 33, 4, 16
    rng = numpy.random.default_rng(17)
    q = rng.standard_normal((b, s, h, d)).astype(numpy.float32)
    k = rng.standard_normal((b, s, h, d)).astype(numpy.float32)
    v = rng.standard_normal((b, s, h, d)).astype(numpy.float32)

    ours = flash_attention(jnp.asarray(q), jnp.asarray(k),
                           jnp.asarray(v), causal=causal,
                           use_pallas=False)
    tq = torch.tensor(q).permute(0, 2, 1, 3).requires_grad_(True)
    tk = torch.tensor(k).permute(0, 2, 1, 3)
    tv = torch.tensor(v).permute(0, 2, 1, 3)
    theirs = torch.nn.functional.scaled_dot_product_attention(
        tq, tk, tv, is_causal=causal)
    numpy.testing.assert_allclose(
        numpy.asarray(ours),
        theirs.permute(0, 2, 1, 3).detach().numpy(), rtol=1e-4,
        atol=1e-5)

    dq = jax.grad(lambda q_: jnp.sum(flash_attention(
        q_, jnp.asarray(k), jnp.asarray(v), causal=causal,
        use_pallas=False) ** 2) * 0.5)(jnp.asarray(q))
    (0.5 * (theirs ** 2).sum()).backward()
    numpy.testing.assert_allclose(
        numpy.asarray(dq),
        tq.grad.permute(0, 2, 1, 3).detach().numpy(), rtol=1e-3,
        atol=1e-4)


def test_znicz_activations_match_torch():
    """matmul's fused epilogues: scaled tanh (1.7159·tanh(0.6666x)),
    Znicz 'relu' = softplus, sigmoid — vs torch composition."""
    from veles_tpu.ops.gemm import matmul

    rng = numpy.random.default_rng(19)
    a = rng.standard_normal((32, 24)).astype(numpy.float32)
    w = (rng.standard_normal((24, 12)) * 0.3).astype(numpy.float32)
    bias = rng.standard_normal(12).astype(numpy.float32)
    ta = torch.tensor(a)
    tw = torch.tensor(w)
    tb = torch.tensor(bias)
    lin = ta @ tw + tb
    for act, torch_fn in [
            ("tanh", lambda z: 1.7159 * torch.tanh(z * 0.6666)),
            ("relu", torch.nn.functional.softplus),
            ("strict_relu", torch.relu),
            ("sigmoid", torch.sigmoid)]:
        ours = matmul(jnp.asarray(a), jnp.asarray(w),
                      jnp.asarray(bias), act, None, False)
        numpy.testing.assert_allclose(
            numpy.asarray(ours), torch_fn(lin).numpy(), rtol=1e-5,
            atol=1e-5)


def test_grouped_conv_matches_torch():
    """The documented `grouping` knob (AlexNet's grouped convolution):
    weights (kh, kw, C/g, K) against torch's groups=g."""
    from veles_tpu.znicz.conv import Conv

    g = 2
    rng = numpy.random.default_rng(23)
    x = rng.standard_normal((2, 9, 9, 8)).astype(numpy.float32)
    w = (rng.standard_normal((3, 3, 8 // g, 6)) * 0.3).astype(
        numpy.float32)
    ours = Conv.pure({"w": w}, jnp.asarray(x), padding=(1, 1, 1, 1),
                     grouping=g)
    tw = torch.tensor(w).permute(3, 2, 0, 1)
    theirs = torch.nn.functional.conv2d(_t(x), tw, padding=1, groups=g)
    numpy.testing.assert_allclose(numpy.asarray(ours),
                                  _from_t(theirs), rtol=1e-4,
                                  atol=1e-5)


def test_deconv_matches_torch_and_adjoint_relation():
    """Deconv vs torch.nn.functional.conv_transpose2d: our transposed
    conv applies the stored (ky, kx, C, K) kernel WITHOUT the spatial
    flip of torch's gradient convention, so the torch twin takes the
    flipped kernel.  Equivalently, Deconv(·; w) is the exact adjoint
    of Conv(·; flip(w)) — an equivalent parameterization (the filter
    is learned; a flip re-parameterizes, it does not change the
    function class), pinned here so the convention can never drift
    silently between XLA, the package golden model, and the native
    engine."""
    from veles_tpu.znicz.conv import Conv
    from veles_tpu.znicz.misc_units import Deconv

    rng = numpy.random.default_rng(29)
    B, H, W, K, C, k, s, p = 2, 5, 5, 4, 3, 3, 2, 1
    x = rng.standard_normal((B, H, W, K)).astype(numpy.float32)
    w = (rng.standard_normal((k, k, C, K)) * 0.3).astype(numpy.float32)
    w_flip = numpy.ascontiguousarray(w[::-1, ::-1])

    ours = numpy.asarray(Deconv.pure({"w": w}, jnp.asarray(x),
                                     padding=(p, p, p, p),
                                     sliding=(s, s)))
    tw = torch.tensor(w_flip).permute(3, 2, 0, 1)
    theirs = torch.nn.functional.conv_transpose2d(
        _t(x), tw, stride=s, padding=p)
    numpy.testing.assert_allclose(ours, _from_t(theirs), rtol=1e-4,
                                  atol=1e-5)

    # adjoint identity: <Conv(y; flip(w)), x> == <y, Deconv(x; w)>
    y = rng.standard_normal(
        (B,) + ours.shape[1:3] + (C,)).astype(numpy.float32)
    conv_y = numpy.asarray(Conv.pure({"w": w_flip}, jnp.asarray(y),
                                     padding=(p, p, p, p),
                                     sliding=(s, s)))
    lhs = float((conv_y * x).sum())
    rhs = float((y * ours).sum())
    assert lhs == pytest.approx(rhs, rel=1e-4)
