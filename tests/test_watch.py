"""veles_tpu.watch tests: in-program health telemetry (knob parity,
zero extra dispatches, strict first-bad-leaf, epoch-scan windows, pod
psum'd agreement), the drop-tolerant telemetry bus (publish roundtrip,
dead/slow-subscriber wall-clock bound, disabled-path no-op), the
dashboard CLI record/replay roundtrip, the blackbox health block, the
web_status/plotter publishers, and the bench_diff watchdog."""

import json
import os
import sys
import time

import numpy
import pytest

from veles_tpu import prng, watch
from veles_tpu.backends import CPUDevice
from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.watch import HealthError, TelemetryReader
from veles_tpu.watch.bus import load_events, record_events
from veles_tpu.znicz.standard_workflow import StandardWorkflow

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


class BlobLoader(FullBatchLoader):
    """The stitched-parity stand-in (tests/test_stitch.py lineage)."""

    def __init__(self, workflow, n_train=200, n_valid=50, dim=32,
                 **kwargs):
        self._cfg = (n_train, n_valid, dim)
        super(BlobLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        n_train, n_valid, dim = self._cfg
        rng = numpy.random.default_rng(42)
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(10), total // 10 + 1)[:total]
        centers = rng.standard_normal((10, dim)) * 3.0
        data = centers[labels] \
            + rng.standard_normal((total, dim)) * 0.7
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels = list(int(x) for x in labels)
        self.class_lengths[:] = [0, n_valid, n_train]


def build(device=None, max_epochs=3, minibatch_size=50, seed=5,
          **loader_kw):
    prng.seed_all(seed)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size, **loader_kw),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": 10 ** 6})
    wf.launcher = DummyLauncher()
    wf.initialize(device=device or CPUDevice())
    return wf


def _params(wf):
    out = []
    for fwd in wf.forwards:
        fwd.weights.map_read()
        out.append(numpy.array(fwd.weights.mem))
        fwd.bias.map_read()
        out.append(numpy.array(fwd.bias.mem))
    for gd in wf.gds:
        gd.gradient_weights.map_read()
        out.append(numpy.array(gd.gradient_weights.mem))
    return out


@pytest.fixture
def watch_env():
    """Snapshot/restore every knob these tests touch, shut the bus
    down, and leave the monitor disarmed."""
    saved = {k: root.common.engine.get(k, d) for k, d in (
        ("health", "off"), ("stitch", "on"), ("epoch_scan", "off"),
        ("metrics_every", 0), ("loader", "auto"))}
    yield root.common.engine
    for key, value in saved.items():
        setattr(root.common.engine, key, value)
    watch.shutdown()
    watch.monitor.reset()


# -- the health knob --------------------------------------------------------

def test_health_knob_parses(watch_env):
    from veles_tpu.watch.health import health_mode
    for value, expect in (("off", "off"), ("", "off"), (0, "off"),
                          ("on", "on"), (True, "on"), (1, "on"),
                          ("strict", "strict"), ("ON", "on")):
        watch_env.health = value
        assert health_mode() == expect, value
    watch_env.health = "loud"
    with pytest.raises(ValueError):
        health_mode()


# -- parity + zero extra dispatches (the acceptance gate) -------------------

@pytest.mark.traced
def test_health_off_bitwise_and_on_zero_extra_dispatches(watch_env):
    """THE gate: health=off is byte-identical to HEAD by construction
    (no instrumentation runs), health=on trains bitwise-identically
    (the stats are extra outputs of the same programs) with EXACTLY
    the same dispatch count — asserted via the trace recorder's
    per-run dispatch delta AND the PerfLedger's per-entry
    steps/dispatch accounting."""
    from veles_tpu import prof, trace

    watch_env.health = "off"
    d0 = trace.recorder.count("segment", "dispatch")
    wf_off = build()
    wf_off.run()
    off_dispatches = trace.recorder.count("segment", "dispatch") - d0
    p_off = _params(wf_off)
    assert wf_off._stitch_segments_[0]._health_groups == []

    watch_env.health = "on"
    d0 = trace.recorder.count("segment", "dispatch")
    wf_on = build()
    wf_on.run()
    on_dispatches = trace.recorder.count("segment", "dispatch") - d0

    assert on_dispatches == off_dispatches, \
        "health=on added %d dispatch(es)" % (on_dispatches
                                             - off_dispatches)
    for a, b in zip(_params(wf_on), p_off):
        numpy.testing.assert_array_equal(a, b)
    assert wf_on.decision.epoch_n_err_pt == wf_off.decision.epoch_n_err_pt
    # the monitor observed every GD dispatch, one step each
    assert watch.monitor.mode == "on"
    assert watch.monitor.steps > 0
    # the stats landed on the GD units as async device scalars
    gd_entries = [e for e in prof.ledger.entries("segment")
                  if "GD" in e.name]
    assert gd_entries
    for gd in wf_on.gds:
        assert hasattr(gd, "health_nonfinite")


def test_health_stats_sane_with_declared_grad_norm(watch_env):
    """The stat definitions: GD groups declare grad_norm (recovered
    from the momentum recurrence), norms are finite and positive,
    update_ratio == update_norm/weight_norm, and every param leaf
    reports a zero non-finite count on a healthy run."""
    watch_env.health = "on"
    wf = build()
    wf.run()
    snap = watch.monitor.snapshot()
    assert snap["mode"] == "on"
    assert snap["step"] == watch.monitor.steps
    assert set(snap["groups"]) == {"GDTanh", "GDSoftmax"}
    for name, group in snap["groups"].items():
        for stat in ("grad_norm", "weight_norm", "update_norm",
                     "update_ratio"):
            assert numpy.isfinite(group[stat]), (name, stat)
            assert group[stat] > 0, (name, stat)
        assert group["update_ratio"] == pytest.approx(
            group["update_norm"] / (group["weight_norm"] + 1e-12),
            rel=1e-4)
        assert group["nonfinite"] == 0
        assert set(group["leaves"]) == {"w", "vw", "b", "vb"}
        assert all(v == 0 for v in group["leaves"].values())
    # the snapshot is cached for web_status / blackbox
    assert watch.last_health() is snap


def test_grad_norm_matches_reference_backward(watch_env):
    """grad_norm is the real ‖grad + decay·w‖: one GD step from a
    fixed state must report the analytically recomputed value."""
    watch_env.health = "on"
    wf = build(max_epochs=1)
    # capture pre-run weights for the FIRST train step's reference
    w0 = [(numpy.array(f.weights.mem), numpy.array(f.bias.mem))
          for f in wf.forwards]
    wf.run()
    snap = watch.monitor.snapshot()
    # reference: replay the softmax layer's first backward by hand is
    # heavy; instead verify consistency through the recurrence on the
    # LAST step — vw_new = mom·vw_old − lr·g  ⇒  with mom=0 (softmax
    # layer's default gradient_moment=0) g = −vw/lr and update_norm =
    # lr·‖g‖ (bias included), so grad_norm == update_norm/lr exactly
    group = snap["groups"]["GDSoftmax"]
    lr = wf.gds[0].learning_rate \
        if wf.gds[0].name == "GDSoftmax" else wf.gds[1].learning_rate
    assert group["grad_norm"] == pytest.approx(
        group["update_norm"] / lr, rel=1e-4)
    assert w0  # silence the capture (documents the fixed pre-state)


@pytest.mark.traced
def test_health_rides_epoch_scan_windows(watch_env):
    """Epoch mode: the instrumented stages fold into the K-step scan
    windows (the stats are scan-body outputs — still zero extra
    dispatches), training stays bitwise-identical to health=off, and
    the monitor counts K steps per window observation."""
    watch_env.health = "off"
    watch_env.epoch_scan = "auto"
    wf_off = build()
    wf_off.run()
    p_off = _params(wf_off)

    watch_env.health = "on"
    wf_on = build()
    wf_on.run()
    report = wf_on.stitch_report()["epoch_scan"]
    assert report["eligible"], report
    assert report["windows"] > 0
    for a, b in zip(_params(wf_on), p_off):
        numpy.testing.assert_array_equal(a, b)
    snap = watch.monitor.snapshot()
    assert snap["groups"]["GDTanh"]["nonfinite"] == 0
    # train windows observed K steps each (valid windows carry no
    # param group): steps == the train-step total
    assert watch.monitor.steps > report["windows"]


# -- strict mode ------------------------------------------------------------

def test_health_off_rebuild_disarms_stale_monitor(watch_env):
    """A rebuild with health=off (or any rebuild that instruments
    nothing) must disarm the monitor: a second workflow in the same
    process must not snapshot — or strict-raise over — the previous
    build's dead units."""
    watch_env.health = "strict"
    wf_a = build(max_epochs=2)
    wf_a.run()
    assert watch.monitor.armed
    # poison A's weights AFTER its run: a stale armed monitor would
    # read these at B's first class close and raise
    wf_a.forwards[0].weights.map_write()
    wf_a.forwards[0].weights.mem[:] = numpy.nan
    watch_env.health = "off"
    wf_b = build(max_epochs=2, seed=9)
    assert not watch.monitor.armed
    assert watch.monitor.groups == []
    wf_b.run()                      # must not raise, must not snapshot
    assert bool(wf_b.decision.complete)
    assert watch.monitor.last_snapshot is None


def test_bus_host_state_stays_blackbox_serializable(watch_env):
    """The bus records the JSON-round-tripped event, so a numpy
    scalar (or any repr-degraded value) in a payload can never make a
    later blackbox dump unserializable."""
    watch.start("tcp://127.0.0.1:0")
    event = watch.publish("epoch", value=numpy.float64(0.5),
                          arr_stat=numpy.int32(3))
    # stored host-side as wire-equal plain types
    stored = watch.latest("epoch")
    assert stored == event
    json.dumps(stored)              # round-trips strictly
    assert watch.recent_events()[-1] is stored


def test_strict_names_first_bad_leaf(watch_env):
    """strict: a NaN planted in the FIRST layer's weights surfaces as
    a typed HealthError naming a poisoned param leaf — and training
    stops at the window boundary instead of finishing a garbage
    epoch."""
    watch_env.health = "strict"
    wf = build(max_epochs=3)
    weights = wf.forwards[0].weights
    weights.map_write()
    weights.mem[0, 0] = numpy.nan
    with pytest.raises(HealthError) as info:
        wf.run()
    err = info.value
    # the NaN propagates through the backward in the same dispatch:
    # the named leaf is the first in stage order (the GD chain runs
    # softmax-first), with the group and slot both named
    group, leaf = err.leaf.split(".")
    assert group in ("GDSoftmax", "GDTanh")
    assert leaf in ("w", "vw", "b", "vb")
    assert err.count > 0
    assert "health=strict" in str(err)
    assert not bool(wf.decision.complete)


def test_strict_clean_run_checks_but_never_raises(watch_env):
    """strict on a healthy run: the cadence fetches fire (bounded by
    metrics_every) and the run completes normally."""
    watch_env.health = "strict"
    watch_env.metrics_every = 2
    wf = build(max_epochs=2)
    wf.run()
    assert bool(wf.decision.complete)
    assert watch.monitor.checks >= 2
    snap = watch.monitor.snapshot()
    assert all(g["nonfinite"] == 0 for g in snap["groups"].values())


def test_strict_epoch_scan_window_boundary(watch_env):
    """strict under epoch mode: the check rides every window commit —
    the poisoned run dies at the FIRST train window, not at an epoch
    close."""
    watch_env.health = "strict"
    watch_env.epoch_scan = "auto"
    wf = build(max_epochs=3)
    wf.forwards[0].weights.map_write()
    wf.forwards[0].weights.mem[:] = numpy.inf
    with pytest.raises(HealthError):
        wf.run()
    report = wf.stitch_report()["epoch_scan"]
    assert report["windows"] <= 2       # died on the first train window


# -- pod: psum'd health agreement -------------------------------------------

def _pod_build(max_epochs=2):
    from veles_tpu.backends import AutoDevice
    prng.seed_all(21)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, n_train=384, n_valid=128, dim=16, minibatch_size=64),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": max_epochs})
    wf.launcher = DummyLauncher()
    wf.initialize(device=AutoDevice())
    return wf


def test_pod_8_shard_health_agrees_with_single_device(watch_env):
    """The pod gate: under an 8-shard PodRuntime the health stats come
    out replicated (GSPMD reduces them in-program — every shard
    agrees by construction), and their values match the single-device
    run up to the in-scan psum's float reordering."""
    import jax
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod.runtime import PodRuntime

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    watch_env.health = "on"
    ref = _pod_build()
    ref.run()
    ref_snap = watch.monitor.snapshot()

    wf = _pod_build()
    runtime = PodRuntime(wf, mesh=mesh_from_topology(
        {"data": 8}, require=("data",)))
    runtime.install()
    wf.run()
    pod_snap = watch.monitor.snapshot()
    assert set(pod_snap["groups"]) == set(ref_snap["groups"])
    for name, group in pod_snap["groups"].items():
        # a sharded stat would fetch as a per-shard value and diverge
        # wildly; replicated-and-psum'd agrees to float tolerance
        for stat in ("grad_norm", "weight_norm", "update_norm"):
            assert group[stat] == pytest.approx(
                ref_snap["groups"][name][stat], rel=1e-3), (name, stat)
        assert group["nonfinite"] == 0


# -- the telemetry bus ------------------------------------------------------

def test_bus_publish_roundtrip_latest_history(watch_env):
    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    try:
        assert reader.sync(bus)
        watch.publish("alpha", value=1)
        watch.publish("beta", {"value": 2}, extra="x")
        events = []
        deadline = time.monotonic() + 5
        while len([e for e in events
                   if not e["kind"].startswith("_")]) < 2 \
                and time.monotonic() < deadline:
            events.extend(reader.drain(timeout_ms=100))
        got = {e["kind"]: e for e in events}
        assert got["alpha"]["value"] == 1
        assert got["beta"]["value"] == 2 and got["beta"]["extra"] == "x"
        for event in (got["alpha"], got["beta"]):
            assert event["seq"] > 0 and "ts" in event and "role" in event
        # host-side conflation + history
        assert watch.latest("alpha")["value"] == 1
        assert [e["kind"] for e in watch.recent_events()
                if not e["kind"].startswith("_")] == ["alpha", "beta"]
        assert bus.describe()["published"] >= 2
    finally:
        reader.close()


def test_bus_drop_tolerance_dead_and_slow_subscriber(watch_env):
    """THE drop-tolerance gate: thousands of publishes against (a) no
    subscriber at all and (b) a subscriber that never reads, under a
    tiny HWM, complete within a hard wall-clock bound — the PUB socket
    drops, it never blocks."""
    bus = watch.start("tcp://127.0.0.1:0", hwm=8)
    payload = {"filler": "x" * 512}
    tic = time.monotonic()
    for i in range(2000):
        watch.publish("flood", payload)
    dead_sec = time.monotonic() - tic
    assert dead_sec < 5.0, "publishing blocked with no subscriber"

    slow = TelemetryReader(bus.endpoint, hwm=2)
    try:
        slow.sync(bus)
        tic = time.monotonic()
        for i in range(2000):
            watch.publish("flood", payload)
        slow_sec = time.monotonic() - tic
        assert slow_sec < 5.0, "a slow subscriber backpressured publish"
        # the per-step cost stays micro even with a wedged peer
        assert slow_sec / 2000 < 2e-3
    finally:
        slow.close()
    assert bus.describe()["published"] + bus.dropped >= 2000


def test_publish_without_bus_is_noop(watch_env):
    assert not watch.enabled()
    assert watch.publish("anything", x=1) is None
    assert watch.latest() == {}
    assert watch.recent_events() == []


def test_reader_sync_never_swallows_real_traffic(watch_env):
    """A sync() probe landing on REAL traffic (a reader joining a bus
    mid-session) retains the event for the next poll instead of
    dropping it."""
    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    try:
        assert reader.sync(bus)
        reader.drain(timeout_ms=100)            # clear join markers
        watch.publish("data", n=7)
        time.sleep(0.2)                         # let the frame queue
        assert reader.sync(bus)                 # probe eats... nothing
        events = reader.drain(timeout_ms=200)
        assert any(e["kind"] == "data" and e["n"] == 7
                   for e in events), events
        # control-frame hygiene: the join probes rode the wire but
        # never entered the telemetry surfaces
        assert "_sync" not in bus.latest
        assert all(not e["kind"].startswith("_")
                   for e in bus.history)
        assert bus.control > 0
        assert bus.describe()["published"] == 1     # just "data"
    finally:
        reader.close()


def test_chaos_bus_event_keeps_target_role(watch_env):
    """A chaos event's TARGET role survives the bus merge (the bus
    stamps 'role' with the publisher's role; the fault target rides
    as target_role)."""
    from veles_tpu import chaos

    watch.start("tcp://127.0.0.1:0")
    chaos.controller._record("slave_kill", "slave_job", None,
                             role="slave")
    event = watch.latest("chaos")
    assert event["action"] == "slave_kill"
    assert event["site"] == "slave_job"
    assert event["target_role"] == "slave"


def test_bus_wire_stays_strict_json_under_inf(watch_env):
    """A diverged run's inf/nan payload (DecisionMSE's pre-first-
    close best_mse, exploded health stats) degrades to repr strings —
    the wire never carries a bare non-RFC ``Infinity`` token."""
    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    try:
        assert reader.sync(bus)
        watch.publish("epoch", best_mse=float("inf"),
                      mse=float("nan"), ok=1.5)
        event = None
        deadline = time.monotonic() + 5
        while event is None and time.monotonic() < deadline:
            got = reader.poll(100)
            if got is not None and got["kind"] == "epoch":
                event = got
        assert event["best_mse"] == "inf"
        assert event["mse"] == "nan"
        assert event["ok"] == 1.5
        # strict parse end to end (what jq / a JS dashboard does)
        json.loads(json.dumps(watch.latest("epoch")),
                   parse_constant=lambda c: pytest.fail(
                       "non-RFC constant %s on the wire" % c))
    finally:
        reader.close()


def test_bus_endpoint_shorthand_forms(watch_env):
    """The config knob documents ':0' (random local port) and bare
    forms — they must start a bus, not hand libzmq an empty host."""
    bus = watch.start(":0")
    assert bus.endpoint.startswith("tcp://127.0.0.1:")
    assert not bus.endpoint.endswith(":0")
    reader = TelemetryReader(bus.endpoint)
    try:
        assert reader.sync(bus)
    finally:
        reader.close()


def test_bus_unserializable_payload_never_raises(watch_env):
    bus = watch.start("tcp://127.0.0.1:0")
    event = watch.publish("weird", obj=object())
    assert event["kind"] == "weird"
    assert bus.describe()["endpoint"].startswith("tcp://")


# -- the training publishers ------------------------------------------------

def test_training_session_publishes_run_epoch_health_perf(watch_env):
    """One stitched training run with the bus + health armed streams
    run/epoch/health/perf events a live subscriber consumes."""
    watch_env.health = "on"
    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    try:
        assert reader.sync(bus)
        wf = build(max_epochs=2)
        wf.run()
        events = reader.drain(timeout_ms=200)
        kinds = {e["kind"] for e in events
                 if not e["kind"].startswith("_")}
        assert {"run", "epoch", "health", "perf"} <= kinds
        runs = [e for e in events if e["kind"] == "run"]
        assert runs[0]["phase"] == "begin"
        assert runs[-1]["phase"] == "end"
        assert "results" in runs[-1]
        epochs = [e for e in events if e["kind"] == "epoch"]
        assert all("n_err_pt" in e and "epoch" in e for e in epochs)
        health = [e for e in events if e["kind"] == "health"][-1]
        assert health["groups"]["GDTanh"]["nonfinite"] == 0
        perf = [e for e in events if e["kind"] == "perf"][-1]
        assert perf["compiles"] > 0
        assert perf["dispatches"] > 0
    finally:
        reader.close()


def test_plotter_publishes_thin_snapshot(watch_env):
    """The rewired seed plotting stack: a plotter run() publishes a
    compact JSON digest onto the bus (no GraphicsServer needed)."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.plotting_units import (AccumulatingPlotter,
                                          MaxMinPlotter)

    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    try:
        assert reader.sync(bus)
        wf = DummyWorkflow()

        class Source(object):
            metric = 0.25
        plotter = AccumulatingPlotter(wf, name="err_plot",
                                      input_field="metric",
                                      label="error")
        plotter.input = Source()
        plotter.run()
        plotter.run()
        mm = MaxMinPlotter(wf, name="mm", input_field=None)
        mm.input = numpy.arange(6.0)
        mm.run()
        events = []
        deadline = time.monotonic() + 5
        while len([e for e in events if e["kind"] == "plot"]) < 3 \
                and time.monotonic() < deadline:
            events.extend(reader.drain(timeout_ms=100))
        plots = [e for e in events if e["kind"] == "plot"]
        acc = [e for e in plots if e["plotter"] == "err_plot"][-1]
        assert acc["label"] == "error"
        assert acc["n"] == 2 and acc["last"] == 0.25
        assert acc["type"] == "AccumulatingPlotter"
        mmev = [e for e in plots if e["plotter"] == "mm"][-1]
        assert mmev["max"] == 5.0 and mmev["min"] == 0.0
    finally:
        reader.close()


def test_web_status_snapshot_carries_health_block(watch_env):
    """The rewired web_status satellite: notifier snapshots include
    the latest health block (and the bus digest when one is live)."""
    from veles_tpu.web_status import StatusNotifier

    watch_env.health = "on"
    wf = build(max_epochs=2)
    wf.run()
    notifier = StatusNotifier("http://127.0.0.1:1/unused")
    try:
        data = notifier.snapshot(wf)
        assert "health" in data
        assert data["health"]["groups"]["GDSoftmax"]["nonfinite"] == 0
        assert "watch" not in data          # no bus configured
        watch.start("tcp://127.0.0.1:0")
        data = notifier.snapshot(wf)
        assert data["watch"]["endpoint"].startswith("tcp://")
    finally:
        notifier.close()


def test_scrape_endpoints_serve_health_gauges(watch_env):
    """The obs/scrape integration: with the health knob armed every
    role's /metrics page (default_sources) carries veles_health_*
    gauges + the bus counters; disarmed, the watch source contributes
    nothing."""
    from veles_tpu.obs.scrape import ScrapeServer, default_sources

    server = ScrapeServer(default_sources(), role="test")
    assert "veles_health_stat" not in server.render()
    watch_env.health = "on"
    wf = build(max_epochs=2)
    wf.run()
    watch.start("tcp://127.0.0.1:0")
    watch.publish("epoch", epoch=1)
    page = server.render()
    assert 'veles_health_stat{group="GDTanh",stat="grad_norm"}' in page
    assert 'veles_health_nonfinite{group="GDSoftmax",leaf="w"} 0' \
        in page
    assert "veles_watch_published_total" in page
    # the exposition parses: families contiguous, one TYPE per name
    types = [line.split()[3] for line in page.splitlines()
             if line.startswith("# TYPE veles_health")]
    assert types and all(t == "gauge" for t in types)


# -- blackbox ---------------------------------------------------------------

@pytest.fixture
def blackbox_dir(tmp_path):
    from veles_tpu.obs import blackbox
    saved = root.common.obs.get("blackbox_dir")
    root.common.obs.blackbox_dir = str(tmp_path / "bb")
    yield root.common.obs.blackbox_dir
    root.common.obs.blackbox_dir = saved
    blackbox.uninstall()


def test_blackbox_dump_embeds_health_and_bus_tail(watch_env,
                                                  blackbox_dir):
    from veles_tpu.obs import blackbox

    watch_env.health = "on"
    watch.start("tcp://127.0.0.1:0")
    wf = build(max_epochs=2)
    wf.run()
    path = blackbox.dump("unit test")
    payload = blackbox.load(path)
    health = payload["watch"]["health"]
    assert health["groups"]["GDTanh"]["nonfinite"] == 0
    kinds = {e["kind"] for e in payload["watch"]["events"]}
    assert "epoch" in kinds and "health" in kinds


def test_chaos_slave_kill_dump_contains_health_block(watch_env,
                                                     blackbox_dir):
    """The ISSUE satellite gate: a chaos slave_kill's flight record
    shows what the numerics looked like at death — the dump carries a
    parseable health block from the training that preceded it."""
    import glob

    from veles_tpu.obs import blackbox
    from veles_tpu.parallel.jobs import JobClient, JobServer

    watch_env.health = "on"
    wf = build(max_epochs=2)
    wf.run()                        # populates the cached snapshot
    assert watch.last_health() is not None

    class Master(object):
        def checksum(self):
            return "watch-v1"

        def generate_data_for_slave(self, slave):
            return {"job_number": 1}

        def apply_data_from_slave(self, data, slave):
            pass

        def drop_slave(self, slave):
            pass

    class Slave(object):
        def checksum(self):
            return "watch-v1"

        def do_job(self, data, callback):
            callback({"ok": True})

    server = JobServer(Master()).start()
    try:
        client = JobClient(Slave(), server.endpoint,
                           death_probability=1.0)
        client.handshake()
        assert client.run() is False, "the kill must fire"
        client.close()
    finally:
        server.stop()
    files = glob.glob(blackbox_dir + "/blackbox-*.json")
    assert files
    payload = blackbox.load(sorted(files)[-1])
    assert "kill" in payload["reason"]
    health = payload["watch"]["health"]
    parsed = json.loads(json.dumps(health))   # parseable end to end
    assert parsed["groups"]["GDSoftmax"]["weight_norm"] > 0
    assert parsed["groups"]["GDSoftmax"]["nonfinite"] == 0


# -- the dashboard CLI ------------------------------------------------------

def test_record_replay_roundtrip(watch_env, tmp_path, capsys):
    """--record persists exactly what the bus delivered; --replay
    renders it back with per-kind counts."""
    from veles_tpu.watch.__main__ import replay

    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    path = str(tmp_path / "session.ndjson")
    try:
        assert reader.sync(bus)
        watch.publish("health", step=4, groups={
            "GDTanh": {"grad_norm": 1.5, "weight_norm": 2.0,
                       "update_ratio": 0.1, "nonfinite": 0}})
        watch.publish("epoch", epoch=1, n_err_pt=3.25)
        events = []
        deadline = time.monotonic() + 5
        while len([e for e in events
                   if not e["kind"].startswith("_")]) < 2 \
                and time.monotonic() < deadline:
            events.extend(reader.drain(timeout_ms=100))
        events = [e for e in events if not e["kind"].startswith("_")]
        record_events(events, path)
        assert load_events(path) == events
        back = replay(path)
        assert back == events
        out = capsys.readouterr().out
        assert "health" in out and "epoch" in out
        assert "GDTanh" in out              # the health block expands
        assert "health×1" in out and "epoch×1" in out
    finally:
        reader.close()


def test_dashboard_render_and_cli_replay(watch_env, tmp_path):
    from veles_tpu.watch.__main__ import main, render

    event = {"kind": "health", "ts": time.time(), "seq": 1,
             "role": "standalone", "step": 8,
             "groups": {"GDTanh": {"grad_norm": 1.0,
                                   "weight_norm": 3.0,
                                   "update_ratio": 0.01,
                                   "nonfinite": 0}}}
    frame = render({"health": event}, received=1)
    assert "KIND" in frame and "health" in frame
    assert "nf=0" in frame
    path = str(tmp_path / "r.ndjson")
    record_events([event], path)
    assert main(["--replay", path]) == 0
    assert main([]) == 2                    # no endpoint: usage


def test_cli_consume_records_live_events(watch_env, tmp_path):
    """The live half of the CLI: consume() drains a real bus for a
    bounded duration and appends every event to the record file."""
    import io

    from veles_tpu.watch.__main__ import consume

    bus = watch.start("tcp://127.0.0.1:0")
    reader = TelemetryReader(bus.endpoint)
    path = str(tmp_path / "live.ndjson")
    try:
        assert reader.sync(bus)
        watch.publish("epoch", epoch=0, n_err_pt=9.0)
        watch.publish("perf", compiles=3)
        out = io.StringIO()
        latest, received = consume(reader, duration=1.0, record=path,
                                   once=True, out=out)
        assert received >= 2
        kinds = {e["kind"] for e in load_events(path)}
        assert {"epoch", "perf"} <= kinds
    finally:
        reader.close()


# -- bench_diff -------------------------------------------------------------

def _bench_diff():
    sys.path.insert(0, SCRIPTS)
    try:
        import bench_diff
    finally:
        sys.path.remove(SCRIPTS)
    return bench_diff


def test_bench_diff_gate_pass_and_regress(tmp_path, capsys):
    bd = _bench_diff()
    banked_path = str(tmp_path / "BENCH_r01.json")
    with open(banked_path, "w") as fout:
        json.dump({"parsed": {
            "metric": "m1", "value": 1000.0, "unit": "images/sec",
            "mfu": 0.4, "sec_per_step": 0.02, "recompiles": 0,
            "dispatches_per_epoch": 2, "device_kind": "cpu"}}, fout)
    fresh_ok = str(tmp_path / "ok.jsonl")
    with open(fresh_ok, "w") as fout:
        fout.write("probe chatter, not json\n")
        fout.write(json.dumps({
            "metric": "m1", "value": 980.0, "unit": "images/sec",
            "mfu": 0.41, "sec_per_step": 0.021, "recompiles": 0,
            "dispatches_per_epoch": 2, "device_kind": "cpu"}) + "\n")
    assert bd.main(["--banked", banked_path,
                    "--fresh", fresh_ok]) == 0
    fresh_bad = str(tmp_path / "bad.jsonl")
    with open(fresh_bad, "w") as fout:
        fout.write(json.dumps({
            "metric": "m1", "value": 700.0, "unit": "images/sec",
            "mfu": 0.2, "sec_per_step": 0.05, "recompiles": 3,
            "dispatches_per_epoch": 9, "device_kind": "cpu"}) + "\n")
    assert bd.main(["--banked", banked_path,
                    "--fresh", fresh_bad]) == 1
    out = capsys.readouterr().out
    for field in ("value", "mfu", "sec_per_step", "recompiles",
                  "dispatches_per_epoch"):
        assert "REGRESSION m1 %s" % field in out, field


def test_bench_diff_device_kind_and_direction_rules(tmp_path):
    bd = _bench_diff()
    assert bd.value_direction({"unit": "images/sec"}) == 1
    assert bd.value_direction({"unit": "tokens/s"}) == 1
    assert bd.value_direction({"unit": "sec_per_step"}) == -1
    assert bd.value_direction({"unit": "ms"}) == -1
    assert bd.value_direction({"unit": "bytes"}) == -1
    banked = {("m1", "TPU v5"): {
        "metric": "m1", "value": 100.0, "unit": "images/sec",
        "device_kind": "TPU v5"}}
    # a CPU fresh line never judged against a banked TPU line
    regs, compared = bd.compare(
        [{"metric": "m1", "value": 1.0, "unit": "images/sec",
          "device_kind": "cpu"}], banked)
    assert compared == 0 and regs == []
    regs, compared = bd.compare(
        [{"metric": "m1", "value": 1.0, "unit": "images/sec",
          "device_kind": "cpu"}], banked, ignore_device=True)
    assert compared == 1 and len(regs) == 1


def test_bench_diff_selftest_on_real_banked_files():
    """The CI self-test must hold against the repo's committed
    BENCH_r0*.json set."""
    bd = _bench_diff()
    assert bd.main(["--selftest"]) == 0


def test_bench_diff_newest_banked_record_wins_per_device(tmp_path):
    bd = _bench_diff()
    old = str(tmp_path / "a.json")
    new = str(tmp_path / "b.json")
    other = str(tmp_path / "c.json")
    with open(old, "w") as fout:
        json.dump({"parsed": {"metric": "m", "value": 10.0,
                              "unit": "images/sec", "ts": 100,
                              "device_kind": "tpu"}}, fout)
    with open(new, "w") as fout:
        json.dump({"parsed": {"metric": "m", "value": 20.0,
                              "unit": "images/sec", "ts": 200,
                              "device_kind": "tpu"}}, fout)
    with open(other, "w") as fout:
        json.dump({"parsed": {"metric": "m", "value": 1.0,
                              "unit": "images/sec", "ts": 300,
                              "device_kind": "cpu"}}, fout)
    banked = bd.load_banked([other, new, old])  # order must not matter
    # newest per (metric, device): the newer CPU line never evicts
    # the TPU gate for the same metric
    assert banked[("m", "tpu")]["value"] == 20.0
    assert banked[("m", "cpu")]["value"] == 1.0
    regs, compared = bd.compare(
        [{"metric": "m", "value": 5.0, "unit": "images/sec",
          "device_kind": "tpu"}], banked)
    assert compared == 1 and len(regs) == 1    # gated vs the TPU line


def test_bench_diff_step_units_stay_lower_better():
    """'sec/step' must not classify as a rate ('/s' is a substring of
    '/step') — a 2x-slower step time is a regression, not a win."""
    bd = _bench_diff()
    assert bd.value_direction({"unit": "sec/step"}) == -1
    assert bd.value_direction({"unit": "ms/step"}) == -1
    banked = {("m", "cpu"): {"metric": "m", "value": 1.0,
                             "unit": "sec/step", "device_kind": "cpu"}}
    regs, compared = bd.compare(
        [{"metric": "m", "value": 2.0, "unit": "sec/step",
          "device_kind": "cpu"}], banked)
    assert compared == 1 and len(regs) == 1
