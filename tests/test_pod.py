"""veles_tpu.pod — one-pod-one-program training: the parity, wire,
elastic-membership and observability acceptance gates, plus the
plumbing it rides (mesh_from_topology, Vector shardings, the V-P02
preflight, the mesh-sharded InferenceEngine port).

The suite runs on the conftest's 8-device virtual CPU mesh, so every
sharded path here exercises real multi-device GSPMD programs."""

import threading
import time

import numpy
import pytest

from veles_tpu import chaos, prof
from veles_tpu.backends import NumpyDevice
from veles_tpu.parallel.jobs import JobServer
from veles_tpu.parallel.mesh import (MeshTopologyError,
                                     mesh_from_topology)
from veles_tpu.pod import (PodError, PodMaster, PodRuntime, PodWorker,
                           eval_metrics, train_epochs)
from veles_tpu.pod.__main__ import SMOKE_EPOCHS, make_workflow

EPOCHS = SMOKE_EPOCHS


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    chaos.controller.disarm()


@pytest.fixture
def live_trace():
    """Knob-based trace enabling (workflow initialize() re-reads the
    knob — mirrors tests/test_chaos.py)."""
    from veles_tpu import trace
    from veles_tpu.config import root
    saved = root.common.engine.get("trace", "off")
    root.common.engine.trace = "on"
    trace.recorder.clear()
    trace.configure()
    yield trace
    root.common.engine.trace = saved
    trace.configure()
    trace.recorder.clear()


def final_weights(wf):
    wf.forwards[0].weights.map_read()
    return numpy.array(wf.forwards[0].weights.mem)


def run_reference(epochs=EPOCHS):
    """Single-device stitched oracle, driven by the SAME per-epoch
    stepper the pod worker uses."""
    wf = make_workflow(max_epochs=epochs)
    for _ in train_epochs(wf, epochs):
        pass
    return wf


# -- mesh_from_topology ------------------------------------------------------

def test_mesh_from_topology_spellings():
    mesh = mesh_from_topology("auto")
    assert mesh.shape["data"] == 8
    assert mesh_from_topology(4).shape == {"data": 4}
    mesh = mesh_from_topology("4x2")
    assert mesh.shape == {"data": 4, "model": 2}
    mesh = mesh_from_topology({"data": -1, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    mesh = mesh_from_topology(None, require=("data", "model"))
    assert mesh.shape == {"data": 8, "model": 1}


def test_mesh_from_topology_typed_errors():
    with pytest.raises(MeshTopologyError):
        mesh_from_topology({"data": 3})          # 3 does not match 8
    with pytest.raises(MeshTopologyError):
        mesh_from_topology({"data": 3, "model": -1})   # 8 % 3
    with pytest.raises(MeshTopologyError):
        mesh_from_topology({"data": -1, "model": -1})  # two wildcards
    with pytest.raises(MeshTopologyError):
        mesh_from_topology({"data": 0})
    with pytest.raises(MeshTopologyError):
        mesh_from_topology("2x2x2")
    with pytest.raises(MeshTopologyError):
        mesh_from_topology("banana")


def test_mesh_from_topology_single_device_fallback():
    import jax
    one = jax.devices()[:1]
    mesh = mesh_from_topology({"data": 8}, devices=one)
    assert mesh.shape == {"data": 1}, \
        "one device must fall back transparently, whatever the knob"


# -- Vector shardings --------------------------------------------------------

def test_vector_set_sharding_preserves_and_places():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veles_tpu.backends import AutoDevice
    from veles_tpu.memory import Vector
    mesh = mesh_from_topology("auto")
    vec = Vector(numpy.arange(64, dtype=numpy.float32))
    vec.initialize(AutoDevice())
    before = numpy.array(vec.devmem)            # single-device upload
    vec.set_sharding(NamedSharding(mesh, P("data")))
    dev = vec.devmem
    assert dev.sharding.is_equivalent_to(
        NamedSharding(mesh, P("data")), 1)
    numpy.testing.assert_array_equal(numpy.asarray(dev), before)
    # values survive a reshard back to replicated, and clearing
    # restores plain device puts
    vec.set_sharding(NamedSharding(mesh, P()))
    numpy.testing.assert_array_equal(numpy.asarray(vec.devmem), before)
    vec.set_sharding(None)
    assert vec.sharding is None
    numpy.testing.assert_array_equal(numpy.asarray(vec.devmem), before)


# -- install preconditions ---------------------------------------------------

def test_pod_requires_stitched_workflow():
    wf = make_workflow(device=NumpyDevice())    # interpret: no segments
    with pytest.raises(PodError):
        PodRuntime(wf).install()


def test_pod_requires_divisible_batch():
    wf = make_workflow(batch=60)                # 60 % 8 != 0
    with pytest.raises(PodError):
        PodRuntime(wf).install()


# -- THE parity gate ---------------------------------------------------------

def test_pod_parity_gate():
    """Acceptance: on the 8-device mesh, pod training produces eval
    metrics equal to the single-device stitched run AND the ZMQ
    master–slave run it replaces, with final weights within numerical
    tolerance (the psum reorders float reductions — bitwise equality
    is not the contract)."""
    from veles_tpu.parallel.jobs import JobClient

    reference_wf = run_reference()
    reference = eval_metrics(reference_wf)
    assert reference["complete"]

    # the pod run (standalone runtime — membership adds control
    # frames, not numerics)
    pod_wf = make_workflow()
    pod = PodRuntime(pod_wf, mesh=mesh_from_topology("auto"))
    pod.install()
    assert pod.shards == 8
    for _ in train_epochs(pod_wf, EPOCHS):
        pass
    pod_metrics = eval_metrics(pod_wf)

    # the ZMQ per-minibatch master–slave run this path replaces
    zmq_master = make_workflow(device=NumpyDevice(), is_master=True)
    zmq_slave = make_workflow(is_slave=True)
    server = JobServer(zmq_master).start()
    try:
        client = JobClient(zmq_slave, server.endpoint,
                           rpc_timeout_ms=2000)
        client.handshake()
        assert client.run() is True
        client.close()
    finally:
        server.stop()
    zmq_metrics = eval_metrics(zmq_master)

    for key in ("complete", "epochs", "best_n_err_pt"):
        assert pod_metrics[key] == reference[key], \
            (key, pod_metrics, reference)
        assert pod_metrics[key] == zmq_metrics[key], \
            (key, pod_metrics, zmq_metrics)
    numpy.testing.assert_allclose(
        final_weights(pod_wf), final_weights(reference_wf),
        rtol=0, atol=5e-5)


# -- THE wire gate -----------------------------------------------------------

def test_pod_wire_gate_zero_per_step_frames():
    """Acceptance: steady-state pod training exchanges ZERO per-step
    gradient/update frames over ZMQ — chaos wire-site counters are
    the probe — and control traffic is O(heartbeats + epochs)."""
    chaos.controller.arm([], seed=1)            # counters only
    recompiles_before = prof.ledger.recompiles
    master_wf = make_workflow(device=NumpyDevice())
    master = PodMaster(master_wf, pods=1, epochs=EPOCHS)
    server = JobServer(master, heartbeat_interval=0.3).start()
    worker = PodWorker(make_workflow(), server.endpoint,
                       rpc_timeout_ms=4000)
    try:
        assert worker.run() is True
    finally:
        worker.close()
        server.stop()
    minibatches = EPOCHS * (512 // 64)
    update_frames = chaos.controller.frames("master_recv", "update")
    epoch_frames = chaos.controller.frames("master_recv", "pod_epoch")
    assert update_frames == 1, \
        "exactly ONE update frame (the final lease result) may ride " \
        "the wire; saw %d for %d minibatches trained" % (
            update_frames, minibatches)
    assert 1 <= epoch_frames <= EPOCHS, \
        "control plane must be O(epochs): %d" % epoch_frames
    assert chaos.controller.frames("master_send", "job") < minibatches
    # the final update installed the pod-trained weights on the master
    assert master.done, "lease never completed"
    assert prof.ledger.recompiles == recompiles_before, \
        "pod steady state must not retrace"
    # per-shard ledger dimension: the segment entries carry the axis
    pod_entries = [e for e in prof.ledger.entries("segment")
                   if e.shards == 8]
    assert pod_entries, "no segment entry carries the shard dimension"
    assert any(e.psum_bytes > 0 for e in pod_entries), \
        "gradient psum traffic never accounted"
    report = prof.report_text()
    assert "pod:" in report and "psum" in report


# -- elastic membership (THE chaos satellite pack) ---------------------------

def test_pod_elastic_chip_kill_parity(live_trace, tmp_path):
    """A seeded chaos schedule kills one simulated chip mid-epoch: the
    pod must reshard (8 -> 4 under the halving policy), bump its
    generation, report it upstream on the next epoch sync, and STILL
    converge to eval parity with the fault-free run — with the
    reshard and its provoking injection visible in the merged
    Perfetto timeline as one pod pid with per-shard lanes."""
    reference = eval_metrics(run_reference())

    chaos.controller.arm([
        {"site": "pod_chip", "action": "chip_kill", "nth": 5},
        {"site": "slave_send", "action": "dup", "op": "update",
         "nth": 1},
    ], seed=7)
    master_wf = make_workflow(device=NumpyDevice())
    master = PodMaster(master_wf, pods=1, epochs=EPOCHS)
    server = JobServer(master, heartbeat_interval=0.3).start()
    worker = PodWorker(make_workflow(), server.endpoint,
                       rpc_timeout_ms=4000)
    try:
        assert worker.run() is True
    finally:
        worker.close()
        bundle_path = str(tmp_path / "pod_session.json")
        server.save_session_profile(bundle_path)
        server.stop()
    injected = chaos.controller.snapshot()["injected"]
    assert injected.get("chip_kill") == 1, injected
    assert worker.runtime.reshards == 1
    assert worker.runtime.shards == 4, \
        "halving policy: 8 devices minus one -> 4-shard data axis"
    assert worker.runtime.generation == 2, \
        "an elastic reshard must bump the generation"
    # ...and the control plane saw the bump
    progress = master.progress.get("pod-0")
    assert progress and progress["generation"] == 2, progress
    # duplicated final update deduplicated by the PR 7 machinery
    assert server.dedup_dropped >= 1
    # eval parity with the fault-free run
    metrics = (master.done.get("pod-0") or {}).get("metrics") or {}
    assert metrics.get("complete") is True
    assert abs(metrics["best_n_err_pt"]
               - reference["best_n_err_pt"]) <= 2.0, \
        (metrics, reference)

    # observability: reshard + injection + per-shard lanes, merged
    assert live_trace.recorder.count("pod", "reshard") == 1
    assert live_trace.recorder.count("chaos") >= 2
    merged = prof.merge.merged_events(prof.merge.load(bundle_path))
    pod_events = [ev for ev in merged if ev.get("role") == "pod"]
    names = {(ev.get("cat"), ev.get("name")) for ev in merged}
    assert ("pod", "reshard") in names
    assert ("chaos", "chip_kill") in names
    lanes = {ev["tid"] for ev in pod_events
             if ev.get("name") == "shard_dispatch"}
    assert {0, 1, 2, 3} <= lanes, \
        "one pod pid must carry a dispatch lane per shard: %r" % lanes


def test_pod_master_kill_and_resume(live_trace):
    """Master crash-recovery on the pod path: kill the master
    mid-lease, restart a fresh one on the same port — the worker
    reconnects, the requeued lease is re-granted, and the worker
    RESUMES from its local epoch counter (its training state never
    left its HBM), completing with eval parity.  The pre-restart
    final update is stale-rejected, the re-granted lease's answer
    applies (PR 7 exactly-once)."""
    reference = eval_metrics(run_reference(epochs=EPOCHS))

    master1 = PodMaster(make_workflow(device=NumpyDevice()),
                        pods=1, epochs=EPOCHS)
    server1 = JobServer(master1, heartbeat_interval=0.3,
                        slave_timeout=8.0).start()
    port = server1.port
    worker = PodWorker(make_workflow(), server1.endpoint,
                       rpc_timeout_ms=1200, reconnect_max_wait=20.0)
    done = []
    runner = threading.Thread(target=lambda: done.append(worker.run()))
    runner.start()
    # wait for at least one epoch sync, then "crash" the master
    deadline = time.time() + 60
    while time.time() < deadline and not master1.progress:
        time.sleep(0.02)
    assert master1.progress, "no epoch sync before the kill"
    server1.kill()

    import zmq
    master2 = PodMaster(make_workflow(device=NumpyDevice()),
                        pods=1, epochs=EPOCHS)
    # the killed server's ROUTER releases the endpoint asynchronously
    # (stop() joins the loop thread with a bound) — retry the rebind
    # like a restarted process's supervisor would
    for _ in range(80):
        try:
            server2 = JobServer(master2, port=port,
                                heartbeat_interval=0.3,
                                slave_timeout=8.0)
            break
        except zmq.error.ZMQError:
            time.sleep(0.25)
    else:
        pytest.fail("killed master's endpoint never released")
    server2.start()
    try:
        runner.join(120)
        assert not runner.is_alive(), "pod session hung after restart"
        assert done == [True]
    finally:
        worker.close()
        server2.stop()
    assert master2.done.get("pod-0"), \
        "the re-granted lease must deliver its final update"
    assert worker._progress.get("pod-0") == EPOCHS
    metrics = master2.done["pod-0"]["metrics"]
    assert metrics.get("complete") is True
    assert abs(metrics["best_n_err_pt"]
               - reference["best_n_err_pt"]) <= 2.0


def test_pod_lease_requeued_on_drop():
    """Elastic membership at the lease level: a dropped worker's
    unfinished lease goes back on the queue and the next worker
    finishes it."""
    from veles_tpu.parallel.jobs import SlaveDescription
    master = PodMaster(make_workflow(device=NumpyDevice()),
                       pods=1, epochs=1)
    slave = SlaveDescription("w1")
    lease = master.generate_data_for_slave(slave)
    assert lease["pod_lease"]["lease"] == "pod-0"
    master.drop_slave(slave)
    other = SlaveDescription("w2")
    again = master.generate_data_for_slave(other)
    assert again["pod_lease"]["lease"] == "pod-0", \
        "the dropped worker's lease must be re-granted"
    from veles_tpu.workflow import NoJobYet
    with pytest.raises(NoJobYet):
        master.generate_data_for_slave(slave)


# -- V-P02 -------------------------------------------------------------------

def test_check_pod_batch_and_budget_and_segments():
    from veles_tpu.analyze import check_pod, rule_catalog
    assert "V-P02" in rule_catalog()
    wf = make_workflow()
    mesh = mesh_from_topology("auto")
    clean = check_pod(wf, mesh)
    assert not clean.has_errors, clean.render_text()
    # batch divisibility
    report = check_pod(wf, mesh, batch_size=60)
    assert any(f.rule == "V-P02" and "divide" in f.message
               for f in report.errors())
    # per-shard residency vs a toy HBM budget
    report = check_pod(wf, mesh, hbm_bytes=1024)
    assert any(f.rule == "V-P02" and "residency" in f.message
               for f in report.errors())
    # param_rules move the check: leaves the rules shard count at
    # 1/shards, so the documented remedy (fsdp_rules/tp_rules) can
    # actually turn a failing residency plan into a passing one —
    # there must exist a budget the replicated plan busts and the
    # sharded plan fits
    from jax.sharding import PartitionSpec as P

    def residency_error(budget, rules=None):
        rep = check_pod(wf, mesh, hbm_bytes=budget, param_rules=rules)
        return any("residency" in f.message for f in rep.errors())

    shard_all = lambda leaf: P("data")     # noqa: E731
    boundary = [b for b in range(1024, 65536, 512)
                if residency_error(b) and not residency_error(
                    b, rules=shard_all)]
    assert boundary, \
        "sharding every param leaf must lower per-shard residency"
    # no data axis at all
    report = check_pod(wf, mesh, data_axis="nope")
    assert report.has_errors
    # an unstitched workflow is named, not crashed on
    loose = make_workflow(device=NumpyDevice())
    report = check_pod(loose, mesh)
    assert any("no stitched segments" in f.message for f in report)


def test_pod_preflight_fail_mode():
    wf = make_workflow(batch=64)
    pod = PodRuntime(wf, preflight="fail")
    pod.install()       # clean plan passes in fail mode
    pod.uninstall()


# -- the serve-engine mesh port ----------------------------------------------

def test_inference_engine_mesh_parity_and_fallback():
    """The gen engine's declarative mesh-sharded forward, ported: the
    same trained workflow served through a pjit'd engine answers
    byte-identically to the single-device engine; a None/1-device
    mesh IS the single-device path."""
    from veles_tpu.serve.engine import InferenceEngine
    wf = run_reference(epochs=1)
    batch = numpy.random.default_rng(3).standard_normal(
        (8, 16)).astype(numpy.float32)
    plain = InferenceEngine.from_workflow(wf, max_batch_size=8)
    plain.warmup()
    sharded = InferenceEngine.from_workflow(
        wf, max_batch_size=8, mesh=mesh_from_topology("auto"))
    assert sharded.mesh is not None
    sharded.warmup()
    numpy.testing.assert_array_equal(plain.infer(batch),
                                     sharded.infer(batch))
    # TP-style param rule: column-shard the hidden layer, still exact
    from jax.sharding import PartitionSpec as P

    def rule(leaf):
        shape = numpy.shape(leaf)
        if len(shape) == 2 and shape[-1] % 8 == 0:
            return P(None, "data")
        return None

    tp = InferenceEngine.from_workflow(
        wf, max_batch_size=8, mesh=mesh_from_topology("auto"),
        param_specs=rule)
    tp.warmup()
    numpy.testing.assert_allclose(tp.infer(batch), plain.infer(batch),
                                  rtol=0, atol=1e-5)
    # single-device fallback: no pjit wrapper at all
    import jax
    one_mesh = mesh_from_topology({"data": 8},
                                  devices=jax.devices()[:1])
    fallback = InferenceEngine.from_workflow(
        wf, max_batch_size=8, mesh=one_mesh)
    assert fallback.mesh is None
    numpy.testing.assert_array_equal(plain.infer(batch),
                                     fallback.infer(batch))


# -- pod-of-pods (multi-host pods, pp/ep rules, device loss) -----------------

def test_multihost_pod_transparent_delegation():
    """A single-process MultiHostPod IS its PodRuntime: same install/
    uninstall lifecycle, describe() decorated with process topology,
    host_range covering the whole dataset."""
    from veles_tpu.pod import MultiHostPod
    wf = make_workflow(max_epochs=1)
    pod = MultiHostPod(wf)
    assert pod.process_count == 1
    assert pod.process_index == 0
    assert pod.is_coordinator
    assert pod.host_range(64) == (0, 64)
    pod.install()
    try:
        assert pod.runtime.installed
        desc = pod.describe()
        assert desc["processes"] == 1
        assert desc["process_index"] == 0
        assert desc["coordinator"] is True
        assert desc["shards"] == pod.runtime.shards
        # assemble: identity placement on one process
        local = numpy.zeros((16, 4), numpy.float32)
        out = pod.assemble(local)
        assert out.shape == (16, 4)
    finally:
        pod.uninstall()
    assert not pod.runtime.installed


def test_device_loss_detector_heartbeat_reshard(live_trace):
    """A silent host is declared lost after ``timeout``: one
    ``jobs:heartbeat_stall`` instant per host, ONE reshard dropping
    its devices_per_host chips, no re-loss on the next poll."""
    from veles_tpu.pod import DeviceLossDetector
    wf = make_workflow(max_epochs=1)
    runtime = PodRuntime(wf, mesh=mesh_from_topology(
        {"data": -1}, require=("data",)))
    runtime.install()
    try:
        clock = {"now": 100.0}
        det = DeviceLossDetector(runtime, timeout=5.0,
                                 devices_per_host=4,
                                 clock=lambda: clock["now"])
        det.beat("host-0")
        det.beat("host-1")
        assert det.hosts() == ["host-0", "host-1"]
        assert det.poll() == []                 # everyone fresh
        clock["now"] += 10.0
        det.beat("host-0")                      # host-0 stays alive
        gen = runtime.generation
        shards = runtime.shards
        stalls = live_trace.recorder.count("jobs", "heartbeat_stall")
        assert det.poll() == ["host-1"]
        assert det.stalls == 1
        assert runtime.generation == gen + 1
        assert runtime.shards == shards - 4
        assert live_trace.recorder.count(
            "jobs", "heartbeat_stall") == stalls + 1
        # the lost host left the table: no repeated reshard
        assert det.poll() == []
        assert det.hosts() == ["host-0"]
        assert runtime.generation == gen + 1
    finally:
        runtime.uninstall()


def test_device_loss_detector_dispatch_failure():
    """Typed classification: an UNAVAILABLE-style runtime error
    reshards and returns True (retry); anything else returns False
    (re-raise) and never touches the mesh."""
    from veles_tpu.pod import DeviceLossDetector, is_device_loss
    assert is_device_loss(RuntimeError("UNAVAILABLE: socket closed"))
    assert is_device_loss(RuntimeError(
        "device lost: slice health check failed"))
    assert is_device_loss(RuntimeError("DEADLINE EXCEEDED waiting"))
    assert not is_device_loss(RuntimeError("Invalid argument: dim 3"))
    assert not is_device_loss(ValueError("unavailable"))
    assert not is_device_loss(None)
    wf = make_workflow(max_epochs=1)
    runtime = PodRuntime(wf, mesh=mesh_from_topology(
        {"data": -1}, require=("data",)))
    runtime.install()
    try:
        det = DeviceLossDetector(runtime, devices_per_host=4)
        gen = runtime.generation
        assert not det.dispatch_failure(ValueError("shape mismatch"))
        assert runtime.generation == gen
        assert det.dispatch_failure(
            RuntimeError("UNAVAILABLE: connection reset by peer"))
        assert det.dispatch_losses == 1
        assert runtime.generation == gen + 1
    finally:
        runtime.uninstall()


def test_pp_ep_rules_shard_leading_dim():
    """pp_rules/ep_rules: stage/expert-stacked leaves shard their
    leading dim over the pipe/expert axis, everything else (scalars,
    small leaves, indivisible leading dims) replicates."""
    import pytest
    from jax.sharding import PartitionSpec as P

    from veles_tpu.parallel.dp import ep_rules, pp_rules
    from veles_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"data": 2, "pipe": 4})
    rules = pp_rules(mesh, min_elements=64)
    assert rules(numpy.zeros((4, 32, 32))) == P("pipe", None, None)
    assert rules(numpy.zeros((8, 64))) == P("pipe", None)
    assert rules(numpy.zeros((3, 64, 64))) is None   # 3 % 4 != 0
    assert rules(numpy.zeros((4, 2))) is None        # too small
    assert rules(numpy.float32(0.5)) is None         # scalar
    with pytest.raises(ValueError):
        pp_rules(make_mesh({"data": -1}))            # no pipe axis
    emesh = make_mesh({"data": 2, "expert": 4})
    erules = ep_rules(emesh, min_elements=64)
    assert erules(numpy.zeros((4, 16, 32))) == P("expert", None, None)
    assert erules(numpy.zeros((4, 8))) is None       # below min_elements
