"""Backend registry + device tests (ref ``veles/tests/`` backend coverage;
runs on the virtual 8-device CPU mesh from conftest)."""

import numpy
import pytest

from veles_tpu.backends import (
    AutoDevice, BackendRegistry, CPUDevice, DeviceInfo, NumpyDevice,
    TPUDevice, make_device)


def test_registry_has_all_backends():
    for name in ("tpu", "cpu", "numpy", "auto"):
        assert name in BackendRegistry.backends


def test_numpy_device_roundtrip():
    dev = NumpyDevice()
    assert dev.exists and dev.is_interpret
    arr = numpy.arange(6, dtype=numpy.float32)
    assert (dev.get(dev.put(arr)) == arr).all()


def test_cpu_device_mesh():
    dev = CPUDevice()
    assert dev.exists
    assert dev.num_devices == 8      # conftest forces 8 virtual devices
    mesh = dev.mesh                  # default: data axis absorbs all
    assert mesh.shape["data"] == 8


def test_custom_mesh_axes():
    dev = CPUDevice()
    mesh = dev.make_mesh({"data": 2, "model": 4})
    assert mesh.shape == {"data": 2, "model": 4}
    mesh2 = dev.make_mesh({"data": -1, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}


def test_cpu_put_get_sync():
    dev = CPUDevice()
    arr = numpy.random.rand(4, 4).astype(numpy.float32)
    dev_arr = dev.put(arr)
    dev.sync()
    assert numpy.allclose(dev.get(dev_arr), arr)


def test_auto_device_picks_best_existing():
    dev = AutoDevice()
    # No TPU under the forced-CPU test env → CPU (priority 20) wins
    # over numpy (priority 10).
    assert dev.BACKEND in ("tpu", "cpu")


def test_make_device_by_name():
    assert make_device("numpy").is_interpret
    with pytest.raises(ValueError):
        make_device("opencl")


def test_tpu_device_absent_under_cpu_env():
    dev = TPUDevice()
    assert not dev.exists


def test_device_pickle_roundtrip():
    import pickle
    dev = CPUDevice()
    restored = pickle.loads(pickle.dumps(dev))
    assert restored.exists
    assert restored.num_devices == 8


def test_device_info_db_roundtrip(tmp_path):
    info = DeviceInfo("TPU v5e")
    info.ratings = {"gemm": {"float32": {"time": 0.01,
                                         "tiles": [256, 512, 256]}}}
    path = str(tmp_path / "device_infos.json")
    DeviceInfo.save_db({"TPU v5e": info}, path)
    db = DeviceInfo.load_db(path)
    assert db["TPU v5e"].get_kernel_tiles("gemm", "float32") == \
        [256, 512, 256]
    assert db["TPU v5e"].get_kernel_tiles("gemm", "bfloat16",
                                          default=[128, 128, 128]) == \
        [128, 128, 128]


def test_device_info_load_db_unwraps_autotune_envelope(tmp_path):
    # scripts.autotune prints a {"devices": ..., "_this_run": ...}
    # envelope; a DB file saved from that stdout must load as if it
    # were the flat table, with _this_run treated as provenance only
    import json
    path = str(tmp_path / "device_infos.json")
    envelope = {
        "devices": {"TPU v5e": {"gemm": {"float32": {
            "tiles": [256, 512, 256]}}}},
        "_this_run": {"device_kind": "TPU v5e", "ts": 0.0, "argv": []},
    }
    with open(path, "w") as fout:
        json.dump(envelope, fout)
    db = DeviceInfo.load_db(path)
    assert "_this_run" not in db
    assert db["TPU v5e"].get_kernel_tiles("gemm", "float32") == \
        [256, 512, 256]
    # a flat DB that happens to contain a model named "devices" plus
    # another real model is NOT an envelope and must load untouched
    flat = {"devices": {"gemm": {}}, "TPU v4": {"gemm": {}}}
    with open(path, "w") as fout:
        json.dump(flat, fout)
    assert set(DeviceInfo.load_db(path)) == {"devices", "TPU v4"}


def test_autotune_sweep_merges_per_device_model(tmp_path):
    # re-running a sweep on a SECOND device kind must not clobber the
    # first's ratings, even when the DB file is a redirected stdout
    # envelope (_this_run stays last-run-only, never a device entry)
    import json

    from veles_tpu.ops.benchmark import autotune_gd

    path = str(tmp_path / "device_infos.json")
    first = DeviceInfo("TPU v4")
    first.ratings["gemm"] = {"float32": [256, 256, 256]}
    with open(path, "w") as fout:
        json.dump({"devices": {"TPU v4": first.ratings},
                   "_this_run": {"device_kind": "TPU v4", "ts": 1.0}},
                  fout)
    autotune_gd(shape=(16, 128, 64), runs=1, db_path=path)
    db = DeviceInfo.load_db(path)
    assert "_this_run" not in db
    assert db["TPU v4"].ratings["gemm"] == {"float32": [256, 256, 256]}
    others = [m for m in db if m != "TPU v4"]
    assert others and any("gd_v2" in db[m].ratings for m in others)
