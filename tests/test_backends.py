"""Backend registry + device tests (ref ``veles/tests/`` backend coverage;
runs on the virtual 8-device CPU mesh from conftest)."""

import numpy
import pytest

from veles_tpu.backends import (
    AutoDevice, BackendRegistry, CPUDevice, DeviceInfo, NumpyDevice,
    TPUDevice, make_device)


def test_registry_has_all_backends():
    for name in ("tpu", "cpu", "numpy", "auto"):
        assert name in BackendRegistry.backends


def test_numpy_device_roundtrip():
    dev = NumpyDevice()
    assert dev.exists and dev.is_interpret
    arr = numpy.arange(6, dtype=numpy.float32)
    assert (dev.get(dev.put(arr)) == arr).all()


def test_cpu_device_mesh():
    dev = CPUDevice()
    assert dev.exists
    assert dev.num_devices == 8      # conftest forces 8 virtual devices
    mesh = dev.mesh                  # default: data axis absorbs all
    assert mesh.shape["data"] == 8


def test_custom_mesh_axes():
    dev = CPUDevice()
    mesh = dev.make_mesh({"data": 2, "model": 4})
    assert mesh.shape == {"data": 2, "model": 4}
    mesh2 = dev.make_mesh({"data": -1, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}


def test_cpu_put_get_sync():
    dev = CPUDevice()
    arr = numpy.random.rand(4, 4).astype(numpy.float32)
    dev_arr = dev.put(arr)
    dev.sync()
    assert numpy.allclose(dev.get(dev_arr), arr)


def test_auto_device_picks_best_existing():
    dev = AutoDevice()
    # No TPU under the forced-CPU test env → CPU (priority 20) wins
    # over numpy (priority 10).
    assert dev.BACKEND in ("tpu", "cpu")


def test_make_device_by_name():
    assert make_device("numpy").is_interpret
    with pytest.raises(ValueError):
        make_device("opencl")


def test_tpu_device_absent_under_cpu_env():
    dev = TPUDevice()
    assert not dev.exists


def test_device_pickle_roundtrip():
    import pickle
    dev = CPUDevice()
    restored = pickle.loads(pickle.dumps(dev))
    assert restored.exists
    assert restored.num_devices == 8


def test_device_info_db_roundtrip(tmp_path):
    info = DeviceInfo("TPU v5e")
    info.ratings = {"gemm": {"float32": {"time": 0.01,
                                         "tiles": [256, 512, 256]}}}
    path = str(tmp_path / "device_infos.json")
    DeviceInfo.save_db({"TPU v5e": info}, path)
    db = DeviceInfo.load_db(path)
    assert db["TPU v5e"].get_kernel_tiles("gemm", "float32") == \
        [256, 512, 256]
    assert db["TPU v5e"].get_kernel_tiles("gemm", "bfloat16",
                                          default=[128, 128, 128]) == \
        [128, 128, 128]
