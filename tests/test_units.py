"""Core graph tests: gates, links, scheduling, loops.

Mirrors reference ``veles/tests/test_units.py`` (gates/links) and
``test_workflow.py`` coverage.
"""

import pickle

import pytest

from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import Unit


def test_link_from_builds_edges():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    b.link_from(a)
    assert a in b.links_from
    assert b in a.links_to


def test_open_gate_requires_all_inputs():
    wf = DummyWorkflow()
    a = DummyUnit(wf)
    b = DummyUnit(wf)
    c = DummyUnit(wf)
    c.link_from(a, b)
    assert not c.open_gate(a)
    assert c.open_gate(b)          # both fired → open and reset
    assert not c.open_gate(a)      # reset worked


def test_linear_run():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    a.link_from(wf.start_point)
    b.link_from(a)
    wf.end_point.link_from(b)
    wf.initialize()
    wf.run()
    assert a.run_count == 1
    assert b.run_count == 1
    assert wf.stopped


def test_diamond_runs_join_once():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b1 = DummyUnit(wf, name="b1")
    b2 = DummyUnit(wf, name="b2")
    c = DummyUnit(wf, name="c")
    a.link_from(wf.start_point)
    b1.link_from(a)
    b2.link_from(a)
    c.link_from(b1, b2)
    wf.end_point.link_from(c)
    wf.initialize()
    wf.run()
    assert c.run_count == 1


def test_gate_block_stops_propagation():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    a.link_from(wf.start_point)
    b.link_from(a)
    wf.end_point.link_from(a)   # alternate path to finish
    b.gate_block <<= True
    wf.initialize()
    wf.run()
    assert b.run_count == 0


def test_gate_skip_propagates_without_running():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    c = DummyUnit(wf, name="c")
    a.link_from(wf.start_point)
    b.link_from(a)
    c.link_from(b)
    wf.end_point.link_from(c)
    b.gate_skip <<= True
    wf.initialize()
    wf.run()
    assert b.run_count == 0
    assert c.run_count == 1


def test_repeater_loop_with_decision_gate():
    """The canonical training loop shape: repeater → body → decision;
    decision's gate_block on the back edge ends the loop."""
    wf = DummyWorkflow()
    rep = Repeater(wf)
    body = DummyUnit(wf, name="body")
    complete = Bool(False)

    class Decision(Unit):
        def __init__(self, workflow, **kwargs):
            super(Decision, self).__init__(workflow, **kwargs)
            self.n = 0

        def run(self):
            nonlocal complete
            self.n += 1
            if self.n >= 5:
                complete <<= True

    dec = Decision(wf)
    rep.link_from(wf.start_point)
    body.link_from(rep)
    dec.link_from(body)
    rep.link_from(dec)             # back edge
    rep.gate_block = complete      # loop exit
    wf.end_point.link_from(dec)
    wf.end_point.gate_block = ~complete
    wf.initialize()
    wf.run()
    assert body.run_count == 5
    assert wf.stopped


def test_deep_loop_no_stack_overflow():
    """10k iterations through the queue scheduler — would overflow a
    recursive scheduler."""
    wf = DummyWorkflow()
    rep = Repeater(wf)
    complete = Bool(False)

    class Counter(Unit):
        def __init__(self, workflow, **kwargs):
            super(Counter, self).__init__(workflow, **kwargs)
            self.n = 0

        def run(self):
            nonlocal complete
            self.n += 1
            if self.n >= 10000:
                complete <<= True

    cnt = Counter(wf)
    rep.link_from(wf.start_point)
    cnt.link_from(rep)
    rep.link_from(cnt)
    rep.gate_block = complete
    wf.end_point.link_from(cnt)
    wf.end_point.gate_block = ~complete
    wf.initialize()
    wf.run()
    assert cnt.n == 10000


def test_link_attrs_aliases_values():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    a.output = 42
    b.link_attrs(a, ("input", "output"))
    assert b.input == 42
    a.output = 43
    assert b.input == 43


def test_one_way_link_write_raises():
    wf = DummyWorkflow()
    a = DummyUnit(wf)
    b = DummyUnit(wf)
    a.output = 1
    b.link_attrs(a, ("input", "output"))
    with pytest.raises(RuntimeError):
        b.input = 99
    assert b.input == 1     # alias intact


def test_bool_expression_survives_pickle():
    """Gate expressions stay live through snapshot/restore: flipping the
    restored operand re-opens the restored gate."""
    flag = Bool(False)
    gate = ~flag
    flag2, gate2 = pickle.loads(pickle.dumps((flag, gate)))
    assert bool(gate2)
    flag2 <<= True
    assert not bool(gate2)


def test_initialize_bug_not_masked_by_requeue():
    """A genuine AttributeError inside initialize() surfaces immediately
    instead of being retried as a missing-demand."""
    wf = DummyWorkflow()
    calls = []

    class Buggy(Unit):
        def initialize(self, **kwargs):
            calls.append(1)
            return self.no_such_attribute

    Buggy(wf).link_from(wf.start_point)
    with pytest.raises(AttributeError):
        wf.initialize()
    assert len(calls) == 1


def test_apply_data_from_slave_length_mismatch():
    wf = DummyWorkflow()
    DummyUnit(wf, name="a").link_from(wf.start_point)
    with pytest.raises(ValueError):
        wf.apply_data_from_slave([None])   # 3 units (start/end/a), 1 entry


def test_link_attrs_two_way():
    wf = DummyWorkflow()
    a = DummyUnit(wf)
    b = DummyUnit(wf)
    a.output = 1
    b.link_attrs(a, ("input", "output"), two_way=True)
    b.input = 7
    assert a.output == 7


def test_demand_raises_on_missing():
    wf = DummyWorkflow()

    class Needy(Unit):
        def __init__(self, workflow, **kwargs):
            super(Needy, self).__init__(workflow, **kwargs)
            self.demand("input")

    needy = Needy(wf)
    needy.link_from(wf.start_point)
    wf.end_point.link_from(needy)
    with pytest.raises(AttributeError):
        wf.initialize()


def test_demand_satisfied_by_link():
    wf = DummyWorkflow()
    producer = DummyUnit(wf)
    producer.output = [1, 2]

    class Needy(Unit):
        def __init__(self, workflow, **kwargs):
            super(Needy, self).__init__(workflow, **kwargs)
            self.demand("input")

    needy = Needy(wf)
    needy.link_attrs(producer, ("input", "output"))
    needy.link_from(wf.start_point)
    wf.end_point.link_from(needy)
    wf.initialize()


def test_initialize_requeues_until_producer_ready():
    """Partial-init requeue (ref workflow.py:329-336): a unit demanded attr
    appears only after its producer's initialize()."""
    wf = DummyWorkflow()

    class Producer(Unit):
        def initialize(self, **kwargs):
            self.output = 99
            super(Producer, self).initialize(**kwargs)

    class Consumer(Unit):
        def __init__(self, workflow, **kwargs):
            super(Consumer, self).__init__(workflow, **kwargs)
            self.demand("input")

    prod = Producer(wf)
    cons = Consumer(wf)
    cons.link_attrs(prod, ("input", "output"))
    # Reverse control order so naive one-pass init would fail:
    cons.link_from(wf.start_point)
    prod.link_from(cons)
    wf.end_point.link_from(prod)
    wf.initialize()
    assert cons.input == 99


def test_bool_expressions():
    a = Bool(False)
    b = Bool(True)
    both = a & b
    either = a | b
    neither = ~either
    assert not both and either and not neither
    a <<= True
    assert both and either and not neither


def test_unit_pickles_without_transients():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="keepme")
    a.payload = [1, 2, 3]
    blob = pickle.dumps(a)
    restored = pickle.loads(blob)
    assert restored.name == "keepme"
    assert restored.payload == [1, 2, 3]
    assert hasattr(restored, "_gate_lock_")   # recreated by init_unpickled


def test_links_forward_after_unpickle_in_fresh_process():
    """Simulates unpickling in a process that never ran link(): the class
    has no _Forward descriptor until init_unpickled reinstalls it."""
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    b = DummyUnit(wf, name="b")
    a.output2 = 5
    b.link_attrs(a, ("input2", "output2"))
    blob = pickle.dumps(wf)
    delattr(DummyUnit, "input2")      # fresh-process class state
    restored = pickle.loads(blob)
    ra, rb = restored["a"], restored["b"]
    ra.output2 = 42
    assert rb.input2 == 42            # forwarding reinstalled


def test_workflow_checksum_stable():
    wf1 = DummyWorkflow()
    DummyUnit(wf1, name="x").link_from(wf1.start_point)
    wf2 = DummyWorkflow()
    DummyUnit(wf2, name="x").link_from(wf2.start_point)
    assert wf1.checksum() == wf2.checksum()


def test_generate_graph_dot():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    a.link_from(wf.start_point)
    dot = wf.generate_graph()
    assert dot.startswith("digraph") and "->" in dot


def test_checksum_distinguishes_workflows_and_fails_closed():
    """Checksum hashes module file bytes + graph structure; a class with
    no retrievable code raises instead of silently matching
    (ref ``veles/workflow.py:852-866`` hashes the workflow file)."""
    from veles_tpu.workflow import ChecksumError

    wf1 = DummyWorkflow()
    DummyUnit(wf1, name="a")
    wf2 = DummyWorkflow()
    DummyUnit(wf2, name="a")
    DummyUnit(wf2, name="b")
    assert wf1.checksum() != wf2.checksum()    # structure differs
    assert wf1.checksum() == wf1.checksum()    # deterministic

    ns = {}
    exec("from veles_tpu.units import Unit\n"
         "class ReplUnit(Unit):\n"
         "    def run(self): pass\n", ns)
    wf3 = DummyWorkflow()
    ns["ReplUnit"](wf3, name="repl")
    with pytest.raises(ChecksumError):
        wf3.checksum()


def test_force_numpy_pins_eager_path():
    """Documented common unit param force_numpy: the unit stays on the
    eager numpy path even with an accelerated device attached."""
    import numpy

    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.all2all import All2AllTanh

    wf = DummyWorkflow()
    unit = All2AllTanh(wf, output_sample_shape=(4,), force_numpy=True)
    unit.input = Vector(numpy.ones((2, 8), numpy.float32))
    unit.initialize(device=CPUDevice())

    called = {"tpu": 0}
    orig = unit.tpu_run

    def spy():
        called["tpu"] += 1
        return orig()

    unit.tpu_run = spy
    unit.run()
    assert called["tpu"] == 0
    assert unit.output.mem.shape == (2, 4)


def test_unit_hot_reload_live_instance(tmp_path, monkeypatch):
    """Unit.reload(): edit a unit's source mid-run, reload, and the
    LIVE instance (state intact) executes the new method body (ref
    units.py:672 xreload; re-designed on importlib + __class__
    re-pointing)."""
    import sys
    import textwrap

    monkeypatch.syspath_prepend(str(tmp_path))
    mod = tmp_path / "hotreload_demo_unit.py"
    mod.write_text(textwrap.dedent("""
        from veles_tpu.units import Unit

        class HotUnit(Unit):
            hide_from_registry = True
            def run(self):
                self.result = "v1-" + self.tag
    """))
    import importlib
    demo = importlib.import_module("hotreload_demo_unit")
    try:
        from veles_tpu.dummy import DummyWorkflow
        wf = DummyWorkflow()
        unit = demo.HotUnit(wf)
        unit.tag = "state"         # live state must survive the patch
        unit.run()
        assert unit.result == "v1-state"
        mod.write_text(mod.read_text().replace("v1-", "v2-"))
        remapped = demo.HotUnit.reload()
        assert remapped >= 1
        unit.run()                 # same instance, new body
        assert unit.result == "v2-state"
        assert unit.tag == "state"
    finally:
        sys.modules.pop("hotreload_demo_unit", None)
