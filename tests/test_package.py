"""Package export/round-trip tests (§2.8 seam): contents.json + npy in
zip/tgz, fp16 precision, PackagedRunner golden vs the live units —
mirrors the reference's packaged-model round-trip tests
(libVeles/tests/workflow_loader.cc against mnist.zip/mnist.tar.gz)."""

import io
import json
import zipfile

import numpy
import pytest

from veles_tpu.backends import NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.memory import Vector
from veles_tpu.package import (
    CONTENTS_NAME, PackagedRunner, export_package)
from veles_tpu.znicz.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.znicz.conv import ConvTanh
from veles_tpu.znicz.normalization_units import LRNormalizerForward
from veles_tpu.znicz.pooling import MaxPooling


def _build_convnet(x):
    """conv→pool→lrn→fc→softmax chain, run on NumpyDevice; returns
    (forwards, golden_output)."""
    wf = DummyWorkflow()
    dev = NumpyDevice()
    conv = ConvTanh(wf, n_kernels=4, kx=3, ky=3)
    conv.input = Vector(x.copy())
    conv.initialize(dev)
    conv.numpy_run()
    pool = MaxPooling(wf, kx=2, ky=2)
    pool.input = conv.output
    pool.initialize(dev)
    pool.numpy_run()
    lrn = LRNormalizerForward(wf)
    lrn.input = pool.output
    lrn.initialize(dev)
    lrn.numpy_run()
    fc = All2AllTanh(wf, output_sample_shape=(16,))
    fc.input = lrn.output
    fc.initialize(dev)
    fc.numpy_run()
    sm = All2AllSoftmax(wf, output_sample_shape=(10,))
    sm.input = fc.output
    sm.initialize(dev)
    sm.numpy_run()
    sm.output.map_read()
    return [conv, pool, lrn, fc, sm], numpy.array(sm.output.mem)


@pytest.fixture(scope="module")
def convnet():
    rng = numpy.random.default_rng(7)
    x = rng.standard_normal((3, 8, 8, 2)).astype(numpy.float32)
    forwards, golden = _build_convnet(x)
    return x, forwards, golden


def test_zip_round_trip(convnet, tmp_path):
    x, forwards, golden = convnet
    path = str(tmp_path / "model.zip")
    contents = export_package(forwards, path)
    assert contents["units"][0]["type"] == "conv_tanh"
    runner = PackagedRunner(path)
    out = runner.run(x)
    assert out.shape == golden.shape
    assert numpy.allclose(out, golden, atol=1e-4)
    # probabilities sum to 1 (softmax tail)
    assert numpy.allclose(out.sum(axis=-1), 1.0, atol=1e-5)


def test_tgz_round_trip(convnet, tmp_path):
    x, forwards, golden = convnet
    path = str(tmp_path / "model.tar.gz")
    export_package(forwards, path, with_stablehlo=False)
    out = PackagedRunner(path).run(x)
    assert numpy.allclose(out, golden, atol=1e-4)


def test_fp16_precision(convnet, tmp_path):
    x, forwards, golden = convnet
    path = str(tmp_path / "model16.zip")
    contents = export_package(forwards, path, precision=16,
                              with_stablehlo=False)
    assert contents["precision"] == 16
    with zipfile.ZipFile(path) as z:
        ref = contents["units"][0]["arrays"]["weights"]
        arr = numpy.load(__import__("io").BytesIO(z.read(ref)))
        assert arr.dtype == numpy.float16
    out = PackagedRunner(path).run(x)
    assert numpy.allclose(out, golden, atol=5e-2)


def test_int8_precision(convnet, tmp_path):
    """precision=8: weights stored as per-output-channel symmetric
    int8 + float scales; the runner dequantizes at load and the
    predictions survive quantization."""
    x, forwards, golden = convnet
    path = str(tmp_path / "model8.zip")
    contents = export_package(forwards, path, precision=8)
    assert contents["precision"] == 8
    # int8 needs a dequantizing reader: pre-int8 readers fail closed
    assert contents["format_version"] == 2
    # no fp32 StableHLO blob riding along with quantized weights
    assert "stablehlo" not in contents
    with zipfile.ZipFile(path) as z:
        arrays = contents["units"][0]["arrays"]
        w = numpy.load(io.BytesIO(z.read(arrays["weights"])))
        s = numpy.load(io.BytesIO(z.read(arrays["weights.scale"])))
        assert w.dtype == numpy.int8
        assert s.dtype == numpy.float32
        assert s.shape == (w.shape[-1],)
        assert numpy.abs(w).max() <= 127
        # bias is NOT quantized
        assert "bias.scale" not in arrays
    out = PackagedRunner(path).run(x)
    assert out.shape == golden.shape
    assert numpy.allclose(out, golden, atol=1e-1)
    assert (out.argmax(-1) == golden.argmax(-1)).all()
    assert numpy.allclose(out.sum(axis=-1), 1.0, atol=1e-5)


def test_int8_package_is_smaller(tmp_path):
    """On a weight-dominated model the int8 package approaches 1/4 the
    fp32 size (random weights don't deflate)."""
    import os

    rng = numpy.random.default_rng(11)
    x = rng.standard_normal((2, 256)).astype(numpy.float32)
    wf = DummyWorkflow()
    fc = All2AllTanh(wf, output_sample_shape=(256,))
    fc.input = Vector(x.copy())
    fc.initialize(NumpyDevice())
    fc.numpy_run()
    p32 = str(tmp_path / "m32.zip")
    p8 = str(tmp_path / "m8.zip")
    export_package([fc], p32, with_stablehlo=False)
    export_package([fc], p8, precision=8, with_stablehlo=False)
    assert os.path.getsize(p8) < 0.4 * os.path.getsize(p32)


def test_contents_schema(convnet, tmp_path):
    x, forwards, _ = convnet
    path = str(tmp_path / "model.zip")
    export_package(forwards, path, with_stablehlo=False)
    with zipfile.ZipFile(path) as z:
        contents = json.loads(z.read(CONTENTS_NAME).decode())
    assert contents["format_version"] == 1
    assert contents["input_shape"] == list(x.shape)
    types = [u["type"] for u in contents["units"]]
    assert types == ["conv_tanh", "max_pooling", "lrn", "all2all_tanh",
                     "softmax"]
    # every array ref resolves
    with zipfile.ZipFile(path) as z:
        names = set(z.namelist())
    for unit in contents["units"]:
        for ref in unit["arrays"].values():
            assert ref in names


def test_stablehlo_export(convnet, tmp_path):
    x, forwards, golden = convnet
    path = str(tmp_path / "model_hlo.zip")
    contents = export_package(forwards, path, with_stablehlo=True)
    if "stablehlo" not in contents:
        pytest.skip("jax.export unavailable for this chain")
    with zipfile.ZipFile(path) as z:
        blob = z.read(contents["stablehlo"])
    assert len(blob) > 100
    # deserialize + run through jax.export to prove the artifact is live
    from jax import export as jax_export
    rerun = jax_export.deserialize(bytearray(blob))
    out = numpy.asarray(rerun.call(x))
    assert numpy.allclose(out, golden, atol=1e-4)


def test_stablehlo_export_lstm(tmp_path):
    """The recurrent scan serializes through jax.export and replays
    identically — the artifact any other StableHLO consumer gets."""
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.rnn import LSTM

    rng = numpy.random.default_rng(9)
    x = rng.standard_normal((4, 7, 5)).astype(numpy.float32)
    wf = DummyWorkflow()
    unit = LSTM(wf, hidden_units=6, last_only=True,
                weights_filling="gaussian")
    unit.input = Vector(x.copy())
    unit.initialize(NumpyDevice())
    unit.numpy_run()
    unit.output.map_read()
    golden = numpy.array(unit.output.mem)

    path = str(tmp_path / "lstm_hlo.zip")
    contents = export_package([unit], path, with_stablehlo=True)
    if "stablehlo" not in contents:
        pytest.skip("jax.export unavailable for this chain")
    with zipfile.ZipFile(path) as z:
        blob = z.read(contents["stablehlo"])
    from jax import export as jax_export
    rerun = jax_export.deserialize(bytearray(blob))
    out = numpy.asarray(rerun.call(x))
    assert out.shape == golden.shape
    assert numpy.allclose(out, golden, atol=1e-4)


def test_mean_disp_round_trip(tmp_path):
    """MeanDispNormalizer packages as 'mean_disp' with rdisp → disp."""
    from veles_tpu.mean_disp_normalizer import MeanDispNormalizer
    wf = DummyWorkflow()
    dev = NumpyDevice()
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((4, 6)).astype(numpy.float32)
    unit = MeanDispNormalizer(wf)
    unit.input = Vector(x.copy())
    unit.mean = Vector(rng.standard_normal(6).astype(numpy.float32))
    unit.rdisp = Vector((rng.random(6) + 0.5).astype(numpy.float32))
    unit.initialize(dev)
    unit.numpy_run()
    unit.output.map_read()
    golden = numpy.array(unit.output.mem)
    path = str(tmp_path / "md.zip")
    # default with_stablehlo=True: a chain without a jax pure form must
    # still package (the StableHLO artifact is just skipped)
    contents = export_package([unit], path)
    assert "stablehlo" not in contents
    out = PackagedRunner(path).run(x)
    assert numpy.allclose(out, golden, atol=1e-5)


def test_checksum_detects_corruption(convnet, tmp_path):
    import io as _io
    x, forwards, _ = convnet
    path = str(tmp_path / "model.zip")
    export_package(forwards, path, with_stablehlo=False)
    with zipfile.ZipFile(path) as z:
        files = {n: z.read(n) for n in z.namelist()}
    victim = next(n for n in files if n.endswith(".npy"))
    files[victim] = files[victim][:-4] + b"\x00\x00\x00\x01"
    with pytest.raises(ValueError, match="checksum"):
        PackagedRunner(files)


def test_mlp_workflow_method(tmp_path):
    """Workflow.package_export API parity (ref workflow.py:868)."""
    wf = DummyWorkflow()
    dev = NumpyDevice()
    rng = numpy.random.default_rng(3)
    x = rng.standard_normal((4, 20)).astype(numpy.float32)
    fc = All2AllTanh(wf, output_sample_shape=(8,))
    fc.input = Vector(x.copy())
    fc.initialize(dev)
    fc.numpy_run()
    sm = All2AllSoftmax(wf, output_sample_shape=(5,))
    sm.input = fc.output
    sm.initialize(dev)
    sm.numpy_run()
    sm.output.map_read()
    wf.forwards = [fc, sm]
    path = str(tmp_path / "mlp.zip")
    wf.package_export(path, with_stablehlo=False)
    out = PackagedRunner(path).run(x)
    assert numpy.allclose(out, numpy.array(sm.output.mem), atol=1e-5)
