"""veles_tpu.serve: the dynamic-batching, AOT-compiled serving engine.

Coverage demanded by the subsystem's acceptance criteria:
batcher coalescing (N concurrent requests → 1 device call), bucket
padding correctness (byte-identical to the un-batched forward),
backpressure (503 / QueueFull instead of stalling), registry hot-swap
under load (old version finishes in-flight work, no torn outputs),
compile-count discipline (zero recompiles after bucket warmup), and —
as a ``-m slow`` closed-loop load test — ≥ 3× the request throughput
of the serial in-workflow RESTfulAPI path on the same MLP.
"""

import base64
import json
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

from veles_tpu.serve import (DynamicBatcher, InferenceEngine,
                             ModelRegistry, QueueFull, ServingMetrics,
                             ServingServer, decode_input)


# ---------------------------------------------------------------------------
# fixtures: a trained tiny MLP workflow (the test_services.py model)
# ---------------------------------------------------------------------------

from veles_tpu.loader.fullbatch import FullBatchLoader  # noqa: E402


class TinyLoader(FullBatchLoader):
    """Module-level (pickles with the snapshot roundtrip test)."""

    def load_data(self):
        rng = numpy.random.default_rng(3)
        n = 80
        labels = (numpy.arange(n) % 4).astype(int)
        centers = rng.standard_normal((4, 8)) * 3
        self.original_data.mem = (
            centers[labels] + rng.standard_normal((n, 8)) * 0.5
        ).astype(numpy.float32)
        self.original_labels = [int(v) for v in labels]
        self.class_lengths[:] = [0, 20, 60]


def _train_tiny(device):
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=20),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
             "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.1}},
        ],
        decision_config={"max_epochs": 2})
    wf.launcher = DummyLauncher()
    wf.initialize(device=device)
    wf.run()
    return wf


@pytest.fixture(scope="module")
def trained_wf():
    from veles_tpu.backends import NumpyDevice
    return _train_tiny(NumpyDevice())


def _identity_engine(scale, dim=4, max_batch_size=8):
    """A real engine computing ``x @ (scale·I)`` — outputs name their
    version, which the hot-swap test exploits."""
    w = numpy.eye(dim, dtype=numpy.float32) * numpy.float32(scale)
    return InferenceEngine([{"w": w}],
                           lambda p, x: x @ p[0]["w"],
                           sample_shape=(dim,),
                           max_batch_size=max_batch_size)


class _StubEngine(object):
    """Engine-shaped test double: counts calls, optional blocking."""

    def __init__(self, max_batch_size=16, block=False):
        self.max_batch_size = max_batch_size
        self.buckets = (max_batch_size,)
        self.compile_count = 0
        self.calls = []                  # batch sizes, in order
        self.release = threading.Event()
        if not block:
            self.release.set()

    def warmup(self):
        return self

    def infer(self, batch):
        self.calls.append(len(batch))
        self.release.wait(30)
        return numpy.asarray(batch, numpy.float32) * 2.0


# ---------------------------------------------------------------------------
# wire decoding (the "JSON (or base64 numpy)" docstring promise)
# ---------------------------------------------------------------------------

class TestWire:
    def test_json_input(self):
        out = decode_input({"input": [[1, 2], [3, 4]]})
        assert out.dtype == numpy.float32 and out.shape == (2, 2)

    def test_1d_gets_batch_dim(self):
        assert decode_input({"input": [1.0, 2.0]}).shape == (1, 2)

    def test_b64_roundtrip(self):
        x = numpy.random.default_rng(0).standard_normal(
            (3, 5)).astype(numpy.float32)
        out = decode_input({
            "input_b64": base64.b64encode(x.tobytes()).decode(),
            "shape": [3, 5], "dtype": "float32"})
        assert out.tobytes() == x.tobytes()

    def test_b64_uint8_casts_to_float32(self):
        x = numpy.arange(6, dtype=numpy.uint8).reshape(2, 3)
        out = decode_input({
            "input_b64": base64.b64encode(x.tobytes()).decode(),
            "shape": [2, 3], "dtype": "uint8"})
        assert out.dtype == numpy.float32
        assert (out == x.astype(numpy.float32)).all()

    @pytest.mark.parametrize("payload", [
        [],                                           # not an object
        {},                                           # neither key
        {"input": [[1]], "input_b64": "AA=="},        # both keys
        {"input": [["not", "numeric"]]},
        {"input_b64": "!!!", "shape": [1, 4]},        # bad base64
        {"input_b64": "AAAA", "shape": [1]},          # byte count
        {"input_b64": "AAAA", "shape": [0]},          # bad shape
        {"input_b64": "AAAA", "shape": [1], "dtype": "complex128"},
    ])
    def test_malformed_rejected(self, payload):
        with pytest.raises(ValueError):
            decode_input(payload)


# ---------------------------------------------------------------------------
# batcher: coalescing + backpressure
# ---------------------------------------------------------------------------

class TestBatcher:
    def test_coalesces_concurrent_requests_into_one_call(self):
        engine = _StubEngine(max_batch_size=16)
        metrics = ServingMetrics()
        batcher = DynamicBatcher(engine, max_wait_ms=500,
                                 metrics=metrics)
        try:
            n = 16
            barrier = threading.Barrier(n)
            futures = [None] * n

            def client(i):
                barrier.wait()
                futures[i] = batcher.submit(
                    numpy.full((1, 4), float(i), numpy.float32))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, fut in enumerate(futures):
                out = fut.result(10)
                assert out.shape == (1, 4)
                assert (out == 2.0 * i).all()    # fan-out kept order
            # N concurrent requests → ONE device call
            assert engine.calls == [n]
            assert metrics.requests_total == n
            assert metrics.batches_total == 1
            assert metrics.batch_fill_ratio() == 1.0
        finally:
            batcher.stop()

    def test_full_queue_sheds_instead_of_stalling(self):
        engine = _StubEngine(max_batch_size=4, block=True)
        metrics = ServingMetrics()
        batcher = DynamicBatcher(engine, max_wait_ms=1,
                                 max_queue_rows=4, metrics=metrics)
        try:
            first = batcher.submit(numpy.ones((1, 4), numpy.float32))
            deadline = time.time() + 5
            while not engine.calls and time.time() < deadline:
                time.sleep(0.005)      # worker now blocked in infer
            assert engine.calls == [1]
            queued = [batcher.submit(numpy.ones((1, 4), numpy.float32))
                      for _ in range(4)]
            with pytest.raises(QueueFull):
                batcher.submit(numpy.ones((1, 4), numpy.float32))
            assert metrics.shed_total == 1
            assert QueueFull.retry_after >= 1      # the 503 wire hint
            engine.release.set()
            assert first.result(10).shape == (1, 4)
            for fut in queued:
                assert (fut.result(10) == 2.0).all()
        finally:
            batcher.stop()

    def test_misshaped_request_rejected_worker_survives(self):
        engine = _identity_engine(1.0, dim=4, max_batch_size=8)
        batcher = DynamicBatcher(engine, max_wait_ms=1)
        try:
            # wrong sample width: rejected at submit, never coalesced
            with pytest.raises(ValueError):
                batcher.submit(numpy.ones((1, 5), numpy.float32))
            # the worker is alive and still serving
            x = numpy.ones((2, 4), numpy.float32)
            assert batcher.infer(x, timeout=10).tobytes() == x.tobytes()
        finally:
            batcher.stop()

    def test_oversized_request_is_chunked_not_rejected(self):
        engine = _identity_engine(1.0, dim=4, max_batch_size=8)
        batcher = DynamicBatcher(engine, max_wait_ms=1,
                                 max_queue_rows=64)
        try:
            x = numpy.random.default_rng(1).standard_normal(
                (20, 4)).astype(numpy.float32)
            out = batcher.infer(x)
            assert out.tobytes() == x.tobytes()    # identity weights
            # beyond max_queue_rows it can NEVER fit: deterministic
            # ValueError (→ 400), not a 503 retried forever
            big = numpy.zeros((65, 4), numpy.float32)
            with pytest.raises(ValueError):
                batcher.submit(big)
        finally:
            batcher.stop()

    def test_infer_deadline_fails_futures_typed(self):
        """Satellite (chaos PR): a hung device call under
        root.common.serve.infer_deadline_ms fails the batch's futures
        with the typed InferDeadlineExceeded (→ HTTP 500) within the
        deadline instead of blocking every queued client forever, and
        the expiry lands in serve metrics."""
        from veles_tpu.config import root
        from veles_tpu.serve.batcher import InferDeadlineExceeded

        engine = _StubEngine(max_batch_size=4, block=True)  # hangs
        metrics = ServingMetrics()
        saved = root.common.serve.get("infer_deadline_ms", 0)
        root.common.serve.infer_deadline_ms = 150
        batcher = DynamicBatcher(engine, max_wait_ms=1,
                                 metrics=metrics)
        try:
            tic = time.perf_counter()
            future = batcher.submit(numpy.ones((2, 4), numpy.float32))
            with pytest.raises(InferDeadlineExceeded):
                future.result(10)
            elapsed = time.perf_counter() - tic
            assert elapsed < 5, "must fail at the deadline, not hang"
            assert metrics.deadline_expired_total == 1
            assert metrics.errors_total == 1
            snap = metrics.snapshot()
            assert snap["deadline_expired_total"] == 1
            assert "deadline_expired_total 1" in metrics.render_text()
            # the worker survives: after the wedged call releases, a
            # fresh request is served normally
            engine.release.set()
            out = batcher.infer(numpy.ones((1, 4), numpy.float32),
                                timeout=10)
            assert out.shape == (1, 4)
        finally:
            root.common.serve.infer_deadline_ms = saved
            batcher.stop(drain=False)

    def test_infer_deadline_off_keeps_direct_path(self):
        """Knob off (the default): infer is called on the worker
        thread directly — no thread-pool hop."""
        from veles_tpu.config import root
        assert float(root.common.serve.get("infer_deadline_ms", 0)) \
            == 0
        worker_threads = []

        class _Recorder(_StubEngine):
            def infer(self, batch):
                worker_threads.append(threading.current_thread().name)
                return super(_Recorder, self).infer(batch)

        batcher = DynamicBatcher(_Recorder(max_batch_size=4),
                                 max_wait_ms=1)
        try:
            batcher.infer(numpy.ones((1, 4), numpy.float32))
            assert worker_threads == ["serve-batcher"]
        finally:
            batcher.stop()

    def test_timed_out_request_costs_no_device_call(self):
        engine = _StubEngine(max_batch_size=4, block=True)
        batcher = DynamicBatcher(engine, max_wait_ms=1)
        try:
            first = batcher.submit(numpy.ones((1, 4), numpy.float32))
            deadline = time.time() + 5
            while not engine.calls and time.time() < deadline:
                time.sleep(0.005)      # worker blocked inside infer
            abandoned = batcher.submit(numpy.ones((1, 4),
                                       numpy.float32))
            assert abandoned.cancel()  # client gave up (504 path)
            engine.release.set()
            assert first.result(10).shape == (1, 4)
            time.sleep(0.2)            # let the worker drain the queue
            # the cancelled request never reached the device
            assert engine.calls == [1]
        finally:
            batcher.stop()


# ---------------------------------------------------------------------------
# engine: bucket padding byte-identity + compile-count discipline
# ---------------------------------------------------------------------------

class TestEngine:
    def test_bucket_padding_byte_identical_and_no_recompiles(
            self, trained_wf):
        engine = InferenceEngine.from_workflow(trained_wf,
                                               max_batch_size=16)
        engine.warmup()
        assert engine.buckets == (1, 2, 4, 8, 16)
        warm = engine.compile_count
        assert warm == len(engine.buckets)
        rng = numpy.random.default_rng(7)
        for n in range(1, 17):
            x = rng.standard_normal((n, 8)).astype(numpy.float32)
            out = engine.infer(x)
            assert out.shape == (n, 4)
            # padded-bucket result == the un-batched forward, BYTE for
            # byte (row-independent graph; see engine.py docstring)
            assert out.tobytes() == engine.reference_forward(x).tobytes()
        # beyond max_batch_size: chunked through the largest bucket
        x = rng.standard_normal((37, 8)).astype(numpy.float32)
        out = engine.infer(x)
        assert out.shape == (37, 4)
        assert out.tobytes() == engine.reference_forward(x).tobytes()
        # capacity accounting for the fill ratio: 16 + 16 + 8
        assert engine.padded_capacity(37) == 40
        assert engine.padded_capacity(3) == 4
        # empty batch: statically-known answer, no device call
        calls = engine.infer_calls
        empty = engine.infer(numpy.empty((0, 8), numpy.float32))
        assert empty.shape == (0, 4)
        assert engine.infer_calls == calls
        assert engine.compile_count == warm    # ZERO steady-state compiles

    def test_from_forwards_matches_lowered_path(self, trained_wf):
        lowered = InferenceEngine.from_workflow(trained_wf,
                                                max_batch_size=8)
        chained = InferenceEngine.from_forwards(trained_wf.forwards,
                                                max_batch_size=8)
        x = numpy.array(trained_wf.loader.original_data.mem[:5])
        assert numpy.allclose(lowered.infer(x), chained.infer(x),
                              atol=1e-6)

    def test_live_engine_tracks_weight_updates(self):
        class _FakeVector(object):
            def __init__(self, arr):
                self.mem = arr

            def map_read(self):
                pass

            def __bool__(self):
                return True

        class _FakeForward(object):
            SKIP_AT_EVAL = False

            def __init__(self):
                self.weights = _FakeVector(
                    numpy.eye(4, dtype=numpy.float32))
                self.bias = None
                self.input = None

            def pure_config(self):
                return {}

            def pure_params(self, host=False):
                return {"w": self.weights.mem}

            @staticmethod
            def pure(params, x):
                return x @ params["w"]

        unit = _FakeForward()
        engine = InferenceEngine.from_forwards(
            [unit], sample_shape=(4,), live=True, max_batch_size=4)
        x = numpy.ones((1, 4), numpy.float32)
        assert (engine.infer(x) == 1.0).all()
        unit.weights.mem = numpy.eye(4, dtype=numpy.float32) * 3.0
        assert (engine.infer(x) == 3.0).all()   # re-read per call


# ---------------------------------------------------------------------------
# registry: hot swap under load
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_hot_swap_under_load_no_torn_outputs(self):
        registry = ModelRegistry(metrics=ServingMetrics(),
                                 batcher_config={"max_wait_ms": 0.5})
        registry.deploy("m", _identity_engine(1.0))
        stop = threading.Event()
        bad, seen = [], set()

        def client():
            x = numpy.ones((2, 4), numpy.float32)
            while not stop.is_set():
                out = registry.infer("m", x, timeout=30)
                values = set(numpy.unique(out).tolist())
                if len(values) != 1:     # torn batch: mixed versions
                    bad.append(out.copy())
                else:
                    seen.add(values.pop())

        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.15)
            registry.deploy("m", _identity_engine(2.0))   # hot swap
            assert registry.get("m").swaps == 1
            time.sleep(0.15)
        finally:
            stop.set()
            for t in threads:
                t.join()
            registry.stop()
        assert not bad, "mixed-version outputs: %r" % bad
        assert seen == {1.0, 2.0}    # both versions actually served
        # post-swap: the new version answers
        # (registry stopped; check the recorded engine directly)

    def test_reshaping_swap_refused_without_opt_in(self):
        registry = ModelRegistry()
        registry.deploy("m", _identity_engine(1.0, dim=4))
        try:
            with pytest.raises(ValueError):
                registry.deploy("m", _identity_engine(1.0, dim=6))
            assert registry.get("m").swaps == 0
            registry.deploy("m", _identity_engine(1.0, dim=6),
                            allow_reshape=True)
            assert registry.get("m").swaps == 1
            x = numpy.ones((1, 6), numpy.float32)
            assert (registry.infer("m", x) == 1.0).all()
        finally:
            registry.stop()

    def test_unknown_model_and_describe(self):
        registry = ModelRegistry()
        with pytest.raises(KeyError):
            registry.get("nope")
        registry.deploy("a", _identity_engine(1.0), version="v7",
                        source="unit-test")
        try:
            info = registry.describe()["a"]
            assert info["version"] == "v7"
            assert info["source"] == "unit-test"
            assert info["compile_count"] == len(info["buckets"])
        finally:
            registry.stop()

    def test_load_snapshot_roundtrip(self, trained_wf, tmp_path):
        from veles_tpu.snapshotter import save_snapshot
        path = save_snapshot(trained_wf, str(tmp_path / "wf.pickle"))
        registry = ModelRegistry()
        try:
            model = registry.load_snapshot("tiny", path)
            assert model.source == path
            x = numpy.array(trained_wf.loader.original_data.mem[:3])
            out = registry.infer("tiny", x)
            ref = InferenceEngine.from_workflow(trained_wf).infer(x)
            assert numpy.allclose(out, ref, atol=1e-6)
        finally:
            registry.stop()


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------

def _post(port, payload, path="/service"):
    req = urllib.request.Request(
        "http://127.0.0.1:%d%s" % (port, path),
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=15) as resp:
        return json.loads(resp.read())


class TestServer:
    def test_wire_contract_and_operational_endpoints(self, trained_wf):
        engine = InferenceEngine.from_workflow(trained_wf,
                                               max_batch_size=16)
        server = ServingServer(engine=engine, port=0,
                               batcher_config={"max_wait_ms": 1})
        server.start()
        try:
            x = numpy.array(trained_wf.loader.original_data.mem[:3])
            out = _post(server.port, {"input": x.tolist()})
            result = numpy.asarray(out["result"])
            assert result.shape == (3, 4)
            assert numpy.allclose(result.sum(axis=1), 1.0, atol=1e-3)
            assert out["model"] == "default"
            # base64 numpy input → identical answer
            out_b64 = _post(server.port, {
                "input_b64": base64.b64encode(x.tobytes()).decode(),
                "shape": list(x.shape), "dtype": "float32"})
            assert out_b64["result"] == out["result"]
            # named-model route + unknown model
            out_named = _post(server.port, {"input": x.tolist()},
                              path="/service/default")
            assert out_named["result"] == out["result"]
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.port, {"input": x.tolist()},
                      path="/service/ghost")
            assert err.value.code == 404
            # malformed → 400 {"error": ...}
            bad = urllib.request.Request(
                "http://127.0.0.1:%d/service" % server.port,
                data=b"not json")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(bad, timeout=5)
            assert err.value.code == 400
            assert "error" in json.loads(err.value.read())
            # healthz + text metrics
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/healthz" % server.port,
                    timeout=5) as resp:
                health = json.loads(resp.read())
            assert health["status"] == "ok"
            assert health["models"]["default"]["compile_count"] == \
                len(engine.buckets)
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % server.port,
                    timeout=5) as resp:
                text = resp.read().decode()
            assert "veles_serve_requests_total" in text
            assert "veles_serve_batch_fill_ratio" in text
            assert 'request_latency_ms{quantile="p99"}' in text
        finally:
            server.stop()

    def test_misshaped_request_maps_to_400(self):
        server = ServingServer(engine=_identity_engine(1.0, dim=4),
                               port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.port, {"input": [[1.0] * 5]})
            assert err.value.code == 400
            assert "shape" in json.loads(err.value.read())["error"]
            # the model still serves well-formed requests after
            out = _post(server.port, {"input": [[1.0] * 4]})
            assert out["result"] == [[1.0] * 4]
        finally:
            server.stop()

    def test_handed_in_registry_adopts_server_metrics(self):
        registry = ModelRegistry()          # built without metrics
        registry.deploy("default", _identity_engine(1.0, dim=4))
        server = ServingServer(registry=registry, port=0).start()
        try:
            _post(server.port, {"input": [[1.0] * 4]})
            # traffic is visible, not silently zero
            assert server.metrics.requests_total == 1
            assert registry.metrics is server.metrics
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/metrics" % server.port,
                    timeout=5) as resp:
                text = resp.read().decode()
            assert "veles_serve_requests_total 1" in text
            assert 'queue_depth{model="default"}' in text
        finally:
            server.stop()

    def test_backpressure_maps_to_503_with_retry_after(self):
        stub = _StubEngine(max_batch_size=4, block=True)
        server = ServingServer(port=0,
                               batcher_config={"max_wait_ms": 1,
                                               "max_queue_rows": 2})
        server.registry.deploy("default", stub)
        server.start()
        results = []

        def client():
            try:
                results.append(_post(server.port,
                                     {"input": [[1.0] * 4]}))
            except urllib.error.HTTPError as e:
                results.append(e)

        threads = [threading.Thread(target=client) for _ in range(6)]
        try:
            for t in threads:
                t.start()
                time.sleep(0.05)   # 1 in-flight, 2 queued, rest shed
            deadline = time.time() + 5
            while len(results) < 3 and time.time() < deadline:
                time.sleep(0.01)
            shed = [r for r in results
                    if isinstance(r, urllib.error.HTTPError)]
            assert shed and all(e.code == 503 for e in shed)
            assert all(e.headers.get("Retry-After") for e in shed)
        finally:
            stub.release.set()
            for t in threads:
                t.join()
            server.stop()
        served = [r for r in results if isinstance(r, dict)]
        assert served and all(r["result"] == [[2.0] * 4]
                              for r in served)
        assert len(served) + len(
            [r for r in results
             if isinstance(r, urllib.error.HTTPError)]) == 6

    def test_web_status_integration(self, trained_wf):
        from veles_tpu.web_status import WebStatus
        status = WebStatus(port=0).start()
        engine = InferenceEngine.from_workflow(trained_wf,
                                               max_batch_size=4)
        server = ServingServer(engine=engine, port=0).start()
        try:
            _post(server.port, {"input": [[0.0] * 8]})
            assert server.notify_status(
                "http://127.0.0.1:%d/update" % status.port,
                run_id="serving-test")
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/status" % status.port,
                    timeout=5) as resp:
                data = json.loads(resp.read())
            serving = data["serving-test"]["results"]["serving"]
            assert serving["requests_total"] >= 1
            assert "latency_ms" in serving
        finally:
            server.stop()
            status.stop()


# ---------------------------------------------------------------------------
# the RESTfulAPI adapter keeps the in-workflow surface
# ---------------------------------------------------------------------------

def test_restful_adapter_b64_and_metrics(trained_wf):
    from veles_tpu.restful_api import RESTfulAPI
    api = RESTfulAPI(trained_wf, port=0)
    api.forwards = trained_wf.forwards
    api.initialize()
    try:
        x = numpy.array(trained_wf.loader.original_data.mem[:2])
        out_json = _post(api.port, {"input": x.tolist()})
        out_b64 = _post(api.port, {
            "input_b64": base64.b64encode(x.tobytes()).decode(),
            "shape": list(x.shape)})           # dtype defaults float32
        assert out_json["result"] == out_b64["result"]
        direct = api.infer(x)
        assert numpy.allclose(numpy.asarray(out_json["result"]),
                              direct, atol=1e-6)
        assert api.metrics.requests_total >= 3
        # the adapter warms lazily (no initialize() stall): only the
        # buckets traffic actually hit are compiled
        assert 0 < api.engine.compile_count <= len(api.engine.buckets)
    finally:
        api.stop()


# ---------------------------------------------------------------------------
# the acceptance gate: ≥ 3× the serial RESTfulAPI path, zero
# recompiles, byte-identical outputs — closed loop, 32 clients
# ---------------------------------------------------------------------------

def _serial_restful_infer(forwards, batch):
    """The pre-serve RESTfulAPI.infer, verbatim: one un-batched eager
    forward per request inside a per-request critical section (link
    swap + restore) — the baseline the batching engine is measured
    against."""
    from veles_tpu.memory import Vector
    batch = numpy.ascontiguousarray(batch, dtype=numpy.float32)
    first = forwards[0]
    with first.data_lock():
        links = first.__dict__.setdefault("_linked_attrs", {})
        saved_link = links.pop("input", None)
        saved_value = first.__dict__.pop("input", None)
        try:
            vec = Vector(batch)
            vec.initialize(first.device)
            first.input = vec
            for unit in forwards:
                unit.run()
            out = forwards[-1].output
            out.map_read()
            return numpy.array(out.mem[:len(batch)])
        finally:
            first.__dict__.pop("input", None)
            if saved_link is not None:
                links["input"] = saved_link
            elif saved_value is not None:
                first.__dict__["input"] = saved_value


def _closed_loop(n_clients, duration, request_fn):
    """n closed-loop clients for ``duration`` sec → completed requests."""
    stop = threading.Event()
    counts = [0] * n_clients
    errors = []

    def client(i):
        rng = numpy.random.default_rng(i)
        x = rng.standard_normal((1, 8)).astype(numpy.float32)
        while not stop.is_set():
            try:
                request_fn(x)
            except Exception as e:  # noqa: BLE001 - report, don't hang
                errors.append(e)
                return
            counts[i] += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    tic = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(20)
    elapsed = time.perf_counter() - tic
    assert not errors, errors[:3]
    return sum(counts) / elapsed


@pytest.mark.slow
def test_dynamic_batching_3x_serial_throughput():
    # CPU JAX end to end (the acceptance criterion's regime): the
    # serial baseline runs the eager forward units on the JAX CPU
    # device — one dispatched forward per request under the critical
    # section, exactly what the pre-serve RESTfulAPI did on an
    # accelerator backend
    from veles_tpu.backends import CPUDevice
    trained_wf = _train_tiny(CPUDevice())
    clients, duration = 32, 2.0
    serial_qps = _closed_loop(
        clients, duration,
        lambda x: _serial_restful_infer(trained_wf.forwards, x))

    engine = InferenceEngine.from_workflow(trained_wf,
                                           max_batch_size=64)
    engine.warmup()
    warm_compiles = engine.compile_count
    metrics = ServingMetrics()
    batcher = DynamicBatcher(engine, max_wait_ms=2, metrics=metrics,
                             max_queue_rows=4096)
    try:
        batched_qps = _closed_loop(
            clients, duration, lambda x: batcher.infer(x, timeout=30))
    finally:
        batcher.stop()

    # ZERO XLA recompiles after bucket warmup
    assert engine.compile_count == warm_compiles
    # byte-identical to the un-batched forward
    probe = numpy.random.default_rng(0).standard_normal(
        (5, 8)).astype(numpy.float32)
    assert engine.infer(probe).tobytes() == \
        engine.reference_forward(probe).tobytes()
    # requests actually coalesced (fill beats one-request batches)
    assert metrics.batches_total < metrics.requests_total
    # the acceptance bar
    assert batched_qps >= 3.0 * serial_qps, \
        "batched %.0f req/s < 3x serial %.0f req/s" % (batched_qps,
                                                       serial_qps)


# ---------------------------------------------------------------------------
# fleet-era satellites: WRR re-weighting, streaming Retry-After, and the
# generative drain-swap ledger contract
# ---------------------------------------------------------------------------

class TestReplicaReweighting:
    def test_set_weights_resets_credits_for_exact_split(self):
        """A 3:1 -> 1:1 re-weight must split EXACTLY within one
        rotation: stale credits (denominated in the old total) would
        keep favouring the previously-starved member."""
        from veles_tpu.serve.registry import ReplicaSet

        class _E:
            def __init__(self, tag):
                self.tag = tag

        a, b = _E("a"), _E("b")
        router = ReplicaSet([(a, 3.0, 1), (b, 1.0, 2)])
        first = [router.pick().tag for _ in range(40)]
        assert first.count("a") == 30 and first.count("b") == 10
        router.set_weights([1.0, 1.0])
        # credits were reset: the very first rotation is already 1:1
        assert sorted(router.pick().tag for _ in range(2)) == ["a", "b"]
        rest = [router.pick().tag for _ in range(20)]
        assert rest.count("a") == 10 and rest.count("b") == 10

    def test_add_remove_replica_reshape_routing(self):
        from veles_tpu.serve.registry import ReplicaSet

        class _E:
            def __init__(self, tag):
                self.tag = tag

        router = ReplicaSet([(_E("a"), 1.0, 1)])
        with pytest.raises(ValueError):
            router.remove_replica(1)          # never empty the set
        router.add_replica(_E("b"), 1.0, version=2)
        assert len(router) == 2
        picks = [router.pick().tag for _ in range(10)]
        assert picks.count("a") == picks.count("b") == 5
        with pytest.raises(KeyError):
            router.remove_replica(99)
        removed = router.remove_replica(2)
        assert removed.tag == "b"
        assert len(router) == 1


def _tiny_gen_engine(seed=0, **kwargs):
    from veles_tpu.gen import GenerativeEngine, TransformerGenModel
    from veles_tpu.samples.transformer import TINY
    kwargs.setdefault("max_slots", 2)
    kwargs.setdefault("max_seq", 32)
    kwargs.setdefault("prefill_buckets", (8,))
    return GenerativeEngine(TransformerGenModel(dict(TINY, seq_len=32)),
                            seed=seed, **kwargs)


class TestGenerativeServing:
    def test_streaming_generate_queue_full_carries_retry_after(self):
        """Satellite contract: the STREAMING /generate route's 503
        shed must carry Retry-After just like the non-streaming
        reply — clients key reconnect back-off off the header."""
        registry = ModelRegistry()
        registry.deploy_generative(
            "lm", _tiny_gen_engine(), warmup=False,
            scheduler_config={"max_queue": 0})
        server = ServingServer(registry=registry, port=0).start()
        try:
            body = json.dumps({"tokens": [1, 2], "max_new_tokens": 4,
                               "stream": True}).encode()
            req = urllib.request.Request(
                "http://127.0.0.1:%d/generate/lm" % server.port,
                data=body, headers={"Content-Type":
                                    "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 503
            assert err.value.headers.get("Retry-After")
            payload = json.loads(err.value.read())
            assert payload["retry_after"] == QueueFull.retry_after
        finally:
            server.stop()

    def test_generative_hot_swap_under_load_releases_v1_ledger(self):
        """Drain swap: v1's in-flight streams finish on v1, new
        requests land on v2, and v1's KV-cache ledger hold is
        released exactly once (close() is idempotent)."""
        from veles_tpu.memory import Watcher

        registry = ModelRegistry()
        v1 = _tiny_gen_engine(seed=1)
        registry.deploy_generative("lm", v1, version=1)
        v1_scheduler = registry.get("lm").scheduler
        kv_before_swap = Watcher.bytes_by_category.get("kv", 0)
        # in-flight load on v1 while the swap happens
        futures = [v1_scheduler.submit([1 + i, 2], 12)
                   for i in range(4)]
        v2 = _tiny_gen_engine(seed=2)
        registry.deploy_generative("lm", v2, version=2)
        # the drain swap let every v1 stream finish with full budget
        assert all(len(f.result(timeout=30)) == 12 for f in futures)
        assert registry.get("lm").version == 2
        assert registry.get("lm").scheduler is not v1_scheduler
        # v1's KV hold left the ledger exactly once; v2's remains
        kv_after = Watcher.bytes_by_category.get("kv", 0)
        assert kv_after == kv_before_swap \
            + v2.kv_cache_bytes - v1.kv_cache_bytes
        v1.close()   # idempotent: a second close must not go negative
        assert Watcher.bytes_by_category.get("kv", 0) == kv_after
        # new requests land on v2 and serve
        assert len(registry.generate("lm", [3, 4],
                                     max_new_tokens=3)) == 3
        registry.undeploy("lm")
        assert Watcher.bytes_by_category.get("kv", 0) == \
            kv_after - v2.kv_cache_bytes
