"""veles_tpu.gen — continuously-batched generative serving tests.

THE parity gate lives here: tokens generated under continuous batching
must be BITWISE identical to sequential one-request-at-a-time decode
for a seeded mixed-length request set (greedy sampling), on both the
single-device and the mesh-sharded engine — the property that makes
iteration-level admission a pure scheduling optimisation rather than a
numerics change.  The ``-m slow`` closed loop then proves the
scheduling is worth having: ≥1.5x tokens/s over the pad-to-slowest
static batcher with zero steady-state compiles.
"""

import json

import numpy
import pytest

from veles_tpu.config import root
from veles_tpu.gen import (GenerativeEngine, GenerativeScheduler,
                           TransformerGenModel, static_generate)
from veles_tpu.samples.transformer import TINY

CFG = dict(TINY, seq_len=64)


def build_engine(seed=0, mesh=None, max_slots=3, max_seq=48,
                 buckets=(8, 16), warm=True, **kwargs):
    engine = GenerativeEngine(
        TransformerGenModel(CFG), max_slots=max_slots,
        max_seq=max_seq, prefill_buckets=buckets, seed=seed,
        mesh=mesh, **kwargs)
    return engine.warmup() if warm else engine


def mixed_workload(n=10, seed=0, max_prompt=16, max_new_hi=10):
    rng = numpy.random.default_rng(seed)
    return [
        (rng.integers(0, CFG["vocab"],
                      int(rng.integers(1, max_prompt))).tolist(),
         int(rng.integers(1, max_new_hi)))
        for _ in range(n)]


def run_continuous(engine, workload):
    scheduler = GenerativeScheduler(engine)
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    scheduler.run_until_idle()
    return [f.result(0) for f in futures], scheduler


def run_sequential(engine, workload):
    scheduler = GenerativeScheduler(engine)
    out = []
    for toks, max_new in workload:
        future = scheduler.submit(toks, max_new)
        scheduler.run_until_idle()
        out.append(future.result(0))
    return out, scheduler


# -- THE parity gate --------------------------------------------------------

def test_continuous_matches_sequential_bitwise():
    """Continuous batching, one-at-a-time sequential decode AND the
    static pad-to-slowest batcher produce bitwise-identical greedy
    token streams for a seeded mixed-length request set."""
    workload = mixed_workload(10)
    engine = build_engine()
    continuous, sched = run_continuous(engine, workload)
    engine.close()
    # continuous actually batched (mixed lengths overlapped)
    assert sched.batch_fill() > 0.5
    engine = build_engine()
    sequential, _ = run_sequential(engine, workload)
    engine.close()
    assert continuous == sequential
    engine = build_engine()
    static, _steps = static_generate(engine, workload)
    engine.close()
    assert static == sequential
    # greedy budgets honoured exactly (no eos in the TINY vocab run)
    assert [len(t) for t in continuous] == [m for _, m in workload]


def test_continuous_matches_sequential_on_mesh():
    """The same parity on the tensor-parallel engine: params sharded
    column/row over the model axis, KV cache sharded over heads."""
    import jax
    from veles_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = make_mesh({"model": 2})
    workload = mixed_workload(6, seed=3, max_new_hi=7)
    engine = build_engine(mesh=mesh, max_slots=2)
    assert engine.mesh is not None and engine.describe()["sharded"]
    continuous, _ = run_continuous(engine, workload)
    engine.close()
    engine = build_engine(mesh=mesh, max_slots=2)
    sequential, _ = run_sequential(engine, workload)
    engine.close()
    assert continuous == sequential


def test_mesh_without_model_axis_falls_back_single_device():
    import jax
    from veles_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    engine = build_engine(mesh=make_mesh({"data": 2}), warm=False)
    assert engine.mesh is None
    assert not engine.describe()["sharded"]
    engine.close()


# -- engine: compile discipline, KV ledger, slots ---------------------------

def test_warmup_compiles_everything_then_nothing():
    from veles_tpu import prof
    engine = build_engine(warm=False)
    assert engine.compile_count == 0
    engine.warmup()
    warm = engine.compile_count
    assert warm == len(engine.prefill_buckets) + 1
    recompiles = prof.ledger.recompiles
    workload = mixed_workload(8, seed=1)
    run_continuous(engine, workload)
    assert engine.compile_count == warm
    assert prof.ledger.recompiles == recompiles
    engine.close()


def test_post_warmup_compile_is_flagged():
    """A prompt needing an unwarmed bucket after warmup() IS served,
    but the sentinel flags the steady-state compile — the serve-bucket
    contract."""
    from veles_tpu import prof
    engine = GenerativeEngine(
        TransformerGenModel(CFG), max_slots=2, max_seq=48,
        prefill_buckets=(8,), seed=0)
    engine._decode_executable()
    engine._prefill_executable(8)
    engine._warmed = True
    flagged = len(prof.flagged)
    recompiles = prof.ledger.recompiles
    engine._prefill_executable(4)      # an unwarmed shape
    assert len(prof.flagged) == flagged + 1
    assert prof.ledger.recompiles == recompiles + 1
    engine.close()


def test_kv_cache_rides_the_hbm_ledger():
    """The reserved ``kv`` category goes live: allocation appears in
    hbm_ledger() current+peak and the /metrics prof gauge line, and
    close() releases it."""
    from veles_tpu import prof
    from veles_tpu.memory import Watcher
    before = Watcher.hbm_ledger()["by_category"].get(
        "kv", {"bytes": 0})["bytes"]
    engine = build_engine(warm=False)
    ledger = Watcher.hbm_ledger()["by_category"]["kv"]
    assert ledger["bytes"] == before + engine.kv_cache_bytes
    assert ledger["peak"] >= ledger["bytes"]
    # the exact layout: 2 tensors x L x slots x S x h x dh x itemsize
    assert engine.kv_cache_bytes == (
        2 * CFG["layers"] * 3 * 48 * CFG["heads"]
        * (CFG["dim"] // CFG["heads"]) * 4)
    text = prof.metrics_text()
    assert 'veles_prof_hbm_bytes{category="kv"}' in text
    engine.close()
    after = Watcher.hbm_ledger()["by_category"]["kv"]["bytes"]
    assert after == before
    engine.close()                      # idempotent


def test_slot_admission_and_eviction():
    engine = build_engine(max_slots=2)
    assert engine.free_slots == 2
    slot_a, _ = engine.prefill([1, 2, 3])
    slot_b, _ = engine.prefill([4])
    assert engine.free_slots == 0
    assert engine.occupancy() == 1.0
    with pytest.raises(RuntimeError):
        engine.prefill([5])
    engine.release_slot(slot_a)
    assert engine.free_slots == 1
    with pytest.raises(ValueError):
        engine.release_slot(slot_a)     # double release
    # freed slots are reused lowest-first (deterministic admission)
    slot_c, _ = engine.prefill([6])
    assert slot_c == slot_a
    engine.release_slot(slot_b)
    engine.release_slot(slot_c)
    engine.close()


def test_prompt_validation():
    engine = build_engine(warm=False)
    with pytest.raises(ValueError):
        engine.prefill([])
    with pytest.raises(ValueError):
        engine.bucket_for(17)           # beyond the largest bucket
    with pytest.raises(ValueError):
        engine.prefill(list(range(48)))  # no room to generate
    engine.close()


def test_eos_stops_generation():
    """A model-declared eos token ends the stream early with
    finish_reason "eos" — verified against the no-eos run's prefix."""
    workload = [(list(range(1, 6)), 8)]
    engine = build_engine()
    baseline, _ = run_continuous(engine, workload)
    engine.close()
    assert len(baseline[0]) == 8
    eos = baseline[0][2]                # the third generated token
    engine = build_engine(eos_id=eos)
    scheduler = GenerativeScheduler(engine)
    future = scheduler.submit(workload[0][0], 8)
    scheduler.run_until_idle()
    got = future.result(0)
    engine.close()
    assert got == baseline[0][:3]       # stops AT the eos token


# -- scheduler: queueing, metrics, streaming --------------------------------

def test_scheduler_bounded_queue_sheds():
    from veles_tpu.serve.batcher import QueueFull
    engine = build_engine(warm=False)
    scheduler = GenerativeScheduler(engine, max_queue=2)
    scheduler.submit([1], 2)
    scheduler.submit([2], 2)
    with pytest.raises(QueueFull):
        scheduler.submit([3], 2)
    with pytest.raises(ValueError):
        scheduler.submit([1], 0)        # bad budget
    with pytest.raises(ValueError):
        scheduler.submit([1] * 17, 2)   # prompt beyond buckets
    with pytest.raises(ValueError):
        scheduler.submit([1] * 8, 48)   # prompt + budget > max_seq
    engine.close()


def test_streaming_tokens_arrive_in_order():
    engine = build_engine()
    scheduler = GenerativeScheduler(engine)
    streamed = []
    future = scheduler.submit([1, 2, 3], 5,
                              on_token=streamed.append)
    scheduler.run_until_idle()
    assert future.result(0) == streamed
    assert len(streamed) == 5
    engine.close()


def test_scheduler_gauges_and_ttft_on_metrics():
    from veles_tpu.serve import ServingMetrics
    metrics = ServingMetrics()
    engine = build_engine()
    scheduler = GenerativeScheduler(engine, metrics=metrics,
                                    name="lm")
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in mixed_workload(6, seed=5)]
    scheduler.run_until_idle()
    assert all(f.done() for f in futures)
    snap = metrics.snapshot()
    assert snap['gen_slot_occupancy{model="lm"}'] == 0.0
    assert snap['gen_admitted_total{model="lm"}'] == 6
    assert snap['gen_tokens_total{model="lm"}'] == \
        scheduler.tokens_total
    assert 0.0 < snap['gen_batch_fill{model="lm"}'] <= 1.0
    assert snap['gen_ttft_p99_ms{model="lm"}'] > 0
    text = metrics.render_text()
    assert 'veles_serve_gen_slot_occupancy{model="lm"}' in text
    assert ('veles_serve_gen_ttft_seconds_bucket{model="lm",le='
            in text)
    assert 'veles_serve_gen_ttft_seconds_count{model="lm"}' in text
    # stop() unregisters — a dead scheduler must not haunt /metrics
    scheduler.stop(drain=False)
    assert 'gen_slot_occupancy{model="lm"}' not in metrics.snapshot()
    engine.close()


def test_perf_report_per_token_decode_accounting():
    from veles_tpu import prof
    engine = build_engine()
    run_continuous(engine, mixed_workload(5, seed=7))
    entries = [e for e in prof.ledger.entries("decode")
               if e.name.startswith(engine.prof_name)]
    assert len(entries) == 1
    assert entries[0].items > 0          # tokens accounted
    assert entries[0].items_per_s() > 0
    assert entries[0].flops_per_item() > 0
    row = entries[0].row(None)
    assert row["items"] == entries[0].items
    text = prof.report_text()
    assert "generative programs (per token):" in text
    assert "tok/s" in text
    engine.close()


# -- registry: generative deploys, replica sets, canary ---------------------

def test_registry_generative_deploy_describe_generate():
    from veles_tpu.serve import ModelRegistry, ServingMetrics
    metrics = ServingMetrics()
    registry = ModelRegistry(metrics=metrics)
    engine = build_engine(warm=False)
    model = registry.deploy_generative("lm", engine, version=7)
    try:
        info = registry.describe()["lm"]
        assert info["generative"] is True
        assert info["version"] == 7
        assert info["max_slots"] == 3
        assert info["prefill_buckets"] == [8, 16]
        assert info["kv_cache_bytes"] == engine.kv_cache_bytes
        out = registry.generate("lm", [1, 2, 3], max_new_tokens=4)
        assert len(out) == 4
        # the request/response path refuses generative names loudly
        with pytest.raises(ValueError):
            registry.submit("lm", numpy.ones((1, 4), numpy.float32))
        assert model.engine is engine
    finally:
        registry.stop()
    # stop() closed the engine's KV hold
    from veles_tpu.memory import Watcher
    assert Watcher.hbm_ledger()["by_category"]["kv"]["bytes"] >= 0
    assert not engine._kv_tracked


def test_registry_refuses_kind_mixups():
    from veles_tpu.serve import InferenceEngine, ModelRegistry
    registry = ModelRegistry()
    plain = InferenceEngine({"w": numpy.eye(4, dtype=numpy.float32)},
                            lambda p, x: x @ p["w"], (4,),
                            max_batch_size=4)
    registry.deploy("m", plain)
    gen_engine = build_engine(warm=False)
    with pytest.raises(ValueError):
        registry.deploy_generative("m", gen_engine, warmup=False)
    gen2 = build_engine(warm=False)
    registry.deploy_generative("lm", gen2, warmup=False)
    plain2 = InferenceEngine({"w": numpy.eye(4, dtype=numpy.float32)},
                             lambda p, x: x @ p["w"], (4,),
                             max_batch_size=4)
    with pytest.raises(ValueError):
        registry.deploy("lm", plain2)
    registry.stop()
    gen_engine.close()


def _dense_engine(scale, n=4):
    from veles_tpu.serve import InferenceEngine
    params = {"w": numpy.full((n, 2), scale, numpy.float32)}
    return InferenceEngine(params, lambda p, x: x @ p["w"], (n,),
                           max_batch_size=8)


def test_replica_set_weighted_split_and_describe():
    """The satellite fix: describe() reports replica weights and
    per-replica versions/served counts — a 3:1 canary split is
    assertable without reaching into privates, and smooth WRR makes
    it EXACT over any multiple of the weight total."""
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    registry.deploy("m", _dense_engine(1.0), version="v1")
    registry.deploy_canary("m", _dense_engine(2.0), weight=0.25,
                           version="v2")
    info = registry.describe()["m"]
    assert [r["version"] for r in info["replicas"]] == ["v1", "v2"]
    assert [r["weight"] for r in info["replicas"]] == [0.75, 0.25]
    rows = numpy.ones((1, 4), numpy.float32)
    for _ in range(40):
        registry.infer("m", rows)
    served = {r["version"]: r["served"]
              for r in registry.describe()["m"]["replicas"]}
    assert served == {"v1": 30, "v2": 10}
    # promotion = a plain deploy; describe() drops the replica table
    registry.deploy("m", _dense_engine(2.0), version="v2")
    assert "replicas" not in registry.describe()["m"]
    registry.stop()


def test_replica_set_guardrails():
    from veles_tpu.serve import ModelRegistry, ReplicaSet
    with pytest.raises(ValueError):
        ReplicaSet([])
    with pytest.raises(ValueError):
        ReplicaSet([(_dense_engine(1.0), 0.0, "v1")])
    with pytest.raises(ValueError):
        ReplicaSet([(_dense_engine(1.0, 4), 1, "a"),
                    (_dense_engine(1.0, 5), 1, "b")])  # shape clash
    registry = ModelRegistry()
    registry.deploy("m", _dense_engine(1.0), version="v1")
    with pytest.raises(ValueError):
        registry.deploy_canary("m", _dense_engine(2.0), weight=1.5)
    registry.deploy_canary("m", _dense_engine(2.0), weight=0.5)
    with pytest.raises(ValueError):   # no canary-on-canary stacks
        registry.deploy_canary("m", _dense_engine(3.0), weight=0.1)
    registry.stop()


def test_replica_set_serves_through_batcher():
    """End to end through the batcher: outputs alternate between the
    replicas' distinct weights at equal split — the swap really routes
    traffic, not just describe() rows."""
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    registry.deploy_replica_set(
        "m", [(_dense_engine(1.0), 1, "one"),
              (_dense_engine(2.0), 1, "two")])
    rows = numpy.ones((1, 4), numpy.float32)
    values = {float(registry.infer("m", rows)[0][0])
              for _ in range(4)}
    assert values == {4.0, 8.0}
    registry.stop()


# -- V-S01 preflight --------------------------------------------------------

class _PlanStub(object):
    """A plan-shaped object for check_generative (no device work)."""

    def __init__(self, **kw):
        class _Model(object):
            causal = kw.pop("causal", True)
            seq_limit = kw.pop("seq_limit", 64)
        self.model = _Model()
        self.max_slots = kw.pop("max_slots", 2)
        self.max_seq = kw.pop("max_seq", 48)
        self.prefill_buckets = kw.pop("prefill_buckets", (8, 16))
        self.kv_cache_bytes = kw.pop("kv_cache_bytes", 1024)
        assert not kw


def test_vs01_catalog_and_rules():
    from veles_tpu.analyze.findings import rule_catalog
    catalog = rule_catalog()
    assert "V-S01" in catalog
    assert catalog["V-S01"][0] == "error"


def test_vs01_plan_checks():
    from veles_tpu.analyze.shapes import check_generative
    assert not check_generative(_PlanStub(),
                                hbm_bytes=1 << 30).has_errors
    assert check_generative(_PlanStub(causal=False)).has_errors
    assert check_generative(_PlanStub(max_slots=0)).has_errors
    assert check_generative(_PlanStub(prefill_buckets=())).has_errors
    assert check_generative(
        _PlanStub(prefill_buckets=(64,))).has_errors   # > max_seq
    assert check_generative(
        _PlanStub(max_seq=128)).has_errors   # > positional table
    # footprint: error over 90% of HBM, warning over half
    big = _PlanStub(kv_cache_bytes=1000)
    assert check_generative(big, hbm_bytes=1000).has_errors
    warn = check_generative(_PlanStub(kv_cache_bytes=600),
                            hbm_bytes=1000)
    assert not warn.has_errors
    assert any(f.severity == "warning" for f in warn.findings)
    # CPU (no HBM table entry) degrades to plan sanity only
    assert not check_generative(_PlanStub(),
                                hbm_bytes=None).has_errors


def test_vs01_gates_deploy_in_fail_mode():
    from veles_tpu.analyze import PreflightError
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    prior = root.common.serve.get("preflight", "warn")
    root.common.serve.preflight = "fail"
    try:
        with pytest.raises(PreflightError):
            registry.preflight_generative(_PlanStub(causal=False))
        assert registry.preflight_generative(_PlanStub()) is not None
        root.common.serve.preflight = "off"
        assert registry.preflight_generative(
            _PlanStub(causal=False)) is None
    finally:
        root.common.serve.preflight = prior
        registry.stop()


# -- wire + server ----------------------------------------------------------

def test_wire_decode_gen_request():
    from veles_tpu.serve.wire import decode_gen_request
    tokens, max_new, stream = decode_gen_request(
        {"tokens": [1, 2, 3], "max_new_tokens": 4, "stream": True})
    assert tokens.dtype == numpy.int32
    assert tokens.tolist() == [1, 2, 3]
    assert (max_new, stream) == (4, True)
    tokens, max_new, stream = decode_gen_request({"tokens": [0]})
    assert (max_new, stream) == (16, False)
    for bad in (
            [],                                   # not a dict
            {},                                   # no tokens
            {"tokens": []},                       # empty
            {"tokens": "abc"},                    # not a list
            {"tokens": [1, -2]},                  # negative
            {"tokens": [1, True]},                # bool masquerade
            {"tokens": [1], "max_new_tokens": 0},
            {"tokens": [1], "max_new_tokens": "9"},
            {"tokens": [1], "stream": "yes"},
    ):
        with pytest.raises(ValueError):
            decode_gen_request(bad)


def test_server_generate_routes():
    from veles_tpu.serve import ModelRegistry, ServingServer
    registry = ModelRegistry()
    registry.deploy_generative("lm", build_engine(warm=False),
                               version=1)
    server = ServingServer(registry=registry)
    try:
        status, payload = server.handle_generate(
            "/generate/lm", json.dumps(
                {"tokens": [1, 2], "max_new_tokens": 3}).encode())
        assert status == 200
        assert len(payload["tokens"]) == 3
        assert payload["model"] == "lm" and payload["version"] == 1
        status, payload = server.handle_generate(
            "/generate/nope", b"{}")
        assert status == 404
        status, payload = server.handle_generate(
            "/generate/lm", b'{"tokens": []}')
        assert status == 400
        status, payload = server.handle_generate(
            "/generate/lm", b"not json")
        assert status == 400
        # default-model route without a generative "default" -> 404
        status, _ = server.handle_generate("/generate", b"{}")
        assert status == 404
        # streamed variant frames every token then the final document
        lines = list(server.stream_generate(
            "/generate/lm", json.dumps(
                {"tokens": [5], "max_new_tokens": 2,
                 "stream": True}).encode()))
        assert lines[0][0] == 200
        events = [json.loads(line) for _s, line in lines]
        assert [e["token"] for e in events[:-1]] == \
            events[-1]["tokens"]
        assert events[-1]["done"] is True
    finally:
        server.stop()


def test_server_predict_route_rejects_generative():
    from veles_tpu.serve import ModelRegistry, ServingServer
    registry = ModelRegistry()
    registry.deploy_generative("lm", build_engine(warm=False))
    server = ServingServer(registry=registry)
    try:
        status, payload = server.handle_generate(
            "/service/lm", b"{}")
        assert status == 404              # wrong prefix entirely
        status, payload = server.handle_predict(
            "/service/lm", json.dumps({"input": [[0.0] * 4]}).encode())
        assert status in (400, 500)       # not a batcher model
    finally:
        server.stop()


# -- the throughput gate ----------------------------------------------------

@pytest.mark.slow
def test_throughput_continuous_vs_static_closed_loop():
    """≥1.5x tokens/s over the pad-to-max static batcher on CPU JAX
    for a closed-loop mixed-length load, with zero steady-state
    compiles after warmup on BOTH engines (recompile sentinel quiet).
    Identical compiled programs and bitwise-identical tokens — the
    speedup is pure iteration-level admission."""
    import time

    from veles_tpu import prof

    cfg = dict(TINY, seq_len=128)
    slots, max_seq, buckets = 4, 96, (8,)
    rng = numpy.random.default_rng(0)
    workload = [
        (rng.integers(0, cfg["vocab"],
                      int(rng.integers(1, 9))).tolist(),
         64 if i % slots == 0 else int(rng.integers(2, 9)))
        for i in range(48)]

    def build():
        return GenerativeEngine(
            TransformerGenModel(cfg), max_slots=slots,
            max_seq=max_seq, prefill_buckets=buckets,
            seed=0).warmup()

    engine = build()
    recompiles0 = prof.ledger.recompiles
    warm = engine.compile_count
    scheduler = GenerativeScheduler(engine)
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    tic = time.perf_counter()
    scheduler.run_until_idle()
    cont_sec = time.perf_counter() - tic
    continuous = [f.result(0) for f in futures]
    cont_tokens = scheduler.tokens_total
    assert engine.compile_count == warm
    fill = scheduler.batch_fill()
    engine.close()

    engine = build()
    tic = time.perf_counter()
    static, _steps = static_generate(engine, workload)
    static_sec = time.perf_counter() - tic
    static_tokens = sum(len(r) for r in static)
    assert engine.compile_count == warm
    engine.close()
    assert prof.ledger.recompiles == recompiles0

    assert static == continuous          # same tokens, bit for bit
    assert cont_tokens == static_tokens
    cont_tps = cont_tokens / cont_sec
    static_tps = static_tokens / static_sec
    assert fill > 0.75
    assert cont_tps >= 1.5 * static_tps, \
        "continuous %.0f tok/s vs static %.0f tok/s (%.2fx, " \
        "fill %.2f)" % (cont_tps, static_tps, cont_tps / static_tps,
                        fill)


# -- review regressions -----------------------------------------------------

def test_metrics_histogram_families_single_type_header():
    """Two generative models' TTFT histograms share ONE HELP/TYPE
    header with both label variants grouped under it — a duplicate
    TYPE line for the same family is a Prometheus parse error that
    kills the whole scrape."""
    from veles_tpu.metrics import LatencyHistogram
    from veles_tpu.serve import ServingMetrics
    metrics = ServingMetrics()
    a, b = LatencyHistogram(), LatencyHistogram()
    a.record(0.01)
    b.record(0.02)
    metrics.register_histogram("gen_ttft_seconds", a, "ttft",
                               labels={"model": "a"})
    metrics.register_histogram("gen_ttft_seconds", b, "ttft",
                               labels={"model": "b"})
    text = metrics.render_text()
    assert text.count(
        "# TYPE veles_serve_gen_ttft_seconds histogram") == 1
    assert 'gen_ttft_seconds_bucket{model="a",le=' in text
    assert 'gen_ttft_seconds_bucket{model="b",le=' in text
    assert 'gen_ttft_seconds_count{model="a"}' in text
    assert 'gen_ttft_seconds_count{model="b"}' in text


def test_failed_prefill_fails_that_request_only():
    """A prefill blow-up fails the popped request's future instead of
    orphaning it; co-admitted requests still get their attempt."""
    engine = build_engine()
    scheduler = GenerativeScheduler(engine)
    boom = {"armed": True}
    real_prefill = engine.prefill

    def flaky_prefill(tokens):
        if boom.pop("armed", False):
            raise RuntimeError("device fault")
        return real_prefill(tokens)

    engine.prefill = flaky_prefill
    doomed = scheduler.submit([1, 2], 3)
    survivor = scheduler.submit([3, 4], 3)
    scheduler.run_until_idle()
    with pytest.raises(RuntimeError):
        doomed.result(0)
    assert survivor.result(0) and len(survivor.result(0)) == 3
    engine.close()


def test_stop_fails_active_futures_loudly():
    """stop(drain=False) must resolve slot-occupying requests with an
    exception — a silent pending future blocks its client for the
    full request timeout against a closed engine."""
    engine = build_engine()
    scheduler = GenerativeScheduler(engine)
    future = scheduler.submit([1, 2, 3], 40)
    scheduler.step()                      # admitted into a slot
    assert scheduler.active_requests() == 1
    scheduler.stop(drain=False)
    with pytest.raises(RuntimeError):
        future.result(0)
    assert engine.free_slots == engine.max_slots
    engine.close()


def test_registry_undeploy_single_model():
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    registry.deploy("m", _dense_engine(1.0), version="v1")
    registry.deploy_generative("lm", build_engine(warm=False))
    registry.undeploy("m")
    assert registry.names() == ["lm"]
    with pytest.raises(KeyError):
        registry.undeploy("m")
    gen_engine = registry.get("lm").engine
    registry.undeploy("lm", drain=False)
    assert registry.names() == []
    assert not gen_engine._kv_tracked    # KV hold released
    registry.stop()


# -- paged KV: THE parity gate extends --------------------------------------

def test_paged_matches_contiguous_bitwise():
    """The paged engine (block pool + tables) produces BITWISE the
    contiguous engine's token streams — continuous, sequential and
    static — for the seeded mixed-length set.  Allocation must not be
    a numerics change."""
    workload = mixed_workload(10)
    engine = build_engine()
    contiguous, _ = run_continuous(engine, workload)
    engine.close()
    engine = build_engine(kv="paged", block_size=8)
    assert engine.describe()["kv"] == "paged"
    paged, sched = run_continuous(engine, workload)
    assert sched.batch_fill() > 0.5
    assert engine.preemptions_total == 0     # full-capacity pool
    engine.close()
    assert paged == contiguous
    engine = build_engine(kv="paged", block_size=8)
    sequential, _ = run_sequential(engine, workload)
    engine.close()
    assert sequential == paged
    engine = build_engine(kv="paged", block_size=8)
    static, _steps = static_generate(engine, workload)
    engine.close()
    assert static == paged


def test_paged_matches_contiguous_on_mesh():
    """The same paged parity on the tensor-parallel engine: pool
    sharded over heads, tables replicated."""
    import jax
    from veles_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = make_mesh({"model": 2})
    workload = mixed_workload(6, seed=3, max_new_hi=7)
    engine = build_engine(mesh=mesh, max_slots=2)
    contiguous, _ = run_continuous(engine, workload)
    engine.close()
    engine = build_engine(mesh=mesh, max_slots=2, kv="paged",
                          block_size=8)
    assert engine.describe()["sharded"]
    paged, _ = run_continuous(engine, workload)
    engine.close()
    assert paged == contiguous


def test_paged_zero_steady_state_compiles():
    from veles_tpu import prof
    engine = build_engine(kv="paged", block_size=8, warm=False)
    engine.warmup()
    warm = engine.compile_count
    assert warm == len(engine.prefill_buckets) + 1
    recompiles = prof.ledger.recompiles
    run_continuous(engine, mixed_workload(8, seed=1))
    assert engine.compile_count == warm
    assert prof.ledger.recompiles == recompiles
    engine.close()


def test_paged_pool_ledger_and_describe():
    """Pool bytes (num_blocks x block_size pages, trash included)
    ride the kv HBM ledger category exactly like the slot cache, and
    describe() exposes the pool surface."""
    from veles_tpu.memory import Watcher
    before = Watcher.hbm_ledger()["by_category"].get(
        "kv", {"bytes": 0})["bytes"]
    engine = build_engine(kv="paged", block_size=8, warm=False)
    assert engine.num_blocks == 3 * (48 // 8) + 1
    assert engine.kv_cache_bytes == (
        2 * CFG["layers"] * engine.num_blocks * 8 * CFG["heads"]
        * (CFG["dim"] // CFG["heads"]) * 4)
    ledger = Watcher.hbm_ledger()["by_category"]["kv"]
    assert ledger["bytes"] == before + engine.kv_cache_bytes
    info = engine.describe()
    assert info["block_size"] == 8
    assert info["blocks_total"] == engine.num_blocks - 1
    assert info["blocks_free"] == info["blocks_total"]
    assert info["preemptions_total"] == 0
    engine.close()
    assert Watcher.hbm_ledger()["by_category"]["kv"]["bytes"] == before


def test_paged_rejects_misaligned_geometry():
    with pytest.raises(ValueError):
        build_engine(kv="paged", block_size=7, warm=False)   # 48 % 7
    with pytest.raises(ValueError):
        build_engine(kv="paged", block_size=8, num_blocks=4,
                     warm=False)            # < one full sequence
    with pytest.raises(ValueError):
        build_engine(kv="nonsense", warm=False)
    with pytest.raises(ValueError):
        # a non-divisor chunk's padded final write would spill past
        # the cache — rejected in BOTH kv modes
        build_engine(prefill_chunk=32, warm=False)   # 48 % 32
    with pytest.raises(ValueError):
        build_engine(kv="paged", block_size=8, prefill_chunk=32,
                     warm=False)


def test_block_pool_deterministic_allocation():
    """Lowest-id-first allocation, sorted release, the trash block
    never handed out — the invariants the bitwise parity gate leans
    on."""
    from veles_tpu.gen import BlockPool, PoolExhausted
    pool = BlockPool(slots=2, max_blocks=4, num_blocks=9,
                     block_size=8)
    ids = pool.admit(0, 17)                  # ceil(17/8) = 3 pages
    assert ids == [1, 2, 3]
    assert pool.tables[0].tolist() == [1, 2, 3, 0]
    assert pool.admit(1, 4) == [4]
    assert not pool.needs_append(0, 20)      # inside page 3
    assert pool.needs_append(0, 24)
    assert pool.append(0, 24) is True
    assert pool.tables[0].tolist() == [1, 2, 3, 5]
    assert pool.blocks_free == 3
    with pytest.raises(ValueError):
        pool.admit(1, 4)                     # slot 1 already owns
    pool.release(0)
    assert pool.blocks_free == 7
    assert pool.tables[0].tolist() == [0, 0, 0, 0]
    # freed pages come back lowest-first
    assert pool.admit(0, 1) == [1]
    exc = None
    pool2 = BlockPool(slots=1, max_blocks=4, num_blocks=5,
                      block_size=8)
    try:
        pool2.admit(0, 33)
    except PoolExhausted as e:
        exc = e
    assert exc is not None and exc.needed == 5 and exc.free == 4


def test_paged_pool_exhaustion_preempts_losslessly():
    """THE preemption gate: a pool too small for the workload must
    preempt (youngest first), requeue, and still produce streams
    byte-identical to the uncontended run — deterministically across
    repeats."""
    workload = mixed_workload(10)
    engine = build_engine(kv="paged", block_size=8)   # full pool
    uncontended, _ = run_continuous(engine, workload)
    engine.close()
    runs = []
    for _ in range(2):
        engine = build_engine(kv="paged", block_size=8, num_blocks=9,
                              prefill_chunk=8)
        tokens, sched = run_continuous(engine, workload)
        assert engine.preemptions_total >= 1
        preemptions = engine.preemptions_total
        engine.close()
        runs.append((tokens, preemptions))
    assert runs[0] == runs[1]                # deterministic
    assert runs[0][0] == uncontended         # lossless


def test_paged_admission_priced_by_pool_headroom():
    """can_admit answers with ACTUAL pages, and the scheduler queues
    (FIFO, head-of-line) instead of failing when the pool is full."""
    engine = build_engine(kv="paged", block_size=8, num_blocks=9,
                          buckets=(8, 16, 40))
    # 8 usable pages; a 16-token prompt needs 2
    assert engine.can_admit(16)
    slot, _ = engine.prefill(list(range(1, 40)))     # 39 -> 5 pages
    assert engine.blocks_free == 3
    assert engine.can_admit(16)
    assert not engine.can_admit(30)          # 4 pages > 3 free
    from veles_tpu.gen import PoolExhausted
    with pytest.raises(PoolExhausted):
        engine.prefill(list(range(1, 31)))
    assert engine.free_slots == 2            # failed admit freed slot
    engine.release_slot(slot)
    assert engine.blocks_free == 8
    # through the scheduler: the queued request WAITS (FIFO) while the
    # long resident holds the pool, then admits when pages free
    scheduler = GenerativeScheduler(engine)
    long_future = scheduler.submit(list(range(1, 40)), 4)
    blocked = scheduler.submit(list(range(1, 31)), 2)
    scheduler.step()                         # long in, blocked queued
    assert not blocked.done()
    assert scheduler.queue_depth() == 1
    scheduler.run_until_idle()
    assert len(long_future.result(0)) == 4
    assert len(blocked.result(0)) == 2
    engine.close()


def test_chunked_prefill_matches_whole_prompt():
    """Chunked admission (one chunk per step, contiguous AND paged)
    reproduces the whole-prompt streams, with exactly TWO warmup
    compiles (decode + the one chunk program)."""
    from veles_tpu import prof
    workload = mixed_workload(8, seed=4)
    engine = build_engine()
    whole, _ = run_continuous(engine, workload)
    engine.close()
    for kw in ({"prefill_chunk": 8},
               {"prefill_chunk": 8, "kv": "paged", "block_size": 8}):
        engine = build_engine(warm=False, **kw)
        engine.warmup()
        assert engine.compile_count == 2, kw
        recompiles = prof.ledger.recompiles
        chunked, _ = run_continuous(engine, workload)
        assert engine.compile_count == 2, kw
        assert prof.ledger.recompiles == recompiles
        engine.close()
        assert chunked == whole, kw


def test_chunked_prefill_config_knobs():
    """root.common.gen.kv / prefill_chunk drive the engine defaults;
    explicit kwargs win."""
    prior_kv = root.common.gen.get("kv", None)
    prior_chunk = root.common.gen.get("prefill_chunk", None)
    root.common.gen.kv = "paged"
    root.common.gen.prefill_chunk = 7        # rounds up to a page
    try:
        engine = build_engine(warm=False, block_size=8)
        assert engine.kv_mode == "paged"
        assert engine.prefill_chunk == 8
        engine.close()
        with pytest.raises(ValueError):
            # contiguous mode takes the raw knob: 48 % 7 -> rejected
            build_engine(warm=False, kv="contiguous")
        engine = build_engine(warm=False, kv="contiguous",
                              prefill_chunk=6)   # kwarg wins
        assert engine.kv_mode == "contiguous"
        assert engine.prefill_chunk == 6
        engine.close()
    finally:
        root.common.gen.kv = prior_kv or "contiguous"
        if prior_chunk is None:
            root.common.gen.prefill_chunk = None
        else:
            root.common.gen.prefill_chunk = prior_chunk


def test_saturated_slot_evicts_via_finish_reason():
    """The satellite fix: an active slot parked at max_seq no longer
    crashes decode_step — the engine excludes it from the dispatch
    and the scheduler routes it through the SHARED finish predicate
    (reason "length"), in both kv modes."""
    for kw in ({}, {"kv": "paged", "block_size": 8}):
        engine = build_engine(**kw)
        scheduler = GenerativeScheduler(engine)
        doomed = scheduler.submit([1, 2, 3], 40)
        survivor = scheduler.submit([4, 5], 3)
        scheduler.step()                     # both admitted
        # park the first slot at capacity (simulates the race the
        # old engine answered with RuntimeError at engine.py:313);
        # deterministic slot 0: the sorted free list admits in order
        engine.slot_len[0] = engine.max_seq
        out = engine.decode_step()           # no raise
        assert out is not None and not out[1][0]
        scheduler.step()
        assert doomed.done()
        assert doomed.result(0)              # resolved, not crashed
        scheduler.run_until_idle()
        assert survivor.result(0) and len(survivor.result(0)) == 3
        assert engine.free_slots == engine.max_slots
        engine.close()


def test_paged_scheduler_gauges_on_metrics():
    """The block-pool gauge surface: blocks total/free, preemptions
    and per-request HBM next to the PR 8 gen gauges, registered and
    unregistered with the scheduler."""
    from veles_tpu.serve import ServingMetrics
    metrics = ServingMetrics()
    engine = build_engine(kv="paged", block_size=8)
    scheduler = GenerativeScheduler(engine, metrics=metrics,
                                    name="lm")
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in mixed_workload(5, seed=6)]
    scheduler.run_until_idle()
    assert all(f.done() for f in futures)
    snap = metrics.snapshot()
    assert snap['gen_blocks_total{model="lm"}'] == \
        engine.blocks_total
    assert snap['gen_blocks_free{model="lm"}'] == engine.blocks_total
    assert snap['gen_preemptions_total{model="lm"}'] == 0
    assert snap['gen_hbm_per_request_bytes{model="lm"}'] == 0
    text = metrics.render_text()
    assert 'veles_serve_gen_blocks_total{model="lm"}' in text
    scheduler.stop(drain=False)
    assert 'gen_blocks_total{model="lm"}' not in metrics.snapshot()
    engine.close()
    # contiguous engines still expose preemptions + per-request HBM
    metrics2 = ServingMetrics()
    engine = build_engine()
    scheduler = GenerativeScheduler(engine, metrics=metrics2,
                                    name="c")
    snap = metrics2.snapshot()
    assert snap['gen_preemptions_total{model="c"}'] == 0
    assert 'gen_blocks_total{model="c"}' not in snap
    scheduler.stop(drain=False)
    engine.close()


def test_vs01_paged_plan_checks():
    """V-S01 learns the paged plan: sublane-hostile block sizes and
    a pool below one sequence are errors; a pool below the observed
    mix and bucket-capped requeue are warnings; pricing follows the
    pool bytes."""
    from veles_tpu.analyze.shapes import check_generative

    def stub(**kw):
        plan = _PlanStub(**{k: v for k, v in kw.items()
                            if k in ("max_slots", "max_seq",
                                     "prefill_buckets",
                                     "kv_cache_bytes")})
        plan.kv_mode = "paged"
        plan.block_size = kw.get("block_size", 8)
        plan.num_blocks = kw.get("num_blocks", 13)
        plan.prefill_chunk = kw.get("prefill_chunk", 8)
        return plan

    assert not check_generative(stub(), hbm_bytes=1 << 30).has_errors
    assert check_generative(stub(block_size=6)).has_errors   # < 8
    assert check_generative(stub(block_size=10)).has_errors  # % 8
    assert check_generative(
        stub(block_size=32)).has_errors      # 48 % 32 != 0
    assert check_generative(stub(num_blocks=4)).has_errors   # < 1 seq
    assert check_generative(
        stub(prefill_chunk=32)).has_errors   # chunk ∤ max_seq
    report = check_generative(stub(num_blocks=7, max_slots=4),
                              hbm_bytes=1 << 30)
    assert not report.has_errors
    assert any(f.severity == "warning" for f in report.findings)
    # whole-prompt paged with buckets below max_seq: requeue warning
    report = check_generative(stub(prefill_chunk=None),
                              hbm_bytes=1 << 30)
    assert any("requeue" in f.message for f in report.findings)


def test_registry_deploys_paged_engine_end_to_end():
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    engine = build_engine(kv="paged", block_size=8,
                          prefill_chunk=8, warm=False)
    registry.deploy_generative("lm", engine, version=1)
    try:
        info = registry.describe()["lm"]
        assert info["kv"] == "paged"
        assert info["blocks_total"] == engine.blocks_total
        out = registry.generate("lm", [1, 2, 3], max_new_tokens=4)
        assert len(out) == 4
    finally:
        registry.stop()
    assert not engine._kv_tracked


# -- the capacity + TTFT gate (paged acceptance) ----------------------------

@pytest.mark.slow
def test_paged_capacity_and_chunked_ttft_closed_loop():
    """The paged mode's reason to exist, measured: (1) at EQUAL kv
    HBM budget (ledger bytes, trash page included) the pool admits
    >= 1.5x the concurrent sequences of the contiguous engine on a
    short-sequence mix the contiguous engine must queue; (2) chunked
    prefill cuts co-resident shorts' TTFT p99 vs whole-prompt
    admission in the same setup — with bitwise token parity
    throughout."""
    import time

    # (1) capacity at equal ledger budget: 2 contiguous slots x 96
    # rows == 24 pages; the pool gets 24 usable (+1 trash, 4% over)
    cfg = dict(TINY, seq_len=128)

    def model():
        return TransformerGenModel(cfg)

    contiguous = GenerativeEngine(
        model(), max_slots=2, max_seq=96, prefill_buckets=(8,),
        seed=0).warmup()
    paged = GenerativeEngine(
        model(), max_slots=8, max_seq=96, prefill_buckets=(8,),
        seed=0, kv="paged", block_size=8, num_blocks=25).warmup()
    assert paged.kv_cache_bytes <= 1.05 * contiguous.kv_cache_bytes
    rng = numpy.random.default_rng(2)
    workload = [
        (rng.integers(0, cfg["vocab"],
                      int(rng.integers(1, 9))).tolist(),
         int(rng.integers(4, 9)))
        for _ in range(24)]

    def run(engine):
        scheduler = GenerativeScheduler(engine)
        futures = [scheduler.submit(toks, max_new)
                   for toks, max_new in workload]
        peak = 0
        while scheduler.queue_depth() or scheduler.active_requests():
            if scheduler.step() == 0:
                break
            peak = max(peak, scheduler.active_requests())
        tokens = [f.result(0) for f in futures]
        engine.close()
        return tokens, peak

    cont_tokens, cont_peak = run(contiguous)
    paged_tokens, paged_peak = run(paged)
    assert paged_tokens == cont_tokens       # parity under pressure
    assert paged_peak >= 1.5 * cont_peak, \
        "paged admitted %d concurrent vs contiguous %d" \
        % (paged_peak, cont_peak)

    # (2) chunked prefill vs whole-prompt admission: one long prompt
    # bursts in with three shorts; whole-prompt mode makes every
    # short's first token wait for the 440-token prefill dispatch,
    # the chunk cadence only for one 64-token chunk.  Big model so
    # prefill compute dominates dispatch overhead; best-of-2 runs
    # per mode absorbs CI timer noise.
    big = {"vocab": 512, "dim": 256, "heads": 4, "layers": 4,
           "mlp_ratio": 4, "seq_len": 512}

    def ttft_run(chunk):
        engine = GenerativeEngine(
            TransformerGenModel(big), max_slots=4, max_seq=512,
            prefill_buckets=(64, 448), seed=0, kv="paged",
            block_size=32, prefill_chunk=chunk).warmup()
        scheduler = GenerativeScheduler(engine)
        rng = numpy.random.default_rng(3)
        jobs = [(rng.integers(0, big["vocab"], 440).tolist(), 3)] + [
            (rng.integers(0, big["vocab"],
                          int(rng.integers(4, 33))).tolist(), 6)
            for _ in range(3)]
        first, futures = {}, []
        for i, (toks, max_new) in enumerate(jobs):
            t0 = time.perf_counter()

            def cb(_tok, i=i, t0=t0):
                if i not in first:
                    first[i] = time.perf_counter() - t0

            futures.append(scheduler.submit(toks, max_new,
                                            on_token=cb))
        scheduler.run_until_idle()
        tokens = [f.result(0) for f in futures]
        engine.close()
        return tokens, max(first[i] for i in (1, 2, 3))

    whole_tokens, whole_p99 = ttft_run(None)
    chunk_tokens, chunk_p99 = ttft_run(64)
    whole_p99 = min(whole_p99, ttft_run(None)[1])
    chunk_p99 = min(chunk_p99, ttft_run(64)[1])
    assert chunk_tokens == whole_tokens      # chunking is not numerics
    assert chunk_p99 < whole_p99, \
        "co-resident TTFT p99: chunked %.3fs vs whole-prompt %.3fs" \
        % (chunk_p99, whole_p99)


# -- prefix cache + speculative decode --------------------------------------

def shared_workload(n=6, stem_len=13, max_new=6, first=20):
    """n requests re-deriving one common stem — the agent-traffic
    shape the radix cache exists for (stem pages shareable, one
    distinct suffix token each)."""
    stem = [(i * 7 + 3) % CFG["vocab"] for i in range(stem_len)]
    return [(stem + [first + i], max_new) for i in range(n)]


def spec_workload(n=4, max_new=10, first=30):
    """Repetitive prompts the n-gram proposer can exploit."""
    stem = (list(range(2, 10)) * 3)[:18]
    return [(stem + [first + i], max_new) for i in range(n)]


def test_block_pool_refcount_sharing():
    """BlockPool refcount unit: admission over shared pages increfs
    before allocating (with rollback), truncate/release decref
    instead of free, and pages_saved prices the sharing."""
    from veles_tpu.gen.paged import BlockPool, PoolExhausted
    pool = BlockPool(slots=4, max_blocks=4, num_blocks=9,
                     block_size=8)
    owner = pool.admit(0, 17)                # pages 1, 2, 3
    assert owner == [1, 2, 3]
    assert [pool.refcount(b) for b in owner] == [1, 1, 1]
    pool.incref(1)
    pool.incref(2)                           # the cache registers two
    assert pool.pages_saved() == 0           # registration != sharing
    shared = pool.admit(1, 20, shared=(1, 2))
    assert shared == [1, 2, 4]               # lowest-id-first suffix
    assert pool.refcount(1) == 3 and pool.refcount(2) == 3
    assert pool.pages_saved() == 2           # slot 1 skipped two pages
    assert pool.blocks_used == 4             # 1, 2, 3, 4 — shared once
    # truncate drops only the UNSHARED tail page
    assert pool.truncate(1, 16) == 1         # one page off the table
    assert pool.refcount(4) == 0             # freed for reuse
    assert pool.refcount(1) == 3             # shared pages untouched
    # release decrefs — the cache's ref keeps the pages alive
    pool.release(0)
    assert pool.refcount(3) == 0
    assert pool.refcount(1) == 2 and pool.refcount(2) == 2
    # rollback: an admit that cannot fit must not leak increfs
    pool.admit(0, 32)                        # 4 pages
    pool.admit(2, 16)                        # 2 pages: pool now full
    with pytest.raises(PoolExhausted):
        pool.admit(3, 24, shared=(1, 2))     # needs 1 fresh, has 0
    assert pool.refcount(1) == 2 and pool.refcount(2) == 2


def test_prefix_radix_tree_unit():
    """PrefixCache unit: page-granular radix match capped at the last
    FULL page, per-tag isolation, LRU-leaf eviction that never frees
    a page with live slot refs, and reclaimable() accounting."""
    from veles_tpu.gen.paged import BlockPool
    from veles_tpu.gen.prefix import PrefixCache
    pool = BlockPool(slots=2, max_blocks=8, num_blocks=17,
                     block_size=4)
    cache = PrefixCache(pool)
    toks = list(range(100, 117))             # 17 tokens, 4 full pages
    bids = pool.admit(0, 17)                 # pages 1..5
    cache.insert(toks, bids[:4], tag="b0")
    assert cache.match(toks, tag="b0") == bids[:4]
    # the LAST token never matches: >= 1 suffix token stays unshared
    assert cache.match(toks[:17], tag="b0") == bids[:4]
    assert cache.match(toks[:9], tag="b0") == bids[:2]
    assert cache.match(toks, tag="chunk8") == []     # tag isolation
    diverged = toks[:6] + [999] + toks[7:]
    assert cache.match(diverged, tag="b0") == bids[:1]
    # live slot refs pin every page: eviction must free NOTHING
    assert cache.cache_only_pages() == 0
    assert cache.reclaimable() == 0
    assert cache.evict(4) == 0
    assert pool.refcount(bids[0]) == 2
    # slot gone -> the whole chain is cache-only and reclaimable
    pool.release(0)
    assert cache.cache_only_pages() == 4
    assert cache.reclaimable() == 4
    assert cache.evict(2) == 2               # deepest leaves first
    assert cache.match(toks, tag="b0") == bids[:2]
    assert pool.refcount(bids[3]) == 0       # actually freed
    cache.clear()
    assert cache.match(toks, tag="b0") == []
    assert pool.blocks_used == 0


def test_prefix_cache_parity_and_sharing():
    """THE prefix gate: prefix_cache=on produces BITWISE the plain
    engine's streams — continuous, sequential, static, chunked — on a
    shared-stem workload, while actually sharing pages (both kv
    modes covered: the cached paged streams equal the contiguous
    engine's)."""
    workload = shared_workload(6)
    engine = build_engine()                  # contiguous reference
    contiguous, _ = run_continuous(engine, workload)
    engine.close()
    engine = build_engine(kv="paged", block_size=8)
    plain, _ = run_continuous(engine, workload)
    engine.close()
    assert plain == contiguous
    engine = build_engine(kv="paged", block_size=8,
                          prefix_cache="on")
    assert engine.describe()["prefix_cache"] == "on"
    cached, _ = run_continuous(engine, workload)
    assert engine.prefix_shared_pages_total >= 1
    assert engine.prefix_hit_rate() > 0
    engine.close()
    assert cached == plain
    engine = build_engine(kv="paged", block_size=8,
                          prefix_cache="on")
    sequential, _ = run_sequential(engine, workload)
    assert engine.prefix_hit_rate() > 0      # every follower matched
    engine.close()
    assert sequential == plain
    engine = build_engine(kv="paged", block_size=8,
                          prefix_cache="on")
    static, _steps = static_generate(engine, workload)
    engine.close()
    assert static == plain
    # chunked admission: adopted chunks SKIP their prefill compute
    engine = build_engine(kv="paged", block_size=8,
                          prefix_cache="on", prefill_chunk=8)
    chunked, _ = run_sequential(engine, workload)
    assert engine.prefix_shared_pages_total >= 1
    engine.close()
    assert chunked == plain


def test_prefix_cache_parity_on_mesh():
    """The same prefix parity on the tensor-parallel engine."""
    import jax
    from veles_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = make_mesh({"model": 2})
    workload = shared_workload(4, max_new=5)
    engine = build_engine(mesh=mesh, max_slots=2, kv="paged",
                          block_size=8)
    plain, _ = run_continuous(engine, workload)
    engine.close()
    engine = build_engine(mesh=mesh, max_slots=2, kv="paged",
                          block_size=8, prefix_cache="on")
    assert engine.describe()["sharded"]
    cached, _ = run_continuous(engine, workload)
    assert engine.prefix_shared_pages_total >= 1
    engine.close()
    assert cached == plain


def test_prefix_cache_parity_int8():
    """Prefix sharing composes with the int8 deploy: quantized
    engines with the cache on/off stream identically."""
    workload = shared_workload(5)
    streams = []
    for kw in ({}, {"prefix_cache": "on"}):
        engine = build_engine(kv="paged", block_size=8, warm=False,
                              **kw)
        engine.quantize_int8(calibration_tokens=workload[0][0])
        engine.warmup()
        tokens, _ = run_continuous(engine, workload)
        if kw:
            assert engine.prefix_shared_pages_total >= 1
        engine.close()
        streams.append(tokens)
    assert streams[0] == streams[1]


def test_prefix_cache_shrinks_kv_ledger():
    """The capacity win, measured: concurrent shared-stem streams
    peak at <= 0.6x the plain engine's pool pages (pages ARE the kv
    ledger: kv_cache_bytes scales linearly in num_blocks), with
    bitwise parity."""

    def peak_run(engine, workload):
        scheduler = GenerativeScheduler(engine)
        futures = [scheduler.submit(toks, max_new)
                   for toks, max_new in workload]
        peak = 0
        while scheduler.queue_depth() or scheduler.active_requests():
            if scheduler.step() == 0:
                break
            peak = max(peak,
                       engine.blocks_total - engine.blocks_free)
        tokens = [f.result(0) for f in futures]
        engine.close()
        return tokens, peak

    workload = shared_workload(3, stem_len=25, max_new=6)
    plain, plain_peak = peak_run(
        build_engine(kv="paged", block_size=8, buckets=(8, 16, 32)),
        workload)
    cached, cached_peak = peak_run(
        build_engine(kv="paged", block_size=8, buckets=(8, 16, 32),
                     prefix_cache="on"), workload)
    assert cached == plain
    assert plain_peak >= 3 * 4               # all three co-resident
    assert cached_peak <= 0.6 * plain_peak, \
        "shared-stem peak %d pages vs plain %d" \
        % (cached_peak, plain_peak)


def test_prefix_admission_prices_unshared_suffix():
    """can_admit(n, tokens) charges only the unshared suffix, counts
    cache-only pages as evictable headroom, and the pool's reclaimer
    actually frees them mid-admission."""
    workload = shared_workload(2, stem_len=25, max_new=4)
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32), num_blocks=7,
                          prefix_cache="on")
    prompt = workload[0][0]
    slot, _token = engine.prefill(prompt)    # 4 of 6 usable pages
    assert engine.blocks_free == 2
    follower = workload[1][0]
    assert not engine.can_admit(len(follower))          # 4 > 2 free
    assert engine.can_admit(len(follower), follower)    # 3 shared
    # release -> the stem goes cache-only: headroom for ANY prompt
    engine.release_slot(slot)
    assert engine.blocks_free == 3           # stem pages still held
    fresh = list(range(40, 70))              # no shared prefix
    assert engine.can_admit(len(fresh))      # 3 free + 3 reclaimable
    slot2, _token = engine.prefill(fresh)    # reclaimer evicts a leaf
    # eviction is LAZY (deepest LRU leaf first) and only as deep as
    # the deficit: the stem chain lost exactly its last page
    assert len(engine._prefix.match(
        follower, engine._prefix_tag(len(follower)))) == 2
    engine.release_slot(slot2)
    engine.close()


def test_speculative_matches_plain_bitwise():
    """THE speculative gate: draft-then-verify greedy decode is
    BITWISE plain decode in both kv modes — acceptance only changes
    dispatch count, never tokens."""
    workload = spec_workload()
    for kw in ({}, {"kv": "paged", "block_size": 8}):
        engine = build_engine(buckets=(8, 16, 32), **kw)
        plain, _ = run_continuous(engine, workload)
        engine.close()
        engine = build_engine(buckets=(8, 16, 32),
                              speculative="ngram", draft_k=4, **kw)
        assert engine.describe()["speculative"] == "ngram"
        spec, sched = run_continuous(engine, workload)
        assert engine.spec_dispatches >= 1
        assert engine.spec_accepted_total >= 1, \
            "repetitive workload must accept something"
        # fewer dispatches than tokens: speculation actually paid
        assert sched.decode_steps < sum(m for _, m in workload)
        engine.close()
        assert spec == plain, kw


def test_speculative_draft_model_parity():
    """Model-based drafting through the registry: same bitwise gate,
    draft quality only affects speed."""
    from veles_tpu.gen import DRAFT_MODELS, register_draft_model
    workload = spec_workload(3, max_new=8)
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32))
    plain, _ = run_continuous(engine, workload)
    engine.close()
    register_draft_model("tiny-draft", TransformerGenModel(CFG))
    try:
        engine = build_engine(kv="paged", block_size=8,
                              buckets=(8, 16, 32),
                              speculative="tiny-draft", draft_k=3)
        spec, _ = run_continuous(engine, workload)
        assert engine.spec_dispatches >= 1
        engine.close()
    finally:
        DRAFT_MODELS.pop("tiny-draft", None)
    assert spec == plain


def test_speculative_zero_acceptance_worst_case():
    """Adversarial proposer wrong at EVERY position: the stream must
    still be bitwise plain decode (row 0 of the verify program is
    plain decode), at zero accepted drafts."""
    workload = spec_workload(3, max_new=6)
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32))
    plain, _ = run_continuous(engine, workload)
    engine.close()
    # oracle: prefix -> the token greedy decode emits next
    wrong = {}
    for (toks, _max_new), out in zip(workload, plain):
        full = list(toks) + [int(t) for t in out]
        for j in range(len(toks), len(full)):
            wrong[tuple(full[:j])] = (full[j] + 1) % CFG["vocab"]

    class _Adversary(object):
        def propose(self, stream, k):
            bad = wrong.get(tuple(int(t) for t in stream), 0)
            return [bad] * int(k)

    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32), speculative="ngram",
                          draft_k=4)
    engine.proposer = _Adversary()
    spec, _ = run_continuous(engine, workload)
    assert engine.spec_accepted_total == 0
    assert engine.spec_dispatches >= 1
    engine.close()
    assert spec == plain


def test_speculative_preempts_mid_draft_losslessly():
    """Pool exhaustion during a speculative session: the youngest
    stream is preempted (possibly mid-span), requeued with its
    tokens-so-far, and every stream still finishes bitwise identical
    to the uncontended run — deterministically across repeats."""
    workload = spec_workload(6, max_new=12)
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32))
    uncontended, _ = run_continuous(engine, workload)
    engine.close()
    runs = []
    for _ in range(2):
        engine = build_engine(kv="paged", block_size=8,
                              buckets=(8, 16, 32), num_blocks=11,
                              speculative="ngram", draft_k=4)
        tokens, _ = run_continuous(engine, workload)
        assert engine.preemptions_total >= 1
        runs.append((tokens, engine.preemptions_total))
        engine.close()
    assert runs[0] == runs[1]                # deterministic
    assert runs[0][0] == uncontended         # lossless


def test_speculative_zero_steady_state_compiles():
    """warmup() compiles the verify program next to the bucket and
    decode programs; a full speculative session then compiles
    NOTHING (sentinel-gated)."""
    from veles_tpu import prof
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32), speculative="ngram",
                          draft_k=4, prefix_cache="on", warm=False)
    engine.warmup()
    warm = engine.compile_count
    assert warm == len(engine.prefill_buckets) + 2   # decode + verify
    recompiles = prof.ledger.recompiles
    spec, _ = run_continuous(
        engine, spec_workload(4) + shared_workload(4, first=60))
    assert engine.spec_dispatches >= 1
    assert engine.compile_count == warm
    assert prof.ledger.recompiles == recompiles
    engine.close()


def test_prefix_spec_gauges_on_metrics():
    """gen_prefix_hit_rate / gen_spec_accept_rate /
    gen_spec_tokens_per_dispatch register and unregister with the
    scheduler and mirror describe()."""
    from veles_tpu.serve import ServingMetrics
    metrics = ServingMetrics()
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32), prefix_cache="on",
                          speculative="ngram", draft_k=4)
    scheduler = GenerativeScheduler(engine, metrics=metrics,
                                    name="ps")
    futures = [scheduler.submit(toks, max_new) for toks, max_new
               in shared_workload(4) + spec_workload(3, first=60)]
    scheduler.run_until_idle()
    assert all(f.done() for f in futures)
    snap = metrics.snapshot()                # gauges round to 4 places
    assert snap['gen_prefix_hit_rate{model="ps"}'] == pytest.approx(
        engine.prefix_hit_rate(), abs=1e-4)
    assert snap['gen_spec_accept_rate{model="ps"}'] == pytest.approx(
        engine.spec_accept_rate(), abs=1e-4)
    assert snap['gen_spec_tokens_per_dispatch{model="ps"}'] == \
        pytest.approx(engine.spec_tokens_per_dispatch(), abs=1e-4)
    assert engine.spec_tokens_per_dispatch() >= 1.0
    info = engine.describe()
    assert info["prefix_cache"] == "on"
    assert info["speculative"] == "ngram"
    assert info["draft_k"] == 4
    assert info["spec_dispatches"] == engine.spec_dispatches
    assert info["prefix_pages"] >= 1
    assert info["prefix_hits_pages_total"] >= 1
    scheduler.stop(drain=False)
    snap = metrics.snapshot()
    assert 'gen_prefix_hit_rate{model="ps"}' not in snap
    assert 'gen_spec_accept_rate{model="ps"}' not in snap
    engine.close()
    # plain engines don't grow the new gauges
    metrics2 = ServingMetrics()
    engine = build_engine(kv="paged", block_size=8)
    scheduler = GenerativeScheduler(engine, metrics=metrics2,
                                    name="p")
    assert 'gen_prefix_hit_rate{model="p"}' not in \
        metrics2.snapshot()
    scheduler.stop(drain=False)
    engine.close()


def test_vs01_prefix_and_spec_checks():
    """V-S01 learns the PR 19 surface: the mean-mix pool warning
    credits observed page sharing, and a draft model proposing into
    a different vocab is flagged before it silently zeroes
    acceptance."""
    from veles_tpu.analyze.shapes import check_generative
    from veles_tpu.gen import DRAFT_MODELS, register_draft_model
    # refcount-aware pricing: 8 usable pages price below the 9-page
    # observed mix until sharing is credited
    workload = shared_workload(3, stem_len=25, max_new=4)
    engine = build_engine(kv="paged", block_size=8,
                          buckets=(8, 16, 32), num_blocks=9,
                          prefix_cache="on")
    report = check_generative(engine, hbm_bytes=1 << 30)
    assert any("preempts instead of batching" in f.message
               for f in report.findings)    # fresh engine: no credit
    s1, _t = engine.prefill(workload[0][0])
    s2, _t = engine.prefill(workload[1][0])
    report = check_generative(engine, hbm_bytes=1 << 30)
    assert not any("preempts instead of batching" in f.message
                   for f in report.findings), \
        "3 shared stem pages must price the 9-page mix into 8 usable"
    engine.release_slot(s1)
    engine.release_slot(s2)
    engine.close()
    # draft-vocab mismatch: the silent-garbage failure mode
    register_draft_model(
        "bad-vocab", TransformerGenModel(dict(CFG,
                                              vocab=2 * CFG["vocab"])))
    try:
        engine = build_engine(kv="paged", block_size=8,
                              speculative="bad-vocab", draft_k=2,
                              warm=False)
        report = check_generative(engine, hbm_bytes=1 << 30)
        assert any("vocab" in f.message and f.severity == "warning"
                   for f in report.findings)
        engine.close()
    finally:
        DRAFT_MODELS.pop("bad-vocab", None)


# -- the compounding tokens/s gate (prefix + spec acceptance) ---------------

@pytest.mark.slow
def test_speculative_tokens_per_slot_closed_loop():
    """The speculative mode's reason to exist, measured: >= 1.3x
    decode tokens/s/slot with the n-gram proposer on a repetitive
    workload, bitwise-identical streams, zero steady recompiles."""
    import time
    from veles_tpu import prof
    big = {"vocab": 512, "dim": 256, "heads": 4, "layers": 4,
           "mlp_ratio": 4, "seq_len": 512}
    stem = ([5, 9, 13, 7] * 24)[:96]
    workload = [(stem + [200 + i], 96) for i in range(2)]

    def run(spec):
        kw = {"speculative": "ngram", "draft_k": 5} if spec else {}
        engine = GenerativeEngine(
            TransformerGenModel(big), max_slots=2, max_seq=256,
            prefill_buckets=(128,), seed=0, kv="paged",
            block_size=16, **kw).warmup()
        recompiles = prof.ledger.recompiles
        scheduler = GenerativeScheduler(engine)
        futures = [scheduler.submit(toks, max_new)
                   for toks, max_new in workload]
        tic = time.perf_counter()
        scheduler.run_until_idle()
        elapsed = time.perf_counter() - tic
        tokens = [f.result(0) for f in futures]
        assert prof.ledger.recompiles == recompiles
        accept = engine.spec_accept_rate() if spec else 0.0
        engine.close()
        emitted = sum(len(t) for t in tokens)
        return tokens, emitted / elapsed, accept

    plain_tokens, plain_tps, _a = run(False)
    plain_tps = max(plain_tps, run(False)[1])    # best-of-2 per mode
    spec_tokens, spec_tps, accept = run(True)
    spec_tps = max(spec_tps, run(True)[1])
    assert spec_tokens == plain_tokens           # the equivalence gate
    assert accept > 0.5, "repetitive stream must mostly accept"
    assert spec_tps >= 1.3 * plain_tps, \
        "speculative %.1f tok/s vs plain %.1f (%.2fx, accept %.2f)" \
        % (spec_tps, plain_tps, spec_tps / plain_tps, accept)


@pytest.mark.slow
def test_prefix_capacity_closed_loop():
    """The prefix cache's reason to exist, measured: at <= 0.7x the
    KV-ledger bytes the cached pool holds >= 1.5x the concurrent
    shared-prefix sequences of the plain paged engine, with bitwise
    token parity and zero steady recompiles."""
    from veles_tpu import prof
    cfg = dict(TINY, seq_len=128)
    stem = [(i * 11 + 5) % cfg["vocab"] for i in range(57)]
    workload = [(stem + [100 + i], 6) for i in range(18)]

    def run(prefix, num_blocks, max_slots):
        engine = GenerativeEngine(
            TransformerGenModel(cfg), max_slots=max_slots,
            max_seq=96, prefill_buckets=(64,), seed=0, kv="paged",
            block_size=8, num_blocks=num_blocks,
            prefix_cache="on" if prefix else None).warmup()
        recompiles = prof.ledger.recompiles
        scheduler = GenerativeScheduler(engine)
        futures = [scheduler.submit(toks, max_new)
                   for toks, max_new in workload]
        peak = 0
        while scheduler.queue_depth() or scheduler.active_requests():
            if scheduler.step() == 0:
                break
            peak = max(peak, scheduler.active_requests())
        tokens = [f.result(0) for f in futures]
        assert prof.ledger.recompiles == recompiles
        bytes_ = engine.kv_cache_bytes
        engine.close()
        return tokens, peak, bytes_

    # plain: 4 slots x 8 pages resident -> 33-page pool
    plain_tokens, plain_peak, plain_bytes = run(False, 33, 4)
    # cached: 0.7x the pool BYTES, yet room for 12 shared streams
    cached_tokens, cached_peak, cached_bytes = run(True, 23, 12)
    assert cached_bytes <= 0.7 * plain_bytes
    assert cached_tokens == plain_tokens
    assert cached_peak >= 1.5 * plain_peak, \
        "cached held %d concurrent vs plain %d at 0.7x bytes" \
        % (cached_peak, plain_peak)
