"""veles_tpu.gen — continuously-batched generative serving tests.

THE parity gate lives here: tokens generated under continuous batching
must be BITWISE identical to sequential one-request-at-a-time decode
for a seeded mixed-length request set (greedy sampling), on both the
single-device and the mesh-sharded engine — the property that makes
iteration-level admission a pure scheduling optimisation rather than a
numerics change.  The ``-m slow`` closed loop then proves the
scheduling is worth having: ≥1.5x tokens/s over the pad-to-slowest
static batcher with zero steady-state compiles.
"""

import json

import numpy
import pytest

from veles_tpu.config import root
from veles_tpu.gen import (GenerativeEngine, GenerativeScheduler,
                           TransformerGenModel, static_generate)
from veles_tpu.samples.transformer import TINY

CFG = dict(TINY, seq_len=64)


def build_engine(seed=0, mesh=None, max_slots=3, max_seq=48,
                 buckets=(8, 16), warm=True, **kwargs):
    engine = GenerativeEngine(
        TransformerGenModel(CFG), max_slots=max_slots,
        max_seq=max_seq, prefill_buckets=buckets, seed=seed,
        mesh=mesh, **kwargs)
    return engine.warmup() if warm else engine


def mixed_workload(n=10, seed=0, max_prompt=16, max_new_hi=10):
    rng = numpy.random.default_rng(seed)
    return [
        (rng.integers(0, CFG["vocab"],
                      int(rng.integers(1, max_prompt))).tolist(),
         int(rng.integers(1, max_new_hi)))
        for _ in range(n)]


def run_continuous(engine, workload):
    scheduler = GenerativeScheduler(engine)
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    scheduler.run_until_idle()
    return [f.result(0) for f in futures], scheduler


def run_sequential(engine, workload):
    scheduler = GenerativeScheduler(engine)
    out = []
    for toks, max_new in workload:
        future = scheduler.submit(toks, max_new)
        scheduler.run_until_idle()
        out.append(future.result(0))
    return out, scheduler


# -- THE parity gate --------------------------------------------------------

def test_continuous_matches_sequential_bitwise():
    """Continuous batching, one-at-a-time sequential decode AND the
    static pad-to-slowest batcher produce bitwise-identical greedy
    token streams for a seeded mixed-length request set."""
    workload = mixed_workload(10)
    engine = build_engine()
    continuous, sched = run_continuous(engine, workload)
    engine.close()
    # continuous actually batched (mixed lengths overlapped)
    assert sched.batch_fill() > 0.5
    engine = build_engine()
    sequential, _ = run_sequential(engine, workload)
    engine.close()
    assert continuous == sequential
    engine = build_engine()
    static, _steps = static_generate(engine, workload)
    engine.close()
    assert static == sequential
    # greedy budgets honoured exactly (no eos in the TINY vocab run)
    assert [len(t) for t in continuous] == [m for _, m in workload]


def test_continuous_matches_sequential_on_mesh():
    """The same parity on the tensor-parallel engine: params sharded
    column/row over the model axis, KV cache sharded over heads."""
    import jax
    from veles_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    mesh = make_mesh({"model": 2})
    workload = mixed_workload(6, seed=3, max_new_hi=7)
    engine = build_engine(mesh=mesh, max_slots=2)
    assert engine.mesh is not None and engine.describe()["sharded"]
    continuous, _ = run_continuous(engine, workload)
    engine.close()
    engine = build_engine(mesh=mesh, max_slots=2)
    sequential, _ = run_sequential(engine, workload)
    engine.close()
    assert continuous == sequential


def test_mesh_without_model_axis_falls_back_single_device():
    import jax
    from veles_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    engine = build_engine(mesh=make_mesh({"data": 2}), warm=False)
    assert engine.mesh is None
    assert not engine.describe()["sharded"]
    engine.close()


# -- engine: compile discipline, KV ledger, slots ---------------------------

def test_warmup_compiles_everything_then_nothing():
    from veles_tpu import prof
    engine = build_engine(warm=False)
    assert engine.compile_count == 0
    engine.warmup()
    warm = engine.compile_count
    assert warm == len(engine.prefill_buckets) + 1
    recompiles = prof.ledger.recompiles
    workload = mixed_workload(8, seed=1)
    run_continuous(engine, workload)
    assert engine.compile_count == warm
    assert prof.ledger.recompiles == recompiles
    engine.close()


def test_post_warmup_compile_is_flagged():
    """A prompt needing an unwarmed bucket after warmup() IS served,
    but the sentinel flags the steady-state compile — the serve-bucket
    contract."""
    from veles_tpu import prof
    engine = GenerativeEngine(
        TransformerGenModel(CFG), max_slots=2, max_seq=48,
        prefill_buckets=(8,), seed=0)
    engine._decode_executable()
    engine._prefill_executable(8)
    engine._warmed = True
    flagged = len(prof.flagged)
    recompiles = prof.ledger.recompiles
    engine._prefill_executable(4)      # an unwarmed shape
    assert len(prof.flagged) == flagged + 1
    assert prof.ledger.recompiles == recompiles + 1
    engine.close()


def test_kv_cache_rides_the_hbm_ledger():
    """The reserved ``kv`` category goes live: allocation appears in
    hbm_ledger() current+peak and the /metrics prof gauge line, and
    close() releases it."""
    from veles_tpu import prof
    from veles_tpu.memory import Watcher
    before = Watcher.hbm_ledger()["by_category"].get(
        "kv", {"bytes": 0})["bytes"]
    engine = build_engine(warm=False)
    ledger = Watcher.hbm_ledger()["by_category"]["kv"]
    assert ledger["bytes"] == before + engine.kv_cache_bytes
    assert ledger["peak"] >= ledger["bytes"]
    # the exact layout: 2 tensors x L x slots x S x h x dh x itemsize
    assert engine.kv_cache_bytes == (
        2 * CFG["layers"] * 3 * 48 * CFG["heads"]
        * (CFG["dim"] // CFG["heads"]) * 4)
    text = prof.metrics_text()
    assert 'veles_prof_hbm_bytes{category="kv"}' in text
    engine.close()
    after = Watcher.hbm_ledger()["by_category"]["kv"]["bytes"]
    assert after == before
    engine.close()                      # idempotent


def test_slot_admission_and_eviction():
    engine = build_engine(max_slots=2)
    assert engine.free_slots == 2
    slot_a, _ = engine.prefill([1, 2, 3])
    slot_b, _ = engine.prefill([4])
    assert engine.free_slots == 0
    assert engine.occupancy() == 1.0
    with pytest.raises(RuntimeError):
        engine.prefill([5])
    engine.release_slot(slot_a)
    assert engine.free_slots == 1
    with pytest.raises(ValueError):
        engine.release_slot(slot_a)     # double release
    # freed slots are reused lowest-first (deterministic admission)
    slot_c, _ = engine.prefill([6])
    assert slot_c == slot_a
    engine.release_slot(slot_b)
    engine.release_slot(slot_c)
    engine.close()


def test_prompt_validation():
    engine = build_engine(warm=False)
    with pytest.raises(ValueError):
        engine.prefill([])
    with pytest.raises(ValueError):
        engine.bucket_for(17)           # beyond the largest bucket
    with pytest.raises(ValueError):
        engine.prefill(list(range(48)))  # no room to generate
    engine.close()


def test_eos_stops_generation():
    """A model-declared eos token ends the stream early with
    finish_reason "eos" — verified against the no-eos run's prefix."""
    workload = [(list(range(1, 6)), 8)]
    engine = build_engine()
    baseline, _ = run_continuous(engine, workload)
    engine.close()
    assert len(baseline[0]) == 8
    eos = baseline[0][2]                # the third generated token
    engine = build_engine(eos_id=eos)
    scheduler = GenerativeScheduler(engine)
    future = scheduler.submit(workload[0][0], 8)
    scheduler.run_until_idle()
    got = future.result(0)
    engine.close()
    assert got == baseline[0][:3]       # stops AT the eos token


# -- scheduler: queueing, metrics, streaming --------------------------------

def test_scheduler_bounded_queue_sheds():
    from veles_tpu.serve.batcher import QueueFull
    engine = build_engine(warm=False)
    scheduler = GenerativeScheduler(engine, max_queue=2)
    scheduler.submit([1], 2)
    scheduler.submit([2], 2)
    with pytest.raises(QueueFull):
        scheduler.submit([3], 2)
    with pytest.raises(ValueError):
        scheduler.submit([1], 0)        # bad budget
    with pytest.raises(ValueError):
        scheduler.submit([1] * 17, 2)   # prompt beyond buckets
    with pytest.raises(ValueError):
        scheduler.submit([1] * 8, 48)   # prompt + budget > max_seq
    engine.close()


def test_streaming_tokens_arrive_in_order():
    engine = build_engine()
    scheduler = GenerativeScheduler(engine)
    streamed = []
    future = scheduler.submit([1, 2, 3], 5,
                              on_token=streamed.append)
    scheduler.run_until_idle()
    assert future.result(0) == streamed
    assert len(streamed) == 5
    engine.close()


def test_scheduler_gauges_and_ttft_on_metrics():
    from veles_tpu.serve import ServingMetrics
    metrics = ServingMetrics()
    engine = build_engine()
    scheduler = GenerativeScheduler(engine, metrics=metrics,
                                    name="lm")
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in mixed_workload(6, seed=5)]
    scheduler.run_until_idle()
    assert all(f.done() for f in futures)
    snap = metrics.snapshot()
    assert snap['gen_slot_occupancy{model="lm"}'] == 0.0
    assert snap['gen_admitted_total{model="lm"}'] == 6
    assert snap['gen_tokens_total{model="lm"}'] == \
        scheduler.tokens_total
    assert 0.0 < snap['gen_batch_fill{model="lm"}'] <= 1.0
    assert snap['gen_ttft_p99_ms{model="lm"}'] > 0
    text = metrics.render_text()
    assert 'veles_serve_gen_slot_occupancy{model="lm"}' in text
    assert ('veles_serve_gen_ttft_seconds_bucket{model="lm",le='
            in text)
    assert 'veles_serve_gen_ttft_seconds_count{model="lm"}' in text
    # stop() unregisters — a dead scheduler must not haunt /metrics
    scheduler.stop(drain=False)
    assert 'gen_slot_occupancy{model="lm"}' not in metrics.snapshot()
    engine.close()


def test_perf_report_per_token_decode_accounting():
    from veles_tpu import prof
    engine = build_engine()
    run_continuous(engine, mixed_workload(5, seed=7))
    entries = [e for e in prof.ledger.entries("decode")
               if e.name.startswith(engine.prof_name)]
    assert len(entries) == 1
    assert entries[0].items > 0          # tokens accounted
    assert entries[0].items_per_s() > 0
    assert entries[0].flops_per_item() > 0
    row = entries[0].row(None)
    assert row["items"] == entries[0].items
    text = prof.report_text()
    assert "generative programs (per token):" in text
    assert "tok/s" in text
    engine.close()


# -- registry: generative deploys, replica sets, canary ---------------------

def test_registry_generative_deploy_describe_generate():
    from veles_tpu.serve import ModelRegistry, ServingMetrics
    metrics = ServingMetrics()
    registry = ModelRegistry(metrics=metrics)
    engine = build_engine(warm=False)
    model = registry.deploy_generative("lm", engine, version=7)
    try:
        info = registry.describe()["lm"]
        assert info["generative"] is True
        assert info["version"] == 7
        assert info["max_slots"] == 3
        assert info["prefill_buckets"] == [8, 16]
        assert info["kv_cache_bytes"] == engine.kv_cache_bytes
        out = registry.generate("lm", [1, 2, 3], max_new_tokens=4)
        assert len(out) == 4
        # the request/response path refuses generative names loudly
        with pytest.raises(ValueError):
            registry.submit("lm", numpy.ones((1, 4), numpy.float32))
        assert model.engine is engine
    finally:
        registry.stop()
    # stop() closed the engine's KV hold
    from veles_tpu.memory import Watcher
    assert Watcher.hbm_ledger()["by_category"]["kv"]["bytes"] >= 0
    assert not engine._kv_tracked


def test_registry_refuses_kind_mixups():
    from veles_tpu.serve import InferenceEngine, ModelRegistry
    registry = ModelRegistry()
    plain = InferenceEngine({"w": numpy.eye(4, dtype=numpy.float32)},
                            lambda p, x: x @ p["w"], (4,),
                            max_batch_size=4)
    registry.deploy("m", plain)
    gen_engine = build_engine(warm=False)
    with pytest.raises(ValueError):
        registry.deploy_generative("m", gen_engine, warmup=False)
    gen2 = build_engine(warm=False)
    registry.deploy_generative("lm", gen2, warmup=False)
    plain2 = InferenceEngine({"w": numpy.eye(4, dtype=numpy.float32)},
                             lambda p, x: x @ p["w"], (4,),
                             max_batch_size=4)
    with pytest.raises(ValueError):
        registry.deploy("lm", plain2)
    registry.stop()
    gen_engine.close()


def _dense_engine(scale, n=4):
    from veles_tpu.serve import InferenceEngine
    params = {"w": numpy.full((n, 2), scale, numpy.float32)}
    return InferenceEngine(params, lambda p, x: x @ p["w"], (n,),
                           max_batch_size=8)


def test_replica_set_weighted_split_and_describe():
    """The satellite fix: describe() reports replica weights and
    per-replica versions/served counts — a 3:1 canary split is
    assertable without reaching into privates, and smooth WRR makes
    it EXACT over any multiple of the weight total."""
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    registry.deploy("m", _dense_engine(1.0), version="v1")
    registry.deploy_canary("m", _dense_engine(2.0), weight=0.25,
                           version="v2")
    info = registry.describe()["m"]
    assert [r["version"] for r in info["replicas"]] == ["v1", "v2"]
    assert [r["weight"] for r in info["replicas"]] == [0.75, 0.25]
    rows = numpy.ones((1, 4), numpy.float32)
    for _ in range(40):
        registry.infer("m", rows)
    served = {r["version"]: r["served"]
              for r in registry.describe()["m"]["replicas"]}
    assert served == {"v1": 30, "v2": 10}
    # promotion = a plain deploy; describe() drops the replica table
    registry.deploy("m", _dense_engine(2.0), version="v2")
    assert "replicas" not in registry.describe()["m"]
    registry.stop()


def test_replica_set_guardrails():
    from veles_tpu.serve import ModelRegistry, ReplicaSet
    with pytest.raises(ValueError):
        ReplicaSet([])
    with pytest.raises(ValueError):
        ReplicaSet([(_dense_engine(1.0), 0.0, "v1")])
    with pytest.raises(ValueError):
        ReplicaSet([(_dense_engine(1.0, 4), 1, "a"),
                    (_dense_engine(1.0, 5), 1, "b")])  # shape clash
    registry = ModelRegistry()
    registry.deploy("m", _dense_engine(1.0), version="v1")
    with pytest.raises(ValueError):
        registry.deploy_canary("m", _dense_engine(2.0), weight=1.5)
    registry.deploy_canary("m", _dense_engine(2.0), weight=0.5)
    with pytest.raises(ValueError):   # no canary-on-canary stacks
        registry.deploy_canary("m", _dense_engine(3.0), weight=0.1)
    registry.stop()


def test_replica_set_serves_through_batcher():
    """End to end through the batcher: outputs alternate between the
    replicas' distinct weights at equal split — the swap really routes
    traffic, not just describe() rows."""
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    registry.deploy_replica_set(
        "m", [(_dense_engine(1.0), 1, "one"),
              (_dense_engine(2.0), 1, "two")])
    rows = numpy.ones((1, 4), numpy.float32)
    values = {float(registry.infer("m", rows)[0][0])
              for _ in range(4)}
    assert values == {4.0, 8.0}
    registry.stop()


# -- V-S01 preflight --------------------------------------------------------

class _PlanStub(object):
    """A plan-shaped object for check_generative (no device work)."""

    def __init__(self, **kw):
        class _Model(object):
            causal = kw.pop("causal", True)
            seq_limit = kw.pop("seq_limit", 64)
        self.model = _Model()
        self.max_slots = kw.pop("max_slots", 2)
        self.max_seq = kw.pop("max_seq", 48)
        self.prefill_buckets = kw.pop("prefill_buckets", (8, 16))
        self.kv_cache_bytes = kw.pop("kv_cache_bytes", 1024)
        assert not kw


def test_vs01_catalog_and_rules():
    from veles_tpu.analyze.findings import rule_catalog
    catalog = rule_catalog()
    assert "V-S01" in catalog
    assert catalog["V-S01"][0] == "error"


def test_vs01_plan_checks():
    from veles_tpu.analyze.shapes import check_generative
    assert not check_generative(_PlanStub(),
                                hbm_bytes=1 << 30).has_errors
    assert check_generative(_PlanStub(causal=False)).has_errors
    assert check_generative(_PlanStub(max_slots=0)).has_errors
    assert check_generative(_PlanStub(prefill_buckets=())).has_errors
    assert check_generative(
        _PlanStub(prefill_buckets=(64,))).has_errors   # > max_seq
    assert check_generative(
        _PlanStub(max_seq=128)).has_errors   # > positional table
    # footprint: error over 90% of HBM, warning over half
    big = _PlanStub(kv_cache_bytes=1000)
    assert check_generative(big, hbm_bytes=1000).has_errors
    warn = check_generative(_PlanStub(kv_cache_bytes=600),
                            hbm_bytes=1000)
    assert not warn.has_errors
    assert any(f.severity == "warning" for f in warn.findings)
    # CPU (no HBM table entry) degrades to plan sanity only
    assert not check_generative(_PlanStub(),
                                hbm_bytes=None).has_errors


def test_vs01_gates_deploy_in_fail_mode():
    from veles_tpu.analyze import PreflightError
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    prior = root.common.serve.get("preflight", "warn")
    root.common.serve.preflight = "fail"
    try:
        with pytest.raises(PreflightError):
            registry.preflight_generative(_PlanStub(causal=False))
        assert registry.preflight_generative(_PlanStub()) is not None
        root.common.serve.preflight = "off"
        assert registry.preflight_generative(
            _PlanStub(causal=False)) is None
    finally:
        root.common.serve.preflight = prior
        registry.stop()


# -- wire + server ----------------------------------------------------------

def test_wire_decode_gen_request():
    from veles_tpu.serve.wire import decode_gen_request
    tokens, max_new, stream = decode_gen_request(
        {"tokens": [1, 2, 3], "max_new_tokens": 4, "stream": True})
    assert tokens.dtype == numpy.int32
    assert tokens.tolist() == [1, 2, 3]
    assert (max_new, stream) == (4, True)
    tokens, max_new, stream = decode_gen_request({"tokens": [0]})
    assert (max_new, stream) == (16, False)
    for bad in (
            [],                                   # not a dict
            {},                                   # no tokens
            {"tokens": []},                       # empty
            {"tokens": "abc"},                    # not a list
            {"tokens": [1, -2]},                  # negative
            {"tokens": [1, True]},                # bool masquerade
            {"tokens": [1], "max_new_tokens": 0},
            {"tokens": [1], "max_new_tokens": "9"},
            {"tokens": [1], "stream": "yes"},
    ):
        with pytest.raises(ValueError):
            decode_gen_request(bad)


def test_server_generate_routes():
    from veles_tpu.serve import ModelRegistry, ServingServer
    registry = ModelRegistry()
    registry.deploy_generative("lm", build_engine(warm=False),
                               version=1)
    server = ServingServer(registry=registry)
    try:
        status, payload = server.handle_generate(
            "/generate/lm", json.dumps(
                {"tokens": [1, 2], "max_new_tokens": 3}).encode())
        assert status == 200
        assert len(payload["tokens"]) == 3
        assert payload["model"] == "lm" and payload["version"] == 1
        status, payload = server.handle_generate(
            "/generate/nope", b"{}")
        assert status == 404
        status, payload = server.handle_generate(
            "/generate/lm", b'{"tokens": []}')
        assert status == 400
        status, payload = server.handle_generate(
            "/generate/lm", b"not json")
        assert status == 400
        # default-model route without a generative "default" -> 404
        status, _ = server.handle_generate("/generate", b"{}")
        assert status == 404
        # streamed variant frames every token then the final document
        lines = list(server.stream_generate(
            "/generate/lm", json.dumps(
                {"tokens": [5], "max_new_tokens": 2,
                 "stream": True}).encode()))
        assert lines[0][0] == 200
        events = [json.loads(line) for _s, line in lines]
        assert [e["token"] for e in events[:-1]] == \
            events[-1]["tokens"]
        assert events[-1]["done"] is True
    finally:
        server.stop()


def test_server_predict_route_rejects_generative():
    from veles_tpu.serve import ModelRegistry, ServingServer
    registry = ModelRegistry()
    registry.deploy_generative("lm", build_engine(warm=False))
    server = ServingServer(registry=registry)
    try:
        status, payload = server.handle_generate(
            "/service/lm", b"{}")
        assert status == 404              # wrong prefix entirely
        status, payload = server.handle_predict(
            "/service/lm", json.dumps({"input": [[0.0] * 4]}).encode())
        assert status in (400, 500)       # not a batcher model
    finally:
        server.stop()


# -- the throughput gate ----------------------------------------------------

@pytest.mark.slow
def test_throughput_continuous_vs_static_closed_loop():
    """≥1.5x tokens/s over the pad-to-max static batcher on CPU JAX
    for a closed-loop mixed-length load, with zero steady-state
    compiles after warmup on BOTH engines (recompile sentinel quiet).
    Identical compiled programs and bitwise-identical tokens — the
    speedup is pure iteration-level admission."""
    import time

    from veles_tpu import prof

    cfg = dict(TINY, seq_len=128)
    slots, max_seq, buckets = 4, 96, (8,)
    rng = numpy.random.default_rng(0)
    workload = [
        (rng.integers(0, cfg["vocab"],
                      int(rng.integers(1, 9))).tolist(),
         64 if i % slots == 0 else int(rng.integers(2, 9)))
        for i in range(48)]

    def build():
        return GenerativeEngine(
            TransformerGenModel(cfg), max_slots=slots,
            max_seq=max_seq, prefill_buckets=buckets,
            seed=0).warmup()

    engine = build()
    recompiles0 = prof.ledger.recompiles
    warm = engine.compile_count
    scheduler = GenerativeScheduler(engine)
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    tic = time.perf_counter()
    scheduler.run_until_idle()
    cont_sec = time.perf_counter() - tic
    continuous = [f.result(0) for f in futures]
    cont_tokens = scheduler.tokens_total
    assert engine.compile_count == warm
    fill = scheduler.batch_fill()
    engine.close()

    engine = build()
    tic = time.perf_counter()
    static, _steps = static_generate(engine, workload)
    static_sec = time.perf_counter() - tic
    static_tokens = sum(len(r) for r in static)
    assert engine.compile_count == warm
    engine.close()
    assert prof.ledger.recompiles == recompiles0

    assert static == continuous          # same tokens, bit for bit
    assert cont_tokens == static_tokens
    cont_tps = cont_tokens / cont_sec
    static_tps = static_tokens / static_sec
    assert fill > 0.75
    assert cont_tps >= 1.5 * static_tps, \
        "continuous %.0f tok/s vs static %.0f tok/s (%.2fx, " \
        "fill %.2f)" % (cont_tps, static_tps, cont_tps / static_tps,
                        fill)


# -- review regressions -----------------------------------------------------

def test_metrics_histogram_families_single_type_header():
    """Two generative models' TTFT histograms share ONE HELP/TYPE
    header with both label variants grouped under it — a duplicate
    TYPE line for the same family is a Prometheus parse error that
    kills the whole scrape."""
    from veles_tpu.metrics import LatencyHistogram
    from veles_tpu.serve import ServingMetrics
    metrics = ServingMetrics()
    a, b = LatencyHistogram(), LatencyHistogram()
    a.record(0.01)
    b.record(0.02)
    metrics.register_histogram("gen_ttft_seconds", a, "ttft",
                               labels={"model": "a"})
    metrics.register_histogram("gen_ttft_seconds", b, "ttft",
                               labels={"model": "b"})
    text = metrics.render_text()
    assert text.count(
        "# TYPE veles_serve_gen_ttft_seconds histogram") == 1
    assert 'gen_ttft_seconds_bucket{model="a",le=' in text
    assert 'gen_ttft_seconds_bucket{model="b",le=' in text
    assert 'gen_ttft_seconds_count{model="a"}' in text
    assert 'gen_ttft_seconds_count{model="b"}' in text


def test_failed_prefill_fails_that_request_only():
    """A prefill blow-up fails the popped request's future instead of
    orphaning it; co-admitted requests still get their attempt."""
    engine = build_engine()
    scheduler = GenerativeScheduler(engine)
    boom = {"armed": True}
    real_prefill = engine.prefill

    def flaky_prefill(tokens):
        if boom.pop("armed", False):
            raise RuntimeError("device fault")
        return real_prefill(tokens)

    engine.prefill = flaky_prefill
    doomed = scheduler.submit([1, 2], 3)
    survivor = scheduler.submit([3, 4], 3)
    scheduler.run_until_idle()
    with pytest.raises(RuntimeError):
        doomed.result(0)
    assert survivor.result(0) and len(survivor.result(0)) == 3
    engine.close()


def test_stop_fails_active_futures_loudly():
    """stop(drain=False) must resolve slot-occupying requests with an
    exception — a silent pending future blocks its client for the
    full request timeout against a closed engine."""
    engine = build_engine()
    scheduler = GenerativeScheduler(engine)
    future = scheduler.submit([1, 2, 3], 40)
    scheduler.step()                      # admitted into a slot
    assert scheduler.active_requests() == 1
    scheduler.stop(drain=False)
    with pytest.raises(RuntimeError):
        future.result(0)
    assert engine.free_slots == engine.max_slots
    engine.close()


def test_registry_undeploy_single_model():
    from veles_tpu.serve import ModelRegistry
    registry = ModelRegistry()
    registry.deploy("m", _dense_engine(1.0), version="v1")
    registry.deploy_generative("lm", build_engine(warm=False))
    registry.undeploy("m")
    assert registry.names() == ["lm"]
    with pytest.raises(KeyError):
        registry.undeploy("m")
    gen_engine = registry.get("lm").engine
    registry.undeploy("lm", drain=False)
    assert registry.names() == []
    assert not gen_engine._kv_tracked    # KV hold released
    registry.stop()
