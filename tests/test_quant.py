"""veles_tpu.quant + ops.qgemm — int8 serving tests.

THE gates live here: interpret-mode parity of the Pallas int8 kernel
against the dense-jnp dequant reference (bitwise where the grid is a
single block, strict-tolerance across remainder tiles / shuffled
scales / every fused activation), quantized-vs-float top-1 agreement
≥99% on the mnist sample logits with a ≤0.35× params-category HBM
ledger line, the PR 8/PR 11 continuous==sequential parity gates
re-run green under ``quantize="int8"`` in BOTH kv modes with zero
steady-state compiles, and the ``-m slow`` ≥1.2× tokens/s floor over
the same-run bf16 engine on CPU JAX.
"""

import gc

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prof, quant
from veles_tpu.config import root
from veles_tpu.memory import Watcher
from veles_tpu.ops import qgemm


@pytest.fixture
def interpret():
    saved = root.common.engine.get("interpret", False)
    root.common.engine.interpret = True
    yield
    root.common.engine.interpret = saved


def params_category_bytes():
    # flush pending finalizers first: a buffer leaked by an earlier
    # test releasing between two snapshots would skew the delta
    gc.collect()
    return Watcher.hbm_ledger()["by_category"].get(
        "params", {}).get("bytes", 0)


# ---------------------------------------------------------------------------
# the quantization walk
# ---------------------------------------------------------------------------

class TestQuantizeWalk:
    def test_quantize_array_per_channel_shapes_and_error_bound(self):
        rng = numpy.random.default_rng(0)
        w = rng.standard_normal((96, 40)).astype(numpy.float32)
        qw = quant.quantize_array(w, axes=(0,))
        assert qw["q"].dtype == numpy.int8
        assert qw["scale"].dtype == numpy.float32
        assert qw["scale"].shape == (1, 40)       # keepdims broadcast
        deq = quant.dequantize_array(qw)
        # abs-max symmetric: per-channel error <= scale/2 (one rint)
        err = numpy.abs(deq - w)
        assert numpy.all(err <= qw["scale"] * 0.5 + 1e-7)
        # the extreme element per channel is exactly representable
        assert numpy.allclose(numpy.abs(deq).max(0),
                              numpy.abs(w).max(0), rtol=1e-2)

    def test_zero_channel_guard(self):
        w = numpy.zeros((8, 4), numpy.float32)
        qw = quant.quantize_array(w)
        assert numpy.all(qw["q"] == 0)
        assert numpy.all(qw["scale"] == 1.0)      # never 0/0

    def test_stage_walk_quantizes_2d_w_only(self):
        rng = numpy.random.default_rng(1)
        stages = [
            {"w": rng.standard_normal((8, 4)).astype(numpy.float32),
             "b": numpy.ones(4, numpy.float32)},
            {"w": rng.standard_normal((3, 3, 2, 5)).astype(
                numpy.float32)},                  # conv kernel: float
            {"seed": numpy.int32(7)},             # dropout: untouched
        ]
        out = quant.quantize_stage_params(stages)
        assert quant.is_quantized_leaf(out[0]["w"])
        assert out[0]["b"].dtype == numpy.float32     # bias kept f32
        assert not quant.is_quantized_leaf(out[1]["w"])
        assert out[1]["w"].dtype == numpy.float32
        assert out[2]["seed"] == 7
        assert quant.tree_is_quantized(out)
        assert not quant.tree_is_quantized(stages)

    def test_stage_walk_transposed_axis(self):
        rng = numpy.random.default_rng(2)
        w = rng.standard_normal((10, 6)).astype(numpy.float32)
        # transposed storage (neurons, fan-in): canonicalized to
        # (fan-in, neurons) at deploy — one scale per output neuron,
        # and the serving kernel consumes q exactly as stored
        out = quant.quantize_stage_params(
            [{"w": w}], axes_list=[{"w": (1,)}])
        assert out[0]["w"]["q"].shape == (6, 10)
        assert out[0]["w"]["scale"].shape == (1, 10)
        assert numpy.allclose(
            quant.dequantize_array(out[0]["w"]), w.T, atol=1e-1)

    def test_nothing_quantizable_is_typed_error(self):
        with pytest.raises(quant.QuantizationError):
            quant.quantize_stage_params([{"b": numpy.ones(
                4, numpy.float32)}])

    def test_tree_nbytes_prices_actual_dtypes(self):
        w = numpy.ones((100, 10), numpy.float32)
        fbytes = quant.tree_nbytes([{"w": w}])
        qbytes = quant.tree_nbytes(quant.quantize_stage_params(
            [{"w": w}]))
        assert fbytes == 4000
        assert qbytes == 1000 + 40        # int8 payload + f32 scales


# ---------------------------------------------------------------------------
# the Pallas kernel vs the dense-jnp dequant reference (interpret mode)
# ---------------------------------------------------------------------------

ACTIVATIONS = (None, "tanh", "sigmoid", "relu", "strict_relu", "gelu")


class TestQGemmParity:
    def test_single_block_bitwise(self, interpret):
        """Grid = ONE block (aligned shapes, tiles cover everything):
        the kernel's dot/scale/bias/activation sequence must be
        BITWISE identical to the dense reference's."""
        rng = numpy.random.default_rng(3)
        a = jnp.asarray(rng.standard_normal((32, 128)),
                        jnp.float32)
        w = rng.standard_normal((128, 128)).astype(numpy.float32)
        qw = quant.quantize_array(w, axes=(0,))
        q = jnp.asarray(qw["q"])
        scale = jnp.asarray(qw["scale"].reshape(-1))
        bias = jnp.asarray(rng.standard_normal(128), jnp.float32)
        for act in ACTIVATIONS:
            ref = qgemm._qmatmul_jnp(a, q, scale, bias, act)
            got = qgemm.qmatmul(a, q, scale, bias, act,
                                use_pallas=True,
                                tiles=(32, 128, 128))
            assert numpy.asarray(ref).tobytes() == \
                numpy.asarray(got).tobytes(), act

    def test_remainder_tiles_and_shuffled_scales(self, interpret):
        """M/N remainder tiles + a K split + permuted (non-monotone)
        scales: strict tolerance vs the dense reference (CPU XLA dots
        of different blocking are not ulp-identical), exact output
        slicing, and the padded columns never leak."""
        rng = numpy.random.default_rng(4)
        a = jnp.asarray(rng.standard_normal((100, 300)), jnp.float32)
        w = rng.standard_normal((300, 136)).astype(numpy.float32)
        qw = quant.quantize_array(w, axes=(0,))
        perm = rng.permutation(136)
        q = jnp.asarray(qw["q"][:, perm])
        scale = jnp.asarray(qw["scale"].reshape(-1)[perm])
        bias = jnp.asarray(rng.standard_normal(136), jnp.float32)
        for act in ACTIVATIONS:
            ref = qgemm._qmatmul_jnp(a, q, scale, bias, act)
            got = qgemm.qmatmul(a, q, scale, bias, act,
                                use_pallas=True,
                                tiles=(32, 128, 128))
            assert got.shape == (100, 136)
            assert numpy.allclose(numpy.asarray(got),
                                  numpy.asarray(ref),
                                  atol=2e-5), act

    def test_no_bias_path(self, interpret):
        rng = numpy.random.default_rng(5)
        a = jnp.asarray(rng.standard_normal((16, 128)), jnp.float32)
        qw = quant.quantize_array(
            rng.standard_normal((128, 128)).astype(numpy.float32))
        q, scale = jnp.asarray(qw["q"]), \
            jnp.asarray(qw["scale"].reshape(-1))
        ref = qgemm._qmatmul_jnp(a, q, scale, None, "relu")
        got = qgemm.qmatmul(a, q, scale, None, "relu",
                            use_pallas=True, tiles=(16, 128, 128))
        assert numpy.asarray(ref).tobytes() == \
            numpy.asarray(got).tobytes()

    def test_dispatch_consults_gemm_int8_rating(self, tmp_path,
                                                monkeypatch):
        """The autotune DB's ``gemm_int8`` row decides the backend
        and supplies the measured tiles, like ``ops.gemm.matmul``'s
        own rows (on-TPU resolution forced for the assertion)."""
        import json

        from veles_tpu.ops import benchmark
        db = {"FakeTPU v9": {"gemm_int8": {"float32": {
            "backend": "pallas", "tiles": [64, 128, 128],
            "sec_per_flop": 1e-12}}}}
        path = tmp_path / "device_infos.json"
        path.write_text(json.dumps(db))
        monkeypatch.setattr(benchmark, "DEVICE_INFOS_JSON", str(path))

        class _Dev:
            device_kind = "FakeTPU v9"

        monkeypatch.setattr(jax, "devices", lambda *a: [_Dev()])
        import veles_tpu.ops as ops_pkg
        monkeypatch.setattr(ops_pkg, "on_tpu", lambda: True)
        benchmark.gemm_choice.cache_clear()
        try:
            use, tiles = qgemm._dispatch(None, None, numpy.float32,
                                         (64, 128, 128))
            assert use is True
            assert tiles == (64, 128, 128)
            # explicit False still wins over the DB
            use, _ = qgemm._dispatch(False, None, numpy.float32)
            assert use is False
        finally:
            benchmark.gemm_choice.cache_clear()

    def test_autotune_gemm_int8_sweep_writes_rating(self, tmp_path):
        """The sweep persists a consultable ``gemm_int8`` row on the
        attached backend (CPU: the Pallas candidates fail to build and
        the dense baseline wins — a recorded verdict, not a crash)."""
        from veles_tpu.backends import DeviceInfo
        from veles_tpu.ops.benchmark import autotune_gemm_int8
        path = str(tmp_path / "db.json")
        info = autotune_gemm_int8(shapes=((64, 64, 64),),
                                  dtypes=("float32",), runs=1,
                                  db_path=path)
        entry = info.ratings["gemm_int8"]["float32"]
        assert entry["backend"] in ("xla", "pallas")
        assert entry["sec_per_flop"] > 0
        reloaded = DeviceInfo.load_db(path)
        assert any("gemm_int8" in i.ratings
                   for i in reloaded.values())


# ---------------------------------------------------------------------------
# the calibration drift gate
# ---------------------------------------------------------------------------

class TestCalibrationGate:
    def test_transformer_drift_error_names_worst_layer(self):
        """Over-budget drift raises typed, NAMING the block weight
        whose solo quantization drifts most — asserted against an
        independent per-key re-measurement (the layernorm'd residual
        stack renormalizes outliers, so the worst key is a property
        of the network, not of where a test plants a spike)."""
        from veles_tpu.gen import TransformerGenModel
        from veles_tpu.samples.transformer import TINY
        model = TransformerGenModel(dict(TINY, seq_len=32))
        params = model.init_params(seed=0)
        tokens = [1, 2, 3, 4]
        with pytest.raises(quant.QuantizationError) as err:
            quant.quantize_gen_params(model, params,
                                      calibration_tokens=tokens)
        assert err.value.drift > quant.DRIFT_TOL
        ref = model.calibration_logits(params, tokens)
        per_key = {
            key: quant.relative_drift(
                ref, model.calibration_logits(
                    quant.quantize_transformer_params(params,
                                                      only=key),
                    tokens))
            for key in quant.core.TRANSFORMER_BLOCK_AXES}
        worst = max(per_key, key=per_key.get)
        assert err.value.layer == "blocks.%s" % worst
        assert err.value.drift == per_key[worst]

    def test_explicit_tol_admits_noisy_model(self):
        from veles_tpu.gen import TransformerGenModel
        from veles_tpu.samples.transformer import TINY
        model = TransformerGenModel(dict(TINY, seq_len=32))
        params = model.init_params(seed=0)
        qparams = quant.quantize_gen_params(
            model, params, calibration_tokens=[1, 2, 3], tol=0.5)
        assert quant.tree_is_quantized(qparams)

    def test_serve_engine_blame_names_stage(self):
        """Int8's real failure mode, caught and blamed: big in-channel
        weights that CANCEL on the calibration inputs (rows ±1e5,
        inputs with equal first two features), so the float output is
        carried by small weights the shared abs-max scale rounds to
        zero — drift ≈ 1 and the typed error names THAT stage."""
        from veles_tpu.serve.engine import InferenceEngine
        from veles_tpu.znicz.all2all import All2All
        rng = numpy.random.default_rng(6)
        w0 = numpy.eye(8, dtype=numpy.float32)
        w1 = rng.standard_normal((8, 4)).astype(numpy.float32)
        w1[0, :] = 1e5
        w1[1, :] = -1e5

        def apply_fn(params, x):
            h = All2All.pure(params[0], x, activation="tanh")
            return All2All.pure(params[1], h)

        calibration = rng.standard_normal((4, 8)).astype(
            numpy.float32)
        calibration[:, 1] = calibration[:, 0]   # the ±1e5 rows cancel
        engine = InferenceEngine([{"w": w0}, {"w": w1}], apply_fn,
                                 sample_shape=(8,), max_batch_size=4)
        try:
            with pytest.raises(quant.QuantizationError) as err:
                engine.quantize_int8(calibration=calibration)
            assert err.value.layer == "stage[1].w"
            assert err.value.drift > 0.5
        finally:
            engine.close()


# ---------------------------------------------------------------------------
# serve engine: mnist top-1 agreement + params-category ledger
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mnist_wf():
    """The mnist sample (784→100→10, synthetic stand-in data), one
    epoch on the numpy device — the acceptance gate's model."""
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.samples.mnist import create_workflow
    wf = create_workflow(device=NumpyDevice(), max_epochs=1,
                         minibatch_size=100)
    wf.run()
    return wf


class TestServeQuantized:
    def test_mnist_top1_agreement_and_params_ledger(self, mnist_wf):
        """THE acceptance gate: int8 deploy of the mnist sample —
        params-category ledger ≤0.35× the float line, top-1 agreement
        ≥99% on the sample's logits, zero steady-state compiles."""
        from veles_tpu.serve.engine import InferenceEngine
        mnist_wf.loader.original_data.map_read()
        rows = numpy.array(mnist_wf.loader.original_data.mem[:512],
                           numpy.float32)

        base = params_category_bytes()
        fengine = InferenceEngine.from_workflow(mnist_wf,
                                               max_batch_size=64)
        float_bytes = params_category_bytes() - base
        assert float_bytes == fengine.params_nbytes > 0
        ref = fengine.infer(rows)

        qengine = InferenceEngine.from_workflow(mnist_wf,
                                                max_batch_size=64)
        qengine.quantize_int8(calibration=rows[:64])
        int8_bytes = qengine.params_nbytes
        assert int8_bytes <= 0.35 * float_bytes
        assert params_category_bytes() - base == \
            float_bytes + int8_bytes
        qengine.warmup()
        warm = qengine.compile_count
        recompiles = prof.ledger.recompiles
        got = qengine.infer(rows)
        assert qengine.compile_count == warm
        assert prof.ledger.recompiles == recompiles
        agreement = (ref.argmax(1) == got.argmax(1)).mean()
        assert agreement >= 0.99
        # close releases exactly this engine's ledger hold
        qengine.close()
        qengine.close()                      # idempotent
        assert params_category_bytes() - base == float_bytes
        fengine.close()
        assert params_category_bytes() == base

    def test_registry_deploy_int8_describe_and_undeploy(self,
                                                        mnist_wf):
        from veles_tpu.serve.engine import InferenceEngine
        from veles_tpu.serve.registry import ModelRegistry
        mnist_wf.loader.original_data.map_read()
        rows = numpy.array(mnist_wf.loader.original_data.mem[:32],
                           numpy.float32)
        base = params_category_bytes()
        registry = ModelRegistry()
        engine = InferenceEngine.from_workflow(mnist_wf,
                                               max_batch_size=16)
        registry.deploy("mnist", engine, quantize="int8",
                        calibration=rows)
        info = registry.describe()["mnist"]
        assert info["quantize"] == "int8"
        assert info["params_bytes"] == engine.params_nbytes
        out = registry.infer("mnist", rows)
        assert out.shape == (32, 10)
        registry.undeploy("mnist")
        assert params_category_bytes() == base

    def test_registry_quantize_knob_and_guards(self, mnist_wf):
        from veles_tpu.serve.engine import InferenceEngine
        from veles_tpu.serve.registry import ModelRegistry
        registry = ModelRegistry()
        saved = root.common.serve.get("quantize", "off")
        try:
            root.common.serve.quantize = "int8"
            engine = InferenceEngine.from_workflow(mnist_wf,
                                                   max_batch_size=8)
            registry.deploy("knob", engine)
            assert engine.quantized == "int8"
            registry.undeploy("knob")
            with pytest.raises(ValueError):
                registry._resolve_quantize("int4")
        finally:
            root.common.serve.quantize = saved
            registry.stop()

    def test_quantize_after_warmup_refused(self, mnist_wf):
        from veles_tpu.serve.engine import InferenceEngine
        engine = InferenceEngine.from_workflow(mnist_wf,
                                               max_batch_size=8)
        try:
            engine.warmup()
            with pytest.raises(RuntimeError):
                engine.quantize_int8()
        finally:
            engine.close()

    def test_live_engine_refused(self, mnist_wf):
        from veles_tpu.serve.engine import InferenceEngine
        engine = InferenceEngine.from_forwards(
            mnist_wf.forwards, live=True)
        try:
            with pytest.raises(ValueError):
                engine.quantize_int8()
        finally:
            engine.close()

    def test_replica_set_quantize_refused(self):
        from veles_tpu.serve.engine import InferenceEngine
        from veles_tpu.serve.registry import ModelRegistry
        w = numpy.eye(4, dtype=numpy.float32)
        engines = [InferenceEngine([{"w": w}],
                                   lambda p, x: x @ p[0]["w"],
                                   sample_shape=(4,),
                                   max_batch_size=4)
                   for _ in range(2)]
        registry = ModelRegistry()
        try:
            with pytest.raises(ValueError):
                registry.deploy_replica_set(
                    "rs", [(engines[0], 1), (engines[1], 1)],
                    quantize="int8")
        finally:
            for engine in engines:
                engine.close()

    def test_all2all_pure_routes_through_gemm_matmul(self):
        """The satellite fix: the header's 'one fused call into
        ops.gemm.matmul' promise now holds on the pure path (the
        stitched/fused/serving forward), byte-identically off-TPU."""
        from unittest import mock

        import veles_tpu.ops.gemm as gemm
        from veles_tpu.znicz.all2all import All2All
        from veles_tpu.znicz.fused import _ACT
        rng = numpy.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal((6, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((8, 5)), jnp.float32)
        b = jnp.asarray(rng.standard_normal(5), jnp.float32)
        with mock.patch.object(gemm, "matmul",
                               wraps=gemm.matmul) as spy:
            out = All2All.pure({"w": w, "b": b}, x,
                               activation="tanh")
            assert spy.call_count == 1
        ref = _ACT["tanh"](
            jnp.dot(x, w, preferred_element_type=jnp.float32) + b)
        assert numpy.asarray(out).tobytes() == \
            numpy.asarray(ref.astype(x.dtype)).tobytes()

    def test_all2all_pure_quantized_leaf(self):
        from veles_tpu.znicz.all2all import All2All
        rng = numpy.random.default_rng(8)
        x = jnp.asarray(rng.standard_normal((6, 8)), jnp.float32)
        w = rng.standard_normal((8, 5)).astype(numpy.float32)
        qw = quant.quantize_array(w, axes=(0,))
        out = All2All.pure({"w": qw}, x, activation="strict_relu")
        ref = numpy.maximum(
            numpy.asarray(x) @ quant.dequantize_array(qw), 0.0)
        assert numpy.allclose(numpy.asarray(out), ref, atol=1e-5)
        # transposed storage: the deploy walk canonicalizes to
        # (fan-in, out) — no per-call int8 transpose in the hot path
        qt = quant.quantize_stage_params(
            [{"w": w.T}], axes_list=[{"w": (1,)}])[0]["w"]
        assert qt["q"].shape == w.shape           # canonical already
        out_t = All2All.pure({"w": qt}, x, activation="strict_relu",
                             transposed=True)
        assert numpy.asarray(out_t).tobytes() == \
            numpy.asarray(out).tobytes()


# ---------------------------------------------------------------------------
# generative engine: the PR 8/PR 11 parity gates under int8
# ---------------------------------------------------------------------------

from veles_tpu.gen import (GenerativeEngine,  # noqa: E402
                           GenerativeScheduler, TransformerGenModel)
from veles_tpu.samples.transformer import TINY  # noqa: E402

CFG = dict(TINY, seq_len=64)


def build_gen(quantize=False, **kwargs):
    engine = GenerativeEngine(
        TransformerGenModel(CFG), max_slots=3, max_seq=48,
        prefill_buckets=(8, 16), seed=0, **kwargs)
    if quantize:
        engine.quantize_int8()
    return engine.warmup()


def gen_workload(n=8, seed=0):
    rng = numpy.random.default_rng(seed)
    return [
        (rng.integers(0, CFG["vocab"],
                      int(rng.integers(1, 16))).tolist(),
         int(rng.integers(1, 10)))
        for _ in range(n)]


def run_continuous(engine, workload):
    scheduler = GenerativeScheduler(engine)
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    scheduler.run_until_idle()
    return [f.result(0) for f in futures]


def run_sequential(engine, workload):
    scheduler = GenerativeScheduler(engine)
    out = []
    for toks, max_new in workload:
        future = scheduler.submit(toks, max_new)
        scheduler.run_until_idle()
        out.append(future.result(0))
    return out


class TestGenQuantized:
    def test_parity_gates_int8_both_kv_modes(self):
        """THE PR 8/PR 11 gates under ``quantize="int8"``: continuous
        == sequential bitwise on the contiguous engine, paged ==
        contiguous bitwise, zero steady-state compiles throughout."""
        workload = gen_workload()
        recompiles = prof.ledger.recompiles
        engine = build_gen(quantize=True)
        warm = engine.compile_count
        continuous = run_continuous(engine, workload)
        assert engine.compile_count == warm
        engine.close()
        engine = build_gen(quantize=True)
        sequential = run_sequential(engine, workload)
        engine.close()
        assert continuous == sequential
        paged = build_gen(quantize=True, kv="paged", block_size=8,
                          num_blocks=3 * 6 + 1, prefill_chunk=8)
        paged_out = run_continuous(paged, workload)
        paged.close()
        assert paged_out == continuous
        assert prof.ledger.recompiles == recompiles
        # budgets honoured exactly (no eos in the TINY vocab run)
        assert [len(t) for t in continuous] == \
            [m for _, m in workload]

    def test_quantize_describe_pricing_and_gauge(self):
        kv_before = Watcher.hbm_ledger()["by_category"].get(
            "kv", {}).get("bytes", 0)
        fengine = build_gen()
        float_bytes = fengine.params_nbytes
        fengine.prefill(list(range(1, 6)))
        float_hbm = fengine.hbm_per_request_bytes()
        fengine.close()
        engine = build_gen(quantize=True)
        info = engine.describe()
        assert info["quantize"] == "int8"
        assert info["params_bytes"] == engine.params_nbytes \
            < float_bytes
        engine.prefill(list(range(1, 6)))
        # the SLO-visible capacity metric reflects the int8 shrink
        assert engine.hbm_per_request_bytes() < float_hbm
        assert engine.hbm_per_request_bytes() > 0
        engine.close()
        assert Watcher.hbm_ledger()["by_category"]["kv"]["bytes"] \
            == kv_before

    def test_registry_deploy_generative_int8(self):
        from veles_tpu.serve.registry import ModelRegistry
        registry = ModelRegistry()
        engine = GenerativeEngine(
            TransformerGenModel(CFG), max_slots=2, max_seq=32,
            prefill_buckets=(8,), seed=0)
        registry.deploy_generative("lm", engine, quantize="int8",
                                   calibration=None)
        try:
            assert engine.quantized == "int8"
            info = registry.describe()["lm"]
            assert info["quantize"] == "int8"
            tokens = registry.generate("lm", [1, 2, 3],
                                       max_new_tokens=4)
            assert len(tokens) == 4
        finally:
            registry.stop()

    def test_quantize_after_warmup_refused(self):
        engine = build_gen()
        try:
            with pytest.raises(RuntimeError):
                engine.quantize_int8()
        finally:
            engine.close()

    def test_ledger_entries_carry_int8_peak_dtype(self, monkeypatch):
        engine = build_gen(quantize=True)
        try:
            entries = list(engine._prof_entries.values())
            assert entries
            assert all(e.peak_dtype == "int8" for e in entries)
            # the denominator swap: on a v5e the int8 peak is 2x bf16
            # (sys.modules lookup: the prof PACKAGE shadows the
            # ledger module attribute with the PerfLedger instance)
            import sys
            monkeypatch.setattr(
                sys.modules["veles_tpu.prof.ledger"], "device_kind",
                lambda: "TPU v5 lite")
            entry = entries[0]
            entry.dispatches, entry.dispatch_ns = 1, int(1e9)
            bf16_peak = 197e12
            assert entry._peak_for(bf16_peak) == 394e12
            assert entry.row(bf16_peak)["peak_dtype"] == "int8"
        finally:
            engine.close()

    def test_peak_int8_table(self):
        from veles_tpu.backends import peak_int8_ops
        assert peak_int8_ops("TPU v5 lite") == 394e12
        assert peak_int8_ops("TPU v4") == 275e12
        assert peak_int8_ops("cpu") is None


@pytest.mark.slow
def test_int8_tokens_per_sec_floor_vs_bf16():
    """The acceptance floor: ≥1.2× tokens/s over the same-run bf16
    engine on CPU JAX.  The win is the honest one int8 serving is FOR:
    at these dims the decode step is weight-STREAMING bound (≈100 MB
    of f32 block weights per step vs 25 MB int8), so moving a quarter
    of the bytes beats the native-bf16 matmul path — measured a
    stable ~1.26× on a single-core avx512_bf16 box (boxes where XLA
    must emulate bf16 clear the floor by far more)."""
    import time

    cfg = {"vocab": 64, "dim": 1024, "heads": 8, "layers": 2,
           "mlp_ratio": 4, "seq_len": 64}
    rng = numpy.random.default_rng(0)
    workload = [(rng.integers(0, cfg["vocab"], 8).tolist(), 24)
                for _ in range(8)]

    def tokens_per_sec(model, quantize=False):
        engine = GenerativeEngine(model, max_slots=4, max_seq=48,
                                  prefill_buckets=(16,), seed=0)
        if quantize:
            engine.quantize_int8()
        engine.warmup()
        best = 0.0
        for _ in range(3):       # best-of-3: shrug off CI scheduler
            scheduler = GenerativeScheduler(engine)   # noise
            futures = [scheduler.submit(toks, max_new)
                       for toks, max_new in workload]
            tic = time.perf_counter()
            scheduler.run_until_idle()
            sec = time.perf_counter() - tic
            assert all(f.done() for f in futures)
            best = max(best, scheduler.tokens_total / sec)
        engine.close()
        return best

    bf16 = tokens_per_sec(
        TransformerGenModel(cfg, compute_dtype=jnp.bfloat16))
    int8 = tokens_per_sec(TransformerGenModel(cfg), quantize=True)
    assert int8 >= 1.2 * bf16, (int8, bf16)
