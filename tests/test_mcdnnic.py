"""mcdnnic topology strings — the documented second way to set
topology (``manualrst_veles_workflow_parameters.rst:583-600``)."""

import numpy
import pytest

from veles_tpu.znicz.mcdnnic import parse_topology


def test_parse_documented_example():
    shape, layers = parse_topology(
        "12x256x256-32C4-MP2-64C4-MP3-32N-4N",
        {"->": {"weights_filling": "uniform", "weights_stddev": 0.05},
         "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}})
    assert shape == (256, 256, 12)
    kinds = [ly["type"] for ly in layers]
    assert kinds == ["conv_tanh", "max_pooling", "conv_tanh",
                     "max_pooling", "all2all_tanh", "softmax"]
    assert layers[0]["->"]["n_kernels"] == 32
    assert layers[0]["->"]["kx"] == 4
    assert layers[0]["->"]["weights_stddev"] == 0.05     # merged
    assert layers[0]["<-"]["learning_rate"] == 0.03
    # pooling receives the shared params too (the docs: "same for
    # each layer"); its own structural keys still come from the token
    assert layers[1]["->"]["kx"] == 2 and layers[1]["->"]["ky"] == 2
    assert layers[1]["->"]["sliding"] == (2, 2)
    assert layers[1]["->"]["weights_stddev"] == 0.05
    assert layers[1]["<-"]["learning_rate"] == 0.03
    assert layers[3]["->"]["sliding"] == (3, 3)
    assert layers[4]["->"]["output_sample_shape"] == 32
    assert layers[5]["->"]["output_sample_shape"] == 4
    assert layers[5]["<-"]["gradient_moment"] == 0.9


def test_parse_rejects_bad_strings():
    with pytest.raises(ValueError, match="output layer"):
        parse_topology("32C4-MP2")           # no trailing N layer
    with pytest.raises(ValueError, match="unknown mcdnnic token"):
        parse_topology("32C4-XX-4N")
    with pytest.raises(ValueError, match="empty"):
        parse_topology("")


def test_parse_structure_beats_shared_parameters():
    """A shared '->' key colliding with a structural key parsed from
    the string must NOT override the string."""
    _s, layers = parse_topology(
        "32C4-64C4-4N", {"->": {"n_kernels": 16,
                                "output_sample_shape": 99}})
    assert layers[0]["->"]["n_kernels"] == 32
    assert layers[1]["->"]["n_kernels"] == 64
    assert layers[2]["->"]["output_sample_shape"] == 4


def test_standard_workflow_from_mcdnnic_topology():
    """A workflow built from the string trains end to end; giving both
    layers and a topology is rejected."""
    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyImages(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(6)
            n = 300
            self.original_data.mem = rng.standard_normal(
                (n, 8, 8, 3)).astype(numpy.float32)
            self.original_labels = [int(v) for v in
                                    rng.integers(0, 5, n)]
            self.class_lengths[:] = [0, n // 3, n - n // 3]

    prng.seed_all(13)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyImages(w, minibatch_size=50),
        mcdnnic_topology="3x8x8-8C3-MP2-16N-5N",
        mcdnnic_parameters={"<-": {"learning_rate": 0.05,
                                   "gradient_moment": 0.9}},
        decision_config={"max_epochs": 2})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice())
    assert [type(u).MAPPING for u in wf.forwards] == \
        ["conv_tanh", "max_pooling", "all2all_tanh", "softmax"]
    wf.run()
    assert numpy.isfinite(float(wf.decision.best_n_err_pt))

    with pytest.raises(ValueError, match="not both"):
        StandardWorkflow(
            None,
            loader_factory=lambda w: TinyImages(w, minibatch_size=50),
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 5}}],
            mcdnnic_topology="8C3-5N")
