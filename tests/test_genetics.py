"""Genetics + ensemble meta-workflow tests (SURVEY §2.6): GA core
convergence, Tuneable config scanning, optimizer modes (in-process and
job-layer distributed), ensemble train/test aggregation."""

import threading

import numpy
import pytest

from veles_tpu.config import Config
from veles_tpu.ensemble import EnsembleModelManager, EnsembleTestManager
from veles_tpu.genetics import (
    Choice, GeneSpec, GeneticsOptimizer, Population, Range,
    decode_genome, fitness_from_results, scan_tuneables)


def _cfg(tree):
    cfg = Config("test_root")
    cfg.update(tree)
    return cfg


class TestPopulation:
    def test_converges_on_sphere(self):
        """GA maximizes -(x-3)² - (y+1)² → optimum (3, -1)."""
        specs = [GeneSpec(-10, 10), GeneSpec(-10, 10)]
        pop = Population(specs, size=24, mutation_rate=0.2)
        for _ in range(30):
            for c in pop.pending:
                x, y = c.genes
                c.fitness = -((x - 3) ** 2 + (y + 1) ** 2)
            pop.evolve()
        best = pop.best
        assert best.fitness > -0.5
        assert abs(best.genes[0] - 3) < 1.0
        assert abs(best.genes[1] + 1) < 1.0

    @pytest.mark.parametrize("crossover", ["uniform", "one_point",
                                           "two_point", "arithmetic"])
    def test_crossover_kinds_respect_bounds(self, crossover):
        specs = [GeneSpec(0, 1), GeneSpec(5, 10, is_int=True),
                 GeneSpec(-2, 2)]
        pop = Population(specs, size=8, crossover=crossover)
        for c in pop.pending:
            c.fitness = float(numpy.sum(c.genes))
        pop.evolve()
        for c in pop.chromosomes:
            for spec, g in zip(specs, c.genes):
                assert spec.min <= g <= spec.max
            assert c.genes[1] == round(c.genes[1])   # int gene stays int

    def test_tournament_selection(self):
        specs = [GeneSpec(0, 1)]
        pop = Population(specs, size=6, selection="tournament")
        for c in pop.pending:
            c.fitness = float(c.genes[0])
        pop.evolve()
        assert pop.generation == 1

    def test_elitism_preserves_best(self):
        specs = [GeneSpec(0, 100)]
        pop = Population(specs, size=10, mutation_rate=1.0)
        for c in pop.pending:
            c.fitness = float(c.genes[0])
        best_before = pop.best.genes[0]
        pop.evolve()
        assert any(c.genes[0] == best_before for c in pop.chromosomes)


class TestTuneables:
    def test_scan_and_decode(self):
        cfg = _cfg({
            "lr": Range(0.01, 0.001, 0.1),
            "layers": {"hidden": Range(100, 10, 500)},
            "act": Choice("tanh", "relu", "sigmoid"),
            "fixed": 42,
        })
        tuneables = scan_tuneables(cfg)
        paths = [p for p, _ in tuneables]
        assert paths == ["act", "layers.hidden", "lr"]
        values = decode_genome(tuneables, [0.0, 250.4, 0.05])
        assert values["act"] == "tanh"
        assert values["layers.hidden"] == 250
        assert abs(values["lr"] - 0.05) < 1e-12

    def test_int_range_detection(self):
        assert Range(10, 1, 100).is_int
        assert not Range(0.1, 0.0, 1.0).is_int
        assert not Range(10, 1, 100.0).is_int

    def test_fitness_from_results(self):
        assert fitness_from_results({"fitness": 3.5}) == 3.5
        assert fitness_from_results(
            {"best_validation_error_pt": 2.0}) == -2.0
        assert fitness_from_results({"accuracy": 0.9}) == 0.9
        assert fitness_from_results({"x": 1.0}, fitness_key="x") == 1.0
        with pytest.raises(ValueError):
            fitness_from_results({"note": "text"})


class TestGeneticsOptimizer:
    def test_in_process_optimization(self):
        cfg = _cfg({"x": Range(0.0, -5.0, 5.0),
                    "y": Range(0.0, -5.0, 5.0)})

        def evaluate(overrides):
            return -((overrides["x"] - 2) ** 2 +
                     (overrides["y"] + 2) ** 2)

        opt = GeneticsOptimizer(population_size=16, generations=15,
                                config=cfg, evaluate=evaluate)
        best = opt.run()
        assert best.fitness > -1.0
        assert abs(best.config_overrides["x"] - 2) < 1.5
        assert opt.evaluations >= 16

    def test_requires_tuneables(self):
        with pytest.raises(ValueError, match="no Tuneable"):
            GeneticsOptimizer(config=_cfg({"a": 1}))

    def test_result_file(self, tmp_path):
        cfg = _cfg({"x": Range(0.5, 0.0, 1.0)})
        path = str(tmp_path / "ga.json")
        opt = GeneticsOptimizer(
            population_size=4, generations=2, config=cfg,
            evaluate=lambda o: o["x"], result_file=path)
        best = opt.run()
        import json
        payload = json.load(open(path))
        assert payload["fitness"] == best.fitness
        assert "x" in payload["overrides"]

    def test_distributed_over_job_layer(self):
        """GA chromosomes as slave jobs through the real ZMQ job layer
        (parity: optimization_workflow.py:186 + server/client FSM)."""
        from veles_tpu.parallel.jobs import JobClient, JobServer
        cfg = _cfg({"x": Range(0.0, -4.0, 4.0)})
        opt = GeneticsOptimizer(population_size=6, generations=3,
                                config=cfg, evaluate=None)
        server = JobServer(opt).start()
        port = server.port

        class GAWorker:
            def __init__(self):
                self.jobs = 0

            def checksum(self):
                return opt.checksum()

            def do_job(self, job, callback):
                self.jobs += 1
                x = job["overrides"]["x"]
                callback({"fitness": -(x - 1.0) ** 2})

        workers = [GAWorker() for _ in range(2)]
        threads = []
        clients = []
        for worker in workers:
            client = JobClient(worker, server.endpoint)
            client.handshake()
            clients.append(client)
            t = threading.Thread(target=client.run)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=60)
        for client in clients:
            client.close()
        server.stop()
        assert opt.best is not None
        assert opt.best.fitness <= 0.0
        assert sum(w.jobs for w in workers) >= 6
        assert opt.population.generation >= 2


QUAD_WORKFLOW = '''
"""Toy workflow: one unit computing (x-2)^2 from config (GA target)."""
from veles_tpu.config import root
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


class Quad(Unit):
    def initialize(self, **kwargs):
        pass

    def run(self):
        self.err = (float(root.quad.x) - 2.0) ** 2

    def get_metric_values(self):
        return {"err": self.err}


def create_workflow(launcher=None):
    wf = Workflow(launcher=launcher)
    unit = Quad(wf)
    unit.link_from(wf.start_point)
    wf.end_point.link_from(unit)
    return wf
'''


def test_subprocess_evaluation(tmp_path):
    """Child `python -m veles_tpu` per chromosome with --result-file
    read-back (ref optimization_workflow.py:268 _exec)."""
    wf_path = tmp_path / "quad_workflow.py"
    wf_path.write_text(QUAD_WORKFLOW)
    cfg = _cfg({"quad": {"x": Range(0.0, -4.0, 4.0)}})
    opt = GeneticsOptimizer(
        population_size=3, generations=2, config=cfg,
        workflow_spec=str(wf_path), extra_args=("-d", "numpy"))
    best = opt.run()
    assert best.fitness > -4.0          # fitness = -err
    assert opt.evaluations >= 3
    assert "quad.x" in best.config_overrides


class TestEnsemble:
    def test_train_and_test_in_process(self, tmp_path):
        trained = []

        def train(overrides):
            trained.append(overrides)
            return {"error": 1.0 / (1 + overrides["common.ensemble.index"])}

        mgr = EnsembleModelManager(
            size=4, train_ratio=0.8, evaluate=train,
            result_file=str(tmp_path / "ens.json"))
        listing = mgr.run()
        assert len(listing["models"]) == 4
        assert len({o["common.engine.seed"] for o in trained}) == 4
        assert all(o["common.ensemble.train_ratio"] == 0.8
                   for o in trained)

        def test_member(overrides):
            return {"n_err": 10 + overrides["common.ensemble.index"]}

        tester = EnsembleTestManager(input_data=listing,
                                     evaluate=test_member)
        payload = tester.run()
        assert payload["aggregate"]["n_err"] == 10 + (0 + 1 + 2 + 3) / 4

    def test_loader_train_ratio_from_config(self):
        """Loaders pick up root.common.ensemble.train_ratio (the manager
        seam)."""
        import numpy as np
        from veles_tpu.config import root
        from veles_tpu.backends import NumpyDevice
        from veles_tpu.dummy import DummyWorkflow
        from veles_tpu.loader.fullbatch import FullBatchLoader

        class ArrayLoader(FullBatchLoader):
            def load_data(self):
                self.original_data.mem = np.zeros((100, 4), np.float32)
                self.original_labels = [0] * 100
                self.class_lengths[:] = (0, 0, 100)

        root.common.ensemble.train_ratio = 0.5
        try:
            wf = DummyWorkflow()
            loader = ArrayLoader(wf, minibatch_size=10)
            loader.initialize(NumpyDevice())
            assert loader.train_ratio == 0.5
            # effective train size halved
            assert loader._effective_class_end_offsets[2] - \
                loader.class_end_offsets[1] == 50
        finally:
            root.common.ensemble.train_ratio = 1.0

    def test_validation(self):
        with pytest.raises(ValueError):
            EnsembleModelManager(size=0)
        with pytest.raises(ValueError):
            EnsembleModelManager(size=2, train_ratio=1.5)
        with pytest.raises(ValueError):
            EnsembleTestManager()


class TestGeneticExampleSample:
    def test_module_markers_and_in_process_fitness(self):
        """The reference's GeneticExample pattern: Range markers at
        module level, fitness via the IResultProvider contract."""
        import importlib

        from veles_tpu.config import root
        from veles_tpu.genetics import tune
        import veles_tpu.samples.genetic_example as ge
        importlib.reload(ge)
        assert isinstance(root.test.x, Range)
        names = [t[0] for t in tune.scan_tuneables(root.test)]
        assert set(names) == {"x", "y"}

        # in-process evaluation through the workflow contract
        root.test.x, root.test.y = 0.33, 0.27     # the exact optimum
        try:
            wf = ge.TestWorkflow()
            from veles_tpu.dummy import DummyLauncher
            wf.launcher = DummyLauncher()
            wf.initialize()
            wf.run()
            results = wf.gather_results()
            assert results["EvaluationFitness"] == pytest.approx(0.0)
        finally:
            root.test.x = Range(0.0, -1.0, 1.0)
            root.test.y = Range(0.0, -1.0, 1.0)

    def test_markers_never_clobber_child_overrides(self):
        """In a GA child the CLI override lands BEFORE the module
        import; re-importing must keep the chromosome's value."""
        import importlib

        from veles_tpu.config import root
        import veles_tpu.samples.genetic_example as ge
        root.test.x = 0.4242
        try:
            importlib.reload(ge)
            assert float(root.test.x) == 0.4242     # not clobbered
            assert isinstance(root.test.y, Range)   # re-planted
        finally:
            root.test.x = Range(0.0, -1.0, 1.0)
