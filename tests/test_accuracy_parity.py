"""STRICT accuracy-parity gates vs the reference's published results
(``manualrst_veles_algorithms.rst:31,50,69``; tabulated in BASELINE.md):

- MNIST MnistSimple MLP: validation error ≤ 1.48 %
- MNIST autoencoder: validation RMSE ≤ 0.5478
- CIFAR-10 convnet: validation error ≤ 17.21 %

These run ONLY when the real datasets are present — this image is
egress-less, so the operator must place them under
``root.common.dirs.datasets`` (default ``~/.veles_tpu/datasets``;
override via the config tree or the VELES_DATASETS env var, which
``samples.datasets`` honors everywhere):

    <datasets>/mnist/{train,t10k}-{images-idx3,labels-idx1}-ubyte[.gz]
    <datasets>/cifar-10-batches-bin/{data_batch_1..5,test_batch}.bin

When the files are absent the tests SKIP (never silently pass on the
synthetic stand-ins — those have their own, tighter bars in
test_samples.py).
"""

import pytest

from veles_tpu.samples.datasets import (
    cifar10_available, mnist_available)

needs_mnist = pytest.mark.skipif(
    not mnist_available(),
    reason="real MNIST IDX files not present under "
           "root.common.dirs.datasets/mnist")
needs_cifar = pytest.mark.skipif(
    not cifar10_available(),
    reason="real CIFAR-10 binary batches not present under "
           "root.common.dirs.datasets/cifar-10-batches-bin")


@needs_mnist
def test_mnist_mlp_parity_1_48pct():
    from veles_tpu import prng
    from veles_tpu.samples import mnist
    prng.seed_all(1234)
    wf = mnist.create_workflow(max_epochs=25, minibatch_size=100)
    wf.run()
    err = wf.gather_results()["best_validation_error_pt"]
    assert err <= 1.48, \
        "MNIST parity gate failed: %.2f%% > 1.48%%" % err


@needs_mnist
def test_mnist_ae_parity_rmse_0_5478():
    from veles_tpu import prng
    from veles_tpu.samples import mnist_ae
    prng.seed_all(1234)
    wf = mnist_ae.create_workflow(max_epochs=15, minibatch_size=100)
    wf.run()
    # decision.best_mse IS the RMSE (logged/snapshotted as "rmse",
    # decision.py:173-182)
    rmse = float(wf.decision.best_mse)
    assert rmse <= 0.5478, \
        "MNIST-AE parity gate failed: rmse %.4f > 0.5478" % rmse


@needs_cifar
def test_cifar_convnet_parity_17_21pct():
    from veles_tpu import prng
    from veles_tpu.samples import cifar10
    prng.seed_all(1234)
    wf = cifar10.create_workflow(max_epochs=40, minibatch_size=100)
    wf.run()
    err = wf.decision.best_n_err_pt
    assert err <= 17.21, \
        "CIFAR-10 parity gate failed: %.2f%% > 17.21%%" % err
