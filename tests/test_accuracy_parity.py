"""STRICT accuracy-parity gates vs the reference's published results
(``manualrst_veles_algorithms.rst:31,50,69``; tabulated in BASELINE.md):

- MNIST MnistSimple MLP: validation error ≤ 1.48 %
- MNIST autoencoder: validation RMSE ≤ 0.5478
- CIFAR-10 convnet: validation error ≤ 17.21 %

These run ONLY when the real datasets are present — this image is
egress-less, so the operator must place them under
``root.common.dirs.datasets`` (default ``~/.veles_tpu/datasets``;
override via the config tree or the VELES_DATASETS env var, which
``samples.datasets`` honors everywhere):

    <datasets>/mnist/{train,t10k}-{images-idx3,labels-idx1}-ubyte[.gz]
    <datasets>/cifar-10-batches-bin/{data_batch_1..5,test_batch}.bin

When the files are absent the tests SKIP (never silently pass on the
synthetic stand-ins — those have their own, tighter bars in
test_samples.py).

The training itself runs in a SUBPROCESS with the session's original
JAX platform restored: conftest.py pins this pytest process to the
virtual CPU mesh, but a 25-epoch full-MNIST run belongs on the real
accelerator the gates target.
"""

import json
import os
import subprocess
import sys

import pytest

import conftest
from veles_tpu.samples.datasets import (
    cifar10_available, mnist_available, stl10_available)

needs_mnist = pytest.mark.skipif(
    not mnist_available(),
    reason="real MNIST IDX files not present under "
           "root.common.dirs.datasets/mnist")
needs_cifar = pytest.mark.skipif(
    not cifar10_available(),
    reason="real CIFAR-10 binary batches not present under "
           "root.common.dirs.datasets/cifar-10-batches-bin")

#: per-gate wall-clock cap; operators on slow backends can raise it
TIMEOUT = float(os.environ.get("VELES_PARITY_TIMEOUT_SEC", "3600"))

_RUNNER = """
import json, sys
from veles_tpu import prng
from veles_tpu.samples import {module}
prng.seed_all(1234)
wf = {module}.create_workflow(max_epochs={epochs}, minibatch_size=100)
wf.run()
print("PARITY_RESULT " + json.dumps({{
    "err_pt": float(getattr(wf.decision, "best_n_err_pt", -1.0)),
    "rmse": float(getattr(wf.decision, "best_mse", -1.0)),
}}))
"""


def _train(module, epochs):
    """Run a sample's full training in a subprocess on the session's
    original (accelerator) platform; returns the decision metrics."""
    env = dict(os.environ)
    if conftest.ORIG_JAX_PLATFORMS is None:
        env.pop("JAX_PLATFORMS", None)
    else:
        env["JAX_PLATFORMS"] = conftest.ORIG_JAX_PLATFORMS
    env["XLA_FLAGS"] = conftest.ORIG_XLA_FLAGS
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                         "")
    proc = subprocess.run(
        [sys.executable, "-c",
         _RUNNER.format(module=module, epochs=epochs)],
        capture_output=True, text=True, timeout=TIMEOUT, env=env,
        cwd=repo_root)
    for line in reversed((proc.stdout or "").splitlines()):
        if line.startswith("PARITY_RESULT "):
            return json.loads(line[len("PARITY_RESULT "):])
    raise AssertionError(
        "parity training run produced no result (rc=%d):\n%s" % (
            proc.returncode, (proc.stderr or "")[-2000:]))


@needs_mnist
def test_mnist_mlp_parity_1_48pct():
    err = _train("mnist", epochs=25)["err_pt"]
    assert 0.0 <= err <= 1.48, \
        "MNIST parity gate failed: %.2f%% > 1.48%%" % err


@needs_mnist
def test_mnist_ae_parity_rmse_0_5478():
    # decision.best_mse IS the RMSE (logged/snapshotted as "rmse",
    # decision.py:173-182)
    rmse = _train("mnist_ae", epochs=15)["rmse"]
    assert 0.0 <= rmse <= 0.5478, \
        "MNIST-AE parity gate failed: rmse %.4f > 0.5478" % rmse


@needs_cifar
def test_cifar_convnet_parity_17_21pct():
    err = _train("cifar10", epochs=40)["err_pt"]
    assert 0.0 <= err <= 17.21, \
        "CIFAR-10 parity gate failed: %.2f%% > 17.21%%" % err


needs_stl10 = pytest.mark.skipif(
    not stl10_available(),
    reason="real STL-10 binaries not present under "
           "root.common.dirs.datasets/stl10_binary")


@needs_stl10
def test_stl10_convnet_parity_35_10pct():
    # ref manualrst_veles_algorithms.rst:51: STL-10 validation 35.10 %
    err = _train("stl10", epochs=40)["err_pt"]
    assert 0.0 <= err <= 35.10, \
        "STL-10 parity gate failed: %.2f%% > 35.10%%" % err
