"""veles_tpu.prof — the performance ledger.

Cost accounting (per-segment flops/bytes from the compiled program +
dispatch clocks → perf_report), the HBM residency ledger, the
recompile sentinel (zero steady-state recompiles on a stitched epoch
and on warmed serve buckets; a deliberately shape-unstable segment
flags EXACTLY one retrace), the heartbeat watchdog, Prometheus
histogram exposition, and the cluster merge (one clock-aligned
Perfetto timeline + per-slave report from a scripted master–slave
session)."""

import json
import logging
import pickle
import threading
import time

import numpy
import pytest

from veles_tpu import prof, trace
from veles_tpu.config import root


@pytest.fixture
def live_trace():
    """Enable the GLOBAL recorder directly; restores the stock
    disabled state (same contract as tests/test_trace.py)."""
    rec = trace.recorder
    saved = (rec.enabled, rec.path, rec.role)
    rec.clear()
    rec.enabled = True
    yield trace
    rec.enabled, rec.path, rec.role = saved
    rec.clear()


@pytest.fixture(autouse=True)
def _clean_sentinel():
    """Each test sees an empty flagged-event list and the default
    sentinel mode."""
    prof.sentinel.reset()
    saved = root.common.engine.get("recompile_sentinel", "warn")
    yield
    root.common.engine.recompile_sentinel = saved
    prof.sentinel.reset()


def _build_stitched_workflow(minibatch_size=32, max_epochs=2):
    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class BlobLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(42)
            n = 200
            labels = numpy.tile(numpy.arange(10), n // 10)
            centers = rng.standard_normal((10, 16)) * 3.0
            self.original_data.mem = (
                centers[labels]
                + rng.standard_normal((n, 16)) * 0.7
            ).astype(numpy.float32)
            self.original_labels = [int(x) for x in labels]
            self.class_lengths[:] = [0, 50, 150]

    prng.seed_all(5)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 10},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}}],
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": 10 ** 6})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice())
    return wf


# -- cost accounting --------------------------------------------------------

def test_cost_of_compiled_program():
    import jax
    compiled = jax.jit(lambda a, b: a @ b).lower(
        numpy.ones((64, 64), numpy.float32),
        numpy.ones((64, 64), numpy.float32)).compile()
    cost = prof.cost_of(compiled)
    assert cost["flops"] > 0
    assert cost["bytes_accessed"] > 0
    assert cost["arg_bytes"] == 2 * 64 * 64 * 4
    assert cost["out_bytes"] == 64 * 64 * 4


def test_stitched_epoch_cost_accounting_and_zero_recompiles():
    """The acceptance run (and the recompile-sentinel gate): a
    stitched epoch registers every segment with non-zero flops/bytes,
    accumulates dispatch wall-time, and steady state never
    recompiles."""
    wf = _build_stitched_workflow()
    # the ledger is process-wide and entries are keyed by segment
    # name (other tests build the same blob workflow), so every
    # assertion below is per-object or a delta around THIS run
    recompiles_before = prof.ledger.recompiles
    flops_before = prof.ledger.flops_dispatched
    wf.run()
    segments = [u.stitch_segment
                for u in wf.units_in_dependency_order()
                if getattr(u, "stitch_segment", None) is not None]
    entries = {s.prof_entry.name: s.prof_entry for s in segments}
    assert entries, "the blob workflow must stitch"
    for segment in segments:
        assert segment._compiled is not None, segment
        assert segment.recompiles == 0, segment
    for entry in entries.values():
        assert entry.flops > 0, entry.name
        assert entry.bytes_accessed > 0, entry.name
        assert entry.dispatches > 0, entry.name
        assert entry.dispatch_ns > 0, entry.name
        assert entry.compiles >= 1, entry.name
        assert entry.achieved_flops() > 0, entry.name
    assert prof.ledger.recompiles == recompiles_before
    assert prof.ledger.flops_dispatched > flops_before
    assert prof.flagged == []
    # the report shows flops / bytes / wall / achieved-FLOP/s per
    # segment (CPU: no peak entry, so the MFU column honestly dashes)
    report = wf.perf_report()
    assert "performance ledger" in report
    assert "stitched segments" in report
    for name in entries:
        assert name[:36] in report
    assert "steady-state recompile(s)" in report
    assert "no peak table entry" in report
    summary = prof.summary()
    row_names = {r["name"] for r in summary["entries"]}
    assert set(entries) <= row_names
    assert summary["totals"]["flops_dispatched"] > 0


def test_mfu_reported_when_peak_entry_exists():
    """MFU = achieved/peak when the device kind has a peak-table
    entry; the summary row carries it."""
    entry = prof.LedgerEntry("segment", "fake")
    entry.cost = {"flops": 1e9, "bytes_accessed": 1.0,
                  "arg_bytes": 0, "out_bytes": 0, "temp_bytes": 0}
    entry.compiles = 1
    entry.dispatches = 10
    entry.dispatch_ns = int(1e8)        # 0.1 s for 10 dispatches
    peak = prof.peak_flops("TPU v5 lite")
    assert peak == 197e12
    mfu = entry.mfu(peak)
    assert mfu == pytest.approx(1e10 / 0.1 / peak)
    assert entry.row(peak)["mfu"] == pytest.approx(mfu, abs=1e-6)
    assert entry.row(None)["mfu"] is None      # CPU fallback


def test_hbm_ledger_categories_and_vector_tags():
    from veles_tpu.memory import Watcher
    wf = _build_stitched_workflow(max_epochs=1)
    # force the lazy uploads this test attributes (run() would)
    wf.loader.minibatch_data.devmem
    wf.forwards[0].weights.devmem
    ledger = Watcher.hbm_ledger(top=10 ** 6)
    # weights/bias upload as params, the resident dataset + shuffled
    # indices as dataset, minibatch buffers as staging.  Assert over
    # the live per-Vector registry, which is self-consistent — the
    # aggregate counters are process-wide and other tests may
    # Watcher.reset() them under still-live buffers.
    live_by_cat = {}
    for row in ledger["top_vectors"]:
        live_by_cat[row["category"]] = \
            live_by_cat.get(row["category"], 0) + row["nbytes"]
    for cat in ("params", "dataset", "staging"):
        assert cat in ledger["by_category"], cat
        assert live_by_cat.get(cat, 0) > 0, cat
    # per-Vector attribution of THIS workflow's buffers
    live = Watcher._vectors
    assert live[id(wf.forwards[0].weights)][3] == "params"
    assert live[id(wf.loader.original_data)][3] == "dataset"
    assert live[id(wf.loader.minibatch_data)][3] == "staging"
    assert ledger["peak_bytes"] >= 0
    assert ledger["top_vectors"], "per-Vector detail must be present"
    # this workflow's resident blob dataset appears with its tag
    assert {"shape": [200, 16], "dtype": "float32",
            "nbytes": 200 * 16 * 4, "category": "dataset"} \
        in ledger["top_vectors"]
    # the tag itself survives pickling (snapshots keep attribution)
    vec = wf.forwards[0].weights
    assert vec.category == "params"
    assert pickle.loads(pickle.dumps(vec)).category == "params"


# -- the recompile sentinel -------------------------------------------------

def _one_stage_segment(scalar_state):
    """A minimal directly-constructed segment whose per-call scalar
    comes from ``scalar_state['k']`` — flipping its python TYPE
    between calls is exactly the silent retrace the sentinel exists
    to catch."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.memory import Vector
    from veles_tpu.stitch import StitchSegment, StitchStage

    class StubUnit(object):
        name = "stub"

        def attach_stitch_segment(self, segment):
            pass

        def run(self):
            raise AssertionError("eager fallback must not fire here")

    device = CPUDevice()
    vx = Vector(numpy.ones((4, 4), numpy.float32)).initialize(device)
    vy = Vector(numpy.zeros((4, 4), numpy.float32)).initialize(device)
    unit = StubUnit()
    stage = StitchStage(
        unit, lambda t: {"y": t["x"] * t["k"]},
        consumes={"x": vx}, produces={"y": vy},
        scalars=lambda: {"k": scalar_state["k"]})
    return StitchSegment([unit], [stage]), vy


def test_shape_unstable_segment_flags_exactly_one_retrace(live_trace):
    """The deliberately-unstable unit: one scalar type flip = exactly
    one flagged retrace event (trace instant + WARNING + ledger
    count), and the dispatch still completes correctly."""
    state = {"k": 2}
    segment, vy = _one_stage_segment(state)
    segment.execute()
    assert segment.prof_entry.compiles == 1
    assert segment.recompiles == 0
    segment.execute()                    # same signature: no retrace
    assert segment.recompiles == 0
    state["k"] = 2.5                     # int -> float: signature drift
    segment.execute()
    assert segment.recompiles == 1
    assert len(prof.flagged) == 1
    assert "segment:stub" in prof.flagged[0]["site"]
    assert "int" in prof.flagged[0]["detail"] \
        and "float" in prof.flagged[0]["detail"]
    assert trace.recorder.count("prof", "recompile") == 1
    numpy.testing.assert_allclose(numpy.asarray(vy.devmem),
                                  numpy.full((4, 4), 2.5))
    # steady again at the new signature: still exactly one event
    segment.execute()
    assert segment.recompiles == 1
    assert len(prof.flagged) == 1
    # ALTERNATING back to a signature seen before swaps the cached
    # executable — no recompile, no new flag (the jit-cache behavior
    # the AOT path replaced), and the math stays right
    state["k"] = 3
    segment.execute()
    assert segment.recompiles == 1
    assert len(prof.flagged) == 1
    assert segment.prof_entry.compiles == 2
    numpy.testing.assert_allclose(numpy.asarray(vy.devmem),
                                  numpy.full((4, 4), 3.0))


def test_sentinel_strict_mode_raises_preflight_error():
    from veles_tpu.analyze import PreflightError
    root.common.engine.recompile_sentinel = "strict"
    state = {"k": 1}
    segment, _vy = _one_stage_segment(state)
    segment.execute()
    state["k"] = 1.5
    with pytest.raises(PreflightError) as err:
        segment.execute()
    assert "V-P01" in str(err.value)
    assert len(prof.flagged) == 1        # flagged BEFORE raising


def test_warmed_serve_buckets_zero_steady_state_recompiles():
    """warmup() promises zero steady-state compiles — the ledger and
    sentinel hold it to that; serving within the warmed buckets never
    flags, an out-of-warmup compile does."""
    from veles_tpu.serve.engine import InferenceEngine
    wf = _build_stitched_workflow(max_epochs=1)
    engine = InferenceEngine.from_forwards(
        wf.forwards, sample_shape=(16,), max_batch_size=8)
    engine.warmup()
    compile_count = engine.compile_count
    recompiles_before = prof.ledger.recompiles
    for n in (1, 2, 3, 5, 8, 7, 4):
        out = engine.infer(numpy.zeros((n, 16), numpy.float32))
        assert out.shape == (n, 10)
    assert engine.compile_count == compile_count
    assert prof.ledger.recompiles == recompiles_before
    assert prof.flagged == []
    # bucket entries carry cost + dispatch clocks
    entries = [e for e in prof.ledger.entries("bucket")
               if e.name.startswith(engine.prof_name)]
    assert entries
    assert all(e.flops > 0 for e in entries)
    assert any(e.dispatches > 0 for e in entries)
    # forcing a compile AFTER warmup is flagged as steady-state
    engine.buckets = engine.buckets + (16,)
    engine._executable(16)
    assert prof.ledger.recompiles == recompiles_before + 1
    assert len(prof.flagged) == 1
    assert "bucket[16]" in prof.flagged[0]["site"]


# -- serve /metrics ---------------------------------------------------------

def test_latency_histogram_prometheus_exposition():
    """Real histogram exposition: cumulative ``le`` buckets +
    ``_sum``/``_count``, consistent with the recorded stream, while
    the percentile text lines stay for the web status page."""
    from veles_tpu.serve.metrics import ServingMetrics
    metrics = ServingMetrics()
    samples = [0.001, 0.004, 0.004, 0.02, 0.3]
    for s in samples:
        metrics.observe_request(s)
    text = metrics.render_text()
    lines = text.splitlines()
    buckets = [ln for ln in lines if ln.startswith(
        "veles_serve_request_latency_seconds_bucket")]
    assert buckets[-1] == \
        'veles_serve_request_latency_seconds_bucket{le="+Inf"} 5'
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "cumulative le buckets"
    assert counts[0] == 0 and counts[-1] == len(samples)
    sum_line = [ln for ln in lines if ln.startswith(
        "veles_serve_request_latency_seconds_sum")][0]
    assert float(sum_line.rsplit(" ", 1)[1]) == \
        pytest.approx(sum(samples))
    assert "veles_serve_request_latency_seconds_count 5" in lines
    assert "# TYPE veles_serve_request_latency_seconds histogram" \
        in lines
    # every observation is <= some finite bound here, so the largest
    # finite bucket already holds all five
    assert counts[-2] == len(samples)
    # the batch histogram family is present too, and the legacy
    # percentile lines survive for web_status
    assert any(ln.startswith(
        "veles_serve_batch_latency_seconds_bucket") for ln in lines)
    assert any('request_latency_ms{quantile="p99"}' in ln
               for ln in lines)


def test_prof_metrics_text_gauges():
    from veles_tpu.memory import Watcher
    _build_stitched_workflow(max_epochs=1)
    text = prof.metrics_text()
    assert "veles_prof_compiles_total" in text
    assert "veles_prof_recompiles_total" in text
    assert 'veles_prof_hbm_bytes{category="params"}' in text
    assert ("veles_prof_hbm_peak_bytes %d" % Watcher.peak_bytes) \
        in text


# -- heartbeat watchdog -----------------------------------------------------

class _ScriptedMaster(object):
    def __init__(self, n_jobs=3):
        self.n_jobs = n_jobs
        self.served = 0
        self.updates = []

    def checksum(self):
        return "prof-v1"

    def generate_data_for_slave(self, slave):
        if self.served >= self.n_jobs:
            return None
        self.served += 1
        return {"job_number": self.served}

    def apply_data_from_slave(self, data, slave):
        self.updates.append(data)

    def drop_slave(self, slave):
        pass


class _ScriptedSlave(object):
    def __init__(self, delay=0.0):
        self.delay = delay

    def checksum(self):
        return "prof-v1"

    def do_job(self, data, callback):
        if self.delay:
            time.sleep(self.delay)
        callback({"result": data["job_number"]})


def test_heartbeat_watchdog_flags_stalled_slave(live_trace, caplog):
    """heartbeat_warn_ms (default off): a scripted slave that
    handshakes and then goes silent draws a WARNING + a
    ``jobs:heartbeat_stall`` instant — once per excursion, well
    before the hard timeout reaps it."""
    from veles_tpu.parallel.jobs import JobClient, JobServer
    saved = root.common.engine.get("heartbeat_warn_ms", None)
    root.common.engine.heartbeat_warn_ms = 80
    master = _ScriptedMaster()
    server = JobServer(master, slave_timeout=30.0,
                       heartbeat_interval=0.05).start()
    try:
        client = JobClient(_ScriptedSlave(), server.endpoint)
        client.handshake()
        # the slave now stalls: no pings, no job requests
        with caplog.at_level(logging.WARNING):
            time.sleep(0.6)
        assert any("heartbeat stalled" in rec.message
                   for rec in caplog.records)
        # warned ONCE per excursion, not once per reaper tick
        assert trace.recorder.count("jobs", "heartbeat_stall") == 1
        assert server.slaves[client.sid].hb_warned
        client.close()
    finally:
        server.stop()
        root.common.engine.heartbeat_warn_ms = saved


def test_heartbeat_watchdog_default_off(live_trace):
    from veles_tpu.parallel.jobs import JobClient, JobServer
    master = _ScriptedMaster()
    server = JobServer(master, slave_timeout=30.0,
                       heartbeat_interval=0.05).start()
    try:
        client = JobClient(_ScriptedSlave(), server.endpoint)
        client.handshake()
        time.sleep(0.3)
        assert trace.recorder.count("jobs", "heartbeat_stall") == 0
        client.close()
    finally:
        server.stop()


# -- cluster merge ----------------------------------------------------------

def _run_scripted_session(tmp_path, n_slaves=2, n_jobs=6):
    """A scripted master–slave session over real ZMQ; every slave
    ships its profile at end-of-run; returns the saved bundle path."""
    from veles_tpu.parallel.jobs import JobClient, JobServer
    master = _ScriptedMaster(n_jobs=n_jobs)
    server = JobServer(master).start()
    clients = [JobClient(_ScriptedSlave(delay=0.01 * (1 + 3 * i)),
                         server.endpoint, sid="s%d" % i)
               for i in range(n_slaves)]
    try:
        threads = []
        for client in clients:
            client.handshake()
        for client in clients:
            t = threading.Thread(target=client.run)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(30)
        for client in clients:
            client.close()
        assert len(master.updates) == n_jobs
        for client in clients:
            assert client.sid in server.slave_profiles, \
                "slave %s did not ship its profile" % client.sid
        path = str(tmp_path / "session_profile.json")
        # in-process session: master and slaves share ONE ring, so
        # the master keeps only its own lanes in the bundle
        server.save_session_profile(path, roles=("master",))
        return path
    finally:
        server.stop()


def test_cluster_merge_timeline_and_report(live_trace, tmp_path):
    """The acceptance scenario: a scripted master–slave session
    merges into ONE Perfetto-loadable timeline with master +
    slave-<sid> tracks, and the cluster report prints per-slave MFU
    and the straggler spread."""
    bundle_path = _run_scripted_session(tmp_path)
    bundle = prof.merge.load(bundle_path)
    assert set(bundle["slaves"]) == {"s0", "s1"}
    for slave_prof in bundle["slaves"].values():
        assert slave_prof["events"], "shipped ring must not be empty"
        assert "totals" in slave_prof["ledger"]
        # in-process shipping keeps each slave to its own lanes
        roles = {ev.get("role") for ev in slave_prof["events"]}
        assert "master" not in roles
    merged = prof.merge.merged_events(bundle)
    ts = [ev["ts_us"] for ev in merged]
    assert ts == sorted(ts)
    out = prof.merge.save_merged(bundle,
                                 str(tmp_path / "merged.json"))
    with open(out) as fin:
        payload = json.load(fin)
    assert payload["traceEvents"], "Perfetto needs traceEvents"
    roles = {ev["args"]["name"] for ev in payload["traceEvents"]
             if ev.get("ph") == "M"}
    assert "master" in roles
    assert {"slave-s0", "slave-s1"} <= roles
    report = prof.merge.cluster_report(bundle)
    assert "slave-s0" in report and "slave-s1" in report
    assert "mfu" in report
    assert "straggler spread" in report
    # the slow slave (3x the per-job delay) is named the straggler
    assert "slowest slave-s1" in report
    assert "aggregate peak HBM" in report


@pytest.mark.traced
def test_prof_cli_offline_and_merge(tmp_path, capsys):
    """``python -m veles_tpu.prof``: a trace export renders the
    per-segment ledger offline (cost rides the compile instants); a
    session bundle renders the cluster report; ``merge`` writes the
    combined timeline.  The ``traced`` marker arms recording through
    the CONFIG knob so ``initialize()`` keeps it on."""
    from veles_tpu.prof.__main__ import main
    wf = _build_stitched_workflow()
    wf.run()
    trace_path = str(tmp_path / "run.json")
    trace.save(trace_path)
    assert main([trace_path]) == 0
    out = capsys.readouterr().out
    assert "performance ledger" in out
    assert "stitched segments" in out
    assert "e+" in out                    # non-zero flops rendered
    assert "0 steady-state recompile(s)" in out
    assert main([trace_path, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)["entries"]
    assert rows and all(r["flops"] > 0 for r in rows)
    bundle_path = _run_scripted_session(tmp_path)
    assert main([bundle_path]) == 0
    assert "straggler spread" in capsys.readouterr().out
    merged_path = str(tmp_path / "merged.json")
    assert main(["merge", bundle_path, "-o", merged_path]) == 0
    assert "merged timeline" in capsys.readouterr().out
    with open(merged_path) as fin:
        assert json.load(fin)["traceEvents"]
    assert main([str(tmp_path / "nope.json")]) == 2
