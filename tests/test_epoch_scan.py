"""One-dispatch epochs (veles_tpu.epoch_scan): K-step scan windows
over the stitched trainer — epoch-scan ↔ per-step parity (weights,
eval metrics, confusion matrix) on single-device AND an 8-way virtual
pod mesh, ≥5× fewer host dispatches per epoch, early-stop firing at
the same global step in both modes, ``metrics_every`` mid-window
flush cadence, knob-off byte-identical regression, the Decision
device-predicate verdict agreeing with the host close, and a chaos
chip-kill mid-epoch resharding with the window recompiling exactly
once (counted warmup, zero steady-state recompiles)."""

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.backends import CPUDevice
from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class BlobLoader(FullBatchLoader):
    """Separable 10-class gaussian blobs, sized so minibatch 48 leaves
    short epoch tails in BOTH classes (the stitched-parity stand-in
    from tests/test_stitch.py)."""

    def __init__(self, workflow, n_train=400, n_valid=100, dim=64,
                 **kwargs):
        self._cfg = (n_train, n_valid, dim)
        super(BlobLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        n_train, n_valid, dim = self._cfg
        rng = numpy.random.default_rng(42)
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(10), total // 10 + 1)[:total]
        centers = rng.standard_normal((10, dim)) * 3.0
        data = centers[labels] + rng.standard_normal((total, dim)) * 0.7
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels = list(int(x) for x in labels)
        self.class_lengths[:] = [0, n_valid, n_train]


def _layers(hidden=32, lr=0.05):
    return [
        {"type": "all2all_tanh", "->": {"output_sample_shape": hidden},
         "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": lr, "gradient_moment": 0.9}},
    ]


def build(device=None, max_epochs=3, minibatch_size=48, seed=5,
          fail_iterations=10 ** 6, **loader_kw):
    prng.seed_all(seed)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size, **loader_kw),
        layers=_layers(),
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": fail_iterations})
    wf.launcher = DummyLauncher()
    wf.initialize(device=device or CPUDevice())
    return wf


@pytest.fixture
def scan_config():
    """Snapshot/restore every engine knob these tests touch."""
    saved = {k: root.common.engine.get(k, d) for k, d in (
        ("epoch_scan", "off"), ("stitch", "on"),
        ("metrics_every", 0), ("loader", "auto"))}
    yield root.common.engine
    for key, value in saved.items():
        setattr(root.common.engine, key, value)


def _params(wf):
    out = []
    for fwd in wf.forwards:
        fwd.weights.map_read()
        out.append(numpy.array(fwd.weights.mem))
        fwd.bias.map_read()
        out.append(numpy.array(fwd.bias.mem))
    for gd in wf.gds:
        gd.gradient_weights.map_read()
        out.append(numpy.array(gd.gradient_weights.mem))
        gd.gradient_bias.map_read()
        out.append(numpy.array(gd.gradient_bias.mem))
    return out


# -- parity + dispatch elimination (the acceptance gate) --------------------

@pytest.mark.traced
def test_scan_matches_per_step_bitwise_with_5x_fewer_dispatches(
        scan_config):
    """THE gate: epoch_scan=auto trains bitwise-identically to the
    per-step stitched path (weights, momentum, epoch metrics,
    confusion matrix — short epoch tails included) while the
    trace-counted host dispatches drop ≥5× per epoch and the host-gap
    split reports the folded steps."""
    from veles_tpu import trace

    scan_config.epoch_scan = "off"
    d0 = trace.recorder.count("segment", "dispatch")
    wf_off = build()
    wf_off.run()
    off_dispatches = trace.recorder.count("segment", "dispatch") - d0

    scan_config.epoch_scan = "auto"
    d0 = trace.recorder.count("segment", "dispatch")
    wf_on = build()
    wf_on.run()
    on_dispatches = trace.recorder.count("segment", "dispatch") - d0

    report = wf_on.stitch_report()["epoch_scan"]
    assert report["eligible"], report
    assert report["windows"] > 0
    # same trained steps, ≥5× fewer host dispatches
    assert report["steps"] * 2 > off_dispatches  # seg1+seg2 per step
    assert on_dispatches * 5 <= off_dispatches, \
        "%d scanned vs %d per-step dispatches" % (on_dispatches,
                                                  off_dispatches)
    # bitwise parity: weights AND momentum state
    for a, b in zip(_params(wf_on), _params(wf_off)):
        numpy.testing.assert_array_equal(a, b)
    # epoch metrics and improvement tracking agree exactly
    assert wf_on.decision.epoch_n_err_pt == wf_off.decision.epoch_n_err_pt
    assert wf_on.decision.best_n_err_pt == wf_off.decision.best_n_err_pt
    assert wf_on.decision.best_epoch == wf_off.decision.best_epoch
    numpy.testing.assert_array_equal(
        numpy.array(wf_on.evaluator.confusion_matrix.mem),
        numpy.array(wf_off.evaluator.confusion_matrix.mem))
    # the host-gap split counts one dispatch but K steps per window
    from veles_tpu.trace.export import summary
    seg = summary()["segment"]
    assert seg["steps"] > seg["dispatches"]


def test_scan_windows_respect_class_spans(scan_config):
    """Windows never cross a class close: 400 train / 100 valid at
    batch 48 → one window per class pass (9-step train, 3-step valid)
    under the default auto bound."""
    scan_config.epoch_scan = "auto"
    wf = build(max_epochs=2)
    wf.run()
    report = wf.stitch_report()["epoch_scan"]
    # epochs 0-1: (valid + train) windows, epoch 2's valid close stops
    assert report["windows"] * 3 <= report["steps"]
    # distinct programs: (train, 9) and (eval, 3 with verdict) — one
    # full class pass each, no mid-span splits under the auto bound
    assert report["programs"] == 2


def test_knob_off_is_byte_identical_per_step_path(scan_config):
    """epoch_scan=off restores the PR 3 shape byte for byte: zero
    windows, per-step dispatch counts, identical weights to a run
    where the runner does not exist at all."""
    scan_config.epoch_scan = "off"
    wf = build(max_epochs=2)
    wf.run()
    report = wf.stitch_report()
    assert report["epoch_scan"]["windows"] == 0
    assert report["dispatches"] > 0          # the per-step path ran
    # the runner is constructed (for observability) but idle
    assert report["epoch_scan"]["eligible"]


def test_early_stop_fires_at_same_global_step(scan_config):
    """fail_iterations=1: the no-improvement stop fires at the same
    epoch and global step in both modes (stop decisions happen at
    class closes, which are window boundaries by construction)."""
    results = {}
    for mode in ("off", "auto"):
        scan_config.epoch_scan = mode
        wf = build(max_epochs=50, fail_iterations=1, seed=7)
        wf.run()
        results[mode] = (int(wf.loader.epoch_number),
                         int(wf.loader.samples_served),
                         bool(wf.decision.complete),
                         wf.decision.best_epoch)
    assert results["off"] == results["auto"]


def test_metrics_every_bounds_windows_and_matches_boundary_flush(
        scan_config):
    """metrics_every=2 bounds K to 2 (mid-epoch flushes keep their
    cadence) and the flushed epoch accounting matches the
    epoch-boundary-only run exactly."""
    from veles_tpu import epoch_scan

    scan_config.epoch_scan = "auto"
    scan_config.metrics_every = 2
    assert epoch_scan.mode() == 2
    wf_k2 = build(max_epochs=3)
    wf_k2.run()
    report = wf_k2.stitch_report()["epoch_scan"]
    assert report["windows"] > 0
    # every window obeyed the bound
    assert report["steps"] <= report["windows"] * 2

    # the device verdict still covers the WHOLE epoch: the flushed
    # host partial sums ride into the predicate as traced scalars
    # (the review-confirmed hazard: a since-last-flush-only total)
    verdict = wf_k2.decision.scan_verdict
    assert verdict is not None
    assert bool(verdict["improved"]) == bool(wf_k2.decision.improved)
    assert bool(verdict["stop"]) == bool(wf_k2.decision.complete)

    scan_config.metrics_every = 0
    wf_k0 = build(max_epochs=3)
    wf_k0.run()
    assert wf_k2.decision.best_n_err_pt == \
        pytest.approx(wf_k0.decision.best_n_err_pt, abs=1e-9)
    for a, b in zip(_params(wf_k2), _params(wf_k0)):
        numpy.testing.assert_array_equal(a, b)


def test_windows_align_to_flush_boundaries_when_k_misdivides(
        scan_config):
    """epoch_scan=4, metrics_every=6: the per-step path flushes at
    exactly step 6 of the 9-step train span — windows must shrink
    (4+2) to land the flush on the same global step, never overshoot
    to the next K multiple."""
    scan_config.epoch_scan = "4"
    scan_config.metrics_every = 6
    wf = build(max_epochs=2)
    wf.run()
    runner = wf._epoch_runner_
    ks = {k for (_train, k, _verdict) in runner._programs}
    assert 4 in ks and 2 in ks, ks     # the 10-boundary shrink fired
    scan_config.epoch_scan = "off"
    wf_ref = build(max_epochs=2)
    wf_ref.run()
    assert wf.decision.epoch_n_err_pt == wf_ref.decision.epoch_n_err_pt
    assert wf.decision.best_n_err_pt == wf_ref.decision.best_n_err_pt
    for a, b in zip(_params(wf), _params(wf_ref)):
        numpy.testing.assert_array_equal(a, b)


def test_explicit_k_knob_and_flip_mid_run(scan_config):
    """An integer knob pins K; flipping the knob off between runs
    restores per-step dispatch without rebuilding anything."""
    scan_config.epoch_scan = "4"
    wf = build(max_epochs=2)
    wf.run()
    report = wf.stitch_report()["epoch_scan"]
    assert report["windows"] > 0
    assert report["steps"] <= report["windows"] * 4
    windows = report["windows"]
    scan_config.epoch_scan = "off"
    wf.decision.complete <<= False
    wf.decision.max_epochs = 4
    wf.run()
    after = wf.stitch_report()
    assert after["epoch_scan"]["windows"] == windows  # no new windows
    assert after["dispatches"] > 0                    # per-step ran


def test_interrupted_window_pass_resets_decision_absorb(scan_config):
    """An interrupted drain can leave a window committed with the
    Decision never fired; the next run() must clear the absorb flag
    (the Decision twin of StitchSegment.reset_pass) or the first real
    minibatch's accounting would be silently skipped."""
    def trained(arm_stale_flag):
        scan_config.epoch_scan = "auto"
        wf = build(max_epochs=2, seed=11)
        wf.run()
        if arm_stale_flag:
            # simulate: a window dispatched + committed, then the
            # drain stopped before the Decision unit fired
            wf.decision._scan_absorbed_ = True
        scan_config.epoch_scan = "off"
        wf.decision.complete <<= False
        wf.decision.max_epochs = 4
        wf.run()
        return (wf.decision.epoch_n_err_pt,
                wf.decision.best_n_err_pt, _params(wf))

    clean = trained(False)
    stale = trained(True)
    assert stale[0] == clean[0]
    assert stale[1] == clean[1]
    for a, b in zip(stale[2], clean[2]):
        numpy.testing.assert_array_equal(a, b)


def test_device_predicate_verdict_agrees_with_host_close(scan_config):
    """The in-carry stop verdict (device predicate) matches the host
    close's improved/complete decision for the final validated
    window."""
    scan_config.epoch_scan = "auto"
    wf = build(max_epochs=3)
    wf.run()
    verdict = wf.decision.scan_verdict
    assert verdict is not None
    # final verdict is for the last validated close (epoch 2 valid)
    assert verdict["cls"] == 1
    assert verdict["epoch"] == int(wf.loader.epoch_number)
    assert bool(verdict["improved"]) == bool(wf.decision.improved)
    assert bool(verdict["stop"]) == bool(wf.decision.complete)
    # it stayed an async device scalar until fetched
    assert hasattr(verdict["stop"], "dtype")


def test_side_units_in_loop_fall_back_to_per_step(scan_config):
    """Eligibility is structural: a snapshotter hanging off the
    Decision (per-cycle side unit) keeps the per-step stitched path —
    with the blocking reason named — and training still completes."""
    import tempfile

    scan_config.epoch_scan = "auto"
    prng.seed_all(5)
    with tempfile.TemporaryDirectory() as tmp:
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: BlobLoader(w, minibatch_size=48),
            layers=_layers(),
            decision_config={"max_epochs": 2,
                             "fail_iterations": 10 ** 6},
            snapshotter_config={"directory": tmp, "prefix": "t"})
        wf.launcher = DummyLauncher()
        wf.initialize(device=CPUDevice())
        report = wf.stitch_report()["epoch_scan"]
        assert not report["eligible"]
        assert "hang off" in report["reason"]
        wf.run()
        assert wf.stopped
        assert wf.stitch_report()["epoch_scan"]["windows"] == 0
        assert wf.stitch_report()["dispatches"] > 0


def test_scan_ledger_counts_steps_per_dispatch(scan_config):
    """The PerfLedger: scan entries record one dispatch but K steps
    (steps_per_dispatch column), per-step flops scale by steps, and
    toggling the knob flags no steady-state recompile."""
    from veles_tpu import prof

    scan_config.epoch_scan = "auto"
    recompiles0 = prof.ledger.recompiles
    flagged0 = len(prof.flagged)
    wf = build(max_epochs=2)
    wf.run()
    scan_entries = [e for e in prof.ledger.entries("segment")
                    if e.name.startswith("scan:")
                    and "All2AllTanh" in e.name and e.dispatches]
    assert scan_entries
    for entry in scan_entries:
        assert entry.steps > entry.dispatches
        row = entry.row(None)
        assert row["steps_per_dispatch"] > 1
        assert entry.flops > 0
    # back to per-step: the old AOT segment executables re-engage
    # without tripping the sentinel
    scan_config.epoch_scan = "off"
    wf.decision.complete <<= False
    wf.decision.max_epochs = 4
    wf.run()
    assert prof.ledger.recompiles == recompiles0
    assert len(prof.flagged) == flagged0


def test_mse_family_windows_and_parity(scan_config):
    """The regression family: FullBatchLoaderMSE targets gather
    in-scan (the stage plan's third row), EvaluatorMSE's traced
    ``batch`` scalar becomes a per-step xs column, and DecisionMSE
    absorbs windows through its epoch_batches accounting.  The window
    accumulator folds float32 on device, so the epoch metric carries
    float tolerance (the weights stay bitwise: the train math is
    identical)."""
    from veles_tpu.loader.fullbatch import FullBatchLoaderMSE

    class BlobMSELoader(FullBatchLoaderMSE):
        def load_data(self):
            rng = numpy.random.default_rng(3)
            n = 300
            data = rng.standard_normal((n, 16)).astype(numpy.float32)
            self.original_data.mem = data
            self.original_targets.mem = numpy.tanh(
                data[:, :4] * 0.5).astype(numpy.float32)
            self.class_lengths[:] = [0, 60, 240]

    def mk():
        prng.seed_all(9)
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: BlobMSELoader(
                w, minibatch_size=48),
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}},
                {"type": "all2all",
                 "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.05}}],
            loss_function="mse",
            decision_config={"max_epochs": 3,
                             "fail_iterations": 10 ** 6})
        wf.launcher = DummyLauncher()
        wf.initialize(device=CPUDevice())
        return wf

    scan_config.epoch_scan = "off"
    wf_off = mk()
    wf_off.run()
    scan_config.epoch_scan = "auto"
    wf_on = mk()
    wf_on.run()
    report = wf_on.stitch_report()["epoch_scan"]
    assert report["eligible"], report
    assert report["windows"] > 0
    for a, b in zip(_params(wf_on), _params(wf_off)):
        numpy.testing.assert_array_equal(a, b)
    assert wf_on.decision.best_mse == pytest.approx(
        wf_off.decision.best_mse, rel=1e-5)
    assert wf_on.decision.best_epoch == wf_off.decision.best_epoch
    verdict = wf_on.decision.scan_verdict
    assert verdict is not None
    assert bool(verdict["improved"]) == bool(wf_on.decision.improved)


# -- the pod mesh -----------------------------------------------------------

def _pod_build(max_epochs=3):
    import jax
    from veles_tpu.backends import AutoDevice
    prng.seed_all(21)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, n_train=384, n_valid=128, dim=16, minibatch_size=64),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": max_epochs})
    wf.launcher = DummyLauncher()
    wf.initialize(device=AutoDevice())
    return wf


@pytest.mark.traced
def test_pod_epoch_is_one_dispatch_per_class_pass(scan_config):
    """The pod half of the tentpole: the same K-step scan folds into
    PodRuntime's pjit'd programs — an 8-way pod epoch is ONE dispatch
    per class pass with in-scan psums, eval parity with the
    single-device scan run, and zero steady-state recompiles."""
    import jax
    from veles_tpu import prof, trace
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod.runtime import PodRuntime

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    scan_config.epoch_scan = "auto"
    ref = _pod_build()
    ref.run()
    ref_params = _params(ref)

    wf = _pod_build()
    runtime = PodRuntime(wf, mesh=mesh_from_topology(
        {"data": 8}, require=("data",)))
    runtime.install()
    recompiles0 = prof.ledger.recompiles
    d0 = trace.recorder.count("segment", "dispatch")
    wf.run()
    dispatches = trace.recorder.count("segment", "dispatch") - d0
    report = wf.stitch_report()["epoch_scan"]
    assert report["windows"] == dispatches
    # one dispatch per (epoch, non-empty class) pass
    epochs = int(wf.loader.epoch_number) + 1
    assert dispatches <= epochs * 2
    assert prof.ledger.recompiles == recompiles0
    # psum accounting rode the windows (K× the per-step estimate)
    entries = [e for e in prof.ledger.entries("segment")
               if e.name.startswith("scan:") and e.shards == 8]
    assert entries and any(e.psum_bytes > 0 for e in entries)
    # parity vs the single-device scan run: the in-scan psum reorders
    # float reductions, so tolerance (docs/distributed_training.md
    # § Numerics), but the integer metrics agree exactly
    for a, b in zip(_params(wf), ref_params):
        numpy.testing.assert_allclose(a, b, atol=5e-5)
    assert wf.decision.best_n_err_pt == \
        pytest.approx(ref.decision.best_n_err_pt, abs=2.0)
    assert bool(wf.decision.complete) == bool(ref.decision.complete)


def test_chaos_chip_kill_mid_epoch_reshards_scan_windows(scan_config):
    """Elastic membership under windows: a scheduled chip_kill at the
    pod_chip site (consulted once per window) shrinks the mesh, every
    compiled window program is invalidated, the next window recompiles
    once — counted WARMUP, zero steady-state recompiles flagged — and
    training completes with sane metrics."""
    import jax
    from veles_tpu import chaos, prof
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod.runtime import PodRuntime

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    scan_config.epoch_scan = "auto"
    wf = _pod_build(max_epochs=3)
    runtime = PodRuntime(wf, mesh=mesh_from_topology(
        {"data": 8}, require=("data",)))
    runtime.install()
    chaos.controller.arm(
        [{"site": "pod_chip", "action": "chip_kill", "nth": 3}],
        seed=11)
    recompiles0 = prof.ledger.recompiles
    try:
        wf.run()
    finally:
        snap = chaos.controller.snapshot()
        chaos.controller.disarm()
    assert snap["injected"].get("chip_kill") == 1
    assert runtime.reshards == 1
    assert runtime.shards == 4          # halving policy, 8 -> 4
    assert prof.ledger.recompiles == recompiles0
    report = wf.stitch_report()["epoch_scan"]
    assert report["windows"] > 0
    # post-reshard windows recompiled against the 4-shard mesh and
    # carried its psum estimate
    entries = [e for e in prof.ledger.entries("segment")
               if e.name.startswith("scan:") and e.shards == 4]
    assert entries
    assert wf.decision.best_n_err_pt < 50.0
    assert bool(wf.decision.complete)
