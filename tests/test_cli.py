"""CLI layer tests: cmdline registry, Launcher modes, __main__ plumbing
(ref test strategy: ``test_launcher.py`` runs master+slave in ONE process
against localhost, SURVEY §4)."""

import json
import os
import sys
import threading

import pytest

from veles_tpu.cmdline import make_parser, register_arguments
from veles_tpu.config import root
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.launcher import Launcher, _split_endpoint
from veles_tpu.units import Unit


def test_parser_core_flags():
    parser = make_parser()
    args, _ = parser.parse_known_args(
        ["veles_tpu.samples.mnist", "-d", "numpy", "--test",
         "root.common.engine.backend=numpy"])
    assert args.workflow == "veles_tpu.samples.mnist"
    assert args.device == "numpy"
    assert args.test


def test_parser_contributor_registry():
    saw = []

    def contribute(parser):
        saw.append(True)
        parser.add_argument("--test-contrib-flag", default="x")

    register_arguments(contribute)
    parser = make_parser()
    args, _ = parser.parse_known_args(["w"])
    assert saw and args.test_contrib_flag == "x"


def test_split_endpoint():
    assert _split_endpoint("1.2.3.4:5000") == ("1.2.3.4", 5000)
    assert _split_endpoint(":5000") == ("127.0.0.1", 5000)
    assert _split_endpoint("5000") == ("127.0.0.1", 5000)


class _CountingUnit(Unit):
    def __init__(self, workflow, **kwargs):
        super(_CountingUnit, self).__init__(workflow, **kwargs)
        self.runs = 0

    def run(self):
        self.runs += 1


def _tiny_workflow():
    wf = DummyWorkflow()
    unit = _CountingUnit(wf)
    unit.link_from(wf.start_point)
    wf.end_point.link_from(unit)
    return wf, unit


def test_launcher_standalone_runs_workflow():
    wf, unit = _tiny_workflow()
    launcher = Launcher(wf, device="numpy")
    assert launcher.is_standalone and wf.launcher is launcher
    assert launcher.workflow is wf  # add_ref via the launcher setter
    launcher.initialize()
    launcher.run()
    assert unit.runs == 1
    status = launcher.status()
    assert status["mode"] == "standalone" and status["stopped"]
    json.loads(launcher.status_json())


def test_launcher_master_slave_exclusive():
    with pytest.raises(ValueError):
        Launcher(listen=":5000", master_address="h:6000")


def test_launcher_modes():
    assert Launcher(listen=":0").is_master
    assert Launcher(master_address="h:1").is_slave


def test_main_runs_sample_module(tmp_path):
    """python -m veles_tpu veles_tpu.samples.mnist -d numpy with a tiny
    config (synthetic data, 1 epoch)."""
    from veles_tpu.__main__ import Main
    result_file = str(tmp_path / "result.json")
    main = Main([
        "veles_tpu.samples.mnist", "-d", "numpy",
        "--result-file", result_file,
    ])
    args = main._parse()
    assert args.workflow == "veles_tpu.samples.mnist"
    main._setup_logging()
    main._seed_random()
    main._apply_config()
    # construct but don't run 25 epochs: dry-run init only
    main.args.dry_run = "init"
    main.module = main._load_module(main.args.workflow)
    wf = main.module.create_workflow(
        launcher=Launcher(device="numpy"), max_epochs=1,
        minibatch_size=50)
    assert not getattr(wf, "_is_initialized", False)
    wf.launcher.initialize()
    assert wf._is_initialized


def test_main_fused_flag(tmp_path):
    """--fused reaches create_workflow and builds the FusedTrainer
    graph (no eager gd chain)."""
    from veles_tpu.__main__ import Main
    main = Main(["veles_tpu.samples.mnist", "-d", "numpy", "--fused"])
    args = main._parse()
    assert args.fused
    main._setup_logging()
    main._seed_random()
    main._apply_config()
    main.module = main._load_module(main.args.workflow)
    extra = {"fused": True} if main.args.fused else {}
    wf = main.module.create_workflow(
        launcher=Launcher(device="numpy"), max_epochs=1,
        minibatch_size=50, **extra)
    assert wf.fused and wf.fused_trainer is not None
    assert wf.gds == []


def test_main_dry_run_init(tmp_path):
    from veles_tpu.__main__ import Main
    graph = str(tmp_path / "graph.dot")
    rc = Main(["veles_tpu.samples.mnist", "-d", "numpy",
               "--dry-run", "init", "--workflow-graph", graph]).run()
    assert rc == 0
    assert os.path.exists(graph)
    assert "digraph" in open(graph).read()


def test_main_loads_workflow_from_file(tmp_path):
    """A user workflow .py file using the create_workflow convention."""
    from veles_tpu.__main__ import Main
    wf_file = tmp_path / "wf.py"
    wf_file.write_text("""
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.units import Unit

class Probe(Unit):
    ran = False
    def run(self):
        Probe.ran = True

def create_workflow(launcher=None, **kwargs):
    wf = DummyWorkflow()
    if launcher is not None:
        wf.launcher = launcher
    probe = Probe(wf)
    probe.link_from(wf.start_point)
    wf.end_point.link_from(probe)
    return wf
""")
    rc = Main([str(wf_file), "-d", "numpy"]).run()
    assert rc == 0
    mod = sys.modules["wf"]
    assert mod.Probe.ran


def test_main_seed_from_file(tmp_path):
    from veles_tpu.__main__ import Main
    from veles_tpu import prng
    seed_file = tmp_path / "seed.bin"
    seed_file.write_bytes(bytes(range(64)))
    main = Main(["w", "-r", "%s:uint32:16" % seed_file])
    main._parse()
    main._seed_random()
    a = prng.get("master").randint(0, 1 << 30)
    main._seed_random()
    assert prng.get("master").randint(0, 1 << 30) == a


def test_main_config_overrides(tmp_path):
    from veles_tpu.__main__ import Main
    cfg = tmp_path / "cfg.py"
    cfg.write_text("root.common.test_marker = 41\n")
    main = Main(["w", str(cfg), "root.common.test_marker2=42"])
    main._parse()
    main._apply_config()
    assert root.common.test_marker == 41
    assert root.common.test_marker2 == 42


def test_master_slave_end_to_end():
    """Launcher-level master+slave in one process (ref
    test_launcher.py:104 testConnectivity)."""
    from veles_tpu.parallel.jobs import JobServer, JobClient

    class JobWorkflow(object):
        """Scripted generate_/apply_ methods (ref test_network.py:52)."""

        def __init__(self):
            self.jobs = list(range(5))
            self.updates = []

        @staticmethod
        def checksum():
            return "tiny"

        def generate_data_for_slave(self, slave=None):
            from veles_tpu.workflow import NoMoreJobs
            if not self.jobs:
                raise NoMoreJobs()
            return self.jobs.pop()

        def apply_data_from_slave(self, data, slave=None):
            self.updates.append(data)

        def drop_slave(self, slave=None):
            pass

    class SlaveWorkflow(object):
        @staticmethod
        def checksum():
            return "tiny"

        def do_job(self, data, callback):
            callback(data * 10)

    master_wf = JobWorkflow()
    server = JobServer(master_wf).start()
    try:
        slave_wf = SlaveWorkflow()
        client = JobClient(slave_wf, server.endpoint)
        client.handshake()
        client.run()
        client.close()
        deadline = 50
        while not server.finished and deadline:
            threading.Event().wait(0.1)
            deadline -= 1
        assert sorted(master_wf.updates) == [0, 10, 20, 30, 40]
    finally:
        server.stop()


def test_version_logo_dump_flags(capsys):
    """--version prints-and-exits; --no-logo suppresses the banner;
    --dump-config prints the root tree; --dry-run load stops before
    construction; --dump-unit-attributes pretty elides arrays."""
    from veles_tpu.__main__ import Main

    assert Main(["--version"]).run() == 0
    out = capsys.readouterr().out
    assert "veles_tpu" in out and "jax" in out

    rc = Main(["veles_tpu.samples.mnist", "--no-logo", "--dry-run",
               "load", "--dump-config", "-d", "cpu"]).run()
    assert rc == 0
    captured = capsys.readouterr()
    assert "common" in captured.out          # the config tree printed
    assert "veles_tpu" not in captured.err   # banner suppressed

    rc = Main(["veles_tpu.samples.mnist", "--no-logo", "--dry-run",
               "init", "--dump-unit-attributes", "pretty",
               "-d", "cpu"]).run()
    assert rc == 0
    out = capsys.readouterr().out
    assert "array" in out                    # big weights elided
    assert "MnistLoader" in out or "loader" in out.lower()


def test_visualize_initializes_without_running(tmp_path, capsys, monkeypatch):
    """--visualize = initialize + graph into the snapshots dir, never
    train (both workflow conventions consult dry_run)."""
    from veles_tpu.__main__ import Main
    from veles_tpu.config import root

    monkeypatch.setattr(root.common.dirs, "snapshots", str(tmp_path),
                        raising=False)
    rc = Main(["veles_tpu.samples.mnist", "--no-logo", "--visualize",
               "-d", "cpu"]).run()
    assert rc == 0
    path = tmp_path / "workflow_graph.dot"
    assert path.exists()
    assert "digraph" in path.read_text()


def test_debug_pickle_names_unit_attribute(tmp_path):
    """--debug-pickle walks container shapes real snapshots have: a
    workflow whose UNIT holds an unpicklable attr is diagnosed down to
    workflow._units[i].attr."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.snapshotter import diagnose_pickle

    wf = DummyWorkflow()
    wf.initialize()
    list(wf)[0].evil_callback = lambda: None
    lines = diagnose_pickle(wf, path="workflow")
    assert any("evil_callback" in line for line in lines), lines


def test_peak_memory_printer(capsys):
    from veles_tpu.__main__ import Main

    Main.print_peak_memory()
    err = capsys.readouterr().err
    assert "Peak resident memory" in err and "MiB" in err


def test_html_help_writes_reference(capsys):
    from veles_tpu.__main__ import Main

    assert Main(["--html-help"]).run() == 0
    out = capsys.readouterr().out
    path = out.strip().rsplit(" ", 1)[-1]
    html = open(path).read()
    assert "--optimize" in html and "<" in html
