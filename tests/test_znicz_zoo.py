"""Conv/pooling/activation/LRN/dropout layer-zoo tests: golden checks vs
hand-computed numpy and an end-to-end conv workflow (the CIFAR-style
config from BASELINE.json.configs[1], shrunk)."""

import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.memory import Vector
from veles_tpu.znicz.activation import ForwardStrictRELU, ForwardTanh
from veles_tpu.znicz.conv import Conv
from veles_tpu.znicz.misc_units import Cutter, Deconv
from veles_tpu.znicz.normalization_units import (
    DropoutForward, LRNormalizerForward)
from veles_tpu.znicz.pooling import (
    AvgPooling, MaxAbsPooling, MaxPooling, StochasticPooling)
from veles_tpu.znicz.standard_workflow import StandardWorkflow


def test_conv_forward_golden():
    """3x3 conv, stride 1, no padding vs naive numpy loops."""
    rng = numpy.random.default_rng(0)
    x = rng.standard_normal((2, 6, 6, 3)).astype(numpy.float32)
    w = rng.standard_normal((3, 3, 3, 4)).astype(numpy.float32)
    b = rng.standard_normal(4).astype(numpy.float32)
    out = numpy.asarray(Conv.pure({"w": jnp.asarray(w),
                                   "b": jnp.asarray(b)},
                                  jnp.asarray(x)))
    ref = numpy.zeros((2, 4, 4, 4), numpy.float32)
    for n in range(2):
        for i in range(4):
            for j in range(4):
                patch = x[n, i:i + 3, j:j + 3, :]
                for k in range(4):
                    ref[n, i, j, k] = (patch * w[:, :, :, k]).sum() + b[k]
    assert numpy.allclose(out, ref, atol=1e-4)


def test_conv_padding_and_stride():
    x = jnp.ones((1, 8, 8, 1), jnp.float32)
    w = jnp.ones((3, 3, 1, 2), jnp.float32)
    out = Conv.pure({"w": w}, x, padding=(1, 1, 1, 1), sliding=(2, 2))
    assert out.shape == (1, 4, 4, 2)
    assert float(out[0, 1, 1, 0]) == 9.0     # interior window all-ones


def test_conv_space_to_depth_exact():
    """The space-to-depth rewrite of a strided conv (s×s spatial phases
    regrouped into input lanes — how a small-channel stride-4 conv like
    AlexNet conv1 reaches MXU lane occupancy) is numerically exact,
    gradients included, across kernel/stride/padding combinations."""
    import jax

    rng = numpy.random.default_rng(7)
    cases = [
        (227, 227, 3, 11, 11, 4, (0, 0, 0, 0)),   # AlexNet conv1
        (32, 32, 3, 5, 5, 2, (2, 1, 2, 1)),       # asymmetric padding
        (17, 19, 8, 3, 3, 3, (1, 1, 0, 2)),       # kernel < stride·2
        (20, 20, 2, 7, 5, 5, (0, 0, 0, 0)),       # kernel < stride (kx)
    ]
    for h, wd, c, ky, kx, s, pad in cases:
        x = rng.standard_normal((2, h, wd, c)).astype(numpy.float32)
        w = rng.standard_normal((ky, kx, c, 16)).astype(numpy.float32)
        b = rng.standard_normal(16).astype(numpy.float32)
        p = {"w": jnp.asarray(w), "b": jnp.asarray(b)}
        ref = Conv.pure(p, jnp.asarray(x), padding=pad, sliding=(s, s),
                        s2d=False)
        new = Conv.pure(p, jnp.asarray(x), padding=pad, sliding=(s, s),
                        s2d=True)
        assert ref.shape == new.shape
        numpy.testing.assert_allclose(numpy.asarray(new),
                                      numpy.asarray(ref), atol=1e-3)

    def loss(p, x_, s2d):
        return Conv.pure(p, x_, sliding=(4, 4), s2d=s2d).sum()

    x = jnp.asarray(rng.standard_normal((2, 31, 31, 3))
                    .astype(numpy.float32))
    p = {"w": jnp.asarray(rng.standard_normal((11, 11, 3, 8))
                          .astype(numpy.float32))}
    g0 = jax.grad(loss)(p, x, False)["w"]
    g1 = jax.grad(loss)(p, x, True)["w"]
    numpy.testing.assert_allclose(numpy.asarray(g1), numpy.asarray(g0),
                                  atol=1e-3)


def test_conv_unit_enables_s2d_for_strided_small_channel():
    """pure_config flips s2d on exactly when it pays: symmetric stride
    > 1 and few input channels (the lanes it frees)."""
    wf = DummyWorkflow()
    unit = Conv(wf, n_kernels=96, kx=11, ky=11, sliding=(4, 4))
    unit.input = Vector(numpy.zeros((2, 227, 227, 3), numpy.float32))
    unit.initialize(device=None)
    assert unit.pure_config()["s2d"] is True

    unit2 = Conv(wf, n_kernels=8, kx=3, ky=3)          # stride 1
    unit2.input = Vector(numpy.zeros((2, 8, 8, 3), numpy.float32))
    unit2.initialize(device=None)
    assert unit2.pure_config()["s2d"] is False

    unit3 = Conv(wf, n_kernels=8, kx=5, ky=5, sliding=(2, 2))
    unit3.input = Vector(numpy.zeros((2, 16, 16, 256), numpy.float32))
    unit3.initialize(device=None)                      # wide input
    assert unit3.pure_config()["s2d"] is False


def test_conv_s2d_dispatch_measurement_outranks_heuristic(monkeypatch):
    """The device DB's measured A/B (autotune_s2d) decides the rewrite
    on eligible convs; ``root.common.engine.s2d_conv`` force-overrides
    both; ineligible convs stay off regardless (r4 window 3: the
    heuristic said s2d, the v5-lite chip said 0.51x)."""
    from veles_tpu.config import root

    def eligible_conv():
        wf = DummyWorkflow()
        unit = Conv(wf, n_kernels=96, kx=11, ky=11, sliding=(4, 4))
        unit.input = Vector(numpy.zeros((2, 227, 227, 3),
                                        numpy.float32))
        unit.initialize(device=None)
        return unit

    # measured verdict wins over the heuristic
    monkeypatch.setattr("veles_tpu.ops.benchmark.s2d_choice",
                        lambda *a, **k: False)
    assert eligible_conv().pure_config()["s2d"] is False
    monkeypatch.setattr("veles_tpu.ops.benchmark.s2d_choice",
                        lambda *a, **k: True)
    assert eligible_conv().pure_config()["s2d"] is True
    # config force outranks the measurement
    monkeypatch.setattr("veles_tpu.ops.benchmark.s2d_choice",
                        lambda *a, **k: True)
    try:
        root.common.engine.s2d_conv = False
        assert eligible_conv().pure_config()["s2d"] is False
        root.common.engine.s2d_conv = True
        assert eligible_conv().pure_config()["s2d"] is True
        # force-on never applies to an INELIGIBLE conv (stride 1)
        wf = DummyWorkflow()
        unit = Conv(wf, n_kernels=8, kx=3, ky=3)
        unit.input = Vector(numpy.zeros((2, 8, 8, 3), numpy.float32))
        unit.initialize(device=None)
        assert unit.pure_config()["s2d"] is False
    finally:
        # remove the key outright (a sentinel value would leak
        # order-dependent state to later config readers)
        root.common.engine.__dict__.pop("s2d_conv", None)


def test_autotune_s2d_writes_db_and_choice_reads_it(tmp_path):
    """autotune_s2d persists the A/B winner; s2d_choice returns it for
    the measured device generation and None for an unmeasured one."""
    from veles_tpu.ops import benchmark as B

    db_path = str(tmp_path / "dev.json")
    info = B.autotune_s2d(batch=2, spatial=19, db_path=db_path)
    entry = info.ratings["s2d_conv"]["bfloat16"]
    assert isinstance(entry["enabled"], bool)
    assert entry["base_ms"] > 0 and entry["s2d_ms"] > 0
    assert entry["enabled"] == (entry["s2d_ms"] < entry["base_ms"])
    assert B.s2d_choice(db_path=db_path) == entry["enabled"]
    # unmeasured generation -> None (callers fall back to heuristic)
    assert B.s2d_choice(db_path=str(tmp_path / "absent.json")) is None


def test_pooling_golden():
    x = numpy.arange(16, dtype=numpy.float32).reshape(1, 4, 4, 1)
    mx = numpy.asarray(MaxPooling.pure({}, jnp.asarray(x), kind="max"))
    av = numpy.asarray(AvgPooling.pure({}, jnp.asarray(x), kind="avg"))
    assert mx.ravel().tolist() == [5, 7, 13, 15]
    assert av.ravel().tolist() == [2.5, 4.5, 10.5, 12.5]


def test_maxabs_pooling_keeps_sign():
    x = numpy.array([[[[1.0], [-5.0]], [[2.0], [3.0]]]],
                    dtype=numpy.float32)
    out = numpy.asarray(MaxAbsPooling.pure({}, jnp.asarray(x), kx=2,
                                           ky=2, sliding=(2, 2),
                                           kind="maxabs"))
    assert out.ravel().tolist() == [-5.0]    # |−5| biggest, sign kept


def test_stochastic_pooling_seed_reproducible():
    rng = numpy.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 4, 4, 3)), jnp.float32)
    a = StochasticPooling.pure({"seed": numpy.int32(7)}, x,
                               kind="stochastic")
    b = StochasticPooling.pure({"seed": numpy.int32(7)}, x,
                               kind="stochastic")
    c = StochasticPooling.pure({"seed": numpy.int32(8)}, x,
                               kind="stochastic")
    assert (numpy.asarray(a) == numpy.asarray(b)).all()
    assert not (numpy.asarray(a) == numpy.asarray(c)).all()
    # every pooled value is an element of its source window
    window = numpy.asarray(x[0, :2, :2, 0])
    assert numpy.asarray(a)[0, 0, 0, 0] in window


def test_lrn_golden():
    x = numpy.random.default_rng(2).standard_normal(
        (2, 3, 3, 8)).astype(numpy.float32)
    out = numpy.asarray(LRNormalizerForward.pure(
        {}, jnp.asarray(x), alpha=1e-4, beta=0.75, k=2.0, n=5))
    # manual for channel 4 of one pixel
    window = (x[0, 0, 0, 2:7] ** 2).sum()
    ref = x[0, 0, 0, 4] / (2.0 + 1e-4 * window) ** 0.75
    assert numpy.isclose(out[0, 0, 0, 4], ref, atol=1e-5)


def test_activation_units_golden():
    x = numpy.linspace(-2, 2, 12, dtype=numpy.float32).reshape(3, 4)
    tanh = numpy.asarray(ForwardTanh.pure({}, jnp.asarray(x),
                                          func="tanh"))
    assert numpy.allclose(tanh, 1.7159 * numpy.tanh(0.6666 * x),
                          atol=1e-5)
    srelu = numpy.asarray(ForwardStrictRELU.pure(
        {}, jnp.asarray(x), func="strict_relu"))
    assert numpy.allclose(srelu, numpy.maximum(x, 0))


def test_dropout_replay_and_forward_mode():
    x = jnp.ones((4, 100), jnp.float32)
    a = DropoutForward.pure({"seed": numpy.int32(3)}, x, keep=0.8)
    b = DropoutForward.pure({"seed": numpy.int32(3)}, x, keep=0.8)
    assert (numpy.asarray(a) == numpy.asarray(b)).all()
    kept = (numpy.asarray(a) > 0).mean()
    assert 0.7 < kept < 0.9
    assert numpy.allclose(numpy.asarray(a)[numpy.asarray(a) > 0],
                          1.0 / 0.8)


def test_cutter_and_deconv_shapes():
    x = jnp.ones((2, 8, 8, 3), jnp.float32)
    cut = Cutter.pure({}, x, window=(2, 2, 4, 4))
    assert cut.shape == (2, 4, 4, 3)
    w = jnp.ones((2, 2, 3, 3), jnp.float32)   # (ky, kx, C_out, K_in)
    up = Deconv.pure({"w": w}, jnp.ones((2, 4, 4, 3), jnp.float32),
                     sliding=(2, 2))
    assert up.shape == (2, 8, 8, 3)


# -- end-to-end conv workflow ------------------------------------------------

class TinyImageLoader(FullBatchLoader):
    """4-class 12×12×3 synthetic images with class-dependent pattern."""

    def load_data(self):
        rng = numpy.random.default_rng(11)
        n = 160
        labels = (numpy.arange(n) % 4).astype(int)
        x = rng.standard_normal((n, 12, 12, 3)).astype(
            numpy.float32) * 0.3
        for i, lbl in enumerate(labels):
            x[i, lbl * 3:(lbl + 1) * 3, :, :] += 2.0
        self.original_data.mem = x
        self.original_labels = [int(v) for v in labels]
        self.class_lengths[:] = [0, 40, 120]


CONV_LAYERS = [
    {"type": "conv_strict_relu",
     "->": {"n_kernels": 8, "kx": 3, "ky": 3, "padding": 1,
            "weights_filling": "gaussian"},
     "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
    {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
    {"type": "softmax", "->": {"output_sample_shape": 4},
     "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
]


@pytest.mark.parametrize("device_cls", [NumpyDevice, CPUDevice])
def test_conv_workflow_trains(device_cls):
    from veles_tpu import prng
    prng.seed_all(13)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyImageLoader(w, minibatch_size=40),
        layers=[{**s} for s in CONV_LAYERS],
        decision_config={"max_epochs": 6})
    wf.launcher = DummyLauncher()
    wf.initialize(device=device_cls())
    wf.run()
    assert wf.decision.best_n_err_pt < 25.0, \
        "conv net failed to learn striped blobs: %.1f%%" % \
        wf.decision.best_n_err_pt


def test_conv_gd_unit_updates_weights_and_reduces_loss():
    """Drive Conv + GDConv units directly: weights move and the conv
    unit's loss on a fixed batch drops over steps."""
    from veles_tpu import prng
    from veles_tpu.znicz.conv import ConvStrictRELU, GDConvStrictRELU
    prng.seed_all(17)
    wf = DummyWorkflow()
    wf.device = CPUDevice()
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((8, 6, 6, 2)).astype(numpy.float32)
    target = numpy.abs(
        rng.standard_normal((8, 4, 4, 3))).astype(numpy.float32)

    from veles_tpu.memory import Vector
    conv = ConvStrictRELU(wf, n_kernels=3, kx=3, ky=3)
    conv.input = Vector(x)
    conv.initialize(device=wf.device)
    gdc = GDConvStrictRELU(wf, learning_rate=0.3,
                           gradient_moment=0.5)
    gdc.setup_from_forward(conv)
    err_vec = Vector(numpy.zeros_like(target))
    gdc.err_output = err_vec
    gdc.initialize(device=wf.device)

    losses = []
    for _ in range(30):
        conv.run()
        conv.output.map_read()
        err = conv.output.mem - target
        losses.append(float((err ** 2).mean()))
        err_vec.map_write()
        err_vec.mem[...] = 2 * err / err.size * err.shape[0]
        gdc.run()
    assert losses[-1] < losses[0] * 0.9


def test_rprop_rule_semantics():
    """GDRProp implements iRprop−: per-weight steps grow under a stable
    gradient sign, move by sign·delta (not gradient magnitude), and a
    sign flip shrinks the step while SKIPPING the move."""
    from veles_tpu import prng
    from veles_tpu.znicz.gd_base import GDRProp
    from veles_tpu.znicz.misc_units import RPropAll2All

    prng.seed_all(3)
    wf = DummyWorkflow()
    wf.device = CPUDevice()
    fwd = RPropAll2All(wf, output_sample_shape=(3,),
                       include_bias=False)
    x = numpy.ones((2, 4), numpy.float32)
    fwd.input = Vector(x)
    fwd.initialize(device=wf.device)
    gd = GDRProp(wf, rprop_delta_init=0.1, need_err_input=False)
    gd.setup_from_forward(fwd)
    err_vec = Vector(numpy.zeros((2, 3), numpy.float32))
    gd.err_output = err_vec
    gd.initialize(device=wf.device)

    fwd.weights.map_read()
    w0 = numpy.array(fwd.weights.mem)

    def step(err_value):
        fwd.run()
        err_vec.map_write()
        err_vec.mem[...] = err_value
        gd.run()
        fwd.weights.map_read()
        return numpy.array(fwd.weights.mem)

    # constant positive err_output → constant positive dW (x all-ones):
    # step 1 moves by delta_init (prev sign 0: no growth yet)
    w1 = step(1.0)
    numpy.testing.assert_allclose(w0 - w1, 0.1, atol=1e-6)
    # step 2, same sign → delta grew to 0.12
    w2 = step(1.0)
    numpy.testing.assert_allclose(w1 - w2, 0.12, atol=1e-6)
    # step 3, FLIPPED sign → no move, delta halves internally
    w3 = step(-1.0)
    numpy.testing.assert_allclose(w3, w2, atol=1e-7)
    # step 4, negative again (prev sign cleared by the flip) → move
    # UP by the shrunk delta 0.06
    w4 = step(-1.0)
    numpy.testing.assert_allclose(w4 - w3, 0.06, atol=1e-6)


def test_rprop_workflow_trains():
    """StandardWorkflow pairs rprop_all2all with gd_rprop and the
    model actually learns."""
    from veles_tpu import prng
    from veles_tpu.samples import mnist

    prng.seed_all(11)
    # rprop is a (full-)batch method — big minibatches, small delta_0
    # (measured 0.0 % on the synthetic set at this config/seed)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=4, minibatch_size=2000,
        layers=[
            {"type": "rprop_all2all",
             "->": {"output_sample_shape": 64},
             "<-": {"rprop_delta_init": 0.001}},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
        ])
    wf.run()
    results = wf.gather_results()
    assert results["best_validation_error_pt"] < 20.0


def test_fused_eval_skips_only_skip_at_eval_units():
    """Fused eval drops layers via the explicit SKIP_AT_EVAL attribute
    (dropout), NOT by introspecting config keys; stochastic pooling
    (also seeded, no SKIP_AT_EVAL) must still run at eval."""
    from veles_tpu import prng
    from veles_tpu.znicz.fused_graph import lower_specs

    assert DropoutForward.SKIP_AT_EVAL is True
    assert not getattr(StochasticPooling, "SKIP_AT_EVAL", False)

    prng.seed_all(7)
    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "softmax", "->": {"output_sample_shape": 4}},
    ]
    params, _step, _eval, apply_fn = lower_specs(layers, (6,))
    prng.seed_all(7)
    params_nodrop, _s, _e, apply_nodrop = lower_specs(
        [layers[0], layers[2]], (6,))
    x = numpy.random.default_rng(0).standard_normal(
        (3, 6)).astype(numpy.float32)
    out = numpy.asarray(apply_fn(params, x, train=False))
    # same weights (same seed + same init order for the two dense
    # layers), dropout skipped → identical eval output
    ref = numpy.asarray(apply_nodrop(params_nodrop, x, train=False))
    numpy.testing.assert_allclose(out, ref, rtol=1e-6)
    # train=True applies the mask → differs from eval
    out_train = numpy.asarray(apply_fn(params, x, train=True))
    assert not numpy.allclose(out, out_train)


def test_depooling_round_trip_max():
    """Depooling scatters each pooled value back to the exact argmax
    position recorded by the paired pooling unit."""
    from veles_tpu.znicz.pooling import Depooling

    rng = numpy.random.default_rng(3)
    x = rng.standard_normal((2, 4, 4, 3)).astype(numpy.float32)
    wf = DummyWorkflow()
    from veles_tpu.memory import Vector
    pool = MaxPooling(wf, kx=2, ky=2, store_offsets=True)
    pool.input = Vector(x)
    pool.initialize(device=None)
    pool.numpy_run()
    depool = Depooling(wf, kx=2, ky=2)
    depool.input = pool.output
    depool.offsets = pool.output_offsets
    depool.initialize(device=None)
    depool.numpy_run()
    out = depool.output.mem
    assert out.shape == x.shape
    # per window: out holds the max at its original position, 0 elsewhere
    for b in range(2):
        for i in range(2):
            for j in range(2):
                for c in range(3):
                    win_x = x[b, 2*i:2*i+2, 2*j:2*j+2, c]
                    win_o = out[b, 2*i:2*i+2, 2*j:2*j+2, c]
                    assert numpy.count_nonzero(win_o) <= 1
                    pos = numpy.unravel_index(win_x.argmax(),
                                              win_x.shape)
                    assert win_o[pos] == pytest.approx(win_x.max())
                    # all other positions zeroed
                    masked = win_o.copy()
                    masked[pos] = 0.0
                    assert not masked.any()


def test_stochastic_pool_depool_unit_and_grad():
    """Combined pool-depool: input-shaped output, one survivor per
    window, and gradients flow through the combined pure (the unit is
    usable inside fused chains)."""
    import jax
    import jax.numpy as jnp

    from veles_tpu import prng
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.pooling import StochasticPoolingDepooling

    prng.seed_all(5)
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((2, 4, 4, 3)).astype(numpy.float32)
    wf = DummyWorkflow()
    unit = StochasticPoolingDepooling(wf, kx=2, ky=2)
    unit.input = Vector(x)
    unit.initialize(device=None)
    unit.numpy_run()
    out = unit.output.mem
    assert out.shape == x.shape
    for b in range(2):
        for i in range(2):
            for j in range(2):
                for c in range(3):
                    win_o = out[b, 2*i:2*i+2, 2*j:2*j+2, c]
                    win_x = x[b, 2*i:2*i+2, 2*j:2*j+2, c]
                    nz = numpy.flatnonzero(win_o)
                    assert len(nz) <= 1
                    if len(nz):
                        # survivor keeps its original value & position
                        pos = numpy.unravel_index(nz[0], win_o.shape)
                        assert win_o[pos] == pytest.approx(win_x[pos])
    g = jax.grad(lambda a: jnp.sum(
        StochasticPoolingDepooling.pure(
            {"seed": jnp.int32(7)}, a, kx=2, ky=2, sliding=(2, 2),
            kind="stochastic") ** 2))(jnp.asarray(x))
    assert numpy.isfinite(numpy.asarray(g)).all()
    assert numpy.count_nonzero(numpy.asarray(g)) > 0


def test_conv_ae_with_pool_depool_trains():
    """Conv-AE sample (conv → stochastic_pool_depool → deconv) builds a
    fused step and reduces reconstruction loss."""
    import jax.numpy as jnp

    from veles_tpu import prng
    from veles_tpu.znicz.fused_graph import lower_specs
    from veles_tpu.samples.mnist_ae import make_conv_layers

    prng.seed_all(11)
    layers = make_conv_layers(kernels=4, learning_rate=0.05)
    params, step, _eval, _apply = lower_specs(layers, (8, 8, 1),
                                              loss="mse")
    rng = numpy.random.default_rng(11)
    x = rng.standard_normal((16, 8, 8, 1)).astype(numpy.float32)
    losses = []
    for _ in range(12):
        params, m = step(params, x, x)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses


def test_reference_layer_registry_complete():
    """Every layer-type name the reference docs enumerate
    (manualrst_veles_workflow_parameters.rst:467-505) resolves in the
    registry, including the short doc spellings."""
    from veles_tpu.units import UnitRegistry
    from veles_tpu.znicz import misc_units  # noqa: F401

    ref = ["all2all_tanh", "stochastic_abs_pool_depool",
           "all2all_sigmoid", "activation_log", "avg_pooling",
           "depooling", "channel_merger", "deconv",
           "activation_tanhlog", "all2all_str", "activation_relu",
           "maxabs_pooling", "rprop_all2all", "stochastic_pooling",
           "conv_str", "channel_splitter", "activation_str",
           "activation_tanh", "activation_sincos", "dropout", "cutter",
           "conv_sigmoid", "max_pooling", "activation_mul", "conv",
           "softmax", "all2all", "norm", "all2all_relu", "zero_filter",
           "stochastic_abs_pooling", "conv_tanh",
           "stochastic_pool_depool", "activation_sigmoid", "conv_relu"]
    missing = [name for name in ref if name not in UnitRegistry.mapped]
    assert not missing, missing


def test_channel_splitter_merger_roundtrip():
    """Two-tower grouping plumbing: split channels, process towers,
    merge back (ref channel_splitting.*)."""
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.misc_units import ChannelMerger, ChannelSplitter

    rng = numpy.random.default_rng(2)
    x = rng.standard_normal((2, 4, 4, 6)).astype(numpy.float32)
    wf = DummyWorkflow()
    lo = ChannelSplitter(wf, start=0, count=2)
    hi = ChannelSplitter(wf, start=2)
    for unit in (lo, hi):
        unit.input = Vector(x)
        unit.initialize(device=None)
        unit.numpy_run()
    assert lo.output.shape == (2, 4, 4, 2)
    assert hi.output.shape == (2, 4, 4, 4)
    numpy.testing.assert_array_equal(lo.output.mem, x[..., :2])
    merger = ChannelMerger(wf).link_inputs(lo, "output", hi, "output")
    merger.initialize()
    merger.run()
    numpy.testing.assert_array_equal(merger.output.mem, x)
    with pytest.raises(ValueError):
        bad = ChannelSplitter(wf, start=5, count=3)
        bad.input = Vector(x)
        bad.initialize(device=None)


def test_zero_filler_mapped_and_masks():
    from veles_tpu.memory import Vector
    from veles_tpu.units import UnitRegistry
    from veles_tpu.znicz.misc_units import ZeroFiller

    assert UnitRegistry.mapped["zero_filter"] is ZeroFiller
    wf = DummyWorkflow()

    class Holder(object):
        weights = Vector(numpy.ones((3, 3), numpy.float32))

    zf = ZeroFiller(wf, mask=numpy.tril(numpy.ones((3, 3),
                                                   numpy.float32)))
    zf.target_unit = Holder()
    zf.run()
    numpy.testing.assert_array_equal(
        Holder.weights.mem, numpy.tril(numpy.ones((3, 3))))


def test_alias_layer_types_train_via_standard_workflow():
    """Doc-spelling aliases build AND train (GD_PAIRS covers them)."""
    from veles_tpu import prng
    prng.seed_all(23)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyImageLoader(w, minibatch_size=40),
        layers=[
            {"type": "conv_str",
             "->": {"n_kernels": 4, "kx": 3, "ky": 3, "padding": 1},
             "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
            {"type": "norm", "->": {"n": 3}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.02}},
        ],
        decision_config={"max_epochs": 2})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    wf.run()
    assert wf.decision.epoch_n_err_pt[1] < 100.0


def test_resizable_all2all_transposed_resize():
    """resize() preserves rows in (neurons, fan-in) storage when
    weights_transposed is set."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.memory import Vector
    from veles_tpu.znicz.misc_units import ResizableAll2All

    wf = DummyWorkflow()
    u = ResizableAll2All(wf, output_sample_shape=(4,),
                         weights_transposed=True)
    u.input = Vector(numpy.zeros((2, 10), numpy.float32))
    u.initialize(device=None)
    assert u.weights.mem.shape == (4, 10)
    old = numpy.array(u.weights.mem)
    u.resize(6)
    assert u.weights.mem.shape == (6, 10)
    numpy.testing.assert_array_equal(u.weights.mem[:4], old)
    u.resize(3)
    assert u.weights.mem.shape == (3, 10)
    numpy.testing.assert_array_equal(u.weights.mem, old[:3])
