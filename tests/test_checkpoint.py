"""Orbax sharded-checkpoint tests (SURVEY §5.4): save on one mesh
topology, restore on another; PRNG streams and loader cursor ride
along."""

import jax
import jax.numpy as jnp
import numpy
import pytest

from veles_tpu import prng
from veles_tpu.checkpoint import TrainCheckpointer
from veles_tpu.parallel import make_mesh

P = jax.sharding.PartitionSpec


def _state_on_mesh(mesh, spec):
    sharding = jax.sharding.NamedSharding(mesh, spec)
    w = jax.device_put(
        numpy.arange(64, dtype=numpy.float32).reshape(8, 8), sharding)
    return {"w": w, "vw": jax.device_put(
        numpy.zeros((8, 8), numpy.float32), sharding),
        "step_scale": jnp.float32(0.5)}


def test_save_restore_same_mesh(tmp_path):
    mesh = make_mesh({"data": 8})
    state = _state_on_mesh(mesh, P("data", None))
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(3, state, loader_state={"epoch": 2, "offset": 40})
    step, restored, loader = ckpt.restore(state)
    assert step == 3
    assert loader == {"epoch": 2, "offset": 40}
    assert numpy.allclose(numpy.asarray(restored["w"]),
                          numpy.asarray(state["w"]))
    ckpt.close()


def test_restore_on_different_topology(tmp_path):
    """Save sharded over 8 devices, restore sharded over 2 — the
    reference's resume-anywhere property at mesh level."""
    mesh8 = make_mesh({"data": 8})
    state8 = _state_on_mesh(mesh8, P("data", None))
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(1, state8)
    ckpt.close()

    mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
    abstract = {
        "w": jax.ShapeDtypeStruct(
            (8, 8), numpy.float32,
            sharding=jax.sharding.NamedSharding(mesh2, P("data", None))),
        "vw": jax.ShapeDtypeStruct(
            (8, 8), numpy.float32,
            sharding=jax.sharding.NamedSharding(mesh2, P(None, "data"))),
        "step_scale": jax.ShapeDtypeStruct((), numpy.float32),
    }
    ckpt2 = TrainCheckpointer(str(tmp_path / "ckpt"))
    step, restored, _loader = ckpt2.restore(abstract)
    assert step == 1
    assert numpy.allclose(numpy.asarray(restored["w"]),
                          numpy.arange(64).reshape(8, 8))
    # restored onto the NEW sharding
    assert restored["w"].sharding.mesh.shape["data"] == 2
    assert len(restored["w"].sharding.device_set) == 2
    ckpt2.close()


def test_solver_state_roundtrip(tmp_path):
    """Fused Adam/iRprop− solver state (second moments, int32 step
    counter, stacked rprop slots) survives the Orbax checkpoint
    round-trip and training resumes bit-exactly."""
    import jax

    from veles_tpu import prng
    from veles_tpu.checkpoint import TrainCheckpointer
    from veles_tpu.znicz.fused_graph import lower_specs

    prng.seed_all(31)
    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
         "<-": {"solver": "adam", "learning_rate": 0.003}},
        {"type": "softmax", "->": {"output_sample_shape": 3},
         "<-": {"solver": "rprop", "rprop_delta_init": 0.01}},
    ]
    params, step_fn, _e, _a = lower_specs(layers, (6,))
    rng = numpy.random.default_rng(0)
    x = rng.standard_normal((16, 6)).astype(numpy.float32)
    labels = (numpy.arange(16) % 3).astype(numpy.int32)
    for _ in range(3):
        params, _m = step_fn(params, x, labels)

    ckpt = TrainCheckpointer(str(tmp_path / "ck"))
    ckpt.save(3, params)
    _step, restored, _loader = ckpt.restore(params)
    ckpt.close()

    cont_a, _ = step_fn(params, x, labels)
    cont_b, _ = step_fn(restored, x, labels)
    for sa, sb in zip(cont_a, cont_b):
        for key in sa:
            if sa[key] is None:
                continue
            numpy.testing.assert_array_equal(numpy.asarray(sa[key]),
                                             numpy.asarray(sb[key]))


def test_prng_streams_resume(tmp_path):
    prng.seed_all(777)
    drawn_before = prng.get("dropout").randint(0, 1 << 30)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(0, {"x": numpy.zeros(2, numpy.float32)})
    # advance the stream past the checkpoint...
    future = [int(prng.get("dropout").randint(0, 1 << 30))
              for _ in range(3)]
    # ...then clobber it and restore
    prng.seed_all(123)
    _step, _state, _loader = ckpt.restore(
        {"x": numpy.zeros(2, numpy.float32)})
    replay = [int(prng.get("dropout").randint(0, 1 << 30))
              for _ in range(3)]
    assert replay == future        # stream continues where it was saved
    assert drawn_before is not None
    ckpt.close()


def test_latest_and_retention(tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), max_to_keep=2)
    state = {"x": numpy.ones(4, numpy.float32)}
    for step in (1, 2, 3):
        ckpt.save(step, state)
    assert ckpt.latest_step() == 3
    # retention dropped step 1
    with pytest.raises(Exception):
        ckpt.restore(state, step=1)
    ckpt.close()


def test_empty_dir_raises(tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ckpt.restore({"x": numpy.zeros(1, numpy.float32)})
    ckpt.close()


def test_jsonify_roundtrip_typed_dict_keys():
    """Loader/prng states keyed by ints (e.g. class-index offsets) must
    survive the JSON round-trip with key types intact (ADVICE r1)."""
    import json
    from veles_tpu.checkpoint import _dejsonify, _jsonify

    state = {2: [1, 2, 3], 0: (4, 5), "name": {"nested": {7: "x"}},
             (1, 2): "tuple-key"}
    wire = json.loads(json.dumps(_jsonify(state)))
    back = _dejsonify(wire)
    assert back[2] == [1, 2, 3]
    assert back[0] == (4, 5)
    assert back["name"]["nested"][7] == "x"
    assert back[(1, 2)] == "tuple-key"
