"""Device-resident input pipeline (ISSUE 4 acceptance): device-loader ↔
host-loader parity (identical minibatch streams, identical end-of-epoch
metrics incl. confusion matrix, short-final-batch masking), the
loader-headed segment in ``wf.stitch_report()``, zero per-step
``device_put`` on the FullBatch fast path (transfer-intercept fixture
over ``Device.put`` — the Vector/staging upload seam), slave jobs
re-using the resident dataset, and the ``-m slow`` ≥ 1.3× floor over
the host-loader stitched path."""

import time

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.base import TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow


class BlobLoader(FullBatchLoader):
    """Separable 10-class blobs; default sizes leave SHORT final
    batches in both the validation and the train span (100 % 48,
    400 % 48 != 0) so tail masking is always exercised."""

    def __init__(self, workflow, n_train=400, n_valid=100, dim=32,
                 **kwargs):
        self._cfg = (n_train, n_valid, dim)
        self.serve_record = []
        super(BlobLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        n_train, n_valid, dim = self._cfg
        rng = numpy.random.default_rng(42)
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(10), total // 10 + 1)[:total]
        centers = rng.standard_normal((10, dim)) * 3.0
        data = centers[labels] + rng.standard_normal((total, dim)) * 0.7
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels = list(int(x) for x in labels)
        self.class_lengths[:] = [0, n_valid, n_train]

    def serve_next_minibatch(self, consumer, **kwargs):
        super(BlobLoader, self).serve_next_minibatch(consumer, **kwargs)
        self.minibatch_indices.map_read()
        self.serve_record.append((
            int(self.minibatch_class), int(self.minibatch_offset),
            int(self.minibatch_size),
            tuple(int(i) for i in
                  self.minibatch_indices.mem[:self.minibatch_size])))


@pytest.fixture
def loader_mode():
    """Snapshot/restore the engine.loader knob."""
    saved = root.common.engine.get("loader", "auto")

    def set_mode(mode):
        root.common.engine.loader = mode
    yield set_mode
    root.common.engine.loader = saved


def _layers(hidden=32):
    return [
        {"type": "all2all_tanh", "->": {"output_sample_shape": hidden},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]


def _build(device=None, minibatch_size=48, max_epochs=3, seed=5,
           **loader_kw):
    prng.seed_all(seed)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=minibatch_size, **loader_kw),
        layers=_layers(),
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": 10 ** 6})
    wf.launcher = DummyLauncher()
    wf.initialize(device=device or CPUDevice())
    return wf


# -- segment shape ----------------------------------------------------------

def test_loader_heads_first_segment_in_report(loader_mode):
    set_mode = loader_mode
    set_mode("device")
    wf = _build()
    report = wf.stitch_report()
    assert report["segments"][0][0] == wf.loader.name
    assert report["loader_headed"] == [True, False]
    assert wf.loader.device_fast_path_active
    # auto resolves the same way on a jit device
    set_mode("auto")
    assert wf.loader.device_fast_path_active
    # host (and interpret devices) keep the loader a barrier
    set_mode("host")
    assert not wf.loader.device_fast_path_active
    wf_host = _build()
    assert wf_host.stitch_report()["loader_headed"] == [False, False]
    set_mode("auto")
    wf_np = _build(device=NumpyDevice())
    assert not wf_np.loader.device_fast_path_active


def test_store_in_device_memory_off_disables_fast_path(loader_mode):
    loader_mode("device")
    wf = _build(store_in_device_memory=False)
    assert not wf.loader.device_fast_path_active
    wf.run()    # the host path still trains to completion
    assert wf.stopped


# -- gather correctness -----------------------------------------------------

def test_in_program_gather_matches_host_reference(loader_mode):
    """Drive the loader-headed segment for a full epoch-and-a-half and
    verify EVERY dispatch against a host reference gather: values,
    label mapping, short-final-batch zero/-1 masking, epoch-wrap
    reshuffle pickup."""
    loader_mode("device")
    wf = _build(max_epochs=100)
    loader = wf.loader
    seg = wf._stitch_segments_[0]
    assert seg.head is loader
    for _ in range(18):     # > one epoch of ceil(500/48)=11 serves
        seg.execute()
        size = loader.minibatch_size
        start = loader.minibatch_offset - size
        loader.shuffled_indices.map_read()
        idx = numpy.array(loader.shuffled_indices.mem[start:start + size])
        loader.minibatch_data.map_read()
        data = loader.minibatch_data.mem
        loader.original_data.map_read()
        numpy.testing.assert_array_equal(
            data[:size], loader.original_data.mem[idx])
        assert (data[size:] == 0).all()
        loader.minibatch_labels.map_read()
        labels = loader.minibatch_labels.mem
        expect = numpy.asarray(loader._mapped_labels)[idx]
        numpy.testing.assert_array_equal(labels[:size], expect)
        assert (labels[size:] == -1).all()
        # the host index mirror agrees (fill_indices -1 tail included)
        loader.minibatch_indices.map_read()
        numpy.testing.assert_array_equal(
            loader.minibatch_indices.mem[:size], idx)
        assert (loader.minibatch_indices.mem[size:] == -1).all()


# -- parity -----------------------------------------------------------------

def test_device_host_parity_streams_metrics_confusion(loader_mode):
    """Identical minibatch streams (class/offset/size/indices per
    serve), end-of-epoch error metrics and confusion matrix between
    the device fast path and the host loader."""
    loader_mode("device")
    wf_dev = _build()
    wf_dev.run()
    loader_mode("host")
    wf_host = _build()
    wf_host.run()
    assert wf_dev.stopped and wf_host.stopped
    # the device run really went through the loader-headed segment
    assert wf_dev.stitch_report()["loader_headed"][0]
    assert wf_dev._stitch_segments_[0].dispatches == \
        len(wf_dev.loader.serve_record)
    # identical serve streams
    assert wf_dev.loader.serve_record == wf_host.loader.serve_record
    # identical end-of-epoch metrics
    for cls in (1, 2):
        a = wf_dev.decision.epoch_n_err_pt[cls]
        b = wf_host.decision.epoch_n_err_pt[cls]
        assert abs(a - b) < 0.5, (cls, a, b)
    assert abs(wf_dev.decision.best_n_err_pt
               - wf_host.decision.best_n_err_pt) < 0.5
    # identical confusion matrices (device-accumulated vs host-fed)
    cm_dev = numpy.array(wf_dev.evaluator.confusion_matrix.mem)
    cm_host = numpy.array(wf_host.evaluator.confusion_matrix.mem)
    assert cm_dev.sum() == cm_host.sum() > 0
    assert numpy.abs(cm_dev - cm_host).sum() <= 0.02 * cm_dev.sum()
    # and the trained parameters agree
    for f_dev, f_host in zip(wf_dev.forwards, wf_host.forwards):
        f_dev.weights.map_read()
        f_host.weights.map_read()
        numpy.testing.assert_allclose(
            f_dev.weights.mem, f_host.weights.mem, atol=5e-3)


def test_mse_targets_ride_the_device_stage(loader_mode):
    """FullBatchLoaderMSE extends the in-program gather with targets —
    an MSE workflow trains through the loader-headed segment and
    matches the host path."""
    from veles_tpu.loader.fullbatch import FullBatchLoaderMSE

    class SynthMSE(FullBatchLoaderMSE):
        def load_data(self):
            rng = numpy.random.default_rng(3)
            n = 120
            data = rng.standard_normal((n, 12)).astype(numpy.float32)
            self.original_data.mem = data
            self.original_targets.mem = (
                data[:, :4] * 0.5).astype(numpy.float32)
            self.class_lengths[:] = [0, 40, 80]

    def build(mode):
        root.common.engine.loader = mode
        prng.seed_all(7)
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: SynthMSE(w, minibatch_size=32),
            layers=[{"type": "all2all_tanh",
                     "->": {"output_sample_shape": 8},
                     "<-": {"learning_rate": 0.05}},
                    {"type": "all2all",
                     "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.05}}],
            loss_function="mse",
            decision_config={"max_epochs": 3,
                             "fail_iterations": 10 ** 6})
        wf.launcher = DummyLauncher()
        wf.initialize(device=CPUDevice())
        return wf

    loader_mode("device")
    wf_dev = build("device")
    assert wf_dev.stitch_report()["loader_headed"][0]
    assert "minibatch_targets" in [
        name for name, *_rest in wf_dev.loader._device_stage_plan()]
    wf_dev.run()
    wf_host = build("host")
    wf_host.run()
    assert wf_dev.decision.best_mse == pytest.approx(
        wf_host.decision.best_mse, rel=1e-3)


# -- transfer elimination ---------------------------------------------------

@pytest.fixture
def put_counter(monkeypatch):
    """Transfer-intercept fixture: counts every Device.put — the seam
    every Vector upload and staging upload goes through."""
    calls = []
    orig = CPUDevice.put

    def counting(self, array):
        calls.append(int(numpy.asarray(array).nbytes))
        return orig(self, array)

    monkeypatch.setattr(CPUDevice, "put", counting)
    return calls


def test_zero_per_step_device_put_on_fast_path(loader_mode,
                                               put_counter):
    loader_mode("device")
    wf = _build(max_epochs=2)
    wf.run()        # warm: one-time dataset/labels/index/param uploads
    steps_before = len(wf.loader.serve_record)
    puts_before = len(put_counter)
    wf.decision.complete <<= False
    wf.decision.max_epochs = wf.loader.epoch_number + 1 + 3
    wf.run()        # three more warm epochs
    steps = len(wf.loader.serve_record) - steps_before
    puts = len(put_counter) - puts_before
    assert steps >= 30
    # the only allowed uploads are the per-epoch-wrap re-uploads of
    # the (small) shuffled-index buffer — nothing per step
    assert puts <= 4, (puts, steps)


def test_host_loader_pays_per_step_uploads(loader_mode, put_counter):
    """The contrast line for the fixture: the host path uploads at
    least the label buffer every serve."""
    loader_mode("host")
    wf = _build(max_epochs=2)
    wf.run()
    puts_before = len(put_counter)
    steps_before = len(wf.loader.serve_record)
    wf.decision.complete <<= False
    wf.decision.max_epochs = wf.loader.epoch_number + 1 + 1
    wf.run()
    steps = len(wf.loader.serve_record) - steps_before
    puts = len(put_counter) - puts_before
    assert puts >= steps


# -- job layer --------------------------------------------------------------

def _mk_distributed(loader_mode_value, prefetch=False, **flags):
    root.common.engine.loader = loader_mode_value
    prng.seed_all(1234)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(
            w, minibatch_size=50, prefetch=prefetch),
        layers=_layers(),
        decision_config={"max_epochs": 2, "fail_iterations": 10 ** 6},
        launcher=DummyLauncher(**flags))
    device = NumpyDevice() if flags.get("is_master") else CPUDevice()
    wf.initialize(device=device)
    return wf


def test_slave_jobs_reuse_resident_dataset(loader_mode, put_counter):
    """Across a whole multi-job slave session the dataset uploads
    exactly ONCE; per job only weights and the index span move."""
    from veles_tpu.parallel.jobs import JobClient, JobServer

    loader_mode("device")
    master = _mk_distributed("device", is_master=True)
    slave = _mk_distributed("device", is_slave=True)
    assert slave.stitch_report()["loader_headed"][0]
    dataset_nbytes = int(slave.loader.original_data.nbytes)
    server = JobServer(master).start()
    try:
        client = JobClient(slave, server.endpoint)
        client.handshake()
        assert client.run()
        client.close()
    finally:
        server.stop()
    assert client.jobs_done > 3
    dataset_puts = [n for n in put_counter if n == dataset_nbytes]
    assert len(dataset_puts) == 1, dataset_puts
    assert master.decision.best_n_err_pt < 50.0


def test_run_prefetch_stages_next_job_index_span(loader_mode):
    """Under the double-buffered job loop the device-path loader
    stages the NEXT job's index span (merge + background upload) and
    apply_data_from_master installs the staged buffer."""
    from veles_tpu.parallel.jobs import JobClient, JobServer

    loader_mode("device")
    master = _mk_distributed("device", is_master=True)
    slave = _mk_distributed("device", prefetch=True, is_slave=True)
    hits = []
    loader = slave.loader
    orig_apply = type(loader).apply_data_from_master

    def spy_apply(self, data):
        key = (int(data["minibatch_offset"]),
               int(data["minibatch_size"]))
        hits.append(key in self._staged_indices_)
        return orig_apply(self, data)

    type(loader).apply_data_from_master = spy_apply
    server = JobServer(master).start()
    try:
        client = JobClient(slave, server.endpoint)
        client.handshake()
        assert client.run_prefetch()
        client.close()
    finally:
        server.stop()
        type(loader).apply_data_from_master = orig_apply
    assert client.jobs_done > 3
    assert any(hits), "no job consumed a staged index span"
    assert not loader._staged_indices_      # nothing leaked
    assert master.decision.best_n_err_pt < 50.0


# -- throughput floor -------------------------------------------------------

@pytest.mark.slow
def test_devloader_throughput_floor_cpu(loader_mode):
    """In-process CPU JAX, MNIST784-shaped data: the device-resident
    input pipeline must run ≥ 1.3× faster than the PR 3 stitched eager
    path with the host loader (same stitched segments otherwise)."""

    def measure(mode):
        root.common.engine.loader = mode
        wf = _build(minibatch_size=16, max_epochs=2, seed=5,
                    n_train=1280, n_valid=320, dim=784)
        wf.run()                          # warm: compiles included
        wf.decision.complete <<= False
        wf.decision.max_epochs = 8
        tic = time.perf_counter()
        wf.run()                          # six warm epochs
        elapsed = time.perf_counter() - tic
        assert wf.stopped
        return elapsed

    t_dev = measure("device")
    t_host = measure("host")
    assert t_host / t_dev >= 1.3, \
        "devloader %.3fs vs host loader %.3fs (%.2fx < 1.3x floor)" % (
            t_dev, t_host, t_host / t_dev)
