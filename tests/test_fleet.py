"""veles_tpu.fleet — disaggregated prefill/decode serving tests.

THE disaggregated parity gate lives here: a fleet session (prefill
role shipping KV pages over the job wire to decode replicas) must
produce BITWISE identical token streams to a single-engine oracle —
including under an injected page-frame drop + dup, and across a
chaos-timed mid-stream replica drain (prefix replay on a survivor).
The autoscaler's closed loop is proven on a synthetic TTFT-p99 burn
breach, and its hysteresis on a recovering/flapping series.
"""

import threading
import time

import numpy
import pytest

from veles_tpu import chaos, prof
from veles_tpu.chaos import Fault
from veles_tpu.fleet import Fleet, FleetAutoscaler
from veles_tpu.gen import (GenerativeEngine, GenerativeScheduler,
                           TransformerGenModel)
from veles_tpu.samples.transformer import TINY

CFG = dict(TINY, seq_len=64)


def build_engine(seed=7, max_slots=3, num_blocks=19, **kwargs):
    return GenerativeEngine(
        TransformerGenModel(CFG), max_slots=max_slots, max_seq=48,
        prefill_buckets=(8, 16), kv="paged", block_size=8,
        num_blocks=num_blocks, prefill_chunk=8, seed=seed, **kwargs)


def mixed_workload(n=8, seed=0, max_new_lo=6, max_new_hi=12):
    rng = numpy.random.RandomState(seed)
    return [
        (rng.randint(1, CFG["vocab"],
                     size=rng.randint(4, 20)).astype(numpy.int32),
         int(rng.randint(max_new_lo, max_new_hi + 1)))
        for _ in range(n)]


def oracle_streams(workload):
    engine = build_engine()
    engine.warmup()
    scheduler = GenerativeScheduler(engine, name="oracle")
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    scheduler.run_until_idle()
    out = [f.result(0) for f in futures]
    scheduler.stop()
    engine.close()
    return out


@pytest.fixture
def fleet():
    built = Fleet(build_engine, decode_replicas=2, name="t",
                  rpc_timeout_ms=600, heartbeat_interval=0.1,
                  max_queue=64).start()
    yield built
    built.stop(drain=False)
    built.close()
    chaos.controller.disarm()


class TestPageHandoff(object):
    def test_export_adopt_bitwise_parity(self):
        """Engine level: pages exported from one engine and adopted
        into another continue the stream bitwise (same seed, fresh
        BlockPool on the destination)."""
        src = build_engine()
        src.warm_handoff()
        src.warmup()
        dst = build_engine()
        dst.warm_handoff()
        dst.warmup()
        prompt = numpy.arange(1, 12, dtype=numpy.int32)

        sched = GenerativeScheduler(src, name="src")
        want = sched.generate(prompt, 10)
        from veles_tpu.gen.scheduler import GenRequest
        job = GenRequest(prompt, 1, export_pages=True)
        sched.submit_request(job)
        sched.run_until_idle()
        payload = job.export
        assert payload is not None
        assert payload["token"] == want[0]
        assert len(payload["k"]) == src._pool.blocks_for(len(prompt))

        slot, token = dst.adopt_sequence(payload)
        got = [token]
        while len(got) < 10:
            tokens, active = dst.decode_step()
            assert active[slot]
            got.append(int(tokens[slot]))
        assert got == want
        sched.stop()
        src.close()
        dst.close()

    def test_fleet_parity_under_page_drop_and_dup(self, fleet):
        """The tier-1 disaggregated gate: fleet streams == oracle
        streams with a page frame DROPPED (exactly-once retry) and a
        page frame DUPLICATED (dedup) on the wire."""
        workload = mixed_workload(n=8, seed=3)
        expected = oracle_streams(workload)
        chaos.controller.arm([
            Fault(site="master_recv", action="drop", op="page", nth=1),
            Fault(site="slave_send", action="dup", op="page", nth=3),
        ], seed=3)
        before = prof.ledger.recompiles
        futures = [fleet.submit(toks, max_new)
                   for toks, max_new in workload]
        results = [f.result(timeout=120.0) for f in futures]
        assert results == expected
        assert fleet.handoffs_total == len(workload)
        # the dup really crossed the wire and was consumed exactly once
        assert chaos.controller.faults_injected >= 2
        assert fleet._master.dedup_dropped >= 1
        assert prof.ledger.recompiles == before

    def test_job_frame_loss_requeues_prompt(self, fleet):
        """A job frame lost master->slave must requeue the prompt
        (have-list / rejoin machinery) and still resolve it."""
        workload = mixed_workload(n=4, seed=5)
        expected = oracle_streams(workload)
        chaos.controller.arm([
            Fault(site="master_send", action="drop", op="job", nth=2),
        ], seed=5)
        futures = [fleet.submit(toks, max_new)
                   for toks, max_new in workload]
        results = [f.result(timeout=120.0) for f in futures]
        assert results == expected
        assert fleet.requeued_total >= 1

    def test_adoption_respects_pool_pricing(self, fleet):
        """More concurrent streams than one replica's pool can hold:
        the handoff admission lane must defer, not fail, and every
        stream still resolves with parity."""
        workload = mixed_workload(n=10, seed=11, max_new_lo=8,
                                  max_new_hi=14)
        expected = oracle_streams(workload)
        futures = [fleet.submit(toks, max_new)
                   for toks, max_new in workload]
        results = [f.result(timeout=120.0) for f in futures]
        assert results == expected


class TestElasticity(object):
    def test_drain_midstream_is_lossless(self, fleet):
        """Chaos-timed scale-down: drain a replica while its streams
        are mid-decode; every stream replays onto the survivor and
        finishes bitwise-identical, zero steady recompiles."""
        workload = mixed_workload(n=6, seed=9, max_new_lo=24,
                                  max_new_hi=32)
        expected = oracle_streams(workload)
        before = prof.ledger.recompiles
        futures = [fleet.submit(toks, max_new)
                   for toks, max_new in workload]
        # wait until decode replicas actually hold streams, then yank
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if any(s.active_requests()
                   for s in fleet.router.engines()):
                break
            time.sleep(0.005)
        replayed = fleet.drain_replica()
        results = [f.result(timeout=120.0) for f in futures]
        assert results == expected
        assert fleet.drains_total == 1
        assert len(fleet.router) == 1
        assert fleet.replayed_total == replayed
        assert prof.ledger.recompiles == before

    def test_shared_prefix_streams_survive_drain(self):
        """PR 19 interaction: with the radix cache on, co-resident
        shared-prefix streams hold refcounted pages — export must not
        ship a page another slot still references, adoption must
        copy-on-adopt only the unshared tail, and a mid-decode drain
        replays everything onto the survivor bitwise-intact."""
        stem = [(i * 5 + 2) % CFG["vocab"] for i in range(13)]
        workload = [
            (numpy.asarray(stem + [30 + i], numpy.int32), 24)
            for i in range(4)]
        expected = oracle_streams(workload)
        cached = Fleet(
            lambda: build_engine(prefix_cache="on"),
            decode_replicas=2, name="px", rpc_timeout_ms=600,
            heartbeat_interval=0.1, max_queue=64).start()
        try:
            futures = [cached.submit(toks, max_new)
                       for toks, max_new in workload]
            deadline = time.time() + 30.0
            while time.time() < deadline:
                if any(s.active_requests()
                       for s in cached.router.engines()):
                    break
                time.sleep(0.005)
            cached.drain_replica()
            results = [f.result(timeout=120.0) for f in futures]
            assert results == expected
            assert len(cached.router) == 1
            # the survivor really shares: every replayed stream
            # re-derives the same stem, adopted copy-on-write
            survivor = cached.router.engines()[0].engine
            assert survivor.prefix_shared_pages_total >= 1
            assert cached.handoffs_total >= len(workload)
        finally:
            cached.stop(drain=False)
            cached.close()

    def test_chaos_replica_drain_via_tick(self, fleet):
        """The chaos ``replica_drain`` process action drives the same
        drain through ``Fleet.tick`` — and refuses to fire the fleet
        down to zero replicas."""
        chaos.controller.arm([
            Fault(site="fleet_decode", action="replica_drain",
                  every=1),
        ], seed=1)
        assert fleet.tick() == "chaos_drain"
        assert len(fleet.router) == 1
        # a second fault fires but the last replica is never drained
        assert fleet.tick() != "chaos_drain"
        assert len(fleet.router) == 1

    def test_drain_refuses_last_replica(self, fleet):
        fleet.drain_replica()
        with pytest.raises(ValueError):
            fleet.drain_replica()

    def test_add_replica_grows_and_serves(self, fleet):
        """Scale-up: a freshly warmed replica joins the router and
        the fleet keeps its parity contract (growth compiles are
        pre-steady, so the recompile gate stays green)."""
        workload = mixed_workload(n=4, seed=13)
        expected = oracle_streams(workload)
        version = fleet.add_replica()
        assert len(fleet.router) == 3
        before = prof.ledger.recompiles
        futures = [fleet.submit(toks, max_new)
                   for toks, max_new in workload]
        results = [f.result(timeout=120.0) for f in futures]
        assert results == expected
        assert prof.ledger.recompiles == before
        assert version in [m["version"]
                           for m in fleet.router.describe()]

    def test_spill_serves_on_prefill_role(self, fleet):
        """Spill credits route admissions end to end through the
        prefill role — same tokens, zero page handoffs for them."""
        workload = mixed_workload(n=3, seed=17)
        expected = oracle_streams(workload)
        fleet.spill(len(workload))
        futures = [fleet.submit(toks, max_new)
                   for toks, max_new in workload]
        results = [f.result(timeout=120.0) for f in futures]
        assert results == expected
        assert fleet.spilled_total == len(workload)
        assert fleet.handoffs_total == 0


class _FleetStub(object):
    """Action recorder standing in for a Fleet (the autoscaler only
    touches this surface)."""

    class _Router(object):
        def __init__(self, stub, n):
            self._stub = stub
            self.n = n

        def __len__(self):
            return self.n

        def engines(self):
            class _E(object):
                class engine(object):
                    free_slots = 2
            return [_E() for _ in range(self.n)]

    def __init__(self, n=2):
        self.router = self._Router(self, n)
        self.actions = []

    def set_weights(self, weights):
        self.actions.append(("weight_shift", list(weights)))

    def spill(self, n):
        self.actions.append(("spill", n))

    def add_replica(self, weight=1.0):
        self.router.n += 1
        self.actions.append(("grow", None))

    def drain_replica(self, version=None):
        self.router.n -= 1
        self.actions.append(("shrink", None))
        return 0


class _ScriptedSLO(object):
    """Deterministic signal source for hysteresis tests."""

    def __init__(self, series):
        self.series = list(series)
        self.i = 0

    def autoscaling_signals(self, now=None):
        burn = self.series[min(self.i, len(self.series) - 1)]
        self.i += 1
        return {"queue_depth": 0.0, "batch_fill": 0.5,
                "ttft_p99_burn_rate": burn}


class TestAutoscaler(object):
    def _scaler(self, series, n=2, **knobs):
        stub = _FleetStub(n)
        knobs.setdefault("breach_ticks", 2)
        knobs.setdefault("recover_ticks", 3)
        knobs.setdefault("cooldown_s", 5.0)
        scaler = FleetAutoscaler(stub, _ScriptedSLO(series), **knobs)
        return stub, scaler

    def test_breach_must_hold_before_acting(self):
        """One breached tick is noise; ``breach_ticks`` consecutive
        breaches act — and the first rung is the weight shift."""
        stub, scaler = self._scaler([5.0, 0.0, 5.0, 5.0])
        t = 100.0
        assert scaler.tick(now=t) is None          # breach #1
        assert scaler.tick(now=t + 1) is None      # healthy resets
        assert scaler.tick(now=t + 2) is None      # breach #1 again
        assert scaler.tick(now=t + 3) == "weight_shift"
        assert stub.actions == [("weight_shift", [3.0, 3.0])]

    def test_escalation_ladder_and_cooldown(self):
        """Sustained breach climbs weight_shift -> spill -> grow, one
        rung per cooldown window; inside the window the scaler only
        observes."""
        stub, scaler = self._scaler([5.0] * 20, cooldown_s=10.0,
                                    max_decode=3)
        t = 100.0
        assert scaler.tick(now=t) is None
        assert scaler.tick(now=t + 1) == "weight_shift"
        # cooldown: breaches keep arriving, nothing fires
        assert scaler.tick(now=t + 2) is None
        assert scaler.tick(now=t + 5) is None
        assert scaler.tick(now=t + 12) == "spill"  # window over
        assert scaler.tick(now=t + 13) is None
        assert scaler.tick(now=t + 24) == "grow"
        assert [a for a, _ in stub.actions] == \
            ["weight_shift", "spill", "grow"]

    def test_recovery_shrinks_after_sustained_health(self):
        stub, scaler = self._scaler([5.0, 5.0] + [0.0] * 10,
                                    cooldown_s=1.0)
        t = 100.0
        scaler.tick(now=t)
        assert scaler.tick(now=t + 1) == "weight_shift"
        got = [scaler.tick(now=t + 2 + i) for i in range(6)]
        assert "shrink" in got
        assert got.index("shrink") >= scaler.recover_ticks - 1
        assert stub.router.n == 1
        # at min_decode: sustained health never drains the last one
        assert all(scaler.tick(now=t + 20 + i) is None
                   for i in range(5))
        assert stub.router.n == 1

    def test_flapping_series_never_acts(self):
        """The hysteresis contract: a breach/recover square wave
        (period below both windows) takes ZERO actions."""
        stub, scaler = self._scaler([5.0, 0.0] * 20)
        for i in range(40):
            assert scaler.tick(now=100.0 + i) is None
        assert stub.actions == []
        assert scaler.ticks_total == 40

    def test_closed_loop_on_real_fleet(self, fleet):
        """End to end: a synthetic TTFT-p99 burn breach through the
        REAL SLO engine makes the REAL fleet shift weights, with the
        action visible on the scrape."""
        now = time.time() + 60.0
        ring = fleet.slo.ring("ttft_p99_ms")
        for i in range(30):
            ring.append(900.0, t=now - 3.0 + i * 0.1)
        actions = [fleet.tick(now=now + i * 0.5)
                   for i in range(fleet.autoscaler.breach_ticks)]
        assert actions[-1] == "weight_shift"
        text = fleet.slo.metrics_text(now=now + 2.0)
        assert 'veles_fleet_autoscaler_actions_total' \
            '{action="weight_shift"} 1' in text
        assert "veles_fleet_handoffs_total" in text


class TestRegistryIntegration(object):
    def test_deploy_fleet_serves_and_undeploys(self, fleet):
        from veles_tpu.serve.registry import ModelRegistry
        registry = ModelRegistry()
        registry.deploy_fleet("disagg", fleet)
        desc = registry.describe()["disagg"]
        assert desc["disaggregated"] is True
        workload = mixed_workload(n=2, seed=21)
        expected = oracle_streams(workload)
        got = [registry.generate("disagg", toks, max_new)
               for toks, max_new in workload]
        assert got == expected
        with pytest.raises(ValueError):
            registry.deploy_fleet("disagg", fleet)
        registry.undeploy("disagg", drain=True)
