"""veles_tpu.analyze tests: graph doctor rules, JAX hazard pass (with
a zero-XLA-compile gate), lint pack self-cleanliness over veles_tpu/
itself, the CLI, and the serve registry pre-flight."""

import json
import textwrap

import numpy
import pytest

from veles_tpu.analyze import (
    PreflightError, analyze_workflow, check_graph, check_shapes,
    lint_paths, rule_catalog)
from veles_tpu.analyze.findings import SEVERITIES, Finding, Report
from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.plumbing import Repeater
from veles_tpu.samples.analyze_demo import create_workflow
from veles_tpu.units import Unit


def rules_of(findings):
    return {f.rule for f in findings}


# -- findings / report ------------------------------------------------------

def test_report_orders_errors_first_and_counts():
    report = Report([
        Finding("info", "V-G06", "c"),
        Finding("error", "V-G01", "a"),
        Finding("warning", "V-J02", "b"),
    ], passes=["graph"])
    assert [f.severity for f in report.sorted()] == list(SEVERITIES)
    assert report.has_errors
    assert report.counts() == {"error": 1, "warning": 1, "info": 1}
    data = json.loads(report.to_json())
    assert data["rules"] == ["V-G01", "V-G06", "V-J02"]


def test_rule_catalog_covers_all_passes():
    catalog = rule_catalog()
    for prefix in ("V-G", "V-J", "V-L"):
        assert any(rule.startswith(prefix) for rule in catalog), prefix
    for rule_id, (severity, desc) in catalog.items():
        assert severity in SEVERITIES
        assert desc


# -- pass 1: graph doctor ---------------------------------------------------

def _clean_workflow():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    a.link_from(wf.start_point)
    wf.end_point.link_from(a)
    return wf, a


def test_doctor_clean_graph_has_no_findings():
    wf, _a = _clean_workflow()
    assert check_graph(wf) == []


def test_doctor_dangling_demand():
    wf, a = _clean_workflow()
    a.demand("minibatch_data")
    findings = check_graph(wf)
    assert "V-G01" in rules_of(findings)
    # linking the demand satisfies the rule even before values flow
    producer = DummyUnit(wf, name="producer")
    producer.link_from(wf.start_point)
    producer.minibatch_data = None
    a.link_attrs(producer, "minibatch_data")
    assert "V-G01" not in rules_of(check_graph(wf))


def test_doctor_unreachable_and_payload_fragility():
    wf, _a = _clean_workflow()
    DummyUnit(wf, name="stray")
    rules = rules_of(check_graph(wf))
    assert "V-G02" in rules
    assert "V-G06" in rules


def test_doctor_gate_deadlock_on_dead_edge():
    wf, a = _clean_workflow()
    ghost = DummyUnit(wf, name="ghost")
    a.link_from(ghost)
    findings = [f for f in check_graph(wf) if f.rule == "V-G03"]
    assert findings and findings[0].unit == "a"


def test_doctor_cycle_without_repeater():
    wf, a = _clean_workflow()
    b = DummyUnit(wf, name="b")
    b.link_from(a)
    a.link_from(b)
    assert "V-G04" in rules_of(check_graph(wf))


def test_doctor_repeater_anchored_cycle_is_legal():
    wf = DummyWorkflow()
    rpt = Repeater(wf, name="rpt")
    body = DummyUnit(wf, name="body")
    rpt.link_from(wf.start_point)
    body.link_from(rpt)
    rpt.link_from(body)
    wf.end_point.link_from(body)
    assert "V-G04" not in rules_of(check_graph(wf))


def test_doctor_unlinked_end_point():
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    a.link_from(wf.start_point)
    findings = [f for f in check_graph(wf) if f.rule == "V-G05"]
    assert len(findings) == 1


def test_unit_introspection_hooks():
    wf, a = _clean_workflow()
    a.demand("labels")
    assert a.unlinked_demands() == ["labels"]
    a.labels = numpy.zeros(3)
    assert a.unlinked_demands() == []
    topo = a.gate_topology()
    assert topo["incoming"] == ["Start"]
    assert not topo["ignores_gate"]


# -- pass 2: JAX hazards ----------------------------------------------------

def test_shapes_demo_rules_with_zero_compiles():
    """The acceptance gate: analyzing the broken demo reports the full
    hazard set via jax.eval_shape with ZERO XLA compiles."""
    import jax
    compiles = []
    try:
        from jax import monitoring
        # abstract tracing (jaxpr_trace) is fine — eval_shape traces;
        # backend_compile is the XLA compile the gate forbids
        monitoring.register_event_duration_secs_listener(
            lambda event, duration, **kw: compiles.append(event)
            if "backend_compile" in event else None)
        probe_armed = True
    except Exception:   # monitoring API moved/missing: skip the probe
        probe_armed = False

    wf = create_workflow()
    before = len(compiles)
    report = analyze_workflow(wf)
    assert len(compiles) == before, \
        "static analysis must not compile: %s" % compiles[before:]
    rules = set(report.rules())
    assert {"V-G01", "V-G02", "V-G03", "V-G04", "V-G05",
            "V-J01", "V-J02", "V-J03", "V-J04", "V-J05"} <= rules
    assert report.has_errors

    if probe_armed:
        # prove the probe detects compiles at all
        jax.jit(lambda x: x + 1)(numpy.ones((4,), numpy.float32))
        assert len(compiles) > before


def test_shapes_clean_chain_from_specs():
    wf = DummyWorkflow()
    wf.layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
    ]
    findings = check_shapes(wf, sample_shape=(12,), batch_size=32)
    assert not [f for f in findings if f.severity == "error"], \
        [f.render() for f in findings]


def test_shapes_broken_spec_flagged():
    wf = DummyWorkflow()
    wf.layers = [{"type": "conv",
                  "->": {"n_kernels": 2, "kx": 9, "ky": 9}}]
    findings = check_shapes(wf, sample_shape=(4, 4, 1), batch_size=32)
    assert "V-J01" in rules_of(findings)


def test_shapes_transfer_hazard_on_named_receivers():
    """V-J05 must catch the documented forms on named receivers, not
    just numpy.asarray: .block_until_ready() / .item() syncs too."""
    from veles_tpu.analyze.shapes import scan_transfer_hazards

    class SyncHappyUnit(Unit):
        hide_from_registry = True

        def run(self):
            self.output.block_until_ready()
            return self.loss.item()

    wf = DummyWorkflow()
    unit = SyncHappyUnit(wf, name="sync_happy")
    findings = scan_transfer_hazards(unit)
    assert len(findings) == 2
    assert rules_of(findings) == {"V-J05"}


def test_shapes_transfer_hazard_resolves_import_aliases(tmp_path):
    """`import numpy as onp; onp.asarray(...)` is the same hazard —
    the scan resolves module-level import aliases."""
    import importlib.util
    mod_file = tmp_path / "aliased_unit.py"
    mod_file.write_text(textwrap.dedent("""\
        import numpy as onp
        from veles_tpu.units import Unit


        class AliasedSyncUnit(Unit):
            hide_from_registry = True

            def run(self):
                self.output = onp.asarray(self.output)
    """))
    spec = importlib.util.spec_from_file_location("aliased_unit",
                                                  str(mod_file))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    from veles_tpu.analyze.shapes import scan_transfer_hazards
    wf = DummyWorkflow()
    unit = module.AliasedSyncUnit(wf, name="aliased")
    findings = scan_transfer_hazards(unit)
    assert rules_of(findings) == {"V-J05"}, \
        [f.render() for f in findings]


def test_shapes_batch_bucket_fit():
    wf = DummyWorkflow()
    findings = check_shapes(wf, sample_shape=(8,), batch_size=48)
    assert "V-J04" in rules_of(findings)
    findings = check_shapes(wf, sample_shape=(8,), batch_size=64)
    assert "V-J04" not in rules_of(findings)


def test_shapes_map_read_hot_loop_rule():
    """V-J06: per-minibatch map_read()/map_write() Vector round-trips
    are flagged in run()/tpu_run() of hot-loop units ONLY — numpy_run
    is the declared interpret path, and a unit off the hot loop keeps
    the plain V-J05 scan."""
    from veles_tpu.analyze.shapes import scan_transfer_hazards

    class CoherenceHappyUnit(Unit):
        hide_from_registry = True

        def run(self):
            self.output.map_read()
            self.weights.map_write()

        def numpy_run(self):
            self.output.map_read()      # legitimate: debug path

    wf = DummyWorkflow()
    unit = CoherenceHappyUnit(wf, name="coherence_happy")
    hot = scan_transfer_hazards(unit, hot_loop=True)
    assert rules_of(hot) == {"V-J06"}
    assert len(hot) == 2                # run() only, not numpy_run()
    assert not scan_transfer_hazards(unit)   # off the hot loop: clean


def test_shapes_hot_loop_scan_covers_evaluator_and_gds():
    """check_shapes scans the whole train hot loop (forwards +
    evaluator + gd chain) — and the ported device-resident evaluators
    leave a real eager workflow V-J06-clean."""
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8),
        layers=[{"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J06" not in rules_of(findings), \
        [f.render() for f in findings]

    # a host-syncing unit planted on the gd chain IS flagged
    class HostyGD(Unit):
        hide_from_registry = True

        def run(self):
            self.err_output.map_read()

    wf.gds.append(HostyGD(wf, name="hosty"))
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J06" in rules_of(findings)


def test_v_j07_device_put_in_hot_loop_run():
    """V-J07 (b): explicit H2D transfers — jax.device_put or
    <device>.put — inside hot-loop run()/tpu_run() bodies are flagged;
    off the hot loop (and in numpy_run) they are not."""
    from veles_tpu.analyze.shapes import scan_transfer_hazards

    class UploadHappyUnit(Unit):
        hide_from_registry = True

        def run(self):
            import jax
            self.batch = jax.device_put(self.batch)

        def tpu_run(self):
            self.batch = self.device.put(self.batch)

        def numpy_run(self):
            import jax
            self.batch = jax.device_put(self.batch)   # debug path

    wf = DummyWorkflow()
    unit = UploadHappyUnit(wf, name="upload_happy")
    hot = scan_transfer_hazards(unit, hot_loop=True)
    assert rules_of(hot) == {"V-J07"}
    assert len(hot) == 2                 # run + tpu_run, not numpy_run
    assert not scan_transfer_hazards(unit)   # off the hot loop: clean


def _v_j07_workflow(device, loader_mode, **loader_kw):
    from veles_tpu.backends import CPUDevice, NumpyDevice
    from veles_tpu.config import root
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    root.common.engine.loader = loader_mode
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8,
                                            **loader_kw),
        layers=[{"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice() if device == "cpu"
                  else NumpyDevice())
    return wf


def test_v_j07_host_filling_fullbatch_loader_flagged():
    """V-J07 (a): an initialized FullBatch loader serving host-side on
    a jit device is flagged; the engaged device fast path (auto) and
    interpret devices stay quiet."""
    from veles_tpu.config import root
    saved = root.common.engine.get("loader", "auto")
    try:
        wf = _v_j07_workflow("cpu", "host")
        findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
        flagged = [f for f in findings if f.rule == "V-J07"]
        assert flagged and flagged[0].unit == wf.loader.name

        root.common.engine.loader = "auto"      # fast path engages
        assert wf.loader.device_fast_path_active
        findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
        assert "V-J07" not in rules_of(findings)

        wf_np = _v_j07_workflow("numpy", "host")   # interpret: quiet
        findings = check_shapes(wf_np, sample_shape=(8,), batch_size=8)
        assert "V-J07" not in rules_of(findings)

        # structurally ineligible (dataset not resident): flipping the
        # config could not engage the path — no misleading warning
        wf_big = _v_j07_workflow("cpu", "auto",
                                 store_in_device_memory=False)
        findings = check_shapes(wf_big, sample_shape=(8,), batch_size=8)
        assert "V-J07" not in rules_of(findings)
    finally:
        root.common.engine.loader = saved


# -- pass 3: lint pack ------------------------------------------------------

def test_lint_self_clean_tier1():
    """veles_tpu/ must stay clean under its own lint pack (the
    satellite fix replaced FireStarter/Repeater private reach-ins
    with Unit.reset_gate)."""
    findings = lint_paths()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_lint_rules_fire(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import time
        import urllib.request
        from time import sleep as zzz
        from veles_tpu.units import Unit


        class SleepyUnit(Unit):
            def run(self):
                zzz(1.0)
                urllib.request.urlopen("http://x")


        class ThreadedUnit(Unit):
            wants_thread = True

            def run(self):
                time.sleep(1.0)


        def meddle(a, b):
            b._gate_lock_.acquire()
            b.links_from[a] = True
            b.links_to.clear()
            b.gate_block = True
            b.gate_skip = False  # analyze: ignore[V-L03]
    """))
    findings = lint_paths([str(tmp_path)])
    rules = rules_of(findings)
    assert rules == {"V-L01", "V-L02", "V-L03", "V-L04"}
    # both blocking forms caught: aliased sleep AND dotted urlopen
    assert len([f for f in findings if f.rule == "V-L01"]) == 2
    # wants_thread opt-in exempts; suppression comment honored
    assert not [f for f in findings if f.unit == "ThreadedUnit"]
    assert len([f for f in findings if f.rule == "V-L03"]) == 1
    # the CLI gate is strict: ANY lint finding exits dirty even
    # though the rules are warning-severity
    from veles_tpu.analyze.__main__ import main
    assert main(["--lint", str(tmp_path)]) == 1


# -- CLI --------------------------------------------------------------------

def test_cli_demo_reports_required_rules(capsys):
    from veles_tpu.analyze.__main__ import main
    rc = main(["veles_tpu.samples.analyze_demo", "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert {"V-G01", "V-G02", "V-G03", "V-G04",
            "V-J01", "V-J05"} <= set(data["rules"])
    assert data["counts"]["error"] >= 4


def test_cli_lint_and_rules(capsys):
    from veles_tpu.analyze.__main__ import main
    assert main(["--lint"]) == 0
    assert "analyze: clean" in capsys.readouterr().out
    assert main(["--rules"]) == 0
    out = capsys.readouterr().out
    assert "V-G01" in out and "V-L01" in out
    assert main([]) == 2


# -- serve pre-flight -------------------------------------------------------

@pytest.fixture
def preflight_mode():
    from veles_tpu.config import root
    saved = root.common.serve.get("preflight", None)

    def set_mode(mode):
        root.common.serve.preflight = mode
    yield set_mode
    if saved is None:
        root.common.serve.__dict__.pop("preflight", None)
    else:
        root.common.serve.preflight = saved


def test_registry_preflight_modes(preflight_mode):
    from veles_tpu.serve.registry import ModelRegistry
    registry = ModelRegistry()
    wf = create_workflow()

    preflight_mode("warn")
    report = registry.preflight(wf, "demo")
    assert report.has_errors    # logged, not raised

    preflight_mode("fail")
    with pytest.raises(PreflightError) as excinfo:
        registry.load_workflow("demo", wf)
    assert excinfo.value.report.errors()
    assert "demo" not in registry

    preflight_mode("off")
    assert registry.preflight(wf, "demo") is None

    preflight_mode("strict")    # typo'd mode must not deploy-anyway
    with pytest.raises(ValueError, match="preflight"):
        registry.preflight(wf, "demo")


def test_registry_preflight_passes_clean_workflow(preflight_mode):
    from veles_tpu.serve.registry import ModelRegistry
    preflight_mode("fail")
    wf, _a = _clean_workflow()
    report = ModelRegistry().preflight(wf, "clean")
    assert not report.has_errors


# -- launcher integration ---------------------------------------------------

def test_main_analyze_flag(capsys):
    from veles_tpu.__main__ import Main
    rc = Main(["--no-logo", "veles_tpu.samples.analyze_demo",
               "--analyze"]).run()
    assert rc == 1
    out = capsys.readouterr().out
    assert "V-G05" in out and "V-J01" in out


# -- V-J08: blocking host syncs on the hot loop -----------------------------

def test_v_j08_blocking_sync_on_hot_loop():
    """V-J08: the unconditionally-blocking syncs — jax.device_get,
    .block_until_ready()/.item(), and float()/int() casts of jnp
    expressions — escalate from the generic V-J05 on hot-loop
    run()/tpu_run() bodies; host math (shape reads, python ints) and
    numpy_run stay quiet, and OFF the hot loop the calls keep their
    plain V-J05 classification."""
    from veles_tpu.analyze.shapes import scan_transfer_hazards

    class BlockyUnit(Unit):
        hide_from_registry = True

        def run(self):
            import jax
            self.loss_host = jax.device_get(self.loss)
            self.err_output.devmem.block_until_ready()

        def tpu_run(self):
            import jax.numpy as jnp
            self.mse = float(jnp.sqrt(self.acc))       # device scalar
            self.n = int(self.output.devmem.sum())     # device scalar
            # deferred-metrics-compatible host math stays clean:
            self.scale = float(self.err_output.shape[0])
            self.batch = int(self.batch_size)

        def numpy_run(self):
            import jax
            return jax.device_get(self.loss)     # debug path: unscanned

    wf = DummyWorkflow()
    unit = BlockyUnit(wf, name="blocky")
    hot = scan_transfer_hazards(unit, hot_loop=True)
    assert rules_of(hot) == {"V-J08"}, [f.render() for f in hot]
    assert len(hot) == 4
    off = scan_transfer_hazards(unit)
    assert rules_of(off) == {"V-J05"}, [f.render() for f in off]
    assert len(off) == 2      # the float()/int() casts are hot-loop-only


def test_v_j08_in_catalog_and_hot_scan_keeps_standard_units_clean():
    """The rule is in the catalog (--rules), and the device-resident
    evaluators' legitimate float(shape)/int(batch_size) host math does
    not trip it — a real eager workflow stays V-J08-clean."""
    assert "V-J08" in rule_catalog()

    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8),
        layers=[{"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J08" not in rules_of(findings), \
        [f.render() for f in findings]


# -- V-J09: retrace hazards on the hot loop ---------------------------------

# a module-level jitted callable WITH static declarations: the V-J09
# call-site scan resolves its static_argnames from this module's AST
import functools  # noqa: E402

import jax  # noqa: E402


def _windowed(x, window=2, scale=1.0):
    return x * scale + window


_windowed_jit = jax.jit(_windowed, static_argnames=("window",))
_windowed_partial = functools.partial(
    jax.jit, static_argnames=("window",))(_windowed)


def test_v_j09_retrace_hazards_on_hot_loop():
    """V-J09: a jax.jit wrapper built per run() call (its compile
    cache dies with the call) and static-declared kwargs fed
    unhashable literals or per-call-computed values; the memoized
    build-once idiom and bare self.attr static config stay quiet."""
    from veles_tpu.analyze.shapes import scan_retrace_hazards

    class RetraceUnit(Unit):
        hide_from_registry = True

        def run(self):
            step = jax.jit(lambda x: x * self.scale)   # fresh per call
            self.out = step(self.data)
            # storing the RESULT on self does not memoize the wrapper
            self.out2 = jax.jit(lambda x: x + self.k)(self.data)

        def tpu_run(self):
            # varying static: computed per call → retrace per value
            self.out = _windowed_jit(self.data,
                                     window=int(self.epoch))
            # unhashable static: trace-time failure / retrace
            self.out = _windowed_partial(self.data, window=[2, 3])

    class CleanUnit(Unit):
        hide_from_registry = True

        def initialize(self, **kwargs):
            pass

        def run(self):
            if getattr(self, "_step_", None) is None:
                # memoized onto self: built once, cache survives
                self._step_ = jax.jit(lambda x: x + 1)
            self.out = self._step_(self.data)

        def tpu_run(self):
            # bare self.attr static config is THE stable idiom
            # (activation/conv units); starred **config is not
            # inspected either
            self.out = _windowed_jit(self.data, window=self.window)
            self.out = _windowed_jit(self.data, scale=float(self.k))

    wf = DummyWorkflow()
    hot = scan_retrace_hazards(RetraceUnit(wf, name="retrace"))
    assert rules_of(hot) == {"V-J09"}, [f.render() for f in hot]
    assert len(hot) == 4
    messages = " | ".join(f.message for f in hot)
    assert "jax.jit wrapper per call" in messages
    assert "computed per call" in messages
    assert "unhashable list literal" in messages
    assert all(f.location for f in hot)
    clean = scan_retrace_hazards(CleanUnit(wf, name="clean"))
    assert clean == [], [f.render() for f in clean]


def test_v_j09_in_catalog_and_real_workflows_stay_clean():
    """The rule is in --rules, check_shapes wires it over the hot
    chain + loader, and the standard znicz units (pure(**config)
    forwarding, module-level jit) stay V-J09-silent."""
    assert "V-J09" in rule_catalog()

    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J09" not in rules_of(findings), \
        [f.render() for f in findings]


# -- V-J10: host-sync hazards under an epoch-scan window --------------------

def test_v_j10_stitch_stage_host_sync_flagged():
    """V-J10: io_callback / jax.debug.print / device_get / .item()
    inside a stitch_stage body would serialize (or break) the K-step
    scan window; the pure-stage idiom stays silent."""
    from veles_tpu.analyze.shapes import scan_epoch_scan_hazards

    class CallbackStage(Unit):
        hide_from_registry = True

        def stitch_stage(self):
            import jax.numpy as jnp

            def fn(t):
                jax.debug.print("step {}", t["x"])
                jax.experimental.io_callback(print, None, t["x"])
                host = jax.device_get(t["x"])
                return {"y": jnp.asarray(host) + t["x"].item()}
            return fn

    class PureStage(Unit):
        hide_from_registry = True

        def stitch_stage(self):
            import jax.numpy as jnp

            def fn(t):
                return {"y": jnp.tanh(t["x"])}
            return fn

    wf = DummyWorkflow()
    hot = scan_epoch_scan_hazards(CallbackStage(wf, name="cb"))
    assert rules_of(hot) == {"V-J10"}, [f.render() for f in hot]
    assert len(hot) == 4
    assert all(f.location for f in hot)
    assert "serialize" in hot[0].message
    clean = scan_epoch_scan_hazards(PureStage(wf, name="pure"))
    assert clean == [], [f.render() for f in clean]


def test_v_j10_decision_override_flagged_and_protocol_silent():
    """V-J10's Decision half: with the epoch_scan knob SET, a
    subclass overriding the per-step run() with host-only logic loses
    the scan protocol marker and is flagged with the device-predicate
    remedy; the stock DecisionGD / DecisionMSE (and a subclass that
    re-opts in) stay silent — and with the knob off (the default) a
    legacy host-logic Decision is not flagged at all (no warning
    noise for a feature the run never enables)."""
    from veles_tpu.analyze.shapes import scan_epoch_scan_hazards
    from veles_tpu.config import root
    from veles_tpu.znicz.decision import DecisionGD, DecisionMSE

    wf = DummyWorkflow()

    class HostOnlyDecision(DecisionGD):
        hide_from_registry = True

        def run(self):
            self.epoch_n_err[0] += float(self.evaluator.n_err)

    host_only = HostOnlyDecision(wf, name="host_only")
    assert scan_epoch_scan_hazards(host_only) == []   # knob off
    saved = root.common.engine.get("epoch_scan", "off")
    root.common.engine.epoch_scan = "auto"
    try:
        flagged = scan_epoch_scan_hazards(host_only)
        assert rules_of(flagged) == {"V-J10"}, \
            [f.render() for f in flagged]
        assert "device-predicate" in flagged[0].fix
        for cls in (DecisionGD, DecisionMSE):
            unit = cls(wf, name="stock_%s" % cls.__name__)
            assert unit.scan_compatible
            assert scan_epoch_scan_hazards(unit) == []

        class ReoptedDecision(DecisionGD):
            hide_from_registry = True

            def run(self):
                super(ReoptedDecision, self).run()

        ReoptedDecision.run.scan_protocol = True
        unit = ReoptedDecision(wf, name="reopted")
        assert unit.scan_compatible
        assert scan_epoch_scan_hazards(unit) == []
    finally:
        root.common.engine.epoch_scan = saved


def test_v_j10_in_catalog_and_check_shapes_wiring():
    """The rule is in --rules and check_shapes runs it over the hot
    chain + loader + decision — the standard workflow stays silent."""
    assert "V-J10" in rule_catalog()

    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J10" not in rules_of(findings), \
        [f.render() for f in findings]


# -- V-J11: host-side finiteness probes -------------------------------------

def test_v_j11_run_body_finiteness_probe_flagged():
    """V-J11: np.isnan / jnp.isfinite in a hot-loop run()/tpu_run()
    body is the per-step divergence poll the in-program health knob
    replaces; a probe-free body stays silent."""
    from veles_tpu.analyze.shapes import scan_finiteness_probes

    class ProbingUnit(Unit):
        hide_from_registry = True

        def run(self):
            if numpy.isnan(self.output.mem).any():
                raise RuntimeError("diverged")

        def tpu_run(self):
            import jax.numpy as jnp
            if jnp.isfinite(self.output.devmem).all().item() == 0:
                raise RuntimeError("diverged")

    class CleanUnit(Unit):
        hide_from_registry = True

        def run(self):
            self.total += float(self.minibatch_size)

        def tpu_run(self):
            # in-program masking: the jnp verdict never reaches the
            # host — legitimate device-side sanitization, not a probe
            import jax.numpy as jnp
            x = self.output.devmem
            self.output.devmem = jnp.where(jnp.isfinite(x), x, 0.0)

    class HostOnlyProbe(Unit):
        hide_from_registry = True

        def run(self):
            # input sanitization over a plain host array: no Vector
            # .mem/.devmem, no jnp — the health knob cannot replace
            # this, so the rule stays silent
            if numpy.isnan(self.raw_batch).any():
                raise ValueError("bad input file")

    wf = DummyWorkflow()
    probe = ProbingUnit(wf, name="probe")
    hot = scan_finiteness_probes(probe)
    assert rules_of(hot) == {"V-J11"}, [f.render() for f in hot]
    assert len(hot) == 2                       # run + tpu_run
    assert all(f.location for f in hot)
    assert "engine.health" in hot[0].fix
    clean = scan_finiteness_probes(CleanUnit(wf, name="clean"))
    assert clean == [], [f.render() for f in clean]
    host_only = scan_finiteness_probes(HostOnlyProbe(wf, name="san"))
    assert host_only == [], [f.render() for f in host_only]
    # one finding per call site across rules: the synced finiteness
    # verdict in tpu_run is V-J11's — the transfer-hazard pass cedes
    # it (no V-J08/V-J05 duplicate for the same .item() node)
    from veles_tpu.analyze.shapes import scan_transfer_hazards
    transfer = scan_transfer_hazards(probe, hot_loop=True)
    assert transfer == [], [f.render() for f in transfer]


def test_v_j11_stitch_stage_synced_probe_flagged_pure_silent():
    """V-J11's stitch_stage half: a jnp finiteness verdict SYNCED to
    the host (float()/.item()) is flagged; the in-program
    jnp.isfinite fold (exactly what the health instrumentation does)
    stays silent."""
    from veles_tpu.analyze.shapes import scan_finiteness_probes

    class SyncedProbeStage(Unit):
        hide_from_registry = True

        def stitch_stage(self):
            import jax.numpy as jnp

            def fn(t):
                if float(jnp.isnan(t["x"]).sum()) > 0:
                    raise RuntimeError("diverged")
                bad = jnp.isinf(t["x"]).any().item()
                return {"y": t["x"], "bad": bad}
            return fn

    class InProgramStage(Unit):
        hide_from_registry = True

        def stitch_stage(self):
            import jax.numpy as jnp

            def fn(t):
                count = jnp.sum(jnp.logical_not(
                    jnp.isfinite(t["x"])))
                # a traced jnp.asarray fold of a finiteness mask is
                # pure in-program math — only the NUMPY-namespace
                # array constructors are host syncs
                mask = jnp.asarray(jnp.isfinite(t["x"]), jnp.float32)
                return {"y": t["x"] * mask,
                        "health_nonfinite": count}
            return fn

    wf = DummyWorkflow()
    unit = SyncedProbeStage(wf, name="synced")
    hot = scan_finiteness_probes(unit)
    assert rules_of(hot) == {"V-J11"}, [f.render() for f in hot]
    assert len(hot) == 2                       # float() + .item()
    clean = scan_finiteness_probes(
        InProgramStage(wf, name="inprog"))
    assert clean == [], [f.render() for f in clean]
    # one finding per call site across the rule pair: V-J10 cedes a
    # synced-finiteness node to the more specific V-J11 (an .item()
    # WITHOUT a finiteness verdict stays V-J10's — see the V-J10
    # tests), so the combined pass never double-reports a line
    from veles_tpu.analyze.shapes import scan_epoch_scan_hazards
    both = scan_epoch_scan_hazards(unit) + hot
    assert rules_of(both) == {"V-J11"}, [f.render() for f in both]
    assert len(both) == 2


def test_v_j11_in_catalog_and_hot_chain_silent():
    """V-J11 is in --rules; check_shapes wires it over the hot chain
    and the stock stitched MLP stays silent (the lint.sh sample gate's
    contract)."""
    assert "V-J11" in rule_catalog()

    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J11" not in rules_of(findings), \
        [f.render() for f in findings]


# -- V-J12: materialized O(S²) attention scores -----------------------------

def test_v_j12_materialized_attention_flagged():
    """V-J12: a softmax over an attention-shaped product (batched
    einsum / q @ k.T / dot_general) in a hot-loop or stitch_stage body
    is the O(S²) score materialization the flash kernel replaces —
    both the direct-nesting and the two-statement idiom fire."""
    from veles_tpu.analyze.shapes import scan_attention_materialization

    class DenseAttention(Unit):
        hide_from_registry = True

        def tpu_run(self):
            import jax
            import jax.numpy as jnp
            q, k, v = (self.q.devmem, self.k.devmem, self.v.devmem)
            # two-statement idiom: scores assigned, then softmaxed
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * self.scale
            p = jax.nn.softmax(scores, axis=-1)
            self.output.devmem = jnp.einsum("bhqk,bhkd->bhqd", p, v)

        def stitch_stage(self):
            import jax
            import jax.numpy as jnp

            def fn(t):
                q, k, v = t["q"], t["k"], t["v"]
                # direct nesting: softmax(q @ k.T)
                p = jax.nn.softmax(
                    jnp.matmul(q, k.swapaxes(-1, -2)) * 0.125,
                    axis=-1)
                return {"out": jnp.matmul(p, v)}
            return fn

    class ClassifierHead(Unit):
        hide_from_registry = True

        def tpu_run(self):
            import jax
            import jax.numpy as jnp
            # activation×weight GEMM then softmax — the stock
            # classifier-head idiom, NOT attention: stays silent
            logits = jnp.dot(self.input.devmem, self.weights.devmem)
            self.output.devmem = jax.nn.softmax(logits, axis=-1)

    class NoSoftmax(Unit):
        hide_from_registry = True

        def tpu_run(self):
            import jax.numpy as jnp
            self.output.devmem = jnp.matmul(
                self.q.devmem, self.k.devmem.swapaxes(-1, -2))

    wf = DummyWorkflow()
    dense = DenseAttention(wf, name="dense")
    hot = scan_attention_materialization(dense)
    assert rules_of(hot) == {"V-J12"}, [f.render() for f in hot]
    assert len(hot) == 2                 # tpu_run + stitch_stage
    assert all(f.location for f in hot)
    assert "flash_attention" in hot[0].fix
    head = scan_attention_materialization(
        ClassifierHead(wf, name="head"))
    assert head == [], [f.render() for f in head]
    plain = scan_attention_materialization(
        NoSoftmax(wf, name="plain"))
    assert plain == [], [f.render() for f in plain]


def test_v_j12_in_catalog_and_stock_samples_silent():
    """V-J12 is in --rules; check_shapes wires it over the hot chain
    and the stock stitched MLP (whose softmax head IS a softmax over
    a GEMM product — the idiom the rule must NOT confuse with
    attention) stays silent."""
    assert "V-J12" in rule_catalog()

    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    class TinyLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (40, 8)).astype(numpy.float32)
            self.original_labels = [int(i % 4) for i in range(40)]
            self.class_lengths[:] = [0, 0, 40]

    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: TinyLoader(w, minibatch_size=8),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax",
                 "->": {"output_sample_shape": 4}}],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())
    findings = check_shapes(wf, sample_shape=(8,), batch_size=8)
    assert "V-J12" not in rules_of(findings), \
        [f.render() for f in findings]
