"""Loader layer tests (mirrors reference loader coverage: 3-set serving
order, epoch flags, shuffling, failed-minibatch requeue, master–slave
index distribution)."""

import numpy
import pytest

from veles_tpu.backends import CPUDevice, NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader import (
    FullBatchLoader, FullBatchLoaderMSE, TEST, TRAIN, VALID)
from veles_tpu.loader.base import Loader


class SyntheticLoader(FullBatchLoader):
    """10-class gaussian blobs: n_test/n_valid/n_train samples of dim."""

    def __init__(self, workflow, n_test=20, n_valid=30, n_train=50, dim=8,
                 n_classes=10, **kwargs):
        self._sizes = (n_test, n_valid, n_train)
        self._dim = dim
        self._n_classes = n_classes
        super(SyntheticLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        total = sum(self._sizes)
        rng = numpy.random.default_rng(7)
        labels = rng.integers(0, self._n_classes, total)
        data = rng.standard_normal((total, self._dim)).astype(
            numpy.float32) + labels[:, None]
        self.original_data.mem = data
        self.original_labels = list(labels)
        self.class_lengths[:] = self._sizes


def make_loader(device=None, **kwargs):
    wf = DummyWorkflow()
    wf.device = device or NumpyDevice()
    loader = SyntheticLoader(wf, **kwargs)
    loader.initialize(device=wf.device)
    return loader


def test_serving_order_test_valid_train():
    loader = make_loader(minibatch_size=10)
    classes = []
    for _ in range(10):   # 100 samples / 10 = 10 minibatches per epoch
        loader.run()
        classes.append(loader.minibatch_class)
    assert classes[:2] == [TEST, TEST]
    assert classes[2:5] == [VALID] * 3
    assert classes[5:] == [TRAIN] * 5


def test_epoch_flags():
    loader = make_loader(minibatch_size=10)
    flags = []
    for _ in range(10):
        loader.run()
        flags.append((bool(loader.last_minibatch),
                      bool(loader.epoch_ended),
                      bool(loader.train_ended)))
    # last minibatch of each class sets last_minibatch
    assert flags[1][0] and flags[4][0] and flags[9][0]
    # epoch_ended on last VALID minibatch
    assert flags[4][1]
    # train_ended on last TRAIN minibatch
    assert flags[9][2]
    assert loader.epoch_number == 0
    loader.run()
    assert loader.epoch_number == 1
    assert loader.minibatch_class == TEST


def test_short_final_batch_padded():
    loader = make_loader(minibatch_size=15)   # test set of 20 → 15 + 5
    loader.run()
    assert loader.minibatch_size == 15
    loader.run()
    assert loader.minibatch_size == 5
    assert (loader.minibatch_indices.mem[5:] == -1).all()
    assert (loader.minibatch_data.mem[5:] == 0).all()
    assert (loader.minibatch_labels.mem[5:] == -1).all()


def test_shuffle_changes_train_only():
    loader = make_loader(minibatch_size=10, shuffle_limit=10)
    before = loader.shuffled_indices.mem.copy()
    # run a full epoch to trigger reshuffle at wrap
    for _ in range(11):
        loader.run()
    after = loader.shuffled_indices.mem
    assert (before[:50] == after[:50]).all()       # test+valid untouched
    assert not (before[50:] == after[50:]).all()   # train reshuffled


def test_shuffle_deterministic_by_prng():
    from veles_tpu import prng
    prng.seed_all(99)
    a = make_loader(minibatch_size=10).shuffled_indices.mem.copy()
    prng.seed_all(99)
    b = make_loader(minibatch_size=10).shuffled_indices.mem.copy()
    assert (a == b).all()


def test_labels_mapped_and_data_gathered():
    loader = make_loader(minibatch_size=100)
    loader.run()
    idx = loader.minibatch_indices.mem[:loader.minibatch_size]
    data = loader.minibatch_data.mem[:loader.minibatch_size]
    # normalization is 'none' → gathered rows equal originals
    assert numpy.allclose(data, loader.original_data.mem[idx])
    assert (loader.minibatch_labels.mem[:loader.minibatch_size] ==
            numpy.asarray(loader.original_labels)[idx]).all()


def test_device_resident_gather_matches_host():
    host = make_loader(minibatch_size=25)
    dev = make_loader(device=CPUDevice(), minibatch_size=25)
    for _ in range(3):
        host.run()
        dev.run()
    assert (host.minibatch_indices.mem == dev.minibatch_indices.mem).all()
    assert numpy.allclose(host.minibatch_data.mem, dev.minibatch_data.mem)
    assert (host.minibatch_labels.mem == dev.minibatch_labels.mem).all()


def test_normalization_mean_disp():
    loader = make_loader(minibatch_size=10,
                         normalization_type="mean_disp")
    # statistics fit on TRAIN span only
    train = loader.original_data.mem[50:]
    assert abs(float(train.mean(axis=0).mean())) < 1.0


def test_master_slave_index_distribution():
    master_loader = make_loader(minibatch_size=10)
    master_loader.workflow.launcher.is_master = True
    master_loader.workflow.launcher.is_standalone = False

    slave_loader = make_loader(minibatch_size=10)
    slave_loader.workflow.launcher.is_slave = True
    slave_loader.workflow.launcher.is_standalone = False

    job = master_loader.generate_data_for_slave(slave="s1")
    assert job["minibatch_size"] == 10
    slave_loader.apply_data_from_master(job)
    slave_loader.run()
    assert (slave_loader.minibatch_indices.mem[:10] ==
            job["indices"]).all()
    # master accounts the update
    master_loader.apply_data_from_slave(True, slave="s1")
    assert master_loader.pending_minibatches_count == 0


def test_drop_slave_requeues():
    loader = make_loader(minibatch_size=10)
    loader.workflow.launcher.is_master = True
    loader.workflow.launcher.is_standalone = False
    job = loader.generate_data_for_slave(slave="dead")
    assert loader.pending_minibatches_count == 1
    loader.drop_slave(slave="dead")
    assert loader.pending_minibatches_count == 0
    assert loader.failed_minibatches
    # next serve retries the failed minibatch
    job2 = loader.generate_data_for_slave(slave="alive")
    assert job2["minibatch_offset"] == job["minibatch_offset"]
    assert loader.total_failed == 1


def test_retried_minibatch_keeps_its_class():
    """A requeued failed minibatch ships with its own class even after
    global_offset advanced into another class span."""
    loader = make_loader(minibatch_size=10)
    loader.workflow.launcher.is_master = True
    loader.workflow.launcher.is_standalone = False
    # advance into TRAIN span, give a TRAIN batch to a slave
    for _ in range(5):
        loader.generate_data_for_slave(slave="warm")
        loader.apply_data_from_slave(True, slave="warm")
    job = loader.generate_data_for_slave(slave="doomed")
    assert job["minibatch_class"] == TRAIN
    # wrap the offset into the next epoch's TEST span while the doomed
    # slave still holds its TRAIN batch...
    for _ in range(5):
        loader.generate_data_for_slave(slave="warm")
        loader.apply_data_from_slave(True, slave="warm")
    assert loader.minibatch_class == TEST
    # ...then it dies; the retry must ship as TRAIN, not current TEST
    loader.drop_slave(slave="doomed")
    retry = loader.generate_data_for_slave(slave="alive")
    assert retry["minibatch_offset"] == job["minibatch_offset"]
    assert retry["minibatch_class"] == TRAIN   # not the current TEST


def test_mse_loader_targets():
    class SynthMSE(FullBatchLoaderMSE):
        def load_data(self):
            rng = numpy.random.default_rng(0)
            self.original_data.mem = rng.standard_normal(
                (30, 4)).astype(numpy.float32)
            self.original_targets.mem = rng.standard_normal(
                (30, 2)).astype(numpy.float32)
            self.class_lengths[:] = [0, 10, 20]

    wf = DummyWorkflow()
    wf.device = NumpyDevice()
    loader = SynthMSE(wf, minibatch_size=7)
    loader.initialize(device=wf.device)
    loader.run()
    idx = loader.minibatch_indices.mem[:loader.minibatch_size]
    assert numpy.allclose(loader.minibatch_targets.mem[:len(idx)],
                          loader.original_targets.mem[idx])


def test_pickle_resume_continues_serving():
    import pickle
    loader = make_loader(minibatch_size=10)
    for _ in range(3):
        loader.run()
    blob = pickle.dumps(loader)
    offset = loader.global_offset
    restored = pickle.loads(blob)
    restored.workflow = DummyWorkflow()
    assert restored.global_offset == offset
    restored.run()
    assert restored.global_offset == offset + 10


def test_validation_ratio_carves_validation_from_train():
    """LoaderWithValidationRatio parity: an all-train dataset with
    validation_ratio in (0,1) yields a RANDOM validation split at
    initialize, and a full workflow validates on it."""
    import pytest

    from veles_tpu import prng
    from veles_tpu.backends import CPUDevice
    from veles_tpu.loader.base import LoaderError
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow
    from veles_tpu.dummy import DummyLauncher

    class AllTrainLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(3)
            n = 400
            self.original_data.mem = rng.standard_normal(
                (n, 16)).astype(numpy.float32)
            self.original_labels = [int(v) for v in
                                    rng.integers(0, 4, n)]
            self.class_lengths[:] = [0, 0, n]

    prng.seed_all(12)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: AllTrainLoader(
            w, minibatch_size=50, validation_ratio=0.25),
        layers=[{"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": 2})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice())
    assert wf.loader.class_lengths == [0, 100, 300]
    # the carve is a RANDOM subset, not the leading block: the
    # validation positions of the index space are a permutation
    wf.loader.shuffled_indices.map_read()
    valid_idx = numpy.array(wf.loader.shuffled_indices.mem[:100])
    assert not numpy.array_equal(valid_idx, numpy.arange(100))
    assert len(set(valid_idx.tolist())) == 100
    wf.run()
    assert float(wf.decision.best_n_err_pt) < 100.0
    assert wf.decision.best_epoch >= 0   # validation actually closed

    # bad ratios are rejected at CONSTRUCTION, before any data loads
    from veles_tpu.dummy import DummyWorkflow
    for bad in (1.5, 0.0, "25%"):
        with pytest.raises(LoaderError, match="validation_ratio"):
            AllTrainLoader(DummyWorkflow(), minibatch_size=50,
                           validation_ratio=bad)
