"""Native C++ runtime tests: packaged-model round trip through the
ctypes bridge, compared against the Python golden runner — the TPU
build's version of libVeles/tests (workflow_loader.cc,
memory_optimizer.cc, numpy_array_loader.cc against mnist.zip fixtures).
"""

import numpy
import pytest

from veles_tpu.backends import NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.memory import Vector
from veles_tpu.package import PackagedRunner, export_package

native = pytest.importorskip("veles_tpu.native")


@pytest.fixture(scope="module")
def lib():
    try:
        return native.load_library()
    except native.NativeError as e:
        pytest.skip("native build unavailable: %s" % e)


def _chain(units_spec, x):
    """Builds + runs a unit chain on NumpyDevice; returns forwards."""
    wf = DummyWorkflow()
    dev = NumpyDevice()
    forwards = []
    inp = Vector(x.copy())
    for ctor, kwargs in units_spec:
        unit = ctor(wf, **kwargs)
        unit.input = inp
        unit.initialize(dev)
        unit.numpy_run()
        forwards.append(unit)
        inp = unit.output
    forwards[-1].output.map_read()
    return forwards, numpy.array(forwards[-1].output.mem)


def test_mlp_zip(lib, tmp_path):
    from veles_tpu.znicz.all2all import All2AllSoftmax, All2AllTanh
    rng = numpy.random.default_rng(0)
    x = rng.standard_normal((8, 24)).astype(numpy.float32)
    forwards, golden = _chain(
        [(All2AllTanh, {"output_sample_shape": (16,)}),
         (All2AllTanh, {"output_sample_shape": (12,)}),
         (All2AllSoftmax, {"output_sample_shape": (5,)})], x)
    path = str(tmp_path / "mlp.zip")
    export_package(forwards, path, with_stablehlo=False)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert out.shape == golden.shape
        assert numpy.allclose(out, golden, atol=1e-5)
        assert numpy.allclose(out.sum(axis=1), 1.0, atol=1e-5)


def test_int8_package_native_matches_python_runner(lib, tmp_path):
    """precision=8 packages: the C++ loader's per-channel dequantize
    must agree with package.py's (identical dequantized weights ->
    float-tolerance agreement), and the quantized predictions must
    match the fp32 golden's argmax."""
    from veles_tpu.znicz.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.znicz.conv import ConvTanh
    from veles_tpu.znicz.pooling import MaxPooling
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((4, 10, 10, 2)).astype(numpy.float32)
    forwards, golden = _chain(
        [(ConvTanh, {"n_kernels": 6, "kx": 3, "ky": 3}),
         (MaxPooling, {"kx": 2, "ky": 2}),
         (All2AllTanh, {"output_sample_shape": (20,)}),
         (All2AllSoftmax, {"output_sample_shape": (5,)})], x)
    path = str(tmp_path / "mlp8.zip")
    export_package(forwards, path, precision=8, with_stablehlo=False)
    py_out = PackagedRunner(path).run(x)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert out.shape == py_out.shape
        assert numpy.allclose(out, py_out, atol=1e-4)
    assert (py_out.argmax(-1) == golden.argmax(-1)).all()


def test_convnet_tgz(lib, tmp_path):
    from veles_tpu.znicz.all2all import All2AllSoftmax
    from veles_tpu.znicz.conv import ConvTanh
    from veles_tpu.znicz.normalization_units import LRNormalizerForward
    from veles_tpu.znicz.pooling import AvgPooling, MaxPooling
    rng = numpy.random.default_rng(1)
    x = rng.standard_normal((4, 12, 12, 3)).astype(numpy.float32)
    forwards, golden = _chain(
        [(ConvTanh, {"n_kernels": 5, "kx": 3, "ky": 3,
                     "padding": (1, 1, 1, 1)}),
         (MaxPooling, {"kx": 2, "ky": 2}),
         (LRNormalizerForward, {}),
         (ConvTanh, {"n_kernels": 4, "kx": 3, "ky": 3,
                     "sliding": (2, 2)}),
         (AvgPooling, {"kx": 2, "ky": 2}),
         (All2AllSoftmax, {"output_sample_shape": (7,)})], x)
    path = str(tmp_path / "conv.tar.gz")
    export_package(forwards, path, with_stablehlo=False)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        # conv epsilon: im2col accumulation order differs from XLA
        assert numpy.allclose(out, golden, atol=1e-3)


def test_batch_reinitialize(lib, tmp_path):
    """Changing batch size re-plans the arena (resume-like property)."""
    from veles_tpu.znicz.all2all import All2AllTanh
    rng = numpy.random.default_rng(2)
    x8 = rng.standard_normal((8, 10)).astype(numpy.float32)
    forwards, _ = _chain(
        [(All2AllTanh, {"output_sample_shape": (6,)})], x8)
    path = str(tmp_path / "m.zip")
    export_package(forwards, path, with_stablehlo=False)
    runner = PackagedRunner(path)
    with native.NativeWorkflow(path) as wf:
        for batch in (8, 3, 17):
            xb = rng.standard_normal((batch, 10)).astype(numpy.float32)
            assert numpy.allclose(wf.run(xb), runner.run(xb), atol=1e-5)


def test_arena_packing(lib, tmp_path):
    """MemoryOptimizer packs buffers: arena < sum of all buffers, and
    ≥ the largest simultaneous pair (parity: memory_optimizer.cc)."""
    from veles_tpu.znicz.all2all import All2AllTanh
    rng = numpy.random.default_rng(3)
    x = rng.standard_normal((4, 64)).astype(numpy.float32)
    forwards, _ = _chain(
        [(All2AllTanh, {"output_sample_shape": (64,)}),
         (All2AllTanh, {"output_sample_shape": (64,)}),
         (All2AllTanh, {"output_sample_shape": (64,)}),
         (All2AllTanh, {"output_sample_shape": (64,)})], x)
    path = str(tmp_path / "deep.zip")
    export_package(forwards, path, with_stablehlo=False)
    with native.NativeWorkflow(path) as wf:
        wf.initialize(4)
        buffers = 5 * 4 * 64  # input + 4 outputs, all (4, 64)
        # pairwise liveness → 2 buffers' worth, never all 5
        assert wf.arena_floats == 2 * 4 * 64
        assert wf.arena_floats < buffers


def test_activation_and_dropout_units(lib, tmp_path):
    from veles_tpu.znicz.activation import ForwardSigmoid, ForwardTanh
    from veles_tpu.znicz.normalization_units import DropoutForward
    rng = numpy.random.default_rng(4)
    x = rng.standard_normal((6, 9)).astype(numpy.float32)
    wf = DummyWorkflow()
    dev = NumpyDevice()
    tanh = ForwardTanh(wf)
    tanh.input = Vector(x.copy())
    tanh.initialize(dev)
    tanh.numpy_run()
    drop = DropoutForward(wf, dropout_ratio=0.4)
    drop.input = tanh.output
    drop.forward_mode <<= True   # inference: identity
    drop.initialize(dev)
    drop.numpy_run()
    sig = ForwardSigmoid(wf)
    sig.input = drop.output
    sig.initialize(dev)
    sig.numpy_run()
    sig.output.map_read()
    golden = numpy.array(sig.output.mem)
    path = str(tmp_path / "acts.zip")
    export_package([tanh, drop, sig], path, with_stablehlo=False)
    with native.NativeWorkflow(path) as nwf:
        assert numpy.allclose(nwf.run(x), golden, atol=1e-5)


def test_lstm_package(lib, tmp_path):
    """Recurrent family through the native engine: LSTM(last_only) →
    softmax, vs the eager numpy chain AND the Python golden runner."""
    from veles_tpu.znicz.all2all import All2AllSoftmax
    from veles_tpu.znicz.rnn import LSTM

    rng = numpy.random.default_rng(4)
    x = rng.standard_normal((6, 9, 7)).astype(numpy.float32)
    forwards, golden = _chain(
        [(LSTM, {"hidden_units": 11, "last_only": True,
                 "weights_filling": "gaussian"}),
         (All2AllSoftmax, {"output_sample_shape": (5,)})], x)
    path = str(tmp_path / "lstm.zip")
    export_package(forwards, path, with_stablehlo=False)
    runner = PackagedRunner(path)
    numpy.testing.assert_allclose(runner.run(x), golden, atol=1e-5)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert out.shape == golden.shape
        numpy.testing.assert_allclose(out, golden, atol=1e-4)


def test_int8_lstm_package(lib, tmp_path):
    """int8 quantization on the recurrent family: LSTM weights
    quantize per gate column ([in+h, 4h] last axis); native and Python
    loaders dequantize identically, predictions track the fp32
    golden."""
    from veles_tpu.znicz.all2all import All2AllSoftmax
    from veles_tpu.znicz.rnn import LSTM

    rng = numpy.random.default_rng(6)
    x = rng.standard_normal((6, 9, 7)).astype(numpy.float32)
    forwards, golden = _chain(
        [(LSTM, {"hidden_units": 11, "last_only": True,
                 "weights_filling": "gaussian"}),
         (All2AllSoftmax, {"output_sample_shape": (5,)})], x)
    path = str(tmp_path / "lstm8.zip")
    export_package(forwards, path, precision=8, with_stablehlo=False)
    py_out = PackagedRunner(path).run(x)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        numpy.testing.assert_allclose(out, py_out, atol=1e-4)
    # recurrence amplifies quantization error; the argmax must hold
    # for (nearly) all of this small batch
    flips = (py_out.argmax(-1) != golden.argmax(-1)).mean()
    assert flips <= 1 / 6


def test_rnn_full_sequence_package(lib, tmp_path):
    """Simple RNN emitting the full (B, T, H) sequence natively."""
    from veles_tpu.znicz.rnn import SimpleRNN

    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((3, 5, 4)).astype(numpy.float32)
    forwards, golden = _chain(
        [(SimpleRNN, {"hidden_units": 6,
                      "weights_filling": "gaussian"})], x)
    path = str(tmp_path / "rnn.zip")
    export_package(forwards, path, with_stablehlo=False)
    runner = PackagedRunner(path)
    numpy.testing.assert_allclose(runner.run(x), golden, atol=1e-5)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert out.shape == golden.shape
        numpy.testing.assert_allclose(out, golden, atol=1e-4)


def test_conv_autoencoder_package(lib, tmp_path):
    """Conv-AE inference natively: conv encoder → deconv decoder
    (transposed conv, stride 2) vs the eager chain and the Python
    golden runner."""
    from veles_tpu.znicz.conv import ConvTanh
    from veles_tpu.znicz.misc_units import Deconv

    rng = numpy.random.default_rng(6)
    x = rng.standard_normal((3, 8, 8, 1)).astype(numpy.float32)
    forwards, golden = _chain(
        [(ConvTanh, {"n_kernels": 4, "kx": 3, "ky": 3, "padding": 1,
                     "sliding": (2, 2),
                     "weights_filling": "gaussian"}),
         (Deconv, {"n_kernels": 4, "kx": 3, "ky": 3, "padding": 1,
                   "sliding": (2, 2), "output_channels": 1,
                   "weights_filling": "gaussian"})], x)
    path = str(tmp_path / "convae.zip")
    export_package(forwards, path, with_stablehlo=False)
    runner = PackagedRunner(path)
    numpy.testing.assert_allclose(runner.run(x), golden, atol=1e-4)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert out.shape == golden.shape
        numpy.testing.assert_allclose(out, golden, atol=1e-4)


def test_cutter_and_channel_splitter_package(lib, tmp_path):
    """Spatial crop + channel slice natively vs the eager chain."""
    from veles_tpu.znicz.misc_units import ChannelSplitter, Cutter

    rng = numpy.random.default_rng(7)
    x = rng.standard_normal((2, 9, 9, 6)).astype(numpy.float32)
    forwards, golden = _chain(
        [(Cutter, {"window": (2, 1, 5, 7)}),
         (ChannelSplitter, {"start": 1, "count": 3})], x)
    path = str(tmp_path / "slices.zip")
    export_package(forwards, path, with_stablehlo=False)
    runner = PackagedRunner(path)
    numpy.testing.assert_allclose(runner.run(x), golden, atol=1e-6)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert out.shape == golden.shape
        numpy.testing.assert_allclose(out, golden, atol=1e-5)


def test_fp16_package(lib, tmp_path):
    from veles_tpu.znicz.all2all import All2AllSoftmax
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((3, 15)).astype(numpy.float32)
    forwards, golden = _chain(
        [(All2AllSoftmax, {"output_sample_shape": (4,)})], x)
    path = str(tmp_path / "m16.zip")
    export_package(forwards, path, precision=16, with_stablehlo=False)
    with native.NativeWorkflow(path) as wf:
        assert numpy.allclose(wf.run(x), golden, atol=5e-2)


def test_corrupt_package_raises(lib, tmp_path):
    path = str(tmp_path / "junk.zip")
    with open(path, "wb") as f:
        f.write(b"this is not a zip")
    with pytest.raises(native.NativeError):
        native.NativeWorkflow(path)


def test_native_logging_bridge(lib, tmp_path, caplog):
    """Native-runtime log messages cross the ctypes seam into Python
    logging with mapped levels (ref libVeles eina-log layer)."""
    import logging as _logging

    import veles_tpu.native as native
    from veles_tpu.znicz.all2all import All2AllTanh

    x = numpy.random.default_rng(0).standard_normal(
        (4, 6)).astype(numpy.float32)
    forwards, _golden = _chain(
        [(All2AllTanh, {"output_sample_shape": (3,)})], x)
    pkg = str(tmp_path / "log.zip")
    export_package(forwards, pkg, with_stablehlo=False)
    lib.veles_native_set_log_level(0)          # debug
    with caplog.at_level(_logging.DEBUG, logger="native.workflow"):
        wf = native.NativeWorkflow(pkg)
        wf.initialize(4)
    records = [r for r in caplog.records
               if r.name.startswith("native.")]
    assert any("loaded package" in r.message for r in records)
    assert any("arena" in r.message and "units" in r.message
               for r in records)
    # raising the native threshold silences below-level messages at
    # the source
    lib.veles_native_set_log_level(3)          # error only
    caplog.clear()
    with caplog.at_level(_logging.DEBUG, logger="native.workflow"):
        wf2 = native.NativeWorkflow(pkg)
        wf2.initialize(4)
    assert not [r for r in caplog.records
                if r.name.startswith("native.")]
    lib.veles_native_set_log_level(2)          # restore default


def test_grouped_conv_package(lib, tmp_path):
    """The documented `grouping` knob survives export: XLA forward,
    the package golden model, and the C++ engine agree on a grouped
    conv stack (output block i reads input channel group i)."""
    from veles_tpu.znicz.all2all import All2AllSoftmax
    from veles_tpu.znicz.conv import ConvTanh
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((4, 10, 10, 6)).astype(numpy.float32)
    forwards, golden = _chain(
        [(ConvTanh, {"n_kernels": 8, "kx": 3, "ky": 3,
                     "padding": (1, 1, 1, 1), "grouping": 2}),
         (All2AllSoftmax, {"output_sample_shape": (5,)})], x)
    assert forwards[0].weights.mem.shape == (3, 3, 3, 8)
    path = str(tmp_path / "grouped.zip")
    export_package(forwards, path, with_stablehlo=False)
    with native.NativeWorkflow(path) as wf:
        out = wf.run(x)
        assert numpy.allclose(out, golden, atol=1e-3)


def test_cpp_component_tests(lib):
    """The C++ component test binary (make -C native test): npy
    parser, JSON, liveness packing, engine thread pool — the libVeles
    per-component googletest discipline, dependency-free."""
    import subprocess

    from veles_tpu import native as native_mod
    result = subprocess.run(
        ["make", "-C", native_mod._NATIVE_DIR, "test"],
        capture_output=True, text=True)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "native tests OK" in result.stdout
