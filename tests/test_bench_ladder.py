"""Parent-orchestration semantics of the bench ladder.

The driver records bench.py's LAST stdout JSON line as the round's
headline metric (BENCH_r{N}.json "parsed"), so the ladder's ordering
contract — AlexNet's line is final no matter which stages bank after
it — is load-bearing, as is the probe's banked-TPU provenance never
being able to crash the run (VERDICT r3 'missing' item 1).
"""

import io
import os
import sys
import json
import contextlib

import pytest

import bench


def _fake_runner(script):
    """_run_stage stand-in: ``script`` maps stage name -> result dict,
    None (simulated timeout), or an Exception to raise."""
    calls = []

    def run(name, timeout, env=None, grace=300):
        calls.append(name)
        spec = script.get(name, {"metric": name, "value": 1.0,
                                 "unit": "images/sec",
                                 "vs_baseline": None,
                                 "device_kind": "TPU v5 lite (fake)"})
        if spec is None:
            return None, "timeout after 1s"
        if isinstance(spec, Exception):
            raise spec
        return dict(spec), None

    run.calls = calls
    return run


@pytest.fixture
def tpu_env(monkeypatch, tmp_path):
    """bench.main() env for a simulated healthy-TPU run with a cold
    compile cache (no .alexnet_warm marker)."""
    for var in ("BENCH_FORCE_CPU", "BENCH_STAGES", "BENCH_TIMEOUT_SCALE"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_BUDGET_SEC", "600")
    # the real _run_stage makedirs the cache dir before any stage runs;
    # the fake runner skips that, so the fixture provides it
    (tmp_path / "xla").mkdir()
    monkeypatch.setattr(bench, "_cache_dir", lambda: str(tmp_path / "xla"))
    script = {"probe": {"platform": "tpu",
                        "device_kind": "TPU v5 lite (fake)",
                        "n_devices": 1}}
    runner = _fake_runner(script)
    monkeypatch.setattr(bench, "_run_stage", runner)
    return script, runner


def _run_main():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    return [json.loads(line) for line in buf.getvalue().strip().splitlines()]


def test_cold_ladder_reemits_headline_last(tpu_env):
    script, runner = tpu_env
    script["lstm"] = None  # a mid-ladder timeout must not derail banking
    lines = _run_main()
    names = [rec["metric"] for rec in lines]
    assert names[0] == "mnist"  # flagship-priority MLP ladder first
    assert names[-1] == "alexnet"  # the driver's parsed headline
    assert names.count("alexnet") == 2  # banked stages ran after it
    assert "transformer" in names and "power" in names
    assert "lstm" not in names  # timed out -> no line, no crash


def test_cold_ladder_no_duplicate_when_alexnet_is_last(tpu_env):
    script, runner = tpu_env
    # every post-flagship stage times out -> alexnet's own line is
    # already final; the re-emit must not print it twice
    for name in ("transformer", "lstm", "mnist_e2e", "mnist_e2e_u8",
                 "power"):
        script[name] = None
    names = [rec["metric"] for rec in _run_main()]
    assert names[-1] == "alexnet"
    assert names.count("alexnet") == 1


def test_warm_cache_keeps_full_ladder(tpu_env, tmp_path):
    _script, runner = tpu_env
    (tmp_path / "xla" / ".alexnet_warm").write_text("TPU v5 lite (fake)")
    names = [rec["metric"] for rec in _run_main()]
    assert "cifar" in names and "kohonen" in names  # full order ran
    assert names[-1] == "alexnet"
    assert names.count("alexnet") == 1


def test_alexnet_success_drops_warm_marker(tpu_env, tmp_path):
    _run_main()
    assert (tmp_path / "xla" / ".alexnet_warm").exists()


def test_alexnet_timeout_leaves_cache_cold(tpu_env, tmp_path):
    script, _runner = tpu_env
    script["alexnet"] = None
    lines = _run_main()
    assert not (tmp_path / "xla" / ".alexnet_warm").exists()
    # ladder still printed the MLP lines it banked before the flagship
    assert any(rec["metric"] == "mnist" for rec in lines)


# ---------------------------------------------------------------------------
# _banked_tpu_lines: provenance must never cost more than itself
# ---------------------------------------------------------------------------

def test_banked_lines_survive_torn_and_garbage_records(monkeypatch,
                                                       tmp_path):
    jsonl = tmp_path / "chip_session_r4" / "bench.jsonl"
    jsonl.parent.mkdir()
    jsonl.write_text("\n".join([
        json.dumps({"metric": "old", "value": 1.0, "unit": "images/sec",
                    "device_kind": "TPU v5 lite"}),
        '"just a string"',            # valid JSON, not a record
        "42",                         # ditto
        json.dumps({"device_kind": None, "metric": "null-kind"}),
        '{"torn": tru',               # torn mid-append
        json.dumps({"metric": "cpu line", "value": 2.0,
                    "unit": "images/sec", "device_kind": "cpu"}),
        json.dumps({"metric": "newest", "value": 3.0,
                    "unit": "images/sec", "device_kind": "TPU v5 lite"}),
    ]) + "\n")
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    banked = bench._banked_tpu_lines()
    metrics = [rec["metric"] for rec in banked]
    # garbage lines cost only themselves: the newest line AFTER the
    # torn one still surfaces, cpu lines are filtered out
    assert metrics == ["old", "newest"]
    assert all(rec["source"] == "chip_session_r4/bench.jsonl"
               for rec in banked)


def test_banked_lines_missing_files_is_empty(monkeypatch, tmp_path):
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    assert bench._banked_tpu_lines() == []


# ---------------------------------------------------------------------------
# scripts/collect_chip_session.py: evidence snapshots never clobber
# ---------------------------------------------------------------------------

def test_collector_never_overwrites_prior_window(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "collect_chip_session",
        os.path.join(os.path.dirname(bench.__file__),
                     "scripts", "collect_chip_session.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    out = tmp_path / "outdir"
    out.mkdir()
    (out / "bench.jsonl").write_text(json.dumps(
        {"metric": "w2", "value": 2.0, "unit": "images/sec",
         "device_kind": "tpu v5 lite"}) + "\n")  # lowercase kind counts
    evidence = tmp_path / "evidence"
    evidence.mkdir()
    (evidence / "bench.jsonl").write_text(json.dumps(
        {"metric": "w1", "value": 1.0, "unit": "images/sec",
         "device_kind": "TPU v5 lite"}) + "\n")

    argv = [sys.argv[0], str(out), str(evidence)]
    old = sys.argv
    sys.argv = argv
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            mod.main()
    finally:
        sys.argv = old
    text = buf.getvalue()
    # window 1 survives byte-for-byte, window 2 lands suffixed, and the
    # table shows BOTH windows' lines
    assert json.loads((evidence / "bench.jsonl").read_text())["metric"] \
        == "w1"
    assert (evidence / "bench.2.jsonl").exists()
    assert "| w1 |" in text and "| w2 |" in text
