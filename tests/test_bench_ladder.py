"""Parent/child semantics of the one-claim bench ladder.

The driver records bench.py's LAST stdout JSON line as the round's
headline metric (BENCH_r{N}.json "parsed"), so the ordering contract —
AlexNet's line is final no matter which stages bank after it — is
load-bearing, as are: the ladder claiming the backend exactly ONCE
(live-window post-mortem: the tunnel relay stops granting claims a few
minutes into a window), streamed lines surviving a parent reap, and the
probe's banked-TPU provenance never being able to crash the run.
"""

import io
import os
import sys
import json
import textwrap
import contextlib

import pytest

import bench


# ---------------------------------------------------------------------------
# _ladder_order: pure ordering policy
# ---------------------------------------------------------------------------

def test_cold_order_puts_flagship_right_after_proving_stage():
    order = bench._ladder_order(True, False, warm=False)
    assert order[0] == "mnist"
    assert order[1] == "alexnet"
    # the other headline artifacts ride the same claim, early
    assert order.index("profile") < order.index("transformer")
    assert set(order) == set(bench._COLD_ORDER)


def test_warm_order_ends_on_the_headline():
    order = bench._ladder_order(True, False, warm=True)
    assert order[-1] == "alexnet"
    assert "cifar" in order and "kohonen" in order


def test_cpu_order_avoids_heavies_and_ends_on_flagship_mlp():
    order = bench._ladder_order(False, True, warm=False)
    assert order[-1] == "mnist"
    assert "alexnet" not in order and "transformer" not in order


def test_only_filters_in_canonical_order():
    order = bench._ladder_order(True, False, warm=True,
                                only={"alexnet", "mnist", "lstm"})
    assert order == ("mnist", "lstm", "alexnet")


# ---------------------------------------------------------------------------
# stage_ladder: the one-claim child
# ---------------------------------------------------------------------------

@pytest.fixture
def child_env(monkeypatch, tmp_path):
    for var in ("BENCH_FORCE_CPU", "BENCH_STAGES", "BENCH_TIMEOUT_SCALE"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_BUDGET_SEC", "600")
    (tmp_path / "xla").mkdir()
    monkeypatch.setattr(bench, "_cache_dir", lambda: str(tmp_path / "xla"))
    monkeypatch.setattr(bench, "stage_probe",
                        lambda: {"platform": "tpu",
                                 "device_kind": "TPU v5 lite (fake)"})
    calls = []

    def fake(name, fail=None):
        def run():
            calls.append(name)
            if fail is not None:
                raise fail
        return run, 60

    stages = {n: fake(n) for n in bench.STAGES}
    monkeypatch.setattr(bench, "STAGES", stages)
    return stages, calls, fake


def test_child_runs_cold_order_and_drops_marker(child_env, tmp_path):
    stages, calls, _fake = child_env
    bench.stage_ladder()
    assert tuple(calls) == bench._COLD_ORDER
    assert (tmp_path / "xla" / ".alexnet_warm").exists()


def test_child_stage_error_does_not_stop_ladder(child_env, tmp_path):
    stages, calls, fake = child_env
    stages["alexnet"] = fake("alexnet", ValueError("boom"))
    bench.stage_ladder()
    assert "mnist_wf" in calls           # ladder kept going to the end
    assert not (tmp_path / "xla" / ".alexnet_warm").exists()


def test_child_stops_after_two_dead_backend_errors(child_env):
    stages, calls, fake = child_env
    dead = RuntimeError("UNAVAILABLE: TPU backend setup/compile error")
    stages["mnist_bf16"] = fake("mnist_bf16", dead)
    stages["mnist_u8"] = fake("mnist_u8", dead)
    bench.stage_ladder()
    # cold order: mnist, alexnet, mnist_bf16(dead), mnist_u8(dead) -> stop
    assert calls == ["mnist", "alexnet", "mnist_bf16", "mnist_u8"]


def test_child_honors_explicit_stage_selection(child_env, monkeypatch):
    _stages, calls, _fake = child_env
    monkeypatch.setenv("BENCH_STAGES", "mnist,alexnet")
    bench.stage_ladder()
    assert calls == ["mnist", "alexnet"]


# ---------------------------------------------------------------------------
# _stream_ladder + main: the streaming parent
# ---------------------------------------------------------------------------

def _fake_child_cmd(body):
    """A real subprocess faking the ladder child."""
    return [sys.executable, "-u", "-c", textwrap.dedent(body)]


def _run_main(monkeypatch, tmp_path, child_body, budget="600"):
    for var in ("BENCH_FORCE_CPU", "BENCH_STAGES", "BENCH_TIMEOUT_SCALE"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_BUDGET_SEC", budget)
    monkeypatch.setattr(bench, "_cache_dir", lambda: str(tmp_path / "xla"))
    monkeypatch.setattr(bench, "_ladder_cmd",
                        lambda: _fake_child_cmd(child_body))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    return [json.loads(line) for line in buf.getvalue().strip().splitlines()]


def test_parent_streams_and_reemits_headline_last(monkeypatch, tmp_path):
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "tpu", "device_kind": "TPU x"}))
        print(json.dumps({"metric": "mnist", "value": 1.0,
                          "unit": "images/sec"}))
        print(json.dumps({"metric":
                          "AlexNet fused train throughput per chip (bf16)",
                          "value": 2.0, "unit": "images/sec"}))
        print("profiler chatter, not JSON")
        print(json.dumps({"metric": "power", "value": 3.0,
                          "unit": "GFLOP/s"}))
    """)
    names = [rec["metric"] for rec in lines]
    assert names[0] == "mnist"
    assert names[-1] == bench.HEADLINE_METRIC   # re-emitted after power
    assert names.count(bench.HEADLINE_METRIC) == 2
    # TPU probe -> no cpu-fallback tagging anywhere
    assert not any("[cpu-fallback]" in n for n in names)


def test_parent_healthy_headline_starved_live_reemits_banked(
        monkeypatch, tmp_path):
    """Live headline landed but a later stage's live line was sample-
    starved (window degraded mid-run): the banked substantive line
    for JUST that metric re-emits, and the live headline is still the
    driver-parsed LAST line (code-review r5)."""
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([
        {"metric": "e2e", "value": 7923.6, "unit": "images/sec",
         "batches_served": 2175, "device_kind": "TPU v5 lite",
         "source": "chip_session_r4/bench.5.jsonl"},
        {"metric": "unrelated-banked", "value": 1.0, "unit": "x",
         "device_kind": "TPU v5 lite",
         "source": "chip_session_r4/bench.5.jsonl"}], 0))
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "tpu", "device_kind": "TPU x"}))
        print(json.dumps({"metric":
                          "AlexNet fused train throughput per chip (bf16)",
                          "value": 12000.0, "unit": "images/sec",
                          "device_kind": "TPU x"}))
        print(json.dumps({"metric": "e2e", "value": 26.5,
                          "unit": "images/sec", "batches_served": 1,
                          "device_kind": "TPU x"}))
    """)
    names = [r["metric"] for r in lines]
    banked = [r for r in lines if r.get("banked")]
    # only the starved metric's banked line — not the whole tail
    assert [r["metric"] for r in banked] == ["e2e"]
    assert banked[0]["value"] == 7923.6
    assert names[-1] == bench.HEADLINE_METRIC


def test_parent_tags_non_tpu_ladder_lines(monkeypatch, tmp_path):
    # pin the banked tail: this fixture's cpu platform routes through
    # _emit_banked_tail, which must not read the real repo's evidence
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([], 0))
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "cpu", "device_kind": "cpu"}))
        print(json.dumps({"metric": "mnist", "value": 1.0,
                          "unit": "images/sec"}))
    """)
    assert lines[0]["metric"] == "mnist [cpu-fallback]"


def test_parent_no_headline_no_duplicate(monkeypatch, tmp_path):
    # no banked evidence in this fixture: the no-headline run must not
    # invent a tail
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([], 0))
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "tpu", "device_kind": "TPU x"}))
        print(json.dumps({"metric": "mnist", "value": 1.0,
                          "unit": "images/sec"}))
    """)
    assert [rec["metric"] for rec in lines] == ["mnist"]


def test_parent_dead_window_emits_banked_headline_last(monkeypatch,
                                                       tmp_path):
    """A TPU window that dies before the flagship stage still ends on
    the banked TPU headline, never a partial/CPU line (VERDICT r4)."""
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([
        {"metric": bench.HEADLINE_METRIC, "value": 12441.0,
         "unit": "images/sec", "device_kind": "TPU v5 lite",
         "source": "chip_session_r4/bench.5.jsonl"}], 0))
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "tpu", "device_kind": "TPU x"}))
        print(json.dumps({"metric": "mnist", "value": 1.0,
                          "unit": "images/sec"}))
    """)
    assert lines[-1]["metric"] == bench.HEADLINE_METRIC
    assert lines[-1]["banked"] is True
    assert lines[-1]["value"] == 12441.0


def test_parent_falls_back_to_cpu_without_probe(monkeypatch, tmp_path):
    # the ladder child dies before printing anything
    monkeypatch.setattr(bench, "_stream_ladder",
                        lambda budget, cap: ([], None))
    cpu_calls = []

    def fake_run_stage(name, timeout, env=None, grace=300):
        cpu_calls.append((name, (env or {}).get("JAX_PLATFORMS")))
        if name == "probe":
            return {"platform": "cpu", "device_kind": "cpu"}, None
        return {"metric": name, "value": 1.0, "unit": "images/sec"}, None

    monkeypatch.setattr(bench, "_run_stage", fake_run_stage)
    # real repo evidence exists; pin the banked tail for determinism
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([
        {"metric": bench.HEADLINE_METRIC, "value": 12441.0,
         "unit": "images/sec", "device_kind": "TPU v5 lite",
         "source": "chip_session_r4/bench.5.jsonl"}], 0))
    for var in ("BENCH_FORCE_CPU", "BENCH_STAGES", "BENCH_TIMEOUT_SCALE"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_BUDGET_SEC", "600")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [json.loads(line) for line in buf.getvalue().strip().splitlines()]
    assert all(name == "probe" or plat == "cpu"
               for name, plat in cpu_calls)
    assert [rec["metric"] for rec in lines] == \
        [n + " [cpu-fallback]" for n in bench._CPU_ORDER] + \
        [bench.HEADLINE_METRIC]
    # the driver-parsed LAST line is the banked TPU headline
    assert lines[-1]["banked"] is True
    assert "tpu" in lines[-1]["device_kind"].lower()


def test_parent_tpu_only_skips_cpu_fallback(monkeypatch, tmp_path):
    """BENCH_TPU_ONLY: a watcher hunting TPU windows has no use for
    cpu-fallback lines — on a refused claim the run goes straight to
    the banked tail (artifact shape preserved, hours of pointless CPU
    ladder skipped)."""
    monkeypatch.setattr(bench, "_stream_ladder",
                        lambda budget, cap: ([], None))
    cpu_calls = []
    monkeypatch.setattr(
        bench, "_run_stage",
        lambda name, timeout, env=None, grace=300:
        cpu_calls.append(name) or ({"metric": name, "value": 1.0,
                                    "unit": "images/sec"}, None))
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([
        {"metric": bench.HEADLINE_METRIC, "value": 12441.0,
         "unit": "images/sec", "device_kind": "TPU v5 lite",
         "source": "chip_session_r4/bench.5.jsonl"}], 0))
    for var in ("BENCH_FORCE_CPU", "BENCH_STAGES",
                "BENCH_TIMEOUT_SCALE"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BENCH_TPU_ONLY", "1")
    monkeypatch.setenv("BENCH_BUDGET_SEC", "600")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [json.loads(line) for line in
             buf.getvalue().strip().splitlines()]
    assert cpu_calls == []                     # no fallback stages ran
    assert lines[-1]["metric"] == bench.HEADLINE_METRIC
    assert lines[-1]["banked"] is True


def test_stream_ladder_reaps_silent_child(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "_cache_dir", lambda: str(tmp_path / "xla"))
    monkeypatch.setattr(bench, "_ladder_cmd", lambda: _fake_child_cmd(
        "import time; time.sleep(60)"))
    records, probe = bench._stream_ladder(budget=60, probe_cap=2)
    assert probe is None and records == []


# ---------------------------------------------------------------------------
# _banked_tpu_lines: provenance must never cost more than itself
# ---------------------------------------------------------------------------

def test_banked_lines_survive_torn_and_garbage_records(monkeypatch,
                                                       tmp_path):
    jsonl = tmp_path / "chip_session_r4" / "bench.jsonl"
    jsonl.parent.mkdir()
    jsonl.write_text("\n".join([
        json.dumps({"metric": "old", "value": 1.0, "unit": "images/sec",
                    "device_kind": "TPU v5 lite"}),
        '"just a string"',            # valid JSON, not a record
        "42",                         # ditto
        json.dumps({"device_kind": None, "metric": "null-kind"}),
        '{"torn": tru',               # torn mid-append
        json.dumps({"metric": "cpu line", "value": 2.0,
                    "unit": "images/sec", "device_kind": "cpu"}),
        json.dumps({"metric": "newest", "value": 3.0,
                    "unit": "images/sec", "device_kind": "TPU v5 lite"}),
    ]) + "\n")
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    banked, superseded = bench._banked_tpu_lines()
    metrics = sorted(rec["metric"] for rec in banked)
    # garbage lines cost only themselves: the newest line AFTER the
    # torn one still surfaces, cpu lines are filtered out
    assert metrics == ["newest", "old"]     # sorted()
    assert superseded == 0
    assert all(rec["source"] == os.path.join("chip_session_r4",
                                             "bench.jsonl")
               for rec in banked)


def test_banked_lines_newest_per_metric_wins(monkeypatch, tmp_path):
    """Per (metric, device kind) only the NEWEST line (collector's
    numeric suffix order — file mtimes are all equal in a fresh git
    checkout) is surfaced; older same-metric lines are counted, not
    listed.  Distinct device kinds never supersede each other."""
    d = tmp_path / "chip_session_r4"
    d.mkdir()
    (d / "bench.jsonl").write_text(json.dumps(
        {"metric": "headline", "value": 1814.0, "unit": "images/sec",
         "device_kind": "TPU v5 lite"}) + "\n")
    (d / "bench.2.jsonl").write_text("\n".join([
        json.dumps({"metric": "headline", "value": 12441.0,
                    "unit": "images/sec",
                    "device_kind": "TPU v5 lite"}),
        json.dumps({"metric": "headline", "value": 999.0,
                    "unit": "images/sec", "device_kind": "Tpu v6"}),
    ]) + "\n")
    # identical checkout mtimes: order must come from the suffix
    t = os.path.getmtime(str(d / "bench.jsonl"))
    os.utime(str(d / "bench.2.jsonl"), (t, t))
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    banked, superseded = bench._banked_tpu_lines()
    by_kind = {rec["device_kind"]: rec for rec in banked}
    assert by_kind["TPU v5 lite"]["value"] == 12441.0   # newest wins
    assert by_kind["TPU v5 lite"]["source"].endswith("bench.2.jsonl")
    assert by_kind["Tpu v6"]["value"] == 999.0  # mixed case, distinct
    assert superseded == 1


def test_banked_lines_missing_files_is_empty(monkeypatch, tmp_path):
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    assert bench._banked_tpu_lines() == ([], 0)


def test_banked_lines_error_record_never_supersedes(monkeypatch,
                                                    tmp_path):
    """A newer window's physics-check FAILURE (value 0.0 + 'error')
    must not canonicalize over an older VALID hardware measurement —
    the opposite of the provenance goal (ADVICE r4)."""
    d = tmp_path / "chip_session_r4"
    d.mkdir()
    (d / "bench.jsonl").write_text(json.dumps(
        {"metric": "headline", "value": 12441.0, "unit": "images/sec",
         "vs_baseline": 8.29, "mfu": 0.39,
         "device_kind": "TPU v5 lite"}) + "\n")
    (d / "bench.2.jsonl").write_text(json.dumps(
        {"metric": "headline", "value": 0.0, "unit": "images/sec",
         "error": "timing failed physics check",
         "device_kind": "TPU v5 lite"}) + "\n")
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    banked, superseded = bench._banked_tpu_lines()
    assert len(banked) == 1
    assert banked[0]["value"] == 12441.0
    assert banked[0]["vs_baseline"] == 8.29     # provenance carried
    assert banked[0]["mfu"] == 0.39
    assert superseded == 1                      # counted, not listed


def test_banked_lines_starved_sample_never_supersedes(monkeypatch,
                                                      tmp_path):
    """A line whose own stage diagnosis says it served almost no
    batches (a window dying mid-stage leaves e2e loops timing ONE
    batch at tunnel-RTT pace — r4 bench.7: 26.5 img/s, batches_served
    1, dispatch 9.6 s) measures the dying transport, not the
    framework: it must not canonicalize over a substantive older
    measurement, but still surfaces when it is ALL there is."""
    d = tmp_path / "chip_session_r4"
    d.mkdir()
    (d / "bench.jsonl").write_text(json.dumps(
        {"metric": "e2e", "value": 7923.6, "unit": "images/sec",
         "batches_served": 2175,
         "device_kind": "TPU v5 lite"}) + "\n")
    (d / "bench.2.jsonl").write_text("\n".join([
        json.dumps({"metric": "e2e", "value": 26.5,
                    "unit": "images/sec", "batches_served": 1,
                    "device_kind": "TPU v5 lite"}),
        json.dumps({"metric": "only-starved", "value": 3.0,
                    "unit": "images/sec", "batches_served": 2,
                    "device_kind": "TPU v5 lite"}),
    ]) + "\n")
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    banked, superseded = bench._banked_tpu_lines()
    by_metric = {rec["metric"]: rec for rec in banked}
    assert by_metric["e2e"]["value"] == 7923.6
    assert by_metric["e2e"]["batches_served"] == 2175
    # a starved line with no substantive sibling still surfaces,
    # explicitly marked
    assert by_metric["only-starved"]["value"] == 3.0
    assert by_metric["only-starved"]["low_confidence"] is True
    assert "low_confidence" not in by_metric["e2e"]
    assert superseded == 1


def test_emit_banked_tail_ignores_starved_live_coverage(monkeypatch,
                                                        tmp_path,
                                                        capsys):
    """A live record that is itself sample-starved (the window died
    mid-stage THIS run) must not count as live coverage — the banked
    substantive line for that metric still re-emits, so the round's
    stdout never carries only the transport-death number
    (code-review r5)."""
    d = tmp_path / "chip_session_r4"
    d.mkdir()
    (d / "bench.jsonl").write_text(json.dumps(
        {"metric": "e2e", "value": 7923.6, "unit": "images/sec",
         "batches_served": 2175,
         "device_kind": "TPU v5 lite"}) + "\n")
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    live = [{"metric": "e2e", "value": 26.5, "unit": "images/sec",
             "batches_served": 1, "device_kind": "TPU v5 lite"}]
    emitted, headline = bench._emit_banked_tail(live)
    out = [json.loads(l) for l in
           capsys.readouterr().out.strip().splitlines()]
    assert emitted and not headline
    assert any(r["metric"] == "e2e" and r["value"] == 7923.6
               and r["banked"] is True for r in out)


def test_emit_banked_tail_headline_last(monkeypatch, tmp_path,
                                        capsys):
    """cpu-fallback run: banked TPU lines are re-emitted as stdout
    RECORDS tagged banked:true, the AlexNet headline LAST, so the
    driver's parsed final line is never a CPU number while hardware
    evidence exists (VERDICT r4 weak item 1)."""
    d = tmp_path / "chip_session_r4"
    d.mkdir()
    (d / "bench.jsonl").write_text("\n".join([
        json.dumps({"metric": bench.HEADLINE_METRIC, "value": 12441.0,
                    "unit": "images/sec", "vs_baseline": 8.29,
                    "device_kind": "TPU v5 lite"}),
        json.dumps({"metric": "other", "value": 5.0,
                    "unit": "x", "device_kind": "TPU v5 lite"}),
        json.dumps({"metric": "covered-live", "value": 7.0,
                    "unit": "x", "device_kind": "TPU v5 lite"}),
    ]) + "\n")
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    live = [{"metric": "covered-live", "value": 7.5, "unit": "x",
             "device_kind": "TPU v5 lite"}]
    assert bench._emit_banked_tail(live) == (True, True)
    out = [json.loads(l) for l in
           capsys.readouterr().out.strip().splitlines()]
    assert [r["metric"] for r in out] == ["other",
                                         bench.HEADLINE_METRIC]
    assert all(r["banked"] is True for r in out)
    assert all("source" in r and "note" in r for r in out)
    assert out[-1]["value"] == 12441.0
    assert out[-1]["vs_baseline"] == 8.29


def test_emit_banked_tail_empty_when_no_evidence(monkeypatch,
                                                 tmp_path, capsys):
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path))
    assert bench._emit_banked_tail([]) == (False, False)
    assert capsys.readouterr().out == ""


def test_parent_dead_window_no_failure_record_after_banked(
        monkeypatch, tmp_path):
    """Probe arrives, zero stages complete: the banked headline must
    be the LAST line — no trailing 0.0 'benchmark failed' record
    displacing it (code-review r5 finding 1)."""
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([
        {"metric": bench.HEADLINE_METRIC, "value": 12441.0,
         "unit": "images/sec", "device_kind": "TPU v5 lite",
         "source": "chip_session_r4/bench.5.jsonl"}], 0))
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "tpu", "device_kind": "TPU x"}))
    """)
    assert [r["metric"] for r in lines] == [bench.HEADLINE_METRIC]
    assert lines[-1]["banked"] is True


def test_parent_cpu_platform_banked_tail_without_headline(monkeypatch,
                                                          tmp_path):
    """Non-TPU platform with banked evidence that holds NO headline
    record: the non-headline banked lines still go out (tagged), and
    nothing is suppressed or duplicated (code-review r5 finding 2)."""
    monkeypatch.setattr(bench, "_banked_tpu_lines", lambda: ([
        {"metric": "lm-profile", "value": 1.0, "unit": "artifact",
         "device_kind": "TPU v5 lite", "source": "x.jsonl"}], 0))
    lines = _run_main(monkeypatch, tmp_path, """
        import json
        print(json.dumps({"platform": "cpu", "device_kind": "cpu"}))
        print(json.dumps({"metric": "power", "value": 3.0,
                          "unit": "GFLOP/s"}))
    """)
    assert [r["metric"] for r in lines] == \
        ["power [cpu-fallback]", "lm-profile"]
    assert lines[-1]["banked"] is True


# ---------------------------------------------------------------------------
# scripts/collect_chip_session.py: evidence snapshots never clobber
# ---------------------------------------------------------------------------

def _load_collector():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "collect_chip_session",
        os.path.join(os.path.dirname(bench.__file__),
                     "scripts", "collect_chip_session.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_collector(mod, out, evidence):
    argv = [sys.argv[0], str(out), str(evidence)]
    old = sys.argv
    sys.argv = argv
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            mod.main()
    finally:
        sys.argv = old
    return buf.getvalue()


def test_collector_starved_and_banked_rows_not_current(tmp_path):
    """EVIDENCE.md must agree with bench._banked_tpu_lines: a newer
    sample-starved line or a banked echo can never be the row marked
    current over a substantive measurement (same r4 26.5-img/s
    incident, evidence-index side)."""
    mod = _load_collector()
    out = tmp_path / "outdir"
    out.mkdir()
    (out / "bench.jsonl").write_text("\n".join([
        json.dumps({"metric": "e2e", "value": 7923.6,
                    "unit": "images/sec", "batches_served": 2175,
                    "device_kind": "TPU v5 lite", "ts": 100}),
        json.dumps({"metric": "e2e", "value": 26.5,
                    "unit": "images/sec", "batches_served": 1,
                    "device_kind": "TPU v5 lite", "ts": 200}),
        json.dumps({"metric": "e2e", "value": 26.5,
                    "unit": "images/sec", "banked": True,
                    "device_kind": "TPU v5 lite", "ts": 300}),
        json.dumps({"metric": "only-starved", "value": 3.0,
                    "unit": "images/sec", "batches_served": 2,
                    "device_kind": "TPU v5 lite", "ts": 150}),
    ]) + "\n")
    evidence = tmp_path / "evidence"
    text = _run_collector(mod, out, evidence)
    rows = [l for l in text.splitlines() if l.startswith("| ")]
    current = [l for l in rows if "**current**" in l]
    # the substantive line is current; the newer starved line and the
    # banked echo are explicitly non-quotable; the starved-only metric
    # is current but flagged
    assert any("7924" in l or "7923" in l for l in current)
    assert not any("| 26.5 |" in l and "**current**" in l
                   for l in rows)
    assert any("sample-starved" in l and "| 26.5 |" in l for l in rows)
    assert any("banked echo" in l for l in rows)
    assert any("LOW CONFIDENCE" in l and "only-starved" in l
               for l in current)


def test_collector_never_overwrites_prior_window(tmp_path):
    mod = _load_collector()

    out = tmp_path / "outdir"
    out.mkdir()
    (out / "bench.jsonl").write_text(json.dumps(
        {"metric": "w2", "value": 2.0, "unit": "images/sec",
         "device_kind": "tpu v5 lite"}) + "\n")  # lowercase kind counts
    evidence = tmp_path / "evidence"
    evidence.mkdir()
    (evidence / "bench.jsonl").write_text(json.dumps(
        {"metric": "w1", "value": 1.0, "unit": "images/sec",
         "device_kind": "TPU v5 lite"}) + "\n")

    argv = [sys.argv[0], str(out), str(evidence)]
    old = sys.argv
    sys.argv = argv
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            mod.main()
    finally:
        sys.argv = old
    text = buf.getvalue()
    # window 1 survives byte-for-byte, window 2 lands suffixed, and the
    # table shows BOTH windows' lines
    assert json.loads((evidence / "bench.jsonl").read_text())["metric"] \
        == "w1"
    assert (evidence / "bench.2.jsonl").exists()
    assert "| w1 |" in text and "| w2 |" in text
