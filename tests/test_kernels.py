"""The ``root.common.engine.kernels`` family acceptance gates
(docs/engine_fast_path.md § Training kernels):

1. interpret-mode PARITY ORACLES — the fused backward-GD Pallas kernel
   (dW + optimizer epilogue / db / dX, every activation × both weight
   storage layouts) against the dense ``znicz.gd._gd_math`` reference,
   and the gather+normalize loader head against its jnp twin;
2. END-TO-END parity — ``kernels=pallas`` must train to the same
   weights as ``kernels=xla`` (documented interpret-mode tolerance)
   with ZERO steady-state recompiles on every training path: the
   stitched-eager per-step program, the folded ``epoch_scan`` window,
   and the 8-device pod (one-pod-one-program pjit, on the conftest's
   virtual CPU mesh);
3. the CPU PERFORMANCE FLOOR (slow) — the fused LM train step of the
   bench ladder must beat its same-run XLA baseline ≥1.2× in the
   long-sequence regime where the materialized [B,H,S,S] attention
   backward is bandwidth-bound (the fused kernels' raison d'être).
"""

import json

import jax.numpy as jnp
import numpy
import pytest

from veles_tpu import prng, prof
from veles_tpu.backends import CPUDevice
from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.ops.gemm import _GD_DERIVS, gd_fused_pallas
from veles_tpu.znicz.gd import _gd_math
from veles_tpu.znicz.standard_workflow import StandardWorkflow


# ---------------------------------------------------------------------------
# 1. interpret-mode parity oracles
# ---------------------------------------------------------------------------

_HP = (0.05, 0.05, 0.0005, 0.0, 0.9, 0.9)   # lr, lr_b, decay ×2, moment ×2


@pytest.mark.parametrize("activation", sorted(_GD_DERIVS, key=str))
@pytest.mark.parametrize("transposed", [False, True])
def test_gd_fused_matches_dense_math(activation, transposed):
    """One kernel call vs ``_gd_math``: every output (w, b, vw, vb,
    err_input) within the documented interpret tolerance, on
    deliberately tile-unaligned shapes."""
    rng = numpy.random.default_rng(7)
    batch, f, n = 24, 70, 50
    x = jnp.asarray(rng.standard_normal((batch, f)), jnp.float32)
    eo = jnp.asarray(rng.standard_normal((batch, n)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((batch, n)), jnp.float32)
    w = jnp.asarray(rng.standard_normal(
        (n, f) if transposed else (f, n)), jnp.float32) * 0.1
    b = jnp.asarray(rng.standard_normal((n,)), jnp.float32)
    vw, vb = jnp.zeros_like(w), jnp.zeros_like(b)
    ref = _gd_math(x, y, eo, w, b, vw, vb, *_HP,
                   activation=activation, transposed=transposed)
    got = gd_fused_pallas(x, y, eo, w, b, vw, vb, *_HP,
                          activation=activation, transposed=transposed,
                          tiles=(32, 32, 8), interpret=True)
    for name, r, g in zip(("w", "b", "vw", "vb", "err_input"), ref,
                          got):
        numpy.testing.assert_allclose(
            numpy.asarray(g), numpy.asarray(r), atol=5e-5, rtol=0,
            err_msg="%s (activation=%s, transposed=%s)"
                    % (name, activation, transposed))


def test_gather_norm_interpret_matches_jnp():
    """The loader head: u8 row gather + normalize, negative indices
    zero-filled, both scalar and per-feature norms."""
    from veles_tpu.ops.gather import (_gather_norm_jnp,
                                      _gather_norm_pallas, _norm_row)
    rng = numpy.random.default_rng(11)
    data = jnp.asarray(rng.integers(0, 256, (37, 5, 3)), jnp.uint8)
    idx = jnp.asarray([3, 36, -1, 0, 17, -1, 9, 2], jnp.int32)
    feat = int(numpy.prod(data.shape[1:]))
    for scale, shift in (
            (1.0 / 255.0, 0.0),
            (rng.standard_normal(feat).astype(numpy.float32),
             rng.standard_normal(feat).astype(numpy.float32))):
        ref = _gather_norm_jnp(data, idx,
                               jnp.asarray(scale, jnp.float32),
                               jnp.asarray(shift, jnp.float32))
        got = _gather_norm_pallas(
            data.reshape(data.shape[0], -1), idx,
            _norm_row(scale, feat), _norm_row(shift, feat),
            interpret=True).reshape(ref.shape)
        numpy.testing.assert_allclose(numpy.asarray(got),
                                      numpy.asarray(ref), atol=1e-6)
        assert float(jnp.max(jnp.abs(got[jnp.asarray([2, 5])]))) == 0.0


# ---------------------------------------------------------------------------
# 2. kernels=pallas end-to-end parity, zero steady-state recompiles
# ---------------------------------------------------------------------------

class BlobLoader(FullBatchLoader):
    """Small separable blobs — enough steps per epoch to surface a
    per-step retrace, small enough for interpret-mode Pallas."""

    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.default_rng(42)
        n_train, n_valid, dim = 96, 32, 16
        total = n_train + n_valid
        labels = numpy.tile(numpy.arange(4), total // 4)[:total]
        centers = rng.standard_normal((4, dim)) * 3.0
        self.original_data.mem = (
            centers[labels] + rng.standard_normal((total, dim)) * 0.5
        ).astype(numpy.float32)
        self.original_labels = [int(v) for v in labels]
        self.class_lengths[:] = [0, n_valid, n_train]


def _build(max_epochs=3):
    prng.seed_all(5)
    wf = StandardWorkflow(
        None,
        loader_factory=lambda w: BlobLoader(w, minibatch_size=16),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 12},
                 "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}}],
        decision_config={"max_epochs": max_epochs})
    wf.launcher = DummyLauncher()
    wf.initialize(device=CPUDevice())
    return wf


def _params(wf):
    out = []
    for fwd in wf.forwards:
        for vec in (fwd.weights, fwd.bias):
            vec.map_read()
            out.append(numpy.array(vec.mem))
    return out


@pytest.fixture
def kernels_config():
    saved = {k: root.common.engine.get(k, d) for k, d in (
        ("kernels", "auto"), ("stitch", "on"), ("epoch_scan", "off"))}
    yield root.common.engine
    for key, value in saved.items():
        setattr(root.common.engine, key, value)


def _ab_run(kernels_config, epoch_scan):
    """Train the xla arm then the pallas arm on the identical seeded
    task; return both parameter sets and the pallas arm's recompile
    delta."""
    kernels_config.epoch_scan = epoch_scan
    kernels_config.kernels = "xla"
    wf = _build()
    wf.run()
    ref = _params(wf)

    kernels_config.kernels = "pallas"
    recompiles0 = prof.ledger.recompiles
    wf = _build()
    wf.run()
    return ref, _params(wf), prof.ledger.recompiles - recompiles0


def _assert_parity(ref, got):
    # interpret-mode Pallas accumulates f32 like the dense arm; the
    # residual drift over 3 epochs stays well under 1e-3
    for i, (r, g) in enumerate(zip(ref, got)):
        numpy.testing.assert_allclose(g, r, atol=1e-3, rtol=1e-3,
                                      err_msg="param %d" % i)


@pytest.mark.traced
def test_pallas_matches_xla_stitched_eager(kernels_config):
    ref, got, recompiled = _ab_run(kernels_config, epoch_scan="off")
    _assert_parity(ref, got)
    assert recompiled == 0, \
        "kernels=pallas retraced the stitched per-step program"
    assert prof.ledger.entries("segment"), \
        "the pallas arm did not run stitched"


@pytest.mark.traced
def test_pallas_matches_xla_epoch_scan_window(kernels_config):
    """The fused kernels are closure constants of the stage build, so
    the K-step scan window folds them without retracing."""
    ref, got, recompiled = _ab_run(kernels_config, epoch_scan="auto")
    _assert_parity(ref, got)
    assert recompiled == 0, \
        "kernels=pallas retraced the epoch_scan window"


@pytest.mark.traced
def test_pallas_matches_xla_pod_8dev(kernels_config):
    """One-pod-one-program on the conftest's forced 8-device CPU mesh:
    kernels=pallas must reach the same eval verdicts and weights as
    kernels=xla, with zero steady-state recompiles."""
    from veles_tpu.parallel.mesh import mesh_from_topology
    from veles_tpu.pod import PodRuntime, eval_metrics, train_epochs
    from veles_tpu.pod.__main__ import make_workflow

    def run(kernels):
        kernels_config.kernels = kernels
        wf = make_workflow(max_epochs=2)
        pod = PodRuntime(wf, mesh=mesh_from_topology("auto"))
        pod.install()
        assert pod.shards == 8
        for _ in train_epochs(wf, 2):
            pass
        wf.forwards[0].weights.map_read()
        return (eval_metrics(wf),
                numpy.array(wf.forwards[0].weights.mem))

    ref_metrics, ref_w = run("xla")
    recompiles0 = prof.ledger.recompiles
    got_metrics, got_w = run("pallas")
    assert prof.ledger.recompiles == recompiles0, \
        "kernels=pallas retraced the pod program"
    for key in ("complete", "epochs", "best_n_err_pt"):
        assert got_metrics[key] == ref_metrics[key], \
            (key, got_metrics, ref_metrics)
    numpy.testing.assert_allclose(got_w, ref_w, atol=1e-3, rtol=1e-3)


# ---------------------------------------------------------------------------
# 3. the CPU performance floor (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fused_lm_train_step_beats_xla_baseline_on_cpu():
    """Acceptance floor: the bench ladder's fused LM train step ≥1.2×
    its same-run XLA baseline on CPU.  Off-TPU both arms run the dense
    fast path (interpret-mode Pallas is exempt from throughput
    claims); the A/B isolates the blockwise flash-attention
    custom_vjp backward + chunked CE against AD's materialized
    [B,H,S,S] scores, pinned to S=8192 — deep in the regime where the
    materialization is bandwidth-bound, so the ratio clears the floor
    with margin over host-load noise (observed 1.32-1.56x).

    Runs in a subprocess WITHOUT the conftest's 8-way virtual device
    split — the split divides the host's intra-op threads, which
    starves the compute-leaning blockwise arm and makes the timing
    meaningless as a floor (the ladder itself never runs split)."""
    import os
    import subprocess
    import sys

    import conftest

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = conftest.ORIG_XLA_FLAGS
    env["BENCH_LM_SEQ"] = "8192"
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
        "PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c",
         "import bench; bench.stage_transformer_lm_train()"],
        capture_output=True, text=True, timeout=580, env=env,
        cwd=repo_root)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    assert lines, "the LM train stage emitted no metric line"
    rec = json.loads(lines[-1])
    assert rec["kernels"] == "fused-vs-xla"
    assert rec["recompiles"] == 0, rec
    assert rec["vs_baseline"] >= 1.2, \
        "fused LM train step below the 1.2x CPU floor: %r" % (rec,)
