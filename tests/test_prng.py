"""PRNG stream tests (mirrors reference ``veles/tests/test_random.py``
determinism guarantees, re-designed for key-splitting semantics)."""

import pickle

import numpy

from veles_tpu import prng


def test_named_streams_independent():
    a = prng.get("master")
    b = prng.get("loader")
    assert a is not b
    assert prng.get("master") is a


def test_deterministic_after_seed():
    s = prng.RandomGenerator("t", seed=7)
    x1 = s.permutation(10)
    s.seed(7)
    x2 = s.permutation(10)
    assert (x1 == x2).all()


def test_jax_keys_unique_and_reproducible():
    import jax
    s1 = prng.RandomGenerator("t", seed=3)
    k1 = s1.key()
    k2 = s1.key()
    # keys differ draw to draw...
    assert not (jax.random.key_data(k1) == jax.random.key_data(k2)).all()
    # ...but replay identically from the same seed
    s2 = prng.RandomGenerator("t", seed=3)
    assert (jax.random.key_data(s2.key()) == jax.random.key_data(k1)).all()


def test_pickle_resumes_stream():
    """A restored stream continues bit-identically to the uninterrupted
    one (snapshot-determinism guarantee)."""
    s = prng.RandomGenerator("t", seed=11)
    s.permutation(5)
    blob = pickle.dumps(s)
    restored = pickle.loads(blob)
    a = numpy.empty(64, dtype=numpy.float32)
    b = numpy.empty(64, dtype=numpy.float32)
    s.fill_uniform(a)
    restored.fill_uniform(b)
    assert (a == b).all()
    assert (s.permutation(100) == restored.permutation(100)).all()


def test_fill_helpers():
    s = prng.RandomGenerator("t", seed=5)
    arr = numpy.zeros((100,), dtype=numpy.float32)
    s.fill_normal(arr, stddev=2.0)
    assert arr.std() > 0.5
    s.fill_uniform(arr, low=0.0, high=1.0)
    assert 0 <= arr.min() and arr.max() <= 1


def test_seed_from_bytes():
    s = prng.RandomGenerator("t", seed=b"some entropy bytes")
    t = prng.RandomGenerator("t", seed=b"some entropy bytes")
    assert s.jax_seed == t.jax_seed
