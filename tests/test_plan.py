"""veles_tpu.analyze.plan + analyze.pricing — the static sharding
planner and the shared pricing core.

Gates here:

* the pricing-core refactor moved ZERO bytes/words in the V-P02 pod
  preflight and V-S01 serving preflight (fixture replay, byte-equal
  JSON vs the pre-refactor oracle in
  tests/fixtures/preflight_pricing.json — regenerate with
  ``python tests/pricing_cases.py`` ONLY when a pricing change is
  intended);
* planner feasibility rules V-P03/V-P04/V-P05 and the ranked table;
* ``PodRuntime(param_rules="auto")`` — bitwise weight parity with the
  explicit-rules run the planner selects, zero steady-state
  recompiles;
* planner-vs-ledger: the predicted psum bytes and per-shard residency
  track the live prof/Watcher ledgers within 10 % on the 8-way pod
  smoke;
* V-L05 knob registry and the ``--fail-on`` exit policy.
"""

import json

import numpy
import pytest
from jax.sharding import PartitionSpec as P

import pricing_cases
from veles_tpu import prof
from veles_tpu.analyze import lint_paths
from veles_tpu.analyze import plan as plan_mod
from veles_tpu.analyze import pricing
from veles_tpu.analyze.__main__ import main as analyze_main
from veles_tpu.analyze.graph import check_graph, unreachable_units
from veles_tpu.config import root
from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.memory import Watcher
from veles_tpu.parallel.mesh import mesh_from_topology
from veles_tpu.pod import PodRuntime, train_epochs
from veles_tpu.pod.__main__ import SMOKE_EPOCHS, make_workflow


def final_weights(wf):
    wf.forwards[0].weights.map_read()
    return numpy.array(wf.forwards[0].weights.mem)


# -- the refactor regression gate -------------------------------------------

def test_pricing_refactor_fixture_parity():
    """check_pod / check_generative reports are byte-identical to the
    pre-refactor oracle across the whole case matrix."""
    with open(pricing_cases.FIXTURE) as fin:
        banked = json.load(fin)
    now = pricing_cases.run_cases()
    assert json.dumps(now, sort_keys=True) == \
        json.dumps(banked, sort_keys=True), \
        "preflight pricing drifted from the banked fixture"


# -- pricing primitives ------------------------------------------------------

def test_collective_formulas():
    assert pricing.ring_all_reduce_bytes(1000, 8) == 1750
    assert pricing.ring_all_reduce_bytes(1000, 1) == 0
    assert pricing.ring_all_gather_bytes(1000, 8) == 875
    assert pricing.ring_all_gather_bytes(1000, 1) == 0
    assert pricing.pipeline_bubble(1, 8) == 0.0
    assert pricing.pipeline_bubble(4, 16) == pytest.approx(3 / 19)


def test_shard_factor_and_divisibility():
    axes = {"data": 4, "model": 2}
    assert pricing.shard_factor(P(), axes) == 1
    assert pricing.shard_factor(P("data"), axes) == 4
    assert pricing.shard_factor(P("data", "model"), axes) == 8
    assert pricing.shard_factor(P(("data", "model")), axes) == 8
    ok, dim, extent, size = pricing.spec_divisible(
        (100, 10), P("data"), axes)
    assert ok
    ok, dim, extent, size = pricing.spec_divisible(
        (7, 10), P("data"), axes)
    assert (ok, dim, extent, size) == (False, 0, 7, 4)


def test_hbm_budget_rule():
    assert pricing.hbm_budget(None) is None
    assert pricing.hbm_budget(0) is None
    assert pricing.hbm_budget(1000) == 900.0


# -- the planner: workflow path ---------------------------------------------

def test_plan_workflow_ranked_table_and_winner():
    wf = make_workflow()
    res = plan_mod.plan_workflow(wf, topology="auto")
    assert res.best is not None
    # batch 64 divides 8 ways and the smoke params are tiny (below
    # min_elements), so plain dp wins and the report is CLEAN even
    # though individual candidates were rejected
    assert res.best.name == "dp8"
    assert not res.report.has_errors
    names = [c.name for c in res.candidates]
    assert "fsdp8" in names and "tp8" in names and "pp8" in names
    table = res.render_table()
    assert "winner dp8" in table and "infeasible" in table
    data = res.to_dict()
    json.dumps(data)    # JSON-able end to end
    assert data["best"] == "dp8"
    assert len(data["candidates"]) == len(res.candidates)
    # rejected candidates carry their findings locally
    tp8 = next(c for c in res.candidates if c.name == "tp8")
    assert not tp8.feasible
    assert tp8.findings[0].rule == "V-P03"


def test_plan_workflow_bad_topology_names_v_p03():
    wf = make_workflow()
    res = plan_mod.plan_workflow(wf, topology=3)
    assert res.best is None
    assert res.report.has_errors
    assert "V-P03" in res.report.rules()
    # batch 64 % 3 != 0 is one of the named reasons
    assert any("does not divide" in f.message
               for f in res.report.findings)


def test_plan_workflow_v_p04_when_nothing_fits():
    wf = make_workflow()
    res = plan_mod.plan_workflow(wf, topology="auto", hbm_bytes=1024)
    assert res.best is None
    assert "V-P04" in res.report.rules()
    finding = next(f for f in res.report.findings
                   if f.rule == "V-P04")
    assert "smallest fix" in finding.message
    assert finding.fix


def test_v_p05_rule_shards_non_divisible_dim():
    cand = plan_mod.Candidate("bad", {"data": 8}, "custom",
                              param_rules=lambda leaf: P("data"))
    n_sharded, sharded_bytes = plan_mod._check_rule_divisibility(
        cand, [((7, 5), 140)])
    assert not cand.feasible
    assert cand.findings[0].rule == "V-P05"
    assert "7 %% 8" in cand.findings[0].message.replace("% 8", "%% 8")


# -- the planner: params-pytree (LM) path -----------------------------------

def test_plan_params_transformer_megatron_specs():
    from veles_tpu.samples import transformer as T
    params = T.param_shapes(T.CONFIG)
    res = plan_mod.plan_params(
        params, topology="auto",
        batch_bytes=8 * T.CONFIG["seq_len"] * 4,
        activation_bytes=8 * T.CONFIG["seq_len"] * T.CONFIG["dim"] * 4,
        param_spec_fn=T.param_specs)
    assert res.best is not None
    # the module's Megatron specs shard every big weight, so pure tp
    # moves the least per step (no grad psum at data=1)
    assert res.best.name == "tp8"
    pp8 = next(c for c in res.candidates if c.name == "pp8")
    # pp candidates are executable now (pp_rules stage-shards the
    # stacked blocks), no longer skeleton-priced
    assert pp8.feasible and not pp8.skeleton and pp8.bubble > 0
    # stacked blocks (leading L=12) stage-shard; embed stays whole
    dp8 = next(c for c in res.candidates if c.name == "dp8")
    assert dp8.feasible
    assert dp8.psum_bytes > res.best.psum_bytes


def test_transformer_param_shapes_matches_init():
    import jax

    from veles_tpu.samples import transformer as T
    shapes = T.param_shapes(T.TINY)
    params = T.init_params(T.TINY, seed=1)
    flat_s = jax.tree.leaves(shapes)
    flat_p = jax.tree.leaves(params)
    assert jax.tree.structure(shapes) == jax.tree.structure(params)
    for sds, leaf in zip(flat_s, flat_p):
        assert tuple(sds.shape) == tuple(leaf.shape)
        assert sds.dtype == leaf.dtype


# -- CLI ---------------------------------------------------------------------

def test_cli_plan_json_transformer(capsys):
    rc = analyze_main(["--plan", "veles_tpu.samples.transformer",
                       "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["best"] == "tp8"
    assert data["candidates"]
    assert data["report"]["counts"]["error"] == 0


def test_cli_plan_bad_topology_exits_nonzero(capsys):
    rc = analyze_main(["--plan", "veles_tpu.samples.mnist",
                       "--topology", "3"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "V-P03" in out


def test_cli_fail_on_policy(tmp_path, capsys):
    bad = tmp_path / "phantom.py"
    bad.write_text("from veles_tpu.config import root\n"
                   "x = root.common.engine.not_a_knob\n")
    assert analyze_main(["--lint", str(bad)]) == 1
    capsys.readouterr()
    # lint findings are warnings: --fail-on error passes them
    assert analyze_main(["--lint", str(bad),
                         "--fail-on", "error"]) == 0
    capsys.readouterr()
    assert analyze_main(["--lint", str(bad),
                         "--fail-on", "warn"]) == 1
    capsys.readouterr()


# -- V-L05 knob registry -----------------------------------------------------

def test_knob_registry_covers_the_package():
    findings = [f for f in lint_paths()
                if f.rule == "V-L05"]
    assert findings == [], \
        "undeclared knob reads: %s" % [f.message for f in findings]


def test_knob_scanner_resolves_get_hops(tmp_path):
    src = tmp_path / "knobby.py"
    src.write_text(
        "from veles_tpu.config import root\n"
        "a = root.common.engine.get(\"pod\").get(\"topology\")\n"
        "b = root.common.fleet.prefill_hosts\n"
        "c = root.common.engine.mesh.axes.to_dict()\n"
        "d = root.common.gen.kv.block_size\n"
        "bad = root.common.engine.pod.warp_speed\n")
    findings = [f for f in lint_paths([str(src)])
                if f.rule == "V-L05"]
    assert len(findings) == 1
    assert "root.common.engine.pod.warp_speed" in findings[0].message


def test_knob_table_renders_markdown():
    from veles_tpu.analyze.knobs import render_knob_table
    table = render_knob_table()
    assert "| knob | description |" in table
    assert "`root.common.engine.pod.param_rules`" in table
    assert "`root.common.fleet.*`" in table


# -- V-G02 shared detection helper ------------------------------------------

def test_v_g02_warning_and_analyzer_agree(caplog):
    # a stray unit: both the analyzer pass and the one-time workflow
    # warning flag it, via the same helper
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    a.link_from(wf.start_point)
    wf.end_point.link_from(a)
    DummyUnit(wf, name="stray")
    flagged = unreachable_units(wf.start_point, wf._units,
                                exclude=(wf.end_point,))
    assert [u.name for u in flagged] == ["stray"]
    assert {f.unit for f in check_graph(wf)
            if f.rule == "V-G02"} == {"stray"}
    import logging
    with caplog.at_level(logging.WARNING):
        wf.units_in_dependency_order()
    assert any("stray" in r.message for r in caplog.records
               if "V-G02" in r.message)


def test_v_g02_excludes_unreachable_end_point(caplog):
    # end_point unreachable: appended for ordering but NOT flagged —
    # V-G05 owns that failure mode (the two rules used to disagree)
    wf = DummyWorkflow()
    a = DummyUnit(wf, name="a")
    a.link_from(wf.start_point)
    assert unreachable_units(wf.start_point, wf._units,
                             exclude=(wf.end_point,)) == []
    assert not any(f.rule == "V-G02" for f in check_graph(wf))
    import logging
    with caplog.at_level(logging.WARNING):
        order = wf.units_in_dependency_order()
    assert wf.end_point in order
    assert not any("V-G02" in r.message for r in caplog.records)


# -- param_rules="auto" + planner-vs-ledger acceptance gates -----------------

def test_auto_param_rules_bitwise_parity_and_ledger():
    """THE gate: an 8-way pod run with ``param_rules="auto"`` is
    bitwise-identical to the same run under the explicit rules the
    planner selected, retraces nothing in steady state, and the
    planner's psum/residency predictions track the live ledgers."""
    mesh = mesh_from_topology("auto")

    explicit_wf = make_workflow()
    explicit_pod = PodRuntime(explicit_wf, mesh=mesh,
                              param_rules=None)
    explicit_pod.install()
    for _ in train_epochs(explicit_wf, SMOKE_EPOCHS):
        pass

    watcher_before = dict(Watcher.bytes_by_category)
    auto_wf = make_workflow()
    auto_pod = PodRuntime(auto_wf, mesh=mesh_from_topology("auto"),
                          param_rules="auto")
    # the STATIC prediction: priced on the un-installed workflow
    # (install placement may narrow host-f64 buffers to f32, which is
    # exactly the drift the 10% ledger gate below absorbs)
    batch = int(auto_wf.loader.max_minibatch_size)
    pred = plan_mod.predicted_estimates(auto_wf, auto_pod.mesh,
                                        param_rules=None)
    pred_seg_by_name = {
        "+".join(seg.names): pricing.segment_psum_bytes(
            seg, batch, auto_pod.shards)
        for seg in auto_wf._stitch_segments_}
    auto_pod.install()
    desc = auto_pod.describe()
    # ledger baselines: prof entries are keyed by segment NAME and
    # accumulate across the whole test session — gate on THIS run's
    # delta, not the lifetime average
    ledger_before = {
        "+".join(seg.names): (seg.prof_entry.psum_bytes,
                              seg.prof_entry.dispatches)
        for seg in auto_wf._stitch_segments_}
    assert desc["auto_plan"] == "dp8"
    # the planner picked the same explicit rule (replicated) — the
    # string resolved BEFORE any sharding was applied
    assert auto_pod.param_rules is None
    assert auto_pod.auto_plan["rule"] == "replicated"

    stepper = train_epochs(auto_wf, SMOKE_EPOCHS)
    next(stepper)                       # warmup epoch (compiles)
    steady_recompiles = prof.ledger.recompiles
    for _ in stepper:
        pass
    assert prof.ledger.recompiles == steady_recompiles, \
        "auto plan must not retrace in steady state"

    assert numpy.array_equal(final_weights(auto_wf),
                             final_weights(explicit_wf)), \
        "auto plan must be bitwise-identical to the explicit run"

    # planner-vs-ledger: psum — the prediction and the runtime's
    # describe() estimate share ONE formula over the same pre-install
    # state, so they agree EXACTLY, and the live per-dispatch ledger
    # accumulation tracks the prediction within 10%
    assert pred.psum_bytes == desc["psum_bytes_per_step"]
    checked = 0
    for segment in auto_wf._stitch_segments_:
        entry = segment.prof_entry
        name = "+".join(segment.names)
        psum0, disp0 = ledger_before[name]
        d_psum = entry.psum_bytes - psum0
        d_disp = entry.dispatches - disp0
        if not d_disp or not d_psum:
            continue
        per_dispatch = d_psum / d_disp
        pred_seg = pred_seg_by_name[name]
        assert abs(per_dispatch - pred_seg) <= 0.1 * max(pred_seg, 1)
        checked += 1
    assert checked, "no live psum ledger entries to check against"

    # planner-vs-ledger: residency — predicted resident bytes vs the
    # Watcher allocations this workflow actually made once training
    # realized every lazy buffer (within 10%; the Watcher ledger is
    # what prof's digest reports as hbm)
    watcher_after = dict(Watcher.bytes_by_category)
    predicted_full = pred.replicated_bytes + pred.sharded_bytes
    live_full = sum(max(0, watcher_after.get(cat, 0)
                        - watcher_before.get(cat, 0))
                    for cat in watcher_after)
    assert live_full > 0
    assert abs(predicted_full - live_full) <= 0.1 * live_full, \
        (predicted_full, live_full)


def test_param_rules_knob_spelling():
    saved = root.common.engine.pod.get("param_rules")
    root.common.engine.pod.param_rules = "auto"
    try:
        wf = DummyWorkflow()
        wf.loader = None
        pod = PodRuntime.__new__(PodRuntime)
        # only exercise the knob read: construct against the smoke
        # mesh with a throwaway workflow
        mesh = mesh_from_topology("auto")
        pod.__init__(wf, mesh=mesh)
        assert pod.param_rules == "auto"
    finally:
        root.common.engine.pod.param_rules = saved


def test_param_rules_rejects_unknown_mode():
    wf = make_workflow()
    pod = PodRuntime(wf, mesh=mesh_from_topology("auto"),
                     param_rules="zebra")
    from veles_tpu.pod import PodError
    with pytest.raises(PodError):
        pod.install()
