"""Rollback parity (ref algorithms doc capability #11): best-state
capture on improvement, restore + lr scaling on plateau — eager and
fused."""

import numpy
import pytest

from veles_tpu import prng


def _drive_epoch_close(wf, epoch, improved):
    """Simulate the Decision's view of one epoch close."""
    wf.loader.epoch_ended <<= True
    wf.loader.epoch_number = epoch
    wf.decision.best_epoch = epoch if improved else epoch - 1


def test_eager_rollback_restores_weights_and_scales_lr():
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(6)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=1, minibatch_size=1000,
        rollback_config={"fail_iterations": 2, "lr_factor": 0.5})
    rb = wf.rollback
    assert rb is not None and rb.trainer is None
    lr0 = float(wf.gds[0].learning_rate)

    # a new best is captured the moment it is DECLARED (validation
    # close — before any further train pass mutates the weights),
    # not at epoch end
    wf.loader.epoch_ended <<= False
    wf.loader.epoch_number = 0
    wf.decision.best_epoch = 0
    rb.run()                                  # captures the best state
    wf.forwards[0].weights.map_read()
    best_w = numpy.array(wf.forwards[0].weights.mem)
    # the weights keep training AFTER the capture (same epoch): the
    # snapshot must not follow them
    wf.forwards[0].weights.map_write()
    wf.forwards[0].weights.mem[...] += 5.0
    _drive_epoch_close(wf, 0, improved=True)
    rb.run()                                  # same best: no recapture

    # training drifts away, then plateaus for 2 epochs
    wf.forwards[0].weights.map_write()
    wf.forwards[0].weights.mem[...] += 123.0
    _drive_epoch_close(wf, 1, improved=False)
    rb.run()
    assert rb.rollbacks == 0                  # one bad epoch: no action
    _drive_epoch_close(wf, 2, improved=False)
    rb.run()
    assert rb.rollbacks == 1
    wf.forwards[0].weights.map_read()
    numpy.testing.assert_array_equal(
        numpy.array(wf.forwards[0].weights.mem), best_w)
    assert float(wf.gds[0].learning_rate) == pytest.approx(lr0 * 0.5)

    # a non-epoch-close run is a no-op
    wf.loader.epoch_ended <<= False
    rb.run()
    assert rb.rollbacks == 1


def test_fused_rollback_restores_solver_state_and_scales_lr():
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(7)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=1, minibatch_size=1000,
        fused=True,
        rollback_config={"fail_iterations": 1, "lr_factor": 0.5})
    rb = wf.rollback
    tr = wf.fused_trainer
    assert rb.trainer is tr
    tr._build()
    lr0 = float(tr.layers[0]["<-"]["learning_rate"])
    _drive_epoch_close(wf, 0, improved=True)
    rb.run()                                  # fused capture
    best = rb._best
    assert best[0] == "fused"
    best_w = numpy.array(best[1][0]["w"])

    # drift the device state, then plateau
    import jax
    tr._params_ = jax.tree_util.tree_map(lambda a: a + 1.0,
                                         tr._params_)
    _drive_epoch_close(wf, 1, improved=False)
    rb.run()
    assert rb.rollbacks == 1
    assert tr._step_ is None                  # rebuild pending
    assert float(tr.layers[0]["<-"]["learning_rate"]) == \
        pytest.approx(lr0 * 0.5)
    tr._build()                               # restores the tree
    numpy.testing.assert_array_equal(
        numpy.asarray(tr._params_[0]["w"]), best_w)
    # momentum velocities restored too (same tree)
    numpy.testing.assert_array_equal(
        numpy.asarray(tr._params_[0]["vw"]),
        numpy.array(best[1][0]["vw"]))
