"""Remote slave bootstrap: the master launcher spawns its own slaves
(ref ``launch_remote_progs`` ``launcher.py:617-660`` + YARN discovery
``:887``), exercised fully locally via the ``sh -c`` launch transform —
the spawned command rides as one argument exactly as ssh would pass it
to the remote shell.
"""

import json
import sys
import textwrap
import threading

import pytest

from veles_tpu.launcher import (
    Launcher, discover_nodes_from_yarn, parse_nodes)

# one module defines the workflow for BOTH sides so the checksum
# handshake passes (the checksum covers the defining source file)
BOOT_MODULE = textwrap.dedent("""
    import numpy
    import sys

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow


    class BootLoader(FullBatchLoader):
        def load_data(self):
            rng = numpy.random.default_rng(5)
            n = 120
            labels = (numpy.arange(n) % 4).astype(int)
            centers = rng.standard_normal((4, 8)) * 3
            self.original_data.mem = (
                centers[labels] + rng.standard_normal((n, 8)) * 0.5
            ).astype(numpy.float32)
            self.original_labels = [int(v) for v in labels]
            self.class_lengths[:] = [0, 40, 80]


    LAYERS = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 6},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05}},
    ]


    def make(launcher):
        prng.seed_all(21)
        wf = StandardWorkflow(
            None,
            loader_factory=lambda w: BootLoader(w, minibatch_size=20),
            layers=[{**s} for s in LAYERS],
            decision_config={"max_epochs": 2})
        wf.launcher = launcher
        return wf


    if __name__ == "__main__":
        # re-import under the canonical module name so unit classes hash
        # identically on both sides (the real CLI loads workflow files
        # by module name too)
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "boot_wf", os.path.abspath(__file__))
        mod = importlib.util.module_from_spec(spec)
        sys.modules["boot_wf"] = mod
        spec.loader.exec_module(mod)
        from veles_tpu.launcher import Launcher
        launcher = Launcher(master_address=sys.argv[1], device="numpy")
        wf = mod.make(launcher)
        launcher.initialize()
        launcher.run()
""")


def test_parse_nodes():
    assert parse_nodes(["hostA", "b:2222", "c x3", "d:22x2",
                        "e.example.com", "linux01", "f*4"]) == [
        ("hostA", 22, 1), ("b", 2222, 1), ("c", 22, 3), ("d", 22, 2),
        ("e.example.com", 22, 1),
        # glued xN after a bare host is a HOSTNAME, not a count
        ("linux01", 22, 1), ("f", 22, 4)]
    with pytest.raises(ValueError):
        parse_nodes(["bad spec::"])
    with pytest.raises(ValueError):
        parse_nodes(["host:abc"])


def test_yarn_discovery():
    import functools
    import http.server

    payload = {"nodes": {"node": [
        {"nodeHostName": "w1", "state": "RUNNING"},
        {"nodeHostName": "w2", "state": "LOST"},
        {"nodeHostName": "w3", "state": "RUNNING"},
    ]}}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            assert self.path == "/ws/v1/cluster/nodes"
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        nodes = discover_nodes_from_yarn(
            "http://127.0.0.1:%d" % httpd.server_port)
        assert nodes == ["w1", "w3"]
    finally:
        httpd.shutdown()


def test_master_bootstraps_slaves_locally(tmp_path):
    """End-to-end: master spawns 2 slaves through the launch transform,
    they connect, do jobs, master's weights move, spawned procs exit."""
    import importlib.util
    import os
    import numpy

    import veles_tpu
    repo_root = os.path.dirname(os.path.dirname(veles_tpu.__file__))
    script = tmp_path / "boot_wf.py"
    script.write_text(BOOT_MODULE)
    spec = importlib.util.spec_from_file_location("boot_wf", str(script))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["boot_wf"] = mod
    try:
        spec.loader.exec_module(mod)
        launcher = Launcher(
            listen="127.0.0.1:0", device="numpy",
            nodes=["localhost x2"],
            slave_launch_transform="sh -c",
            # spawned processes don't get pytest's conftest env or
            # sys.path — pin the virtual CPU platform and the repo
            # root explicitly, like conftest does for this process
            slave_command="env -u PALLAS_AXON_POOL_IPS "
                          "JAX_PLATFORMS=cpu PYTHONPATH=%s %s %s "
                          "%%(master)s"
                          % (repo_root, sys.executable, script),
            advertise_host="127.0.0.1")
        wf = mod.make(launcher)
        launcher.initialize()
        w_before = numpy.array(wf.forwards[0].weights.mem)
        launcher.run()
        assert launcher._server.endpoint
        assert not launcher._spawned_          # reaped
        assert any(s.jobs_done > 0
                   for s in launcher._server.slaves.values()), \
            "no spawned slave completed a job"
        w_after = numpy.array(wf.forwards[0].weights.mem)
        assert not numpy.allclose(w_before, w_after)
    finally:
        sys.modules.pop("boot_wf", None)
