"""Utility-script tests (SURVEY §2.5): snapshot diffing, frontend
generation, forge CLI round trip."""

import json
import subprocess
import sys

import numpy
import pytest

from veles_tpu.backends import NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.memory import Vector
from veles_tpu.scripts.compare_snapshots import compare
from veles_tpu.scripts.generate_frontend import generate
from veles_tpu.units import Unit


class WeightUnit(Unit):
    def __init__(self, workflow, **kwargs):
        super(WeightUnit, self).__init__(workflow, **kwargs)
        self.weights = Vector()

    def initialize(self, **kwargs):
        pass

    def run(self):
        pass


def _wf(scale):
    wf = DummyWorkflow()
    unit = WeightUnit(wf, name="W")
    unit.weights.reset(numpy.full((3, 3), scale, numpy.float32))
    unit.link_from(wf.start_point)
    wf.end_point.link_from(unit)
    return wf


def test_compare_equal():
    rows, worst = compare(_wf(1.0), _wf(1.0))
    assert worst == 0.0
    assert ("W.weights", "equal", 0.0) in rows


def test_compare_different():
    rows, worst = compare(_wf(1.0), _wf(2.0))
    assert worst == 1.0
    assert any(status == "DIFFERENT" for _, status, _ in rows)


def test_compare_snapshot_files(tmp_path):
    """End-to-end through real snapshot files + the CLI main()."""
    from veles_tpu.scripts.compare_snapshots import main
    from veles_tpu.snapshotter import save_snapshot
    a, b = _wf(1.0), _wf(1.0)
    pa = str(tmp_path / "a.snap.gz")
    pb = str(tmp_path / "b.snap.gz")
    save_snapshot(a, pa)
    save_snapshot(b, pb)
    assert main([pa, pb]) == 0


def test_frontend_generation(tmp_path):
    html = generate()
    assert "<form" in html
    assert "data-flag=\"--result-file\"" in html or \
        "data-flag=\"--result-file" in html
    assert "compose()" in html
    # core positional + a sample of registered flags present
    for flag in ("--listen", "--master-address", "--snapshot"):
        assert flag in html, flag


def test_forge_cli_round_trip(tmp_path):
    from veles_tpu.forge import ForgeServer
    from veles_tpu.scripts.forge_cli import main
    from veles_tpu.package import export_package
    from veles_tpu.znicz.all2all import All2AllTanh

    wf = DummyWorkflow()
    fc = All2AllTanh(wf, output_sample_shape=(3,))
    fc.input = Vector(numpy.zeros((2, 5), numpy.float32))
    fc.initialize(NumpyDevice())
    pkg = str(tmp_path / "m.zip")
    export_package([fc], pkg, with_stablehlo=False)

    server = ForgeServer(str(tmp_path / "store"),
                         tokens={"t": "u"}).start()
    try:
        assert main(["upload", "mlp", pkg, "--server", server.endpoint,
                     "--token", "t"]) == 0
        assert main(["list", "--server", server.endpoint]) == 0
        dest = str(tmp_path / "out.zip")
        assert main(["fetch", "mlp", dest,
                     "--server", server.endpoint]) == 0
        assert open(dest, "rb").read() == open(pkg, "rb").read()
        assert main(["delete", "mlp", "--server", server.endpoint,
                     "--token", "t"]) == 0
    finally:
        server.stop()
