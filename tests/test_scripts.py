"""Utility-script tests (SURVEY §2.5): snapshot diffing, frontend
generation, forge CLI round trip."""

import json
import subprocess
import sys

import numpy
import pytest

from veles_tpu.backends import NumpyDevice
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.memory import Vector
from veles_tpu.scripts.compare_snapshots import compare
from veles_tpu.scripts.generate_frontend import generate
from veles_tpu.units import Unit


class WeightUnit(Unit):
    def __init__(self, workflow, **kwargs):
        super(WeightUnit, self).__init__(workflow, **kwargs)
        self.weights = Vector()

    def initialize(self, **kwargs):
        pass

    def run(self):
        pass


def _wf(scale):
    wf = DummyWorkflow()
    unit = WeightUnit(wf, name="W")
    unit.weights.reset(numpy.full((3, 3), scale, numpy.float32))
    unit.link_from(wf.start_point)
    wf.end_point.link_from(unit)
    return wf


def test_compare_equal():
    rows, worst = compare(_wf(1.0), _wf(1.0))
    assert worst == 0.0
    assert ("W.weights", "equal", 0.0) in rows


def test_compare_different():
    rows, worst = compare(_wf(1.0), _wf(2.0))
    assert worst == 1.0
    assert any(status == "DIFFERENT" for _, status, _ in rows)


def test_compare_snapshot_files(tmp_path):
    """End-to-end through real snapshot files + the CLI main()."""
    from veles_tpu.scripts.compare_snapshots import main
    from veles_tpu.snapshotter import save_snapshot
    a, b = _wf(1.0), _wf(1.0)
    pa = str(tmp_path / "a.snap.gz")
    pb = str(tmp_path / "b.snap.gz")
    save_snapshot(a, pa)
    save_snapshot(b, pb)
    assert main([pa, pb]) == 0


def test_frontend_generation(tmp_path):
    html = generate()
    assert "<form" in html
    assert "data-flag=\"--result-file\"" in html or \
        "data-flag=\"--result-file" in html
    assert "compose()" in html
    # core positional + a sample of registered flags present
    for flag in ("--listen", "--master-address", "--snapshot"):
        assert flag in html, flag


def test_forge_cli_round_trip(tmp_path):
    from veles_tpu.forge import ForgeServer
    from veles_tpu.scripts.forge_cli import main
    from veles_tpu.package import export_package
    from veles_tpu.znicz.all2all import All2AllTanh

    wf = DummyWorkflow()
    fc = All2AllTanh(wf, output_sample_shape=(3,))
    fc.input = Vector(numpy.zeros((2, 5), numpy.float32))
    fc.initialize(NumpyDevice())
    pkg = str(tmp_path / "m.zip")
    export_package([fc], pkg, with_stablehlo=False)

    server = ForgeServer(str(tmp_path / "store"),
                         tokens={"t": "u"}).start()
    try:
        assert main(["upload", "mlp", pkg, "--server", server.endpoint,
                     "--token", "t"]) == 0
        assert main(["list", "--server", server.endpoint]) == 0
        dest = str(tmp_path / "out.zip")
        assert main(["fetch", "mlp", dest,
                     "--server", server.endpoint]) == 0
        assert open(dest, "rb").read() == open(pkg, "rb").read()
        assert main(["delete", "mlp", "--server", server.endpoint,
                     "--token", "t"]) == 0
    finally:
        server.stop()


class TestBBoxer:
    """Image bbox labeling tool (ref ``veles/scripts/bboxer.py``):
    selections persist as <image>.json sidecars; concurrent edits
    conflict (403) unless overwritten."""

    @staticmethod
    def _start(tmp_path):
        import asyncio
        import threading

        from veles_tpu.scripts.bboxer import make_app

        # a tiny but valid PNG
        png = bytes.fromhex(
            "89504e470d0a1a0a0000000d49484452000000010000000108060000001f"
            "15c4890000000d49444154789c6260606060000000050001a5f645400000"
            "000049454e44ae426082")
        (tmp_path / "img.png").write_bytes(png)
        ready = threading.Event()
        state = {}

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            import tornado.ioloop
            app = make_app(str(tmp_path))
            server = app.listen(0)
            state["port"] = list(server._sockets.values())[0]\
                .getsockname()[1]
            state["ioloop"] = tornado.ioloop.IOLoop.current()
            ready.set()
            state["ioloop"].start()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert ready.wait(10)
        return state

    def test_sidecar_roundtrip_and_conflict(self, tmp_path):
        import json as _json
        import urllib.request
        import urllib.error

        state = self._start(tmp_path)
        base = "http://127.0.0.1:%d" % state["port"]

        def post(path, payload):
            req = urllib.request.Request(
                base + path, data=_json.dumps(payload).encode())
            with urllib.request.urlopen(req) as resp:
                return resp.read()

        # empty selections initially
        assert _json.loads(post("/selections", {"file": "img.png"})) \
            == []
        boxes = [{"x": 1, "y": 2, "w": 3, "h": 4, "label": "cat"}]
        post("/update", {"file": "img.png", "selections": boxes,
                         "overwrite": False})
        assert _json.loads((tmp_path / "img.png.json").read_text()) \
            == boxes
        assert _json.loads(post("/selections", {"file": "img.png"})) \
            == boxes
        # conflicting non-overwrite update → 403
        other = [{"x": 9, "y": 9, "w": 1, "h": 1, "label": "dog"}]
        try:
            post("/update", {"file": "img.png", "selections": other,
                             "overwrite": False})
            raise AssertionError("conflict not rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 403
        # overwrite wins
        post("/update", {"file": "img.png", "selections": other,
                         "overwrite": True})
        assert _json.loads((tmp_path / "img.png.json").read_text()) \
            == other
        # path traversal rejected
        try:
            post("/selections", {"file": "../escape.png"})
            raise AssertionError("traversal not rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 403
        # the index page lists the image and serves it back
        with urllib.request.urlopen(base + "/") as resp:
            page = resp.read().decode()
        assert 'data-f="img.png"' in page   # clickable via delegation
        with urllib.request.urlopen(base + "/image/img.png") as resp:
            assert resp.read().startswith(b"\x89PNG")
        state["ioloop"].add_callback(state["ioloop"].stop)


def test_profile_step_per_layer_table():
    """profile_step.measure_per_layer: one row per layer from prefix
    differences; the final prefix REUSES the supplied full-forward
    measurement (its flops land in the last row); a full-forward
    SMALLER than the measured prefixes forces negative differences,
    which the clamp must floor at zero."""
    from veles_tpu.samples import cifar10
    from veles_tpu.scripts import profile_step

    rows = profile_step.measure_per_layer(
        "cifar10", batch=4, k=4, full_forward=(0.5, 4.2e9))
    assert len(rows) == len(cifar10.LAYERS)
    # labels carry position + unit type
    assert rows[0][0].startswith("01 conv")
    assert all(sec >= 0.0 and flops >= 0.0 for _l, sec, flops in rows)
    # the injected full-forward anchors the LAST row: its flops are
    # the 4.2 GFLOP total minus the (tiny, batch-4) prefix-7 flops —
    # a re-timing regression could not produce this value
    assert rows[-1][2] > 4.0e9
    # the injected 0.5 s dwarfs every CPU prefix: virtually all of it
    # must surface in the final row (proves the reuse, not a re-time)
    assert rows[-1][1] > 0.4

    # full_forward SMALLER than the measured prefixes: the final
    # difference goes negative and must be clamped to exactly 0
    rows0 = profile_step.measure_per_layer(
        "cifar10", batch=4, k=4, full_forward=(0.0, 0.0))
    assert rows0[-1][1] == 0.0 and rows0[-1][2] == 0.0


def test_profile_step_per_layer_report_rendering(tmp_path,
                                                 monkeypatch):
    """main(--per-layer) renders the table from measure_per_layer's
    rows (sweep stubbed out — the sweep itself is covered above)."""
    from veles_tpu.scripts import profile_step

    monkeypatch.setattr(
        profile_step, "measure_per_layer",
        lambda sample, batch, k=8, full_forward=None: [
            ("01 conv_strict_relu", 1e-3, 2.0e9),
            ("02 max_pooling", 1e-4, 0.0)])
    out = tmp_path / "P.md"
    rc = profile_step.main(["--sample", "cifar10", "--batch", "4",
                            "--k", "4", "--per-layer",
                            "--out", str(out)])
    assert rc == 0
    text = out.read_text()
    assert "Per-layer forward (prefix-difference)" in text
    assert "01 conv_strict_relu" in text and "02 max_pooling" in text
    # recurrent samples skip with a note instead of a wrong table
    monkeypatch.setattr(
        profile_step, "measure_per_layer",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError(
            "per-layer must not run for recurrent samples")))
    rc = profile_step.main(["--sample", "mnist_rnn", "--batch", "4",
                            "--k", "4", "--per-layer",
                            "--out", str(out)])
    assert rc == 0
    assert "skipped for mnist_rnn" in out.read_text()


def test_bench_power_stage_vs_titan(monkeypatch, capsys):
    """The power stage reports the reference-anchored chain-time ratio
    (GTX TITAN float P0, 0.1642 s — the one absolute throughput number
    the reference ships) and refuses physically impossible timings."""
    import json

    import bench

    monkeypatch.setattr(bench, "_device_kind", lambda: "TPU v5 lite")
    monkeypatch.setattr(bench, "_peak_flops", lambda kind: 197e12)
    from veles_tpu.ops import benchmark as B

    # healthy: ~9.3 ms/chain = ~193 TFLOP/s; TITAN's recorded matmul
    # rate is 2*3001^3/0.1642 = 329 GFLOP/s -> rate ratio ~586
    monkeypatch.setattr(B, "estimate_device_power",
                        lambda: (0.00926, 192963.0))
    bench.stage_power()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["vs_baseline"] == pytest.approx(
        192963.0 / bench.TITAN_MATMUL_GFLOPS, rel=1e-3)
    assert 500 < line["vs_baseline"] < 700
    assert line["value"] == pytest.approx(192963.0)
    assert "rate-vs-rate" in line["baseline"]

    # faster than the chip's peak: refused, never published
    monkeypatch.setattr(B, "estimate_device_power",
                        lambda: (0.004, 447000.0))
    bench.stage_power()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["vs_baseline"] is None and "physics" in line["error"]
