"""Fused train step + mesh data parallelism (runs on the virtual
8-device CPU mesh)."""

import jax
import numpy
import pytest

from veles_tpu import prng
from veles_tpu.parallel import data_parallel, make_mesh
from veles_tpu.parallel.dp import shard_params
from veles_tpu.znicz.fused import (
    init_mlp_params, lower_workflow, make_eval_step, make_train_step,
    mlp_apply, update_workflow, _specs_static)

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 4},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]


def _data(n=64, dim=12, classes=4, seed=0):
    rng = numpy.random.default_rng(seed)
    labels = (numpy.arange(n) % classes).astype(numpy.int32)
    centers = rng.standard_normal((classes, dim)) * 3
    x = (centers[labels] + rng.standard_normal((n, dim))).astype(
        numpy.float32)
    return x, labels


def test_fused_step_learns():
    prng.seed_all(0)
    params = init_mlp_params(12, LAYERS)
    step = jax.jit(make_train_step(LAYERS))
    x, labels = _data()
    first = None
    for i in range(60):
        params, metrics = step(params, x, labels)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.5
    assert int(metrics["n_err"]) <= 5


def test_fused_matches_eager_units():
    """One fused step == one eager unit-graph step (same math)."""
    from veles_tpu.backends import NumpyDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.znicz.standard_workflow import StandardWorkflow

    x, labels = _data(n=32)

    class L(FullBatchLoader):
        def load_data(self):
            self.original_data.mem = x
            self.original_labels = list(int(v) for v in labels)
            self.class_lengths[:] = [0, 0, 32]

    prng.seed_all(7)
    wf = StandardWorkflow(
        None, loader_factory=lambda w: L(w, minibatch_size=32,
                                         shuffle_limit=0),
        layers=[{**s} for s in LAYERS],
        decision_config={"max_epochs": 1})
    wf.launcher = DummyLauncher()
    wf.initialize(device=NumpyDevice())

    params, step = lower_workflow(wf)
    # eager one minibatch
    wf.loader.run()
    for fwd in wf.forwards:
        fwd.run()
    wf.evaluator.run()
    for gdu in wf.gds:
        gdu.run()
    # fused one step on the same batch
    mb_x = numpy.array(wf.loader.minibatch_data.mem)
    mb_y = numpy.array(wf.loader.minibatch_labels.mem)
    new_params, _ = jax.jit(step)(params, mb_x, mb_y)
    for layer, fwd in zip(new_params, wf.forwards):
        assert numpy.allclose(numpy.asarray(layer["w"]), fwd.weights.mem,
                              atol=1e-4), fwd.name
        assert numpy.allclose(numpy.asarray(layer["b"]), fwd.bias.mem,
                              atol=1e-4), fwd.name
    # write-back path
    update_workflow(wf, new_params)
    assert numpy.allclose(wf.forwards[0].weights.mem,
                          numpy.asarray(new_params[0]["w"]))


def test_fused_short_batch_matches_eager_scaling():
    """Padded short batch: fused gradients scale by padded length like
    the eager units (valid-count scaling would overstep 1.5x)."""
    prng.seed_all(9)
    params = init_mlp_params(12, LAYERS)
    step = jax.jit(make_train_step(LAYERS))
    x, labels = _data(n=15)
    x = numpy.vstack([x, numpy.zeros((5, 12), numpy.float32)])
    labels = numpy.concatenate([labels,
                                numpy.full(5, -1, numpy.int32)])
    new_params, metrics = step(params, x, labels)
    # manual check of output-layer bias grad scaling
    static = _specs_static(LAYERS)
    out = mlp_apply(params, x, static)
    onehot = numpy.zeros((20, 4), numpy.float32)
    for i, l in enumerate(labels[:15]):
        onehot[i, l] = 1
    delta = (numpy.asarray(out) - onehot)
    delta[15:] = 0
    grad_b = delta.sum(axis=0) / 20.0          # padded length, not 15
    lr = LAYERS[-1]["<-"]["learning_rate"]
    expect_b = numpy.asarray(params[-1]["b"]) - lr * grad_b
    assert numpy.allclose(numpy.asarray(new_params[-1]["b"]), expect_b,
                          atol=1e-5)


def test_data_parallel_8_devices_matches_single():
    prng.seed_all(1)
    params_a = init_mlp_params(12, LAYERS)
    params_b = jax.tree.map(numpy.copy, params_a)
    x, labels = _data(n=64)
    step = make_train_step(LAYERS)
    single = jax.jit(step)
    mesh = make_mesh({"data": 8})
    assert mesh.shape["data"] == 8
    dp = data_parallel(step, mesh, params_a, donate_params=False)
    for _ in range(3):
        params_a, m_dp = dp(params_a, x, labels)
        params_b, m_single = single(params_b, x, labels)
    assert numpy.allclose(numpy.asarray(params_a[0]["w"]),
                          numpy.asarray(params_b[0]["w"]), atol=1e-5)
    assert int(m_dp["n_err"]) == int(m_single["n_err"])


def test_fsdp_sharded_params_match_replicated():
    """ZeRO/FSDP storage via fsdp_rules: every large parameter (and its
    solver state) is sharded over the data axis, XLA gathers/scatters
    as needed, and the math matches the replicated run exactly."""
    from veles_tpu.parallel.dp import fsdp_rules, shard_params

    prng.seed_all(1)
    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    params_a = init_mlp_params(16, layers)
    params_b = jax.tree.map(numpy.copy, params_a)
    x, labels = _data(n=64, dim=16)
    step = make_train_step(layers)
    mesh = make_mesh({"data": 8})
    rules = fsdp_rules(mesh, min_elements=64)
    fsdp = data_parallel(step, mesh, params_a, donate_params=False,
                         param_rules=rules)
    params_a = shard_params(params_a, mesh, param_rules=rules)
    # the first layer's weight (16, 32) really is sharded over 'data'
    w_shard = params_a[0]["w"].sharding
    assert "data" in str(w_shard.spec), w_shard
    assert not params_a[0]["w"].sharding.is_fully_replicated
    rep = data_parallel(step, mesh, params_b, donate_params=False)
    for _ in range(3):
        params_a, m_f = fsdp(params_a, x, labels)
        params_b, m_r = rep(params_b, x, labels)
    assert int(m_f["n_err"]) == int(m_r["n_err"])
    numpy.testing.assert_allclose(
        numpy.asarray(params_a[0]["w"]),
        numpy.asarray(params_b[0]["w"]), atol=1e-5)
    # state stayed sharded across steps (ZeRO: optimizer state too)
    assert not params_a[0]["vw"].sharding.is_fully_replicated


def test_dp_2x4_mesh_with_model_axis():
    """data×model mesh: params sharded on the model axis (TP) still
    produce the same training step results."""
    from jax.sharding import PartitionSpec as P
    prng.seed_all(2)
    params = init_mlp_params(12, LAYERS)
    reference = jax.tree.map(numpy.copy, params)
    x, labels = _data(n=32)
    mesh = make_mesh({"data": 2, "model": 4})

    def rules(leaf):
        # shard the hidden dimension of 2-D weights over 'model'
        if getattr(leaf, "ndim", 0) == 2 and leaf.shape[1] % 4 == 0:
            return P(None, "model")
        return None

    step = make_train_step(LAYERS)
    dp = data_parallel(step, mesh, params, donate_params=False,
                       param_rules=rules)
    out_tp, m_tp = dp(params, x, labels)
    out_ref, m_ref = jax.jit(step)(reference, x, labels)
    assert numpy.allclose(numpy.asarray(out_tp[0]["w"]),
                          numpy.asarray(out_ref[0]["w"]), atol=1e-5)
    assert int(m_tp["n_err"]) == int(m_ref["n_err"])


def test_shard_params_topology_change():
    """Snapshot on one topology, reshard on another (§5.4 resume)."""
    prng.seed_all(3)
    params = init_mlp_params(12, LAYERS)
    mesh8 = make_mesh({"data": 8})
    placed = shard_params(params, mesh8)
    mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
    replaced = shard_params(jax.tree.map(numpy.asarray, placed), mesh2)
    assert numpy.allclose(numpy.asarray(replaced[0]["w"]),
                          numpy.asarray(params[0]["w"]))


def test_fused_regularizers_l1_and_ortho():
    """l1_vs_l2 mixes sign(w) into the decay term; factor_ortho pushes
    WᵀW toward I — both verified against hand-computed updates."""
    import jax.numpy as jnp

    from veles_tpu.znicz.fused_graph import lower_specs
    from veles_tpu.znicz.gd_base import ortho_grad

    # single linear layer, MSE loss, lr small: one step's weight change
    # must equal -lr * (grad + decay*((1-l)w + l*sign(w)) + ortho)
    w0 = numpy.array([[1.5, -0.5], [0.5, 2.0]], numpy.float32)
    spec = [{"type": "all2all",
             "->": {"output_sample_shape": 2, "include_bias": False},
             "init": {"weights": w0},
             "<-": {"learning_rate": 0.1, "weights_decay": 0.2,
                    "l1_vs_l2": 0.7, "factor_ortho": 0.05}}]
    prng.seed_all(5)
    params, step_fn, _e, _a = lower_specs(spec, (2,), loss="mse")
    x = numpy.array([[1.0, 0.0], [0.0, 1.0]], numpy.float32)
    target = numpy.zeros((2, 2), numpy.float32)
    new, _m = step_fn(params, x, target)

    out = x @ w0
    grad = x.T @ (out - target) / 2 / 2   # d(mean-over-dim MSE/2)/dW
    reg = 0.2 * (0.3 * w0 + 0.7 * numpy.sign(w0))
    ortho = numpy.asarray(ortho_grad(jnp.asarray(w0), 0.05))
    expect = w0 - 0.1 * (grad + reg + ortho)
    numpy.testing.assert_allclose(numpy.asarray(new[0]["w"]), expect,
                                  atol=1e-5)


@pytest.mark.parametrize("solver", ["adam", "rprop", "adagrad",
                                    "adadelta"])
def test_fused_solver_selection_learns(solver):
    """Per-layer 'solver' in the <- spec swaps the fused update rule;
    both alternatives actually train."""
    from veles_tpu.znicz.fused_graph import lower_specs

    prng.seed_all(42)
    knobs = {"solver": solver}
    if solver == "rprop":
        knobs["rprop_delta_init"] = 0.001
    elif solver == "adadelta":
        knobs["learning_rate"] = 1.0        # canonical adadelta scale
    elif solver == "adagrad":
        knobs["learning_rate"] = 0.05
    else:
        knobs["learning_rate"] = 0.003
    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
         "<-": dict(knobs)},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": dict(knobs)},
    ]
    params, step_fn, _e, _a = lower_specs(layers, (12,))
    x, labels = _data(n=128)
    first = None
    for _ in range(40):
        params, metrics = step_fn(params, x, labels)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.6
    # solver state invariants
    for state in params:
        if state.get("w") is None:
            continue
        if solver == "adam":
            assert int(state["t"]) == 40
            assert state["sw"].shape == state["w"].shape
            assert float(jax.numpy.min(state["sw"])) >= 0.0
        elif solver in ("adagrad", "adadelta"):
            # squared-gradient accumulator is nonnegative and grew
            assert float(jax.numpy.min(state["sw"])) >= 0.0
            assert float(jax.numpy.max(state["sw"])) > 0.0
        else:
            delta, prev = state["vw"][0], state["vw"][1]
            assert float(jax.numpy.min(delta)) >= 1e-6
            assert float(jax.numpy.max(delta)) <= 50.0
            signs = numpy.unique(numpy.asarray(prev))
            assert set(signs).issubset({-1.0, 0.0, 1.0})


def test_fused_step_compiles_exactly_once_across_calls():
    """The trainer's params are COMMITTED device arrays: an
    uncommitted input (plain device_put) plus the step's committed
    output params would re-key the jit cache on the SECOND call and
    recompile the entire step — observed as a 9.6-20 s first-loop
    stall per chip session (r4 session 4 compile log)."""
    import jax

    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(1)
    wf = mnist.create_workflow(device=CPUDevice(), max_epochs=1,
                               minibatch_size=500, fused=True)
    wf.fused_trainer._build()
    tr = wf.fused_trainer
    x = jax.device_put(numpy.zeros((500, 784), numpy.float32))
    labels = jax.device_put(numpy.zeros((500,), numpy.int32))
    params, _m = tr._step_(tr._params_, x, labels)
    assert tr._step_._cache_size() == 1
    params, _m = tr._step_(params, x, labels)
    params, _m = tr._step_(params, x, labels)
    assert tr._step_._cache_size() == 1, \
        "step retraced: params committed-ness must match its outputs"


def test_standard_workflow_fused_mode_trains():
    """StandardWorkflow(fused=True): the graph keeps the loader /
    Decision / services, the math runs as ONE program per minibatch
    (FusedTrainer), and weights sync back into the forward units."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(1)
    wf = mnist.create_workflow(device=CPUDevice(), max_epochs=2,
                               minibatch_size=500, fused=True)
    assert wf.fused_trainer is not None
    assert wf.gds == []                  # no eager backward chain
    # the trainer seeds from the units' REAL initialized weights (the
    # forwards initialize after the trainer, hence the lazy build)
    wf.forwards[0].weights.map_read()
    w_init = numpy.array(wf.forwards[0].weights.mem)
    assert not numpy.allclose(w_init, 0.0)
    wf.fused_trainer._build()
    numpy.testing.assert_allclose(
        numpy.asarray(wf.fused_trainer._params_[0]["w"]), w_init,
        atol=0)
    wf.run()
    results = wf.gather_results()
    # same bar as the eager-mode sample test (measured 25 % there)
    assert results["best_validation_error_pt"] < 35.0
    # the trained parameters are visible in the unit graph
    wf.forwards[0].weights.map_read()
    w_unit = numpy.array(wf.forwards[0].weights.mem)
    w_fused = numpy.asarray(wf.fused_trainer._params_[0]["w"])
    numpy.testing.assert_allclose(w_unit, w_fused, atol=1e-6)


def test_standard_workflow_fused_mse_trains():
    """fused=True with an MSE stack (autoencoder shape): DecisionMSE
    reads the trainer's mse metric."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist_ae

    prng.seed_all(2)
    # minibatch 300 does NOT divide the synthetic class sizes: the
    # short-tail slicing path (MSE has no validity mask) is exercised
    wf = mnist_ae.create_workflow(device=CPUDevice(), max_epochs=2,
                                  minibatch_size=300, fused=True)
    wf.run()
    results = wf.gather_results()
    assert numpy.isfinite(results["best_rmse"])
    assert float(wf.decision.best_mse) < numpy.inf


def test_fused_workflow_deterministic():
    """Two identically-seeded fused runs (incl. dropout's per-stage
    seed streams) produce bit-identical weights — the reproducible-
    randomness contract under jit."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    def train_once():
        prng.seed_all(77)
        # 2 epochs: max_epochs=1 would stop after the initial eval
        # pass with zero train steps, making the comparison vacuous
        wf = mnist.create_workflow(
            device=CPUDevice(), max_epochs=2, minibatch_size=500,
            fused=True,
            layers=[
                {"type": "all2all_tanh",
                 "->": {"output_sample_shape": 32},
                 "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
                {"type": "dropout", "->": {"dropout_ratio": 0.3}},
                {"type": "softmax", "->": {"output_sample_shape": 10},
                 "<-": {"learning_rate": 0.03}},
            ])
        wf.run()
        wf.forwards[0].weights.map_read()
        return numpy.array(wf.forwards[0].weights.mem)

    numpy.testing.assert_array_equal(train_once(), train_once())


def test_standard_workflow_fused_snapshot_resume(tmp_path):
    """A fused workflow pickles and resumes: the trainer's device
    state is rebuilt from the unit weights it synced at epoch end, so
    training continues from the trained parameters."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.samples import mnist
    from veles_tpu.snapshotter import load_snapshot

    prng.seed_all(1)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=500,
        fused=True, snapshot_dir=str(tmp_path))
    wf.run()
    first_best = float(wf.decision.best_n_err_pt)
    # Decision triggered at least the first-improvement export
    assert wf.snapshotter.destination is not None
    wf.forwards[0].weights.map_read()
    w_trained = numpy.array(wf.forwards[0].weights.mem)

    # the Decision-triggered snapshot is the BEST epoch's cut, which
    # equals the final weights only when the last epoch improved — a
    # numerics coin-flip XLA CPU thread availability can tip.  The
    # equality leg uses an explicit operator export of the final state
    # (the same public API), which is deterministic.
    from veles_tpu.mutable import LinkableAttribute
    LinkableAttribute.unlink(wf.snapshotter, "suffix")
    wf.snapshotter.suffix = "final"
    wf.snapshotter.export()

    restored = load_snapshot(wf.snapshotter.destination)
    restored.launcher = DummyLauncher()
    # the trainer's jitted state is deliberately not pickled
    assert restored.fused_trainer._step_ is None
    restored.forwards[0].weights.map_read()
    numpy.testing.assert_allclose(
        numpy.array(restored.forwards[0].weights.mem), w_trained)
    restored.decision.complete <<= False
    restored.decision.max_epochs = 3
    restored.initialize(device=CPUDevice())
    restored.run()
    assert restored.loader.epoch_number >= 2
    # resumed training did not regress below the snapshot's best
    assert float(restored.decision.best_n_err_pt) <= first_best + 1e-6


def test_fused_snapshot_preserves_solver_state(tmp_path):
    """Snapshotter resume continues with the SAME optimizer dynamics:
    the momentum velocities pickled with the workflow are restored
    into the rebuilt device state (parity with the eager path, where
    the gradient Vectors live in the snapshot)."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.samples import mnist
    from veles_tpu.snapshotter import load_snapshot

    prng.seed_all(5)
    # NB max_epochs=1 completes after the initial validation pass with
    # zero train steps; 2 epochs = one real training epoch
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=500,
        fused=True, snapshot_dir=str(tmp_path))
    wf.run()
    v_orig = [numpy.asarray(st["vw"])
              for st in wf.fused_trainer._params_ if "vw" in st]
    assert v_orig and any(numpy.abs(v).max() > 0 for v in v_orig)

    # explicit final export: the velocities compared below must be the
    # FINAL ones, not the best-epoch ones (see the resume test above)
    from veles_tpu.mutable import LinkableAttribute
    LinkableAttribute.unlink(wf.snapshotter, "suffix")
    wf.snapshotter.suffix = "final"
    wf.snapshotter.export()

    restored = load_snapshot(wf.snapshotter.destination)
    restored.launcher = DummyLauncher()
    assert restored.fused_trainer.solver_state is not None
    restored.decision.complete <<= False
    restored.decision.max_epochs = 2
    restored.initialize(device=CPUDevice())
    restored.fused_trainer._build()
    v_rest = [numpy.asarray(st["vw"])
              for st in restored.fused_trainer._params_ if "vw" in st]
    assert len(v_rest) == len(v_orig)
    for a, b in zip(v_orig, v_rest):
        numpy.testing.assert_array_equal(b, a)


def test_standard_workflow_fused_mesh_dp():
    """fused_config={'mesh_axes': ...}: the workflow's FusedTrainer
    trains data-parallel over the 8-device mesh (the BASELINE
    north-star AlexNet-DP shape, via the graph), optionally with FSDP
    param storage."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(1)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=500,
        fused=True,
        fused_config={"mesh_axes": {"data": -1}, "fsdp": True})
    wf.run()
    results = wf.gather_results()
    assert results["best_validation_error_pt"] < 35.0
    # params are mesh-sharded (FSDP): not fully replicated
    w = wf.fused_trainer._params_[0]["w"]
    assert not w.sharding.is_fully_replicated


def test_grad_accum_matches_full_batch():
    """grad_accum=N (the reference's accumulate_gradient, as an
    in-step scan over microbatches) produces the same update as the
    full-batch step, with microbatch-sized activation memory."""
    from veles_tpu.znicz.fused_graph import lower_specs

    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05}},
    ]
    prng.seed_all(9)
    params_a, step_a, _e, _a = lower_specs(layers, (12,))
    prng.seed_all(9)
    params_b, step_b, _e2, _a2 = lower_specs(layers, (12,),
                                             grad_accum=4)
    x, labels = _data(n=64)
    for _ in range(3):
        params_a, m_a = step_a(params_a, x, labels)
        params_b, m_b = step_b(params_b, x, labels)
    assert int(m_a["n_err"]) == int(m_b["n_err"])
    assert float(m_a["loss"]) == pytest.approx(float(m_b["loss"]),
                                               rel=1e-5)
    for sa, sb in zip(params_a, params_b):
        numpy.testing.assert_allclose(numpy.asarray(sa["w"]),
                                      numpy.asarray(sb["w"]),
                                      atol=1e-5)

    with pytest.raises(ValueError, match="not divisible"):
        step_b(params_b, x[:30], labels[:30])


def test_grad_accum_microbatches_draw_distinct_dropout_masks():
    """Each microbatch in the grad-accum scan must draw its own
    dropout mask.  Probe: duplicate a half-batch — if both microbatches
    used the SAME mask, the grad_accum=2 update on the duplicated batch
    would exactly equal the grad_accum=1 update on the half batch
    (average of two identical gradients); distinct masks break that."""
    from veles_tpu.znicz.fused_graph import lower_specs

    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
         "<-": {"learning_rate": 0.05}},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05}},
    ]
    prng.seed_all(21)
    params_half, step_half, _e, _a = lower_specs(layers, (12,))
    prng.seed_all(21)            # identical init weights AND seeds
    params_dup, step_dup, _e2, _a2 = lower_specs(layers, (12,),
                                                 grad_accum=2)
    x_half, l_half = _data(n=16)
    x_dup = numpy.concatenate([x_half, x_half])
    l_dup = numpy.concatenate([l_half, l_half])
    params_half, _m = step_half(params_half, x_half, l_half)
    params_dup, _m2 = step_dup(params_dup, x_dup, l_dup)
    w_half = numpy.asarray(params_half[0]["w"])
    w_dup = numpy.asarray(params_dup[0]["w"])
    assert not numpy.allclose(w_half, w_dup, atol=1e-7)


def test_fused_tail_smaller_than_divisor_skips_step():
    """A train tail batch SMALLER than grad_accum × data-axis (here:
    6000 % 857 = 1 < grad_accum=4) must be skipped, not handed to the
    traced step as an indivisible size (which raised mid-epoch)."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(3)
    # 2 epochs: max_epochs=1 stops at the initial eval close with zero
    # train steps, so the tail path would never execute
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=857,
        fused=True, fused_config={"grad_accum": 4})
    wf.run()                      # raised ValueError before the fix
    results = wf.gather_results()
    assert numpy.isfinite(results["best_validation_error_pt"])
    # the epoch-boundary weight sync still happened
    wf.forwards[0].weights.map_read()
    numpy.testing.assert_allclose(
        numpy.array(wf.forwards[0].weights.mem),
        numpy.asarray(wf.fused_trainer._params_[0]["w"]), atol=1e-6)


def test_fused_unknown_solver_rejected():
    from veles_tpu.znicz.fused_graph import lower_specs

    with pytest.raises(ValueError, match="unknown solver"):
        lower_specs([{"type": "softmax",
                      "->": {"output_sample_shape": 2},
                      "<-": {"solver": "sgdfast"}}], (4,))


def test_remat_matches_and_rematerializes():
    """lower_specs(remat=...): numerically identical step, with the
    checkpoint primitive actually present in the jaxpr (activations
    recomputed in backward instead of held in HBM)."""
    from veles_tpu.znicz.fused_graph import lower_specs

    specs = [
        {"type": "conv_tanh", "->": {"n_kernels": 4, "kx": 3, "ky": 3},
         "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
         "<-": {"learning_rate": 0.01}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.01}},
    ]
    rng = numpy.random.default_rng(5)
    x = rng.standard_normal((8, 10, 10, 2)).astype(numpy.float32)
    labels = (numpy.arange(8) % 4).astype(numpy.int32)

    prng.seed_all(7)
    params0, step0, _e, _a = lower_specs(specs, (10, 10, 2))
    prng.seed_all(7)
    params1, step1, _e, _a = lower_specs(specs, (10, 10, 2),
                                         remat=True)
    new0, m0 = step0(params0, x, labels)
    new1, m1 = step1(params1, x, labels)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]),
                                              rel=1e-6)
    for s0, s1 in zip(new0, new1):
        for key in s0:
            if s0[key] is None:
                continue
            numpy.testing.assert_allclose(
                numpy.asarray(s0[key]), numpy.asarray(s1[key]),
                atol=1e-5)
    # the checkpoint (remat) primitive is really in the program
    jaxpr1 = jax.make_jaxpr(step1)(params1, x, labels)
    jaxpr0 = jax.make_jaxpr(step0)(params0, x, labels)
    assert "remat" in str(jaxpr1)
    assert "remat" not in str(jaxpr0)

    # per-layer opt-in: only the flagged layer is checkpointed
    specs_one = [dict(s) for s in specs]
    specs_one[0]["remat"] = True
    prng.seed_all(7)
    _p, step_one, _e2, _a2 = lower_specs(specs_one, (10, 10, 2))
    assert "remat" in str(jax.make_jaxpr(step_one)(params1, x, labels))


def test_eval_step():
    prng.seed_all(4)
    params = init_mlp_params(12, LAYERS)
    x, labels = _data(n=16)
    ev = jax.jit(make_eval_step(LAYERS))
    out = ev(params, x, labels)
    assert 0 <= int(out["n_err"]) <= 16
    assert int(out["n"]) == 16


def test_graft_entry_contract():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (128, 10)
    assert numpy.allclose(numpy.asarray(out).sum(axis=1), 1.0, atol=1e-3)


@pytest.mark.slow
def test_dryrun_multichip_8():
    # compiles the whole real-dims multichip ladder (~85 s on the
    # virtual CPU mesh) — outside the tier-1 budget
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


def test_standard_workflow_fused_mesh_tp():
    """fused_config={'mesh_axes': {'data': 2, 'model': 4}, 'tp': True}:
    Megatron column-parallel weights through the workflow — each chip
    holds 1/4 of every wide layer's neurons, batch splits on 'data',
    and training still converges; tp+fsdp merge onto distinct dims."""
    from jax.sharding import PartitionSpec as P

    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(22)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=500,
        fused=True,
        fused_config={"mesh_axes": {"data": 2, "model": 4},
                      "tp": True})
    wf.run()
    results = wf.gather_results()
    assert results["best_validation_error_pt"] < 35.0
    w = wf.fused_trainer._params_[0]["w"]          # (784, 100)
    assert not w.sharding.is_fully_replicated
    assert w.sharding.spec == P(None, "model")
    # momentum velocity shards with its weight
    vw = wf.fused_trainer._params_[0]["vw"]
    assert vw.sharding.spec == P(None, "model")

    # tp+fsdp: contested dims resolve TP-first, FSDP takes the rest
    prng.seed_all(22)
    wf2 = mnist.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=500,
        fused=True,
        fused_config={"mesh_axes": {"data": 2, "model": 4},
                      "tp": True, "fsdp": True})
    wf2.run()
    w2 = wf2.fused_trainer._params_[0]["w"]
    assert w2.sharding.spec == P("data", "model")
    assert numpy.isfinite(
        wf2.gather_results()["best_validation_error_pt"])


def test_tp_requires_model_axis():
    from veles_tpu.parallel.dp import tp_rules

    with pytest.raises(ValueError, match="model"):
        tp_rules(make_mesh({"data": 8}))


def test_fused_u8_input_norm_matches_f32_path():
    """uint8-resident x + in-step normalization (mlp_apply input_norm)
    trains identically to pre-normalized float32 x — the storage-dtype
    change may not alter the trajectory."""
    import numpy
    import jax.numpy as jnp

    from veles_tpu import prng
    from veles_tpu.znicz.fused import init_mlp_params, make_train_step

    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.05}},
    ]
    rng = numpy.random.default_rng(7)
    xu8 = rng.integers(0, 256, (64, 49)).astype(numpy.uint8)
    labels = rng.integers(0, 10, 64).astype(numpy.int32)
    xf32 = (xu8.astype(numpy.float32) / 255.0) - 0.5

    prng.seed_all(99)
    p_f32 = init_mlp_params(49, layers)
    prng.seed_all(99)
    p_u8 = init_mlp_params(49, layers)

    step_f32 = make_train_step(layers)
    step_u8 = make_train_step(layers, input_norm=(1.0 / 255.0, -0.5))
    for _ in range(5):
        p_f32, m_f32 = step_f32(p_f32, jnp.asarray(xf32),
                                jnp.asarray(labels))
        p_u8, m_u8 = step_u8(p_u8, jnp.asarray(xu8),
                             jnp.asarray(labels))
    numpy.testing.assert_allclose(
        numpy.asarray(p_f32[0]["w"]), numpy.asarray(p_u8[0]["w"]),
        rtol=1e-5, atol=1e-6)
    assert int(m_f32["n_err"]) == int(m_u8["n_err"])


def test_epoch_runner_matches_host_loop():
    """epoch_runner (one-program epoch: in-program permutation +
    gather + step scan) must produce BIT-identical params to the
    host-driven loop applying the same step over the same permuted
    minibatches."""
    import jax
    import numpy
    from veles_tpu.znicz.fused_graph import epoch_runner, lower_specs

    rng = numpy.random.default_rng(0)
    n, batch = 43, 8       # 43 % 8 == 3: the dropped-tail leg is real
    data = rng.integers(0, 256, (n, 12)).astype(numpy.uint8)
    labels = rng.integers(0, 4, n).astype(numpy.int32)
    specs = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 6},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    params, step_fn, _e, _a = lower_specs(
        specs, (12,),
        input_norm=(numpy.float32(1 / 255.0), numpy.float32(0.0)))

    key = jax.random.key(7)
    epoch_fn = jax.jit(epoch_runner(step_fn, n, batch))
    p_epoch, metrics = epoch_fn(params, data, labels, key)

    # the host-driven oracle: same permutation, same minibatches
    perm = numpy.asarray(jax.random.permutation(key, n))
    steps = n // batch
    p_host = params
    host_step = jax.jit(step_fn)
    for i in range(steps):
        idx = perm[i * batch:(i + 1) * batch]
        p_host, _m = host_step(p_host, data[idx], labels[idx])

    # scan-body and standalone compilations may round differently;
    # same tolerance as test_fused_u8_input_norm_matches_f32_path
    for a, b in zip(jax.tree.leaves(p_epoch), jax.tree.leaves(p_host)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b),
                                      rtol=1e-5, atol=1e-6)
    # stacked per-minibatch metrics, short tail dropped
    assert all(numpy.asarray(v).shape[0] == steps
               for v in metrics.values())


def test_epoch_runner_pallas_gather_inside_scan_matches_xla():
    """The one-program epoch with the Pallas DMA gather forced
    (interpret mode on CPU) must equal the XLA-gather epoch bit for
    bit — pins the exact composition the TPU path runs when the
    device DB's gather verdict says pallas."""
    import jax
    import numpy
    from veles_tpu.config import root
    from veles_tpu.znicz.fused_graph import epoch_runner, lower_specs

    rng = numpy.random.default_rng(1)
    n, batch = 32, 8
    data = rng.integers(0, 256, (n, 12)).astype(numpy.uint8)
    labels = rng.integers(0, 4, n).astype(numpy.int32)
    specs = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 6},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    params, step_fn, _e, _a = lower_specs(
        specs, (12,),
        input_norm=(numpy.float32(1 / 255.0), numpy.float32(0.0)))
    key = jax.random.key(3)
    p_xla, _ = jax.jit(epoch_runner(step_fn, n, batch))(
        params, data, labels, key)
    from veles_tpu.ops import gather as G
    real_pallas = G._gather_pallas
    hits = []

    def counting(*a, **k):
        hits.append(1)
        return real_pallas(*a, **k)

    _ABSENT = object()
    saved = {k: root.common.engine.__dict__.get(k, _ABSENT)
             for k in ("pallas_gather", "interpret")}
    try:
        root.common.engine.pallas_gather = True
        root.common.engine.interpret = True
        G._gather_pallas = counting
        p_pl, _ = jax.jit(epoch_runner(step_fn, n, batch))(
            params, data, labels, key)
    finally:
        G._gather_pallas = real_pallas
        for k, v in saved.items():      # restore, don't just delete
            if v is _ABSENT:
                root.common.engine.__dict__.pop(k, None)
            else:
                root.common.engine.__dict__[k] = v
    assert hits, "the Pallas kernel was never dispatched"
    for a, b in zip(jax.tree.leaves(p_xla), jax.tree.leaves(p_pl)):
        numpy.testing.assert_array_equal(numpy.asarray(a),
                                         numpy.asarray(b))


def test_epoch_runner_rejects_tiny_dataset():
    import pytest as _pytest
    from veles_tpu.znicz.fused_graph import epoch_runner

    with _pytest.raises(ValueError):
        epoch_runner(lambda p, x, y: (p, {}), n_samples=4, batch=8)


def test_data_parallel_epoch_matches_single_device():
    """One-program DP epoch over the 8-device mesh: the globally-
    permuted sampling makes its result comparable to the single-device
    epoch_runner with the same key — params agree to float tolerance,
    while the dataset lives sharded over the data axis."""
    import jax
    import numpy
    from veles_tpu.parallel.dp import data_parallel_epoch
    from veles_tpu.parallel.mesh import make_mesh
    from veles_tpu.znicz.fused_graph import epoch_runner, lower_specs

    rng = numpy.random.default_rng(2)
    n, batch = 64, 16
    data = rng.integers(0, 256, (n, 12)).astype(numpy.uint8)
    labels = rng.integers(0, 4, n).astype(numpy.int32)
    specs = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 6},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    params, step_fn, _e, _a = lower_specs(
        specs, (12,),
        input_norm=(numpy.float32(1 / 255.0), numpy.float32(0.0)))

    key = jax.random.key(3)
    single = jax.jit(epoch_runner(step_fn, n, batch))
    p_single, m_single = single(params, data, labels, key)

    mesh = make_mesh({"data": 8})
    dp_epoch = data_parallel_epoch(step_fn, mesh, params, n, batch)
    p_dp, m_dp = dp_epoch(params, data, labels, key)
    for a, b in zip(jax.tree.leaves(p_single), jax.tree.leaves(p_dp)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b),
                                      rtol=1e-5, atol=1e-6)
    numpy.testing.assert_allclose(
        numpy.asarray(m_single["loss"]), numpy.asarray(m_dp["loss"]),
        rtol=1e-5, atol=1e-6)
    # the dataset really was sharded over the mesh's data axis
    placed = jax.device_put(
        data, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data")))
    assert not placed.sharding.is_fully_replicated


def test_data_parallel_epoch_local_matches_simulation():
    """Local-sampler DP epoch (shard_map + in-step pmean): each shard
    permutes its own slice; the update equals a single-device step on
    the CONCATENATION of all shards' m-th local minibatches (equal
    shard batches make the pmean of per-shard mean-grads the global-
    batch gradient).  Verified against that exact simulation."""
    import jax
    import numpy
    from veles_tpu.parallel.dp import data_parallel_epoch_local
    from veles_tpu.parallel.mesh import make_mesh
    from veles_tpu.znicz.fused_graph import lower_specs

    shards, n_local, batch_local = 4, 16, 4
    n = shards * n_local
    rng = numpy.random.default_rng(4)
    data = rng.integers(0, 256, (n, 12)).astype(numpy.uint8)
    labels = rng.integers(0, 4, n).astype(numpy.int32)
    specs = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 6},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    norm = (numpy.float32(1 / 255.0), numpy.float32(0.0))
    params, step_red, _e, _a = lower_specs(
        specs, (12,), input_norm=norm, grad_reduce_axis="data")
    mesh = make_mesh({"data": shards})
    key = jax.random.key(11)
    epoch_fn = data_parallel_epoch_local(step_red, mesh, n_local,
                                         batch_local)
    p_mesh, m_mesh = epoch_fn(params, data, labels, key)

    # single-device simulation of the same semantics (REUSING the
    # same initial params — lower_specs draws from the stateful init
    # PRNG, so a second call would start from different weights)
    _params2, step_plain, _e2, _a2 = lower_specs(
        specs, (12,), input_norm=norm)
    step_plain = jax.jit(step_plain)
    perms = [numpy.asarray(jax.random.permutation(
        jax.random.fold_in(key, i), n_local)) for i in range(shards)]
    steps = n_local // batch_local
    p_sim = params
    for m in range(steps):
        idx = numpy.concatenate([
            i * n_local + perms[i][m * batch_local:(m + 1) * batch_local]
            for i in range(shards)])
        p_sim, m_sim = step_plain(p_sim, data[idx], labels[idx])

    for a, b in zip(jax.tree.leaves(p_mesh), jax.tree.leaves(p_sim)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b),
                                      rtol=1e-5, atol=1e-6)
    # final minibatch's globally-reduced error count matches too
    assert float(numpy.asarray(m_mesh["n_err"])[-1]) == \
        float(numpy.asarray(m_sim["n_err"]))


def test_fused_epoch_mode_trains_and_keeps_decision_stream():
    """fused_config={'epoch_mode': True}: the whole TRAIN epoch runs
    as one program; Decision still receives a per-minibatch metric
    stream and the workflow trains to the usual synthetic accuracy.
    minibatch 512 does NOT divide the train set, so the dropped-tail
    replay leg is exercised too."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(1)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=3, minibatch_size=512,
        fused=True, fused_config={"epoch_mode": True})
    assert wf.fused_trainer.epoch_mode
    wf.run()
    results = wf.gather_results()
    assert results["best_validation_error_pt"] < 35.0
    # the epoch program really was built and consumed
    assert wf.fused_trainer._epoch_fn_ is not None
    assert wf.fused_trainer.epoch_key_counter >= 2
    # weights synced back into the unit graph at epoch boundaries
    wf.forwards[0].weights.map_read()
    import numpy as _np
    assert float(_np.abs(wf.forwards[0].weights.mem).max()) > 0


def test_fused_epoch_mode_on_mesh():
    """'One workflow, any mode' (ref manualrst_veles_distributed_
    training.rst:14-16): StandardWorkflow(fused, epoch_mode,
    mesh_axes) routes the whole-epoch program through
    parallel.dp.data_parallel_epoch — batch sharded over the 8-device
    CPU mesh, gradient all-reduce inside the one-dispatch epoch —
    and still trains to the usual synthetic accuracy (VERDICT r4
    next-round item 5)."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(1)
    wf = mnist.create_workflow(
        device=CPUDevice(), max_epochs=3, minibatch_size=512,
        fused=True,
        fused_config={"epoch_mode": True, "mesh_axes": {"data": -1}})
    wf.run()
    results = wf.gather_results()
    assert results["best_validation_error_pt"] < 35.0
    assert wf.fused_trainer._epoch_fn_ is not None
    # the resident TRAIN slice really is sharded over the data axis
    assert not wf.fused_trainer._epoch_data_.sharding \
        .is_fully_replicated


def test_fused_epoch_mode_mse_autoencoder():
    """epoch_mode with the MSE loss (the AE family): the epoch
    program gathers resident float targets and the per-minibatch
    replay feeds Decision's mse stream (VERDICT r4 item 5)."""
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist_ae

    prng.seed_all(2)
    wf = mnist_ae.create_workflow(
        device=CPUDevice(), max_epochs=2, minibatch_size=500,
        fused=True, fused_config={"epoch_mode": True})
    wf.run()
    assert wf.fused_trainer._epoch_fn_ is not None
    assert wf.fused_trainer.epoch_key_counter >= 1
    # the replay populated the mse metric (an RMSE, finite, nonzero)
    results = wf.gather_results()
    assert 0.0 < results["best_rmse"] < 10.0


def test_fused_epoch_mode_rejects_train_ratio():
    # bagged runs (train_ratio) are per-minibatch-path only
    from veles_tpu.backends import CPUDevice
    from veles_tpu.samples import mnist

    prng.seed_all(2)
    wf3 = mnist.create_workflow(
        device=CPUDevice(), max_epochs=1, minibatch_size=500,
        fused=True, fused_config={"epoch_mode": True})
    wf3.loader.train_ratio = 0.5
    with pytest.raises(NotImplementedError):
        wf3.run()


def test_data_parallel_epoch_with_tp_rules():
    """DP×TP one-program epoch: epoch_runner's jit composition accepts
    param_rules, so wide layers shard column-parallel over 'model'
    while the epoch result still matches the single-device run."""
    import jax
    import numpy
    from veles_tpu.parallel.dp import data_parallel_epoch, tp_rules
    from veles_tpu.parallel.mesh import make_mesh
    from veles_tpu.znicz.fused_graph import epoch_runner, lower_specs

    rng = numpy.random.default_rng(9)
    n, batch = 32, 8
    data = rng.integers(0, 256, (n, 12)).astype(numpy.uint8)
    labels = rng.integers(0, 4, n).astype(numpy.int32)
    specs = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    params, step_fn, _e, _a = lower_specs(
        specs, (12,),
        input_norm=(numpy.float32(1 / 255.0), numpy.float32(0.0)))
    key = jax.random.key(5)
    p_single, _m = jax.jit(epoch_runner(step_fn, n, batch))(
        params, data, labels, key)

    mesh = make_mesh({"data": 2, "model": 4})
    rules = tp_rules(mesh, min_elements=64)
    epoch_fn = data_parallel_epoch(step_fn, mesh, params, n, batch,
                                   param_rules=rules)
    p_mesh, _m2 = epoch_fn(params, data, labels, key)
    # the wide layer's weight really is model-sharded
    w0 = p_mesh[0]["w"]
    assert not w0.sharding.is_fully_replicated
    for a, b in zip(jax.tree.leaves(p_single), jax.tree.leaves(p_mesh)):
        numpy.testing.assert_allclose(numpy.asarray(a),
                                      numpy.asarray(b),
                                      rtol=1e-4, atol=1e-5)
